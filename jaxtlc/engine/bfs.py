"""Fully device-resident BFS model-checking engine.

The TLC BFS core replacement (tlc2.tool.Worker + DiskStateQueue +
OffHeapDiskFPSet, /root/reference/KubeAPI.toolbox/Model_1/MC.out:5): one
``lax.while_loop`` whose body pops a fixed-size chunk from a device-resident
ring-buffer frontier, expands it through the vmapped next-state kernel,
evaluates invariants, fingerprints + dedups against the device hash table,
and appends the new states - no host round-trips until the state space is
exhausted or a violation is found.

Level-synchronous by construction: a chunk never crosses a BFS level
boundary (`level_end` fences the FIFO), so reported depth is the exact BFS
level count, matching TLC's "depth of the complete state graph search"
(MC.out:1101), and in-batch fingerprint arbitration never has to choose
between states of different levels.

Violation handling: the fused loop carries a violation code + the offending
encoded state; on violation the CLI re-runs in the host driver
(engine.hostdriver) which keeps parent pointers and reconstructs the
counterexample trace (TLC trace-explorer analog, SURVEY.md §2.3 E11).

Counters are maintained per action label (generated + distinct), feeding the
TLC-style coverage report (E9) in io/tlc_log.py.
"""

from __future__ import annotations

import functools
import time
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..config import ModelConfig
from ..spec.codec import get_codec
from ..spec.invariants import make_invariant_kernel
from ..spec.kernel import initial_vectors, make_kernel
from ..spec.labels import LABELS
from .fingerprint import DEFAULT_FP_INDEX, DEFAULT_SEED, fp64_words
from .fpset import FPSet, fpset_insert, fpset_new

# violation codes
OK = 0
VIOL_TYPEOK = 1
VIOL_ONLYONEVERSION = 2
VIOL_ASSERT = 3
VIOL_DEADLOCK = 4
VIOL_SLOT_OVERFLOW = 5
VIOL_FPSET_FULL = 6
VIOL_QUEUE_FULL = 7
VIOL_ROUTE_OVERFLOW = 8

VIOLATION_NAMES = {
    OK: "none",
    VIOL_TYPEOK: "Invariant TypeOK is violated",
    VIOL_ONLYONEVERSION: "Invariant OnlyOneVersion is violated",
    VIOL_ASSERT: "Assertion failure (PlusCal assert)",
    VIOL_DEADLOCK: "Deadlock reached",
    VIOL_SLOT_OVERFLOW: "Codec slot overflow (raise ModelConfig bounds)",
    VIOL_FPSET_FULL: "Fingerprint table full (raise fp_capacity)",
    VIOL_QUEUE_FULL: "Frontier queue full (raise queue_capacity)",
    VIOL_ROUTE_OVERFLOW: "Routing bucket overflow (raise route_factor)",
}


class EngineCarry(NamedTuple):
    fps: FPSet
    queue: jnp.ndarray  # [qcap + 1, F] (last row = scatter dump)
    qhead: jnp.ndarray  # int32
    qtail: jnp.ndarray  # int32
    level_end: jnp.ndarray  # int32: queue index fencing the current level
    level: jnp.ndarray  # int32: BFS level of states being popped (init = 1)
    depth: jnp.ndarray  # int32: deepest nonempty level
    generated: jnp.ndarray  # uint32
    distinct: jnp.ndarray  # uint32
    act_gen: jnp.ndarray  # [n_labels + 1] uint32
    act_dist: jnp.ndarray  # [n_labels + 1] uint32
    outdeg_hist: jnp.ndarray  # [L + 2] uint32: #popped states with d new
    # children (TLC's outdegree, MC.out:1104); last row = scatter dump
    viol: jnp.ndarray  # int32 code
    viol_state: jnp.ndarray  # [F] int32
    viol_action: jnp.ndarray  # int32


class CheckResult(NamedTuple):
    generated: int
    distinct: int
    depth: int
    queue_left: int
    violation: int
    violation_name: str
    violation_state: np.ndarray
    violation_action: int
    action_generated: dict
    action_distinct: dict
    wall_s: float
    iterations: int
    # (avg, min, max, p95) of TLC's outdegree = distinct new states per
    # expanded state (matches MC.out:1104); None when not tracked (sharded)
    outdegree: tuple = None


def make_engine(
    cfg: ModelConfig,
    chunk: int = 1024,
    queue_capacity: int = 1 << 15,
    fp_capacity: int = 1 << 20,
    fp_index: int = DEFAULT_FP_INDEX,
    seed: int = DEFAULT_SEED,
):
    """Build (init_fn, run_fn, step_fn) for one configuration.

    init_fn() -> EngineCarry seeded with the Init states.
    run_fn(carry) -> EngineCarry after exhaustion/violation (jitted, fused).
    step_fn(carry) -> EngineCarry after ONE chunk (jitted; for checkpointed
    / incremental runs).
    """
    cdc = get_codec(cfg)
    F = cdc.n_fields
    step = make_kernel(cfg)
    L = step.n_lanes
    inv_check = make_invariant_kernel(cfg)
    n_labels = len(LABELS)
    nbits = cdc.nbits
    qcap = queue_capacity

    def init_fn() -> EngineCarry:
        inits = jnp.asarray(initial_vectors(cfg))
        n0 = inits.shape[0]
        queue = jnp.zeros((qcap + 1, F), jnp.int32).at[:n0].set(inits)
        packed = cdc.pack(inits)
        lo, hi = fp64_words(packed, nbits, fp_index, seed)
        fps, is_new = fpset_insert(
            fpset_new(fp_capacity), lo, hi, jnp.ones(n0, bool)
        )
        distinct0 = is_new.sum().astype(jnp.uint32)
        return EngineCarry(
            fps=fps,
            queue=queue,
            qhead=jnp.int32(0),
            qtail=jnp.int32(n0),
            level_end=jnp.int32(n0),
            level=jnp.int32(1),
            depth=jnp.int32(1),
            generated=jnp.uint32(n0),
            distinct=distinct0,
            act_gen=jnp.zeros(n_labels + 1, jnp.uint32),
            act_dist=jnp.zeros(n_labels + 1, jnp.uint32),
            outdeg_hist=jnp.zeros(L + 2, jnp.uint32),
            viol=jnp.int32(OK),
            viol_state=jnp.zeros(F, jnp.int32),
            viol_action=jnp.int32(-1),
        )

    def body(c: EngineCarry) -> EngineCarry:
        avail = jnp.minimum(c.level_end, c.qtail) - c.qhead
        n = jnp.minimum(chunk, avail)
        rows = jnp.arange(chunk, dtype=jnp.int32)
        mask = rows < n
        idx = (c.qhead + rows) % qcap
        batch = c.queue[idx]

        succs, valid, action, afail, ovf = jax.vmap(step)(batch)
        valid = valid & mask[:, None]
        afail = afail & valid
        ovf = ovf & valid
        dead = mask & ~valid.any(axis=1)

        flat = succs.reshape(chunk * L, F)
        fvalid = valid.reshape(-1)
        faction = action.reshape(-1)

        inv = jax.vmap(inv_check)(flat)
        bad_type = fvalid & ((inv & 1) == 0)
        bad_oov = fvalid & ((inv & 2) == 0)

        packed = cdc.pack(flat)
        lo, hi = fp64_words(packed, nbits, fp_index, seed)

        fp_full = (c.distinct.astype(jnp.int32) + chunk * L) > int(
            fp_capacity * 0.85
        )
        insert_mask = fvalid & ~fp_full
        fps, is_new = fpset_insert(c.fps, lo, hi, insert_mask)

        n_new = is_new.sum().astype(jnp.int32)
        q_full = (c.qtail - c.qhead) + n_new > qcap

        # append new states (prefix-sum positions; dump row for non-new)
        pos = c.qtail + jnp.cumsum(is_new.astype(jnp.int32)) - 1
        tgt = jnp.where(is_new & ~q_full, pos % qcap, qcap)
        queue = c.queue.at[tgt].set(flat)

        # counters
        generated = c.generated + valid.sum().astype(jnp.uint32)
        distinct = c.distinct + n_new.astype(jnp.uint32)
        act_gen = c.act_gen.at[jnp.where(fvalid, faction, n_labels)].add(1)
        act_dist = c.act_dist.at[jnp.where(is_new, faction, n_labels)].add(1)
        # TLC outdegree = distinct new successors per expanded state
        newdeg = is_new.reshape(chunk, L).sum(axis=1)
        outdeg_hist = c.outdeg_hist.at[jnp.where(mask, newdeg, L + 1)].add(1)

        # violations (first wins; priority: invariant > assert > deadlock >
        # capacity).  Capture the offending state: candidate for invariants,
        # source state for assert/deadlock.
        def first_state(mask_flat, states):
            i = jnp.argmax(mask_flat)
            return states[i]

        viol = c.viol
        viol_state = c.viol_state
        viol_action = c.viol_action

        for code, vmask, states, acts in (
            (VIOL_TYPEOK, bad_type, flat, faction),
            (VIOL_ONLYONEVERSION, bad_oov, flat, faction),
            (VIOL_ASSERT, afail.reshape(-1), jnp.repeat(batch, L, axis=0), faction),
            (VIOL_DEADLOCK, dead, batch, jnp.full(chunk, -1, jnp.int32)),
            (VIOL_SLOT_OVERFLOW, ovf.reshape(-1), jnp.repeat(batch, L, axis=0), faction),
        ):
            hit = vmask.any() & (viol == OK)
            viol = jnp.where(hit, code, viol)
            viol_state = jnp.where(hit, first_state(vmask, states), viol_state)
            viol_action = jnp.where(
                hit, acts[jnp.argmax(vmask)].astype(jnp.int32), viol_action
            )
        hit = fp_full & fvalid.any() & (viol == OK)
        viol = jnp.where(hit, VIOL_FPSET_FULL, viol)
        hit = q_full & (viol == OK)
        viol = jnp.where(hit, VIOL_QUEUE_FULL, viol)

        # advance FIFO + level bookkeeping
        qhead = c.qhead + n
        qtail = jnp.where(q_full, c.qtail, c.qtail + n_new)
        level_done = qhead == c.level_end
        more = qtail > qhead
        level = jnp.where(level_done & more, c.level + 1, c.level)
        depth = jnp.maximum(c.depth, jnp.where(more, level, c.level))
        level_end = jnp.where(level_done, qtail, c.level_end)

        return EngineCarry(
            fps=fps,
            queue=queue,
            qhead=qhead,
            qtail=qtail,
            level_end=level_end,
            level=level,
            depth=depth,
            generated=generated,
            distinct=distinct,
            act_gen=act_gen,
            act_dist=act_dist,
            outdeg_hist=outdeg_hist,
            viol=viol,
            viol_state=viol_state,
            viol_action=viol_action,
        )

    def cond(c: EngineCarry):
        return (c.qtail > c.qhead) & (c.viol == OK)

    @jax.jit
    def run_fn(c: EngineCarry) -> EngineCarry:
        return lax.while_loop(cond, body, c)

    @jax.jit
    def step_fn(c: EngineCarry) -> EngineCarry:
        return lax.cond(cond(c), body, lambda x: x, c)

    return init_fn, run_fn, step_fn


def check(
    cfg: ModelConfig,
    chunk: int = 1024,
    queue_capacity: int = 1 << 15,
    fp_capacity: int = 1 << 20,
    fp_index: int = DEFAULT_FP_INDEX,
    seed: int = DEFAULT_SEED,
) -> CheckResult:
    """Run an exhaustive check; the single-device engine entry point.

    The fused loop is AOT-compiled (`lower().compile()`) before timing, so
    wall_s measures execution only - the honest time-to-exhaustive figure
    (compilation is a one-time cost, amortized in TLC by the JVM the same
    way)."""
    init_fn, run_fn, _ = make_engine(
        cfg, chunk, queue_capacity, fp_capacity, fp_index, seed
    )
    carry = init_fn()
    compiled = run_fn.lower(carry).compile()
    t0 = time.time()
    carry = jax.block_until_ready(compiled(carry))
    wall = time.time() - t0
    return result_from_carry(carry, wall)


def outdegree_from_hist(hist: np.ndarray):
    """(avg, min, max, p95) of TLC's outdegree from a new-children
    histogram (hist[d] = #expanded states with d new successors); None if
    empty.  Matches MC.out:1104's reporting convention."""
    hist = np.asarray(hist, dtype=np.int64)
    total = hist.sum()
    if total == 0:
        return None
    degs = np.arange(len(hist))
    nz = np.flatnonzero(hist)
    cum = np.cumsum(hist)
    p95 = int(degs[np.searchsorted(cum, 0.95 * total)])
    return (
        int(round((degs * hist).sum() / total)),
        int(nz[0]),
        int(nz[-1]),
        p95,
    )


def result_from_carry(
    carry: EngineCarry, wall_s: float, iterations: int = -1
) -> CheckResult:
    """Pull a finished (or interrupted) carry to host as a CheckResult."""
    act_gen = np.asarray(carry.act_gen)[: len(LABELS)]
    act_dist = np.asarray(carry.act_dist)[: len(LABELS)]
    hist = np.asarray(carry.outdeg_hist)[:-1].astype(np.int64)  # drop dump
    outdegree = outdegree_from_hist(hist)
    return CheckResult(
        generated=int(carry.generated),
        distinct=int(carry.distinct),
        depth=int(carry.depth),
        queue_left=int(carry.qtail - carry.qhead),
        violation=int(carry.viol),
        violation_name=VIOLATION_NAMES[int(carry.viol)],
        violation_state=np.asarray(carry.viol_state),
        violation_action=int(carry.viol_action),
        action_generated={
            LABELS[i]: int(v) for i, v in enumerate(act_gen) if v
        },
        action_distinct={
            LABELS[i]: int(v) for i, v in enumerate(act_dist) if v
        },
        wall_s=wall_s,
        iterations=iterations,
        outdegree=outdegree,
    )
