"""Fully device-resident BFS model-checking engine.

The TLC BFS core replacement (tlc2.tool.Worker + DiskStateQueue +
OffHeapDiskFPSet, /root/reference/KubeAPI.toolbox/Model_1/MC.out:5): one
``lax.while_loop`` whose body pops a fixed-size chunk from the frontier,
expands it through the vmapped next-state kernel, evaluates invariants,
fingerprints + dedups against the device hash table, and appends the new
states - no host round-trips until the state space is exhausted or a
violation is found.

v4 data layout, driven by on-chip microbenchmarks (tools/microbench.py:
random row scatters ~140ns/row dominate; contiguous dynamic-slice writes
are 3-9x cheaper; sorts are cheap):

* The frontier is a ping-pong pair of level buffers of *packed* state
  words ([2, qcap + 2*chunk, W] uint32): pops are contiguous dynamic
  slices, appends are contiguous dynamic-update-slices of fingerprint-
  sorted new states - no row scatters on the queue at all.  States are
  unpacked to field vectors only at the kernel boundary (codec.unpack).
* Dedup probes only the sort-compacted unique candidates
  (fpset.fpset_insert_sorted), and per-new-state bookkeeping (enqueue,
  per-action distinct counts, outdegree credit) runs over compacted
  A-wide segments instead of the full chunk*L candidate array.
* Fingerprints ride the MXU (fingerprint.fp64_words_mxu).
* Per-action generated counters are factorized through the dispatch
  structure (all lanes of a client share that client's pc label; server
  lanes are always APIStart) instead of scatter-adds over all candidates.

Level-synchronous by construction: a chunk never crosses a BFS level
boundary, so reported depth is the exact BFS level count, matching TLC's
"depth of the complete state graph search" (MC.out:1101), and in-batch
fingerprint arbitration never has to choose between states of different
levels.

Violation handling: the fused loop carries a violation code + the
offending encoded state; on violation the CLI re-runs in the host driver
(engine.hostdriver) which keeps parent pointers and reconstructs the
counterexample trace (TLC trace-explorer analog, SURVEY.md §2.3 E11).
"""

from __future__ import annotations

import time
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..config import ModelConfig
from ..spec.labels import LABELS
from .fingerprint import DEFAULT_FP_INDEX, DEFAULT_SEED, fp64_words_mxu
from .fpset import fpset_insert_sorted, fpset_new

# violation codes
OK = 0
VIOL_TYPEOK = 1
VIOL_ONLYONEVERSION = 2
VIOL_ASSERT = 3
VIOL_DEADLOCK = 4
VIOL_SLOT_OVERFLOW = 5
VIOL_FPSET_FULL = 6
VIOL_QUEUE_FULL = 7
VIOL_ROUTE_OVERFLOW = 8

VIOLATION_NAMES = {
    OK: "none",
    VIOL_TYPEOK: "Invariant TypeOK is violated",
    VIOL_ONLYONEVERSION: "Invariant OnlyOneVersion is violated",
    VIOL_ASSERT: "Assertion failure (PlusCal assert)",
    VIOL_DEADLOCK: "Deadlock reached",
    VIOL_SLOT_OVERFLOW: "Codec slot overflow (raise ModelConfig bounds)",
    VIOL_FPSET_FULL: "Fingerprint table full (raise fp_capacity)",
    VIOL_QUEUE_FULL: "Frontier queue full (raise queue_capacity)",
    VIOL_ROUTE_OVERFLOW: "Routing bucket overflow (raise route_factor)",
}


class EngineCarry(NamedTuple):
    fps: "FPSet"  # noqa: F821 - fpset.FPSet
    queue: jnp.ndarray  # [2, qcap + 2*chunk, W] uint32 packed level buffers
    parity: jnp.ndarray  # int32: which buffer holds the CURRENT level
    qhead: jnp.ndarray  # int32: pop position within the current level
    level_n: jnp.ndarray  # int32: states in the current level
    next_n: jnp.ndarray  # int32: states appended to the next level so far
    level: jnp.ndarray  # int32: BFS level of states being popped (init = 1)
    depth: jnp.ndarray  # int32: deepest nonempty level
    generated: jnp.ndarray  # uint32
    distinct: jnp.ndarray  # uint32
    act_gen: jnp.ndarray  # [n_labels + 1] uint32
    act_dist: jnp.ndarray  # [n_labels + 1] uint32
    outdeg_hist: jnp.ndarray  # [L + 2] uint32: #popped states with d new
    # children (TLC's outdegree, MC.out:1104); last row = scatter dump
    viol: jnp.ndarray  # int32 code
    viol_state: jnp.ndarray  # [F] int32
    viol_action: jnp.ndarray  # int32


class CheckResult(NamedTuple):
    generated: int
    distinct: int
    depth: int
    queue_left: int
    violation: int
    violation_name: str
    violation_state: np.ndarray
    violation_action: int
    action_generated: dict
    action_distinct: dict
    wall_s: float
    iterations: int
    # (avg, min, max, p95) of TLC's outdegree = distinct new states per
    # expanded state (matches MC.out:1104); None when not tracked (sharded)
    outdegree: tuple = None
    # TLC's "based on the actual fingerprints" collision estimate
    # (MC.out:42); None when the engine variant doesn't compute it
    actual_fp_collision: float = None
    # final fingerprint-table load: distinct / fp_capacity (summed over
    # shards for the mesh engine); None when the driver didn't compute it.
    # Reported on the 2193 stats line so users can size fp_capacity (and
    # see how close a run came to the fp_highwater regrow trigger)
    fp_occupancy: float = None


def carry_done(carry: EngineCarry) -> bool:
    """Host-side termination check (used by the checkpointed driver)."""
    return (
        int(carry.level_n) - int(carry.qhead) <= 0 and int(carry.next_n) == 0
    ) or int(carry.viol) != OK


DEFAULT_FP_HIGHWATER = 0.85


def make_engine(
    cfg: ModelConfig,
    chunk: int = 1024,
    queue_capacity: int = 1 << 15,
    fp_capacity: int = 1 << 20,
    fp_index: int = DEFAULT_FP_INDEX,
    seed: int = DEFAULT_SEED,
    fp_highwater: float = DEFAULT_FP_HIGHWATER,
):
    """Build (init_fn, run_fn, step_fn) for one KubeAPI configuration.

    The hand-tuned KubeAPI path of make_backend_engine: the factorized
    per-action counters and the rest of the v4 loop now come through the
    SpecBackend seam, so this is a specialization, not a privilege."""
    from .backend import kubeapi_backend

    return make_backend_engine(
        kubeapi_backend(cfg), chunk, queue_capacity, fp_capacity,
        fp_index, seed, fp_highwater=fp_highwater,
    )


def make_backend_engine(
    backend,
    chunk: int = 1024,
    queue_capacity: int = 1 << 15,
    fp_capacity: int = 1 << 20,
    fp_index: int = DEFAULT_FP_INDEX,
    seed: int = DEFAULT_SEED,
    fp_highwater: float = DEFAULT_FP_HIGHWATER,
    check_deadlock: bool = None,
):
    """Build (init_fn, run_fn, step_fn) over any SpecBackend.

    init_fn() -> EngineCarry seeded with the Init states.
    run_fn(carry) -> EngineCarry after exhaustion/violation (jitted, fused).
    step_fn(carry) -> EngineCarry after ONE chunk (jitted; for checkpointed
    / incremental runs).

    queue_capacity bounds the width of a single BFS level (the frontier),
    not the total state count: levels ping-pong between two buffers.

    fp_highwater is the fingerprint-table load fraction at which the run
    halts with VIOL_FPSET_FULL instead of degrading into long straggler
    walks (open addressing past ~0.85 load is where probe cost blows up);
    the supervisor's auto-regrow doubles fp_capacity at this trigger.

    check_deadlock overrides the backend's default (TLC's -deadlock
    switch; None takes backend.check_deadlock).
    """
    assert 0.0 < fp_highwater <= 1.0, "fp_highwater must be in (0, 1]"
    cdc = backend.cdc
    F = cdc.n_fields
    W = (cdc.nbits + 31) // 32
    step = backend.step
    L = backend.n_lanes
    inv_check = backend.inv_check
    inv_codes = backend.inv_codes
    n_labels = len(backend.labels)
    nbits = cdc.nbits
    qcap = queue_capacity
    if check_deadlock is None:
        check_deadlock = backend.check_deadlock
    # two-tier adaptive stepping: a step's cost is dominated by fixed
    # chunk-sized work regardless of how few states it pops, so narrow
    # levels (the BFS ramp/tail) and level remainders run a small body
    # instead of paying a full big-chunk step
    small = chunk // 16 if chunk >= 1 << 14 else 0

    label_ids = jnp.arange(n_labels, dtype=jnp.int32)
    lane_action = backend.lane_action
    gen_counts_fn = backend.gen_counts

    def init_fn() -> EngineCarry:
        inits = jnp.asarray(backend.initial_vectors())
        n0 = inits.shape[0]
        assert n0 <= chunk and n0 <= qcap, "raise chunk/queue_capacity"
        packed0 = cdc.pack(inits)
        queue = (
            jnp.zeros((2, qcap + 2 * chunk, W), jnp.uint32)
            .at[0, :n0]
            .set(packed0)
        )
        lo, hi = fp64_words_mxu(packed0, nbits, fp_index, seed)
        fps, is_new_c, _, _ = fpset_insert_sorted(
            fpset_new(fp_capacity), lo, hi, jnp.ones(n0, bool)
        )
        distinct0 = is_new_c.sum().astype(jnp.uint32)
        # invariants hold on the initial states too (TLC checks them
        # before the first Next application)
        inv0 = jax.vmap(inv_check)(inits)
        viol = jnp.int32(OK)
        viol_state = jnp.zeros(F, jnp.int32)
        for k, code in enumerate(inv_codes):
            bad = (inv0 & (1 << k)) == 0
            hit = bad.any() & (viol == OK)
            viol = jnp.where(hit, code, viol)
            viol_state = jnp.where(hit, inits[jnp.argmax(bad)], viol_state)
        return EngineCarry(
            fps=fps,
            queue=queue,
            parity=jnp.int32(0),
            qhead=jnp.int32(0),
            level_n=jnp.int32(n0),
            next_n=jnp.int32(0),
            level=jnp.int32(1),
            depth=jnp.int32(1),
            generated=jnp.uint32(n0),
            distinct=distinct0,
            act_gen=jnp.zeros(n_labels + 1, jnp.uint32),
            act_dist=jnp.zeros(n_labels + 1, jnp.uint32),
            outdeg_hist=jnp.zeros(L + 2, jnp.uint32),
            viol=viol,
            viol_state=viol_state,
            viol_action=jnp.int32(-1),
        )

    def make_body(ck: int):
        """One BFS step popping up to `ck` states (carry shape-invariant)."""
        ncand = ck * L
        # compaction widths: probe/claim/enqueue touch only this many rows
        # per segment; steady-state new-per-chunk == chunk, so 2x covers
        # bursts and the segment loops keep worst cases exact
        R = min(2 * ck, ncand)  # fpset probe width
        CW = min(2 * ck, R)  # fpset round-0 claim width
        A = min(2 * ck, ncand)  # enqueue/stat segment width
        return lambda c: step_body(c, ck, ncand, R, CW, A)

    def step_body(c: EngineCarry, chunk: int, ncand: int, R: int, CW: int,
                  A: int) -> EngineCarry:
        avail = c.level_n - c.qhead
        n = jnp.minimum(chunk, avail)
        rows = jnp.arange(chunk, dtype=jnp.int32)
        mask = rows < n

        # contiguous pop (the buffer is chunk-padded so no OOB clamping)
        block = lax.dynamic_slice(
            c.queue, (c.parity, c.qhead, jnp.int32(0)), (1, chunk, W)
        )[0]
        batch = cdc.unpack(block)

        succs, valid, action, afail, ovf = jax.vmap(step)(batch)
        valid = valid & mask[:, None]
        afail = afail & valid
        ovf = ovf & valid
        dead = (
            mask & ~valid.any(axis=1) if check_deadlock
            else jnp.zeros(chunk, bool)
        )

        flat = succs.reshape(ncand, F)
        fvalid = valid.reshape(-1)
        faction = action.reshape(-1)

        inv = jax.vmap(inv_check)(flat)
        inv_bad = [
            fvalid & ((inv & (1 << k)) == 0)
            for k in range(len(inv_codes))
        ]

        packed = cdc.pack(flat)
        lo, hi = fp64_words_mxu(packed, nbits, fp_index, seed)

        fp_full = (c.distinct.astype(jnp.int32) + ncand) > int(
            fp_capacity * fp_highwater
        )
        insert_mask = fvalid & ~fp_full
        fps, is_new_c, c_idx, nreps = fpset_insert_sorted(
            c.fps, lo, hi, insert_mask, probe_width=R, claim_width=CW
        )
        n_new = is_new_c.sum().astype(jnp.int32)
        q_full = c.next_n + n_new > qcap

        # enqueue + per-new-state stats: bring new entries to the front
        # ordered by original lane index (2-key sort) - the same append
        # order as the v3 scatter engine, so pop order and therefore
        # in-batch attribution statistics (outdegree min/max, MC.out:1104)
        # are preserved bit-for-bit.  All new entries sit in the first
        # nreps compacted positions, so when nreps fits the probe width
        # the sort runs at R width instead of ncand (~6x less comparator
        # traffic); the full-width branch covers all-distinct bursts.
        new_key = (~is_new_c).astype(jnp.uint32)
        cidx_u = c_idx.astype(jnp.uint32)

        def e_sorted_sliced(_):
            _, e = lax.sort(
                (new_key[:R], cidx_u[:R]), num_keys=2, is_stable=True
            )
            return jnp.concatenate([e, jnp.zeros(ncand - R, jnp.uint32)])

        def e_sorted_full(_):
            _, e = lax.sort((new_key, cidx_u), num_keys=2, is_stable=True)
            return e

        if R == ncand:
            _, e_idx = lax.sort(
                (new_key, cidx_u), num_keys=2, is_stable=True
            )
        else:
            e_idx = lax.cond(
                nreps <= R, e_sorted_sliced, e_sorted_full, 0
            )
        e_idx_p = jnp.concatenate([e_idx, jnp.zeros(A, jnp.uint32)])

        def enq_cond(st):
            _, _, s = st
            return s * A < n_new

        def enq_body(st):
            queue, act_dist, s = st
            offs = s * A
            idx_a = lax.dynamic_slice(e_idx_p, (offs,), (A,)).astype(
                jnp.int32
            )
            active = (jnp.arange(A) + offs) < n_new
            rows_a = packed[idx_a]  # [A, W] row gather (the only one)
            woff = jnp.minimum(c.next_n + offs, qcap)
            queue = lax.dynamic_update_slice(
                queue, rows_a[None], (1 - c.parity, woff, jnp.int32(0))
            )
            # per-action distinct counts by [A, n_labels] compare-reduce
            # (scatter-adds cost ~140ns/element on-chip)
            acts_a = faction[idx_a]
            act_dist = act_dist.at[:n_labels].add(
                (
                    (acts_a[:, None] == label_ids[None, :])
                    & active[:, None]
                ).sum(axis=0).astype(jnp.uint32)
            )
            return queue, act_dist, s + 1

        queue, act_dist, _ = lax.while_loop(
            enq_cond, enq_body, (c.queue, c.act_dist, jnp.int32(0))
        )

        # outdegree histogram of the popped states (TLC's outdegree =
        # distinct new successors per expansion, MC.out:1104) via run
        # lengths: e_idx's active prefix is ascending in source row, so
        # each row's new-child count is a run length - no [chunk+1]-bin
        # scatter-add
        pos = jnp.arange(ncand)
        active_new = pos < n_new
        src_e = jnp.where(active_new, e_idx.astype(jnp.int32) // L, -1)
        startf = jnp.concatenate(
            [jnp.ones(1, bool), src_e[1:] != src_e[:-1]]
        ) & active_new
        endf = jnp.concatenate(
            [src_e[1:] != src_e[:-1], jnp.ones(1, bool)]
        ) & active_new
        run0 = lax.cummax(jnp.where(startf, pos, 0))
        run_len = jnp.where(endf, pos - run0 + 1, 0)
        nruns = startf.sum()
        deg_hist = (
            (run_len[:, None] == jnp.arange(1, L + 1)[None, :])
            .sum(axis=0)
            .astype(jnp.uint32)
        )
        outdeg_hist = c.outdeg_hist.at[1 : L + 1].add(deg_hist)
        outdeg_hist = outdeg_hist.at[0].add(
            (n - nruns).astype(jnp.uint32)
        )

        # per-action generated counters, scatter-free: the backend's
        # factorized hook (KubeAPI dispatch structure, PERF.md item 5)
        # when it has one, a [L, n_labels] fold for static lane
        # dispatches (gen/struct compilers), a per-candidate
        # compare-reduce otherwise
        if gen_counts_fn is not None:
            gen_counts = gen_counts_fn(batch, valid)
        elif lane_action is not None:
            lane_counts = valid.sum(axis=0).astype(jnp.uint32)
            gen_counts = (
                (lane_action[:, None] == label_ids[None, :])
                * lane_counts[:, None]
            ).sum(axis=0).astype(jnp.uint32)
        else:
            gen_counts = (
                (faction[:, None] == label_ids[None, :])
                & fvalid[:, None]
            ).sum(axis=0).astype(jnp.uint32)
        act_gen = c.act_gen.at[:n_labels].add(gen_counts)

        generated = c.generated + valid.sum().astype(jnp.uint32)
        distinct = c.distinct + n_new.astype(jnp.uint32)

        # violations (first wins; priority: invariant > assert > deadlock >
        # capacity).  Capture the offending state: candidate for invariants,
        # source state for assert/deadlock.
        def first_state(mask_flat, states):
            i = jnp.argmax(mask_flat)
            return states[i]

        viol = c.viol
        viol_state = c.viol_state
        viol_action = c.viol_action

        for code, vmask, states, acts in (
            *((code, bad, flat, faction)
              for code, bad in zip(inv_codes, inv_bad)),
            (VIOL_ASSERT, afail.reshape(-1), jnp.repeat(batch, L, axis=0), faction),
            (VIOL_DEADLOCK, dead, batch, jnp.full(chunk, -1, jnp.int32)),
            (VIOL_SLOT_OVERFLOW, ovf.reshape(-1), jnp.repeat(batch, L, axis=0), faction),
        ):
            hit = vmask.any() & (viol == OK)
            viol = jnp.where(hit, code, viol)
            viol_state = jnp.where(hit, first_state(vmask, states), viol_state)
            viol_action = jnp.where(
                hit, acts[jnp.argmax(vmask)].astype(jnp.int32), viol_action
            )
        hit = fp_full & fvalid.any() & (viol == OK)
        viol = jnp.where(hit, VIOL_FPSET_FULL, viol)
        hit = q_full & (viol == OK)
        viol = jnp.where(hit, VIOL_QUEUE_FULL, viol)

        # level bookkeeping: ping-pong at the level boundary
        qhead = c.qhead + n
        next_n = jnp.minimum(c.next_n + n_new, qcap)
        level_done = qhead >= c.level_n
        advance = level_done & (next_n > 0)
        parity = jnp.where(level_done, 1 - c.parity, c.parity)
        level_n = jnp.where(level_done, next_n, c.level_n)
        next_n = jnp.where(level_done, 0, next_n)
        qhead = jnp.where(level_done, 0, qhead)
        level = jnp.where(advance, c.level + 1, c.level)
        depth = jnp.maximum(c.depth, level)

        return EngineCarry(
            fps=fps,
            queue=queue,
            parity=parity,
            qhead=qhead,
            level_n=level_n,
            next_n=next_n,
            level=level,
            depth=depth,
            generated=generated,
            distinct=distinct,
            act_gen=act_gen,
            act_dist=act_dist,
            outdeg_hist=outdeg_hist,
            viol=viol,
            viol_state=viol_state,
            viol_action=viol_action,
        )

    big_body = make_body(chunk)
    if small:
        small_body = make_body(small)
        # break-even: a big step costs ~what chunk/small small steps cost,
        # so take the big body only when the level remainder mostly fills it
        def body(c: EngineCarry) -> EngineCarry:
            avail = c.level_n - c.qhead
            return lax.cond(avail >= chunk // 2, big_body, small_body, c)
    else:
        body = big_body

    def cond(c: EngineCarry):
        return ((c.qhead < c.level_n) | (c.next_n > 0)) & (c.viol == OK)

    @jax.jit
    def run_fn(c: EngineCarry) -> EngineCarry:
        return lax.while_loop(cond, body, c)

    @jax.jit
    def step_fn(c: EngineCarry) -> EngineCarry:
        return lax.cond(cond(c), body, lambda x: x, c)

    return init_fn, run_fn, step_fn


def check(
    cfg: ModelConfig,
    chunk: int = 1024,
    queue_capacity: int = 1 << 15,
    fp_capacity: int = 1 << 20,
    fp_index: int = DEFAULT_FP_INDEX,
    seed: int = DEFAULT_SEED,
    fp_highwater: float = DEFAULT_FP_HIGHWATER,
) -> CheckResult:
    """Run an exhaustive check; the single-device engine entry point.

    The fused loop is AOT-compiled (`lower().compile()`) before timing, so
    wall_s measures execution only - the honest time-to-exhaustive figure
    (compilation is a one-time cost, amortized in TLC by the JVM the same
    way)."""
    init_fn, run_fn, _ = make_engine(
        cfg, chunk, queue_capacity, fp_capacity, fp_index, seed,
        fp_highwater=fp_highwater,
    )
    carry = init_fn()
    compiled = run_fn.lower(carry).compile()
    t0 = time.time()
    carry = jax.block_until_ready(compiled(carry))
    wall = time.time() - t0
    from .fpset import fpset_actual_collision

    afc = float(fpset_actual_collision(carry.fps))
    return result_from_carry(carry, wall, fp_capacity=fp_capacity)._replace(
        actual_fp_collision=afc
    )


class EnumCarry(NamedTuple):
    """Carry of the fused state enumerator (liveness edge-capture pass 1).

    Unlike EngineCarry's ping-pong level buffers, `states` is APPEND-ONLY:
    a state's row index is its permanent id (BFS append order), which is
    exactly what the device-resident liveness subsystem (jaxtlc.live)
    needs - the edge relation is expressed over these ids."""

    fps: tuple  # fpset.FPSet
    states: jnp.ndarray  # [cap + A, W] uint32 packed states, id = row
    head: jnp.ndarray  # int32: next id to expand
    tail: jnp.ndarray  # int32: number of distinct states stored
    viol: jnp.ndarray  # int32: OK or a capacity/overflow code


def make_enumerator(
    backend,
    chunk: int = 1024,
    state_capacity: int = 1 << 20,
    fp_capacity: int = 1 << 20,
    fp_index: int = DEFAULT_FP_INDEX,
    seed: int = DEFAULT_SEED,
    fp_highwater: float = DEFAULT_FP_HIGHWATER,
):
    """Build (init_fn, run_fn) for the fused distinct-state enumerator.

    The optional capture mode of the BFS core: the same vmapped kernel +
    MXU fingerprints + sort-compacted dedup as the exhaustive engine, but
    the frontier is the append-only `states` array itself (a work-list
    pop cursor instead of level fencing), so after one fused
    `lax.while_loop` the whole reachable set sits on device in id order.
    `backend` is any engine.sharded.SpecBackend (kubeapi_backend /
    gen_backend), so every frontend that can run sharded can be
    enumerated - the seam the liveness capture (jaxtlc.live.capture)
    feeds on.

    Halts loudly with VIOL_QUEUE_FULL when `state_capacity` is exceeded
    (the caller's cue to raise it or spill), VIOL_FPSET_FULL /
    VIOL_SLOT_OVERFLOW as in the exhaustive engine.
    """
    cdc = backend.cdc
    F = cdc.n_fields
    W = (cdc.nbits + 31) // 32
    step = backend.step
    L = backend.n_lanes
    nbits = cdc.nbits
    cap = state_capacity
    ncand = chunk * L
    R = min(2 * chunk, ncand)
    A = min(2 * chunk, ncand)

    def init_fn() -> EnumCarry:
        inits = jnp.asarray(backend.initial_vectors())
        n0 = inits.shape[0]
        assert n0 <= chunk and n0 <= cap, "raise chunk/state_capacity"
        packed0 = cdc.pack(inits)
        states = jnp.zeros((cap + A, W), jnp.uint32).at[:n0].set(packed0)
        lo, hi = fp64_words_mxu(packed0, nbits, fp_index, seed)
        fps, _, _, _ = fpset_insert_sorted(
            fpset_new(fp_capacity), lo, hi, jnp.ones(n0, bool)
        )
        return EnumCarry(
            fps=fps,
            states=states,
            head=jnp.int32(0),
            tail=jnp.int32(n0),
            viol=jnp.int32(OK),
        )

    def body(c: EnumCarry) -> EnumCarry:
        avail = c.tail - c.head
        n = jnp.minimum(chunk, avail)
        rows = jnp.arange(chunk, dtype=jnp.int32)
        mask = rows < n

        block = lax.dynamic_slice(
            c.states, (c.head, jnp.int32(0)), (chunk, W)
        )
        batch = cdc.unpack(block)
        succs, valid, _action, _afail, ovf = jax.vmap(step)(batch)
        valid = valid & mask[:, None]
        ovf = ovf & valid

        flat = succs.reshape(ncand, F)
        fvalid = valid.reshape(-1)
        packed = cdc.pack(flat)
        lo, hi = fp64_words_mxu(packed, nbits, fp_index, seed)

        fp_full = (c.tail + ncand) > int(fp_capacity * fp_highwater)
        fps, is_new_c, c_idx, _ = fpset_insert_sorted(
            c.fps, lo, hi, fvalid & ~fp_full, probe_width=R, claim_width=R
        )
        n_new = is_new_c.sum().astype(jnp.int32)
        s_full = c.tail + n_new > cap

        # append new states at the tail in candidate order (the engines'
        # sort-compact + A-wide contiguous-write pattern)
        _, e_idx = lax.sort(
            ((~is_new_c).astype(jnp.uint32), c_idx.astype(jnp.uint32)),
            num_keys=2,
            is_stable=True,
        )
        e_idx_p = jnp.concatenate([e_idx, jnp.zeros(A, jnp.uint32)])

        def enq_cond(st):
            _, s = st
            return s * A < n_new

        def enq_body(st):
            states, s = st
            offs = s * A
            idx_a = lax.dynamic_slice(e_idx_p, (offs,), (A,)).astype(
                jnp.int32
            )
            rows_a = packed[idx_a]
            woff = jnp.minimum(c.tail + offs, cap)
            states = lax.dynamic_update_slice(
                states, rows_a, (woff, jnp.int32(0))
            )
            return states, s + 1

        states, _ = lax.while_loop(
            enq_cond, enq_body, (c.states, jnp.int32(0))
        )

        viol = c.viol
        viol = jnp.where(ovf.any() & (viol == OK), VIOL_SLOT_OVERFLOW, viol)
        viol = jnp.where(
            fp_full & fvalid.any() & (viol == OK), VIOL_FPSET_FULL, viol
        )
        viol = jnp.where(s_full & (viol == OK), VIOL_QUEUE_FULL, viol)
        tail = jnp.where(s_full, c.tail, c.tail + n_new)
        return EnumCarry(
            fps=fps, states=states, head=c.head + n, tail=tail, viol=viol
        )

    def cond(c: EnumCarry):
        return (c.head < c.tail) & (c.viol == OK)

    @jax.jit
    def run_fn(c: EnumCarry) -> EnumCarry:
        return lax.while_loop(cond, body, c)

    return init_fn, run_fn


def outdegree_from_hist(hist: np.ndarray):
    """(avg, min, max, p95) of TLC's outdegree from a new-children
    histogram (hist[d] = #expanded states with d new successors); None if
    empty.  Matches MC.out:1104's reporting convention."""
    hist = np.asarray(hist, dtype=np.int64)
    total = hist.sum()
    if total == 0:
        return None
    degs = np.arange(len(hist))
    nz = np.flatnonzero(hist)
    cum = np.cumsum(hist)
    p95 = int(degs[np.searchsorted(cum, 0.95 * total)])
    return (
        int(round((degs * hist).sum() / total)),
        int(nz[0]),
        int(nz[-1]),
        p95,
    )


def result_from_carry(
    carry: EngineCarry, wall_s: float, iterations: int = -1,
    fp_capacity: int = 0, labels: tuple = LABELS, viol_names: dict = None,
) -> CheckResult:
    """Pull a finished (or interrupted) carry to host as a CheckResult."""
    act_gen = np.asarray(carry.act_gen)[: len(labels)]
    act_dist = np.asarray(carry.act_dist)[: len(labels)]
    hist = np.asarray(carry.outdeg_hist)[:-1].astype(np.int64)  # drop dump
    outdegree = outdegree_from_hist(hist)
    occupancy = (
        int(carry.distinct) / fp_capacity if fp_capacity else None
    )
    viol = int(carry.viol)
    vname = (viol_names or {}).get(viol) or VIOLATION_NAMES.get(
        viol, f"violation {viol}"
    )
    return CheckResult(
        generated=int(carry.generated),
        distinct=int(carry.distinct),
        depth=int(carry.depth),
        queue_left=int(carry.level_n) - int(carry.qhead) + int(carry.next_n),
        violation=viol,
        violation_name=vname,
        violation_state=np.asarray(carry.viol_state),
        violation_action=int(carry.viol_action),
        action_generated={
            labels[i]: int(v) for i, v in enumerate(act_gen) if v
        },
        action_distinct={
            labels[i]: int(v) for i, v in enumerate(act_dist) if v
        },
        wall_s=wall_s,
        iterations=iterations,
        outdegree=outdegree,
        fp_occupancy=occupancy,
    )
