"""Fully device-resident BFS model-checking engine.

The TLC BFS core replacement (tlc2.tool.Worker + DiskStateQueue +
OffHeapDiskFPSet, /root/reference/KubeAPI.toolbox/Model_1/MC.out:5): one
``lax.while_loop`` whose body pops a fixed-size chunk from the frontier,
expands it through the vmapped next-state kernel, evaluates invariants,
fingerprints + dedups against the device hash table, and appends the new
states - no host round-trips until the state space is exhausted or a
violation is found.

v4 data layout, driven by on-chip microbenchmarks (tools/microbench.py:
random row scatters ~140ns/row dominate; contiguous dynamic-slice writes
are 3-9x cheaper; sorts are cheap):

* The frontier is a ping-pong pair of level buffers of *packed* state
  words ([2, qcap + 2*chunk, W] uint32): pops are contiguous dynamic
  slices, appends are contiguous dynamic-update-slices of fingerprint-
  sorted new states - no row scatters on the queue at all.  States are
  unpacked to field vectors only at the kernel boundary (codec.unpack).
* Dedup probes only the sort-compacted unique candidates
  (fpset.fpset_insert_sorted), and per-new-state bookkeeping (enqueue,
  per-action distinct counts, outdegree credit) runs over compacted
  A-wide segments instead of the full chunk*L candidate array.
* Fingerprints ride the MXU (fingerprint.fp64_words_mxu).
* Per-action generated counters are factorized through the dispatch
  structure (all lanes of a client share that client's pc label; server
  lanes are always APIStart) instead of scatter-adds over all candidates.

Level-synchronous by construction: a chunk never crosses a BFS level
boundary, so reported depth is the exact BFS level count, matching TLC's
"depth of the complete state graph search" (MC.out:1101), and in-batch
fingerprint arbitration never has to choose between states of different
levels.

Violation handling: the fused loop carries a violation code + the
offending encoded state; on violation the CLI re-runs in the host driver
(engine.hostdriver) which keeps parent pointers and reconstructs the
counterexample trace (TLC trace-explorer analog, SURVEY.md §2.3 E11).
"""

from __future__ import annotations

import time
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..config import ModelConfig
from ..spec.labels import LABELS
from .fingerprint import DEFAULT_FP_INDEX, DEFAULT_SEED, fp64_words_mxu
from .fpset import fpset_insert_dedup, fpset_insert_sorted, fpset_new

# violation codes
OK = 0
VIOL_TYPEOK = 1
VIOL_ONLYONEVERSION = 2
VIOL_ASSERT = 3
VIOL_DEADLOCK = 4
VIOL_SLOT_OVERFLOW = 5
VIOL_FPSET_FULL = 6
VIOL_QUEUE_FULL = 7
VIOL_ROUTE_OVERFLOW = 8

VIOLATION_NAMES = {
    OK: "none",
    VIOL_TYPEOK: "Invariant TypeOK is violated",
    VIOL_ONLYONEVERSION: "Invariant OnlyOneVersion is violated",
    VIOL_ASSERT: "Assertion failure (PlusCal assert)",
    VIOL_DEADLOCK: "Deadlock reached",
    VIOL_SLOT_OVERFLOW: "Codec slot overflow (raise ModelConfig bounds)",
    VIOL_FPSET_FULL: ("Fingerprint table full (auto-grow doubles it; "
                      "when device memory is exhausted the host spill "
                      "tier takes over - raise fp_capacity only to "
                      "avoid the regrow recompiles)"),
    VIOL_QUEUE_FULL: "Frontier queue full (raise queue_capacity)",
    VIOL_ROUTE_OVERFLOW: "Routing bucket overflow (raise route_factor)",
}


class EngineCarry(NamedTuple):
    fps: "FPSet"  # noqa: F821 - fpset.FPSet
    queue: jnp.ndarray  # [2, qcap + 2*chunk, W] uint32 packed level buffers
    parity: jnp.ndarray  # int32: which buffer holds the CURRENT level
    qhead: jnp.ndarray  # int32: pop position within the current level
    level_n: jnp.ndarray  # int32: states in the current level
    next_n: jnp.ndarray  # int32: states appended to the next level so far
    level: jnp.ndarray  # int32: BFS level of states being popped (init = 1)
    depth: jnp.ndarray  # int32: deepest nonempty level
    generated: jnp.ndarray  # uint32
    distinct: jnp.ndarray  # uint32
    act_gen: jnp.ndarray  # [n_labels + 1] uint32
    act_dist: jnp.ndarray  # [n_labels + 1] uint32
    outdeg_hist: jnp.ndarray  # [L + 2] uint32: #popped states with d new
    # children (TLC's outdegree, MC.out:1104); last row = scatter dump
    viol: jnp.ndarray  # int32 code
    viol_state: jnp.ndarray  # [F] int32
    viol_action: jnp.ndarray  # int32
    # --- pipelined-engine staged block (None on unpipelined engines) ---
    # The expand-stage output (backend.ExpandOut) of the in-flight pop:
    # popped and expanded but not yet committed - the next loop body
    # commits it while expanding the following block, so XLA can overlap
    # block k's kernel/fingerprint work with block k-1's sort/probe/
    # enqueue row ops (PERF.md round 7).  None leaves vanish from the
    # pytree, so unpipelined carries keep their exact pre-pipeline
    # checkpoint layout.
    st_packed: jnp.ndarray = None  # [chunk*L, W] uint32
    st_lo: jnp.ndarray = None  # [chunk*L] uint32
    st_hi: jnp.ndarray = None  # [chunk*L] uint32
    st_valid: jnp.ndarray = None  # [chunk*L] bool
    st_action: jnp.ndarray = None  # [chunk*L] int32
    st_gen: jnp.ndarray = None  # [n_labels] uint32
    st_n: jnp.ndarray = None  # int32: popped rows staged (0 = empty)
    st_viol: jnp.ndarray = None  # int32 expand-stage violation code
    st_viol_state: jnp.ndarray = None  # [F] int32
    st_viol_action: jnp.ndarray = None  # int32
    # --- observability counter ring (None when obs is off) ------------
    # One row per completed BFS level (obs.counters layout), written
    # with a single contiguous row store per body (non-flip bodies hit
    # the dump row), read back at segment fences.  None leaves vanish
    # from the pytree, so obs-off carries keep the pre-obs checkpoint
    # layout bit-for-bit.
    obs_ring: jnp.ndarray = None  # [obs_slots + 1, cols] uint32
    obs_head: jnp.ndarray = None  # int32 level rows ever written
    obs_bodies: jnp.ndarray = None  # uint32 loop bodies executed
    obs_expanded: jnp.ndarray = None  # uint32 states popped so far
    # --- host spill tier (None when spill mode is off) ----------------
    # Cumulative count of candidates the HOST fingerprint store vetoed
    # (already-seen fingerprints whose device-table entry was flushed to
    # host RAM - engine.spill).  Present only on spill-mode carries, so
    # every other engine keeps its exact checkpoint layout.
    spill_hits: jnp.ndarray = None  # uint32
    # --- runtime certificate (None without a backend cert_check) -------
    # Sticky bool: some generated state violated a bound the certified
    # abstract interpretation claimed (analysis.absint).  Latched every
    # body, mirrored into the obs ring's COL_CERT, escalated to an
    # error verdict by the check drivers - never silent.
    cert_viol: jnp.ndarray = None  # bool
    st_cert: jnp.ndarray = None  # staged block's cert bit (pipelined)
    # staged block's raw pre-pack fields ([chunk*L, F] int32): present
    # only on deferred-evaluation pipelined carries (ISSUE 15), where
    # the commit gathers the fresh-insert claimants from it.  None
    # leaves vanish, so immediate-mode carries keep their layout.
    st_flat: jnp.ndarray = None
    # --- device coverage plane (None without a backend coverage plane)
    # Cumulative [n_sites] uint32 per-site visit counters (obs.coverage,
    # ISSUE 11): incremented by every commit from the expand stage's
    # block increments, read back at segment fences, migrated verbatim
    # on regrow, checkpointed/resumed, psum-merged across shards.  Pure
    # telemetry, exactly like the obs ring above.
    cov_counts: jnp.ndarray = None  # [n_sites] uint32
    st_cov: jnp.ndarray = None  # staged block's increments (pipelined)
    # --- state-space reduction (None without backend.reduce, ISSUE 18)
    # Sticky bool: the orbit-certification sample of some block failed
    # to re-canonicalize (engine.reduce.ReducePlan.orbit_check) - the
    # symmetry plan is not acting as a permutation group, so the orbit
    # dedup cannot be trusted.  Latched every body, mirrored into the
    # obs ring's COL_SYM, escalated to an error verdict by the check
    # drivers - the COL_CERT pattern exactly.
    sym_viol: jnp.ndarray = None  # bool
    st_sym: jnp.ndarray = None  # staged block's orbit-check bit
    # Cumulative uint32: candidate transitions the POR ample-set mask
    # pruned at expand time (journalled as the `reduce` event's counter
    # delta; state counts legitimately shrink under POR)
    por_pruned: jnp.ndarray = None  # uint32
    st_pruned: jnp.ndarray = None  # staged block's pruned count


class CheckResult(NamedTuple):
    generated: int
    distinct: int
    depth: int
    queue_left: int
    violation: int
    violation_name: str
    violation_state: np.ndarray
    violation_action: int
    action_generated: dict
    action_distinct: dict
    wall_s: float
    iterations: int
    # (avg, min, max, p95) of TLC's outdegree = distinct new states per
    # expanded state (matches MC.out:1104); None when not tracked (sharded)
    outdegree: tuple = None
    # TLC's "based on the actual fingerprints" collision estimate
    # (MC.out:42); None when the engine variant doesn't compute it
    actual_fp_collision: float = None
    # final fingerprint-table load: distinct / fp_capacity (summed over
    # shards for the mesh engine); None when the driver didn't compute it.
    # Reported on the 2193 stats line so users can size fp_capacity (and
    # see how close a run came to the fp_highwater regrow trigger)
    fp_occupancy: float = None
    # device per-site coverage totals ({site key: visits}, obs.coverage);
    # None when the engine carried no coverage plane
    site_coverage: dict = None
    # runtime-certificate verdict of a narrowed (certified-bound) run:
    # None = no certificate check carried; False = every generated
    # state satisfied the certified bounds; True = a claimed bound was
    # VIOLATED - the check drivers escalate this to an error verdict
    cert_violated: bool = None
    # final fingerprint-table words ([n_buckets, 2*BUCKET] uint32 on
    # host), captured ONLY when the artifact cache wants to derive the
    # reachable-set tier from a clean single-device run
    # (struct.artifacts.states_from_table); None everywhere else so
    # results stay light
    fp_table: object = None
    # orbit-certification verdict of a symmetry-reduced run: None = no
    # orbit check carried; False = every sampled canonical row
    # re-canonicalized consistently; True = the symmetry plan LIED -
    # the check drivers escalate this to an error verdict (exit 1)
    sym_violated: bool = None
    # candidate transitions pruned by POR ample sets (None when POR is
    # off) - the journalled counter delta of the `reduce` event
    por_pruned: int = None


def carry_done(carry: EngineCarry) -> bool:
    """Host-side termination check (used by the checkpointed driver)."""
    if int(carry.viol) != OK:
        return True
    pending = carry.st_n is not None and int(carry.st_n) > 0
    return (
        int(carry.level_n) - int(carry.qhead) <= 0
        and int(carry.next_n) == 0
        and not pending
    )


DEFAULT_FP_HIGHWATER = 0.85

# -sort-free auto threshold: the fitted cost model (COSTMODEL.json,
# PERF.md round 11) shows the two full-width dedup sorts dominating
# commit at large chunks (8.3 of 9.3 ms at chunk 2048 = 89%); at small
# chunks the sorts are cheap and the slab setup is pure overhead, so
# auto keeps the sorted path there.
SORT_FREE_AUTO_CHUNK = 2048


def resolve_sort_free(sort_free, chunk: int) -> bool:
    """Resolve the tri-state -sort-free flag (None = auto) for an
    engine popping `chunk` states per step.  Deterministic in the
    geometry alone, so every layer that needs the resolved mode -
    engine factories, struct engine memos, checkpoint meta, the resume
    path - computes the same answer without coordination."""
    if sort_free is not None:
        return bool(sort_free)
    return chunk >= SORT_FREE_AUTO_CHUNK


# -deferred-inv auto threshold (ISSUE 15): the fitted cost model
# (COSTMODEL.json v2) puts the invariant+fingerprint subphase at 69%
# of the sort-free step at chunk 2048 (14.2 of 20.6 ms) - the
# per-candidate chunk*L invariant evaluation is the dominant lever
# there, and deferring it to the ~2*chunk fresh-insert claimants is
# the distinct-first collapse.  At small chunks the claimant gather +
# segment loop is overhead against a cheap candidate sweep, so auto
# keeps the immediate evaluation - same shape, and deliberately the
# same threshold, as the sort-free auto rule.
DEFERRED_AUTO_CHUNK = 2048


def resolve_deferred(deferred, chunk: int) -> bool:
    """Resolve the tri-state -deferred-inv flag (None = auto) for an
    engine popping `chunk` states per step.  Deterministic in the
    geometry alone - exactly like resolve_sort_free - so engine memos,
    EnginePool keys, checkpoint meta, resume commands and journal
    run_start params all compute the same answer without
    coordination."""
    if deferred is not None:
        return bool(deferred)
    return chunk >= DEFERRED_AUTO_CHUNK


def resolve_symmetry(symmetry, chunk: int = 0) -> bool:
    """Resolve the tri-state -symmetry flag (None = auto).  Auto is
    OFF: orbit dedup legitimately SHRINKS the distinct-state count, so
    unlike sort-free/deferred it is not a pure performance mode and
    must be opted into.  Same resolver shape as resolve_sort_free so
    engine memos, checkpoint meta, resume commands and journal params
    all agree without coordination (`chunk` is accepted for signature
    symmetry; the answer does not depend on it)."""
    if symmetry is not None:
        return bool(symmetry)
    return False


def resolve_por(por, chunk: int = 0) -> bool:
    """Resolve the tri-state -por flag (None = auto).  Auto is OFF for
    the same reason as resolve_symmetry: ample-set pruning changes the
    explored-state counts (verdicts are preserved, counts are not)."""
    if por is not None:
        return bool(por)
    return False


def make_engine(
    cfg: ModelConfig,
    chunk: int = 1024,
    queue_capacity: int = 1 << 15,
    fp_capacity: int = 1 << 20,
    fp_index: int = DEFAULT_FP_INDEX,
    seed: int = DEFAULT_SEED,
    fp_highwater: float = DEFAULT_FP_HIGHWATER,
    pipeline: bool = False,
    donate: bool = True,
    obs_slots: int = 0,
    coverage: bool = False,
    sort_free: bool = None,
    deferred: bool = None,
):
    """Build (init_fn, run_fn, step_fn) for one KubeAPI configuration.

    The hand-tuned KubeAPI path of make_backend_engine: the factorized
    per-action counters and the rest of the v4 loop now come through the
    SpecBackend seam, so this is a specialization, not a privilege.
    `coverage` compiles the device per-site coverage plane in
    (spec.coverage_device; the carry layout changes, so checkpoints
    record the flag)."""
    from .backend import kubeapi_backend

    return make_backend_engine(
        kubeapi_backend(cfg, coverage=coverage), chunk, queue_capacity,
        fp_capacity, fp_index, seed, fp_highwater=fp_highwater,
        pipeline=pipeline, donate=donate, obs_slots=obs_slots,
        sort_free=sort_free, deferred=deferred,
    )


def make_stage_pair(
    backend,
    ck: int,
    *,
    queue_capacity: int,
    fp_capacity: int,
    fp_highwater: float = DEFAULT_FP_HIGHWATER,
    check_deadlock: bool = None,
    fp_index: int = DEFAULT_FP_INDEX,
    seed: int = DEFAULT_SEED,
    obs_slots: int = 0,
    spill: bool = False,
    sort_free: bool = False,
    deferred: bool = False,
):
    """(pop_expand, commit) at pop width `ck` - the two halves of one
    BFS step, shared by every composition: the unpipelined body runs
    them back to back, the pipelined body runs commit on the PREVIOUS
    block's staged ExpandOut while pop_expand works on the next block,
    and the host spill driver (engine.spill) interleaves a host-tier
    membership check between them.

    sort_free=True (a RESOLVED bool here; factories resolve the
    tri-state flag via resolve_sort_free) commits through the hash-slab
    dedup (fpset.fpset_insert_slab) instead of the two full-width
    stable sorts - bit-identical results by contract, so every engine
    composed from this seam (fused, pipelined, spill, phased, narrowed,
    covered) inherits the mode with no per-engine code.  The slab is an
    ephemeral per-commit tensor derived from this pair's geometry, so
    regrow/chunk-shrink rebuilds migrate it by construction.

    deferred=True (a RESOLVED bool; factories resolve the tri-state
    flag via resolve_deferred) moves invariant + certificate
    evaluation from the expand stage to THIS commit, running them only
    on the fresh-insert claimants (backend.make_deferred_checker: TLC
    checks a state when first generated, and first generation is the
    distinct insert) - ~probe-width rows instead of chunk*L candidate
    lanes (ISSUE 15).  Verdict, counters, fpset TABLE words and
    rendered traces are bit-for-bit the immediate path's; only the
    violation-LANE attribution changes, to the pinned highest-lane
    rule (the checker docstring).  Because both modes meet at this one
    seam, every composed engine - fused, pipelined, spill, phased,
    narrowed, covered - inherits the mode with no per-engine code.

    spill=True builds the commit for spill mode: it takes an extra
    `veto` mask ([ck * n_lanes] bool, candidates the HOST fingerprint
    store already holds - treated exactly like a device-table hit: not
    new, not enqueued, no stat credit), skips the fp-capacity halt (the
    host driver flushes the device table before dispatching a chunk
    that could overflow it, so the halt can never be needed), and
    accumulates the cumulative `spill_hits` carry counter (obs ring
    COL_SPILL).  Dedup verdicts are unchanged otherwise, so a spill-
    mode run's final statistics are bit-for-bit a correctly-sized clean
    run's (tests/test_spill.py pins this through the chaos matrix)."""
    from ..obs.counters import (
        pack_row,
        ring_update,
        sticky_overflow,
        wrapped_any,
    )
    from .backend import make_expand_stage

    cdc = backend.cdc
    W = (cdc.nbits + 31) // 32
    L = backend.n_lanes
    n_labels = len(backend.labels)
    qcap = queue_capacity
    label_ids = jnp.arange(n_labels, dtype=jnp.int32)
    ncand = ck * L
    # compaction widths: probe/claim/enqueue touch only this many rows
    # per segment; steady-state new-per-chunk == chunk, so 2x covers
    # bursts and the segment loops keep worst cases exact
    R = min(2 * ck, ncand)  # fpset probe width
    CW = min(2 * ck, R)  # fpset round-0 claim width
    A = min(2 * ck, ncand)  # enqueue/stat segment width
    expand_fn = make_expand_stage(
        backend, ck, check_deadlock, fp_index, seed, deferred=deferred
    )
    # deferred-evaluation checker (ISSUE 15): invariants + certificate
    # over the fresh-insert claimants, at the probe width the insert
    # already compacts to.  None when there is nothing to check.
    checker = None
    if deferred and (backend.inv_codes or backend.cert_check is not None):
        from .backend import make_deferred_checker

        checker = make_deferred_checker(backend, ncand, probe_width=R)

    def pop_expand(c: EngineCarry):
        """Expand stage: contiguous pop + backend expand.  Reads only
        the pre-commit carry (queue buffer `parity`, which the commit
        stage never writes), so XLA may schedule it alongside the
        commit of the previous block."""
        avail = c.level_n - c.qhead
        n = jnp.clip(avail, 0, ck)
        rows = jnp.arange(ck, dtype=jnp.int32)
        mask = rows < n
        # contiguous pop (the buffer is chunk-padded: no OOB clamping)
        block = lax.dynamic_slice(
            c.queue, (c.parity, c.qhead, jnp.int32(0)), (1, ck, W)
        )[0]
        batch = cdc.unpack(block)
        return expand_fn(batch, mask), n

    def commit(c: EngineCarry, ex, n, qhead_pop, qhead_out, veto=None):
        """Commit stage for one block's ExpandOut `ex` (`n` popped
        rows): fpset probe/claim over the sort-compacted candidates,
        contiguous enqueue, counters, violation merge and level
        fencing.  `qhead_pop` is the pop cursor right after `ex`'s
        block was popped (the level-done basis); `qhead_out` is the
        cursor to keep when the level does not flip (the pipelined
        fused body passes the post-expand cursor here)."""
        if spill:
            # the host driver enforces device-tier residency, so the
            # capacity halt is off; host-vetoed candidates dedup
            # exactly like a device-table hit
            fp_full = jnp.bool_(False)
            insert_mask = ex.valid & ~veto
        else:
            fp_full = (c.distinct.astype(jnp.int32) + ncand) > int(
                fp_capacity * fp_highwater
            )
            insert_mask = ex.valid & ~fp_full
        fps, is_new_c, c_idx, nreps = fpset_insert_dedup(
            c.fps, ex.lo, ex.hi, insert_mask,
            probe_width=R, claim_width=CW, sort_free=sort_free,
        )
        n_new = is_new_c.sum().astype(jnp.int32)
        q_full = c.next_n + n_new > qcap

        # enqueue + per-new-state stats: bring new entries to the
        # front ordered by original lane index (2-key sort) - the
        # same append order as the v3 scatter engine, so pop order
        # and therefore in-batch attribution statistics (outdegree
        # min/max, MC.out:1104) are preserved bit-for-bit.  All new
        # entries sit in the first nreps compacted positions, so
        # when nreps fits the probe width the sort runs at R width
        # instead of ncand (~6x less comparator traffic); the
        # full-width branch covers all-distinct bursts.
        new_key = (~is_new_c).astype(jnp.uint32)
        cidx_u = c_idx.astype(jnp.uint32)

        def e_sorted_sliced(_):
            _, e = lax.sort(
                (new_key[:R], cidx_u[:R]), num_keys=2, is_stable=True
            )
            return jnp.concatenate(
                [e, jnp.zeros(ncand - R, jnp.uint32)]
            )

        def e_sorted_full(_):
            _, e = lax.sort(
                (new_key, cidx_u), num_keys=2, is_stable=True
            )
            return e

        if R == ncand:
            _, e_idx = lax.sort(
                (new_key, cidx_u), num_keys=2, is_stable=True
            )
        else:
            e_idx = lax.cond(
                nreps <= R, e_sorted_sliced, e_sorted_full, 0
            )
        e_idx_p = jnp.concatenate([e_idx, jnp.zeros(A, jnp.uint32)])

        def enq_cond(st):
            _, _, s = st
            return s * A < n_new

        def enq_body(st):
            queue, act_dist, s = st
            offs = s * A
            idx_a = lax.dynamic_slice(e_idx_p, (offs,), (A,)).astype(
                jnp.int32
            )
            active = (jnp.arange(A) + offs) < n_new
            rows_a = ex.packed[idx_a]  # [A, W] row gather (the only one)
            woff = jnp.minimum(c.next_n + offs, qcap)
            queue = lax.dynamic_update_slice(
                queue, rows_a[None], (1 - c.parity, woff, jnp.int32(0))
            )
            # per-action distinct counts by [A, n_labels] compare-
            # reduce (scatter-adds cost ~140ns/element on-chip)
            acts_a = ex.action[idx_a]
            act_dist = act_dist.at[:n_labels].add(
                (
                    (acts_a[:, None] == label_ids[None, :])
                    & active[:, None]
                ).sum(axis=0).astype(jnp.uint32)
            )
            return queue, act_dist, s + 1

        queue, act_dist, _ = lax.while_loop(
            enq_cond, enq_body, (c.queue, c.act_dist, jnp.int32(0))
        )

        # outdegree histogram of the popped states (TLC's outdegree =
        # distinct new successors per expansion, MC.out:1104) via run
        # lengths: e_idx's active prefix is ascending in source row,
        # so each row's new-child count is a run length - no
        # [chunk+1]-bin scatter-add
        pos = jnp.arange(ncand)
        active_new = pos < n_new
        src_e = jnp.where(active_new, e_idx.astype(jnp.int32) // L, -1)
        startf = jnp.concatenate(
            [jnp.ones(1, bool), src_e[1:] != src_e[:-1]]
        ) & active_new
        endf = jnp.concatenate(
            [src_e[1:] != src_e[:-1], jnp.ones(1, bool)]
        ) & active_new
        run0 = lax.cummax(jnp.where(startf, pos, 0))
        run_len = jnp.where(endf, pos - run0 + 1, 0)
        nruns = startf.sum()
        deg_hist = (
            (run_len[:, None] == jnp.arange(1, L + 1)[None, :])
            .sum(axis=0)
            .astype(jnp.uint32)
        )
        outdeg_hist = c.outdeg_hist.at[1 : L + 1].add(deg_hist)
        outdeg_hist = outdeg_hist.at[0].add(
            (n - nruns).astype(jnp.uint32)
        )

        act_gen = c.act_gen.at[:n_labels].add(ex.gen)
        generated = c.generated + ex.valid.sum().astype(jnp.uint32)
        distinct = c.distinct + n_new.astype(jnp.uint32)

        # violations, first wins: carried > deferred invariant (when
        # evaluation is deferred, checked on the fresh claimants just
        # inserted - outranking the kernel-derived codes exactly as
        # the immediate reduce orders invariant > assert) >
        # expand-stage (invariant > assert > deadlock > slot,
        # pre-reduced in ex) > capacity
        viol = c.viol
        viol_state = c.viol_state
        viol_action = c.viol_action
        d_cert = None
        if checker is not None:
            d_viol, d_state, d_action, d_cert = checker(
                ex.flat, ex.action, is_new_c, c_idx, nreps
            )
            hit = (d_viol != OK) & (viol == OK)
            viol = jnp.where(hit, d_viol, viol)
            viol_state = jnp.where(hit, d_state, viol_state)
            viol_action = jnp.where(hit, d_action, viol_action)
        hit = (ex.viol != OK) & (viol == OK)
        viol = jnp.where(hit, ex.viol, viol)
        viol_state = jnp.where(hit, ex.viol_state, viol_state)
        viol_action = jnp.where(hit, ex.viol_action, viol_action)
        if not spill:
            hit = fp_full & ex.valid.any() & (viol == OK)
            viol = jnp.where(hit, VIOL_FPSET_FULL, viol)
        hit = q_full & (viol == OK)
        viol = jnp.where(hit, VIOL_QUEUE_FULL, viol)

        # level bookkeeping: ping-pong at the level boundary
        next_n = jnp.minimum(c.next_n + n_new, qcap)
        level_done = qhead_pop >= c.level_n
        advance = level_done & (next_n > 0)
        parity = jnp.where(level_done, 1 - c.parity, c.parity)
        level_n = jnp.where(level_done, next_n, c.level_n)
        next_n = jnp.where(level_done, 0, next_n)
        qhead = jnp.where(level_done, 0, qhead_out)
        level = jnp.where(advance, c.level + 1, c.level)
        depth = jnp.maximum(c.depth, level)

        extra = {}
        if spill:
            extra["spill_hits"] = c.spill_hits + (
                veto & ex.valid
            ).sum().astype(jnp.uint32)
        cert_now = None
        cert_src = d_cert if deferred else ex.cert
        if cert_src is not None and c.cert_viol is not None:
            # sticky: once any block's certificate check fired, every
            # later carry (and ring row) carries the flag (deferred
            # mode latches it from the commit-site checker instead of
            # the staged expand bit - same column, same stickiness)
            cert_now = c.cert_viol | cert_src
            extra["cert_viol"] = cert_now
        sym_now = None
        if ex.sym is not None and c.sym_viol is not None:
            # orbit certification (ISSUE 18): same sticky latch as the
            # certificate bit - computed at expand on the canonical
            # fields, so the deferred mode needs no commit-site variant
            sym_now = c.sym_viol | ex.sym
            extra["sym_viol"] = sym_now
        if ex.pruned is not None and c.por_pruned is not None:
            extra["por_pruned"] = c.por_pruned + ex.pruned
        if ex.cov is not None and c.cov_counts is not None:
            # device coverage plane: fold this block's per-site visit
            # increments into the cumulative counters (telemetry only)
            extra["cov_counts"] = c.cov_counts + ex.cov
        obs = {}
        if obs_slots:
            # one telemetry row per completed level (post-commit
            # cumulative counters; the dump row absorbs non-flip
            # bodies so the store is unconditional).  The sticky
            # COL_OVERFLOW flag marks any uint32 wrap so saturated
            # counters are detected, never silently wrong
            obs_bodies = c.obs_bodies + jnp.uint32(1)
            obs_expanded = c.obs_expanded + n.astype(jnp.uint32)
            wrap_pairs = [
                (generated, c.generated), (distinct, c.distinct),
                (act_gen, c.act_gen), (act_dist, c.act_dist),
                (obs_bodies, c.obs_bodies),
                (obs_expanded, c.obs_expanded),
            ]
            if spill:
                wrap_pairs.append((extra["spill_hits"], c.spill_hits))
            if "cov_counts" in extra:
                wrap_pairs.append((extra["cov_counts"], c.cov_counts))
            wrapped = wrapped_any(wrap_pairs)
            row = pack_row(
                c.level, generated, distinct, level_n, obs_bodies,
                obs_expanded, act_gen[:n_labels],
                act_dist[:n_labels],
                overflow=sticky_overflow(c.obs_ring, wrapped),
                spill=extra.get("spill_hits"),
                cert=cert_now,
                sym=sym_now,
            )
            ring, head = ring_update(
                c.obs_ring, c.obs_head, row, level_done
            )
            obs = dict(obs_ring=ring, obs_head=head,
                       obs_bodies=obs_bodies,
                       obs_expanded=obs_expanded)

        return c._replace(
            fps=fps,
            queue=queue,
            parity=parity,
            qhead=qhead,
            level_n=level_n,
            next_n=next_n,
            level=level,
            depth=depth,
            generated=generated,
            distinct=distinct,
            act_gen=act_gen,
            act_dist=act_dist,
            outdeg_hist=outdeg_hist,
            viol=viol,
            viol_state=viol_state,
            viol_action=viol_action,
            **extra,
            **obs,
        )

    return pop_expand, commit


def make_backend_engine(
    backend,
    chunk: int = 1024,
    queue_capacity: int = 1 << 15,
    fp_capacity: int = 1 << 20,
    fp_index: int = DEFAULT_FP_INDEX,
    seed: int = DEFAULT_SEED,
    fp_highwater: float = DEFAULT_FP_HIGHWATER,
    check_deadlock: bool = None,
    pipeline: bool = False,
    donate: bool = True,
    obs_slots: int = 0,
    sort_free: bool = None,
    deferred: bool = None,
):
    """Build (init_fn, run_fn, step_fn) over any SpecBackend.

    init_fn() -> EngineCarry seeded with the Init states.
    run_fn(carry) -> EngineCarry after exhaustion/violation (jitted, fused).
    step_fn(carry) -> EngineCarry after ONE chunk (jitted; for checkpointed
    / incremental runs).

    queue_capacity bounds the width of a single BFS level (the frontier),
    not the total state count: levels ping-pong between two buffers.

    fp_highwater is the fingerprint-table load fraction at which the run
    halts with VIOL_FPSET_FULL instead of degrading into long straggler
    walks (open addressing past ~0.85 load is where probe cost blows up);
    the supervisor's auto-regrow doubles fp_capacity at this trigger.

    check_deadlock overrides the backend's default (TLC's -deadlock
    switch; None takes backend.check_deadlock).

    pipeline=True software-pipelines the step: the body is split into an
    expand stage (unpack -> kernel -> invariants -> fingerprints) and a
    commit stage (sort-compact dedup -> fpset probe/claim -> enqueue +
    counters), and the carry stages block k's ExpandOut so body i
    commits block k-1 WHILE expanding block k - two blocks in flight,
    giving the XLA scheduler overlap freedom across the stages (SURVEY
    §2.4 level pipelining; PERF.md round 7).  The pop sequence and every
    arbitration decision are unchanged, so a pipelined run is bit-for-bit
    identical to the unpipelined engine at the same chunk (full
    signature: counts, depth, per-action, outdegree, fpset content) for
    chunks below the two-tier threshold; at chunk >= 2^14 the pipelined
    engine runs single-tier (full-width stages) where the unpipelined
    engine would switch to small bodies, so exact counts still match but
    in-batch attribution may not.  For overlap in the one-step-per-level
    regime, run the pipelined engine at HALF the unpipelined sweet-spot
    chunk so every level spans >= 2 blocks (PERF.md round 7 sizing).

    donate=True (ignored on CPU, where XLA has no donation) marks the
    carry argument of run_fn/step_fn donated so XLA aliases the ping-pong
    queue/candidate buffers across iterations instead of copying.  Pass
    donate=False when the SAME carry value is fed to the engine twice
    (profilers, the resil supervisor's retry-from-last-good loop).

    obs_slots > 0 carries the observability counter ring (obs.counters):
    one cumulative-counter row per completed BFS level, written with a
    single contiguous row store per body (the dump-row trick makes the
    write unconditional).  The ring is pure telemetry - it feeds no
    control flow and no arbitration - so check results with obs on are
    bit-for-bit those of an obs-off run (bench.py --obs-ab gates the
    wall-clock overhead at <= 2%).

    sort_free (tri-state: None = auto, resolve_sort_free) selects the
    hash-slab commit dedup in place of the two full-width stable sorts
    (ISSUE 12).  Results are BIT-FOR-BIT the sorted path's - full
    signature plus fpset TABLE words (bench.py --commit-ab gates it) -
    the flag is purely a performance mode, but it is still recorded in
    engine memos and checkpoint meta so a resume can never silently
    cross modes.

    deferred (tri-state: None = auto, resolve_deferred) moves
    invariant + certificate evaluation to the commit stage, over the
    fresh-insert claimants only (ISSUE 15; make_stage_pair docstring).
    Verdict, full counter signature, fpset TABLE words and rendered
    traces are bit-for-bit the immediate path's (bench.py --expand-ab
    gates it); violation-LANE attribution follows the pinned
    highest-lane rule.  Like sort_free, the resolved mode is engine-
    memo and checkpoint-meta material - a wrong-mode -recover is a
    loud pre-build rejection - because the pipelined staged-block
    layout changes (st_flat replaces st_cert) and attribution must
    never silently flip across a resume.
    """
    from ..obs.counters import ring_new
    from .backend import ExpandOut

    assert 0.0 < fp_highwater <= 1.0, "fp_highwater must be in (0, 1]"
    sort_free = resolve_sort_free(sort_free, chunk)
    deferred = resolve_deferred(deferred, chunk)
    has_cert = backend.cert_check is not None
    # in deferred mode the staged ExpandOut carries the raw fields
    # (st_flat) and no cert bit (the commit-site checker derives it)
    stage_cert = has_cert and not deferred
    # state-space reduction (ISSUE 18): presence of the plan / POR
    # rights decides the carry leaves, mirroring make_expand_stage's
    # own gating exactly so staged blocks and ExpandOut always agree
    red = backend.reduce
    has_sym = red is not None and red.plan is not None
    has_por = bool(
        red is not None and red.por and red.safe_ids
        and backend.lane_action is not None
    )
    cov_plane = backend.coverage
    n_sites = cov_plane.n_sites if cov_plane is not None else 0
    cdc = backend.cdc
    F = cdc.n_fields
    W = (cdc.nbits + 31) // 32
    L = backend.n_lanes
    inv_check = backend.inv_check
    inv_codes = backend.inv_codes
    n_labels = len(backend.labels)
    nbits = cdc.nbits
    qcap = queue_capacity
    if check_deadlock is None:
        check_deadlock = backend.check_deadlock
    # two-tier adaptive stepping: a step's cost is dominated by fixed
    # chunk-sized work regardless of how few states it pops, so narrow
    # levels (the BFS ramp/tail) and level remainders run a small body
    # instead of paying a full big-chunk step.  The pipelined engine is
    # single-tier: its staged-block carry has one static width, and
    # mixing widths would change the pop sequence vs the bit-exactness
    # contract above.
    small = chunk // 16 if (chunk >= 1 << 14 and not pipeline) else 0

    label_ids = jnp.arange(n_labels, dtype=jnp.int32)
    ncand_full = chunk * L

    def init_fn(inits=None) -> EngineCarry:
        # `inits` overrides the backend's Init set ([n0, F] int32 field
        # vectors): the constant-config sweep engine (jaxtlc.serve.sweep)
        # seeds one carry per configuration through the same packing /
        # fpset-insert / init-invariant path, so a seeded carry is
        # exactly what a backend with that Init would have produced
        if inits is None:
            inits = backend.initial_vectors()
        inits = jnp.asarray(inits)
        if has_sym:
            # seed the frontier with orbit representatives: Init is
            # permutation-closed (symfind verified init_ast mentions no
            # symmetric atom), so every reachable orbit stays reachable
            # from the canonicalized seeds
            inits = red.plan.canon(inits)
        n0 = inits.shape[0]
        assert n0 <= chunk and n0 <= qcap, "raise chunk/queue_capacity"
        packed0 = cdc.pack(inits)
        queue = (
            jnp.zeros((2, qcap + 2 * chunk, W), jnp.uint32)
            .at[0, :n0]
            .set(packed0)
        )
        lo, hi = fp64_words_mxu(packed0, nbits, fp_index, seed)
        fps, is_new_c, _, _ = fpset_insert_sorted(
            fpset_new(fp_capacity), lo, hi, jnp.ones(n0, bool)
        )
        distinct0 = is_new_c.sum().astype(jnp.uint32)
        # invariants hold on the initial states too (TLC checks them
        # before the first Next application)
        inv0 = jax.vmap(inv_check)(inits)
        viol = jnp.int32(OK)
        viol_state = jnp.zeros(F, jnp.int32)
        for k, code in enumerate(inv_codes):
            bad = (inv0 & (1 << k)) == 0
            hit = bad.any() & (viol == OK)
            viol = jnp.where(hit, code, viol)
            viol_state = jnp.where(hit, inits[jnp.argmax(bad)], viol_state)
        staged = {}
        if pipeline:
            staged = dict(
                st_packed=jnp.zeros((ncand_full, W), jnp.uint32),
                st_lo=jnp.zeros(ncand_full, jnp.uint32),
                st_hi=jnp.zeros(ncand_full, jnp.uint32),
                st_valid=jnp.zeros(ncand_full, bool),
                st_action=jnp.zeros(ncand_full, jnp.int32),
                st_gen=jnp.zeros(n_labels, jnp.uint32),
                st_n=jnp.int32(0),
                st_viol=jnp.int32(OK),
                st_viol_state=jnp.zeros(F, jnp.int32),
                st_viol_action=jnp.int32(-1),
            )
            if stage_cert:
                staged["st_cert"] = jnp.bool_(False)
            if cov_plane is not None:
                staged["st_cov"] = jnp.zeros(n_sites, jnp.uint32)
            if deferred:
                staged["st_flat"] = jnp.zeros((ncand_full, F), jnp.int32)
            if has_sym:
                staged["st_sym"] = jnp.bool_(False)
            if has_por:
                staged["st_pruned"] = jnp.uint32(0)
        if has_cert:
            staged["cert_viol"] = jnp.bool_(False)
        if has_sym:
            staged["sym_viol"] = jnp.bool_(False)
        if has_por:
            staged["por_pruned"] = jnp.uint32(0)
        if cov_plane is not None:
            # coverage counters seeded with the Init-site visits (the
            # host-side charge for the seed states; zero when the plane
            # tracks no Init sites)
            staged["cov_counts"] = jnp.asarray(
                cov_plane.seed(np.asarray(inits))
            )
        obs = {}
        if obs_slots:
            ring, head = ring_new(obs_slots, n_labels)
            obs = dict(
                obs_ring=ring, obs_head=head,
                obs_bodies=jnp.uint32(0), obs_expanded=jnp.uint32(0),
            )
        return EngineCarry(
            fps=fps,
            queue=queue,
            parity=jnp.int32(0),
            qhead=jnp.int32(0),
            level_n=jnp.int32(n0),
            next_n=jnp.int32(0),
            level=jnp.int32(1),
            depth=jnp.int32(1),
            generated=jnp.uint32(n0),
            distinct=distinct0,
            act_gen=jnp.zeros(n_labels + 1, jnp.uint32),
            act_dist=jnp.zeros(n_labels + 1, jnp.uint32),
            outdeg_hist=jnp.zeros(L + 2, jnp.uint32),
            viol=viol,
            viol_state=viol_state,
            viol_action=jnp.int32(-1),
            **staged,
            **obs,
        )

    def make_stages(ck: int):
        """(pop_expand, commit) at pop width `ck` - the module-level
        make_stage_pair specialized to this engine's geometry (the
        lift that lets the host spill driver, engine.spill, reuse the
        exact commit the fused/pipelined bodies run)."""
        return make_stage_pair(
            backend, ck, queue_capacity=qcap, fp_capacity=fp_capacity,
            fp_highwater=fp_highwater, check_deadlock=check_deadlock,
            fp_index=fp_index, seed=seed, obs_slots=obs_slots,
            sort_free=sort_free, deferred=deferred,
        )

    def make_body(ck: int):
        """One fused BFS step popping up to `ck` states: expand + commit
        of the SAME block, back to back (the unpipelined body)."""
        pop_expand, commit = make_stages(ck)

        def body(c: EngineCarry) -> EngineCarry:
            ex, n = pop_expand(c)
            return commit(c, ex, n, c.qhead + n, c.qhead + n)

        return body

    if pipeline:
        pop_expand, commit = make_stages(chunk)

        def with_staged(c: EngineCarry, ex, n) -> EngineCarry:
            extra = {"st_cert": ex.cert} if stage_cert else {}
            if cov_plane is not None:
                extra["st_cov"] = ex.cov
            if deferred:
                extra["st_flat"] = ex.flat
            if has_sym:
                extra["st_sym"] = ex.sym
            if has_por:
                extra["st_pruned"] = ex.pruned
            return c._replace(
                st_packed=ex.packed, st_lo=ex.lo, st_hi=ex.hi,
                st_valid=ex.valid, st_action=ex.action, st_gen=ex.gen,
                st_n=n, st_viol=ex.viol, st_viol_state=ex.viol_state,
                st_viol_action=ex.viol_action, **extra,
            )

        def staged_ex(c: EngineCarry) -> ExpandOut:
            return ExpandOut(
                packed=c.st_packed, lo=c.st_lo, hi=c.st_hi,
                valid=c.st_valid, action=c.st_action, gen=c.st_gen,
                viol=c.st_viol, viol_state=c.st_viol_state,
                viol_action=c.st_viol_action,
                cert=c.st_cert if stage_cert else None,
                cov=c.st_cov if cov_plane is not None else None,
                flat=c.st_flat if deferred else None,
                sym=c.st_sym if has_sym else None,
                pruned=c.st_pruned if has_por else None,
            )

        # The two-deep pipeline body, bubble-free: the staged block k-1
        # commits WHILE block k expands from the PRE-commit carry (the
        # commit stage never writes the current-level buffer, so the two
        # halves are data-independent and XLA may overlap them).  At a
        # level boundary (will_flip: the staged block was the level's
        # last pop) the expansion instead reads the POST-commit carry -
        # the freshly flipped level - which serializes that one body but
        # keeps the body count equal to the unpipelined engine's (no
        # idle half-bodies: two earlier formulations paid an
        # fpset-table copy per body through conditional pass-through,
        # or a full-width empty-commit sort set per level bubble).  The
        # expand conditional's results are only the staged ExpandOut -
        # never the table/queue - so the untaken branch costs nothing.
        def body(c: EngineCarry) -> EngineCarry:
            will_flip = c.qhead >= c.level_n
            c2 = commit(c, staged_ex(c), c.st_n, c.qhead, c.qhead)

            def expand_pre(_):
                return pop_expand(c)

            def expand_post(_):
                return pop_expand(c2)

            ex, n = lax.cond(will_flip, expand_post, expand_pre, 0)
            return with_staged(c2._replace(qhead=c2.qhead + n), ex, n)

        def cond(c: EngineCarry):
            return (
                (c.qhead < c.level_n) | (c.next_n > 0) | (c.st_n > 0)
            ) & (c.viol == OK)

    else:
        big_body = make_body(chunk)
        if small:
            small_body = make_body(small)
            # break-even: a big step costs ~what chunk/small small steps
            # cost, so take the big body only when the level remainder
            # mostly fills it
            def body(c: EngineCarry) -> EngineCarry:
                avail = c.level_n - c.qhead
                return lax.cond(avail >= chunk // 2, big_body, small_body, c)
        else:
            body = big_body

        def cond(c: EngineCarry):
            return ((c.qhead < c.level_n) | (c.next_n > 0)) & (c.viol == OK)

    # donate the carry so XLA aliases the ping-pong queue / staged
    # candidate buffers in place of copies (CPU has no donation support;
    # requesting it there only emits warnings)
    donate_ok = bool(donate) and jax.devices()[0].platform != "cpu"
    jit_kw = {"donate_argnums": (0,)} if donate_ok else {}

    run_fn = jax.jit(
        lambda c: lax.while_loop(cond, body, c), **jit_kw
    )
    step_fn = jax.jit(
        lambda c: lax.cond(cond(c), body, lambda x: x, c), **jit_kw
    )
    # donation metadata for the preflight audit (analysis.engine_audit):
    # donate_requested is the factory intent, donates_carry what XLA
    # will actually do on this platform - the gap is the class of bug
    # that only reproduces on device
    for fn in (run_fn, step_fn):
        fn.donate_requested = bool(donate)
        fn.donates_carry = donate_ok
    # JAXTLC_DEBUG_DONATION=1: simulate donation semantics on every
    # backend by poisoning the input carry after each call, so a
    # use-after-donate fails fast on CPU instead of only on TPU
    from ..analysis.donation import wrap_if_debugging

    run_fn = wrap_if_debugging(run_fn, bool(donate))
    step_fn = wrap_if_debugging(step_fn, bool(donate))
    return init_fn, run_fn, step_fn


def check(
    cfg: ModelConfig,
    chunk: int = 1024,
    queue_capacity: int = 1 << 15,
    fp_capacity: int = 1 << 20,
    fp_index: int = DEFAULT_FP_INDEX,
    seed: int = DEFAULT_SEED,
    fp_highwater: float = DEFAULT_FP_HIGHWATER,
    pipeline: bool = False,
    obs_slots: int = 0,
    coverage: bool = False,
    sort_free: bool = None,
    deferred: bool = None,
) -> CheckResult:
    """Run an exhaustive check; the single-device engine entry point.

    The fused loop is AOT-compiled (`lower().compile()`) before timing, so
    wall_s measures execution only - the honest time-to-exhaustive figure
    (compilation is a one-time cost, amortized in TLC by the JVM the same
    way)."""
    from .backend import kubeapi_backend

    backend = kubeapi_backend(cfg, coverage=coverage)
    init_fn, run_fn, _ = make_backend_engine(
        backend, chunk, queue_capacity, fp_capacity, fp_index, seed,
        fp_highwater=fp_highwater, pipeline=pipeline, obs_slots=obs_slots,
        sort_free=sort_free, deferred=deferred,
    )
    carry = init_fn()
    compiled = run_fn.lower(carry).compile()
    t0 = time.time()
    carry = jax.block_until_ready(compiled(carry))
    wall = time.time() - t0
    from .fpset import fpset_actual_collision

    afc = float(fpset_actual_collision(carry.fps))
    sites = backend.coverage.sites if backend.coverage else None
    return result_from_carry(
        carry, wall, fp_capacity=fp_capacity, sites=sites
    )._replace(actual_fp_collision=afc)


def obs_rows(carry, labels: tuple = None, since: int = 0,
             fp_capacity: int = 0):
    """Decode the carry's observability ring into journal-`level`-event
    dicts (oldest first) plus the new head cursor.  ([], since) when obs
    is off - callers need no obs-awareness of their own."""
    from ..obs.counters import rows_from_ring

    if getattr(carry, "obs_ring", None) is None:
        return [], int(since)
    head = int(carry.obs_head)
    return (
        rows_from_ring(
            np.asarray(carry.obs_ring), head, labels=labels,
            since=since, fp_capacity=fp_capacity,
        ),
        head,
    )


class EnumCarry(NamedTuple):
    """Carry of the fused state enumerator (liveness edge-capture pass 1).

    Unlike EngineCarry's ping-pong level buffers, `states` is APPEND-ONLY:
    a state's row index is its permanent id (BFS append order), which is
    exactly what the device-resident liveness subsystem (jaxtlc.live)
    needs - the edge relation is expressed over these ids."""

    fps: tuple  # fpset.FPSet
    states: jnp.ndarray  # [cap + A, W] uint32 packed states, id = row
    head: jnp.ndarray  # int32: next id to expand
    tail: jnp.ndarray  # int32: number of distinct states stored
    viol: jnp.ndarray  # int32: OK or a capacity/overflow code
    # observability ring (None when obs is off): the enumerator is
    # level-less, so one row per BODY (ring wraps; cumulative counters
    # keep totals exact) - queue col = unexpanded backlog
    obs_ring: jnp.ndarray = None  # [obs_slots + 1, cols] uint32
    obs_head: jnp.ndarray = None  # int32 rows ever written


def make_enumerator(
    backend,
    chunk: int = 1024,
    state_capacity: int = 1 << 20,
    fp_capacity: int = 1 << 20,
    fp_index: int = DEFAULT_FP_INDEX,
    seed: int = DEFAULT_SEED,
    fp_highwater: float = DEFAULT_FP_HIGHWATER,
    obs_slots: int = 0,
):
    """Build (init_fn, run_fn) for the fused distinct-state enumerator.

    The optional capture mode of the BFS core: the same vmapped kernel +
    MXU fingerprints + sort-compacted dedup as the exhaustive engine, but
    the frontier is the append-only `states` array itself (a work-list
    pop cursor instead of level fencing), so after one fused
    `lax.while_loop` the whole reachable set sits on device in id order.
    `backend` is any engine.sharded.SpecBackend (kubeapi_backend /
    gen_backend), so every frontend that can run sharded can be
    enumerated - the seam the liveness capture (jaxtlc.live.capture)
    feeds on.

    Halts loudly with VIOL_QUEUE_FULL when `state_capacity` is exceeded
    (the caller's cue to raise it or spill), VIOL_FPSET_FULL /
    VIOL_SLOT_OVERFLOW as in the exhaustive engine.
    """
    from ..obs.counters import pack_row, ring_new, ring_update

    cdc = backend.cdc
    F = cdc.n_fields
    W = (cdc.nbits + 31) // 32
    step = backend.step
    L = backend.n_lanes
    n_labels = len(backend.labels)
    nbits = cdc.nbits
    cap = state_capacity
    ncand = chunk * L
    R = min(2 * chunk, ncand)
    A = min(2 * chunk, ncand)

    def init_fn() -> EnumCarry:
        inits = jnp.asarray(backend.initial_vectors())
        n0 = inits.shape[0]
        assert n0 <= chunk and n0 <= cap, "raise chunk/state_capacity"
        packed0 = cdc.pack(inits)
        states = jnp.zeros((cap + A, W), jnp.uint32).at[:n0].set(packed0)
        lo, hi = fp64_words_mxu(packed0, nbits, fp_index, seed)
        fps, _, _, _ = fpset_insert_sorted(
            fpset_new(fp_capacity), lo, hi, jnp.ones(n0, bool)
        )
        obs = {}
        if obs_slots:
            ring, rhead = ring_new(obs_slots, n_labels)
            obs = dict(obs_ring=ring, obs_head=rhead)
        return EnumCarry(
            fps=fps,
            states=states,
            head=jnp.int32(0),
            tail=jnp.int32(n0),
            viol=jnp.int32(OK),
            **obs,
        )

    def body(c: EnumCarry) -> EnumCarry:
        avail = c.tail - c.head
        n = jnp.minimum(chunk, avail)
        rows = jnp.arange(chunk, dtype=jnp.int32)
        mask = rows < n

        block = lax.dynamic_slice(
            c.states, (c.head, jnp.int32(0)), (chunk, W)
        )
        batch = cdc.unpack(block)
        succs, valid, _action, _afail, ovf = jax.vmap(step)(batch)
        valid = valid & mask[:, None]
        ovf = ovf & valid

        flat = succs.reshape(ncand, F)
        fvalid = valid.reshape(-1)
        packed = cdc.pack(flat)
        lo, hi = fp64_words_mxu(packed, nbits, fp_index, seed)

        fp_full = (c.tail + ncand) > int(fp_capacity * fp_highwater)
        fps, is_new_c, c_idx, _ = fpset_insert_sorted(
            c.fps, lo, hi, fvalid & ~fp_full, probe_width=R, claim_width=R
        )
        n_new = is_new_c.sum().astype(jnp.int32)
        s_full = c.tail + n_new > cap

        # append new states at the tail in candidate order (the engines'
        # sort-compact + A-wide contiguous-write pattern)
        _, e_idx = lax.sort(
            ((~is_new_c).astype(jnp.uint32), c_idx.astype(jnp.uint32)),
            num_keys=2,
            is_stable=True,
        )
        e_idx_p = jnp.concatenate([e_idx, jnp.zeros(A, jnp.uint32)])

        def enq_cond(st):
            _, s = st
            return s * A < n_new

        def enq_body(st):
            states, s = st
            offs = s * A
            idx_a = lax.dynamic_slice(e_idx_p, (offs,), (A,)).astype(
                jnp.int32
            )
            rows_a = packed[idx_a]
            woff = jnp.minimum(c.tail + offs, cap)
            states = lax.dynamic_update_slice(
                states, rows_a, (woff, jnp.int32(0))
            )
            return states, s + 1

        states, _ = lax.while_loop(
            enq_cond, enq_body, (c.states, jnp.int32(0))
        )

        viol = c.viol
        viol = jnp.where(ovf.any() & (viol == OK), VIOL_SLOT_OVERFLOW, viol)
        viol = jnp.where(
            fp_full & fvalid.any() & (viol == OK), VIOL_FPSET_FULL, viol
        )
        viol = jnp.where(s_full & (viol == OK), VIOL_QUEUE_FULL, viol)
        tail = jnp.where(s_full, c.tail, c.tail + n_new)
        obs = {}
        if obs_slots:
            # one row per body (the enumerator has no levels): distinct
            # doubles as generated-distinct, queue = unexpanded backlog
            from ..obs.counters import sticky_overflow, wrapped_any

            zeros = jnp.zeros(n_labels, jnp.uint32)
            wrapped = wrapped_any([(tail.astype(jnp.uint32),
                                    c.tail.astype(jnp.uint32))])
            row = pack_row(
                jnp.int32(0), tail, tail, tail - (c.head + n),
                c.obs_head + 1, c.head + n, zeros, zeros,
                overflow=sticky_overflow(c.obs_ring, wrapped),
            )
            ring, rhead = ring_update(
                c.obs_ring, c.obs_head, row, jnp.bool_(True)
            )
            obs = dict(obs_ring=ring, obs_head=rhead)
        return EnumCarry(
            fps=fps, states=states, head=c.head + n, tail=tail,
            viol=viol, **obs,
        )

    def cond(c: EnumCarry):
        return (c.head < c.tail) & (c.viol == OK)

    @jax.jit
    def run_fn(c: EnumCarry) -> EnumCarry:
        return lax.while_loop(cond, body, c)

    return init_fn, run_fn


def outdegree_from_hist(hist: np.ndarray):
    """(avg, min, max, p95) of TLC's outdegree from a new-children
    histogram (hist[d] = #expanded states with d new successors); None if
    empty.  Matches MC.out:1104's reporting convention."""
    hist = np.asarray(hist, dtype=np.int64)
    total = hist.sum()
    if total == 0:
        return None
    degs = np.arange(len(hist))
    nz = np.flatnonzero(hist)
    cum = np.cumsum(hist)
    p95 = int(degs[np.searchsorted(cum, 0.95 * total)])
    return (
        int(round((degs * hist).sum() / total)),
        int(nz[0]),
        int(nz[-1]),
        p95,
    )


def cov_totals(carry) -> "np.ndarray | None":
    """Cumulative per-site coverage counters of a carry ([n_sites]
    int64 host array; shard carries sum their device partials), or
    None when no coverage plane rides the carry."""
    counts = getattr(carry, "cov_counts", None)
    if counts is None:
        return None
    counts = np.asarray(counts).astype(np.int64)
    if counts.ndim == 2:  # sharded: [D, n_sites] partials
        counts = counts.sum(axis=0)
    return counts


def result_from_carry(
    carry: EngineCarry, wall_s: float, iterations: int = -1,
    fp_capacity: int = 0, labels: tuple = LABELS, viol_names: dict = None,
    sites: tuple = None,
) -> CheckResult:
    """Pull a finished (or interrupted) carry to host as a CheckResult."""
    act_gen = np.asarray(carry.act_gen)[: len(labels)]
    act_dist = np.asarray(carry.act_dist)[: len(labels)]
    hist = np.asarray(carry.outdeg_hist)[:-1].astype(np.int64)  # drop dump
    outdegree = outdegree_from_hist(hist)
    occupancy = (
        int(carry.distinct) / fp_capacity if fp_capacity else None
    )
    viol = int(carry.viol)
    vname = (viol_names or {}).get(viol) or VIOLATION_NAMES.get(
        viol, f"violation {viol}"
    )
    # a pipelined carry's staged block is popped but uncommitted work -
    # still "on queue" in TLC's sense (states handed to a worker)
    staged_n = int(carry.st_n) if carry.st_n is not None else 0
    cert = getattr(carry, "cert_viol", None)
    cert_violated = bool(cert) if cert is not None else None
    sym = getattr(carry, "sym_viol", None)
    sym_violated = bool(sym) if sym is not None else None
    pruned = getattr(carry, "por_pruned", None)
    if pruned is not None:
        pruned = int(np.asarray(pruned).sum())  # shards carry partials
    por_pruned = pruned
    site_coverage = None
    totals = cov_totals(carry)
    if totals is not None and sites is not None:
        from ..obs.coverage import site_totals_dict

        site_coverage = site_totals_dict(sites, totals)
    return CheckResult(
        generated=int(carry.generated),
        distinct=int(carry.distinct),
        depth=int(carry.depth),
        queue_left=(
            int(carry.level_n) - int(carry.qhead) + int(carry.next_n)
            + staged_n
        ),
        violation=viol,
        violation_name=vname,
        violation_state=np.asarray(carry.viol_state),
        violation_action=int(carry.viol_action),
        action_generated={
            labels[i]: int(v) for i, v in enumerate(act_gen) if v
        },
        action_distinct={
            labels[i]: int(v) for i, v in enumerate(act_dist) if v
        },
        wall_s=wall_s,
        iterations=iterations,
        outdegree=outdegree,
        fp_occupancy=occupancy,
        cert_violated=cert_violated,
        site_coverage=site_coverage,
        sym_violated=sym_violated,
        por_pruned=por_pruned,
    )
