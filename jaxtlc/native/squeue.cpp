// Disk-backed BFS frontier queue - the DiskStateQueue analog.
//
// TLC's frontier FIFO spills to disk (DiskStateQueue,
// /root/reference/KubeAPI.toolbox/Model_1/MC.out:5) so exhaustive runs are
// bounded by disk, not RAM. This is the native tier for the hybrid engine:
// fixed-size encoded-state records, strict FIFO, file-backed with a small
// write buffer. Levels are fenced by the *caller* (the record layout is
// opaque here), so BFS depth accounting stays exact.
//
// C ABI for ctypes.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>

namespace {

struct Queue {
  FILE *f = nullptr;
  uint64_t record_bytes = 0;
  uint64_t head = 0;  // records popped
  uint64_t tail = 0;  // records pushed
  std::string path;
};

}  // namespace

extern "C" {

void *sq_open(const char *path, uint64_t record_bytes) {
  Queue *q = new Queue();
  q->path = path;
  q->record_bytes = record_bytes;
  q->f = fopen(path, "w+b");
  if (!q->f) {
    delete q;
    return nullptr;
  }
  setvbuf(q->f, nullptr, _IOFBF, 1 << 20);
  return q;
}

// reopen an existing queue file WITHOUT truncation, restoring the
// head/tail cursors a checkpoint recorded (the -recover path)
void *sq_open_at(const char *path, uint64_t record_bytes, uint64_t head,
                 uint64_t tail) {
  Queue *q = new Queue();
  q->path = path;
  q->record_bytes = record_bytes;
  q->f = fopen(path, "r+b");
  if (!q->f) {
    delete q;
    return nullptr;
  }
  setvbuf(q->f, nullptr, _IOFBF, 1 << 20);
  q->head = head;
  q->tail = tail;
  return q;
}

// flush buffered writes to the file (checkpoint barrier)
int sq_sync(void *handle) {
  Queue *q = static_cast<Queue *>(handle);
  return fflush(q->f) ? -1 : 0;
}

uint64_t sq_head(void *handle) { return static_cast<Queue *>(handle)->head; }

int sq_push(void *handle, const void *records, int64_t n) {
  Queue *q = static_cast<Queue *>(handle);
  if (fseeko(q->f, static_cast<off_t>(q->tail * q->record_bytes), SEEK_SET))
    return -1;
  if (fwrite(records, q->record_bytes, static_cast<size_t>(n), q->f) !=
      static_cast<size_t>(n))
    return -1;
  q->tail += static_cast<uint64_t>(n);
  return 0;
}

// pops up to max_n records into out; returns the number popped
int64_t sq_pop(void *handle, void *out, int64_t max_n) {
  Queue *q = static_cast<Queue *>(handle);
  uint64_t avail = q->tail - q->head;
  uint64_t take = avail < static_cast<uint64_t>(max_n)
                      ? avail
                      : static_cast<uint64_t>(max_n);
  if (take == 0) return 0;
  if (fflush(q->f)) return -1;
  if (fseeko(q->f, static_cast<off_t>(q->head * q->record_bytes), SEEK_SET))
    return -1;
  if (fread(out, q->record_bytes, take, q->f) != take) return -1;
  q->head += take;
  return static_cast<int64_t>(take);
}

uint64_t sq_len(void *handle) {
  Queue *q = static_cast<Queue *>(handle);
  return q->tail - q->head;
}

uint64_t sq_tail(void *handle) { return static_cast<Queue *>(handle)->tail; }

// own_file: remove the backing file (set for library-created temp files;
// caller-owned paths are left in place)
void sq_close(void *handle, int own_file) {
  Queue *q = static_cast<Queue *>(handle);
  if (q->f) fclose(q->f);
  if (own_file) remove(q->path.c_str());
  delete q;
}

}  // extern "C"
