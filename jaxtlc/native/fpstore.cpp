// Host-authoritative fingerprint store - the OffHeapDiskFPSet analog.
//
// TLC keeps its 64-bit fingerprint set in an off-heap open-addressing table
// that spills to disk (/root/reference/KubeAPI.toolbox/Model_1/MC.out:5).
// This is the native tier of the TPU engine's hybrid mode: the device does
// expansion + in-batch dedup, and streams candidate fingerprints here for
// authoritative dedup when the state space exceeds device HBM.
//
// Design: open-addressing (triangular probing, power-of-two capacity) over
// a mmap'd file, 8 bytes per entry (the full 64-bit fingerprint; 0 is the
// empty sentinel, and the real fingerprint 0 is tracked by a header flag so
// no two fingerprints are ever conflated). The mmap IS the
// persistence: checkpointing the store is an fsync + header write, and the
// OS pages cold regions to disk under memory pressure - the same
// "off-heap + disk spill" behavior OffHeapDiskFPSet implements by hand.
// Grows by rehash-doubling at 60% load.
//
// Exposed as a C ABI for ctypes (no pybind11 in this environment).

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <fcntl.h>
#include <string>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

struct Header {
  uint64_t magic;     // "JAXTLCFP"
  uint64_t capacity;  // slots (power of two)
  uint64_t count;     // fingerprints stored (including the zero fp)
  uint64_t has_zero;  // the fingerprint 0 itself (0 is the slot sentinel)
};

constexpr uint64_t kMagic = 0x4a4158544c434650ull;

struct Store {
  int fd = -1;
  Header *hdr = nullptr;    // mmap base
  uint64_t *slots = nullptr;  // hdr + 1
  std::string path;
};

inline uint64_t home_slot(uint64_t fp, uint64_t cap) {
  // The host tier keeps its own avalanche hash + triangular probing,
  // deliberately independent of the device table's bucketized layout
  // (../engine/fpset.py): the two stores never exchange slot indices,
  // only membership verdicts.
  uint32_t lo = static_cast<uint32_t>(fp);
  uint32_t hi = static_cast<uint32_t>(fp >> 32);
  uint32_t h = (lo ^ (hi * 0x9E3779B1u)) * 0x85EBCA6Bu;
  h ^= h >> 15;
  return h & (cap - 1);
}

bool map_file(Store *s, uint64_t capacity, bool create) {
  uint64_t bytes = sizeof(Header) + capacity * sizeof(uint64_t);
  if (create && ftruncate(s->fd, static_cast<off_t>(bytes)) != 0) return false;
  void *base = mmap(nullptr, bytes, PROT_READ | PROT_WRITE, MAP_SHARED, s->fd, 0);
  if (base == MAP_FAILED) return false;
  s->hdr = static_cast<Header *>(base);
  s->slots = reinterpret_cast<uint64_t *>(s->hdr + 1);
  if (create) {
    s->hdr->magic = kMagic;
    s->hdr->capacity = capacity;
    s->hdr->count = 0;
    s->hdr->has_zero = 0;
  }
  return true;
}

void unmap(Store *s) {
  if (s->hdr) {
    munmap(s->hdr, sizeof(Header) + s->hdr->capacity * sizeof(uint64_t));
    s->hdr = nullptr;
    s->slots = nullptr;
  }
}

// insert fp (nonzero); returns true if newly inserted
bool insert_one(uint64_t *slots, uint64_t cap, uint64_t fp, uint64_t *count) {
  uint64_t sl = home_slot(fp, cap);
  uint64_t step = 1;
  for (;;) {
    uint64_t v = slots[sl];
    if (v == 0) {
      slots[sl] = fp;
      ++*count;
      return true;
    }
    if (v == fp) return false;
    sl = (sl + step) & (cap - 1);
    ++step;
  }
}

bool grow(Store *s) {
  uint64_t old_cap = s->hdr->capacity;
  uint64_t new_cap = old_cap * 2;
  std::string tmp = s->path + ".grow";
  int nfd = open(tmp.c_str(), O_RDWR | O_CREAT | O_TRUNC, 0644);
  if (nfd < 0) return false;
  Store ns;
  ns.fd = nfd;
  ns.path = tmp;
  if (!map_file(&ns, new_cap, /*create=*/true)) {
    close(nfd);
    return false;
  }
  uint64_t cnt = 0;
  for (uint64_t i = 0; i < old_cap; i++) {
    uint64_t v = s->slots[i];
    if (v != 0) insert_one(ns.slots, new_cap, v, &cnt);
  }
  ns.hdr->count = cnt + s->hdr->has_zero;
  ns.hdr->has_zero = s->hdr->has_zero;
  unmap(s);
  close(s->fd);
  s->fd = -1;  // fps_close must not double-close on a failure below
  unmap(&ns);
  close(nfd);
  if (rename(tmp.c_str(), s->path.c_str()) != 0) return false;
  s->fd = open(s->path.c_str(), O_RDWR, 0644);
  if (s->fd < 0) return false;
  return map_file(s, new_cap, /*create=*/false);
}

}  // namespace

extern "C" {

void *fps_open(const char *path, uint64_t initial_capacity) {
  Store *s = new Store();
  s->path = path;
  bool exists = access(path, F_OK) == 0;
  s->fd = open(path, O_RDWR | O_CREAT, 0644);
  if (s->fd < 0) {
    delete s;
    return nullptr;
  }
  if (exists) {
    struct stat st;
    fstat(s->fd, &st);
    if (st.st_size >= static_cast<off_t>(sizeof(Header))) {
      Header h;
      if (pread(s->fd, &h, sizeof(h), 0) == sizeof(h) && h.magic == kMagic) {
        if (!map_file(s, h.capacity, /*create=*/false)) {
          close(s->fd);
          delete s;
          return nullptr;
        }
        return s;
      }
    }
  }
  uint64_t cap = 64;
  while (cap < initial_capacity) cap <<= 1;
  if (!map_file(s, cap, /*create=*/true)) {
    close(s->fd);
    delete s;
    return nullptr;
  }
  return s;
}

// lo/hi: n fingerprint word lanes; mask in: candidate flags, out: is_new
int fps_insert_batch(void *handle, const uint32_t *lo, const uint32_t *hi,
                     uint8_t *mask, int64_t n) {
  Store *s = static_cast<Store *>(handle);
  for (int64_t i = 0; i < n; i++) {
    if (!mask[i]) continue;
    if (s->hdr->count * 10 >= s->hdr->capacity * 6) {  // grow at 60% load
      if (!grow(s)) return -1;
    }
    uint64_t fp = (static_cast<uint64_t>(hi[i]) << 32) | lo[i];
    if (fp == 0) {  // 0 is the slot sentinel; track it in the header
      mask[i] = s->hdr->has_zero ? 0 : 1;
      if (!s->hdr->has_zero) {
        s->hdr->has_zero = 1;
        ++s->hdr->count;
      }
      continue;
    }
    mask[i] = insert_one(s->slots, s->hdr->capacity, fp, &s->hdr->count) ? 1 : 0;
  }
  return 0;
}

uint64_t fps_count(void *handle) {
  return static_cast<Store *>(handle)->hdr->count;
}

uint64_t fps_capacity(void *handle) {
  return static_cast<Store *>(handle)->hdr->capacity;
}

int fps_sync(void *handle) {
  Store *s = static_cast<Store *>(handle);
  uint64_t bytes = sizeof(Header) + s->hdr->capacity * sizeof(uint64_t);
  return msync(s->hdr, bytes, MS_SYNC);
}

void fps_close(void *handle) {
  Store *s = static_cast<Store *>(handle);
  unmap(s);
  if (s->fd >= 0) close(s->fd);
  delete s;
}

}  // extern "C"
