"""Native (C++) host tier: disk-backed fingerprint store + state queue.

The runtime analog of TLC's OffHeapDiskFPSet / DiskStateQueue
(/root/reference/KubeAPI.toolbox/Model_1/MC.out:5): C++ via a C ABI, loaded
with ctypes (pybind11 is not available in this environment), compiled once
per machine into ``~/.cache/jaxtlc`` on first import.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import tempfile

import numpy as np

_SRC_DIR = os.path.dirname(os.path.abspath(__file__))
_SOURCES = ["fpstore.cpp", "squeue.cpp"]


def _build() -> str:
    srcs = [os.path.join(_SRC_DIR, s) for s in _SOURCES]
    digest = hashlib.sha256()
    for p in srcs:
        with open(p, "rb") as f:
            digest.update(f.read())
    cache = os.path.join(
        os.environ.get("XDG_CACHE_HOME", os.path.expanduser("~/.cache")),
        "jaxtlc",
    )
    os.makedirs(cache, exist_ok=True)
    so = os.path.join(cache, f"jaxtlc_native_{digest.hexdigest()[:16]}.so")
    if not os.path.exists(so):
        tmp = so + f".build{os.getpid()}"
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", tmp]
            + srcs,
            check=True,
            capture_output=True,
        )
        os.replace(tmp, so)
    return so


_lib = None


def lib() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        _lib = ctypes.CDLL(_build())
        _lib.fps_open.restype = ctypes.c_void_p
        _lib.fps_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        _lib.fps_insert_batch.restype = ctypes.c_int
        _lib.fps_insert_batch.argtypes = [
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_void_p,
            ctypes.c_int64,
        ]
        _lib.fps_count.restype = ctypes.c_uint64
        _lib.fps_count.argtypes = [ctypes.c_void_p]
        _lib.fps_capacity.restype = ctypes.c_uint64
        _lib.fps_capacity.argtypes = [ctypes.c_void_p]
        _lib.fps_sync.restype = ctypes.c_int
        _lib.fps_sync.argtypes = [ctypes.c_void_p]
        _lib.fps_close.argtypes = [ctypes.c_void_p]
        _lib.sq_open.restype = ctypes.c_void_p
        _lib.sq_open.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        _lib.sq_push.restype = ctypes.c_int
        _lib.sq_push.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64]
        _lib.sq_pop.restype = ctypes.c_int64
        _lib.sq_pop.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64]
        _lib.sq_len.restype = ctypes.c_uint64
        _lib.sq_len.argtypes = [ctypes.c_void_p]
        _lib.sq_tail.restype = ctypes.c_uint64
        _lib.sq_tail.argtypes = [ctypes.c_void_p]
        _lib.sq_open_at.restype = ctypes.c_void_p
        _lib.sq_open_at.argtypes = [
            ctypes.c_char_p,
            ctypes.c_uint64,
            ctypes.c_uint64,
            ctypes.c_uint64,
        ]
        _lib.sq_sync.restype = ctypes.c_int
        _lib.sq_sync.argtypes = [ctypes.c_void_p]
        _lib.sq_head.restype = ctypes.c_uint64
        _lib.sq_head.argtypes = [ctypes.c_void_p]
        _lib.sq_close.argtypes = [ctypes.c_void_p, ctypes.c_int]
    return _lib


class HostFPStore:
    """Disk-backed (mmap) authoritative fingerprint set.

    fresh=True (the default) removes any existing file at `path` first: a
    store opened for a new run must start empty, or the recovered contents
    silently dedup the new run's states away.  Pass fresh=False to recover
    a previous run's set (the TLC -recover analog)."""

    def __init__(
        self,
        path: str = None,
        initial_capacity: int = 1 << 20,
        fresh: bool = True,
    ):
        self._own_tmp = path is None
        if path is None:
            fd, path = tempfile.mkstemp(suffix=".fps")
            os.close(fd)
            os.unlink(path)
        elif fresh and os.path.exists(path):
            os.unlink(path)
        self.path = path
        self._h = lib().fps_open(path.encode(), initial_capacity)
        if not self._h:
            raise OSError(f"fps_open failed for {path!r}")

    def insert(self, lo: np.ndarray, hi: np.ndarray, mask: np.ndarray):
        """lo/hi uint32 [n], mask bool [n] -> is_new bool [n]."""
        lo = np.ascontiguousarray(lo, dtype=np.uint32)
        hi = np.ascontiguousarray(hi, dtype=np.uint32)
        m = np.ascontiguousarray(mask, dtype=np.uint8)
        rc = lib().fps_insert_batch(
            self._h,
            lo.ctypes.data_as(ctypes.c_void_p),
            hi.ctypes.data_as(ctypes.c_void_p),
            m.ctypes.data_as(ctypes.c_void_p),
            len(lo),
        )
        if rc != 0:
            raise MemoryError("fingerprint store grow failed")
        return m.astype(bool)

    def __len__(self) -> int:
        return int(lib().fps_count(self._h))

    @property
    def capacity(self) -> int:
        return int(lib().fps_capacity(self._h))

    def sync(self) -> None:
        if lib().fps_sync(self._h) != 0:
            raise OSError("fps_sync failed")

    def close(self) -> None:
        if self._h:
            lib().fps_close(self._h)
            self._h = None
            if self._own_tmp and os.path.exists(self.path):
                os.unlink(self.path)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class HostStateQueue:
    """Disk-backed FIFO of fixed-size encoded-state records.

    The backing file is scratch space: it is truncated on open, and removed
    on close only when the library created it (no `path` given) - a
    caller-supplied path is left in place."""

    def __init__(self, record_fields: int, path: str = None,
                 resume_head: int = None, resume_tail: int = None):
        self._own_tmp = path is None
        if path is None:
            fd, path = tempfile.mkstemp(suffix=".sq")
            os.close(fd)
        self.path = path
        self.record_fields = record_fields
        self._rb = record_fields * 4
        if resume_head is not None:
            # reopen without truncation at checkpointed cursors
            self._h = lib().sq_open_at(
                path.encode(), self._rb, resume_head, resume_tail
            )
        else:
            self._h = lib().sq_open(path.encode(), self._rb)
        if not self._h:
            raise OSError(f"sq_open failed for {path!r}")

    def push(self, records: np.ndarray) -> None:
        """records: int32 [n, record_fields]."""
        r = np.ascontiguousarray(records, dtype=np.int32)
        assert r.ndim == 2 and r.shape[1] == self.record_fields
        if lib().sq_push(self._h, r.ctypes.data_as(ctypes.c_void_p), r.shape[0]):
            raise OSError("sq_push failed")

    def pop(self, max_n: int) -> np.ndarray:
        out = np.empty((max_n, self.record_fields), dtype=np.int32)
        n = lib().sq_pop(self._h, out.ctypes.data_as(ctypes.c_void_p), max_n)
        if n < 0:
            raise OSError("sq_pop failed")
        return out[:n]

    def __len__(self) -> int:
        return int(lib().sq_len(self._h))

    @property
    def total_pushed(self) -> int:
        return int(lib().sq_tail(self._h))

    @property
    def head(self) -> int:
        return int(lib().sq_head(self._h))

    def sync(self) -> None:
        if lib().sq_sync(self._h) != 0:
            raise OSError("sq_sync failed")

    def close(self) -> None:
        if self._h:
            lib().sq_close(self._h, 1 if self._own_tmp else 0)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
