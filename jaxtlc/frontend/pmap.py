"""Toolbox .pmap consumption (M4): the generated-TLA -> PlusCal source map.

`KubeAPI.tla.pmap` (/root/reference/KubeAPI.toolbox/KubeAPI.tla.pmap) is a
Java-serialized ``pcal.TLAtoPCalMapping``: for every line of the PlusCal
TRANSLATION region of the .tla file it stores mapping objects (source
tokens and paren pairs) pointing back into the PlusCal algorithm text.
The Toolbox uses it to jump from TLC errors (reported against generated
TLA lines) to the PlusCal the user wrote; TLC itself never reads it.

This module implements the consumer: a dependency-free reader for the
Java Object Serialization Stream Protocol subset these files use
(TC_OBJECT/TC_CLASSDESC/TC_ARRAY/TC_STRING/TC_REFERENCE/TC_NULL, plain
SC_SERIALIZABLE classes), plus the location query the trace renderer
needs: TLA line -> PlusCal (line, column) of the nearest mapped token.

Object model (pcal/TLAtoPCalMapping.java, pcal/MappingObject.java):
  * ``tlaStartLine``: first TLA line (1-based) of the translation region;
    ``mapping[i]`` describes TLA line ``tlaStartLine + i``.
  * ``algLine``/``algColumn``: 0-based position of the ``--algorithm``
    token; PCalLocation lines are relative to it.
  * MappingObject subclasses: SourceToken (begin/end column + PlusCal
    location), Begin/EndTLAToken (column only), Left/RightParen
    (PlusCal location).
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Tuple

MAGIC = 0xACED

TC_NULL = 0x70
TC_REFERENCE = 0x71
TC_CLASSDESC = 0x72
TC_OBJECT = 0x73
TC_STRING = 0x74
TC_ARRAY = 0x75
TC_ENDBLOCKDATA = 0x78
BASE_HANDLE = 0x7E0000

_PRIM_SIZES = {"B": 1, "C": 2, "D": 8, "F": 4, "I": 4, "J": 8, "S": 2,
               "Z": 1}
_PRIM_FMT = {"B": ">b", "C": ">H", "D": ">d", "F": ">f", "I": ">i",
             "J": ">q", "S": ">h", "Z": ">?"}


class PmapError(ValueError):
    pass


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0
        self.handles: List[object] = []

    def take(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise PmapError("truncated stream")
        b = self.data[self.pos:self.pos + n]
        self.pos += n
        return b

    def u1(self) -> int:
        return self.take(1)[0]

    def u2(self) -> int:
        return struct.unpack(">H", self.take(2))[0]

    def i4(self) -> int:
        return struct.unpack(">i", self.take(4))[0]

    def utf(self) -> str:
        n = self.u2()
        return self.take(n).decode("utf-8")

    def new_handle(self, obj) -> int:
        self.handles.append(obj)
        return BASE_HANDLE + len(self.handles) - 1

    def ref(self):
        h = self.i4() - BASE_HANDLE
        if not 0 <= h < len(self.handles):
            raise PmapError(f"bad handle {h}")
        return self.handles[h]

    # -- grammar ----------------------------------------------------------

    def stream(self):
        if self.u2() != MAGIC or self.u2() != 5:
            raise PmapError("not a Java serialization stream")
        return self.content()

    def content(self):
        tc = self.u1()
        if tc == TC_OBJECT:
            return self.object()
        if tc == TC_ARRAY:
            return self.array()
        if tc == TC_STRING:
            s = self.utf()
            self.new_handle(s)
            return s
        if tc == TC_REFERENCE:
            return self.ref()
        if tc == TC_NULL:
            return None
        raise PmapError(f"unsupported type code 0x{tc:02x}")

    def class_desc(self) -> Dict:
        tc = self.u1()
        if tc == TC_NULL:
            return None
        if tc == TC_REFERENCE:
            return self.ref()
        if tc != TC_CLASSDESC:
            raise PmapError(f"expected classDesc, got 0x{tc:02x}")
        name = self.utf()
        self.take(8)  # serialVersionUID
        desc: Dict = {"name": name}
        self.new_handle(desc)
        flags = self.u1()
        if flags & ~0x02:
            raise PmapError(
                f"class {name}: only plain SC_SERIALIZABLE supported "
                f"(flags 0x{flags:02x})"
            )
        nfields = self.u2()
        fields = []
        for _ in range(nfields):
            t = chr(self.u1())
            fname = self.utf()
            if t in ("L", "["):
                self.content()  # the field's type-name string
            fields.append((t, fname))
        desc["fields"] = fields
        if self.u1() != TC_ENDBLOCKDATA:
            raise PmapError("expected end of class annotation")
        desc["super"] = self.class_desc()
        return desc

    def object(self) -> Dict:
        desc = self.class_desc()
        obj: Dict = {"__class__": desc["name"]}
        self.new_handle(obj)
        # field values: superclass first
        chain = []
        d = desc
        while d is not None:
            chain.append(d)
            d = d["super"]
        for d in reversed(chain):
            for t, fname in d["fields"]:
                if t in _PRIM_SIZES:
                    obj[fname] = struct.unpack(
                        _PRIM_FMT[t], self.take(_PRIM_SIZES[t])
                    )[0]
                else:
                    obj[fname] = self.content()
        return obj

    def array(self) -> List:
        desc = self.class_desc()
        arr: List = []
        self.new_handle(arr)
        n = self.i4()
        comp = desc["name"][1]  # "[Lpcal..." -> component type code
        for _ in range(n):
            if comp in _PRIM_SIZES:
                arr.append(struct.unpack(
                    _PRIM_FMT[comp], self.take(_PRIM_SIZES[comp]))[0])
            else:
                arr.append(self.content())
        return arr


class TLAtoPCalMapping:
    """Parsed mapping + the TLA-line -> PlusCal-location query."""

    def __init__(self, alg_line: int, alg_column: int, tla_start_line: int,
                 mapping: List[List[Dict]]):
        self.alg_line = alg_line
        self.alg_column = alg_column
        self.tla_start_line = tla_start_line
        self.mapping = mapping

    @property
    def n_lines(self) -> int:
        return len(self.mapping)

    def pcal_location(self, tla_line: int) -> Optional[Tuple[int, int]]:
        """PlusCal (1-based file line, 0-based column) of the first mapped
        token on the given 1-based TLA line; scans earlier translation
        lines if that line carries only structural tokens."""
        row0 = tla_line - self.tla_start_line
        if not 0 <= row0 < len(self.mapping):
            return None
        for row in range(row0, -1, -1):
            for obj in self.mapping[row]:
                # SourceToken carries an origin Region; parens carry a
                # bare PCalLocation
                loc = obj.get("location")
                origin = obj.get("origin")
                if isinstance(origin, dict):
                    loc = origin.get("begin")
                if isinstance(loc, dict) and "line" in loc:
                    # PCalLocation.line is the 0-based absolute file line
                    # (verified against the committed artifact: the CStart
                    # action row points at the `either` statement,
                    # KubeAPI.tla:167)
                    return (loc["line"] + 1, loc["column"])
        return None


def parse_pmap_bytes(data: bytes) -> TLAtoPCalMapping:
    try:
        root = _Reader(data).stream()
    except PmapError:
        raise
    except Exception as e:  # noqa: BLE001 - parser boundary: corrupt
        # bytes can surface as UnicodeDecodeError / struct.error /
        # RecursionError etc.; callers guard on PmapError only
        raise PmapError(f"corrupt pmap stream: {type(e).__name__}: {e}")
    if not isinstance(root, dict) or (
        root.get("__class__") != "pcal.TLAtoPCalMapping"
    ):
        raise PmapError("unexpected root object")
    try:
        return TLAtoPCalMapping(
            alg_line=root["algLine"],
            alg_column=root["algColumn"],
            tla_start_line=root["tlaStartLine"],
            mapping=root["mapping"],
        )
    except KeyError as e:
        raise PmapError(f"pmap root missing field {e}")


def parse_pmap_file(path: str) -> TLAtoPCalMapping:
    with open(path, "rb") as f:
        return parse_pmap_bytes(f.read())
