"""Model resolution: MC.cfg + MC.tla (+ .launch) -> an executable run spec.

This is the L4 model-configuration layer (SURVEY.md §1): the three nested
config layers of the reference - .launch (Toolbox knobs) -> MC.cfg (TLC
DSL) -> MC.tla (constant definitions) - resolved against the spec the
engine can execute.

Spec frontend scope (SURVEY.md §7 item 9): the engine executes the KubeAPI
action system via hand-written codegen of the committed TLA translation
(/root/reference/KubeAPI.tla:373-768), generalized over the constants and
the scaled bounds.  Loading an MC for a different root spec is a clear
error, not a silent misrun.

The .pmap file (Java-serialized pcal.TLAtoPCalMapping) is the Toolbox's
generated-TLA -> PlusCal source map used to render traces at PlusCal level;
our action identifiers *are* the PlusCal labels (the translation names its
actions after them), so the mapping semantics are native here: traces are
reported with PlusCal labels + the reference's line numbers (io.tlc_log).
"""

from __future__ import annotations

import dataclasses
import os
from typing import List, Optional

from ..config import ModelConfig
from ..engine.fingerprint import DEFAULT_FP_INDEX
from .launch import LaunchConfig, parse_launch_file
from .mc_cfg import TLCConfig, parse_cfg_file
from .mc_tla import eval_constant, parse_mc_tla_file

KNOWN_INVARIANTS = ("TypeOK", "OnlyOneVersion")
KNOWN_PROPERTIES = ("ReconcileCompletes", "CleansUpProperly")


@dataclasses.dataclass
class RunSpec:
    model: ModelConfig
    invariants: List[str]
    properties: List[str]  # declared; liveness checking is deferred (E8)
    check_deadlock: bool
    workers: str  # "tpu" | "auto" | int-as-string
    fp_index: int
    spec_name: str
    model_name: str


@dataclasses.dataclass
class GenRunSpec:
    """A resolved run for the generic frontend (non-KubeAPI root spec)."""

    genspec: object  # gen.ir.GenSpec
    invariants: List[str]
    properties: List[str]
    check_deadlock: bool
    workers: str
    fp_index: int
    spec_name: str
    model_name: str
    tla_path: str = ""  # module source (coverage line numbers)


@dataclasses.dataclass
class StructRunSpec:
    """A resolved run for the structural frontend: the full-module path
    (records, sets of records, procedure stacks, CHOOSE) that executes
    specs outside the gen subset - the reference's own KubeAPI.tla
    included (-frontend struct)."""

    structmodel: object  # struct.loader.StructModel
    invariants: List[str]
    properties: List[str]
    check_deadlock: bool
    workers: str
    fp_index: int
    spec_name: str
    model_name: str


def resolve(
    cfg_path: str,
    launch_path: Optional[str] = None,
    workers: str = "tpu",
    fp_index: Optional[int] = None,
    check_deadlock: bool = True,
    frontend: str = "auto",
    const_overrides: Optional[dict] = None,
) -> RunSpec:
    """Resolve a run from an MC.cfg (with sibling MC.tla) like TLC would.

    frontend: "auto" picks the hand-tuned KubeAPI path for the KubeAPI
    root spec, the gen-subset compiler for subset specs, and falls back
    to the structural frontend for anything else; "hand"/"gen"/"struct"
    force a path (struct runs ANY spec, KubeAPI included).

    const_overrides: already-evaluated CONSTANT values layered on top
    of the cfg's (the serve tier's per-job overrides); they win over
    both the cfg assignments and the MC.tla substitutions, on every
    frontend path."""
    if frontend not in ("auto", "hand", "gen", "struct"):
        raise ValueError(f"unknown -frontend {frontend!r}")
    cfg: TLCConfig = parse_cfg_file(cfg_path)
    model_dir = os.path.dirname(os.path.abspath(cfg_path))
    mc_tla_path = os.path.join(model_dir, "MC.tla")
    consts = dict(cfg.constants)
    extends: List[str] = []
    if os.path.exists(mc_tla_path):
        mc = parse_mc_tla_file(mc_tla_path)
        extends = mc.extends
        for name, defname in cfg.substitutions.items():
            if defname in mc.definitions:
                consts[name] = mc.definitions[defname]
    if const_overrides:
        consts.update(const_overrides)

    launch: Optional[LaunchConfig] = None
    if launch_path is None:
        toolbox_dir = os.path.dirname(model_dir)
        for f in sorted(os.listdir(toolbox_dir)) if os.path.isdir(toolbox_dir) else []:
            if f.endswith(".launch"):
                launch_path = os.path.join(toolbox_dir, f)
                break
    if launch_path and os.path.exists(launch_path):
        launch = parse_launch_file(launch_path)

    spec_name = launch.spec_name if launch else (extends[0] if extends else "")
    if spec_name in ("", "KubeAPI") and not extends and not os.path.exists(
        mc_tla_path
    ):
        # no MC.tla: the cfg may sit next to a bare root module; prefer
        # TLC's Foo.cfg <-> Foo.tla convention, then a module named like
        # the toolbox dir ("Foo.toolbox" -> Foo.tla), and refuse to guess
        # among several unrelated candidates (the alphabetically-first
        # pick could silently grab a helper module)
        cands = sorted(
            f[:-4] for f in os.listdir(model_dir) if f.endswith(".tla")
        )
        cfg_base = os.path.splitext(os.path.basename(cfg_path))[0]
        toolbox = os.path.basename(os.path.dirname(model_dir))
        toolbox = toolbox[:-8] if toolbox.endswith(".toolbox") else toolbox
        preferred = [p for p in (cfg_base, toolbox) if p in cands]
        if preferred:
            spec_name = preferred[0]
        elif len(cands) == 1:
            spec_name = cands[0]
        elif cands:
            raise ValueError(
                f"ambiguous root spec: several .tla modules next to the "
                f"config ({', '.join(cands)}) and none matches the config "
                f"name {cfg_base!r} or toolbox name {toolbox!r}; add a "
                ".launch file or an "
                "MC.tla naming the root module"
            )
    if frontend == "struct" or (
        frontend == "auto" and spec_name not in ("", "KubeAPI")
        and not os.path.exists(
            os.path.join(model_dir, f"{spec_name}.tla"))
        and os.path.exists(mc_tla_path)
    ):
        # forced structural path, or a non-KubeAPI MC whose root module
        # resolves through EXTENDS rather than a sibling file
        return _resolve_struct(cfg_path, cfg, launch, spec_name,
                               check_deadlock, workers, fp_index,
                               model_dir, const_overrides)
    if frontend == "hand" and spec_name not in ("", "KubeAPI"):
        raise ValueError(
            f"-frontend hand supports only the KubeAPI root spec, "
            f"not {spec_name!r}"
        )
    if spec_name not in ("", "KubeAPI") or frontend == "gen":
        # generic frontend (E1): execute any PlusCal-translation-subset
        # module found next to the config; outside-subset specs fall
        # back to the structural frontend (full expression language)
        tla_path = os.path.join(model_dir, f"{spec_name}.tla")
        if not os.path.exists(tla_path):
            raise ValueError(
                f"root spec {spec_name!r}: no {spec_name}.tla next to the "
                "config (the generic frontend loads the module from there)"
            )
        from ..gen.tla_parse import SpecParseError, load_genspec

        try:
            genspec = load_genspec(
                tla_path, consts, list(cfg.invariants), list(cfg.properties)
            )
        except SpecParseError as e:
            if frontend == "gen":
                raise ValueError(
                    f"root spec {spec_name!r} is outside the supported "
                    f"PlusCal-translation subset: {e}"
                )
            return _resolve_struct(cfg_path, cfg, launch, spec_name,
                                   check_deadlock, workers, fp_index,
                                   model_dir, const_overrides)
        if launch:
            # launch-file knobs apply to generic specs exactly as to the
            # KubeAPI path (deadlock switch, fpIndex)
            check_deadlock = launch.check_deadlock
            if fp_index is None:
                fp_index = launch.fp_index
        return GenRunSpec(
            genspec=genspec,
            invariants=list(cfg.invariants),
            properties=list(cfg.properties),
            check_deadlock=check_deadlock,
            workers=workers,
            fp_index=DEFAULT_FP_INDEX if fp_index is None else fp_index,
            spec_name=spec_name,
            model_name=os.path.basename(model_dir),
            tla_path=tla_path,
        )
    if cfg.specification not in (None, "Spec"):
        raise ValueError(f"unsupported SPECIFICATION {cfg.specification!r}")

    def boolify(name: str, default: bool) -> bool:
        v = consts.get(name, default)
        if isinstance(v, str):
            v = eval_constant(v)
        if not isinstance(v, bool):
            raise ValueError(f"constant {name} must be BOOLEAN, got {v!r}")
        return v

    model = ModelConfig(
        requests_can_fail=boolify("REQUESTS_CAN_FAIL", True),
        requests_can_timeout=boolify("REQUESTS_CAN_TIMEOUT", True),
    )

    invariants = [i for i in cfg.invariants if i]
    for inv in invariants:
        if inv not in KNOWN_INVARIANTS:
            raise ValueError(f"unknown INVARIANT {inv!r}")
    properties = list(cfg.properties)
    if launch:
        # launch-level enable/disable flags refine the cfg lists (launch:18-23)
        enabled_inv = {n for n, on in launch.invariants if on}
        if launch.invariants:
            invariants = [i for i in invariants if i in enabled_inv]
        properties = [n for n, on in launch.properties if on]
        check_deadlock = launch.check_deadlock
        if fp_index is None:
            fp_index = launch.fp_index

    return RunSpec(
        model=model,
        invariants=invariants,
        properties=properties,
        check_deadlock=check_deadlock,
        workers=workers,
        fp_index=DEFAULT_FP_INDEX if fp_index is None else fp_index,
        spec_name=spec_name or "KubeAPI",
        model_name=(launch.model_name if launch else os.path.basename(model_dir)),
    )


def _resolve_struct(cfg_path, cfg, launch, spec_name, check_deadlock,
                    workers, fp_index, model_dir,
                    const_overrides=None) -> StructRunSpec:
    from ..struct.loader import StructLoadError, load as load_struct
    from ..struct.parser import StructParseError

    try:
        sm = load_struct(cfg_path, const_overrides=const_overrides)
    except (StructLoadError, StructParseError) as e:
        raise ValueError(
            f"root spec {spec_name!r}: structural frontend cannot load "
            f"the module: {e}"
        )
    if launch:
        check_deadlock = launch.check_deadlock
        if fp_index is None:
            fp_index = launch.fp_index
    return StructRunSpec(
        structmodel=sm,
        invariants=list(cfg.invariants),
        properties=list(cfg.properties),
        check_deadlock=check_deadlock,
        workers=workers,
        fp_index=DEFAULT_FP_INDEX if fp_index is None else fp_index,
        spec_name=sm.root_name or spec_name,
        model_name=os.path.basename(model_dir),
    )
