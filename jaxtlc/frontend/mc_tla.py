"""MC.tla constant-override parser.

The Toolbox writes model constant overrides as an MC module EXTENDS-ing the
spec with generated definitions (/root/reference/KubeAPI.toolbox/Model_1/
MC.tla:1-14):

    \\* CONSTANT definitions @modelParameterConstants:1REQUESTS_CAN_FAIL
    const_1666989587949106000 ==
    TRUE

MC.cfg then binds `REQUESTS_CAN_FAIL <- const_1666989587949106000`.  We
parse the definition bodies (constant expressions only - the subset the
Toolbox generates for constant overrides) and the EXTENDS list.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List


@dataclasses.dataclass
class MCModule:
    extends: List[str]
    definitions: Dict[str, str]  # definition name -> literal body text


_DEF = re.compile(r"^(\w+)\s*==\s*(.*)$")


def parse_mc_tla(text: str) -> MCModule:
    extends: List[str] = []
    definitions: Dict[str, str] = {}
    pending: str = ""
    cur: str = ""
    for raw in text.splitlines():
        line = raw.split("\\*")[0].rstrip()
        s = line.strip()
        if s.startswith("EXTENDS"):
            extends = [x.strip() for x in s[len("EXTENDS"):].split(",")]
            continue
        if s.startswith("----") or s.startswith("===="):
            if cur and pending:
                definitions[cur] = pending.strip()
            cur, pending = "", ""
            continue
        m = _DEF.match(s)
        if m:
            if cur and pending:
                definitions[cur] = pending.strip()
            cur = m.group(1)
            pending = m.group(2)
            continue
        if cur:
            pending = (pending + " " + s).strip()
    if cur and pending:
        definitions[cur] = pending.strip()
    return MCModule(extends, definitions)


def parse_mc_tla_file(path: str) -> MCModule:
    with open(path, "r", encoding="utf-8") as f:
        return parse_mc_tla(f.read())


def eval_constant(body: str):
    """Evaluate a Toolbox-generated constant body (literal subset)."""
    b = body.strip()
    if b == "TRUE":
        return True
    if b == "FALSE":
        return False
    if re.fullmatch(r"-?\d+", b):
        return int(b)
    if b.startswith('"') and b.endswith('"'):
        return b[1:-1]
    return b  # model value / unresolved expression: keep symbolic
