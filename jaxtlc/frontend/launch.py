"""Toolbox .launch reader.

Parses the Eclipse launch configuration the Toolbox serializes per model run
(/root/reference/KubeAPI.toolbox/KubeAPI___Model_1.launch:1-37): worker
count (:33), fingerprint polynomial index (:8), deadlock checking (:16),
invariant/property selection with the 1/0 enabled prefix (:18-23), the
distributed-TLC knobs (:4-7), and constant assignments (:28-30).
"""

from __future__ import annotations

import dataclasses
import xml.etree.ElementTree as ET
from typing import Dict, List, Tuple


@dataclasses.dataclass
class LaunchConfig:
    spec_name: str
    model_name: str
    behavior_spec: str
    workers: int
    fp_index: int
    check_deadlock: bool
    invariants: List[Tuple[str, bool]]  # (name, enabled)
    properties: List[Tuple[str, bool]]
    constants: Dict[str, str]
    distributed_tlc: str
    distributed_fpset_count: int
    distributed_nodes_count: int


def parse_launch_file(path: str) -> LaunchConfig:
    root = ET.parse(path).getroot()
    s: Dict[str, str] = {}
    i: Dict[str, int] = {}
    b: Dict[str, bool] = {}
    lists: Dict[str, List[str]] = {}
    for el in root:
        key = el.get("key", "")
        if el.tag == "stringAttribute":
            s[key] = el.get("value", "")
        elif el.tag == "intAttribute":
            i[key] = int(el.get("value", "0"))
        elif el.tag == "booleanAttribute":
            b[key] = el.get("value") == "true"
        elif el.tag == "listAttribute":
            lists[key] = [e.get("value", "") for e in el]

    def flagged(entries: List[str]) -> List[Tuple[str, bool]]:
        # leading "1" = enabled, "0" = defined-but-disabled (launch:18-23)
        return [(e[1:], e[:1] == "1") for e in entries if e]

    constants: Dict[str, str] = {}
    for entry in lists.get("modelParameterConstants", []):
        # format: name;;value;kind;flag (launch:28-30)
        parts = entry.split(";")
        if len(parts) >= 3:
            constants[parts[0]] = parts[2]

    return LaunchConfig(
        spec_name=s.get("specName", ""),
        model_name=s.get("configurationName", ""),
        behavior_spec=s.get("modelBehaviorSpec", ""),
        workers=i.get("numberOfWorkers", 1),
        fp_index=i.get("fpIndex", 0),
        check_deadlock=b.get("modelCorrectnessCheckDeadlock", False),
        invariants=flagged(lists.get("modelCorrectnessInvariants", [])),
        properties=flagged(lists.get("modelCorrectnessProperties", [])),
        constants=constants,
        distributed_tlc=s.get("distributedTLC", "off"),
        distributed_fpset_count=i.get("distributedFPSetCount", 0),
        distributed_nodes_count=i.get("distributedNodesCount", 1),
    )
