"""TLC configuration (MC.cfg) parser.

Parses the TLC config DSL as exercised by the reference
(/root/reference/KubeAPI.toolbox/Model_1/MC.cfg:1-15): CONSTANT
declarations/substitutions, SPECIFICATION, INVARIANT and PROPERTY lists.
This file pair (MC.cfg + MC.tla) is "the plugin boundary the TPU backend
must accept unchanged" (SURVEY.md §1 L4->L3); the reference artifacts parse
as-is.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional


@dataclasses.dataclass
class TLCConfig:
    constants: Dict[str, str]  # CONSTANT name = value
    substitutions: Dict[str, str]  # CONSTANT name <- definition-name
    specification: Optional[str]
    invariants: List[str]
    properties: List[str]
    init: Optional[str] = None
    next: Optional[str] = None


_SECTION = re.compile(
    r"^(CONSTANTS?|SPECIFICATION|INVARIANTS?|PROPERTY|PROPERTIES|INIT|NEXT)\b"
)


def parse_cfg(text: str) -> TLCConfig:
    cfg = TLCConfig({}, {}, None, [], [])
    section = None
    for raw in text.splitlines():
        line = raw.split("\\*")[0].strip()  # \* comments
        if not line:
            continue
        m = _SECTION.match(line)
        if m:
            section = m.group(1)
            line = line[m.end():].strip()
            if not line:
                continue
        if section is None:
            continue
        if section.startswith("CONSTANT"):
            if "<-" in line:
                name, val = (x.strip() for x in line.split("<-", 1))
                cfg.substitutions[name] = val
            elif "=" in line:
                name, val = (x.strip() for x in line.split("=", 1))
                cfg.constants[name] = val
            else:
                # bare model-value declaration
                cfg.constants[line] = line
        elif section == "SPECIFICATION":
            cfg.specification = line
        elif section.startswith("INVARIANT"):
            cfg.invariants.extend(line.split())
        elif section in ("PROPERTY", "PROPERTIES"):
            cfg.properties.extend(line.split())
        elif section == "INIT":
            cfg.init = line
        elif section == "NEXT":
            cfg.next = line
    return cfg


def parse_cfg_file(path: str) -> TLCConfig:
    with open(path, "r", encoding="utf-8") as f:
        return parse_cfg(f.read())
