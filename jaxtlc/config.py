"""Model configuration: constants + finite bounds for the tensor codec.

The reference pins its two fault-injection constants in
/root/reference/KubeAPI.toolbox/Model_1/MC.tla:4-11 (both TRUE) and binds them
via MC.cfg:2-8.  The state space is finite because every domain in the spec is
finite; this module records those bounds so the codec can allocate fixed-width
slots (SURVEY.md §7 "hard parts": bounds must be config-driven with overflow
detection).

Scaled configs (BASELINE.json: N controllers x M objects) generalize the
process set: N *reconciler* clients - copies of the spec's `process Client`
(KubeAPI.tla:161-220), each owning a private Secret kind and one PVC - plus
M *binder* controllers - copies of `process PVCController`
(KubeAPI.tla:225-260), each able to bind ANY unbound PVC.  All PVCs share the
"PVC" kind, so binders couple every reconciler's state machine exactly the
way the single PVCController couples with the single Client in Model_1;
secrets get per-reconciler kinds so one client's cleanup (which deletes every
listed object of its secret kind, KubeAPI.tla:618-629) cannot delete another
client's secret and break the reconcile assert (KubeAPI.tla:196).
`shouldReconcile` becomes a function over the reconciler set (the spec's is
`[{"Client"} -> BOOLEAN]`, KubeAPI.tla:465), giving 2^N initial states.

Everything downstream (codec widths, kernel lane counts) derives from this
object; Model_1 is the (1 reconciler, 1 binder) instance with the reference's
exact names.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

RECONCILER = "reconciler"
BINDER = "binder"


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Bounds and constants for one model-checking run."""

    # Fault-injection constants (KubeAPI.tla:4-9; MC.tla:4-11)
    requests_can_fail: bool = True
    requests_can_timeout: bool = True

    # Object identities (kind, name) that can ever exist in apiState.
    # Model_1 only ever writes Secret/"foo" (KubeAPI.tla:176) and PVC/"mypvc"
    # (KubeAPI.tla:182).
    identities: Tuple[Tuple[str, str], ...] = (("Secret", "foo"), ("PVC", "mypvc"))

    # Client processes (issue API/ListAPI calls; ProcSet minus the server,
    # KubeAPI.tla:453).  Order fixes the vv bit assignment and the request
    # slot order.
    clients: Tuple[str, ...] = ("Client", "PVCController")

    # Self-test mutation: deliberately break one transition rule so the
    # violation-detection + trace-reconstruction pipeline can be exercised
    # end-to-end (the spec itself is correct, so no real config violates).
    #   ""            - faithful semantics
    #   "delete_noop" - server Delete leaves apiState unchanged; the
    #                   cleanup assert at KubeAPI.tla:216 must then fire
    #   "sticky_reconcile" - C2 does not clear shouldReconcile; the
    #                   ReconcileCompletes liveness property must then fail
    mutation: str = ""

    # Role of each client, aligned with `clients`: RECONCILER runs the
    # Client label machine (CStart..C5), BINDER runs the PVCController one
    # (PVCStart..PVCDone).
    roles: Tuple[str, ...] = (RECONCILER, BINDER)

    # Per-client (secret_identity_index, pvc_identity_index) into
    # `identities` for reconcilers ((-1, -1) for binders): the objects that
    # client's Force/Get calls target (KubeAPI.tla:176,182).
    targets: Tuple[Tuple[int, int], ...] = ((0, 1), (-1, -1))

    def __post_init__(self):
        assert len(self.roles) == len(self.clients) == len(self.targets)
        for role, (si, pi) in zip(self.roles, self.targets):
            if role == RECONCILER:
                assert 0 <= si < len(self.identities)
                assert 0 <= pi < len(self.identities)
                assert self.identities[pi][0] == "PVC", (
                    "reconciler PVC target must have kind 'PVC' "
                    "(binders list that kind, KubeAPI.tla:227)"
                )
            else:
                assert role == BINDER and (si, pi) == (-1, -1)

    @property
    def kinds(self) -> Tuple[str, ...]:
        seen = []
        for k, _ in self.identities:
            if k not in seen:
                seen.append(k)
        return tuple(seen)

    @property
    def n_identities(self) -> int:
        return len(self.identities)

    @property
    def n_clients(self) -> int:
        return len(self.clients)

    @property
    def processes(self) -> Tuple[str, ...]:
        """ProcSet (KubeAPI.tla:453): the clients plus the API server."""
        return self.clients + ("Server",)

    @property
    def reconciler_indices(self) -> Tuple[int, ...]:
        """Client indices running the reconciler label machine, in order;
        position in this tuple == that client's shouldReconcile bit."""
        return tuple(i for i, r in enumerate(self.roles) if r == RECONCILER)

    @property
    def n_reconcilers(self) -> int:
        return len(self.reconciler_indices)

    @property
    def max_per_kind(self) -> int:
        """Max number of identities sharing one kind == list-result bound."""
        return max(sum(1 for k, _ in self.identities if k == kk) for kk in self.kinds)

    def identity_id(self, kind: str, name: str) -> int:
        return self.identities.index((kind, name))

    def sr_index(self, client_index: int) -> int:
        """shouldReconcile bit position for a reconciler client."""
        return self.reconciler_indices.index(client_index)


# The configuration checked by the committed reference run
# (/root/reference/KubeAPI.toolbox/Model_1/MC.out).
MODEL_1 = ModelConfig(requests_can_fail=True, requests_can_timeout=True)

# The fault-injection smoke-test matrix (SURVEY.md §4 item 3): turning the
# constants off shrinks the state space - the natural fast-CI corners.
MATRIX = {
    (False, False): ModelConfig(False, False),
    (False, True): ModelConfig(False, True),
    (True, False): ModelConfig(True, False),
    (True, True): MODEL_1,
}


def make_scaled(
    n_reconcilers: int = 2,
    n_binders: int = 1,
    requests_can_fail: bool = True,
    requests_can_timeout: bool = True,
    mutation: str = "",
) -> ModelConfig:
    """N-controller x M-object generalization (BASELINE.json "KubeAPI.tla
    scaled"): n_reconcilers Client copies + n_binders PVCController copies
    over 2*n_reconcilers object identities."""
    assert n_reconcilers >= 1
    identities = []
    clients, roles, targets = [], [], []
    for i in range(n_reconcilers):
        identities.append((f"Secret{i}", "foo"))
        identities.append(("PVC", f"pvc{i}"))
        clients.append(f"Client{i}")
        roles.append(RECONCILER)
        targets.append((2 * i, 2 * i + 1))
    for j in range(n_binders):
        clients.append(f"PVCCtl{j}")
        roles.append(BINDER)
        targets.append((-1, -1))
    return ModelConfig(
        requests_can_fail,
        requests_can_timeout,
        tuple(identities),
        tuple(clients),
        mutation,
        tuple(roles),
        tuple(targets),
    )


def scaled_config():
    """The `bench.py --scaled` workload: config + engine sizing.

    This is the workload the 50x throughput target is defined on
    (BASELINE.json): a frontier wide enough to keep the MXU/VPU busy, unlike
    Model_1 whose peak frontier is ~906 states (MC.out:35).
    """
    cfg = make_scaled(n_reconcilers=2, n_binders=1, requests_can_fail=False,
                      requests_can_timeout=False)
    # chunk 128k is the measured on-chip optimum for the v4 engine (v5e:
    # 507k distinct/s vs 355-380k at 64k and 403k at 256k - the avg BFS
    # level is ~104k wide, so 128k pops a whole level per step while
    # larger chunks pay for static candidate width they can't fill).
    # fp_capacity 4x the state count keeps end-of-run load at 0.29: the
    # batched bucket probe pays for the worst straggler walk in the
    # batch, and 2^27 measured SLOWER (427k/s) from table memory traffic.
    return cfg, dict(chunk=131072, queue_capacity=1 << 21,
                     fp_capacity=1 << 26)
