"""Model configuration: constants + finite bounds for the tensor codec.

The reference pins its two fault-injection constants in
/root/reference/KubeAPI.toolbox/Model_1/MC.tla:4-11 (both TRUE) and binds them
via MC.cfg:2-8.  The state space is finite because every domain in the spec is
finite; this module records those bounds so the codec can allocate fixed-width
slots (SURVEY.md §7 "hard parts": bounds must be config-driven with overflow
detection).

Scaled configs (BASELINE.json: N controllers x M objects) grow `identities`
and `clients`; everything downstream (codec widths, kernel lane counts) is
derived from this object.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Bounds and constants for one model-checking run."""

    # Fault-injection constants (KubeAPI.tla:4-9; MC.tla:4-11)
    requests_can_fail: bool = True
    requests_can_timeout: bool = True

    # Object identities (kind, name) that can ever exist in apiState.
    # Model_1 only ever writes Secret/"foo" (KubeAPI.tla:176) and PVC/"mypvc"
    # (KubeAPI.tla:182).
    identities: Tuple[Tuple[str, str], ...] = (("Secret", "foo"), ("PVC", "mypvc"))

    # Client processes (issue API/ListAPI calls; ProcSet minus the server,
    # KubeAPI.tla:453).  Order fixes the vv bit assignment and the request
    # slot order.
    clients: Tuple[str, ...] = ("Client", "PVCController")

    # Self-test mutation: deliberately break one transition rule so the
    # violation-detection + trace-reconstruction pipeline can be exercised
    # end-to-end (the spec itself is correct, so no real config violates).
    #   ""            - faithful semantics
    #   "delete_noop" - server Delete leaves apiState unchanged; the
    #                   cleanup assert at KubeAPI.tla:216 must then fire
    mutation: str = ""

    @property
    def kinds(self) -> Tuple[str, ...]:
        seen = []
        for k, _ in self.identities:
            if k not in seen:
                seen.append(k)
        return tuple(seen)

    @property
    def n_identities(self) -> int:
        return len(self.identities)

    @property
    def n_clients(self) -> int:
        return len(self.clients)

    @property
    def max_per_kind(self) -> int:
        """Max number of identities sharing one kind == list-result bound."""
        return max(sum(1 for k, _ in self.identities if k == kk) for kk in self.kinds)

    def identity_id(self, kind: str, name: str) -> int:
        return self.identities.index((kind, name))


# The configuration checked by the committed reference run
# (/root/reference/KubeAPI.toolbox/Model_1/MC.out).
MODEL_1 = ModelConfig(requests_can_fail=True, requests_can_timeout=True)

# The fault-injection smoke-test matrix (SURVEY.md §4 item 3): turning the
# constants off shrinks the state space - the natural fast-CI corners.
MATRIX = {
    (False, False): ModelConfig(False, False),
    (False, True): ModelConfig(False, True),
    (True, False): ModelConfig(True, False),
    (True, True): MODEL_1,
}
