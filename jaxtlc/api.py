"""Engine-as-a-library: the check-orchestration layer (ISSUE 9).

`run_check(CheckRequest) -> CheckOutcome` is the one entrypoint every
front door shares: the CLI (`python -m jaxtlc.cli check`, a thin
argparse shim now), the checking service (`jaxtlc.serve` - a long-lived
server submitting many jobs per process), and tests all orchestrate a
check through this module.  Until round 9 the CLI owned all of this
(cli.py at 1331 lines); a serving process cannot shell out to argparse,
so the orchestration moved here wholesale - frontend resolution,
preflight gating, engine dispatch (fused / sharded / hybrid / struct /
gen, supervised or raw), liveness, trace reconstruction, journal
lifecycle and the TLC log protocol.

The TLC transcript is written to `CheckRequest.out` (default: the
process stdout, which is what keeps the CLI's pinned transcripts
byte-identical); a server passes an io.StringIO per job and stores the
transcript as the job's output.  Exit-code conventions are unchanged
(0 ok / 12 safety / 13 liveness / 75 interrupted-or-exhausted /
1 usage+error); `CheckOutcome.verdict` is the same fact as a string.
"""

from __future__ import annotations

import dataclasses
import os
import sys
import time
from typing import Optional, TextIO

from . import __version__
from .config import ModelConfig
from .engine.fingerprint import DEFAULT_SEED
from .frontend.model import RunSpec, resolve
from .io.tlc_log import TLCLog


@dataclasses.dataclass
class CheckRequest:
    """One check, fully specified - the library form of the CLI flag
    set (field names match the argparse dests on purpose: the CLI
    builds a request with `CheckRequest.from_args(args)` and every
    default below mirrors the flag default, so flag semantics are
    documented once, in cli.py)."""

    config: str
    workers: str = "tpu"
    frontend: str = "auto"
    fpset: str = "JaxFPSet"
    fp: Optional[int] = None
    sharded: int = 0
    chunk: int = 1024
    pipeline: bool = False
    # tri-state -sort-free/-no-sort-free: None = auto (the engines
    # resolve it against the chunk, engine.bfs.resolve_sort_free)
    sortfree: Optional[bool] = None
    # tri-state -deferred-inv/-no-deferred-inv (ISSUE 15): None = auto
    # (resolved against the chunk, engine.bfs.resolve_deferred) -
    # invariant/certificate evaluation on the fresh-insert claimants
    # at the commit stage instead of every chunk*L candidate lane.
    # The -simulate tier ignores it: every walker state is "fresh", so
    # the sim engines keep their immediate per-walker invariant path.
    deferredinv: Optional[bool] = None
    # tri-state -symmetry/-no-symmetry and -por/-no-por (ISSUE 18):
    # None = auto (resolve_symmetry/resolve_por - currently OFF: both
    # reductions legitimately shrink the state counts, so they are
    # opt-in, not auto-on perf modes).  -symmetry canonicalizes every
    # successor to its orbit representative over statically-verified
    # symmetric constant sets (runtime orbit certificate on single
    # device); -por prunes commutative interleavings of provably safe
    # actions.  Struct frontend only.
    symmetry: Optional[bool] = None
    por: Optional[bool] = None
    routefactor: float = 2.0
    qcap: int = 1 << 15
    fpcap: int = 1 << 20
    checkpoint: str = ""
    checkpointevery: int = 256
    recover: bool = False
    autogrow: bool = True
    spill: str = "auto"
    maxregrow: int = 8
    retry: int = 2
    faults: str = ""
    obs: bool = True
    obsslots: int = 256
    journal: str = ""
    serve: int = 0
    phasetiming: bool = False
    traceout: str = ""
    xprof: str = ""
    analyze: bool = False
    preflight: bool = True
    narrow: bool = False
    coverage: bool = False
    liveness: bool = False
    liveness_host: bool = False
    fairness: str = "wf_next"
    nodeadlock: bool = False
    noTool: bool = False
    traceExpressions: str = ""
    mutation: str = ""
    # incremental re-checking (struct.artifacts, ISSUE 13): the
    # content-addressed verdict + reachable-set cache.  artifactcache
    # overrides the store directory ("" = JAXTLC_ARTIFACT_CACHE or
    # ~/.cache/jaxtlc/artifacts); noartifactcache disables both tiers
    # for this run; recheck forces a cache BYPASS on read (the run
    # still refreshes the artifacts it produces)
    artifactcache: str = ""
    noartifactcache: bool = False
    recheck: bool = False
    # simulation tier (jaxtlc.sim, ISSUE 14): -simulate swaps the
    # exhaustive BFS for W vmapped random walks of depth N - the cheap
    # smoke-check job class.  Every walk lane is a pure function of
    # (simseed, lane), so violations replay host-side from the seed
    # alone (sim.replay); a clean sim verdict is a SMOKE verdict and
    # never publishes to the artifact-cache verdict tier
    simulate: bool = False
    depth: int = 100
    walkers: int = 256
    simseed: int = 0
    # invariant inference (jaxtlc.infer, ISSUE 16): -infer swaps
    # checking for the conjecture -> filter -> certify loop - a third
    # verdict class beside exhaustive and smoke.  Like sim it never
    # publishes to the artifact-cache verdict tier (its verdict is
    # about CANDIDATES, not the spec's stated invariants); unlike sim
    # it READS the reachable-set artifact as exact filter evidence
    infer: bool = False
    inferbudget: int = 64
    # -- library-only knobs (no CLI flag) -------------------------------
    # MC.cfg-style constant overrides applied on top of the config's
    # baked values (the serve path: a job's constants must shape the
    # checked configuration on EVERY route, supervised included)
    constants: dict = dataclasses.field(default_factory=dict)
    # programmatic drain request (ISSUE 17): a threading.Event the
    # caller sets to preempt THIS run at the next segment boundary -
    # the in-process twin of SIGTERM, riding the same checkpoint +
    # exit-75 machinery (resil.supervisor / sim.driver honor it).  The
    # serve scheduler's deadline/priority/cancel preemptions all route
    # through here, so preempting one job never signals the server
    drain: Optional[object] = dataclasses.field(
        default=None, repr=False, compare=False
    )
    # transcript / error sinks; None = the process stdout / stderr (the
    # CLI path - pinned transcripts depend on it)
    out: Optional[TextIO] = dataclasses.field(
        default=None, repr=False, compare=False
    )
    err: Optional[TextIO] = dataclasses.field(
        default=None, repr=False, compare=False
    )

    @classmethod
    def from_args(cls, args) -> "CheckRequest":
        """Build a request from an argparse namespace (unknown request
        fields keep their defaults, extra namespace attrs are ignored -
        the two sides may evolve independently)."""
        kw = {}
        for f in dataclasses.fields(cls):
            if f.name in ("out", "err"):
                continue
            if hasattr(args, f.name):
                kw[f.name] = getattr(args, f.name)
        return cls(**kw)


VERDICT_BY_EXIT = {
    0: "ok",
    1: "error",
    12: "violation",
    13: "liveness_violation",
    75: "interrupted",
}


@dataclasses.dataclass
class CheckOutcome:
    """What a check did: the exit-code fact, its string form, the
    engine-level result (None when resolution/preflight failed before
    any engine ran), and where the run journal landed."""

    exit_code: int
    verdict: str
    result: object = None  # engine.bfs.CheckResult | None
    journal_path: str = ""


def run_check(req: CheckRequest) -> CheckOutcome:
    """Run one check end to end.  Everything the CLI `check` subcommand
    did - resolution, preflight, dispatch, liveness, traces, journal -
    against the request's sinks instead of the process streams."""
    rc = _run_check(req)
    return CheckOutcome(
        exit_code=rc,
        verdict=VERDICT_BY_EXIT.get(rc, f"exit_{rc}"),
        result=getattr(req, "_result", None),
        journal_path=getattr(req, "_journal_path", "") or "",
    )


def _err(args) -> TextIO:
    return getattr(args, "err", None) or sys.stderr


def _run_check(args) -> int:
    try:
        spec: RunSpec = resolve(
            args.config,
            workers=args.workers,
            fp_index=args.fp,
            check_deadlock=not args.nodeadlock,
            frontend=args.frontend,
            const_overrides=getattr(args, "constants", None) or None,
        )
    except (ValueError, OSError) as e:
        print(f"Error: {e}", file=_err(args))
        return 1
    from .frontend.model import GenRunSpec, StructRunSpec

    if getattr(args, "simulate", False) and not isinstance(
            spec, StructRunSpec):
        # the simulation tier rides the struct frontend (the host
        # interpreter renders its replayed traces); -frontend struct
        # runs ANY spec, so this is a spelling, not a capability, gap
        print("Error: -simulate requires the structural frontend "
              "(re-run with -frontend struct)", file=_err(args))
        return 1
    if getattr(args, "infer", False):
        if getattr(args, "simulate", False):
            print("Error: -infer and -simulate are distinct job "
                  "classes (pick one)", file=_err(args))
            return 1
        if not isinstance(spec, StructRunSpec):
            # inference conjectures over the struct IR's shapes; like
            # -simulate this is a spelling, not a capability, gap
            print("Error: -infer requires the structural frontend "
                  "(re-run with -frontend struct)", file=_err(args))
            return 1
    if isinstance(spec, GenRunSpec):
        return _run_check_gen(args, spec)
    if isinstance(spec, StructRunSpec):
        return _run_check_struct(args, spec)
    from .frontend.model import KNOWN_PROPERTIES

    unknown = [q for q in spec.properties if q not in KNOWN_PROPERTIES]
    if unknown:
        print(
            f"Error: unknown PROPERTY {', '.join(unknown)} "
            f"(supported: {', '.join(KNOWN_PROPERTIES)})",
            file=_err(args),
        )
        return 1
    if args.mutation:
        spec.model = dataclasses.replace(spec.model, mutation=args.mutation)
    if args.recover and not args.checkpoint:
        print("Error: -recover requires -checkpoint PATH", file=_err(args))
        return 1

    log = TLCLog(out=args.out, tool_mode=not args.noTool,
                 **_render_sources(args.config, spec.spec_name))
    import jax

    device = str(jax.devices()[0])
    log.version(__version__)
    log.banner(spec.fp_index, DEFAULT_SEED, spec.workers, device)
    log.sany(*_sany_inputs(args.config, spec.spec_name))
    log.starting()
    log.computing_init()

    _open_journal(
        args, workload=spec.spec_name,
        engine=("hybrid" if args.fpset == "DiskFPSet"
                else "sharded" if args.sharded else "single"),
        device=device,
        params=dict(chunk=args.chunk, queue_capacity=args.qcap,
                    fp_capacity=args.fpcap, sharded=args.sharded,
                    pipeline=args.pipeline,
                    sort_free=_sort_free(args),
                    deferred=_deferred(args),
                    obs_slots=_obs_slots(args)),
    )

    def _kubeapi_preflight(deep):
        from .analysis.preflight import preflight_kubeapi

        return preflight_kubeapi(
            spec.model, fp_capacity=args.fpcap, chunk=args.chunk,
            queue_capacity=args.qcap, deep=deep,
        )

    rc = _preflight_gate(args, log, _kubeapi_preflight)
    if rc is not None:
        return rc
    t0 = time.time()
    from .resil import SlotOverflowError

    sup = None  # SupervisedResult when the resil supervisor ran
    try:
        with _xprof(args):
            r, sup = _dispatch_check(args, spec, log)
    except SlotOverflowError as e:
        log.msg(1000, f"Run stopped: {e}", severity=1)
        _finish_journal(args, log)
        return 1
    except FileNotFoundError as e:
        print(f"Error: {e}", file=_err(args))
        _finish_journal(args, log)
        return 1
    args._result = r
    log.init_done(2 ** spec.model.n_reconcilers)

    if sup is not None and sup.interrupted:
        # the interrupted banner (with the resume command) was already
        # emitted by the supervisor's event hook
        from .resil import EXIT_INTERRUPTED

        log.progress(r.depth, r.generated, r.distinct, r.queue_left)
        log.final_counts(r.generated, r.distinct, r.queue_left)
        _finish_journal(args, log, r=None, sup=sup)
        return EXIT_INTERRUPTED

    from .engine.bfs import (
        VIOL_ASSERT,
        VIOL_DEADLOCK,
        VIOL_ONLYONEVERSION,
        VIOL_TYPEOK,
    )

    violated = r.violation != 0
    liveness_violated = False
    if not violated and (args.liveness or spec.properties):
        from .live.check import check_properties_device, use_device_path
        from .spec.codec import get_codec
        from .spec.pretty import state_to_tla

        props = spec.properties or ["ReconcileCompletes", "CleansUpProperly"]
        device_path = use_device_path(
            r.distinct, args.fairness, args.liveness_host
        )
        log.checking_temporal(
            r.distinct, "device" if device_path else "host"
        )
        if device_path:
            mesh = None
            if args.sharded:
                from jax.sharding import Mesh

                import numpy as np

                mesh = Mesh(np.array(jax.devices()[: args.sharded]),
                            ("fp",))
            results = check_properties_device(
                spec.model, props, chunk=args.chunk,
                state_capacity=args.fpcap, fp_capacity=args.fpcap,
                mesh=mesh,
                spill_path=args.checkpoint or None,
            )
        else:
            from .engine.liveness import build_graph, check_properties

            graph = build_graph(spec.model, chunk=args.chunk)
            results = check_properties(
                spec.model, props, graph=graph,
                fairness=args.fairness,
            )
        decode = get_codec(spec.model).decode
        for res in results:
            if res.holds:
                log.msg(1000, f"Temporal property {res.name} holds "
                              f"(fairness: {args.fairness}).")
                continue
            liveness_violated = True
            log.msg(2116, f"Temporal properties were violated: {res.name} "
                          f"(fairness: {args.fairness})", severity=1)
            idx = 1
            for enc, act in zip(res.prefix, res.prefix_actions):
                log.trace_state(idx, act, state_to_tla(decode(enc), spec.model))
                idx += 1
            log.msg(1000, "-- The following states form a cycle "
                          "(back to the first of them) --")
            for enc, act in zip(res.cycle, res.cycle_actions):
                log.trace_state(idx, act, state_to_tla(decode(enc), spec.model))
                idx += 1
    if violated:
        if r.violation == VIOL_TYPEOK and "TypeOK" in spec.invariants:
            log.invariant_violated("TypeOK")
        elif r.violation == VIOL_ONLYONEVERSION and (
            "OnlyOneVersion" in spec.invariants
        ):
            log.invariant_violated("OnlyOneVersion")
        elif r.violation == VIOL_ASSERT:
            log.assertion_failed("Failure of PlusCal assertion.")
        elif r.violation == VIOL_DEADLOCK and spec.check_deadlock:
            log.deadlock()
        else:
            log.msg(1000, f"Run stopped: {r.violation_name}", severity=1)
        _print_trace(log, spec.model, args.chunk,
                     trace_expr_file=args.traceExpressions,
                     check_deadlock=spec.check_deadlock)
    elif not liveness_violated:
        log.success(r.generated, r.distinct,
                    getattr(r, "actual_fp_collision", None),
                    occupancy=getattr(r, "fp_occupancy", None))
        if args.coverage:
            # full per-expression dump (MC.out:44-1092): re-walk the space
            # with the instrumented evaluator (host-side; slow for large
            # configs - TLC's coverage mode pays a similar tax)
            from .spec.coverage import render_coverage, run_coverage

            cov = run_coverage(spec.model)
            stamp = time.strftime("%Y-%m-%d %H:%M:%S")
            for line in render_coverage(cov, stamp, tool_mode=log.tool):
                log.raw(line)
        else:
            log.coverage(2, r.action_generated, r.action_distinct)

    log.progress(r.depth, r.generated, r.distinct, r.queue_left)
    log.final_counts(r.generated, r.distinct, r.queue_left)
    log.depth(r.depth)
    if r.outdegree is not None:
        log.outdegree(*r.outdegree)
    log.finished(int((time.time() - t0) * 1000))
    _finish_journal(
        args, log, r=r, sup=sup,
        verdict="liveness_violation" if liveness_violated else None,
        wall_s=time.time() - t0,
    )
    if violated:
        return 12
    return 13 if liveness_violated else 0  # TLC liveness exit convention


def _xprof(args):
    """jax.profiler trace context for `-xprof DIR` (the ground-truth
    device timeline; the journal's -trace-out is the cheap host view).
    A no-op context when the flag is off."""
    import contextlib

    if not args.xprof:
        return contextlib.nullcontext()
    import jax

    return jax.profiler.trace(args.xprof)


def _dispatch_check(args, spec, log):
    """Run the KubeAPI-path engine picked by the flags.  Returns
    (CheckResult, SupervisedResult-or-None).

    Dispatch priority: DiskFPSet routes to the host tier even when
    -sharded is given (sharding then means fingerprint-space partitions).
    The resil supervisor wraps the device engines whenever -auto-grow
    (default) or -checkpoint is in play; -no-auto-grow without
    -checkpoint keeps the raw fused single-dispatch path."""
    import jax

    if args.sharded and args.fpset != "DiskFPSet":
        import numpy as np
        from jax.sharding import Mesh

        from .engine.sharded import check_sharded

        mesh = Mesh(np.array(jax.devices()[: args.sharded]), ("fp",))
        if args.checkpoint or args.autogrow:
            from .resil import check_sharded_supervised

            sup = check_sharded_supervised(
                spec.model,
                mesh,
                chunk=args.chunk,
                queue_capacity=args.qcap,
                fp_capacity=args.fpcap,
                route_factor=args.routefactor,
                pipeline=args.pipeline,
                obs_slots=_obs_slots(args),
                coverage=args.coverage,
                sort_free=args.sortfree,
                deferred=args.deferredinv,
                opts=_sup_opts(args, log),
            )
            return sup.result, sup
        from .engine.backend import kubeapi_backend

        return check_sharded(
            spec.model,
            mesh,
            chunk=args.chunk,
            queue_capacity=args.qcap,
            fp_capacity=args.fpcap,
            route_factor=args.routefactor,
            backend=kubeapi_backend(spec.model,
                                    coverage=args.coverage),
            pipeline=args.pipeline,
            obs_slots=_obs_slots(args),
            sort_free=args.sortfree,
            deferred=args.deferredinv,
        ), None
    if args.fpset == "DiskFPSet":
        # the OffHeapDiskFPSet/DiskStateQueue analog: authoritative dedup +
        # frontier in the native (C++, disk-bounded) host tier.  Composes
        # with -checkpoint (the disk tier's files ARE the snapshot, as in
        # TLC) and with -sharded N (N fingerprint-space partitions - the
        # distributed-fingerprint-server analog, launch:4)
        from .engine.hybrid import check_hybrid

        nparts = max(args.sharded, 1)
        if nparts & (nparts - 1):
            raise FileNotFoundError(
                "-sharded with -fpset DiskFPSet needs a power-of-two "
                f"partition count, got {nparts}"
            )
        return check_hybrid(
            spec.model,
            chunk=args.chunk,
            fp_index=spec.fp_index,
            fp_partitions=nparts,
            ckpt_path=args.checkpoint or None,
            ckpt_every=args.checkpointevery,
            resume=args.recover,
        ), None
    if args.checkpoint or args.autogrow:
        from .resil import check_supervised

        sup = check_supervised(
            spec.model,
            chunk=args.chunk,
            queue_capacity=args.qcap,
            fp_capacity=args.fpcap,
            fp_index=spec.fp_index,
            pipeline=args.pipeline,
            obs_slots=_obs_slots(args),
            coverage=args.coverage,
            sort_free=args.sortfree,
            deferred=args.deferredinv,
            opts=_sup_opts(args, log),
        )
        return sup.result, sup
    from .engine.bfs import check

    return check(
        spec.model,
        chunk=args.chunk,
        queue_capacity=args.qcap,
        fp_capacity=args.fpcap,
        fp_index=spec.fp_index,
        pipeline=args.pipeline,
        obs_slots=_obs_slots(args),
        coverage=args.coverage,
        sort_free=args.sortfree,
        deferred=args.deferredinv,
    ), None


def _preflight_gate(args, log, build_report):
    """Run the preflight suite before a check (ISSUE 6 pipeline).

    -no-preflight skips entirely; -analyze runs the deep mode (adds
    the engine jaxpr purity trace - tracing only, no XLA compile).
    Findings journal as schema-validated `analysis` events and render
    as TLC-style warning banners (derived views of the same events, so
    they cannot disagree); a clean preflight is silent.  Returns the
    nonzero exit code on error-severity findings, None to proceed."""
    if not args.preflight:
        return None
    from .analysis.report import emit_to_journal
    from .obs.views import render_tlc_event

    try:
        report = build_report(args.analyze)
    except Exception as e:  # a broken lint must never block a run
        log.msg(1000, f"Preflight analysis skipped: {e}", severity=1)
        return None
    journal = getattr(args, "_journal", None)

    def on_event(kind, info):
        import time as _time

        from .obs.schema import SCHEMA_VERSION

        render_tlc_event(log, {"v": SCHEMA_VERSION, "t": _time.time(),
                               "event": kind, **info})

    emit_to_journal(journal, report, on_event=on_event)
    if report.errors:
        if journal is not None:
            journal.event("final", verdict="error", generated=0,
                          distinct=0, depth=0, queue=0, wall_s=0.0,
                          interrupted=False)
        log.msg(1000, "Preflight analysis found error-severity "
                      "findings; run aborted (-no-preflight to "
                      "override).", severity=1)
        _finish_journal(args, log)
        return report.exit_code
    return None


def _sup_opts(args, log, capture_fps: bool = False):
    """SupervisorOptions from the request.  Every supervisor event is
    written to the run journal FIRST (the single source of truth), then
    the TLC-style banner is rendered as a derived view of that journal
    event (obs.views.render_tlc_event) - the 2200 Progress line and the
    checkpoint/recovery/regrow banners cannot drift from what the
    journal records."""
    from .obs.views import render_tlc_event
    from .resil import FaultPlan, SupervisorOptions

    journal = getattr(args, "_journal", None)
    resume_cmd = _resume_command(args)

    def on_event(kind, info):
        if journal is not None:
            ev = journal.event(kind, **info)
        else:
            import time as _time

            from .obs.schema import SCHEMA_VERSION

            ev = {"v": SCHEMA_VERSION, "t": _time.time(),
                  "event": kind, **info}
        render_tlc_event(log, ev, resume_cmd=resume_cmd)

    return SupervisorOptions(
        auto_grow=args.autogrow,
        max_regrow=args.maxregrow,
        retries=args.retry,
        ckpt_path=args.checkpoint or None,
        ckpt_every=args.checkpointevery,
        resume=args.recover,
        spill=args.spill,
        phase_timing=args.phasetiming,
        faults=FaultPlan.parse(args.faults) if args.faults else None,
        capture_fps=capture_fps,
        on_event=on_event,
        drain=getattr(args, "drain", None),
    )


def _obs_slots(args) -> int:
    """Counter-ring depth in effect: -no-obs disables the device tier
    entirely (the A/B baseline; also the shape pre-obs checkpoints
    expect), otherwise -obs-slots levels of history ride the carry."""
    return args.obsslots if args.obs else 0


def _sort_free(args) -> bool:
    """The RESOLVED -sort-free mode this run's engines will use (the
    run_start journal manifest records the fact, not the tri-state)."""
    from .engine.bfs import resolve_sort_free

    return resolve_sort_free(getattr(args, "sortfree", None), args.chunk)


def _deferred(args) -> bool:
    """The RESOLVED -deferred-inv mode this run's engines will use
    (journal manifests record the fact, not the tri-state; the same
    resolve the engine factories / memos / checkpoint meta compute)."""
    from .engine.bfs import resolve_deferred

    return resolve_deferred(getattr(args, "deferredinv", None),
                            args.chunk)


def _symmetry(args) -> bool:
    """The RESOLVED -symmetry mode this run's engines will use (journal
    manifests record the fact, not the tri-state)."""
    from .engine.bfs import resolve_symmetry

    return resolve_symmetry(getattr(args, "symmetry", None), args.chunk)


def _por(args) -> bool:
    """The RESOLVED -por mode this run's engines will use."""
    from .engine.bfs import resolve_por

    return resolve_por(getattr(args, "por", None), args.chunk)


def _open_journal(args, workload: str, engine: str, device: str,
                  params: dict):
    """Create the run journal and stamp the manifest.

    Path resolution: -journal PATH wins; else a -checkpoint run
    journals beside its snapshots (PATH.journal.jsonl) so preemption
    and -recover find it; else the journal is in-memory only (still
    powers -trace-out).  A -recover run APPENDS and stamps run_resume:
    one continuous journal per logical run, not one per attempt."""
    from . import __version__ as _v
    from .obs.journal import RunJournal

    path = args.journal or (
        args.checkpoint + ".journal.jsonl" if args.checkpoint else ""
    )
    if not path and args.serve:
        # the monitor serves journal FILES; an unjournaled -serve run
        # gets one beside the temp dir (printed below via the server)
        import tempfile

        path = os.path.join(
            tempfile.gettempdir(),
            f"jaxtlc-{os.getpid()}.journal.jsonl",
        )
    resume = bool(args.recover and path and os.path.exists(path))
    j = RunJournal(path or None, resume=resume)
    if resume:
        j.event("run_resume", version=_v, path=path)
    else:
        j.event("run_start", version=_v, workload=workload,
                engine=engine, device=device, params=params)
    args._journal = j
    args._journal_path = path or ""
    if args.serve:
        # live ops plane: /metrics + /events (SSE) + /runs over this
        # run's journal directory for the run's whole lifetime
        from .obs.serve import start_server

        args._server = start_server(
            os.path.dirname(os.path.abspath(path)) or ".",
            port=args.serve,
        )
        print(f"jaxtlc monitor at {args._server.url} "
              "(/runs /metrics /events /journal)", file=_err(args))
    return j


def _finish_journal(args, log, r=None, sup=None, verdict: str = None,
                    wall_s: float = 0.0) -> None:
    """Close out the journal: the final event (when the supervisor did
    not already emit one), the violation record, and the -trace-out
    export (reading the WHOLE journal file so a resumed run's trace
    covers both attempts)."""
    j = getattr(args, "_journal", None)
    if j is None:
        return
    try:
        if r is not None and r.violation != 0:
            j.event("violation", code=int(r.violation),
                    name=r.violation_name)
        if verdict == "liveness_violation":
            j.event("violation", code=13,
                    name="Temporal properties were violated")
        if sup is None and r is not None:
            v = verdict or ("violation" if r.violation != 0 else "ok")
            j.event("final", verdict=v, generated=r.generated,
                    distinct=r.distinct, depth=r.depth,
                    queue=r.queue_left, wall_s=round(wall_s, 6),
                    interrupted=False)
        if args.traceout:
            from .obs.journal import read as read_journal
            from .obs.trace import export_chrome_trace

            events = read_journal(j.path, validate=False) if j.path \
                else j.events
            n = export_chrome_trace(events, args.traceout)
            j.event("trace_export", path=args.traceout, events=n)
            log.msg(1000, f"Timeline trace written to {args.traceout} "
                          f"({n} events; open in ui.perfetto.dev).")
    finally:
        j.close()
        args._journal = None
        server = getattr(args, "_server", None)
        if server is not None:
            server.shutdown()
            args._server = None


def _resume_command(args) -> str:
    """The command an interrupted run prints (geometry travels inside the
    checkpoint meta, so only the run-shaping flags need repeating)."""
    parts = ["python -m jaxtlc.cli check", args.config]
    if args.checkpoint:
        parts += ["-checkpoint", args.checkpoint, "-recover"]
    if args.chunk != 1024:
        parts += ["-chunk", str(args.chunk)]
    if args.sharded:
        parts += ["-sharded", str(args.sharded)]
    if args.pipeline:
        parts += ["-pipeline"]  # checkpoints only resume in the same mode
    if getattr(args, "sortfree", None) is not None:
        # auto re-resolves identically from the chunk; only an explicit
        # override must travel so the meta mode check stays satisfied
        parts += ["-sort-free" if args.sortfree else "-no-sort-free"]
    if getattr(args, "deferredinv", None) is not None:
        # same contract as -sort-free: auto re-resolves from the chunk
        parts += ["-deferred-inv" if args.deferredinv
                  else "-no-deferred-inv"]
    if getattr(args, "symmetry", None) is not None:
        # same contract: a reduced frontier is a different exploration,
        # the resume must repeat the mode or the meta check rejects it
        parts += ["-symmetry" if args.symmetry else "-no-symmetry"]
    if getattr(args, "por", None) is not None:
        parts += ["-por" if args.por else "-no-por"]
    if getattr(args, "narrow", False):
        parts += ["-narrow"]  # the narrowed codec is a different layout
    if getattr(args, "coverage", False):
        parts += ["-coverage"]  # the covered carry is a different layout
    if getattr(args, "simulate", False):
        # a walk is a pure function of (seed, walkers, depth): the
        # resume must repeat all three or the cursor meta mismatches
        parts += ["-simulate", "-depth", str(args.depth),
                  "-walkers", str(args.walkers),
                  "-sim-seed", str(args.simseed)]
    if args.frontend != "auto":
        parts += ["-frontend", args.frontend]
    if not args.checkpoint:
        return ("re-run from scratch (no -checkpoint was set): "
                + " ".join(parts))
    return " ".join(parts)


def _render_sources(cfg_path: str, spec_name: str) -> dict:
    """Rendering inputs derived from the model directory (M4): the
    action-line table scanned from the spec's committed translation, and
    the Toolbox .pmap (generated-TLA -> PlusCal source map) when present."""
    out = {}
    model_dir = os.path.dirname(os.path.abspath(cfg_path))
    tla = os.path.join(model_dir, f"{spec_name}.tla")
    if os.path.exists(tla):
        from .io.tlc_log import action_lines_from_spec

        out["action_lines"] = action_lines_from_spec(tla)
    pmap_path = os.path.join(
        os.path.dirname(model_dir), f"{spec_name}.tla.pmap"
    )
    if os.path.exists(pmap_path):
        from .frontend.pmap import PmapError, parse_pmap_file

        try:
            out["pcal_map"] = parse_pmap_file(pmap_path)
        except PmapError:
            pass  # a corrupt pmap must not break the run (Toolbox parity)
    return out


def _sany_inputs(cfg_path: str, spec_name: str):
    """Files actually read + modules resolved, for the SANY log section."""
    model_dir = os.path.dirname(os.path.abspath(cfg_path))
    files, modules = [], []
    # TLC's order (MC.out:8-24): the root MC.tla parses first, semantic
    # processing finishes with the root module last
    mc = os.path.join(model_dir, "MC.tla")
    if os.path.exists(mc):
        files.append(mc)
    sp = os.path.join(model_dir, f"{spec_name}.tla")
    if os.path.exists(sp):
        files.append(sp)
        modules.append(spec_name)
    if os.path.exists(mc):
        modules.append("MC")
    return files, modules


def _run_check_gen(args, spec) -> int:
    """Check a generic-frontend spec (E1): device engine + host liveness.

    -sharded runs the gen lane kernel through the mesh engine (the same
    fp-space partition + all_to_all routing as the KubeAPI path);
    -checkpoint/-recover snapshot the whole sharded carry (a 1-device
    mesh when -sharded is not given), mirroring TLC applying its
    distribution/checkpoint machinery to any spec."""
    from .gen import oracle as go
    from .gen.engine import check_gen

    g = spec.genspec

    def props():
        for name, (p_ast, q_ast) in g.properties.items():
            yield name, p_ast, q_ast, None

    def check():
        if not (args.sharded or args.checkpoint):
            return check_gen(
                g,
                chunk=args.chunk,
                queue_capacity=args.qcap,
                fp_capacity=args.fpcap,
                fp_index=spec.fp_index,
                check_deadlock=spec.check_deadlock,
            )
        import jax
        import numpy as np
        from jax.sharding import Mesh

        from .engine.sharded import (
            check_sharded,
            check_sharded_with_checkpoints,
            gen_backend,
        )

        n_dev = args.sharded or 1
        mesh = Mesh(np.array(jax.devices()[:n_dev]), ("fp",))
        backend = gen_backend(g)
        kw = dict(
            chunk=args.chunk,
            queue_capacity=args.qcap,
            fp_capacity=args.fpcap,
            route_factor=args.routefactor,
            backend=backend,
            pipeline=args.pipeline,
            obs_slots=_obs_slots(args),
            sort_free=args.sortfree,
            deferred=args.deferredinv,
        )
        if args.checkpoint:
            meta_config = {
                "spec": spec.spec_name,
                "constants": {
                    k: sorted(v) if isinstance(v, frozenset) else v
                    for k, v in g.constants.items()
                },
            }
            return check_sharded_with_checkpoints(
                None, mesh, ckpt_path=args.checkpoint,
                ckpt_every=args.checkpointevery, resume=args.recover,
                meta_config=meta_config, **kw,
            )
        return check_sharded(None, mesh, **kw)

    def leads_to(name, p, q, distinct=0):
        from .live.check import check_leads_to_device, use_device_path

        if use_device_path(distinct, args.fairness, args.liveness_host):
            mesh = None
            if args.sharded:
                import jax
                import numpy as np
                from jax.sharding import Mesh

                mesh = Mesh(np.array(jax.devices()[: args.sharded]),
                            ("fp",))
            return check_leads_to_device(
                g, p, q, name, chunk=args.chunk,
                state_capacity=args.fpcap, fp_capacity=args.fpcap,
                mesh=mesh, spill_path=args.checkpoint or None,
            )
        return go.check_leads_to(g, p, q, name, fairness=args.fairness)

    kit = _InterpKit(
        kind="generic",
        extra_unsupported=(
            ("-nodeadlock with -sharded/-checkpoint",
             (args.sharded or args.checkpoint)
             and not spec.check_deadlock),
        ),
        check=lambda: (check(), None),
        init_count=lambda: 1,
        properties=props,
        check_leads_to=leads_to,
        fairness_label=args.fairness,
        state_to_tla=lambda st: go.state_to_tla(g, st),
        state_env=lambda st: go.state_env(g, st),
        violation_trace=lambda: go.violation_trace(
            g, check_deadlock=spec.check_deadlock
        ),
        coverage=lambda: _gen_coverage_lines(spec, g),
        preflight=lambda deep: _gen_preflight(args, g, deep),
    )
    return _run_check_interp(args, spec, kit)


def _gen_preflight(args, g, deep):
    from .analysis.preflight import preflight_gen

    return preflight_gen(g, fp_capacity=args.fpcap, deep=deep)


def _gen_coverage_lines(spec, g):
    from .gen.coverage import coverage_walk, render_coverage

    text = ""
    if spec.tla_path:
        try:
            with open(spec.tla_path) as f:
                text = f.read()
        except OSError:
            pass
    init_count, cov = coverage_walk(g, text)
    return render_coverage(
        spec.spec_name, init_count, cov,
        time.strftime("%Y-%m-%d %H:%M:%S"),
    )


def _run_check_struct(args, spec) -> int:
    """Check a structural-frontend spec (E1): the full-module path that
    runs specs outside the gen subset - the reference's own KubeAPI.tla
    included.  The LaneCompiler step is a first-class engine kernel now:
    struct runs ride the production engines - segmented + supervised by
    default (auto-regrow, checkpoints, SIGTERM drain), mesh-sharded
    with -sharded - with the persistent step-compile cache warm-starting
    repeated runs.  Host graph for liveness, host re-run for traces;
    same log protocol and exit conventions."""
    from .struct import oracle as so
    from .struct.backend import struct_meta_config
    from .struct.cache import get_backend
    from .struct.engine import check_struct, check_struct_sharded

    sm = spec.structmodel
    system = sm.system
    if args.recover and not args.checkpoint:
        print("Error: -recover requires -checkpoint PATH", file=_err(args))
        return 1
    if getattr(args, "simulate", False):
        # the simulation tier (jaxtlc.sim, ISSUE 14): random-walk
        # smoke checking instead of exhaustive BFS
        return _run_sim_struct(args, spec)
    if getattr(args, "infer", False):
        # invariant inference (jaxtlc.infer, ISSUE 16): conjecture ->
        # filter -> certify instead of checking
        return _run_infer_struct(args, spec)
    log_holder = []

    # -narrow: the certified-bound narrowed codec (analysis.absint).
    # Only a CERTIFIED report narrows; an uncertified one keeps the
    # baseline layout and says so up front (the run stays correct
    # either way - runtime traps / the certificate column enforce it)
    bounds = None
    if args.narrow:
        from .struct.cache import get_bounds

        bounds = get_bounds(sm)
        if not bounds.certified:
            bounds = None

    # incremental re-checking (ISSUE 13): the artifact plan decides,
    # BEFORE any engine build, whether this check can be answered from
    # the verdict tier (unchanged spec -> cached CheckOutcome) or the
    # reachable-set tier (invariant-only edit -> BFS-free vmapped
    # invariant pass).  Resume/fault/mutation/coverage/profiling runs
    # opt out - they exist to exercise the engines themselves.
    art_plan = _artifact_plan(args, spec, sm, bounds)
    capture = art_plan is not None and not args.sharded

    def check():
        log = log_holder[0]
        ckd = spec.check_deadlock
        cov = args.coverage
        sym, por = _symmetry(args), _por(args)
        kw = dict(chunk=args.chunk, queue_capacity=args.qcap,
                  fp_capacity=args.fpcap)
        if args.sharded:
            import numpy as np
            import jax
            from jax.sharding import Mesh

            mesh = Mesh(np.array(jax.devices()[: args.sharded]), ("fp",))
            if args.checkpoint or args.autogrow:
                from .resil import check_sharded_supervised

                sup = check_sharded_supervised(
                    None, mesh,
                    backend=get_backend(sm, ckd, bounds=bounds,
                                        elide=False, coverage=cov,
                                        symmetry=sym, por=por),
                    meta_config=struct_meta_config(sm, bounds=bounds),
                    route_factor=args.routefactor,
                    pipeline=args.pipeline,
                    obs_slots=_obs_slots(args),
                    sort_free=args.sortfree,
                    deferred=args.deferredinv,
                    opts=_sup_opts(args, log), **kw,
                )
                return sup.result, sup
            return check_struct_sharded(
                sm, mesh, route_factor=args.routefactor,
                check_deadlock=ckd, pipeline=args.pipeline,
                obs_slots=_obs_slots(args), bounds=bounds,
                coverage=cov, sort_free=args.sortfree,
                deferred=args.deferredinv, symmetry=args.symmetry,
                por=args.por, **kw,
            ), None
        if args.checkpoint or args.autogrow:
            from .resil import check_supervised

            sup = check_supervised(
                None, fp_index=spec.fp_index,
                backend=get_backend(sm, ckd, bounds=bounds,
                                    coverage=cov, symmetry=sym,
                                    por=por),
                meta_config=struct_meta_config(sm, bounds=bounds),
                check_deadlock=ckd,
                pipeline=args.pipeline,
                obs_slots=_obs_slots(args),
                sort_free=args.sortfree,
                deferred=args.deferredinv,
                opts=_sup_opts(args, log, capture_fps=capture), **kw,
            )
            return sup.result, sup
        return check_struct(
            sm, fp_index=spec.fp_index, check_deadlock=ckd,
            pipeline=args.pipeline, obs_slots=_obs_slots(args),
            bounds=bounds, coverage=cov, sort_free=args.sortfree,
            deferred=args.deferredinv, symmetry=args.symmetry,
            por=args.por, capture_fps=capture, **kw,
        ), None

    def props():
        for name in spec.properties:
            ast = sm.properties[name]
            if ast[0] != "leadsto" or ast[1][0] == "box":
                yield name, None, None, (
                    "only plain P ~> Q is checked on the structural path"
                )
                continue
            yield name, ast[1], ast[2], None

    def action_order():
        # MC.out prints actions in module-definition order; lane labels
        # ARE definition names, so def_order is the rendering order
        names = set(get_backend(sm, spec.check_deadlock).labels)
        ordered = [n for n in sm.module.def_order if n in names]
        return ordered + [n for n in sorted(names) if n not in ordered]

    def coverage_device(r, n_init):
        # the device coverage plane's end-of-run dump (MC.out format):
        # counts straight off the carry - no host re-walk
        if getattr(r, "site_coverage", None) is None:
            return None
        from .obs.coverage import render_site_dump

        plane = get_backend(sm, spec.check_deadlock, bounds=bounds,
                            coverage=True).coverage
        counts = [r.site_coverage.get(s.key, 0) for s in plane.sites]
        return render_site_dump(
            plane.sites, counts, plane.module or spec.spec_name,
            time.strftime("%Y-%m-%d %H:%M:%S"), init_count=n_init,
            act_gen=r.action_generated, act_dist=r.action_distinct,
            order=action_order(),  # module-definition (MC.out) order
        )

    def dead_site_lint(r):
        # zero-visit sites cross-checked against the static
        # unreachable-action lint: a statically-REACHABLE site that
        # never fired is the dynamic counterpart of the PR 6 lint
        return _struct_dead_sites(args, spec, sm, bounds, r)

    def reduce_info():
        # the journal `reduce` event's static half (ISSUE 18): what
        # the reduction machinery resolved for this run (the backend
        # memo makes this a cache hit, not a recompile)
        sym, por = _symmetry(args), _por(args)
        if not (sym or por):
            return None
        red = get_backend(
            sm, spec.check_deadlock, bounds=bounds,
            elide=not args.sharded, coverage=args.coverage,
            symmetry=sym, por=por,
        ).reduce
        if red is None:
            return None
        return dict(
            symmetry=sym, por=por,
            orbit_factor=red.orbit_factor,
            symmetric_sets={k: list(v) for k, v in red.sym_sets},
            dropped_sets=dict(red.dropped_sets),
            safe_actions=len(red.safe_ids),
        )

    kit = _InterpKit(
        kind="structural",
        # the structural liveness graph is wf_next-only so far
        extra_unsupported=(
            ("-fairness wf_process", args.fairness == "wf_process"),
        ),
        check=check,
        # lazy: Init enumeration is real work on struct specs and must
        # not run when the flags are about to be rejected
        init_count=lambda: len(system.initial_states()),
        properties=props,
        check_leads_to=lambda name, p, q, **_kw: so.check_leads_to(
            system, p, q, name
        ),
        fairness_label="wf_next",
        state_to_tla=lambda st: so.state_to_tla(system, st),
        state_env=lambda st: so.state_env(system, st),
        violation_trace=lambda: so.violation_trace(
            system, sm.invariants, check_deadlock=spec.check_deadlock
        ),
        action_order=action_order,
        preflight=lambda deep: _struct_preflight(args, spec, sm, deep),
        coverage_device=coverage_device,
        dead_site_lint=dead_site_lint,
        artifact_plan=art_plan,
        reduce_info=reduce_info,
    )
    return _run_check_interp(args, spec, kit, log_holder=log_holder)


def _run_sim_struct(args, spec) -> int:
    """The simulation tier (jaxtlc.sim, ISSUE 14): W vmapped random
    walks of depth N through the struct backend's own kernels, with
    seed-exact host replay for violations.

    The transcript discipline mirrors the exhaustive struct path - the
    same banner/journal/preflight plumbing, the same violation message
    and 2217 trace rendering - but the success message says SMOKE, not
    "model checking completed": a clean walk proves nothing about
    unsampled behaviors, which is also why this path journals an
    artifact-cache BYPASS instead of writing a verdict artifact."""
    from .resil import EXIT_INTERRUPTED, FaultPlan
    from .sim.driver import run_sim_supervised
    from .sim.replay import replay_lane, walk_trace
    from .struct import artifacts as _arts
    from .struct import oracle as so
    from .struct.cache import get_backend

    sm = spec.structmodel
    unsupported = [
        flag for flag, on in (
            ("-sharded", args.sharded),
            ("-pipeline", args.pipeline),
            ("-liveness", args.liveness),
            ("-coverage", args.coverage),
            ("-narrow", args.narrow),
            ("-phase-timing", args.phasetiming),
            ("-mutation", args.mutation),
            ("-symmetry", getattr(args, "symmetry", None)),
            ("-por", getattr(args, "por", None)),
            ("-fpset DiskFPSet", args.fpset != "JaxFPSet"),
        ) if on
    ]
    if unsupported:
        print(
            f"Error: {', '.join(unsupported)} not supported with "
            "-simulate (walks carry no frontier/liveness machinery)",
            file=_err(args),
        )
        return 1
    log = TLCLog(out=args.out, tool_mode=not args.noTool)
    import jax

    device = str(jax.devices()[0])
    log.version(__version__)
    log.banner(spec.fp_index, DEFAULT_SEED, spec.workers, device)
    log.sany(*_sany_inputs(args.config, spec.spec_name))
    log.starting()
    log.computing_init()
    _open_journal(
        args, workload=spec.spec_name, engine="sim", device=device,
        params=dict(walkers=args.walkers, depth=args.depth,
                    sim_seed=args.simseed, fp_capacity=args.fpcap,
                    frontend="struct"),
    )
    j = getattr(args, "_journal", None)
    # artifact-cache honesty (ISSUE 14 satellite): when a store is
    # configured, this run journals an explicit BYPASS - a poisoned
    # verdict tier would silently answer later EXHAUSTIVE queries with
    # an incomplete-search verdict
    if _arts.store_for(args) is not None and j is not None:
        j.event("cache", tier="verdict", outcome="bypass", key="",
                reason="simulation verdicts are from incomplete "
                       "search and never publish")
    rc = _preflight_gate(
        args, log, lambda deep: _struct_preflight(args, spec, sm, deep)
    )
    if rc is not None:
        return rc
    log.msg(1000, f"Running random simulation: {args.walkers} walks "
                  f"to depth {args.depth} (seed {args.simseed}).")
    from .sim.liveness import expressible as _live_expressible

    live_props = []
    for name in spec.properties:
        # cfg-declared temporal properties: plain P ~> Q is checked on
        # the sampled behaviors after the walk (lasso detection, TLC's
        # -simulate analog); shapes the trace checker cannot express
        # keep the skip notice
        skip = _live_expressible(sm.properties[name])
        if skip is not None:
            log.msg(1000, f"Temporal property {name} skipped: {skip}.",
                    severity=1)
        else:
            live_props.append(name)
    t0 = time.time()
    resume_cmd = _resume_command(args)

    def on_event(kind, info):
        if j is not None:
            ev = j.event(kind, **info)
        else:
            from .obs.schema import SCHEMA_VERSION

            ev = {"v": SCHEMA_VERSION, "t": time.time(),
                  "event": kind, **info}
        from .obs.views import render_tlc_event

        render_tlc_event(log, ev, resume_cmd=resume_cmd)

    try:
        sup = run_sim_supervised(
            sm, seed=args.simseed, walkers=args.walkers,
            depth=args.depth, fp_capacity=args.fpcap,
            check_deadlock=spec.check_deadlock,
            ckpt_path=args.checkpoint or None,
            ckpt_every=args.checkpointevery, resume=args.recover,
            faults=(FaultPlan.parse(args.faults) if args.faults
                    else None),
            on_event=on_event,
            drain=getattr(args, "drain", None),
        )
    except (FileNotFoundError, ValueError) as e:
        print(f"Error: {e}", file=_err(args))
        _finish_journal(args, log)
        return 1
    r = sup.result
    args._result = r
    log.init_done(len(sm.system.initial_states()))
    if j is not None:
        j.event("sim", phase="summary", walkers=r.walkers,
                depth=r.depth, steps=r.steps,
                transitions=r.transitions, seed=r.seed,
                distinct_est=r.distinct,
                fp_saturated=r.fp_saturated, halted=r.halted,
                depth_hist=[list(p) for p in r.depth_hist],
                violation=r.violation)
    if sup.interrupted:
        if j is not None:
            j.event("final", verdict="interrupted",
                    generated=r.generated, distinct=r.distinct,
                    depth=r.steps, queue=0,
                    wall_s=round(time.time() - t0, 6),
                    interrupted=True)
        _finish_journal(args, log)
        return EXIT_INTERRUPTED
    violated = r.violation != 0
    if violated:
        log.msg(2110 if r.violation >= 100 else 1000,
                r.violation_name, severity=1)
        # seed-exact replay: the lane's walk IS the counterexample -
        # re-derived host-side from (seed, lane) alone, decoded through
        # the struct codec, rendered through the same 2217 path the
        # BFS trace uses (byte-for-byte transcripts on a forced path)
        backend = get_backend(sm, spec.check_deadlock)
        walk = replay_lane(
            backend, r.seed, r.violation_lane,
            max(r.violation_step, 0),
            check_deadlock=spec.check_deadlock,
        )
        if j is not None:
            j.event("sim", phase="replay", walkers=r.walkers,
                    depth=r.depth, steps=len(walk.fields) - 1,
                    transitions=len(walk.fields) - 1,
                    lane=r.violation_lane, seed=r.seed,
                    violation=walk.violation)
        if walk.violation != r.violation:
            log.msg(1000, "Violation was not reproducible in host "
                          "replay", severity=1)
        else:
            for i, (st, act) in enumerate(
                    walk_trace(walk, backend.cdc), start=1):
                head = (f"State {i}: <Initial predicate>" if act is None
                        else f"State {i}: <{act}>")
                log.msg(2217,
                        head + "\n" + so.state_to_tla(sm.system, st),
                        severity=1)
    else:
        sat = " (sampling filter saturated: estimate is a floor)" \
            if r.fp_saturated else ""
        log.msg(1000, f"Simulation complete: {r.walkers} walks, "
                      f"{r.transitions} transitions taken to depth "
                      f"{r.steps}, ~{r.distinct} distinct states "
                      f"sampled{sat}.")
        log.msg(1000, "No violation found in the sampled behaviors "
                      "(simulation is NOT exhaustive - this is a "
                      "smoke verdict).")
    liveness_violated = False
    if not violated and live_props:
        # liveness on the sampled traces (ISSUE 16 satellite): lasso
        # detection over the walk trajectories, re-derived from the
        # seed (a lane is a pure function of (seed, lane) - the same
        # replay guarantee the safety trace uses)
        from .sim.liveness import check_walk_leads_to, walk_trajectories

        trajs = walk_trajectories(
            sm, args.walkers, args.depth, args.simseed,
            check_deadlock=spec.check_deadlock,
        )
        for name in live_props:
            ast = sm.properties[name]
            res = check_walk_leads_to(sm, ast[1], ast[2], name, trajs)
            if j is not None:
                j.event("sim", phase="liveness", walkers=args.walkers,
                        depth=args.depth, steps=r.steps,
                        transitions=r.transitions, property=name,
                        lassos=res.lassos, holds=res.holds)
            if res.holds:
                log.msg(1000, f"Temporal property {name}: no "
                              f"violating lasso in the sampled "
                              f"behaviors ({res.lassos} lasso(s) "
                              f"examined; sampling is NOT "
                              f"exhaustive).")
                continue
            liveness_violated = True
            log.msg(2116, f"Temporal properties were violated: {name}",
                    severity=1)
            idx = 1
            for st in res.prefix:
                log.trace_state(idx, None,
                                so.state_to_tla(sm.system, st))
                idx += 1
            log.msg(1000, "-- The following states form a cycle "
                          "(back to the first of them) --")
            for st in res.cycle:
                log.trace_state(idx, None,
                                so.state_to_tla(sm.system, st))
                idx += 1
    log.progress(r.steps, r.generated, r.distinct, 0)
    log.final_counts(r.generated, r.distinct, 0)
    log.finished(int((time.time() - t0) * 1000))
    if j is not None:
        if violated:
            j.event("violation", code=int(r.violation),
                    name=r.violation_name)
        elif liveness_violated:
            j.event("violation", code=13,
                    name="Temporal properties were violated")
        j.event("final",
                verdict=("violation" if violated else
                         "liveness_violation" if liveness_violated
                         else "ok"),
                generated=r.generated, distinct=r.distinct,
                depth=r.steps, queue=0,
                wall_s=round(time.time() - t0, 6), interrupted=False)
    _finish_journal(args, log)
    if violated:
        return 12
    return 13 if liveness_violated else 0


def _run_infer_struct(args, spec) -> int:
    """The inference job class (jaxtlc.infer, ISSUE 16): conjecture
    candidate invariants over the struct IR, kill the ones reachable
    evidence refutes in vmapped [P, S] filter dispatches, certify the
    survivors inductive - the same banner/journal/preflight plumbing
    as a check, but the product is a transcript of CERTIFIED candidate
    invariants (and an honest "consistent with evidence only" list),
    not a pass/fail verdict about the spec.  The run exits 12 only
    when EXACT evidence kills a cfg-named invariant - a real reachable
    violation - and never publishes to the artifact-cache verdict
    tier."""
    from .infer.driver import run_infer
    from .struct import artifacts as _arts

    sm = spec.structmodel
    unsupported = [
        flag for flag, on in (
            ("-sharded", args.sharded),
            ("-pipeline", args.pipeline),
            ("-liveness", args.liveness),
            ("-coverage", args.coverage),
            ("-narrow", args.narrow),
            ("-phase-timing", args.phasetiming),
            ("-mutation", args.mutation),
            ("-checkpoint", args.checkpoint),
            ("-recover", args.recover),
            ("-faults", args.faults),
            ("-symmetry", getattr(args, "symmetry", None)),
            ("-por", getattr(args, "por", None)),
            ("-fpset DiskFPSet", args.fpset != "JaxFPSet"),
        ) if on
    ]
    if unsupported:
        print(
            f"Error: {', '.join(unsupported)} not supported with "
            "-infer (inference carries no frontier/checkpoint "
            "machinery)",
            file=_err(args),
        )
        return 1
    log = TLCLog(out=args.out, tool_mode=not args.noTool)
    import jax

    device = str(jax.devices()[0])
    log.version(__version__)
    log.banner(spec.fp_index, DEFAULT_SEED, spec.workers, device)
    log.sany(*_sany_inputs(args.config, spec.spec_name))
    log.starting()
    log.computing_init()
    _open_journal(
        args, workload=spec.spec_name, engine="infer", device=device,
        params=dict(budget=args.inferbudget, walkers=args.walkers,
                    depth=args.depth, sim_seed=args.simseed,
                    frontend="struct"),
    )
    j = getattr(args, "_journal", None)
    # artifact-cache honesty: inference READS the reachable-set tier
    # as filter evidence but its verdict is about candidates, not the
    # stated invariants - it never publishes to the verdict tier
    if _arts.store_for(args) is not None and j is not None:
        j.event("cache", tier="verdict", outcome="bypass", key="",
                reason="inference verdicts are about candidate "
                       "invariants and never publish")
    rc = _preflight_gate(
        args, log, lambda deep: _struct_preflight(args, spec, sm, deep)
    )
    if rc is not None:
        return rc
    log.msg(1000, f"Running invariant inference: budget "
                  f"{args.inferbudget} candidates "
                  f"(walk geometry {args.walkers}x{args.depth}, "
                  f"seed {args.simseed}).")
    t0 = time.time()
    resume_cmd = _resume_command(args)

    def on_event(kind, info):
        if j is not None:
            ev = j.event(kind, **info)
        else:
            from .obs.schema import SCHEMA_VERSION

            ev = {"v": SCHEMA_VERSION, "t": time.time(),
                  "event": kind, **info}
        from .obs.views import render_tlc_event

        render_tlc_event(log, ev, resume_cmd=resume_cmd)

    running = {"killed": 0}

    def on_round(row):
        running["killed"] += row["killed"]
        on_event("infer", dict(
            phase="round",
            candidates=row["survivors"] + running["killed"],
            killed=running["killed"], survivors=row["survivors"],
            certified=0, round=row["round"],
            evidence=row["evidence"], n_states=row["n_states"],
        ))

    try:
        rep = run_infer(
            sm, budget=args.inferbudget, walkers=args.walkers,
            depth=args.depth, seed=args.simseed,
            check_deadlock=spec.check_deadlock, on_round=on_round,
        )
    except (FileNotFoundError, ValueError) as e:
        print(f"Error: {e}", file=_err(args))
        _finish_journal(args, log)
        return 1
    args._result = rep
    log.init_done(len(sm.system.initial_states()))
    on_event("infer", dict(
        phase="summary", candidates=rep.candidates, killed=rep.killed,
        survivors=len(rep.survivors), certified=len(rep.certified),
        certified_names=[c.name for c in rep.certified],
        evidence=rep.evidence, n_states=rep.n_states,
        dropped=rep.dropped,
    ))
    violated = bool(rep.cfg_killed)
    if violated:
        for name in rep.cfg_killed:
            log.msg(2110, f"Invariant {name} is violated (refuted by "
                          f"a reachable state in the exact evidence "
                          f"set).", severity=1)
    evid = (f"exact {rep.evidence} evidence ({rep.n_states} states)"
            if rep.exact else
            f"sampled walk evidence ({rep.n_states} states - "
            f"NOT exhaustive)")
    log.msg(1000, f"Inference complete: {rep.candidates} candidates "
                  f"({rep.dropped} beyond budget), {rep.killed} killed "
                  f"by {evid}.")
    for c, basis in zip(rep.certified, rep.cert_basis):
        line = c.name if c.source == "cfg" else f"{c.name} == {c.text}"
        log.msg(1000, f"Certified inductive invariant [{basis}]: "
                      f"{line}")
    uncert = [c for c in rep.survivors if c not in rep.certified]
    for c in uncert:
        line = c.name if c.source == "cfg" else f"{c.name} == {c.text}"
        log.msg(1000, f"Consistent with evidence only (NOT certified): "
                      f"{line}", severity=1)
    for name in rep.uncompiled:
        log.msg(1000, f"Candidate {name} skipped: outside the lane-"
                      f"compilable subset.", severity=1)
    log.progress(0, rep.n_states, rep.n_states, 0)
    log.final_counts(rep.n_states, rep.n_states, 0)
    log.finished(int((time.time() - t0) * 1000))
    if j is not None:
        if violated:
            j.event("violation", code=100,
                    name=f"Invariant {rep.cfg_killed[0]} is violated.")
        j.event("final",
                verdict="violation" if violated else "ok",
                generated=rep.n_states, distinct=rep.n_states,
                depth=0, queue=0,
                wall_s=round(time.time() - t0, 6), interrupted=False)
    _finish_journal(args, log)
    return 12 if violated else 0


def _artifact_plan(args, spec, sm, bounds):
    """The incremental-re-checking plan for a struct run (ISSUE 13), or
    None when the run is ineligible: resume/fault/mutation runs exist
    to exercise the engines, coverage/phase-timing/xprof runs produce
    run-shaped artifacts a cached verdict cannot, and -no-artifact-cache
    (or JAXTLC_ARTIFACT_CACHE=off) disables the store outright."""
    if (args.recover or args.faults or args.mutation or args.coverage
            or args.phasetiming or args.xprof
            or getattr(args, "simulate", False)
            or getattr(args, "infer", False)):
        # simulate/infer are unreachable here (both paths branch off
        # before plans are built) but stay on the list as defense in
        # depth: a simulation verdict is from INCOMPLETE search, an
        # inference verdict is about CANDIDATES - neither may publish
        # to the verdict tier
        return None
    if _symmetry(args) or _por(args):
        # a reduced run's fp table is the REDUCED reachable set: its
        # verdict is sound but its reachable-set tier would silently
        # under-cover an invariant-only re-check whose NEW invariant
        # the symmetry verifier never saw - reduced runs neither read
        # nor publish artifacts
        return None
    from .struct import artifacts as _arts

    store = _arts.store_for(args)
    if store is None:
        return None
    return _arts.ArtifactPlan(
        store, sm,
        check_deadlock=spec.check_deadlock,
        properties=tuple(spec.properties),
        fp_capacity=args.fpcap,
        bounds=bounds,
        fp_index=spec.fp_index,
        bypass_read=bool(args.recheck),
    )


def _struct_dead_sites(args, spec, sm, bounds, r):
    """The dead-site lint closure (ISSUE 11 satellite): at final
    verdict, sites with zero visits are cross-checked against
    speclint's unreachable-action findings - a statically-REACHABLE
    site that never fired becomes a warning-severity `analysis`
    journal event (the end-of-run dynamic counterpart of the PR 6
    static lint).  Returns the (layer, check, severity, subject,
    detail) event dicts; the interp runner journals + renders them."""
    if getattr(r, "site_coverage", None) is None:
        return []
    from .analysis.speclint import analyze_spec
    from .obs.coverage import zero_sites
    from .struct.cache import get_backend

    plane = get_backend(sm, spec.check_deadlock, bounds=bounds,
                        coverage=True).coverage
    counts = [r.site_coverage.get(s.key, 0) for s in plane.sites]
    dead = zero_sites(plane.sites, counts)
    if not dead:
        return []
    try:
        static_dead = {
            f.subject for f in analyze_spec(sm).findings
            if f.check == "unreachable-action"
        }
    except Exception:  # a broken lint must never block the verdict
        static_dead = set()
    events = []
    reachable_dead = [s for s in dead if s.action not in static_dead]
    for s in reachable_dead[:20]:
        what = s.loc or s.kind
        events.append(dict(
            layer="spec", check="dead-site", severity="warning",
            subject=s.key,
            detail=(f"site never fired in this run ({s.action}: {what})"
                    " although the action is statically reachable; the"
                    " configuration may be too small to exercise it"),
        ))
    if len(reachable_dead) > 20:
        events.append(dict(
            layer="spec", check="dead-site", severity="warning",
            subject=sm.root_name,
            detail=(f"{len(reachable_dead) - 20} further zero-visit "
                    "sites suppressed (see /coverage for the full "
                    "table)"),
        ))
    return events


def _struct_preflight(args, spec, sm, deep):
    from .analysis.preflight import preflight_struct

    backend = None
    if deep:
        # the same memoized backend the run is about to use: the deep
        # audit adds a jaxpr trace, never a second lane compile
        from .struct.cache import get_backend

        backend = get_backend(sm, spec.check_deadlock)
    # the certified bound report rides along in deep mode (-analyze)
    # and whenever -narrow is in play (the user should see what the
    # narrowed codec is built from / why narrowing was refused)
    bounds = None
    if deep or args.narrow:
        from .struct.cache import get_bounds

        bounds = get_bounds(sm)
    return preflight_struct(
        sm, fp_capacity=args.fpcap, chunk=args.chunk,
        queue_capacity=args.qcap, check_deadlock=spec.check_deadlock,
        deep=deep, backend=backend, bounds=bounds,
        narrow=args.narrow, symmetry=_symmetry(args),
    )


class _InterpKit:
    """Everything the shared interpreted-spec runner needs from a
    frontend: one object so the gen/struct runners cannot drift."""

    def __init__(self, kind, extra_unsupported, check, init_count,
                 properties, check_leads_to, fairness_label,
                 state_to_tla, state_env, violation_trace,
                 coverage=None, action_order=None, preflight=None,
                 coverage_device=None, dead_site_lint=None,
                 artifact_plan=None, reduce_info=None):
        self.kind = kind
        self.extra_unsupported = extra_unsupported
        self.check = check  # () -> (CheckResult, SupervisedResult | None)
        self.init_count = init_count
        self.properties = properties
        self.check_leads_to = check_leads_to
        self.fairness_label = fairness_label
        self.state_to_tla = state_to_tla
        self.state_env = state_env
        self.violation_trace = violation_trace
        self.coverage = coverage  # () -> dump lines, or None
        self.action_order = action_order  # () -> coverage line order
        self.preflight = preflight  # (deep) -> AnalysisReport, or None
        # (r, n_init) -> device site-dump lines | None (obs.coverage)
        self.coverage_device = coverage_device
        # (r) -> analysis-event dicts for zero-visit reachable sites
        self.dead_site_lint = dead_site_lint
        # struct.artifacts.ArtifactPlan | None: the incremental
        # re-checking seam (verdict/reach lookup before any engine
        # build, clean-verdict artifact write after)
        self.artifact_plan = artifact_plan
        # () -> dict | None: state-space reduction facts for the
        # journal `reduce` event (struct frontend, ISSUE 18)
        self.reduce_info = reduce_info


def _run_check_interp(args, spec, kit: "_InterpKit",
                      log_holder: list = None) -> int:
    """Shared runner for the interpreted frontends (gen + struct): the
    KubeAPI-engine knobs are rejected, the device engine checks safety,
    the host graph checks liveness, and violations re-run on the host
    interpreter for the trace.  TLC log protocol + exit conventions."""
    unsupported = [
        flag for flag, on in (
            ("-fpset DiskFPSet", args.fpset != "JaxFPSet"),
            ("-mutation", args.mutation),
            *kit.extra_unsupported,
        ) if on
    ]
    if unsupported:
        print(
            f"Error: {', '.join(unsupported)} not supported for "
            f"{kit.kind}-frontend specs yet",
            file=_err(args),
        )
        return 1
    log = TLCLog(out=args.out, tool_mode=not args.noTool)
    if log_holder is not None:
        log_holder.append(log)
    import jax

    device = str(jax.devices()[0])
    log.version(__version__)
    log.banner(spec.fp_index, DEFAULT_SEED, spec.workers, device)
    log.sany(*_sany_inputs(args.config, spec.spec_name))
    log.starting()
    log.computing_init()
    _open_journal(
        args, workload=spec.spec_name,
        engine="sharded" if args.sharded else "single",
        device=device,
        params=dict(chunk=args.chunk, queue_capacity=args.qcap,
                    fp_capacity=args.fpcap, sharded=args.sharded,
                    pipeline=args.pipeline, frontend=kit.kind,
                    sort_free=_sort_free(args),
                    deferred=_deferred(args),
                    symmetry=_symmetry(args), por=_por(args),
                    obs_slots=_obs_slots(args)),
    )
    # incremental re-checking (ISSUE 13): try the artifact tiers BEFORE
    # preflight or any engine build.  A verdict hit swaps the engine
    # dispatch for the cached result (and stands in for the temporal
    # checks the cached clean verdict already attests); a reach hit
    # swaps it for the BFS-free invariant pass.  Everything downstream
    # - transcript, journal, violation traces - runs unchanged, so a
    # cached answer renders exactly like a fresh run.
    cache_tier = None
    plan = kit.artifact_plan
    if plan is not None:
        fast = plan.fast_check(getattr(args, "_journal", None), log)
        if fast is not None:
            cache_tier, fast_fn, n_init_cached = fast
            kit.check = fast_fn
            kit.init_count = lambda: n_init_cached
            if cache_tier == "verdict":
                from .struct.artifacts import _PropertyHolds

                kit.check_leads_to = (
                    lambda name, p, q, **_kw: _PropertyHolds()
                )
    if kit.preflight is not None and cache_tier != "verdict":
        rc = _preflight_gate(args, log, kit.preflight)
        if rc is not None:
            return rc
    t0 = time.time()
    from .resil import SlotOverflowError

    try:
        with _xprof(args):
            r, sup = kit.check()
    except SlotOverflowError as e:
        log.msg(1000, f"Run stopped: {e}", severity=1)
        _finish_journal(args, log)
        return 1
    except FileNotFoundError as e:
        print(f"Error: {e}", file=_err(args))
        _finish_journal(args, log)
        return 1
    args._result = r
    n_init = kit.init_count()
    log.init_done(n_init)
    if sup is not None and sup.interrupted:
        # the interrupted banner (with the resume command) was emitted
        # by the supervisor's event hook
        from .resil import EXIT_INTERRUPTED

        log.progress(r.depth, r.generated, r.distinct, r.queue_left)
        log.final_counts(r.generated, r.distinct, r.queue_left)
        _finish_journal(args, log, r=None, sup=sup)
        return EXIT_INTERRUPTED
    red_info = kit.reduce_info() if kit.reduce_info is not None else None
    if red_info is not None:
        # the `reduce` journal event (schema v1, ISSUE 18): how much
        # the reduction actually bought this run.  ample_hit_rate is
        # pruned/(generated+pruned) - the share of candidate
        # transitions the singleton ample sets cut before dedup
        pruned = int(getattr(r, "por_pruned", None) or 0)
        total = int(r.generated) + pruned
        j = getattr(args, "_journal", None)
        if j is not None:
            j.event(
                "reduce",
                states_pruned=pruned,
                ample_hit_rate=(round(pruned / total, 6) if total
                                else 0.0),
                generated=int(r.generated),
                distinct=int(r.distinct),
                **red_info,
            )
    if getattr(r, "sym_violated", False):
        # the runtime orbit certificate tripped: the canonicalization
        # was NOT constant on some reachable orbit, so the symmetry
        # reduction may have merged states it had no right to merge -
        # every count (and the clean verdict) is untrustworthy.  Loud
        # error verdict, same discipline as the bound certificate
        detail = ("runtime orbit-certificate violation: the symmetry "
                  "canonicalization is not orbit-invariant on a "
                  "reachable state; re-run with -no-symmetry and "
                  "report the spec (the symmetry verification is "
                  "unsound)")
        j = getattr(args, "_journal", None)
        if j is not None:
            j.event("analysis", layer="spec", check="orbit-certificate",
                    severity="error", subject=spec.spec_name,
                    detail=detail)
            j.event("final", verdict="error", generated=r.generated,
                    distinct=r.distinct, depth=r.depth,
                    queue=r.queue_left,
                    wall_s=round(time.time() - t0, 6),
                    interrupted=False)
        log.msg(1000, f"ERROR: {detail}", severity=1)
        _finish_journal(args, log)
        return 1
    if getattr(r, "cert_violated", False):
        # the runtime certificate tripped: a reachable state violated a
        # bound the certified abstract interpretation claimed, so every
        # count this narrowed run produced is untrustworthy.  Loud
        # error verdict, never a silent narrowing (the views banner
        # already fired at the level event; this is the structured
        # record + the exit code)
        detail = ("runtime certificate violation: a reachable state "
                  "lies outside the certified bounds the narrowed "
                  "codec was built from; re-run with -no-narrow and "
                  "report the spec (the bound certification is "
                  "unsound)")
        j = getattr(args, "_journal", None)
        if j is not None:
            j.event("analysis", layer="spec", check="bound-certificate",
                    severity="error", subject=spec.spec_name,
                    detail=detail)
            j.event("final", verdict="error", generated=r.generated,
                    distinct=r.distinct, depth=r.depth,
                    queue=r.queue_left,
                    wall_s=round(time.time() - t0, 6),
                    interrupted=False)
        log.msg(1000, f"ERROR: {detail}", severity=1)
        _finish_journal(args, log)
        return 1
    violated = r.violation != 0
    liveness_violated = False
    if not violated and spec.properties:
        from .live.check import use_device_path

        log.checking_temporal(
            r.distinct,
            "device" if kit.kind == "generic" and use_device_path(
                r.distinct, args.fairness, args.liveness_host
            ) else "host",
        )
        for name, p_ast, q_ast, skip in kit.properties():
            if skip is not None:
                log.msg(1000, f"Temporal property {name} skipped: "
                              f"{skip}.", severity=1)
                continue
            res = kit.check_leads_to(name, p_ast, q_ast,
                                     distinct=r.distinct)
            if res.holds:
                log.msg(1000, f"Temporal property {name} holds "
                              f"(fairness: {kit.fairness_label}).")
                continue
            liveness_violated = True
            log.msg(2116, f"Temporal properties were violated: {name}",
                    severity=1)
            idx = 1
            for st in res.lasso_prefix:
                log.trace_state(idx, None, kit.state_to_tla(st))
                idx += 1
            log.msg(1000, "-- The following states form a cycle "
                          "(back to the first of them) --")
            for st in res.lasso_cycle:
                log.trace_state(idx, None, kit.state_to_tla(st))
                idx += 1
    if violated:
        log.msg(2110 if r.violation >= 100 else 1000,
                r.violation_name, severity=1)
        found = kit.violation_trace()
        if found is None:
            log.msg(1000, "Violation was not reproducible in host mode",
                    severity=1)
        else:
            expr_rows = None
            if args.traceExpressions:
                # trace-explorer re-evaluation over interpreted states
                from .spec.texpr import (
                    TexprError,
                    eval_over_envs,
                    parse_expressions,
                )

                try:
                    with open(args.traceExpressions) as f:
                        exprs = parse_expressions(f.read())
                    expr_rows = eval_over_envs(
                        exprs,
                        [kit.state_env(st) for st, _ in found[1]],
                    )
                except (OSError, TexprError) as e:
                    log.msg(1000, f"Trace expressions skipped: {e}",
                            severity=1)
            for i, (st, act) in enumerate(found[1], start=1):
                head = (f"State {i}: <Initial predicate>" if act is None
                        else f"State {i}: <{act}>")
                text = kit.state_to_tla(st)
                if expr_rows is not None:
                    from .spec.pretty import value_to_tla

                    text += "".join(
                        f"\n/\\ {res.name} = "
                        + (f"<evaluation failed: {res.value}>"
                           if res.failed else value_to_tla(res.value))
                        for res in expr_rows[i - 1]
                    )
                log.msg(2217, head + "\n" + text, severity=1)
    elif not liveness_violated:
        log.success(r.generated, r.distinct,
                    getattr(r, "actual_fp_collision", None),
                    occupancy=getattr(r, "fp_occupancy", None))
        dev_lines = None
        if args.coverage and kit.coverage_device is not None:
            dev_lines = kit.coverage_device(r, n_init)
        if dev_lines is not None:
            # the DEVICE per-site dump (MC.out format): counts came off
            # the carry live - no host re-walk (ISSUE 11)
            log.coverage_site_dump(dev_lines)
            j = getattr(args, "_journal", None)
            if j is not None and not any(
                e["event"] == "coverage" for e in j.events
            ):
                # unsupervised (raw-engine) runs have no segment
                # fences: journal the cumulative table once so the
                # serve plane / covdiff see this run's coverage too
                j.event(
                    "coverage",
                    visited=sum(1 for v in r.site_coverage.values()
                                if v),
                    sites=len(r.site_coverage),
                    delta={k: v for k, v in r.site_coverage.items()
                           if v},
                )
            if kit.dead_site_lint is not None:
                from .obs.views import render_tlc_event

                j = getattr(args, "_journal", None)
                for info in kit.dead_site_lint(r):
                    if j is not None:
                        ev = j.event("analysis", **info)
                    else:
                        from .obs.schema import SCHEMA_VERSION

                        ev = {"v": SCHEMA_VERSION, "t": time.time(),
                              "event": "analysis", **info}
                    render_tlc_event(log, ev)
        elif args.coverage and kit.coverage is not None:
            # full per-expression dump: host re-walk with instrumented
            # evaluation, the KubeAPI path's discipline applied to the
            # generic frontend (slow for large configs, like TLC's own
            # coverage mode)
            log.coverage_gen_dump(kit.coverage())
        else:
            act_gen, act_dist = r.action_generated, r.action_distinct
            if kit.action_order is not None:
                # per-action lines in module-definition (MC.out) order,
                # zero-fire actions printed 0:0 exactly as TLC does
                order = kit.action_order()
                act_gen = {a: act_gen.get(a, 0) for a in order}
                act_dist = {a: act_dist.get(a, 0) for a in order}
            log.coverage_generic(spec.spec_name, n_init,
                                 act_gen, act_dist)
    log.progress(r.depth, r.generated, r.distinct, r.queue_left)
    log.final_counts(r.generated, r.distinct, r.queue_left)
    log.depth(r.depth)
    log.finished(int((time.time() - t0) * 1000))
    if (plan is not None and not violated and not liveness_violated
            and (sup is None or not (sup.interrupted
                                     or getattr(sup, "exhausted",
                                                False)))):
        # the clean-final-verdict write point: error/violation/
        # interrupted/exhausted runs never reach this branch, and
        # record() re-checks violation + certificate itself
        try:
            plan.record(
                r, n_init=n_init,
                journal=getattr(args, "_journal", None),
                action_order=(kit.action_order()
                              if kit.action_order is not None else None),
            )
        except OSError as e:  # a full disk must not fail the verdict
            log.msg(1000, f"Warning: artifact cache write failed: {e}",
                    severity=1)
    _finish_journal(
        args, log, r=r, sup=sup,
        verdict="liveness_violation" if liveness_violated else None,
        wall_s=time.time() - t0,
    )
    if violated:
        return 12
    return 13 if liveness_violated else 0


def _print_trace(log: TLCLog, model: ModelConfig, chunk: int,
                 trace_expr_file: str = "",
                 check_deadlock: bool = True) -> None:
    from .engine.trace import find_violation_trace
    from .spec.pretty import state_to_tla

    found = find_violation_trace(model, chunk=chunk,
                                 check_deadlock=check_deadlock)
    if found is None:
        log.msg(1000, "Violation was not reproducible in host mode", severity=1)
        return
    _, trace = found
    expr_rows = None
    if trace_expr_file:
        # the Toolbox trace-explorer pass (MC_TE.out slot): evaluate each
        # user expression in every trace state, shown as extra conjuncts.
        # A bad/missing expression file must never lose the trace itself.
        from .spec.pretty import value_to_tla
        from .spec.texpr import TexprError, eval_over_trace, parse_expressions

        try:
            with open(trace_expr_file) as f:
                exprs = parse_expressions(f.read())
            expr_rows = eval_over_trace(exprs, trace, model)
        except (OSError, TexprError) as e:
            log.msg(1000, f"Trace expressions skipped: {e}", severity=1)
    for i, (st, act) in enumerate(trace, start=1):
        text = state_to_tla(st, model)
        if expr_rows is not None:
            text += "".join(
                f"\n/\\ {res.name} = "
                + (f"<evaluation failed: {res.value}>" if res.failed
                   else value_to_tla(res.value))
                for res in expr_rows[i - 1]
            )
        log.trace_state(i, act, text)
