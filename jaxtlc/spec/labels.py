"""Shared label/enum tables for the KubeAPI action system.

These enumerate the control-flow labels, verbs, and response codes of the
reference spec (/root/reference/KubeAPI.tla: labels at 471-756, Verbs at :415,
Responses at :421).  Both the host oracle interpreter and the tensorized TPU
kernel index into these tables, so their integer encodings agree by
construction.
"""

# Process identifiers are config-driven (ModelConfig.processes mirrors
# ProcSet, KubeAPI.tla:453): N reconciler clients + M binders + "Server".

# PlusCal labels == TLA actions (KubeAPI.tla:471-756).
# Order is the canonical integer encoding used by the codec.
LABELS = (
    # procedure API (KubeAPI.tla:471-497)
    "DoRequest",
    "DoReply",
    # procedure ListAPI (KubeAPI.tla:499-526)
    "DoListRequest",
    "DoListReply",
    # process Client (KubeAPI.tla:528-653)
    "CStart",
    "C1",
    "C10",
    "C11",
    "c12",
    "C13",
    "C2",
    "C3",
    "C8",
    "C6",
    "C7",
    "C4",
    "C5",
    # process PVCController (KubeAPI.tla:655-693)
    "PVCStart",
    "PVCListedPVCs",
    "PVCHavePVCs",
    "PVCDone",
    # process APIServer (KubeAPI.tla:698-756)
    "APIStart",
)
LABEL_ID = {name: i for i, name in enumerate(LABELS)}

# API verbs (KubeAPI.tla:415).  "Create" is never issued by Model_1's
# processes but is part of the verb enum and the server dispatch.
VERBS = ("Create", "Get", "Update", "Delete", "Force")
VERB_ID = {v: i for i, v in enumerate(VERBS)}

# Request status codes (KubeAPI.tla:421)
RESPONSES = ("Pending", "Ok", "Error")
RESPONSE_ID = {r: i for i, r in enumerate(RESPONSES)}

# TLC's defaultInitValue model value (KubeAPI.tla:374, Init :460-463).
DEFAULT_INIT = "__defaultInitValue__"

# Procedure ids for stack frames (frames at KubeAPI.tla:535-539 etc.)
PROC_API = "API"
PROC_LISTAPI = "ListAPI"
