"""Fixed-width tensor codec for KubeAPI states.

Encodes the full variable vector (vars, /root/reference/KubeAPI.tla:450-451)
as a flat vector of F int32 *fields* - the working representation of the
vmapped kernel - plus a bit-packer that compresses a field vector to W uint32
words for fingerprinting (the canonical wire form).

Design points (SURVEY.md §7 item 1 and "hard parts"):

* **Set-valued state with partial domains**: API objects may lack vv/spec
  (DOMAIN tests at KubeAPI.tla:29-31, 94-95).  Every object is one int32 word
  of presence-bit-guarded fields; `apiState` and each list result are arrays
  of such words kept in *canonical descending order* so TLA set equality ==
  array equality and fingerprints are permutation-invariant.
* **Bounds are config-driven**: slot counts derive from ModelConfig
  (identities, clients, max_per_kind); scaled-constant configs change only
  the config.  Slot overflow is detected by the kernel, not silently dropped.
* **No native int64**: the packed form is uint32 words; the 64-bit
  fingerprint is computed from them in 2-lane form (engine.fingerprint).

Object word layout (LSB..MSB):
    [has_spec:1][vv:NC][has_vv:1][ident:IB][present:1]
`present` is the most-significant used bit so that plain descending sort of
words puts present objects first - the canonical order.  A present object
with `spec` always satisfies spec == [pvname |-> name] (the only spec value
the spec ever constructs, KubeAPI.tla:675-678); encode() asserts this.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, Tuple

import jax.numpy as jnp
import numpy as np

from ..config import ModelConfig
from . import oracle
from .labels import (
    DEFAULT_INIT,
    LABELS,
    LABEL_ID,
    PROC_API,
    PROC_LISTAPI,
    RESPONSES,
    RESPONSE_ID,
    VERBS,
    VERB_ID,
)


def _bits_for(n: int) -> int:
    return max(1, (n - 1).bit_length())


@dataclasses.dataclass(frozen=True)
class Field:
    name: str
    count: int  # number of int32 lanes
    width: int  # bits per lane when packed


class Codec:
    """Field layout + encode/decode/pack for one ModelConfig."""

    def __init__(self, cfg: ModelConfig):
        self.cfg = cfg
        ni, nc = cfg.n_identities, cfg.n_clients
        ls = cfg.max_per_kind
        self.ni, self.nc, self.ls = ni, nc, ls
        self.ib = _bits_for(ni)
        self.kb = _bits_for(len(cfg.kinds))
        self.lb = _bits_for(len(LABELS))
        # object word layout
        self.o_spec = 0
        self.o_vv = 1
        self.o_hasvv = 1 + nc
        self.o_ident = 2 + nc
        self.o_present = 2 + nc + self.ib
        self.obj_bits = self.o_present + 1
        # request word layout: [status:2][op:3][present:1] above the obj word
        self.r_obj = 0
        self.r_status = self.obj_bits
        self.r_op = self.obj_bits + 2
        self.r_present = self.obj_bits + 5
        self.req_bits = self.r_present + 1
        # list-request meta word: [status:2][kind:kb][present:1]
        self.lm_status = 0
        self.lm_kind = 2
        self.lm_present = 2 + self.kb
        self.lm_bits = self.lm_present + 1
        # stack word: [retpc:lb][proc:1][present:1]
        self.s_retpc = 0
        self.s_proc = self.lb
        self.s_present = self.lb + 1
        self.stk_bits = self.s_present + 1

        self.nr = cfg.n_reconcilers
        self.fields: List[Field] = [
            Field("api", ni, self.obj_bits),
            Field("req", nc, self.req_bits),
            Field("lreq_meta", nc, self.lm_bits),
            Field("lreq_obj", nc * ls, self.obj_bits),
            Field("pc", nc + 1, self.lb),
            Field("stack", nc, self.stk_bits),
            Field("p_op", nc, 3),  # 0 = defaultInitValue, else 1 + verb id
            Field("p_obj", nc, self.obj_bits),  # 0 = dIV (present bit clear)
            Field("p_kind", nc, self.kb + 1),  # 0 = dIV, else 1 + kind id
            Field("sr", self.nr, 1),  # shouldReconcile, one bit/reconciler
        ]
        self.offsets: Dict[str, int] = {}
        off = 0
        for f in self.fields:
            self.offsets[f.name] = off
            off += f.count
        self.n_fields = off
        self.nbits = sum(f.count * f.width for f in self.fields)
        self.n_words = (self.nbits + 31) // 32
        self.kind_id = {k: i for i, k in enumerate(cfg.kinds)}
        self.client_id = {c: i for i, c in enumerate(cfg.clients)}

    # -- slicing helpers ----------------------------------------------------

    def sl(self, name: str):
        off = self.offsets[name]
        cnt = next(f.count for f in self.fields if f.name == name)
        return slice(off, off + cnt)

    # -- object word (host) -------------------------------------------------

    def encode_obj(self, o) -> int:
        """Oracle object record -> object word."""
        kind, name = oracle.fld(o, "k"), oracle.fld(o, "n")
        ident = self.cfg.identity_id(kind, name)
        w = (1 << self.o_present) | (ident << self.o_ident)
        vv = oracle.fld(o, "vv")
        if vv is not None or oracle.has(o, "vv"):
            w |= 1 << self.o_hasvv
            for c in vv:
                w |= 1 << (self.o_vv + self.client_id[c])
        if oracle.has(o, "spec"):
            spec = oracle.fld(o, "spec")
            assert spec == oracle.rec(pvname=name), (
                "codec invariant: spec is always [pvname |-> own name] "
                f"(KubeAPI.tla:675-678); got {spec!r}"
            )
            w |= 1 << self.o_spec
        assert not oracle.has(o, "status"), "objects never carry status"
        return w

    def decode_obj(self, w: int):
        """Object word -> oracle object record (None if absent)."""
        if not (w >> self.o_present) & 1:
            return None
        ident = (w >> self.o_ident) & ((1 << self.ib) - 1)
        kind, name = self.cfg.identities[ident]
        d = {"k": kind, "n": name}
        if (w >> self.o_hasvv) & 1:
            d["vv"] = frozenset(
                self.cfg.clients[i]
                for i in range(self.nc)
                if (w >> (self.o_vv + i)) & 1
            )
        if (w >> self.o_spec) & 1:
            d["spec"] = oracle.rec(pvname=name)
        return tuple(sorted(d.items()))

    # -- full state (host) --------------------------------------------------

    def encode(self, st: oracle.State) -> np.ndarray:
        """Oracle state -> canonical field vector (np.int32[F])."""
        v = np.zeros(self.n_fields, dtype=np.int64)
        # apiState: canonical descending order
        words = sorted((self.encode_obj(o) for o in st.api_state), reverse=True)
        assert len(words) <= self.ni, "apiState slot overflow"
        v[self.sl("api")][: len(words)] = words
        # requests
        req = v[self.sl("req")]
        for c, r in st.requests:
            ci = self.client_id[c]
            w = (1 << self.r_present)
            w |= VERB_ID[oracle.fld(r, "op")] << self.r_op
            w |= RESPONSE_ID[oracle.fld(r, "status")] << self.r_status
            w |= self.encode_obj(oracle.fld(r, "obj")) << self.r_obj
            req[ci] = w
        # listRequests
        lm = v[self.sl("lreq_meta")]
        lo = v[self.sl("lreq_obj")]
        for c, r in st.list_requests:
            ci = self.client_id[c]
            w = (1 << self.lm_present)
            w |= self.kind_id[oracle.fld(r, "kind")] << self.lm_kind
            w |= RESPONSE_ID[oracle.fld(r, "status")] << self.lm_status
            lm[ci] = w
            objs = sorted(
                (self.encode_obj(o) for o in oracle.fld(r, "objs")), reverse=True
            )
            assert len(objs) <= self.ls, "list slot overflow"
            lo[ci * self.ls : ci * self.ls + len(objs)] = objs
        # pc
        v[self.sl("pc")] = [LABEL_ID[l] for l in st.pc]
        # stack (client processes only; server never calls, KubeAPI.tla:698)
        stk = v[self.sl("stack")]
        assert not st.stack[self.nc], "server stack is always empty"
        for ci in range(self.nc):
            frames = st.stack[ci]
            assert len(frames) <= 1, "procedures never nest (SURVEY.md §7)"
            if frames:
                f = frames[0]
                w = 1 << self.s_present
                if oracle.fld(f, "procedure") == PROC_LISTAPI:
                    w |= 1 << self.s_proc
                    assert oracle.fld(f, "kind") == DEFAULT_INIT, (
                        "frames always save defaultInitValue params"
                    )
                else:
                    assert oracle.fld(f, "op") == DEFAULT_INIT
                    assert oracle.fld(f, "obj") == DEFAULT_INIT
                w |= LABEL_ID[oracle.fld(f, "pc")] << self.s_retpc
                stk[ci] = w
        # procedure params (client processes; server's stay defaultInitValue)
        for name, enc in (
            ("p_op", lambda x: 0 if x == DEFAULT_INIT else 1 + VERB_ID[x]),
            ("p_obj", lambda x: 0 if x == DEFAULT_INIT else self.encode_obj(x)),
            ("p_kind", lambda x: 0 if x == DEFAULT_INIT else 1 + self.kind_id[x]),
        ):
            src = {"p_op": st.op, "p_obj": st.obj, "p_kind": st.kind}[name]
            assert src[self.nc] == DEFAULT_INIT, "server params never assigned"
            arr = v[self.sl(name)]
            for ci in range(self.nc):
                arr[ci] = enc(src[ci])
        assert len(st.should_reconcile) == self.nr
        v[self.sl("sr")] = [int(b) for b in st.should_reconcile]
        return v.astype(np.int32)

    def decode(self, vec) -> oracle.State:
        """Field vector -> oracle state (inverse of encode on canonical vecs)."""
        v = np.asarray(vec, dtype=np.int64)
        api = frozenset(
            o
            for o in (self.decode_obj(int(w)) for w in v[self.sl("api")])
            if o is not None
        )
        requests = ()
        for ci, w in enumerate(v[self.sl("req")]):
            w = int(w)
            if not (w >> self.r_present) & 1:
                continue
            r = oracle.rec(
                op=VERBS[(w >> self.r_op) & 7],
                obj=self.decode_obj((w >> self.r_obj) & ((1 << self.obj_bits) - 1)),
                status=RESPONSES[(w >> self.r_status) & 3],
            )
            requests = oracle.pmap_set(requests, self.cfg.clients[ci], r)
        list_requests = ()
        lo = v[self.sl("lreq_obj")]
        for ci, w in enumerate(v[self.sl("lreq_meta")]):
            w = int(w)
            if not (w >> self.lm_present) & 1:
                continue
            objs = frozenset(
                o
                for o in (
                    self.decode_obj(int(x))
                    for x in lo[ci * self.ls : (ci + 1) * self.ls]
                )
                if o is not None
            )
            r = oracle.rec(
                kind=self.cfg.kinds[(w >> self.lm_kind) & ((1 << self.kb) - 1)],
                objs=objs,
                status=RESPONSES[(w >> self.lm_status) & 3],
            )
            list_requests = oracle.pmap_set(list_requests, self.cfg.clients[ci], r)
        pc = tuple(LABELS[int(x)] for x in v[self.sl("pc")])
        stack: List[tuple] = []
        for ci in range(self.nc):
            w = int(v[self.sl("stack")][ci])
            if (w >> self.s_present) & 1:
                ret = LABELS[(w >> self.s_retpc) & ((1 << self.lb) - 1)]
                if (w >> self.s_proc) & 1:
                    frame = oracle.rec(
                        procedure=PROC_LISTAPI, pc=ret, kind=DEFAULT_INIT
                    )
                else:
                    frame = oracle.rec(
                        procedure=PROC_API, pc=ret, op=DEFAULT_INIT, obj=DEFAULT_INIT
                    )
                stack.append((frame,))
            else:
                stack.append(())
        stack.append(())  # server
        p_op, p_obj, p_kind = [], [], []
        for ci in range(self.nc):
            w = int(v[self.sl("p_op")][ci])
            p_op.append(DEFAULT_INIT if w == 0 else VERBS[w - 1])
            w = int(v[self.sl("p_obj")][ci])
            o = self.decode_obj(w)
            p_obj.append(DEFAULT_INIT if o is None else o)
            w = int(v[self.sl("p_kind")][ci])
            p_kind.append(DEFAULT_INIT if w == 0 else self.cfg.kinds[w - 1])
        for lst in (p_op, p_obj, p_kind):
            lst.append(DEFAULT_INIT)
        return oracle.State(
            api_state=api,
            requests=requests,
            list_requests=list_requests,
            pc=pc,
            stack=tuple(stack),
            op=tuple(p_op),
            obj=tuple(p_obj),
            kind=tuple(p_kind),
            should_reconcile=tuple(bool(x) for x in v[self.sl("sr")]),
        )

    # -- canonicalization + packing (device) --------------------------------

    def canonicalize(self, vecs):
        """Sort set-valued slot groups descending: [..., F] -> [..., F].

        apiState slots and each client's list-result slots are TLA sets;
        descending word order is the canonical representative (present bit is
        the top used bit, so present slots sort first).
        """
        api = self.sl("api")
        out = vecs.at[..., api].set(
            -jnp.sort(-vecs[..., api], axis=-1)
        )
        lo_off = self.offsets["lreq_obj"]
        if self.ls > 1:
            for ci in range(self.nc):
                s = slice(lo_off + ci * self.ls, lo_off + (ci + 1) * self.ls)
                out = out.at[..., s].set(-jnp.sort(-out[..., s], axis=-1))
        return out

    def pack(self, vecs):
        """[..., F] int32 field vectors -> [..., W] uint32 packed words."""
        v = vecs.astype(jnp.uint32)
        lanes = []  # (field lane array [...,], width)
        for f in self.fields:
            off = self.offsets[f.name]
            for j in range(f.count):
                lanes.append((v[..., off + j], f.width))
        words = []
        cur = None
        cur_bits = 0
        for lane, width in lanes:
            remaining = lane
            rbits = width
            while rbits > 0:
                if cur is None:
                    cur = jnp.zeros_like(lane)
                    cur_bits = 0
                take = min(rbits, 32 - cur_bits)
                cur = cur | ((remaining & ((jnp.uint32(1) << take) - jnp.uint32(1))) << cur_bits)
                remaining = remaining >> take
                rbits -= take
                cur_bits += take
                if cur_bits == 32:
                    words.append(cur)
                    cur = None
        if cur is not None:
            words.append(cur)
        return jnp.stack(words, axis=-1)

    def unpack(self, words):
        """[..., W] uint32 packed words -> [..., F] int32 field vectors.

        Exact inverse of pack() (property-tested in tests/test_codec.py);
        the packed form is the engine's at-rest representation (queue rows,
        fingerprint input), unpacked only at the kernel boundary.
        """
        w = words.astype(jnp.uint32)
        out = [None] * self.n_fields
        wi = 0
        bitpos = 0
        for f in self.fields:
            off = self.offsets[f.name]
            for j in range(f.count):
                width = f.width
                val = jnp.zeros_like(w[..., 0])
                got = 0
                while got < width:
                    take = min(width - got, 32 - bitpos)
                    piece = (w[..., wi] >> bitpos) & jnp.uint32(
                        (1 << take) - 1
                    )
                    val = val | (piece << got)
                    got += take
                    bitpos += take
                    if bitpos == 32:
                        wi += 1
                        bitpos = 0
                out[off + j] = val.astype(jnp.int32)
        return jnp.stack(out, axis=-1)

    # -- kernel-facing structured view --------------------------------------

    def to_sdict(self, vec):
        """[F] field vector -> structured dict (kernel working form)."""
        return {
            "api": vec[self.sl("api")],
            "req": vec[self.sl("req")],
            "lreq_meta": vec[self.sl("lreq_meta")],
            "lreq_obj": vec[self.sl("lreq_obj")].reshape(self.nc, self.ls),
            "pc": vec[self.sl("pc")],
            "stack": vec[self.sl("stack")],
            "p_op": vec[self.sl("p_op")],
            "p_obj": vec[self.sl("p_obj")],
            "p_kind": vec[self.sl("p_kind")],
            "sr": vec[self.sl("sr")],
        }

    def from_sdict(self, sd):
        """Structured dict -> [F] field vector."""
        return jnp.concatenate(
            [
                sd["api"],
                sd["req"],
                sd["lreq_meta"],
                sd["lreq_obj"].reshape(self.nc * self.ls),
                sd["pc"],
                sd["stack"],
                sd["p_op"],
                sd["p_obj"],
                sd["p_kind"],
                sd["sr"],
            ]
        )

    def pack_host(self, vec) -> int:
        """Host packer (python int) - property-test reference for pack()."""
        v = np.asarray(vec, dtype=np.int64)
        out, pos = 0, 0
        for f in self.fields:
            off = self.offsets[f.name]
            for j in range(f.count):
                out |= (int(v[off + j]) & ((1 << f.width) - 1)) << pos
                pos += f.width
        assert pos == self.nbits
        return out


@functools.lru_cache(maxsize=None)
def get_codec(cfg: ModelConfig) -> Codec:
    return Codec(cfg)
