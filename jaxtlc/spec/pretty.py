"""TLA+-syntax state rendering for counterexample traces.

Formats decoded oracle states the way TLC prints trace states (one
`/\\ var = value` conjunct per variable, TLA record/set/function syntax) so
traces are readable next to the reference artifacts and parseable by
Toolbox-style tooling.  The pmap capability (SURVEY.md §2.2 M4) - rendering
at PlusCal level - is covered by the action labels in the trace header;
variable values print at TLA level exactly like TLC's.
"""

from __future__ import annotations

from typing import Iterable

from ..config import ModelConfig
from .labels import DEFAULT_INIT
from .oracle import State


def value_to_tla(v) -> str:
    """Public value renderer (trace-expression output uses it)."""
    return _value(v)


def _value(v) -> str:
    if v is None:
        return "defaultInitValue"
    if isinstance(v, bool):
        return "TRUE" if v else "FALSE"
    if isinstance(v, str):
        return "defaultInitValue" if v == DEFAULT_INIT else f'"{v}"'
    if isinstance(v, frozenset):
        return "{" + ", ".join(sorted(_value(x) for x in v)) + "}"
    if isinstance(v, tuple):
        if v and all(isinstance(x, tuple) and len(x) == 2 for x in v):
            # record
            return (
                "[" + ", ".join(f"{k} |-> {_value(val)}" for k, val in v) + "]"
            )
        return "<<" + ", ".join(_value(x) for x in v) + ">>"
    return str(v)


def _fn(domain: Iterable[str], values) -> str:
    pairs = [f"{d} |-> {_value(v)}" for d, v in zip(domain, values)]
    return "[" + ", ".join(pairs) + "]"


def _partial_fn(entries) -> str:
    if not entries:
        return "<<>>"  # TLC prints the empty function this way
    return " @@ ".join(f"{c} :> {_value(r)}" for c, r in entries)


def state_to_tla(st: State, cfg: ModelConfig) -> str:
    procs = cfg.processes
    reconcilers = [cfg.clients[i] for i in cfg.reconciler_indices]
    lines = [
        f"/\\ apiState = {_value(st.api_state)}",
        f"/\\ requests = {_partial_fn(st.requests)}",
        f"/\\ listRequests = {_partial_fn(st.list_requests)}",
        f"/\\ pc = {_fn(procs, st.pc)}",
        "/\\ stack = "
        + _fn(procs, [tuple(fr for fr in s) for s in st.stack]),
        f"/\\ op = {_fn(procs, st.op)}",
        f"/\\ obj = {_fn(procs, st.obj)}",
        f"/\\ kind = {_fn(procs, st.kind)}",
        f"/\\ shouldReconcile = {_fn(reconcilers, st.should_reconcile)}",
    ]
    return "\n".join(lines)
