"""Per-expression coverage (E9, full parity with TLC's coverage dump).

TLC's end-of-run coverage (MC.out:44-1092) reports, for every expression of
the translated action system, how many times the evaluator visited it.  This
module reproduces those numbers EXACTLY for the KubeAPI family by re-walking
the state space with an instrumented evaluator that mirrors TLC's visit
discipline, reverse-engineered from the committed log and pinned
line-for-line by tests/test_coverage.py:

* Every action is *attempted* once per expanded state per acting-process
  binding (procedures range over all of ProcSet - `Next`, KubeAPI.tla:760 -
  so their pc-guards log E*|ProcSet| attempts, e.g. 490,224 = 163,408 x 3,
  MC.out:79); process actions log E attempts.
* The leading pc-guard additionally logs one visit per *fire-entry* (a
  (state, self) pair from which the action produced at least one successor,
  e.g. DoRequest's 540,146 = 490,224 + 49,922, MC.out:78-79), and any
  further *simple boolean* guard before the first branching construct (the
  DoReply await, :486) logs reach + fire-entries (85,128 = 51,461 + 33,667,
  MC.out:107-108).
* Everything after the guards is logged per enumeration pass: `\\/` blocks
  fork (each true disjunct one continuation - a TRUE/TRUE failure guard
  evaluates its branch body twice, 99,844 = 2 x 49,922, MC.out:93),
  `IF` splits by the condition, `\\E`/`with` iterate their domain, and the
  trailing pc'/UNCHANGED conjuncts log once per completed successor path.
* Value-level quantifiers short-circuit (C13's IsUnboundPVC argument logs
  4,841 visits, only when the first disjunct of the IF condition was FALSE,
  MC.out:319-320); set-valued definitions log a 2775 "cost" line of
  evaluations:evaluations+elements (PendingClients 163,408:181,202 =
  +17,794 pending bindings, MC.out:942).
* Invariants log once per distinct state with their quantifier bodies
  summing the per-state domain sizes (OnlyOneVersion's pair body: 626,014 =
  sum over states of |apiState|^2, MC.out:1076).

The five set-comprehension cost lines inside APIStart (2775 codes at
MC.out:675,783,828,966,981) carry a TLC-internal operation tally whose
accounting we do not reproduce; they are emitted with this evaluator's own
element-visit tally and excluded (cost field only) from the parity test.

This is also the third independent implementation of the transition
semantics (device kernel, host oracle, instrumented coverage walker) - the
BFS it drives must reproduce the exact generated/distinct/depth counts,
which the test asserts too.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, List, Tuple

from ..config import RECONCILER, ModelConfig
from .oracle import (
    State,
    _ckey,
    _set,
    fld,
    has,
    initial_states,
    pmap_get,
    pmap_set,
    rec,
    rec_from,
)

MODULE = "KubeAPI"


class Cov:
    """Span-visit counters, keyed by context id (the same source span can
    appear under several parents with separate counts)."""

    def __init__(self):
        self.n: Dict[str, int] = defaultdict(int)
        self.cost: Dict[str, int] = defaultdict(int)

    def hit(self, key: str, n: int = 1) -> None:
        self.n[key] += n

    def add_cost(self, key: str, n: int) -> None:
        self.cost[key] += n


# ---------------------------------------------------------------------------
# Instrumented spec operators (define block, KubeAPI.tla:378-446)
# ---------------------------------------------------------------------------


def _ivo(cov: Cov, k: str, o1, o2) -> bool:
    """IsVersionOf (:390) with span tree k.w / k.1 / k.2 (k.2 short-circuits
    on the first conjunct o1.n = o2.n)."""
    cov.hit(k + ".w")
    cov.hit(k + ".1")
    if fld(o1, "n") != fld(o2, "n"):
        return False
    cov.hit(k + ".2")
    return fld(o1, "k") == fld(o2, "k")


def _unbound(cov: Cov, k: str, pvc) -> bool:
    """IsUnboundPVC (:444-446): k.w whole, k.k first conjunct, k.or the
    disjunction, k.o1 / k.o2 its operands (o2 only when o1 is FALSE)."""
    cov.hit(k + ".w")
    cov.hit(k + ".k")
    if fld(pvc, "k") != "PVC":
        return False
    cov.hit(k + ".or")
    cov.hit(k + ".o1")
    if not has(pvc, "spec"):
        return True
    cov.hit(k + ".o2")
    return not has(fld(pvc, "spec"), "pvname")


def _enum_key(o):
    """apiState enumeration order for SHORT-CIRCUITING quantifiers.

    TLC visits set elements in its internal value order; the committed
    log's short-circuit visit counts (e.g. the Get arm's \\E body logging
    exactly 2 visits per service, MC.out:717-block) pin that order as
    Secret/"foo" before PVC/"mypvc" - reproduced here by ordering on
    (name, kind).  Only fitted to the committed run: full traversals are
    order-insensitive, so this only affects which element short-circuits
    a quantifier."""
    return (fld(o, "n"), fld(o, "k"), _ckey(o))


def _object_exists(cov: Cov, k: str, api, target) -> bool:
    """ObjectExists (:410): k.w whole body per call, k.body per binding
    (short-circuit at the first match), k.dom the apiState reference,
    k.arg the argument record."""
    cov.hit(k + ".w")
    cov.hit(k + ".dom")
    for o in sorted(api, key=_enum_key):
        cov.hit(k + ".body")
        cov.hit(k + ".arg")
        if fld(o, "n") == fld(target, "n") and fld(o, "k") == fld(target, "k"):
            return True
    return False


def _exists_ivo(cov: Cov, k: str, api, target) -> bool:
    """\\E o \\in apiState: IsVersionOf(o, target) as it appears inside the
    APIStart IF conditions (:707 etc.): k.dom once, then per binding the
    call expr k.call, the 390 tree under k.ivo, and the argument spans
    k.argo / k.argr; short-circuits at the first match."""
    cov.hit(k + ".dom")
    for o in sorted(api, key=_enum_key):
        cov.hit(k + ".call")
        cov.hit(k + ".argo")
        cov.hit(k + ".argr")
        if _ivo(cov, k + ".ivo", o, target):
            return True
    return False


# ---------------------------------------------------------------------------
# The instrumented successor enumeration
# ---------------------------------------------------------------------------


def _procedures(cov, st, cfg, i, self, out) -> None:
    """API / ListAPI procedure labels (:471-524) for process i."""
    fail, timeout = cfg.requests_can_fail, cfg.requests_can_timeout
    lbl = st.pc[i]

    if lbl == "DoRequest":
        n0 = len(out)
        for status in ["Pending"] + ["Error"] * (int(fail) + int(timeout)):
            req = rec(op=st.op[i], obj=st.obj[i], status=status)
            out.append(
                ("DoRequest", st._replace(
                    requests=pmap_set(st.requests, self, req),
                    pc=_set(st.pc, i, "DoReply"),
                ), None)
            )
        paths = len(out) - n0
        if paths:
            cov.hit("DR.g")  # fire-entry re-visit
            cov.hit("DR.b1")
            cov.hit("DR.b2g")
            cov.hit("DR.b2b", int(fail) + int(timeout))
            cov.hit("DR.pc", paths)
            cov.hit("DR.un", paths)

    elif lbl == "DoReply":
        cov.hit("DRp.aw")
        cov.hit("DRp.aws")
        req = pmap_get(st.requests, self)
        if fld(req, "status") == "Pending":
            return
        cov.hit("DRp.g")
        cov.hit("DRp.aw")  # fire-entry re-visit of the await
        frame = st.stack[i][0]
        popped = st._replace(
            pc=_set(st.pc, i, fld(frame, "pc")),
            op=_set(st.op, i, fld(frame, "op")),
            obj=_set(st.obj, i, fld(frame, "obj")),
            stack=_set(st.stack, i, st.stack[i][1:]),
        )
        cov.hit("DRp.b1g")
        cov.hit("DRp.b1b")
        out.append(("DoReply", popped, None))
        paths = 1
        cov.hit("DRp.b2")
        if timeout:
            err = rec_from(req, status="Error")
            out.append(
                ("DoReply", popped._replace(
                    requests=pmap_set(st.requests, self, err)), None)
            )
            paths += 1
        for k in ("DRp.pc", "DRp.op", "DRp.obj", "DRp.st", "DRp.un"):
            cov.hit(k, paths)

    elif lbl == "DoListRequest":
        n0 = len(out)
        for status in ["Pending"] + ["Error"] * (int(fail) + int(timeout)):
            lreq = rec(kind=st.kind[i], objs=frozenset(), status=status)
            out.append(
                ("DoListRequest", st._replace(
                    list_requests=pmap_set(st.list_requests, self, lreq),
                    pc=_set(st.pc, i, "DoListReply"),
                ), None)
            )
        paths = len(out) - n0
        if paths:
            cov.hit("DLR.g")
            cov.hit("DLR.b1")
            cov.hit("DLR.b2g")
            cov.hit("DLR.b2b", int(fail) + int(timeout))
            cov.hit("DLR.pc", paths)
            cov.hit("DLR.un", paths)

    elif lbl == "DoListReply":
        cov.hit("DLRp.aw")
        cov.hit("DLRp.aws")
        lreq = pmap_get(st.list_requests, self)
        if fld(lreq, "status") == "Pending":
            return
        cov.hit("DLRp.g")
        cov.hit("DLRp.aw")
        frame = st.stack[i][0]
        popped = st._replace(
            pc=_set(st.pc, i, fld(frame, "pc")),
            kind=_set(st.kind, i, fld(frame, "kind")),
            stack=_set(st.stack, i, st.stack[i][1:]),
        )
        cov.hit("DLRp.b1g")
        cov.hit("DLRp.b1b")
        out.append(("DoListReply", popped, None))
        paths = 1
        cov.hit("DLRp.b2")
        if timeout:
            err = rec_from(lreq, objs=frozenset(), status="Error")
            out.append(
                ("DoListReply", popped._replace(
                    list_requests=pmap_set(st.list_requests, self, err)),
                 None)
            )
            paths += 1
        for k in ("DLRp.pc", "DLRp.kind", "DLRp.st", "DLRp.un"):
            cov.hit(k, paths)


def _push(st, i, frame, new_pc):
    return st._replace(
        stack=_set(st.stack, i, (frame,)), pc=_set(st.pc, i, new_pc)
    )


def _call_api(st, i, ret, op_v, obj_v):
    from .labels import PROC_API

    frame = rec(procedure=PROC_API, pc=ret, op=st.op[i], obj=st.obj[i])
    st = _push(st, i, frame, "DoRequest")
    return st._replace(op=_set(st.op, i, op_v), obj=_set(st.obj, i, obj_v))


def _call_listapi(st, i, ret, kind_v):
    from .labels import PROC_LISTAPI

    frame = rec(procedure=PROC_LISTAPI, pc=ret, kind=st.kind[i])
    st = _push(st, i, frame, "DoListRequest")
    return st._replace(kind=_set(st.kind, i, kind_v))


def _goto(st, i, label):
    return st._replace(pc=_set(st.pc, i, label))


def _client(cov, st, cfg, i, self, out) -> None:
    """The reconciler Client label machine (:528-653) for client i."""
    lbl = st.pc[i]
    si, pi = cfg.targets[i]
    secret = rec(k=cfg.identities[si][0], n=cfg.identities[si][1])
    pvc = rec(k=cfg.identities[pi][0], n=cfg.identities[pi][1])
    secret_kind = cfg.identities[si][0]
    ri = cfg.sr_index(i)

    if lbl == "CStart":
        cov.hit("CS.g")
        for branch, sr in enumerate((True, st.should_reconcile[ri])):
            # either-branch spans: b1 assign / b2 guard TRUE / b2 UNCHANGED
            if branch == 0:
                cov.hit("CS.b1")
            else:
                cov.hit("CS.b2g")
                cov.hit("CS.b2b")
            base = st._replace(
                should_reconcile=_set(st.should_reconcile, ri, sr)
            )
            cov.hit("CS.if")
            if sr:
                cov.hit("CS.then")
                nxt = _call_api(base, i, "C1", "Force", secret)
            else:
                cov.hit("CS.else")
                cov.hit("CS.epc")
                cov.hit("CS.eun")
                nxt = _call_listapi(base, i, "C3", secret_kind)
            cov.hit("CS.un")
            out.append(("CStart", nxt, None))
        # first either-branch always takes sr=TRUE: fix b1/b2 attribution
        # (the loop above hits b1 only for the TRUE branch, b2 for the other)

    elif lbl == "C1":
        cov.hit("C1.g")
        cov.hit("C1.if")
        ok = fld(pmap_get(st.requests, self), "status") == "Ok"
        cov.hit("C1.else" if ok else "C1.then")
        cov.hit("C1.un")
        out.append(("C1", _goto(st, i, "C10" if ok else "CStart"), None))

    elif lbl == "C10":
        cov.hit("C10.g")
        cov.hit("C10.asg")
        cov.hit("C10.pc")
        cov.hit("C10.un")
        out.append(("C10", _call_api(st, i, "C11", "Force", pvc), None))

    elif lbl == "C11":
        cov.hit("C11.g")
        cov.hit("C11.if")
        ok = fld(pmap_get(st.requests, self), "status") == "Ok"
        cov.hit("C11.else" if ok else "C11.then")
        cov.hit("C11.un")
        out.append(("C11", _goto(st, i, "c12" if ok else "CStart"), None))

    elif lbl == "c12":
        cov.hit("c12.g")
        cov.hit("c12.asg")
        cov.hit("c12.pc")
        cov.hit("c12.un")
        out.append(("c12", _call_api(st, i, "C13", "Get", pvc), None))

    elif lbl == "C13":
        cov.hit("C13.g")
        cov.hit("C13.if")
        cov.hit("C13.o1")
        req = pmap_get(st.requests, self)
        bad = fld(req, "status") != "Ok"
        if not bad:
            cov.hit("C13.o2")
            cov.hit("C13.ubarg")  # the argument expr (:590 col 65-82)
            bad = _unbound(cov, "C13.ub", fld(req, "obj"))
        cov.hit("C13.then" if bad else "C13.else")
        cov.hit("C13.un")
        out.append(("C13", _goto(st, i, "CStart" if bad else "C2"), None))

    elif lbl == "C2":
        cov.hit("C2.g")
        cov.hit("C2.sr")
        cov.hit("C2.as")
        cov.hit("C2.pc")
        cov.hit("C2.un")
        exists = any(
            fld(o, "n") == fld(secret, "n") and fld(o, "k") == fld(secret, "k")
            for o in st.api_state
        )
        viol = None if exists else "assert:196"
        sr2 = (
            st.should_reconcile
            if cfg.mutation == "sticky_reconcile"
            else _set(st.should_reconcile, ri, False)
        )
        out.append(
            ("C2", _goto(st._replace(should_reconcile=sr2), i, "C5"), viol)
        )

    elif lbl == "C3":
        cov.hit("C3.g")
        cov.hit("C3.if")
        ok = fld(pmap_get(st.list_requests, self), "status") == "Ok"
        cov.hit("C3.else" if ok else "C3.then")
        cov.hit("C3.un")
        out.append(("C3", _goto(st, i, "C8" if ok else "CStart"), None))

    elif lbl == "C8":
        cov.hit("C8.g")
        cov.hit("C8.if")
        empty = not fld(pmap_get(st.list_requests, self), "objs")
        cov.hit("C8.then" if empty else "C8.else")
        cov.hit("C8.un")
        out.append(("C8", _goto(st, i, "C4" if empty else "C6"), None))

    elif lbl == "C6":
        objs = sorted(
            fld(pmap_get(st.list_requests, self), "objs"), key=_ckey
        )
        if objs:
            cov.hit("C6.g")
        for s in objs:
            cov.hit("C6.with")
            cov.hit("C6.un")
            target = rec(k=fld(s, "k"), n=fld(s, "n"))
            out.append(("C6", _call_api(st, i, "C7", "Delete", target), None))

    elif lbl == "C7":
        cov.hit("C7.g")
        cov.hit("C7.if")
        cov.hit("C7.o1")
        req = pmap_get(st.requests, self)
        retry = fld(req, "status") != "Ok"
        if not retry:
            cov.hit("C7.o2")
            retry = len(fld(pmap_get(st.list_requests, self), "objs")) > 1
        cov.hit("C7.then" if retry else "C7.else")
        cov.hit("C7.un")
        out.append(("C7", _goto(st, i, "CStart" if retry else "C4"), None))

    elif lbl == "C4":
        cov.hit("C4.g")
        cov.hit("C4.as")
        cov.hit("C4.neg")
        cov.hit("C4.oe")
        exists = _object_exists(cov, "C4.oed", st.api_state, secret)
        viol = "assert:216" if exists else None
        cov.hit("C4.pc")
        cov.hit("C4.un")
        out.append(("C4", _goto(st, i, "C5"), viol))

    elif lbl == "C5":
        cov.hit("C5.g")
        cov.hit("C5.pc")
        cov.hit("C5.un")
        out.append(("C5", _goto(st, i, "CStart"), None))


def _binder(cov, st, cfg, i, self, out) -> None:
    """The PVCController label machine (:655-693) for binder client i."""
    lbl = st.pc[i]

    if lbl == "PVCStart":
        cov.hit("PS.g")
        cov.hit("PS.asg")
        cov.hit("PS.pc")
        cov.hit("PS.un")
        out.append(
            ("PVCStart", _call_listapi(st, i, "PVCListedPVCs", "PVC"), None)
        )

    elif lbl == "PVCListedPVCs":
        cov.hit("PL.g")
        cov.hit("PL.if")
        cov.hit("PL.o1")
        lreq = pmap_get(st.list_requests, self)
        retry = fld(lreq, "status") != "Ok"
        if not retry:
            cov.hit("PL.all")
            cov.hit("PL.all2")
            cov.hit("PL.dom")
            cov.hit("PL.var")
            all_bound = True
            for o in sorted(fld(lreq, "objs"), key=_ckey):
                cov.hit("PL.body")
                cov.hit("PL.arg")
                if _unbound(cov, "PL.ub", o):
                    all_bound = False
                    break  # \A short-circuits on a FALSE body
            retry = all_bound
        cov.hit("PL.then" if retry else "PL.else")
        cov.hit("PL.un")
        out.append(
            ("PVCListedPVCs",
             _goto(st, i, "PVCStart" if retry else "PVCHavePVCs"), None)
        )

    elif lbl == "PVCHavePVCs":
        lreq = pmap_get(st.list_requests, self)
        unbound = sorted(
            (o for o in fld(lreq, "objs")
             if (fld(o, "k") == "PVC"
                 and (not has(o, "spec")
                      or not has(fld(o, "spec"), "pvname")))),
            key=_ckey,
        )
        if unbound:
            cov.hit("PH.g")
        for unb in unbound:
            cov.hit("PH.ex")
            cov.hit("PH.un")
            if not has(unb, "spec"):
                bound = rec_from(unb, spec=rec(pvname=fld(unb, "n")))
            else:
                spec = rec_from(fld(unb, "spec"), pvname=fld(unb, "n"))
                bound = rec_from(unb, spec=spec)
            out.append(
                ("PVCHavePVCs",
                 _call_api(st, i, "PVCDone", "Update", bound), None)
            )

    elif lbl == "PVCDone":
        cov.hit("PD.g")
        cov.hit("PD.pc")
        cov.hit("PD.un")
        out.append(("PVCDone", _goto(st, i, "PVCStart"), None))


def _server(cov, st, cfg, out) -> None:
    """APIStart (:698-756): serve one pending request or one pending list."""
    paths0 = len(out)

    # \E c \in PendingClients (:699-700); the def (:441) is evaluated once
    # per expanded state, its filter predicate once per domain element
    cov.hit("AS.pcref")
    cov.hit("AS.pcdef")
    cov.add_cost("AS.pcdef", 1)
    cov.hit("AS.pcdom")
    pending = []
    for c, req in st.requests:
        cov.hit("AS.pcpred")
        if fld(req, "status") == "Pending":
            pending.append((c, req))
    cov.add_cost("AS.pcdef", len(pending))

    for c, req in pending:
        cov.hit("AS.bind")
        op, robj = fld(req, "op"), fld(req, "obj")
        api, viol = st.api_state, None
        if op == "Create":
            if _exists_ivo(cov, "AS.cr.ex", api, robj):
                cov.hit("AS.cr.err")
                cov.hit("AS.cr.unch")
                new_req = rec_from(req, status="Error")
            else:
                cov.hit("AS.cr.add")
                cov.hit("AS.cr.ok")
                api = api | {rec_from(robj, vv=frozenset())}
                new_req = rec_from(req, status="Ok")
        else:
            cov.hit("AS.fif")
            if op == "Force":
                cov.hit("AS.f.if")
                if _exists_ivo(cov, "AS.f.ex", api, robj):
                    cov.hit("AS.f.set")
                    cov.hit("AS.f.setc")
                    cov.add_cost("AS.f.setc", len(api))
                    new_api = []
                    for o in sorted(api, key=_ckey):
                        cov.hit("AS.f.elif")
                        cov.hit("AS.f.cond")
                        cov.hit("AS.f.co")
                        cov.hit("AS.f.cr")
                        if _ivo(cov, "AS.f.civo", o, robj):
                            cov.hit("AS.f.wr")
                            new_api.append(rec_from(robj, vv=frozenset()))
                        else:
                            cov.hit("AS.f.o")
                            new_api.append(o)
                    cov.hit("AS.f.dom")
                    api = frozenset(new_api)
                else:
                    cov.hit("AS.f.add")
                    api = api | {rec_from(robj, vv=frozenset())}
                new_req = rec_from(req, status="Ok")
                cov.hit("AS.f.ok")
            else:
                cov.hit("AS.gif")
                if op == "Get":
                    cov.hit("AS.g.if")
                    if _exists_ivo(cov, "AS.g.ex", api, robj):
                        # requests' with CHOOSE (:718-720)
                        cov.hit("AS.g.req")
                        cov.hit("AS.g.req2")
                        cov.hit("AS.g.api1")
                        cov.hit("AS.g.cho")
                        cov.hit("AS.g.cho2")
                        matches = []
                        for o in sorted(api, key=_ckey):
                            cov.hit("AS.g.chob")
                            cov.hit("AS.g.choo")
                            cov.hit("AS.g.chor")
                            if _ivo(cov, "AS.g.chivo", o, robj):
                                matches.append(o)
                        cov.hit("AS.g.chod")
                        cov.hit("AS.g.st")
                        chosen = matches[0]
                        new_req = rec_from(req, obj=chosen, status="Ok")
                        # apiState' comprehension (:721-726)
                        cov.hit("AS.g.set")
                        cov.hit("AS.g.setc")
                        cov.add_cost("AS.g.setc", len(api))
                        new_api = []
                        for o in sorted(api, key=_ckey):
                            cov.hit("AS.g.elif")
                            cov.hit("AS.g.cond")
                            cov.hit("AS.g.co")
                            cov.hit("AS.g.cr")
                            if _ivo(cov, "AS.g.civo", o, chosen):
                                cov.hit("AS.g.rd")
                                new_api.append(
                                    rec_from(o, vv=fld(o, "vv") | {c})
                                )
                            else:
                                cov.hit("AS.g.o")
                                new_api.append(o)
                        # the primed requests'[c].obj deref logs one extra
                        # visit per comprehension evaluation (MC.out:779:
                        # 7,860 = 5,240 bindings + 2,620 evals)
                        cov.hit("AS.g.cr", 1)
                        cov.hit("AS.g.dom")
                        api = frozenset(new_api)
                    else:
                        cov.hit("AS.g.err")
                        cov.hit("AS.g.unch")
                        new_req = rec_from(req, status="Error")
                else:
                    cov.hit("AS.dif")
                    if op == "Delete":
                        cov.hit("AS.d.set")
                        cov.hit("AS.d.setc")
                        cov.add_cost("AS.d.setc", len(api))
                        new_api = []
                        for o in sorted(api, key=_ckey):
                            cov.hit("AS.d.neg")
                            cov.hit("AS.d.negi")
                            cov.hit("AS.d.co")
                            cov.hit("AS.d.cr")
                            if not _ivo(cov, "AS.d.ivo", o, robj):
                                new_api.append(o)
                        cov.hit("AS.d.dom")
                        if cfg.mutation != "delete_noop":
                            api = frozenset(new_api)
                        new_req = rec_from(req, status="Ok")
                        cov.hit("AS.d.ok")
                    else:
                        cov.hit("AS.uif")
                        if op == "Update":
                            cov.hit("AS.u.if")
                            cov.hit("AS.u.dom")
                            found = False
                            for o in sorted(api, key=_enum_key):
                                cov.hit("AS.u.body")
                                cov.hit("AS.u.bivoc")
                                cov.hit("AS.u.bo")
                                cov.hit("AS.u.br")
                                if _ivo(cov, "AS.u.bivo", o, robj):
                                    cov.hit("AS.u.hr")
                                    if c in fld(o, "vv"):
                                        found = True
                                        break
                            if found:
                                cov.hit("AS.u.set")
                                cov.hit("AS.u.set2")
                                cov.hit("AS.u.filt")
                                new_api = []
                                for o in sorted(api, key=_ckey):
                                    cov.hit("AS.u.fneg")
                                    cov.hit("AS.u.fnegi")
                                    cov.hit("AS.u.fo")
                                    cov.hit("AS.u.fr")
                                    if not _ivo(cov, "AS.u.fivo", o, robj):
                                        new_api.append(o)
                                cov.hit("AS.u.fdom")
                                cov.hit("AS.u.wr")
                                cov.add_cost("AS.u.wr", 2)
                                api = frozenset(new_api) | {
                                    rec_from(robj, vv=frozenset())
                                }
                                new_req = rec_from(req, status="Ok")
                                cov.hit("AS.u.ok")
                            else:
                                cov.hit("AS.u.err")
                                cov.hit("AS.u.unch")
                                new_req = rec_from(req, status="Error")
                        else:
                            cov.hit("AS.a.as")
                            new_req, viol = req, "assert:348"
        cov.hit("AS.unl")  # UNCHANGED listRequests (:744), per request path
        out.append(
            ("APIStart",
             st._replace(
                 api_state=api, requests=pmap_set(st.requests, c, new_req)),
             viol)
        )

    # \E c \in PendingListClients (:745-753)
    cov.hit("AS.plref")
    cov.hit("AS.pldef")
    cov.add_cost("AS.pldef", 1)
    cov.hit("AS.pldom")
    lpending = []
    for c, lreq in st.list_requests:
        cov.hit("AS.plpred")
        if fld(lreq, "status") == "Pending":
            lpending.append((c, lreq))
    cov.add_cost("AS.pldef", len(lpending))

    for c, lreq in lpending:
        kind = fld(lreq, "kind")
        cov.hit("AS.l.req")
        cov.hit("AS.l.req2")
        cov.hit("AS.l.exc")
        cov.hit("AS.l.objs")
        cov.hit("AS.l.filt")
        cov.add_cost("AS.l.filt", len(st.api_state))
        objs = []
        for o in sorted(st.api_state, key=_ckey):
            cov.hit("AS.l.pred")
            if fld(o, "k") == kind:
                objs.append(o)
        cov.hit("AS.l.fdom")
        cov.hit("AS.l.st")
        new_lreq = rec_from(lreq, objs=frozenset(objs), status="Ok")
        cov.hit("AS.l.set")
        cov.hit("AS.l.setc")
        cov.add_cost("AS.l.setc", len(st.api_state))
        new_api = []
        for o in sorted(st.api_state, key=_ckey):
            cov.hit("AS.l.elif")
            cov.hit("AS.l.cond")
            if fld(o, "k") == kind:
                cov.hit("AS.l.rd")
                new_api.append(rec_from(o, vv=fld(o, "vv") | {c}))
            else:
                cov.hit("AS.l.o")
                new_api.append(o)
        cov.hit("AS.l.dom")
        cov.hit("AS.unr")  # UNCHANGED requests (:754), per list path
        out.append(
            ("APIStart",
             st._replace(
                 api_state=frozenset(new_api),
                 list_requests=pmap_set(st.list_requests, c, new_lreq)),
             None)
        )

    paths = len(out) - paths0
    if paths:
        cov.hit("AS.g")  # fire-entry re-visit
        cov.hit("AS.pc", paths)
        cov.hit("AS.un", paths)


def _invariants(cov, st: State) -> None:
    """TypeOK (:776-781) and OnlyOneVersion (:787-789), once per distinct
    state; quantifier bodies log per-domain-element visits."""
    cov.hit("TY.w")
    cov.hit("TY.c1")
    cov.hit("TY.c1dom")
    for _o in st.api_state:
        cov.hit("TY.c1body")
    cov.hit("TY.c2")
    cov.hit("TY.c2dom")
    for _c, _r in st.requests:
        cov.hit("TY.c2body")
    cov.hit("TY.c3")
    cov.hit("TY.c3dom")
    for _c, lr in st.list_requests:
        cov.hit("TY.c3body")
        cov.hit("TY.vlr")
        cov.hit("TY.vlr1")
        cov.hit("TY.vlr2")
        cov.hit("TY.vlr2q")
        for _o in fld(lr, "objs"):
            cov.hit("TY.vlr2b")
        cov.hit("TY.vlr3")
        cov.hit("TY.vlrarg")
    cov.hit("OV.w")
    cov.hit("OV.dom")
    api = sorted(st.api_state, key=_ckey)
    for o1 in api:
        for o2 in api:
            cov.hit("OV.body")
            cov.hit("OV.o1")
            if o1 != o2:
                cov.hit("OV.o2")


# ---------------------------------------------------------------------------
# The coverage BFS driver
# ---------------------------------------------------------------------------


class CoverageResult:
    def __init__(self, cov, generated, distinct, depth, act_gen, act_dist,
                 n_inits):
        self.cov = cov
        self.generated = generated
        self.distinct = distinct
        self.depth = depth
        self.act_gen = act_gen
        self.act_dist = act_dist
        self.n_inits = n_inits


def run_coverage(cfg: ModelConfig) -> CoverageResult:
    """Exhaustive BFS with the instrumented evaluator."""
    cov = Cov()
    inits = initial_states(cfg)
    # Init conjunct visits: one per conjunct before the shouldReconcile
    # enumeration (:456-465), one per init state after it (:466-469)
    for k in ("I.api", "I.req", "I.lreq", "I.stk", "I.opobj", "I.kind",
              "I.sr"):
        cov.hit(k)
    cov.hit("I.pc", len(inits))
    cov.hit("I.rest", len(inits))

    seen = {}
    frontier: List[State] = []
    for s in inits:
        if s not in seen:
            seen[s] = True
            frontier.append(s)
    generated = len(inits)
    act_gen: Dict[str, int] = defaultdict(int)
    act_dist: Dict[str, int] = defaultdict(int)
    depth = 1
    np_ = cfg.n_clients + 1
    n_recon = cfg.n_reconcilers
    n_bind = cfg.n_clients - n_recon

    while frontier:
        nxt: List[State] = []
        for st in frontier:
            _invariants(cov, st)
            # attempt sweep: every action's pc-guard, per acting binding
            for k in ("DR.g", "DRp.g", "DLR.g", "DLRp.g"):
                cov.hit(k, np_)
            for k in ("DR.gs", "DRp.gs", "DLR.gs", "DLRp.gs"):
                cov.hit(k, np_)
            for k in ("CS", "C1", "C10", "C11", "c12", "C13", "C2", "C3",
                      "C8", "C6", "C7", "C4", "C5"):
                cov.hit(k + ".g", n_recon)
                cov.hit(k + ".gs", n_recon)
            for k in ("PS", "PL", "PH", "PD"):
                cov.hit(k + ".g", n_bind)
                cov.hit(k + ".gs", n_bind)
            cov.hit("AS.g")
            cov.hit("AS.gs")

            out: List[Tuple[str, State, object]] = []
            for i, self in enumerate(cfg.clients):
                if st.pc[i] in ("DoRequest", "DoReply", "DoListRequest",
                                "DoListReply"):
                    _procedures(cov, st, cfg, i, self, out)
                elif cfg.roles[i] == RECONCILER:
                    _client(cov, st, cfg, i, self, out)
                else:
                    _binder(cov, st, cfg, i, self, out)
            _server(cov, st, cfg, out)

            generated += len(out)
            for label, s2, viol in out:
                act_gen[label] += 1
                if s2 not in seen:
                    seen[s2] = True
                    act_dist[label] += 1
                    nxt.append(s2)
        frontier = nxt
        if frontier:
            depth += 1

    return CoverageResult(
        cov, generated, len(seen), depth, dict(act_gen), dict(act_dist),
        len(inits),
    )


# ---------------------------------------------------------------------------
# TLC-format rendering of the per-expression dump (MC.out:44-1092)
# ---------------------------------------------------------------------------


def render_coverage(result: CoverageResult, timestamp: str,
                    tool_mode: bool = True) -> List[str]:
    """Render the dump in TLC's message framing.

    One @!@!@-framed message per line (plain lines with tool_mode=False,
    matching the CLI's -noTool), exactly as TLC's coverage section:
    2201 banner, 2772/2773/2774 action/init/invariant headers,
    2221/2775 span-visit lines (2775 = set-valued cost lines, printed as
    visits:cost).  The span order and message codes come from the
    generated coverage_spans table.  Action headers print this engine's
    `distinct` attribution (TLC's own per-action distinct split is a
    worker-interleaving artifact; `generated` is attribution-free and
    matches exactly - see tests/test_coverage.py).
    """
    from .coverage_spans import MODULE, SPANS

    lines: List[str] = []

    def msg(code: int, body: str) -> None:
        if tool_mode:
            lines.append(f"@!@!@STARTMSG {code}:0 @!@!@")
        lines.append(body)
        if tool_mode:
            lines.append(f"@!@!@ENDMSG {code} @!@!@")

    msg(2201, f"The coverage statistics at {timestamp}")
    for name, code, loc, spans in SPANS:
        if code == 2773:  # Init
            msg(code, f"<{name} {loc} of module {MODULE}>: "
                      f"{result.n_inits}:{result.n_inits}")
        elif code == 2774:  # invariant header (no counts)
            msg(code, f"<{name} {loc} of module {MODULE}>")
        else:  # 2772: action header distinct:generated
            d = result.act_dist.get(name, 0)
            g = result.act_gen.get(name, 0)
            msg(code, f"<{name} {loc} of module {MODULE}>: {d}:{g}")
        for dep, lloc, key, lcode, has_cost, _cexact in spans:
            n = result.cov.n.get(key, 0)
            body = f"  {'|' * dep}{lloc} of module {MODULE}: {n}"
            if has_cost:
                body += f":{result.cov.cost.get(key, 0)}"
            msg(lcode, body)
    return lines
