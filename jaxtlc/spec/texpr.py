"""Trace-expression evaluation (the Toolbox trace-explorer capability).

TLC's trace explorer re-runs a counterexample trace with user-supplied TLA+
expressions evaluated in every state and prints them as extra variables
(the MC_TE.out slot in the reference toolbox,
/root/reference/KubeAPI.toolbox/Model_1/MC_TE.out - the committed instance
is an error-free run, so it carries no expression blocks; the capability is
the per-state re-evaluation itself).  Equivalent here: `jaxtlc check
-traceExpressions FILE` parses one expression per line and the CLI appends
an `/\\ name = value` conjunct per expression to every reconstructed trace
state.

Expression language: the TLA+ subset the spec's state values need -
  * variables (apiState, requests, listRequests, pc, stack, op, obj, kind,
    shouldReconcile), primed variants (`pc'` = value in the NEXT trace
    state; in the final state a prime reads the same state, i.e. the
    trailing stuttering step)
  * literals: integers, strings, TRUE/FALSE, {set, ...}, <<tuple, ...>>,
    [field |-> value, ...] records
  * operators: = # < <= > >= + - .. \\in \\notin \\subseteq \\cup \\cap
    \\ (set difference), /\\ \\/ ~ =>, function application f[x], record
    access r.f, Cardinality(S), Len(t)
  * bounded quantifiers \\A / \\E x \\in S : P, function literals
    [x \\in S |-> e], updates [f EXCEPT ![i] = e, ...] with @, integer
    ranges a..b  (the PlusCal-translation subset - the generic spec
    frontend, jaxtlc.gen, evaluates action bodies with this module)
Not supported (documented scope): CHOOSE, LET, unbounded quantifiers,
recursive operators - finite-state specs can rewrite these by enumeration.

Values use the oracle's canonical Python model (oracle.State docstring):
sets are frozensets, records/functions are key-sorted tuples of pairs,
sequences are tuples - so equality against trace states is exact.
"""

from __future__ import annotations

import re
from typing import List, NamedTuple, Optional, Tuple

from ..config import ModelConfig
from .labels import DEFAULT_INIT
from .oracle import State

# ---------------------------------------------------------------------------
# Tokenizer
# ---------------------------------------------------------------------------

_TOKEN_RE = re.compile(
    r"""
    (?P<ws>\s+)
  | (?P<comment>\\\*.*)
  | (?P<land>/\\)
  | (?P<lor>\\/)
  | (?P<forall>\\A\b)
  | (?P<exists>\\E\b)
  | (?P<op>\\(?:in|notin|subseteq|cup|cap)\b)
  | (?P<setminus>\\)
  | (?P<implies>=>)
  | (?P<mapsto>\|->)
  | (?P<range>\.\.)
  | (?P<le><=)
  | (?P<ge>>=)
  | (?P<ltup><<)
  | (?P<rtup>>>)
  | (?P<eq>=)
  | (?P<ne>\#|/=)
  | (?P<lt><)
  | (?P<gt>>)
  | (?P<num>\d+)
  | (?P<str>"[^"]*")
  | (?P<name>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<sym>[()\[\]{},.~'+\-!@:])
    """,
    re.VERBOSE,
)


def _tokenize(src: str) -> List[Tuple[str, str]]:
    out, pos = [], 0
    while pos < len(src):
        m = _TOKEN_RE.match(src, pos)
        if not m:
            raise TexprError(f"cannot tokenize at: {src[pos:pos + 20]!r}")
        kind = m.lastgroup
        if kind not in ("ws", "comment"):
            out.append((kind, m.group()))
        pos = m.end()
    out.append(("eof", ""))
    return out


class TexprError(ValueError):
    pass


# ---------------------------------------------------------------------------
# AST + parser (precedence climbing; => loosest, then \/, then /\)
# ---------------------------------------------------------------------------


class _Parser:
    def __init__(self, tokens):
        self.toks = tokens
        self.i = 0

    def peek(self):
        return self.toks[self.i]

    def next(self):
        t = self.toks[self.i]
        self.i += 1
        return t

    def expect(self, kind):
        k, v = self.next()
        if k != kind and v != kind:
            raise TexprError(f"expected {kind}, got {v!r}")
        return v

    def parse(self):
        e = self.parse_implies()
        if self.peek()[0] != "eof":
            raise TexprError(f"trailing input at {self.peek()[1]!r}")
        return e

    def parse_implies(self):
        k, _ = self.peek()
        if k in ("forall", "exists"):
            self.next()
            _, var = self.next()
            kk, vv = self.next()
            if (kk, vv) != ("op", r"\in"):
                raise TexprError("expected \\in in quantifier")
            dom = self.parse_setop()
            if self.next() != ("sym", ":"):
                raise TexprError("expected : in quantifier")
            body = self.parse_implies()
            return ("forall" if k == "forall" else "exists", var, dom, body)
        left = self.parse_or()
        if self.peek()[0] == "implies":
            self.next()
            right = self.parse_implies()
            return ("implies", left, right)
        return left

    def parse_or(self):
        left = self.parse_and()
        while self.peek()[0] == "lor":
            self.next()
            left = ("or", left, self.parse_and())
        return left

    def parse_and(self):
        left = self.parse_not()
        while self.peek()[0] == "land":
            self.next()
            left = ("and", left, self.parse_not())
        return left

    def parse_not(self):
        if self.peek() == ("sym", "~"):
            self.next()
            return ("not", self.parse_not())
        return self.parse_cmp()

    _CMP = {"eq": "=", "ne": "#", "lt": "<", "gt": ">", "le": "<=",
            "ge": ">="}

    def parse_cmp(self):
        left = self.parse_setop()
        k, v = self.peek()
        if k in self._CMP:
            self.next()
            return ("cmp", self._CMP[k], left, self.parse_setop())
        if k == "op" and v in (r"\in", r"\notin", r"\subseteq"):
            self.next()
            return ("cmp", v, left, self.parse_setop())
        return left

    def parse_setop(self):
        left = self.parse_range()
        while True:
            k, v = self.peek()
            if k == "op" and v in (r"\cup", r"\cap"):
                self.next()
                left = (v, left, self.parse_range())
            elif k == "setminus":
                self.next()
                left = ("\\", left, self.parse_range())
            else:
                return left

    def parse_range(self):
        # TLA's .. binds looser than +/- (0..N-1 is 0..(N-1))
        left = self.parse_add()
        if self.peek()[0] == "range":
            self.next()
            return ("..", left, self.parse_add())
        return left

    def parse_add(self):
        left = self.parse_postfix()
        while self.peek() in (("sym", "+"), ("sym", "-")):
            _, v = self.next()
            left = (v, left, self.parse_postfix())
        return left

    def parse_postfix(self):
        e = self.parse_atom()
        while True:
            t = self.peek()
            if t == ("sym", "["):
                self.next()
                arg = self.parse_implies()
                self.expect("]")
                e = ("apply", e, arg)
            elif t == ("sym", "."):
                self.next()
                _, fname = self.next()
                e = ("apply", e, ("str", fname))
            elif t == ("sym", "'"):
                self.next()
                if e[0] != "var":
                    raise TexprError("prime (') only applies to variables")
                e = ("var'", e[1])
            else:
                return e

    def parse_atom(self):
        k, v = self.next()
        if k == "num":
            return ("num", int(v))
        if k == "str":
            return ("str", v[1:-1])
        if k == "name":
            if v == "TRUE":
                return ("bool", True)
            if v == "FALSE":
                return ("bool", False)
            if v == "BOOLEAN":
                return ("set", [("bool", False), ("bool", True)])
            if v in ("Cardinality", "Len") and self.peek() == ("sym", "("):
                self.next()
                arg = self.parse_implies()
                self.expect(")")
                return ("call", v, arg)
            return ("var", v)
        if (k, v) == ("sym", "("):
            e = self.parse_implies()
            self.expect(")")
            return e
        if (k, v) == ("sym", "{"):
            items = []
            if self.peek() != ("sym", "}"):
                items.append(self.parse_implies())
                while self.peek() == ("sym", ","):
                    self.next()
                    items.append(self.parse_implies())
            self.expect("}")
            return ("set", items)
        if k == "ltup":
            items = []
            if self.peek()[0] != "rtup":
                items.append(self.parse_implies())
                while self.peek() == ("sym", ","):
                    self.next()
                    items.append(self.parse_implies())
            if self.next()[0] != "rtup":
                raise TexprError("expected >>")
            return ("tuple", items)
        if (k, v) == ("sym", "["):
            # three bracket forms: record [f |-> e, ...], function literal
            # [x \in S |-> e], and update [f EXCEPT ![i] = e, ...]
            save = self.i
            nk, nv = self.next()
            if nk == "name" and self.peek()[0] == "mapsto":
                self.i = save
                return self.parse_record_literal()
            if nk == "name" and self.peek() == ("op", r"\in"):
                self.next()
                dom = self.parse_setop()
                if self.next()[0] != "mapsto":
                    raise TexprError("expected |-> in function literal")
                body = self.parse_implies()
                self.expect("]")
                return ("fnlit", nv, dom, body)
            self.i = save
            fexpr = self.parse_postfix()
            nk, nv = self.next()
            if (nk, nv) != ("name", "EXCEPT"):
                raise TexprError("expected EXCEPT in bracket expression")
            updates = []
            while True:
                if self.next() != ("sym", "!"):
                    raise TexprError("expected ! in EXCEPT")
                idxs = []
                while self.peek() == ("sym", "["):
                    self.next()
                    idxs.append(self.parse_implies())
                    self.expect("]")
                if not idxs:
                    raise TexprError("expected [index] in EXCEPT")
                if self.next()[0] != "eq":
                    raise TexprError("expected = in EXCEPT")
                val = self.parse_implies()
                # multi-index ![i][j] = nested single-index updates
                updates.append((idxs, val))
                nk, nv = self.next()
                if (nk, nv) == ("sym", "]"):
                    break
                if (nk, nv) != ("sym", ","):
                    raise TexprError("expected , or ] in EXCEPT")
            return ("except", fexpr, updates)
        if (k, v) == ("sym", "@"):
            return ("atref",)
        raise TexprError(f"unexpected token {v!r}")

    def parse_record_literal(self):
        fields = []
        while True:
            _, fname = self.next()
            if self.next()[0] != "mapsto":
                raise TexprError("expected |-> in record literal")
            fields.append((fname, self.parse_implies()))
            nk, nv = self.next()
            if (nk, nv) == ("sym", "]"):
                break
            if (nk, nv) != ("sym", ","):
                raise TexprError("expected , or ] in record literal")
        return ("record", fields)


def parse(src: str):
    return _Parser(_tokenize(src)).parse()


# ---------------------------------------------------------------------------
# Evaluation
# ---------------------------------------------------------------------------


def canon(v):
    """Canonicalize to the oracle's value model (pair-records key-sorted)."""
    if isinstance(v, tuple) and v and all(
        isinstance(x, tuple) and len(x) == 2 and isinstance(x[0], str)
        for x in v
    ):
        return tuple(sorted((k, canon(x)) for k, x in v))
    if isinstance(v, tuple):
        return tuple(canon(x) for x in v)
    if isinstance(v, frozenset):
        return frozenset(canon(x) for x in v)
    return v


def state_env(st: State, cfg: ModelConfig) -> dict:
    """Variable environment of a decoded oracle state (TLA names)."""
    procs = cfg.processes
    reconcilers = [cfg.clients[i] for i in cfg.reconciler_indices]

    def fn(values):
        return tuple(sorted(zip(procs, (canon(x) for x in values))))

    return {
        "apiState": canon(st.api_state),
        "requests": canon(st.requests),
        "listRequests": canon(st.list_requests),
        "pc": fn(st.pc),
        "stack": fn(tuple(tuple(fr for fr in s) for s in st.stack)),
        "op": fn(st.op),
        "obj": fn(st.obj),
        "kind": fn(st.kind),
        "shouldReconcile": tuple(
            sorted(zip(reconcilers, st.should_reconcile))
        ),
        "defaultInitValue": DEFAULT_INIT,
    }


def _apply(f, arg):
    if isinstance(f, tuple):
        # string keys distinguish records/functions from sequences of
        # pairs (same convention as canon; a 2-field record inside a
        # sequence must NOT make the sequence look like a function)
        if f and all(
            isinstance(x, tuple) and len(x) == 2 and isinstance(x[0], str)
            for x in f
        ):
            for k, val in f:
                if k == arg:
                    return val
            raise TexprError(f"{arg!r} not in function domain")
        if isinstance(arg, int) and 1 <= arg <= len(f):
            return f[arg - 1]  # sequences are 1-indexed
        raise TexprError(f"index {arg!r} out of sequence range")
    raise TexprError(f"cannot apply non-function {f!r}")


def evaluate(ast, env: dict, env_next: Optional[dict] = None):
    """Evaluate over a state env (and the next state's, for primes)."""
    op = ast[0]
    if op in ("num", "str", "bool"):
        return ast[1]
    if op == "var":
        if ast[1] not in env:
            raise TexprError(f"unknown variable {ast[1]!r}")
        return env[ast[1]]
    if op == "var'":
        e2 = env_next if env_next is not None else env
        if ast[1] not in e2:
            raise TexprError(f"unknown variable {ast[1]!r}")
        return e2[ast[1]]
    if op == "set":
        return frozenset(evaluate(x, env, env_next) for x in ast[1])
    if op == "tuple":
        return tuple(evaluate(x, env, env_next) for x in ast[1])
    if op == "record":
        return canon(
            tuple((k, evaluate(x, env, env_next)) for k, x in ast[1])
        )
    if op == "apply":
        return _apply(
            evaluate(ast[1], env, env_next), evaluate(ast[2], env, env_next)
        )
    if op == "call":
        v = evaluate(ast[2], env, env_next)
        if ast[1] == "Cardinality":
            if not isinstance(v, frozenset):
                raise TexprError("Cardinality expects a set")
            return len(v)
        if not isinstance(v, tuple):
            raise TexprError("Len expects a sequence")
        return len(v)
    if op == "not":
        return not _as_bool(evaluate(ast[1], env, env_next))
    if op == "and":
        return _as_bool(evaluate(ast[1], env, env_next)) and _as_bool(
            evaluate(ast[2], env, env_next)
        )
    if op == "or":
        return _as_bool(evaluate(ast[1], env, env_next)) or _as_bool(
            evaluate(ast[2], env, env_next)
        )
    if op == "implies":
        return (not _as_bool(evaluate(ast[1], env, env_next))) or _as_bool(
            evaluate(ast[2], env, env_next)
        )
    if op in ("+", "-"):
        a = evaluate(ast[1], env, env_next)
        b = evaluate(ast[2], env, env_next)
        return a + b if op == "+" else a - b
    if op in (r"\cup", r"\cap", "\\"):
        a = evaluate(ast[1], env, env_next)
        b = evaluate(ast[2], env, env_next)
        if not (isinstance(a, frozenset) and isinstance(b, frozenset)):
            raise TexprError(f"{op} expects sets")
        return {r"\cup": a | b, r"\cap": a & b, "\\": a - b}[op]
    if op in ("forall", "exists"):
        _, var, dom_ast, body = ast
        dom = evaluate(dom_ast, env, env_next)
        if not isinstance(dom, frozenset):
            raise TexprError("quantifier domain must be a set")
        vals = []
        for x in sorted(dom, key=repr):
            e2 = dict(env)
            e2[var] = x
            en2 = dict(env_next, **{var: x}) if env_next is not None else None
            vals.append(_as_bool(evaluate(body, e2, en2)))
        return all(vals) if op == "forall" else any(vals)
    if op == "..":
        a = evaluate(ast[1], env, env_next)
        b = evaluate(ast[2], env, env_next)
        if not (isinstance(a, int) and isinstance(b, int)):
            raise TexprError(".. expects integers")
        return frozenset(range(a, b + 1))
    if op == "fnlit":
        _, var, dom_ast, body = ast
        dom = evaluate(dom_ast, env, env_next)
        if not isinstance(dom, frozenset):
            raise TexprError("function domain must be a set")
        pairs = []
        for x in sorted(dom, key=repr):
            e2 = dict(env)
            e2[var] = x
            en2 = dict(env_next, **{var: x}) if env_next is not None else None
            pairs.append((x, evaluate(body, e2, en2)))
        if all(isinstance(x, str) for x, _ in pairs):
            return tuple(sorted(pairs))
        if set(x for x, _ in pairs) == set(range(1, len(pairs) + 1)):
            return tuple(v for _, v in sorted(pairs))  # 1..n -> sequence
        raise TexprError("function domain must be strings or 1..n")
    if op == "except":
        f = evaluate(ast[1], env, env_next)
        for idxs_ast, val_ast in ast[2]:
            idxs = [evaluate(i, env, env_next) for i in idxs_ast]
            f = _except_update(f, idxs, val_ast, env, env_next)
        return f
    if op == "atref":
        if "@" not in env:
            raise TexprError("@ outside EXCEPT")
        return env["@"]
    if op == "cmp":
        sym = ast[1]
        a = evaluate(ast[2], env, env_next)
        b = evaluate(ast[3], env, env_next)
        if sym == "=":
            return a == b
        if sym == "#":
            return a != b
        if sym == r"\in":
            return a in b
        if sym == r"\notin":
            return a not in b
        if sym == r"\subseteq":
            return a <= b
        return {"<": a < b, ">": a > b, "<=": a <= b, ">=": a >= b}[sym]
    raise TexprError(f"unhandled AST node {op!r}")


def _except_update(f, idxs, val_ast, env, env_next):
    """[f EXCEPT ![i1][i2]... = val]: nested single-level updates; @ in
    val reads the innermost old value."""
    idx = idxs[0]
    old = _apply(f, idx)
    if len(idxs) > 1:
        val = _except_update(old, idxs[1:], val_ast, env, env_next)
    else:
        e2 = dict(env)
        e2["@"] = old
        en2 = (dict(env_next, **{"@": old})
               if env_next is not None else None)
        val = evaluate(val_ast, e2, en2)
    if isinstance(f, tuple) and f and all(
        isinstance(x, tuple) and len(x) == 2 and isinstance(x[0], str)
        for x in f
    ):
        return tuple(sorted(((k, val if k == idx else v) for k, v in f)))
    if isinstance(f, tuple) and isinstance(idx, int):
        return f[: idx - 1] + (val,) + f[idx:]
    raise TexprError("EXCEPT on a non-function")


def _as_bool(v):
    if not isinstance(v, bool):
        raise TexprError(f"expected BOOLEAN, got {v!r}")
    return v


# ---------------------------------------------------------------------------
# Expression files + trace evaluation
# ---------------------------------------------------------------------------


class TraceExpression(NamedTuple):
    name: str  # display name (Toolbox uses the expression text itself)
    ast: tuple


def parse_expressions(text: str) -> List[TraceExpression]:
    """One expression per line; `Name == Expr` names it, `\\* ...` comments
    and blank lines are skipped (the Toolbox trace-expression pane model)."""
    out = []
    for ln in text.splitlines():
        ln = ln.strip()
        if not ln or ln.startswith("\\*"):
            continue
        m = re.match(r"^([A-Za-z_][A-Za-z0-9_]*)\s*==\s*(.+)$", ln)
        name, src = (m.group(1), m.group(2)) if m else (ln, ln)
        out.append(TraceExpression(name, parse(src)))
    return out


class ExprResult(NamedTuple):
    name: str
    value: object  # evaluated value, or the error message when failed
    failed: bool


def eval_over_envs(
    exprs: List[TraceExpression],
    envs: List[dict],
) -> List[List[ExprResult]]:
    """Per trace state env: [ExprResult(name, value, failed), ...].

    Primed variables in state i read state i+1; the final state reads
    itself (the trailing stuttering step, TLC's convention for the last
    state of a finite trace).  Evaluation failures (including Python-level
    type errors from mis-typed expressions, e.g. `pc["Client"] < 3`)
    degrade to a failed ExprResult carrying the message - one bad
    expression never loses the trace.  Spec-agnostic: the KubeAPI path
    builds envs with state_env, the generic frontend with
    gen.oracle.state_env."""
    rows = []
    for i, env in enumerate(envs):
        env_next = envs[i + 1] if i + 1 < len(envs) else env
        row = []
        for ex in exprs:
            try:
                row.append(
                    ExprResult(ex.name, evaluate(ex.ast, env, env_next), False)
                )
            except (TexprError, TypeError, KeyError, IndexError) as e:
                row.append(ExprResult(ex.name, str(e) or type(e).__name__,
                                      True))
        rows.append(row)
    return rows


def eval_over_trace(
    exprs: List[TraceExpression],
    trace: List[Tuple[State, Optional[str]]],
    cfg: ModelConfig,
) -> List[List[ExprResult]]:
    """eval_over_envs over a KubeAPI-oracle trace."""
    return eval_over_envs(exprs, [state_env(st, cfg) for st, _ in trace])
