"""KubeAPI device coverage plane: TLC's span counters, on the chip.

The host coverage walker (spec.coverage) reproduces the reference
MC.out per-expression dump EXACTLY by re-walking the whole state space
a third time with an instrumented evaluator.  This module moves the
deterministic part of that accounting INTO the compiled kernels: every
span whose visit count is a pure function of per-state facts the codec
already holds - label occupancy, request/list statuses, apiState
membership, version-vector bits, shouldReconcile - becomes a device
site whose per-block increment is computed alongside the vmapped step
and accumulated in the carry's cumulative coverage tensor.  The tracked
table is pinned SITE-FOR-SITE against the host walker on the FF corner
in tier-1 (tests/test_coverage_device.py) and against the Model_1 walk
in the slow suite.

What stays host-only (tracked=False, by design not omission): spans
inside SHORT-CIRCUITING enumerations whose visit count depends on
TLC's element iteration order mid-scan (`\\E o \\in apiState` existence
probes, the PVCListedPVCs `\\A` body, the Update `\\E` body).  Every
non-short-circuiting enumeration (set comprehensions, the Get CHOOSE,
the Delete filter) IS tracked - their loops visit every element, so the
counts are sums over apiState the device computes exactly, including
the IsVersionOf short-circuit structure via name/kind-equality tables.

Site keys are the span keys spec/coverage_spans.py pins, so the device
counters, the host walker and the committed MC.out all speak one
vocabulary; render through obs.coverage.render_site_dump or diff with
tools/covdiff.py.
"""

from __future__ import annotations

from typing import Dict, List

import numpy as np

from ..config import RECONCILER, ModelConfig
from ..obs.coverage import CoveragePlane, Site
from .codec import get_codec
from .labels import LABEL_ID, LABELS, VERB_ID

# reconciler-machine labels (the Client label machine CStart..C5) and
# binder-machine labels, in walker order
_RECON_LABELS = ("CStart", "C1", "C10", "C11", "c12", "C13", "C2",
                 "C3", "C8", "C6", "C7", "C4", "C5")
_BINDER_LABELS = ("PVCStart", "PVCListedPVCs", "PVCHavePVCs", "PVCDone")
_PROC_LABELS = ("DoRequest", "DoReply", "DoListRequest", "DoListReply")
_PROC_KEY = {"DoRequest": "DR", "DoReply": "DRp",
             "DoListRequest": "DLR", "DoListReply": "DLRp"}
_RECON_KEY = {lbl: lbl for lbl in _RECON_LABELS}
_RECON_KEY["CStart"] = "CS"
_BINDER_KEY = {"PVCStart": "PS", "PVCListedPVCs": "PL",
               "PVCHavePVCs": "PH", "PVCDone": "PD"}


def _span_locs() -> Dict[str, str]:
    """span key -> source loc from the generated span table (KubeAPI
    only; other configs render the key)."""
    try:
        from .coverage_spans import SPANS
    except ImportError:  # pragma: no cover
        return {}
    out: Dict[str, str] = {}
    for _name, _code, _loc, lines in SPANS:
        for _dep, loc, key, _lcode, _hc, _ce in lines:
            out.setdefault(key, loc)
    return out


def kubeapi_coverage_plane(cfg: ModelConfig) -> CoveragePlane:
    """Build the device coverage plane for one KubeAPI configuration.

    The site table opens with one "action" site per label (the
    per-action generated counts - the PR 3 coverage lines are a prefix
    view), followed by the tracked span-key sites.  count() computes
    every increment from the popped batch's decoded fields + lane
    validity - no extra kernel work, no host sync."""
    import jax.numpy as jnp

    cdc = get_codec(cfg)
    nc, ni, ls, nr = cdc.nc, cdc.ni, cdc.ls, cdc.nr
    np_procs = nc + 1
    n_bind = nc - nr

    api_off = cdc.offsets["api"]
    req_off = cdc.offsets["req"]
    lm_off = cdc.offsets["lreq_meta"]
    lo_off = cdc.offsets["lreq_obj"]
    pc_off = cdc.offsets["pc"]
    sr_off = cdc.offsets["sr"]

    imask = (1 << cdc.ib) - 1

    # identity tables: name/kind equality + (name, kind) strict order
    # (the _enum_key scan position of _object_exists) + PVC-kind flags
    names = [n for _, n in cfg.identities]
    kinds = [k for k, _ in cfg.identities]
    NEQ = np.asarray([[a == b for b in names] for a in names])
    KEQ = np.asarray([[a == b for b in kinds] for a in kinds])
    NKEQ = NEQ & KEQ
    LT = np.asarray([
        [(na, ka) < (nb, kb)
         for nb, kb in zip(names, kinds)]
        for na, ka in zip(names, kinds)
    ])
    IS_PVC = np.asarray([k == "PVC" for k in kinds])
    KIND_ID = np.asarray([cdc.kind_id[k] for k in kinds], np.int32)

    fail_t = int(cfg.requests_can_fail) + int(cfg.requests_can_timeout)
    timeout = int(cfg.requests_can_timeout)

    # ------------------------------------------------------------------
    # the tracked-site registry: (key, action, fn) where fn(ctx) is a
    # per-state [ck] int32 contribution or an int constant-per-state
    # ------------------------------------------------------------------
    entries: List[tuple] = []

    def site(key, action, fn):
        entries.append((key, action, fn))

    # ---- context builder -------------------------------------------------

    def build_ctx(batch):
        ctx = {}
        aw = batch[:, api_off:api_off + ni]
        ctx["api_present"] = ((aw >> cdc.o_present) & 1).astype(bool)
        ctx["api_ident"] = (aw >> cdc.o_ident) & imask
        ctx["api_vv"] = aw  # vv bit c of slot: (aw >> (o_vv + c)) & 1
        ctx["api_n"] = ctx["api_present"].sum(axis=1)

        ctx["req_w"] = batch[:, req_off:req_off + nc]
        ctx["lm_w"] = batch[:, lm_off:lm_off + nc]
        ctx["pc"] = batch[:, pc_off:pc_off + nc + 1]
        ctx["sr"] = batch[:, sr_off:sr_off + nr]
        ctx["lo_w"] = batch[:, lo_off:lo_off + nc * ls]
        return ctx

    def _memo(ctx, key, fn):
        """Emit a shared subexpression into the block's graph ONCE:
        the ~300 site formulas lean on a few dozen leaf vectors, and
        the CPU backend pays per-op dispatch, so deduplication at
        trace time (not XLA CSE) is what keeps the hook cheap."""
        v = ctx.get(key)
        if v is None:
            v = fn()
            ctx[key] = v
        return v

    def req_present(ctx, i):
        return _memo(ctx, ("rp", i), lambda: (
            (ctx["req_w"][:, i] >> cdc.r_present) & 1).astype(bool))

    def req_status(ctx, i):
        return _memo(ctx, ("rs", i), lambda: (
            ctx["req_w"][:, i] >> cdc.r_status) & 3)

    def req_op(ctx, i):
        return _memo(ctx, ("ro", i), lambda: (
            ctx["req_w"][:, i] >> cdc.r_op) & 7)

    def req_obj_ident(ctx, i):
        return _memo(ctx, ("roi", i), lambda: (
            (ctx["req_w"][:, i] >> cdc.r_obj) >> cdc.o_ident) & imask)

    def req_obj_has_spec(ctx, i):
        return _memo(ctx, ("rospec", i), lambda: (
            ((ctx["req_w"][:, i] >> cdc.r_obj) >> cdc.o_spec) & 1
        ).astype(bool))

    def lm_present(ctx, i):
        return _memo(ctx, ("lmp", i), lambda: (
            (ctx["lm_w"][:, i] >> cdc.lm_present) & 1).astype(bool))

    def lm_status(ctx, i):
        return _memo(ctx, ("lms", i), lambda: (
            ctx["lm_w"][:, i] >> cdc.lm_status) & 3)

    def lm_kind(ctx, i):
        return _memo(ctx, ("lmk", i), lambda: (
            ctx["lm_w"][:, i] >> cdc.lm_kind) & ((1 << cdc.kb) - 1))

    def lobj_present(ctx, i, s):
        return _memo(ctx, ("lop", i, s), lambda: (
            (ctx["lo_w"][:, i * ls + s] >> cdc.o_present) & 1
        ).astype(bool))

    def lobj_has_spec(ctx, i, s):
        return _memo(ctx, ("lospec", i, s), lambda: (
            (ctx["lo_w"][:, i * ls + s] >> cdc.o_spec) & 1
        ).astype(bool))

    def occ(ctx, i, label):
        return _memo(ctx, ("occ", i, label), lambda: (
            ctx["pc"][:, i] == LABEL_ID[label]).astype(jnp.int32))

    # matches of obj-ident t over the api slots, per state
    def api_count(ctx, pred_table, ident, key=None):
        """Sum over api slots of present & pred_table[slot_ident,
        ident] (pred_table [ni, ni]); memoized under `key`."""
        def build():
            t = jnp.asarray(pred_table)
            per = t[ctx["api_ident"], ident[:, None]]
            return (per & ctx["api_present"]).sum(axis=1).astype(
                jnp.int32)
        if key is None:
            return build()
        return _memo(ctx, ("apic",) + key, build)

    # ---- procedure labels (DR/DRp/DLR/DLRp) ------------------------------

    def proc_occ(ctx, label):
        def build():
            out = 0
            for i in range(nc):
                out = out + occ(ctx, i, label)
            return out
        return _memo(ctx, ("proc_occ", label), build)

    def ready_count(ctx, label, status_fn):
        def build():
            out = 0
            for i in range(nc):
                out = out + occ(ctx, i, label) * (
                    status_fn(ctx, i) != 0
                ).astype(jnp.int32)
            return out
        return _memo(ctx, ("ready", label), build)

    def _mk_proc_sites():
        # DoRequest / DoListRequest: fire whenever occupied; paths =
        # 1 + fail + timeout per firing
        for label, meta_s in (("DoRequest", "b"), ("DoListRequest", "b")):
            k = _PROC_KEY[label]
            fire = (lambda c, lb=label: proc_occ(c, lb))
            site(f"{k}.g", label,
                 lambda c, f=fire: np_procs + f(c))
            site(f"{k}.gs", label, np_procs)
            site(f"{k}.b1", label, fire)
            site(f"{k}.b2g", label, fire)
            site(f"{k}.b2b", label,
                 lambda c, f=fire: f(c) * fail_t)
            paths = (lambda c, f=fire: f(c) * (1 + fail_t))
            site(f"{k}.pc", label, paths)
            site(f"{k}.un", label, paths)
        # DoReply / DoListReply: await logs occupancy + fire re-visit,
        # fire iff the (list) request is no longer Pending; paths =
        # 1 + timeout per firing
        for label, st_fn in (("DoReply", req_status),
                             ("DoListReply", lm_status)):
            k = _PROC_KEY[label]
            o = (lambda c, lb=label: proc_occ(c, lb))
            fire = (lambda c, lb=label, sf=st_fn:
                    ready_count(c, lb, sf))
            site(f"{k}.g", label,
                 lambda c, f=fire: np_procs + f(c))
            site(f"{k}.gs", label, np_procs)
            site(f"{k}.aw", label,
                 lambda c, oc=o, f=fire: oc(c) + f(c))
            site(f"{k}.aws", label, o)
            site(f"{k}.b1g", label, fire)
            site(f"{k}.b1b", label, fire)
            site(f"{k}.b2", label, fire)
            paths = (lambda c, f=fire: f(c) * (1 + timeout))
            for sub in (("pc", "op", "obj", "st", "un")
                        if label == "DoReply"
                        else ("pc", "kind", "st", "un")):
                site(f"{k}.{sub}", label, paths)

    _mk_proc_sites()

    # ---- reconciler client machine ---------------------------------------

    recon = [(i, cfg.sr_index(i), cfg.targets[i])
             for i, r in enumerate(cfg.roles) if r == RECONCILER]

    _rsum_n = [0]

    def rsum(fn):
        """Sum fn(ctx, i, ri, (si, pi)) over reconciler clients;
        the summed vector is memoized per closure so sites sharing an
        aggregate emit it once."""
        _rsum_n[0] += 1
        key = ("rsum", _rsum_n[0])

        def out(ctx):
            def build():
                acc = 0
                for i, ri, tg in recon:
                    acc = acc + fn(ctx, i, ri, tg)
                return acc
            return _memo(ctx, key, build)
        return out

    def _attempt(key, label, fire_fn):
        site(f"{key}.g", label,
             lambda c, f=fire_fn: nr + f(c))
        site(f"{key}.gs", label, nr)

    def _mk_recon_sites():
        # CStart: two either-paths per firing; branch by shouldReconcile
        o_cs = rsum(lambda c, i, ri, tg: occ(c, i, "CStart"))
        _attempt("CS", "CStart", o_cs)
        for sub in ("b1", "b2g", "b2b"):
            site(f"CS.{sub}", "CStart", o_cs)
        site("CS.if", "CStart", lambda c: 2 * o_cs(c))
        site("CS.un", "CStart", lambda c: 2 * o_cs(c))
        site("CS.then", "CStart", rsum(
            lambda c, i, ri, tg:
            occ(c, i, "CStart") * (1 + c["sr"][:, ri])))
        cs_else = rsum(
            lambda c, i, ri, tg:
            occ(c, i, "CStart") * (1 - c["sr"][:, ri]))
        site("CS.else", "CStart", cs_else)
        site("CS.epc", "CStart", cs_else)
        site("CS.eun", "CStart", cs_else)

        # request-status IF labels: C1/C11 (then = not-Ok), C3 on list
        for label, key, st_fn in (("C1", "C1", req_status),
                                  ("C11", "C11", req_status),
                                  ("C3", "C3", lm_status)):
            o = rsum(lambda c, i, ri, tg, lb=label: occ(c, i, lb))
            ok = rsum(lambda c, i, ri, tg, lb=label, sf=st_fn:
                      occ(c, i, lb) * (sf(c, i) == 1).astype(jnp.int32))
            _attempt(key, label, o)
            site(f"{key}.if", label, o)
            site(f"{key}.then", label, lambda c, oc=o, okc=ok:
             oc(c) - okc(c))
            site(f"{key}.else", label, ok)
            site(f"{key}.un", label, o)

        # straight-line labels
        for label, key, subs in (
            ("C10", "C10", ("asg", "pc", "un")),
            ("c12", "c12", ("asg", "pc", "un")),
            ("C2", "C2", ("sr", "as", "pc", "un")),
            ("C5", "C5", ("pc", "un")),
        ):
            o = rsum(lambda c, i, ri, tg, lb=label: occ(c, i, lb))
            _attempt(key, label, o)
            for sub in subs:
                site(f"{key}.{sub}", label, o)

        # C13: Get reply triage through IsUnboundPVC
        o13 = rsum(lambda c, i, ri, tg: occ(c, i, "C13"))
        ok13 = rsum(lambda c, i, ri, tg:
                    occ(c, i, "C13")
                    * (req_status(c, i) == 1).astype(jnp.int32))
        _attempt("C13", "C13", o13)
        site("C13.if", "C13", o13)
        site("C13.o1", "C13", o13)
        site("C13.o2", "C13", ok13)
        site("C13.ubarg", "C13", ok13)
        site("C13.ub.w", "C13", ok13)
        site("C13.ub.k", "C13", ok13)

        def _c13(fn):
            return rsum(lambda c, i, ri, tg:
                        occ(c, i, "C13")
                        * (req_status(c, i) == 1).astype(jnp.int32)
                        * fn(c, i))

        is_pvc_t = jnp.asarray(IS_PVC)
        ub_or = _c13(lambda c, i:
                     is_pvc_t[req_obj_ident(c, i)].astype(jnp.int32))
        site("C13.ub.or", "C13", ub_or)
        site("C13.ub.o1", "C13", ub_or)
        site("C13.ub.o2", "C13", _c13(
            lambda c, i: (is_pvc_t[req_obj_ident(c, i)]
                          & req_obj_has_spec(c, i)).astype(jnp.int32)))
        unbound = lambda c, i: (  # noqa: E731
            is_pvc_t[req_obj_ident(c, i)]
            & ~req_obj_has_spec(c, i)).astype(jnp.int32)
        bad13 = rsum(lambda c, i, ri, tg:
                     occ(c, i, "C13") * jnp.where(
                         req_status(c, i) == 1, unbound(c, i), 1))
        site("C13.then", "C13", bad13)
        site("C13.else", "C13", lambda c: o13(c) - bad13(c))
        site("C13.un", "C13", o13)

        # C8: branch on whether the listed object set is empty
        o8 = rsum(lambda c, i, ri, tg: occ(c, i, "C8"))
        def _nobjs(c, i):
            def build():
                n = 0
                for s in range(ls):
                    n = n + lobj_present(c, i, s).astype(jnp.int32)
                return n
            return _memo(c, ("nobjs", i), build)
        empty8 = rsum(lambda c, i, ri, tg:
                      occ(c, i, "C8")
                      * (_nobjs(c, i) == 0).astype(jnp.int32))
        _attempt("C8", "C8", o8)
        site("C8.if", "C8", o8)
        site("C8.then", "C8", empty8)
        site("C8.else", "C8", lambda c: o8(c) - empty8(c))
        site("C8.un", "C8", o8)

        # C6: one `with` path per listed object; fire-entry re-visit
        # only when the list is nonempty
        o6ne = rsum(lambda c, i, ri, tg:
                    occ(c, i, "C6")
                    * (_nobjs(c, i) > 0).astype(jnp.int32))
        site("C6.g", "C6", lambda c: nr + o6ne(c))
        site("C6.gs", "C6", nr)
        paths6 = rsum(lambda c, i, ri, tg: occ(c, i, "C6") * _nobjs(c, i))
        site("C6.with", "C6", paths6)
        site("C6.un", "C6", paths6)

        # C7: retry unless the delete succeeded AND one object remains
        o7 = rsum(lambda c, i, ri, tg: occ(c, i, "C7"))
        ok7 = rsum(lambda c, i, ri, tg:
                   occ(c, i, "C7")
                   * (req_status(c, i) == 1).astype(jnp.int32))
        _attempt("C7", "C7", o7)
        site("C7.if", "C7", o7)
        site("C7.o1", "C7", o7)
        site("C7.o2", "C7", ok7)
        retry7 = rsum(lambda c, i, ri, tg:
                      occ(c, i, "C7") * jnp.where(
                          req_status(c, i) == 1,
                          (_nobjs(c, i) > 1).astype(jnp.int32), 1))
        site("C7.then", "C7", retry7)
        site("C7.else", "C7", lambda c: o7(c) - retry7(c))
        site("C7.un", "C7", o7)

        # C4: the ObjectExists scan - position of the first (n, k)
        # match in the walker's sorted enumeration, or |api| when none
        o4 = rsum(lambda c, i, ri, tg: occ(c, i, "C4"))
        _attempt("C4", "C4", o4)
        for sub in ("as", "neg", "oe", "pc", "un"):
            site(f"C4.{sub}", "C4", o4)
        site("C4.oed.w", "C4", o4)
        site("C4.oed.dom", "C4", o4)

        def _oed_iters(c, i, si):
            tgt = jnp.full(c["api_n"].shape, si, jnp.int32)
            match = api_count(c, NKEQ, tgt)
            less = api_count(c, LT, tgt)  # slots with (n,k) < target
            return jnp.where(match > 0, less + 1, c["api_n"])

        oed = rsum(lambda c, i, ri, tg:
                   occ(c, i, "C4") * _oed_iters(c, i, tg[0]))
        site("C4.oed.body", "C4", oed)
        site("C4.oed.arg", "C4", oed)

    _mk_recon_sites()

    # ---- binder machine --------------------------------------------------

    binders = [i for i, r in enumerate(cfg.roles) if r != RECONCILER]

    _bsum_n = [0]

    def bsum(fn):
        _bsum_n[0] += 1
        key = ("bsum", _bsum_n[0])

        def out(ctx):
            def build():
                acc = 0
                for i in binders:
                    acc = acc + fn(ctx, i)
                return acc
            return _memo(ctx, key, build)
        return out

    def _battempt(key, label, fire_fn):
        site(f"{key}.g", label,
             lambda c, f=fire_fn: n_bind + f(c))
        site(f"{key}.gs", label, n_bind)

    def _mk_binder_sites():
        for label, key, subs in (("PVCStart", "PS", ("asg", "pc", "un")),
                                 ("PVCDone", "PD", ("pc", "un"))):
            o = bsum(lambda c, i, lb=label: occ(c, i, lb))
            _battempt(key, label, o)
            for sub in subs:
                site(f"{key}.{sub}", label, o)

        # PVCListedPVCs: retry on list failure OR everything bound
        opl = bsum(lambda c, i: occ(c, i, "PVCListedPVCs"))
        okpl = bsum(lambda c, i:
                    occ(c, i, "PVCListedPVCs")
                    * (lm_status(c, i) == 1).astype(jnp.int32))
        _battempt("PL", "PVCListedPVCs", opl)
        site("PL.if", "PVCListedPVCs", opl)
        site("PL.o1", "PVCListedPVCs", opl)
        for sub in ("all", "all2", "dom", "var"):
            site(f"PL.{sub}", "PVCListedPVCs", okpl)

        def _any_unbound(c, i):
            any_u = jnp.zeros(c["api_n"].shape, bool)
            for s in range(ls):
                any_u = any_u | (lobj_present(c, i, s)
                                 & ~lobj_has_spec(c, i, s))
            return any_u

        retry_pl = bsum(lambda c, i:
                        occ(c, i, "PVCListedPVCs") * jnp.where(
                            lm_status(c, i) == 1,
                            (~_any_unbound(c, i)).astype(jnp.int32), 1))
        site("PL.then", "PVCListedPVCs", retry_pl)
        site("PL.else", "PVCListedPVCs",
             lambda c: opl(c) - retry_pl(c))
        site("PL.un", "PVCListedPVCs", opl)

        # PVCHavePVCs: one \E path per unbound listed PVC
        def _n_unbound(c, i):
            n = 0
            for s in range(ls):
                n = n + (lobj_present(c, i, s)
                         & ~lobj_has_spec(c, i, s)).astype(jnp.int32)
            return n

        ph_ne = bsum(lambda c, i:
                     occ(c, i, "PVCHavePVCs")
                     * (_n_unbound(c, i) > 0).astype(jnp.int32))
        site("PH.g", "PVCHavePVCs",
             lambda c: n_bind + ph_ne(c))
        site("PH.gs", "PVCHavePVCs", n_bind)
        ph_paths = bsum(lambda c, i:
                        occ(c, i, "PVCHavePVCs") * _n_unbound(c, i))
        site("PH.ex", "PVCHavePVCs", ph_paths)
        site("PH.un", "PVCHavePVCs", ph_paths)

    _mk_binder_sites()

    # ---- the API server --------------------------------------------------

    def _pending(c, i):
        return (req_present(c, i)
                & (req_status(c, i) == 0)).astype(jnp.int32)

    def _lpending(c, i):
        return (lm_present(c, i)
                & (lm_status(c, i) == 0)).astype(jnp.int32)

    def _op_is(c, i, verb):
        return (_pending(c, i)
                * (req_op(c, i) == VERB_ID[verb]).astype(jnp.int32))

    _csum_n = [0]

    def csum(fn):
        _csum_n[0] += 1
        key = ("csum", _csum_n[0])

        def out(ctx):
            def build():
                acc = 0
                for i in range(nc):
                    acc = acc + fn(ctx, i)
                return acc
            return _memo(ctx, key, build)
        return out

    def _mk_server_sites():
        pend = csum(_pending)
        lpend = csum(_lpending)
        paths = lambda c: pend(c) + lpend(c)  # noqa: E731
        fires = lambda c: (paths(c) > 0).astype(jnp.int32)  # noqa: E731
        site("AS.g", "APIStart", lambda c: 1 + fires(c))
        site("AS.gs", "APIStart", 1)
        for sub in ("pcref", "pcdef", "pcdom"):
            site(f"AS.{sub}", "APIStart", 1)
        site("AS.pcpred", "APIStart",
             csum(lambda c, i: req_present(c, i).astype(jnp.int32)))
        for sub in ("plref", "pldef", "pldom"):
            site(f"AS.{sub}", "APIStart", 1)
        site("AS.plpred", "APIStart",
             csum(lambda c, i: lm_present(c, i).astype(jnp.int32)))
        site("AS.bind", "APIStart", pend)
        site("AS.unl", "APIStart", pend)
        site("AS.unr", "APIStart", lpend)
        site("AS.pc", "APIStart", paths)
        site("AS.un", "APIStart", paths)

        # op dispatch: Create is never issued by this family's
        # processes, so the Force/Get/Delete/Update ladder is exact
        site("AS.fif", "APIStart", pend)
        force = csum(lambda c, i: _op_is(c, i, "Force"))
        site("AS.f.if", "APIStart", force)

        def _exists(c, i):
            return _memo(c, ("exists", i), lambda: api_count(
                c, NKEQ, req_obj_ident(c, i), key=("nkeq", i)) > 0)

        f_ex = csum(lambda c, i:
                    _op_is(c, i, "Force")
                    * _exists(c, i).astype(jnp.int32))
        site("AS.f.add", "APIStart", lambda c: force(c) - f_ex(c))
        site("AS.f.ok", "APIStart", force)
        for sub in ("set", "setc", "dom"):
            site(f"AS.f.{sub}", "APIStart", f_ex)
        f_elems = csum(lambda c, i:
                       _op_is(c, i, "Force")
                       * _exists(c, i).astype(jnp.int32) * c["api_n"])
        for sub in ("elif", "cond", "co", "cr"):
            site(f"AS.f.{sub}", "APIStart", f_elems)
        site("AS.f.civo.w", "APIStart", f_elems)
        site("AS.f.civo.1", "APIStart", f_elems)
        f_nmatch = csum(lambda c, i:
                        _op_is(c, i, "Force")
                        * _exists(c, i).astype(jnp.int32)
                        * api_count(c, NEQ, req_obj_ident(c, i), key=("neq", i)))
        site("AS.f.civo.2", "APIStart", f_nmatch)
        f_match = csum(lambda c, i:
                       _op_is(c, i, "Force")
                       * _exists(c, i).astype(jnp.int32)
                       * api_count(c, NKEQ, req_obj_ident(c, i), key=("nkeq", i)))
        site("AS.f.wr", "APIStart", f_match)
        site("AS.f.o", "APIStart", lambda c: f_elems(c) - f_match(c))

        get = csum(lambda c, i: _op_is(c, i, "Get"))
        site("AS.gif", "APIStart", lambda c: pend(c) - force(c))
        site("AS.g.if", "APIStart", get)
        g_ex = csum(lambda c, i:
                    _op_is(c, i, "Get") * _exists(c, i).astype(jnp.int32))
        site("AS.g.err", "APIStart", lambda c: get(c) - g_ex(c))
        site("AS.g.unch", "APIStart", lambda c: get(c) - g_ex(c))
        for sub in ("req", "req2", "api1", "cho", "cho2", "chod",
                    "st", "set", "setc", "dom"):
            site(f"AS.g.{sub}", "APIStart", g_ex)
        g_elems = csum(lambda c, i:
                       _op_is(c, i, "Get")
                       * _exists(c, i).astype(jnp.int32) * c["api_n"])
        for sub in ("chob", "choo", "chor", "elif", "cond", "co"):
            site(f"AS.g.{sub}", "APIStart", g_elems)
        # the primed requests'[c].obj deref logs one extra visit per
        # comprehension evaluation (spec.coverage's AS.g.cr note)
        site("AS.g.cr", "APIStart", lambda c: g_elems(c) + g_ex(c))
        g_nmatch = csum(lambda c, i:
                        _op_is(c, i, "Get")
                        * _exists(c, i).astype(jnp.int32)
                        * api_count(c, NEQ, req_obj_ident(c, i), key=("neq", i)))
        site("AS.g.chivo.w", "APIStart", g_elems)
        site("AS.g.chivo.1", "APIStart", g_elems)
        site("AS.g.chivo.2", "APIStart", g_nmatch)
        site("AS.g.civo.w", "APIStart", g_elems)
        site("AS.g.civo.1", "APIStart", g_elems)
        site("AS.g.civo.2", "APIStart", g_nmatch)
        g_match = csum(lambda c, i:
                       _op_is(c, i, "Get")
                       * _exists(c, i).astype(jnp.int32)
                       * api_count(c, NKEQ, req_obj_ident(c, i), key=("nkeq", i)))
        site("AS.g.rd", "APIStart", g_match)
        site("AS.g.o", "APIStart", lambda c: g_elems(c) - g_match(c))

        delete = csum(lambda c, i: _op_is(c, i, "Delete"))
        site("AS.dif", "APIStart", lambda c: pend(c) - force(c) - get(c))
        for sub in ("set", "setc", "dom", "ok"):
            site(f"AS.d.{sub}", "APIStart", delete)
        d_elems = csum(lambda c, i: _op_is(c, i, "Delete") * c["api_n"])
        for sub in ("neg", "negi", "co", "cr", "ivo.w", "ivo.1"):
            site(f"AS.d.{sub}", "APIStart", d_elems)
        d_nmatch = csum(lambda c, i:
                        _op_is(c, i, "Delete")
                        * api_count(c, NEQ, req_obj_ident(c, i), key=("neq", i)))
        site("AS.d.ivo.2", "APIStart", d_nmatch)

        upd = csum(lambda c, i: _op_is(c, i, "Update"))
        site("AS.uif", "APIStart",
             lambda c: pend(c) - force(c) - get(c) - delete(c))
        site("AS.u.if", "APIStart", upd)
        site("AS.u.dom", "APIStart", upd)

        def _found(c, i):
            """Some api object matches robj AND already lists client i
            in its version vector (the Update success condition)."""
            t = jnp.asarray(NKEQ)
            per = t[c["api_ident"], req_obj_ident(c, i)[:, None]]
            vv = ((c["api_vv"] >> (cdc.o_vv + i)) & 1).astype(bool)
            return (per & c["api_present"] & vv).any(axis=1)

        u_found = csum(lambda c, i:
                       _op_is(c, i, "Update")
                       * _found(c, i).astype(jnp.int32))
        for sub in ("set", "set2", "filt", "fdom", "wr", "ok"):
            site(f"AS.u.{sub}", "APIStart", u_found)
        site("AS.u.err", "APIStart", lambda c: upd(c) - u_found(c))
        site("AS.u.unch", "APIStart", lambda c: upd(c) - u_found(c))
        u_elems = csum(lambda c, i:
                       _op_is(c, i, "Update")
                       * _found(c, i).astype(jnp.int32) * c["api_n"])
        for sub in ("fneg", "fnegi", "fo", "fr", "fivo.w", "fivo.1"):
            site(f"AS.u.{sub}", "APIStart", u_elems)
        u_nmatch = csum(lambda c, i:
                        _op_is(c, i, "Update")
                        * _found(c, i).astype(jnp.int32)
                        * api_count(c, NEQ, req_obj_ident(c, i), key=("neq", i)))
        site("AS.u.fivo.2", "APIStart", u_nmatch)

        # list serving: every site on the list path iterates the full
        # apiState (no short-circuit), so all counts are exact
        for sub in ("l.req", "l.req2", "l.exc", "l.objs", "l.filt",
                    "l.fdom", "l.st", "l.set", "l.setc", "l.dom"):
            site(f"AS.{sub}", "APIStart", lpend)
        l_elems = csum(lambda c, i: _lpending(c, i) * c["api_n"])
        site("AS.l.pred", "APIStart", l_elems)
        site("AS.l.elif", "APIStart", l_elems)
        site("AS.l.cond", "APIStart", l_elems)

        def _kind_matches(c, i):
            kid = jnp.asarray(KIND_ID)[c["api_ident"]]
            per = kid == lm_kind(c, i)[:, None]
            return (per & c["api_present"]).sum(axis=1).astype(jnp.int32)

        l_rd = csum(lambda c, i: _lpending(c, i) * _kind_matches(c, i))
        site("AS.l.rd", "APIStart", l_rd)
        site("AS.l.o", "APIStart", lambda c: l_elems(c) - l_rd(c))

    _mk_server_sites()

    # ---- invariants (one evaluation per expanded = distinct state) -------

    def _mk_inv_sites():
        for sub in ("w", "c1", "c1dom", "c2", "c2dom", "c3", "c3dom"):
            site(f"TY.{sub}", "TypeOK", 1)
        site("TY.c1body", "TypeOK", lambda c: c["api_n"])
        site("TY.c2body", "TypeOK",
             csum(lambda c, i: req_present(c, i).astype(jnp.int32)))
        lm_n = csum(lambda c, i: lm_present(c, i).astype(jnp.int32))
        site("TY.c3body", "TypeOK", lm_n)
        for sub in ("vlr", "vlr1", "vlr2", "vlr2q", "vlr3", "vlrarg"):
            site(f"TY.{sub}", "TypeOK", lm_n)

        def _lobj_total(c):
            n = 0
            for i in range(nc):
                for s in range(ls):
                    n = n + lobj_present(c, i, s).astype(jnp.int32)
            return n

        site("TY.vlr2b", "TypeOK", _lobj_total)
        site("OV.w", "OnlyOneVersion", 1)
        site("OV.dom", "OnlyOneVersion", 1)
        site("OV.body", "OnlyOneVersion",
             lambda c: c["api_n"] * c["api_n"])
        site("OV.o1", "OnlyOneVersion",
             lambda c: c["api_n"] * c["api_n"])
        site("OV.o2", "OnlyOneVersion",
             lambda c: c["api_n"] * (c["api_n"] - 1))

    _mk_inv_sites()

    # ------------------------------------------------------------------
    # assemble the plane
    # ------------------------------------------------------------------
    locs = _span_locs() if cfg.identities == MODEL1_IDENTITIES else {}
    action_sites = [Site(key=a, kind="action", action=a)
                    for a in LABELS]
    fine_sites = [
        Site(key=k, kind="span", action=a, loc=locs.get(k, ""))
        for k, a, _fn in entries
    ]
    init_keys = ["I.api", "I.req", "I.lreq", "I.stk", "I.opobj",
                 "I.kind", "I.sr", "I.pc", "I.rest"]
    init_sites = [Site(key=k, kind="init", action="Init")
                  for k in init_keys]
    sites = tuple(action_sites) + tuple(init_sites) + tuple(fine_sites)

    n_labels = len(LABELS)
    label_ids_np = np.arange(n_labels, dtype=np.int32)
    APISTART_ID = LABEL_ID["APIStart"]

    def count(batch, mask, valid):
        # per-action generated prefix: the same factorized fold as
        # kubeapi_backend.gen_counts (one accounting, two renderings)
        label_ids = jnp.asarray(label_ids_np)
        CL_ = (valid.shape[1] - 2 * nc) // nc
        act = jnp.zeros(n_labels, jnp.uint32)
        for ci in range(nc):
            vc = valid[:, ci * CL_:(ci + 1) * CL_].sum(axis=1)
            pcs = batch[:, pc_off + ci]
            act = act + (
                (pcs[:, None] == label_ids[None, :]) * vc[:, None]
            ).sum(axis=0).astype(jnp.uint32)
        act = act.at[APISTART_ID].add(
            valid[:, nc * CL_:].sum().astype(jnp.uint32)
        )

        ctx = build_ctx(batch)
        ctx["_E"] = mask.sum().astype(jnp.int32)
        m = mask.astype(jnp.int32)
        ck = batch.shape[0]
        # one [S, ck] stack + ONE masked matvec instead of S separate
        # multiply-reduces: the per-site arithmetic fuses into a
        # handful of elementwise ops and a single dot, which is what
        # keeps the measured -coverage overhead in the sub-percent
        # range (bench.py --cov-ab)
        cols = []
        for _k, _a, fn in entries:
            v = fn(ctx) if callable(fn) else jnp.int32(fn)
            if getattr(v, "ndim", 0) == 0:
                v = jnp.broadcast_to(v[None], (ck,))
            cols.append(v.astype(jnp.int32))
        if cols:
            fine = jnp.stack(cols) @ m
            fine = fine.astype(jnp.uint32)
        else:
            fine = jnp.zeros(0, jnp.uint32)
        init_zeros = jnp.zeros(len(init_sites), jnp.uint32)
        return jnp.concatenate([act, init_zeros, fine])

    def init_count(inits: np.ndarray) -> np.ndarray:
        out = np.zeros(len(sites), np.uint32)
        n0 = inits.shape[0]
        base = len(action_sites)
        for j, k in enumerate(init_keys):
            out[base + j] = n0 if k in ("I.pc", "I.rest") else 1
        return out

    return CoveragePlane(sites=sites, count=count,
                         init_count=init_count, module="KubeAPI")


MODEL1_IDENTITIES = (("Secret", "foo"), ("PVC", "mypvc"))
