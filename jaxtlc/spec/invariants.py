"""Vectorized invariant kernels: TypeOK + OnlyOneVersion.

TLC evaluates the configured invariants (MC.cfg:13-15) on every distinct
state (coverage blocks at /root/reference/KubeAPI.toolbox/Model_1/MC.out:1020
TypeOK, :1074 OnlyOneVersion).  Here they are branch-free predicate kernels
over encoded field vectors, evaluated on every candidate successor in the
same fused pass as expansion (SURVEY.md §2.3 E6: "vectorized predicate
kernels fused into the next-state pass").

The codec discharges parts of TypeOK by construction (field widths cannot
express an out-of-enum op, for instance), but every clause with runtime
content is checked for real: identity ranges, status/op ranges, the
listed-object kind agreement `\\A o \\in r.objs: o.k = r.kind`
(KubeAPI.tla:434-435), and OnlyOneVersion's pairwise identity uniqueness
(KubeAPI.tla:787-789) - the latter is a genuine check because the codec uses
anonymous object slots, so a buggy transition *could* materialize two
versions of one identity.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from ..config import ModelConfig
from .codec import get_codec


def make_invariant_kernel(cfg: ModelConfig):
    """Build ``check(vec[F]) -> ok_bits int32`` (bit0 TypeOK, bit1
    OnlyOneVersion; a set bit means the invariant HOLDS)."""
    cdc = get_codec(cfg)
    ni, nc, ls = cdc.ni, cdc.nc, cdc.ls
    n_ident = cfg.n_identities
    n_kinds = len(cfg.kinds)
    ident_kind = jnp.asarray([cdc.kind_id[k] for k, _ in cfg.identities], jnp.int32)

    def present(w):
        return (w >> cdc.o_present) & 1

    def ident(w):
        return (w >> cdc.o_ident) & ((1 << cdc.ib) - 1)

    def obj_ok(w):
        """IsValidAPIObject (KubeAPI.tla:378-384) over an object word."""
        return jnp.where(present(w) == 1, ident(w) < n_ident, w == 0)

    def check(vec):
        sd = cdc.to_sdict(vec)
        api, req, lm, lo = sd["api"], sd["req"], sd["lreq_meta"], sd["lreq_obj"]

        # TypeOK (KubeAPI.tla:776-781)
        ok = obj_ok(api).all()
        rp = ((req >> cdc.r_present) & 1) == 1
        r_op = (req >> cdc.r_op) & 7
        r_st = (req >> cdc.r_status) & 3
        r_obj = (req >> cdc.r_obj) & ((1 << cdc.obj_bits) - 1)
        req_ok = (~rp) | (
            (r_op <= 4) & (r_st <= 2) & (present(r_obj) == 1) & obj_ok(r_obj)
        )
        ok &= req_ok.all()
        lp = ((lm >> cdc.lm_present) & 1) == 1
        l_kind = (lm >> cdc.lm_kind) & ((1 << cdc.kb) - 1)
        l_st = (lm >> cdc.lm_status) & 3
        lo_pres = present(lo) == 1  # [nc, ls]
        lo_kind = jnp.take(ident_kind, ident(lo))  # [nc, ls]
        objs_ok = (~lo_pres | (obj_ok(lo).astype(bool) & (lo_kind == l_kind[:, None]))).all(
            axis=1
        )
        # absent list request must have all-zero slots (canonical form)
        objs_zero = (lo == 0).all(axis=1)
        lreq_ok = jnp.where(lp, (l_kind < n_kinds) & (l_st <= 2) & objs_ok, objs_zero)
        ok &= lreq_ok.all()
        type_ok = ok

        # OnlyOneVersion (KubeAPI.tla:787-789): pairwise identity uniqueness
        pres = present(api) == 1
        ids = ident(api)
        pair = (pres[:, None] & pres[None, :]) & (ids[:, None] == ids[None, :])
        pair = pair & ~jnp.eye(ni, dtype=bool)
        only_one = ~pair.any()

        return type_ok.astype(jnp.int32) | (only_one.astype(jnp.int32) << 1)

    return check


@functools.lru_cache(maxsize=None)
def batched_invariants(cfg: ModelConfig):
    return jax.jit(jax.vmap(make_invariant_kernel(cfg)))
