"""Vmapped next-state kernel for the KubeAPI action system.

The TPU-native replacement for TLC's worker successor generation
(tlc2.tool.Worker, evidenced at
/root/reference/KubeAPI.toolbox/Model_1/MC.out:5): one branch-free function
``step(state) -> (succ[L, F], valid[L], action[L], assert_fail[L],
overflow[L])`` that enumerates *every* satisfying assignment of Next
(/root/reference/KubeAPI.tla:760-763) as a statically-shaped lane.  `vmap`
lifts it over the frontier batch; all nondeterminism (SURVEY.md §3.4) is
unrolled into lanes:

* lanes [0, CL)          - Client process (pc-dispatched over its labels)
* lanes [CL, 2*CL)       - PVCController process
* lanes [2*CL, 2*CL+NC)  - APIServer servicing client c's pending request
* lanes [.., 2*CL+2*NC)  - APIServer servicing client c's pending list

where CL = max(2, 1 + fail + timeout, LS) (see lane_layout): the fault
switches size DoRequest's per-disjunct failure lanes (KubeAPI.tla:471-483
- the Error branch fires once per true constant, see oracle.py), CStart's
either needs 2, and LS covers `with s \\in listRequests[self].objs`
fan-out (KubeAPI.tla:618-629, :673-688).

Per-label handlers are ordinary jnp expressions combined with `where`
selects on pc - no data-dependent Python control flow, so the whole step
jits to a single fused XLA computation (branchless dispatch is the TPU idiom
replacing TLC's Java virtual dispatch).

Inline assertions (KubeAPI.tla:196, :216, :348) surface as per-lane
`assert_fail` flags evaluated when their action fires, exactly when TLC
evaluates them.  Slot overflow (scaled configs exceeding codec bounds,
SURVEY.md §7 hard parts) surfaces as per-lane `overflow` flags.
"""

from __future__ import annotations

import functools
from typing import List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import RECONCILER, ModelConfig
from .codec import Codec, get_codec
from .labels import LABEL_ID, VERB_ID

I32 = jnp.int32


def _sel(mask, a, b):
    """Elementwise dict/tuple select (mask scalar bool)."""
    return jax.tree.map(lambda x, y: jnp.where(mask, x, y), a, b)


def lane_layout(cfg: ModelConfig) -> Tuple[int, int]:
    """(CL, L): client lane-block width and total lane count.  Lane l acts
    for process l // CL when l < nc*CL, else the server.  Single source of
    truth for anything (e.g. the liveness graph builder) that must map
    lanes back to acting processes.

    CL is the widest per-label lane fan-out actually reachable under the
    config's fault switches: DoRequest/DoListRequest need 1 + fail +
    timeout lanes (KubeAPI.tla:471-483), CStart needs 2 (the either at
    :529-531), and the `with`-fanout labels need ls (:618-629, :673-688).
    Fault-free configs therefore run 2-wide client blocks instead of 3 -
    a 20% lane cut that every vector phase of the engine inherits."""
    cdc = get_codec(cfg)
    CL = max(2, 1 + int(cfg.requests_can_fail) + int(cfg.requests_can_timeout),
             cdc.ls)
    return CL, cdc.nc * CL + 2 * cdc.nc


def make_kernel(cfg: ModelConfig):
    """Build ``step(vec[F]) -> (succ[L,F], valid[L], action[L], afail[L],
    overflow[L])`` for one config.  All loops below are over static python
    ints and unroll at trace time."""
    cdc = get_codec(cfg)
    ni, nc, ls = cdc.ni, cdc.nc, cdc.ls
    CL, L = lane_layout(cfg)

    fail = bool(cfg.requests_can_fail)
    timeout = bool(cfg.requests_can_timeout)

    # static tables / constants
    ident_kind = jnp.asarray(
        [cdc.kind_id[k] for k, _ in cfg.identities], dtype=I32
    )
    pvc_kind = cdc.kind_id.get("PVC", -1)
    secret_kind = cdc.kind_id.get("Secret", -1)
    obj_mask = (1 << cdc.obj_bits) - 1
    vv_field_mask = ((1 << nc) - 1) << cdc.o_vv

    def obj_word(kind: str, name: str, vv=0, has_vv=False, spec=False) -> int:
        w = (1 << cdc.o_present) | (cfg.identity_id(kind, name) << cdc.o_ident)
        if has_vv:
            w |= (1 << cdc.o_hasvv) | (vv << cdc.o_vv)
        if spec:
            w |= 1 << cdc.o_spec
        return w

    # -- object word ops ----------------------------------------------------

    def present(w):
        return (w >> cdc.o_present) & 1

    def ident(w):
        return (w >> cdc.o_ident) & ((1 << cdc.ib) - 1)

    def kind_of(w):
        return jnp.take(ident_kind, ident(w))

    def has_spec(w):
        return (w >> cdc.o_spec) & 1

    def write_w(w):
        """Write (KubeAPI.tla:395): vv := {} - set has_vv, clear vv bits."""
        return (w & ~vv_field_mask) | (1 << cdc.o_hasvv)

    def read_w(w, ci: int):
        """Read (KubeAPI.tla:399): add client ci to vv."""
        return w | (1 << (cdc.o_vv + ci))

    def unbound_pvc(w):
        """IsUnboundPVC (KubeAPI.tla:444-446).  The codec guarantees a
        present spec is exactly [pvname |-> name], so 'no pvname' == 'no
        spec'."""
        return (present(w) == 1) & (kind_of(w) == pvc_kind) & (has_spec(w) == 0)

    # -- request word ops ---------------------------------------------------

    def req_word(op_id, obj_w, status_id):
        return (
            (1 << cdc.r_present)
            | (op_id << cdc.r_op)
            | (status_id << cdc.r_status)
            | (obj_w << cdc.r_obj)
        )

    def req_status(w):
        return (w >> cdc.r_status) & 3

    def req_op(w):
        return (w >> cdc.r_op) & 7

    def req_obj(w):
        return (w >> cdc.r_obj) & obj_mask

    def req_with_status(w, status_id):
        return (w & ~(3 << cdc.r_status)) | (status_id << cdc.r_status)

    def req_with_obj(w, obj_w):
        return (w & ~(obj_mask << cdc.r_obj)) | (obj_w << cdc.r_obj)

    def lm_word(kind_id, status_id):
        return (1 << cdc.lm_present) | (kind_id << cdc.lm_kind) | (
            status_id << cdc.lm_status
        )

    def lm_status(w):
        return (w >> cdc.lm_status) & 3

    def lm_kind(w):
        return (w >> cdc.lm_kind) & ((1 << cdc.kb) - 1)

    def lm_with(w, status_id):
        return (w & ~(3 << cdc.lm_status)) | (status_id << cdc.lm_status)

    PENDING, OK, ERROR = 0, 1, 2  # RESPONSE_ID order

    # -- state helpers ------------------------------------------------------

    def set_pc(sd, i, label):
        return {**sd, "pc": sd["pc"].at[i].set(LABEL_ID[label])}

    def set_sr(sd, ri: int, v: int):
        return {**sd, "sr": sd["sr"].at[ri].set(v)}

    def call_api(sd, i, ret, verb, obj_w):
        """call API(op, obj): push frame saving dIV params (KubeAPI.tla
        :535-539; frames provably always save defaultInitValue - asserted by
        the codec) and assign op/obj."""
        frame = (1 << cdc.s_present) | (LABEL_ID[ret] << cdc.s_retpc)
        sd = {
            **sd,
            "stack": sd["stack"].at[i].set(frame),
            "p_op": sd["p_op"].at[i].set(1 + VERB_ID[verb]),
            "p_obj": sd["p_obj"].at[i].set(obj_w),
        }
        return set_pc(sd, i, "DoRequest")

    def call_listapi(sd, i, ret, kind_name):
        frame = (
            (1 << cdc.s_present)
            | (1 << cdc.s_proc)
            | (LABEL_ID[ret] << cdc.s_retpc)
        )
        sd = {
            **sd,
            "stack": sd["stack"].at[i].set(frame),
            "p_kind": sd["p_kind"].at[i].set(1 + cdc.kind_id[kind_name]),
        }
        return set_pc(sd, i, "DoListRequest")

    def api_exists(sd, obj_w):
        """ObjectExists (KubeAPI.tla:410) + the match mask."""
        match = (present(sd["api"]) == 1) & (ident(sd["api"]) == ident(obj_w))
        return match, match.any()

    INVALID = None  # placeholder meaning "lane statically absent"

    # -- per-label handlers: return list of (valid, sdict, afail) -----------

    def h_do_request(sd, i):
        obj_w = sd["p_obj"][i]
        op_id = sd["p_op"][i] - 1
        lanes = []
        for status, on in ((PENDING, True), (ERROR, fail), (ERROR, timeout)):
            if not on:
                continue
            nxt = set_pc(
                {**sd, "req": sd["req"].at[i].set(req_word(op_id, obj_w, status))},
                i,
                "DoReply",
            )
            lanes.append((jnp.bool_(True), nxt, jnp.bool_(False)))
        return lanes

    def h_do_reply(sd, i):
        rw = sd["req"][i]
        guard = req_status(rw) != PENDING
        frame = sd["stack"][i]
        retpc = (frame >> cdc.s_retpc) & ((1 << cdc.lb) - 1)
        popped = {
            **sd,
            "pc": sd["pc"].at[i].set(retpc),
            "stack": sd["stack"].at[i].set(0),
            "p_op": sd["p_op"].at[i].set(0),
            "p_obj": sd["p_obj"].at[i].set(0),
        }
        lanes = [(guard, popped, jnp.bool_(False))]
        if timeout:
            erred = {**popped, "req": popped["req"].at[i].set(req_with_status(rw, ERROR))}
            lanes.append((guard, erred, jnp.bool_(False)))
        return lanes

    def h_do_list_request(sd, i):
        kind_id = sd["p_kind"][i] - 1
        lanes = []
        for status, on in ((PENDING, True), (ERROR, fail), (ERROR, timeout)):
            if not on:
                continue
            nxt = {
                **sd,
                "lreq_meta": sd["lreq_meta"].at[i].set(lm_word(kind_id, status)),
                "lreq_obj": sd["lreq_obj"].at[i].set(jnp.zeros(ls, I32)),
            }
            lanes.append((jnp.bool_(True), set_pc(nxt, i, "DoListReply"), jnp.bool_(False)))
        return lanes

    def h_do_list_reply(sd, i):
        lw = sd["lreq_meta"][i]
        guard = lm_status(lw) != PENDING
        frame = sd["stack"][i]
        retpc = (frame >> cdc.s_retpc) & ((1 << cdc.lb) - 1)
        popped = {
            **sd,
            "pc": sd["pc"].at[i].set(retpc),
            "stack": sd["stack"].at[i].set(0),
            "p_kind": sd["p_kind"].at[i].set(0),
        }
        lanes = [(guard, popped, jnp.bool_(False))]
        if timeout:
            erred = {
                **popped,
                "lreq_meta": popped["lreq_meta"].at[i].set(lm_with(lw, ERROR)),
                "lreq_obj": popped["lreq_obj"].at[i].set(jnp.zeros(ls, I32)),
            }
            lanes.append((guard, erred, jnp.bool_(False)))
        return lanes

    def _branch(sd, i, cond, then_lbl, else_lbl):
        t = set_pc(sd, i, then_lbl)
        e = set_pc(sd, i, else_lbl)
        return [(jnp.bool_(True), _sel(cond, t, e), jnp.bool_(False))]

    def h_c1(sd, i):
        return _branch(sd, i, req_status(sd["req"][i]) == OK, "C10", "CStart")

    def h_c11(sd, i):
        return _branch(sd, i, req_status(sd["req"][i]) == OK, "c12", "CStart")

    def h_c13(sd, i):
        rw = sd["req"][i]
        ok = (req_status(rw) == OK) & ~unbound_pvc(req_obj(rw))
        return _branch(sd, i, ok, "C2", "CStart")

    def h_c3(sd, i):
        return _branch(sd, i, lm_status(sd["lreq_meta"][i]) == OK, "C8", "CStart")

    def h_c8(sd, i):
        empty = (present(sd["lreq_obj"][i]) == 0).all()
        return _branch(sd, i, empty, "C4", "C6")

    def h_c6(sd, i):
        # with s \in listRequests[self].objs: Delete [k |-> s.k, n |-> s.n]
        # (KubeAPI.tla:618-629) - the target is a BARE record: no vv/spec.
        lanes = []
        for j in range(ls):
            s = sd["lreq_obj"][i, j]
            bare = (1 << cdc.o_present) | (ident(s) << cdc.o_ident)
            nxt = call_api(sd, i, "C7", "Delete", bare)
            lanes.append((present(s) == 1, nxt, jnp.bool_(False)))
        while len(lanes) < CL:
            lanes.append(INVALID)
        return lanes

    def h_c7(sd, i):
        ok = (req_status(sd["req"][i]) == OK) & (
            present(sd["lreq_obj"][i]).sum() <= 1
        )
        return _branch(sd, i, ok, "C4", "CStart")

    def h_c5(sd, i):
        return [(jnp.bool_(True), set_pc(sd, i, "CStart"), jnp.bool_(False))]

    def make_reconciler_extras(ci: int):
        """Per-client handlers for the labels that reference the client's own
        target objects (KubeAPI.tla:176,182) or its shouldReconcile bit."""
        si, pi = cfg.targets[ci]
        sk, sn = cfg.identities[si]
        pk, pn = cfg.identities[pi]
        secret_w = obj_word(sk, sn)
        pvc_w = obj_word(pk, pn)
        ri = cfg.sr_index(ci)

        def h_cstart(sd, i):
            # KubeAPI.tla:528-549: lane0 = either-branch sr':=TRUE; lane1 =
            # skip branch; the IF dispatches on the *new* value.
            recon = call_api(set_sr(sd, ri, 1), i, "C1", "Force", secret_w)
            cleanup = call_listapi(set_sr(sd, ri, 0), i, "C3", sk)
            skip = _sel(sd["sr"][ri] == 1, recon, cleanup)
            return [
                (jnp.bool_(True), recon, jnp.bool_(False)),
                (jnp.bool_(True), skip, jnp.bool_(False)),
                INVALID,
            ]

        def h_c10(sd, i):
            return [(jnp.bool_(True), call_api(sd, i, "C11", "Force", pvc_w), jnp.bool_(False))]

        def h_c12(sd, i):
            return [(jnp.bool_(True), call_api(sd, i, "C13", "Get", pvc_w), jnp.bool_(False))]

        def h_c2(sd, i):
            # assert ObjectExists(own secret) (KubeAPI.tla:196 -> :598-599)
            _, found = api_exists(sd, jnp.int32(secret_w))
            base = sd if cfg.mutation == "sticky_reconcile" else set_sr(sd, ri, 0)
            nxt = set_pc(base, i, "C5")
            return [(jnp.bool_(True), nxt, ~found)]

        def h_c4(sd, i):
            _, found = api_exists(sd, jnp.int32(secret_w))
            return [(jnp.bool_(True), set_pc(sd, i, "C5"), found)]

        return {"CStart": h_cstart, "C10": h_c10, "c12": h_c12,
                "C2": h_c2, "C4": h_c4}

    def h_pvc_start(sd, i):
        return [
            (jnp.bool_(True), call_listapi(sd, i, "PVCListedPVCs", "PVC"), jnp.bool_(False))
        ]

    def h_pvc_listed(sd, i):
        lw = sd["lreq_meta"][i]
        any_unbound = unbound_pvc(sd["lreq_obj"][i]).any()
        ok = (lm_status(lw) == OK) & any_unbound
        return _branch(sd, i, ok, "PVCHavePVCs", "PVCStart")

    def h_pvc_have(sd, i):
        # one lane per unbound listed PVC; bound = unb + spec[pvname |-> unb.n]
        # (KubeAPI.tla:673-688) - in codec terms: set the has_spec bit.
        lanes = []
        for j in range(ls):
            unb = sd["lreq_obj"][i, j]
            bound = unb | (1 << cdc.o_spec)
            nxt = call_api(sd, i, "PVCDone", "Update", bound)
            lanes.append((unbound_pvc(unb), nxt, jnp.bool_(False)))
        while len(lanes) < CL:
            lanes.append(INVALID)
        return lanes

    def h_pvc_done(sd, i):
        return [(jnp.bool_(True), set_pc(sd, i, "PVCStart"), jnp.bool_(False))]

    PROC_HANDLERS = {
        "DoRequest": h_do_request,
        "DoReply": h_do_reply,
        "DoListRequest": h_do_list_request,
        "DoListReply": h_do_list_reply,
    }
    RECONCILER_BASE = {
        "C1": h_c1,
        "C11": h_c11,
        "C13": h_c13,
        "C3": h_c3,
        "C8": h_c8,
        "C6": h_c6,
        "C7": h_c7,
        "C5": h_c5,
    }
    BINDER_HANDLERS = {
        **PROC_HANDLERS,
        "PVCStart": h_pvc_start,
        "PVCListedPVCs": h_pvc_listed,
        "PVCHavePVCs": h_pvc_have,
        "PVCDone": h_pvc_done,
    }
    # per-client handler table (static; resolved at trace time)
    HANDLERS_BY_CLIENT = [
        {**PROC_HANDLERS, **RECONCILER_BASE, **make_reconciler_extras(ci)}
        if cfg.roles[ci] == RECONCILER
        else BINDER_HANDLERS
        for ci in range(nc)
    ]

    # -- APIServer lanes (KubeAPI.tla:698-756) ------------------------------

    def server_req_lane(sd, c: int):
        """Service client c's pending single-object request (:699-743)."""
        rw = sd["req"][c]
        valid = (((rw >> cdc.r_present) & 1) == 1) & (req_status(rw) == PENDING)
        op = req_op(rw)
        robj = req_obj(rw)
        api = sd["api"]
        match, found = api_exists(sd, robj)
        free = present(api) == 0
        free_idx = jnp.argmax(free)
        can_insert = free.any()
        written = write_w(robj)
        inserted = api.at[free_idx].set(written)  # used under `can_insert`

        # Create (:700-705)
        create_api = jnp.where(found, api, jnp.where(can_insert, inserted, api))
        create_st = jnp.where(found, ERROR, OK)
        create_ovf = ~found & ~can_insert
        # Force (:706-715)
        force_api = jnp.where(
            found, jnp.where(match, written, api), jnp.where(can_insert, inserted, api)
        )
        force_st = jnp.full((), OK, I32)
        force_ovf = ~found & ~can_insert
        # Get (:716-728): CHOOSE the (single) match; request obj becomes the
        # PRE-read copy; apiState copy gets vv |= {c}.
        chosen = jnp.where(match, api, 0).max()  # exactly one match when found
        get_api = jnp.where(found, jnp.where(match, read_w(api, c), api), api)
        get_st = jnp.where(found, OK, ERROR)
        # Delete (:729-731); under the "delete_noop" self-test mutation the
        # removal is skipped so the cleanup assert (KubeAPI.tla:216) fires
        del_api = api if cfg.mutation == "delete_noop" else jnp.where(match, 0, api)
        # Update (:732-739): optimistic concurrency via HasRead
        hasread = (match & (((api >> (cdc.o_vv + c)) & 1) == 1)).any()
        upd_api = jnp.where(hasread, jnp.where(match, written, api), api)
        upd_st = jnp.where(hasread, OK, ERROR)

        is_create = op == VERB_ID["Create"]
        is_force = op == VERB_ID["Force"]
        is_get = op == VERB_ID["Get"]
        is_delete = op == VERB_ID["Delete"]
        is_update = op == VERB_ID["Update"]
        afail = valid & ~(is_create | is_force | is_get | is_delete | is_update)

        new_api = jnp.where(
            is_create,
            create_api,
            jnp.where(
                is_force,
                force_api,
                jnp.where(is_get, get_api, jnp.where(is_delete, del_api, upd_api)),
            ),
        )
        new_st = jnp.where(
            is_create,
            create_st,
            jnp.where(
                is_force,
                force_st,
                jnp.where(is_get, get_st, jnp.where(is_delete, OK, upd_st)),
            ),
        )
        new_rw = req_with_status(rw, new_st)
        new_rw = jnp.where(is_get & found, req_with_obj(new_rw, chosen), new_rw)
        overflow = valid & jnp.where(is_create, create_ovf, is_force & force_ovf)
        nxt = {**sd, "api": new_api, "req": sd["req"].at[c].set(new_rw)}
        return valid, nxt, afail, overflow

    def server_list_lane(sd, c: int):
        """Service client c's pending list request (:745-753)."""
        lw = sd["lreq_meta"][c]
        valid = (((lw >> cdc.lm_present) & 1) == 1) & (lm_status(lw) == PENDING)
        kind = lm_kind(lw)
        api = sd["api"]
        match = (present(api) == 1) & (kind_of(api) == kind)
        # compact the PRE-read copies into the ls list slots (descending
        # canonical order); overflow if more matches than slots
        matched = jnp.where(match, api, 0)
        compacted = -jnp.sort(-matched)[:ls]
        overflow = valid & (match.sum() > ls)
        new_api = jnp.where(match, read_w(api, c), api)
        nxt = {
            **sd,
            "api": new_api,
            "lreq_meta": sd["lreq_meta"].at[c].set(lm_with(lw, OK)),
            "lreq_obj": sd["lreq_obj"].at[c].set(compacted),
        }
        return valid, nxt, jnp.bool_(False), overflow

    # -- assemble the full lane vector --------------------------------------

    APISTART_ID = LABEL_ID["APIStart"]

    def step(vec):
        sd = cdc.to_sdict(vec)
        zero_lane = (jnp.bool_(False), sd, jnp.int32(0), jnp.bool_(False), jnp.bool_(False))
        lanes: List = [zero_lane] * L

        for i in range(nc):
            handlers = HANDLERS_BY_CLIENT[i]
            acc = [zero_lane] * CL
            lbl = sd["pc"][i]
            for name, handler in handlers.items():
                mask = lbl == LABEL_ID[name]
                hl = handler(sd, i)
                aid = jnp.int32(LABEL_ID[name])
                for k, lane in enumerate(hl):
                    if lane is INVALID:
                        continue
                    assert k < CL, f"label {name} emits lane {k} >= CL={CL}"
                    v, s2, af = lane
                    cand = (mask & v, s2, aid, mask & af, jnp.bool_(False))
                    acc[k] = _sel(mask, cand, acc[k])
            for k in range(CL):
                lanes[i * CL + k] = acc[k]

        for c in range(nc):
            v, s2, af, ovf = server_req_lane(sd, c)
            lanes[nc * CL + c] = (v, s2, jnp.int32(APISTART_ID), v & af, ovf)
            v, s2, af, ovf = server_list_lane(sd, c)
            lanes[nc * CL + nc + c] = (v, s2, jnp.int32(APISTART_ID), v & af, ovf)

        succs = jnp.stack([cdc.from_sdict(s) for _, s, _, _, _ in lanes])
        succs = cdc.canonicalize(succs)
        valid = jnp.stack([v for v, _, _, _, _ in lanes])
        action = jnp.stack([a for _, _, a, _, _ in lanes])
        afail = jnp.stack([f for _, _, _, f, _ in lanes])
        overflow = jnp.stack([o for _, _, _, _, o in lanes])
        return succs, valid, action, afail, overflow

    step.n_lanes = L
    step.codec = cdc
    return step


@functools.lru_cache(maxsize=None)
def batched_kernel(cfg: ModelConfig):
    """jit(vmap(step)) over a frontier batch: [B,F] -> ([B,L,F], [B,L], ...)."""
    return jax.jit(jax.vmap(make_kernel(cfg)))


def initial_vectors(cfg: ModelConfig) -> np.ndarray:
    """Init (KubeAPI.tla:455-469) as encoded field vectors (2 states)."""
    from . import oracle

    cdc = get_codec(cfg)
    return np.stack([cdc.encode(s) for s in oracle.initial_states(cfg)])
