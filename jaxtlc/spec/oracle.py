"""Host-side reference interpreter ("oracle") for the KubeAPI action system.

This is a direct, explicit-state implementation of the *semantics* of the
generated TLA+ translation at /root/reference/KubeAPI.tla:455-763 (Init
:455-469, one function per action label :471-756, Next :760-763).  It exists
for two reasons:

1. Differential validation: the tensorized TPU kernel (jaxtlc.spec.kernel)
   must produce, level by level, exactly the same reachable-state sets as this
   interpreter, and this interpreter must reproduce the committed TLC run's
   statistics (2 initial states MC.out:32; 577,736 generated / 163,408
   distinct MC.out:1098; depth 124 MC.out:1101).
2. Counterexample re-evaluation (trace-explorer analog, SURVEY.md §2.3 E11).

Process structure is config-driven (jaxtlc.config): each RECONCILER client
runs the `process Client` label machine (KubeAPI.tla:161-220) over its own
target secret/PVC identities, each BINDER runs `process PVCController`
(KubeAPI.tla:225-260); Model_1 is the 1x1 instance.  `shouldReconcile` is a
tuple of per-reconciler booleans (the spec's `[{"Client"} -> BOOLEAN]`,
KubeAPI.tla:465).

States are immutable nested tuples so they hash; records are represented as
tuples of sorted (field, value) pairs; TLA sets as frozensets.  No code is
copied from the reference - the reference is a TLA+ spec, this is an original
Python implementation of its transition relation.
"""

from __future__ import annotations

import itertools
from typing import Dict, Iterable, List, NamedTuple, Optional, Tuple

from ..config import RECONCILER, ModelConfig
from .labels import DEFAULT_INIT, PROC_API, PROC_LISTAPI

# ---------------------------------------------------------------------------
# Value helpers: records are tuples of sorted (key, value) pairs.
# ---------------------------------------------------------------------------


def rec(**fields):
    return tuple(sorted(fields.items()))


def rec_from(pairs: Iterable[Tuple[str, object]], **updates):
    d = dict(pairs)
    d.update(updates)
    return tuple(sorted(d.items()))


def fld(r, name, default=None):
    for k, v in r:
        if k == name:
            return v
    return default


def has(r, name) -> bool:
    return any(k == name for k, _ in r)


# --- spec operators (KubeAPI.tla define block :378-446) --------------------


def is_version_of(o1, o2) -> bool:
    """IsVersionOf (KubeAPI.tla:390): name and kind match."""
    return fld(o1, "n") == fld(o2, "n") and fld(o1, "k") == fld(o2, "k")


def write(o):
    """Write (KubeAPI.tla:395): left-biased merge sets vv := {}."""
    return rec_from(o, vv=frozenset())


def read(o, c):
    """Read (KubeAPI.tla:399): add client c to the version vector."""
    return rec_from(o, vv=fld(o, "vv") | {c})


def has_read(o, c) -> bool:
    """HasRead (KubeAPI.tla:404)."""
    return c in fld(o, "vv")


def is_unbound_pvc(pvc) -> bool:
    """IsUnboundPVC (KubeAPI.tla:444-446)."""
    if fld(pvc, "k") != "PVC":
        return False
    if not has(pvc, "spec"):
        return True
    return not has(fld(pvc, "spec"), "pvname")


def object_exists(api_state, obj) -> bool:
    """ObjectExists (KubeAPI.tla:410)."""
    return any(is_version_of(o, obj) for o in api_state)


# ---------------------------------------------------------------------------
# State
# ---------------------------------------------------------------------------


class State(NamedTuple):
    """Full variable vector (vars, KubeAPI.tla:450-451)."""

    api_state: frozenset  # set of object records
    requests: tuple  # sorted ((client, request-record), ...) - partial fn
    list_requests: tuple  # sorted ((client, listreq-record), ...)
    pc: tuple  # per-process label, processes = clients + Server
    stack: tuple  # per-process tuple of frames (records)
    op: tuple  # per-process procedure param
    obj: tuple
    kind: tuple
    should_reconcile: tuple  # per-reconciler booleans


def pmap_get(m: tuple, c: str):
    for k, v in m:
        if k == c:
            return v
    return None


def pmap_set(m: tuple, c: str, v) -> tuple:
    d = dict(m)
    d[c] = v
    return tuple(sorted(d.items()))


def _set(t: tuple, i: int, v) -> tuple:
    return t[:i] + (v,) + t[i + 1 :]


def initial_states(cfg: ModelConfig) -> List[State]:
    """Init (KubeAPI.tla:455-469): shouldReconcile ranges over
    [reconcilers -> BOOLEAN] => 2^R states (2 in Model_1, MC.out:32)."""
    np_ = cfg.n_clients + 1
    base = dict(
        api_state=frozenset(),
        requests=(),
        list_requests=(),
        pc=tuple(
            "CStart" if r == RECONCILER else "PVCStart" for r in cfg.roles
        )
        + ("APIStart",),
        stack=((),) * np_,
        op=(DEFAULT_INIT,) * np_,
        obj=(DEFAULT_INIT,) * np_,
        kind=(DEFAULT_INIT,) * np_,
    )
    return [
        State(should_reconcile=bits, **base)
        for bits in itertools.product(
            (False, True), repeat=cfg.n_reconcilers
        )
    ]


# ---------------------------------------------------------------------------
# Successor relation
# ---------------------------------------------------------------------------


class Succ(NamedTuple):
    label: str  # action label that produced this successor
    state: State
    violation: Optional[str]  # assert-failure id, else None
    proc: int = -1  # acting process index (n_clients = the server)


def _ckey(v):
    """Total-order sort key for spec values (frozensets lack a total order)."""
    if isinstance(v, frozenset):
        return (1, tuple(sorted((_ckey(x) for x in v))))
    if isinstance(v, tuple):
        return (2, tuple(_ckey(x) for x in v))
    return (0, v)


def _push(st: State, i: int, frame, new_pc: str) -> State:
    """Common call-site shape (e.g. CStart :535-540): push one frame."""
    assert len(st.stack[i]) == 0, "procedures never nest in this spec"
    return st._replace(stack=_set(st.stack, i, (frame,)), pc=_set(st.pc, i, new_pc))


def _call_api(st: State, i: int, ret: str, op_v: str, obj_v) -> State:
    """call API(op, obj): frame stores the *old* op/obj (KubeAPI.tla:535-539)."""
    frame = rec(procedure=PROC_API, pc=ret, op=st.op[i], obj=st.obj[i])
    st = _push(st, i, frame, "DoRequest")
    return st._replace(op=_set(st.op, i, op_v), obj=_set(st.obj, i, obj_v))


def _call_listapi(st: State, i: int, ret: str, kind_v: str) -> State:
    frame = rec(procedure=PROC_LISTAPI, pc=ret, kind=st.kind[i])
    st = _push(st, i, frame, "DoListRequest")
    return st._replace(kind=_set(st.kind, i, kind_v))


def _goto(st: State, i: int, label: str) -> State:
    return st._replace(pc=_set(st.pc, i, label))


def successors(st: State, cfg: ModelConfig) -> List[Succ]:
    """Enumerate every satisfying assignment of Next (KubeAPI.tla:760-763).

    Each (action, nondeterministic-choice) combination yields one entry -
    matching TLC's generated-states accounting (MC.out:1098).
    """
    out: List[Succ] = []
    fail, timeout = cfg.requests_can_fail, cfg.requests_can_timeout
    proc_bounds: List[int] = []  # len(out) after each client's block

    for i, self in enumerate(cfg.clients):
        proc_bounds.append(len(out))
        lbl = st.pc[i]
        is_recon = cfg.roles[i] == RECONCILER
        if is_recon:
            si, pi = cfg.targets[i]
            secret = rec(k=cfg.identities[si][0], n=cfg.identities[si][1])
            pvc = rec(k=cfg.identities[pi][0], n=cfg.identities[pi][1])
            secret_kind = cfg.identities[si][0]
            ri = cfg.sr_index(i)

        if lbl == "DoRequest":
            # KubeAPI.tla:471-483 - either deliver Pending or (FAIL \/ TIMEOUT)
            # Error.  TLC enumerates each true disjunct of the guard
            # REQUESTS_CAN_FAIL \/ REQUESTS_CAN_TIMEOUT as its own branch, so
            # with both constants TRUE the Error successor is generated twice
            # (confirmed by MC.out:78 - 149,766 = 3 x 49,922 firings).
            lanes = ["Pending"] + ["Error"] * (int(fail) + int(timeout))
            for status in lanes:
                req = rec(op=st.op[i], obj=st.obj[i], status=status)
                nxt = st._replace(
                    requests=pmap_set(st.requests, self, req),
                    pc=_set(st.pc, i, "DoReply"),
                )
                out.append(Succ("DoRequest", nxt, None))

        elif lbl == "DoReply":
            # KubeAPI.tla:485-495 - guarded await, then skip or timeout-Error
            req = pmap_get(st.requests, self)
            if fld(req, "status") == "Pending":
                continue
            frame = st.stack[i][0]
            popped = st._replace(
                pc=_set(st.pc, i, fld(frame, "pc")),
                op=_set(st.op, i, fld(frame, "op")),
                obj=_set(st.obj, i, fld(frame, "obj")),
                stack=_set(st.stack, i, st.stack[i][1:]),
            )
            out.append(Succ("DoReply", popped, None))
            if timeout:
                err = rec_from(req, status="Error")
                nxt = popped._replace(requests=pmap_set(st.requests, self, err))
                out.append(Succ("DoReply", nxt, None))

        elif lbl == "DoListRequest":
            # KubeAPI.tla:499-511 - same per-disjunct enumeration of the
            # failure guard as DoRequest (MC.out:141 - 82,416 = 3 x 27,472).
            for status in ["Pending"] + ["Error"] * (int(fail) + int(timeout)):
                lreq = rec(kind=st.kind[i], objs=frozenset(), status=status)
                nxt = st._replace(
                    list_requests=pmap_set(st.list_requests, self, lreq),
                    pc=_set(st.pc, i, "DoListReply"),
                )
                out.append(Succ("DoListRequest", nxt, None))

        elif lbl == "DoListReply":
            # KubeAPI.tla:513-524
            lreq = pmap_get(st.list_requests, self)
            if fld(lreq, "status") == "Pending":
                continue
            frame = st.stack[i][0]
            popped = st._replace(
                pc=_set(st.pc, i, fld(frame, "pc")),
                kind=_set(st.kind, i, fld(frame, "kind")),
                stack=_set(st.stack, i, st.stack[i][1:]),
            )
            out.append(Succ("DoListReply", popped, None))
            if timeout:
                err = rec_from(lreq, objs=frozenset(), status="Error")
                nxt = popped._replace(list_requests=pmap_set(st.list_requests, self, err))
                out.append(Succ("DoListReply", nxt, None))

        elif lbl == "CStart":
            # KubeAPI.tla:528-549: either set TRUE or skip; the IF branches on
            # the NEW value (shouldReconcile').  Both either-branches are
            # always enumerated - when shouldReconcile is already TRUE they
            # coincide, and TLC still counts two generated states.
            for sr in (True, st.should_reconcile[ri]):
                base = st._replace(
                    should_reconcile=_set(st.should_reconcile, ri, sr)
                )
                if sr:
                    nxt = _call_api(base, i, "C1", "Force", secret)
                else:
                    nxt = _call_listapi(base, i, "C3", secret_kind)
                out.append(Succ("CStart", nxt, None))

        elif lbl == "C1":
            ok = fld(pmap_get(st.requests, self), "status") == "Ok"
            out.append(Succ("C1", _goto(st, i, "C10" if ok else "CStart"), None))

        elif lbl == "C10":
            out.append(Succ("C10", _call_api(st, i, "C11", "Force", pvc), None))

        elif lbl == "C11":
            ok = fld(pmap_get(st.requests, self), "status") == "Ok"
            out.append(Succ("C11", _goto(st, i, "c12" if ok else "CStart"), None))

        elif lbl == "c12":
            out.append(Succ("c12", _call_api(st, i, "C13", "Get", pvc), None))

        elif lbl == "C13":
            req = pmap_get(st.requests, self)
            ok = fld(req, "status") == "Ok" and not is_unbound_pvc(fld(req, "obj"))
            out.append(Succ("C13", _goto(st, i, "C2" if ok else "CStart"), None))

        elif lbl == "C2":
            # KubeAPI.tla:596-602 + assert at :196 (translated :598-599)
            viol = None if object_exists(st.api_state, secret) else "assert:196"
            sr2 = (
                st.should_reconcile
                if cfg.mutation == "sticky_reconcile"
                else _set(st.should_reconcile, ri, False)
            )
            nxt = _goto(st._replace(should_reconcile=sr2), i, "C5")
            out.append(Succ("C2", nxt, viol))

        elif lbl == "C3":
            ok = fld(pmap_get(st.list_requests, self), "status") == "Ok"
            out.append(Succ("C3", _goto(st, i, "C8" if ok else "CStart"), None))

        elif lbl == "C8":
            empty = not fld(pmap_get(st.list_requests, self), "objs")
            out.append(Succ("C8", _goto(st, i, "C4" if empty else "C6"), None))

        elif lbl == "C6":
            # KubeAPI.tla:618-629: with s \in listRequests[self].objs - one
            # lane per listed object
            for s in sorted(fld(pmap_get(st.list_requests, self), "objs"), key=_ckey):
                target = rec(k=fld(s, "k"), n=fld(s, "n"))
                out.append(Succ("C6", _call_api(st, i, "C7", "Delete", target), None))

        elif lbl == "C7":
            req = pmap_get(st.requests, self)
            lreq = pmap_get(st.list_requests, self)
            ok = fld(req, "status") == "Ok" and len(fld(lreq, "objs")) <= 1
            out.append(Succ("C7", _goto(st, i, "C4" if ok else "CStart"), None))

        elif lbl == "C4":
            viol = "assert:216" if object_exists(st.api_state, secret) else None
            out.append(Succ("C4", _goto(st, i, "C5"), viol))

        elif lbl == "C5":
            out.append(Succ("C5", _goto(st, i, "CStart"), None))

        elif lbl == "PVCStart":
            out.append(
                Succ("PVCStart", _call_listapi(st, i, "PVCListedPVCs", "PVC"), None)
            )

        elif lbl == "PVCListedPVCs":
            lreq = pmap_get(st.list_requests, self)
            unbound = [o for o in fld(lreq, "objs") if is_unbound_pvc(o)]
            ok = fld(lreq, "status") == "Ok" and unbound
            out.append(
                Succ(
                    "PVCListedPVCs",
                    _goto(st, i, "PVCHavePVCs" if ok else "PVCStart"),
                    None,
                )
            )

        elif lbl == "PVCHavePVCs":
            # KubeAPI.tla:673-688: one lane per unbound listed PVC; bound adds
            # spec.pvname := unb.n (LET at :675-678)
            lreq = pmap_get(st.list_requests, self)
            for unb in sorted(
                (o for o in fld(lreq, "objs") if is_unbound_pvc(o)), key=_ckey
            ):
                if not has(unb, "spec"):
                    bound = rec_from(unb, spec=rec(pvname=fld(unb, "n")))
                else:
                    spec = rec_from(fld(unb, "spec"), pvname=fld(unb, "n"))
                    bound = rec_from(unb, spec=spec)
                out.append(
                    Succ("PVCHavePVCs", _call_api(st, i, "PVCDone", "Update", bound), None)
                )

        elif lbl == "PVCDone":
            out.append(Succ("PVCDone", _goto(st, i, "PVCStart"), None))

        else:  # pragma: no cover
            raise AssertionError(f"unknown label {lbl!r}")

    proc_bounds.append(len(out))  # start of the server block
    out.extend(_server_lanes(st, cfg))
    # tag each successor with its acting process (client index or server):
    # client i's block is [proc_bounds[i], proc_bounds[i+1])
    tagged: List[Succ] = []
    for p in range(len(cfg.clients)):
        tagged.extend(
            s._replace(proc=p) for s in out[proc_bounds[p] : proc_bounds[p + 1]]
        )
    tagged.extend(
        s._replace(proc=cfg.n_clients) for s in out[proc_bounds[-1] :]
    )
    return tagged


def _server_lanes(st: State, cfg: ModelConfig) -> List[Succ]:
    """APIStart (KubeAPI.tla:698-756): one lane per pending (list-)client."""
    out: List[Succ] = []
    # \E c \in PendingClients (KubeAPI.tla:441, :699)
    for c, req in st.requests:
        if fld(req, "status") != "Pending":
            continue
        op, robj = fld(req, "op"), fld(req, "obj")
        api, viol = st.api_state, None
        if op == "Create":  # :700-705
            if object_exists(api, robj):
                new_req = rec_from(req, status="Error")
            else:
                api = api | {write(robj)}
                new_req = rec_from(req, status="Ok")
        elif op == "Force":  # :706-715
            if object_exists(api, robj):
                api = frozenset(
                    write(robj) if is_version_of(o, robj) else o for o in api
                )
            else:
                api = api | {write(robj)}
            new_req = rec_from(req, status="Ok")
        elif op == "Get":  # :716-728; CHOOSE is deterministic - exactly one match
            matches = sorted((o for o in api if is_version_of(o, robj)), key=_ckey)
            if matches:
                chosen = matches[0]
                new_req = rec_from(req, obj=chosen, status="Ok")
                api = frozenset(
                    read(o, c) if is_version_of(o, chosen) else o for o in api
                )
            else:
                new_req = rec_from(req, status="Error")
        elif op == "Delete":  # :729-731
            if cfg.mutation != "delete_noop":
                api = frozenset(o for o in api if not is_version_of(o, robj))
            new_req = rec_from(req, status="Ok")
        elif op == "Update":  # :732-739 - optimistic concurrency via HasRead
            if any(is_version_of(o, robj) and has_read(o, c) for o in api):
                api = frozenset(
                    o for o in api if not is_version_of(o, robj)
                ) | {write(robj)}
                new_req = rec_from(req, status="Ok")
            else:
                new_req = rec_from(req, status="Error")
        else:  # :740-741 assert FALSE
            new_req, viol = req, "assert:348"
        out.append(
            Succ(
                "APIStart",
                st._replace(api_state=api, requests=pmap_set(st.requests, c, new_req)),
                viol,
            )
        )
    # \E c \in PendingListClients (KubeAPI.tla:442, :745-753)
    for c, lreq in st.list_requests:
        if fld(lreq, "status") != "Pending":
            continue
        kind = fld(lreq, "kind")
        objs = frozenset(o for o in st.api_state if fld(o, "k") == kind)
        new_lreq = rec_from(lreq, objs=objs, status="Ok")
        api = frozenset(
            read(o, c) if fld(o, "k") == kind else o for o in st.api_state
        )
        out.append(
            Succ(
                "APIStart",
                st._replace(
                    api_state=api, list_requests=pmap_set(st.list_requests, c, new_lreq)
                ),
                None,
            )
        )
    return out


# ---------------------------------------------------------------------------
# Invariants (KubeAPI.tla:776-789)
# ---------------------------------------------------------------------------


def _is_valid_api_object(o) -> bool:
    """IsValidAPIObject (KubeAPI.tla:378-384)."""
    dom = {k for k, _ in o}
    return (
        {"n", "k"} <= dom
        and dom <= {"n", "k", "vv", "spec", "status"}
        and isinstance(fld(o, "n"), str)
        and isinstance(fld(o, "k"), str)
    )


def type_ok(st: State) -> bool:
    """TypeOK (KubeAPI.tla:776-781)."""
    if not all(_is_valid_api_object(o) for o in st.api_state):
        return False
    for _, r in st.requests:
        if {k for k, _ in r} != {"op", "obj", "status"}:
            return False
        if fld(r, "op") not in ("Create", "Get", "Update", "Delete", "Force"):
            return False
        if not _is_valid_api_object(fld(r, "obj")):
            return False
        if fld(r, "status") not in ("Pending", "Ok", "Error"):
            return False
    for _, r in st.list_requests:
        if {k for k, _ in r} != {"kind", "objs", "status"}:
            return False
        if not all(
            _is_valid_api_object(o) and fld(o, "k") == fld(r, "kind")
            for o in fld(r, "objs")
        ):
            return False
        if fld(r, "status") not in ("Pending", "Ok", "Error"):
            return False
    return True


def only_one_version(st: State) -> bool:
    """OnlyOneVersion (KubeAPI.tla:787-789)."""
    objs = list(st.api_state)
    for a in range(len(objs)):
        for b in range(a + 1, len(objs)):
            if is_version_of(objs[a], objs[b]):
                return False
    return True


# ---------------------------------------------------------------------------
# BFS driver (explicit-state; the TLC-equivalent host checker)
# ---------------------------------------------------------------------------


class BFSResult(NamedTuple):
    generated: int
    distinct: int
    depth: int
    max_outdegree: int
    min_outdegree: int
    violations: List[Tuple[str, State]]
    levels: List[int]  # distinct states per BFS level (level 1 = Init)


def bfs(
    cfg: ModelConfig,
    check_invariants: bool = True,
    max_states: int = 10_000_000,
    collect_levels: bool = False,
    on_level=None,
) -> BFSResult:
    """Level-synchronous BFS over the reachable state graph.

    Mirrors TLC's accounting: initial states count toward both generated and
    distinct (MC.out:29-32); every enumerated successor counts as generated;
    distinct = unique states; depth = number of BFS levels with Init at
    level 1 (MC.out:1101).
    """
    inits = initial_states(cfg)
    seen: Dict[State, int] = {}
    generated = 0
    violations: List[Tuple[str, State]] = []
    frontier: List[State] = []
    for s in inits:
        generated += 1
        if s not in seen:
            seen[s] = 1
            frontier.append(s)
    depth = 1
    levels = [len(frontier)]
    max_out, min_out = 0, 1 << 30
    while frontier:
        if on_level is not None:
            on_level(depth, frontier)
        nxt: List[State] = []
        for s in frontier:
            succs = successors(s, cfg)
            generated += len(succs)
            outdeg = len({x.state for x in succs})
            max_out = max(max_out, outdeg)
            min_out = min(min_out, outdeg)
            if outdeg == 0:
                violations.append(("deadlock", s))
            for x in succs:
                if x.violation:
                    violations.append((x.violation, s))
                if x.state not in seen:
                    seen[x.state] = depth + 1
                    nxt.append(x.state)
                    if check_invariants:
                        if not type_ok(x.state):
                            violations.append(("TypeOK", x.state))
                        if not only_one_version(x.state):
                            violations.append(("OnlyOneVersion", x.state))
        if len(seen) > max_states:
            raise RuntimeError("state-space bound exceeded")
        frontier = nxt
        if frontier:
            depth += 1
            levels.append(len(frontier))
    return BFSResult(
        generated, len(seen), depth, max_out, min_out, violations, levels
    )
