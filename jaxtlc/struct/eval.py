"""TLA+ value semantics for the structural frontend (E1).

Evaluates the parser's ASTs over the oracle's canonical value model
(spec.oracle State docstring): sets are frozensets, records/functions
are key-sorted tuples of (key, value) pairs, sequences are tuples -
so states produced here compare equal to hand-oracle states directly.

Covers the full expression language of the reference's committed
translation (/root/reference/KubeAPI.tla:373-768) plus its invariants
and define-block operators (:376-446,776-789): DOMAIN, :> and @@, IF /
CASE / LET / CHOOSE, set filter/map, sequence ops (Head/Tail/Append/
\\o/Len), function sets [S -> T], EXCEPT paths, user operator
application, Assert.  CHOOSE picks the canonically-least witness
(deterministic; TLC's pick is also deterministic but order-internal -
for specs whose CHOOSE is semantically unique, e.g. KubeAPI's Get
:311 under the OnlyOneVersion invariant, the values agree).

Original implementation; TLC's evaluator is Java and none of it is
translated here.
"""

from __future__ import annotations

from itertools import product as _product
from typing import Dict, Optional

from ..spec.labels import DEFAULT_INIT
from .parser import Definition

_SORT_KEY = repr  # deterministic iteration order over set elements


class StructEvalError(ValueError):
    pass


class TlaAssertionError(ValueError):
    """A TLA+ Assert(...) fired during action evaluation."""

    def __init__(self, msg: str):
        super().__init__(msg)
        self.tla_msg = msg


class UnboundPrime(StructEvalError):
    """A primed variable was read before the action assigned it."""


class _Sentinel:
    def __init__(self, name):
        self.name = name

    def __repr__(self):
        return self.name


STRING = _Sentinel("STRING")
NAT = _Sentinel("Nat")
INT = _Sentinel("Int")

BUILTIN_SETS = {
    "STRING": STRING,
    "Nat": NAT,
    "Int": INT,
    "BOOLEAN": frozenset({False, True}),
}


def canon(v):
    """Canonicalize nested containers to the oracle value model.

    A tuple of (string, value) pairs reads as a string-keyed function -
    the only tuple shape the model cannot disambiguate from a sequence
    of string-first 2-tuples.  Genuine functions are always constructed
    key-sorted with distinct keys (record literal, _pairs_to_fn, EXCEPT,
    @@), so a duplicate or out-of-order key proves the value is really a
    SEQUENCE about to be silently reordered/misrouted: raise loudly
    instead (ADVICE.md eval.py:75)."""
    if isinstance(v, tuple) and v and all(
        isinstance(x, tuple) and len(x) == 2 and isinstance(x[0], str)
        for x in v
    ):
        keys = [k for k, _ in v]
        if len(set(keys)) != len(keys) or keys != sorted(keys):
            raise StructEvalError(
                "ambiguous value: a tuple of (string, value) pairs with "
                "duplicate or unsorted keys is a sequence that would be "
                f"misread as a string-keyed function: {v!r}"
            )
        return tuple(sorted((k, canon(x)) for k, x in v))
    if isinstance(v, tuple):
        return tuple(canon(x) for x in v)
    if isinstance(v, frozenset):
        return frozenset(canon(x) for x in v)
    return v


def is_fn(v) -> bool:
    """Function/record: non-empty tuple of (str, value) pairs.  The empty
    tuple is both the empty function and the empty sequence - all its
    uses below are consistent for either reading."""
    return isinstance(v, tuple) and all(
        isinstance(x, tuple) and len(x) == 2 and isinstance(x[0], str)
        for x in v
    )


def fn_apply(f, arg):
    if isinstance(f, tuple):
        if f and is_fn(f):
            for k, v in f:
                if k == arg:
                    return v
            raise StructEvalError(f"{arg!r} not in DOMAIN")
        if isinstance(arg, int) and 1 <= arg <= len(f):
            return f[arg - 1]
        raise StructEvalError(f"index {arg!r} outside sequence/function")
    raise StructEvalError(f"cannot apply non-function {f!r}")


def fn_domain(f):
    if isinstance(f, tuple):
        if f and is_fn(f):
            return frozenset(k for k, _ in f)
        return frozenset(range(1, len(f) + 1))
    raise StructEvalError(f"DOMAIN of non-function {f!r}")


def fn_merge(left, right):
    """left @@ right: domain union, left-biased (TLC's TLC.tla @@)."""
    if not (is_fn(left) and is_fn(right)):
        raise StructEvalError("@@ expects functions")
    d = dict(right)
    d.update(dict(left))
    return tuple(sorted(d.items()))


class Evaluator:
    """Expression evaluator over a module's definitions + constants."""

    def __init__(self, defs: Dict[str, Definition],
                 constants: Dict[str, object]):
        self.defs = defs
        self.constants = constants

    # -- name resolution ---------------------------------------------------

    def _resolve_name(self, name: str, env: dict, primed: Optional[dict]):
        if env is not None and name in env:
            v = env[name]
            if isinstance(v, Definition):
                if v.params:
                    raise StructEvalError(
                        f"operator {name} needs {len(v.params)} arguments"
                    )
                return self.eval(v.body, env, primed)
            return v
        if name in self.constants:
            return self.constants[name]
        if name in BUILTIN_SETS:
            return BUILTIN_SETS[name]
        d = self.defs.get(name)
        if d is not None:
            if d.params:
                raise StructEvalError(
                    f"operator {name} needs {len(d.params)} arguments"
                )
            return self.eval(d.body, env, primed)
        raise StructEvalError(f"unknown name {name!r}")

    # -- evaluation --------------------------------------------------------

    def eval(self, ast, env: dict, primed: Optional[dict] = None):
        op = ast[0]
        if op in ("num", "str", "bool"):
            return ast[1]
        if op == "name":
            return self._resolve_name(ast[1], env, primed)
        if op == "prime":
            if primed is None or ast[1] not in primed:
                raise UnboundPrime(f"{ast[1]}' read before assignment")
            return primed[ast[1]]
        if op == "setlit":
            return frozenset(self.eval(x, env, primed) for x in ast[1])
        if op == "tuple":
            return tuple(self.eval(x, env, primed) for x in ast[1])
        if op == "record":
            return tuple(sorted(
                (k, self.eval(x, env, primed)) for k, x in ast[1]
            ))
        if op == "apply":
            return fn_apply(
                self.eval(ast[1], env, primed), self.eval(ast[2], env, primed)
            )
        if op == "domain":
            return fn_domain(self.eval(ast[1], env, primed))
        if op == "not":
            return not self._bool(ast[1], env, primed)
        if op == "and":
            return all(self._bool(x, env, primed) for x in ast[1])
        if op == "or":
            return any(self._bool(x, env, primed) for x in ast[1])
        if op == "implies":
            return (not self._bool(ast[1], env, primed)) or self._bool(
                ast[2], env, primed
            )
        if op == "cmp":
            return self._cmp(ast, env, primed)
        if op == "binop":
            return self._binop(ast, env, primed)
        if op == "if":
            c = self._bool(ast[1], env, primed)
            return self.eval(ast[2] if c else ast[3], env, primed)
        if op == "case":
            for g, e in ast[1]:
                if self._bool(g, env, primed):
                    return self.eval(e, env, primed)
            if ast[2] is not None:
                return self.eval(ast[2], env, primed)
            raise StructEvalError("CASE: no arm matched and no OTHER")
        if op == "let":
            env2 = dict(env)
            for name, params, body in ast[1]:
                if params:
                    env2[name] = Definition(name, params, body)
                else:
                    # non-parameterized LET bindings are evaluated eagerly
                    # (their value cannot depend on later bindings)
                    env2[name] = self.eval(body, env2, primed)
            return self.eval(ast[2], env2, primed)
        if op == "choose":
            _, var, dom_ast, pred = ast
            dom = self._set(dom_ast, env, primed)
            for x in sorted(dom, key=_SORT_KEY):
                env2 = dict(env)
                env2[var] = x
                if self._bool(pred, env2, primed):
                    return x
            raise StructEvalError("CHOOSE: no witness")
        if op in ("forall", "exists"):
            _, names, dom_ast, body = ast
            dom = sorted(self._set(dom_ast, env, primed), key=_SORT_KEY)

            def results():
                # short-circuit like TLC: a witness/falsifier stops
                # enumeration before later combos can raise
                for combo in _product(dom, repeat=len(names)):
                    env2 = dict(env)
                    env2.update(zip(names, combo))
                    yield self._bool(body, env2, primed)

            return all(results()) if op == "forall" else any(results())
        if op == "setfilter":
            _, var, dom_ast, pred = ast
            dom = self._set(dom_ast, env, primed)
            out = []
            for x in sorted(dom, key=_SORT_KEY):
                env2 = dict(env)
                env2[var] = x
                if self._bool(pred, env2, primed):
                    out.append(x)
            return frozenset(out)
        if op == "setmap":
            _, expr, var, dom_ast = ast
            dom = self._set(dom_ast, env, primed)
            out = []
            for x in sorted(dom, key=_SORT_KEY):
                env2 = dict(env)
                env2[var] = x
                out.append(self.eval(expr, env2, primed))
            return frozenset(out)
        if op == "fnlit":
            _, var, dom_ast, body = ast
            dom = self._set(dom_ast, env, primed)
            pairs = []
            for x in sorted(dom, key=_SORT_KEY):
                env2 = dict(env)
                env2[var] = x
                pairs.append((x, self.eval(body, env2, primed)))
            return _pairs_to_fn(pairs)
        if op == "funcset":
            dom = sorted(self._set(ast[1], env, primed), key=_SORT_KEY)
            rng = sorted(self._set(ast[2], env, primed), key=_SORT_KEY)
            fns = []
            for values in _product(rng, repeat=len(dom)):
                fns.append(_pairs_to_fn(list(zip(dom, values))))
            return frozenset(fns)
        if op == "except":
            f = self.eval(ast[1], env, primed)
            for path_asts, val_ast in ast[2]:
                path = [self.eval(p, env, primed) for p in path_asts]
                f = self._except(f, path, val_ast, env, primed)
            return f
        if op == "atref":
            if "@" not in env:
                raise StructEvalError("@ outside EXCEPT")
            return env["@"]
        if op == "call":
            return self._call(ast, env, primed)
        if op == "unchanged":
            raise StructEvalError(
                "UNCHANGED outside an action conjunction"
            )
        if op in ("box", "leadsto", "spec"):
            raise StructEvalError(
                f"temporal operator {op} has no state-level value"
            )
        raise StructEvalError(f"unhandled AST node {op!r}")

    # -- helpers -----------------------------------------------------------

    def _bool(self, ast, env, primed) -> bool:
        v = self.eval(ast, env, primed)
        if not isinstance(v, bool):
            raise StructEvalError(f"expected BOOLEAN, got {v!r}")
        return v

    def _set(self, ast, env, primed) -> frozenset:
        v = self.eval(ast, env, primed)
        if not isinstance(v, frozenset):
            raise StructEvalError(f"expected a set, got {v!r}")
        return v

    def _cmp(self, ast, env, primed):
        _, sym, la, ra = ast
        a = self.eval(la, env, primed)
        b = self.eval(ra, env, primed)
        if sym == "=":
            return a == b
        if sym == "#":
            return a != b
        if sym in (r"\in", r"\notin"):
            inn = self._member(a, b)
            return inn if sym == r"\in" else not inn
        if sym == r"\subseteq":
            if not (isinstance(a, frozenset) and isinstance(b, frozenset)):
                raise StructEvalError("\\subseteq expects sets")
            return a <= b
        try:
            return {"<": a < b, ">": a > b, "<=": a <= b, ">=": a >= b}[sym]
        except TypeError:
            raise StructEvalError(f"cannot order {a!r} {sym} {b!r}")

    @staticmethod
    def _member(a, b) -> bool:
        if isinstance(b, frozenset):
            return a in b
        if b is STRING:
            # model values (defaultInitValue) are not strings in TLC
            return isinstance(a, str) and a != DEFAULT_INIT
        if b is NAT:
            return isinstance(a, int) and not isinstance(a, bool) and a >= 0
        if b is INT:
            return isinstance(a, int) and not isinstance(a, bool)
        raise StructEvalError(f"\\in over non-set {b!r}")

    def _binop(self, ast, env, primed):
        _, sym, la, ra = ast
        a = self.eval(la, env, primed)
        b = self.eval(ra, env, primed)
        if sym in (r"\cup", r"\cap", "\\"):
            if not (isinstance(a, frozenset) and isinstance(b, frozenset)):
                raise StructEvalError(f"{sym} expects sets")
            return {r"\cup": a | b, r"\cap": a & b, "\\": a - b}[sym]
        if sym in ("+", "-", "*"):
            if not (isinstance(a, int) and isinstance(b, int)):
                raise StructEvalError(f"{sym} expects integers")
            return {"+": a + b, "-": a - b, "*": a * b}[sym]
        if sym == "..":
            return frozenset(range(a, b + 1))
        if sym == r"\o":
            if not (isinstance(a, tuple) and isinstance(b, tuple)):
                raise StructEvalError("\\o expects sequences")
            return a + b
        if sym == "@@":
            return fn_merge(a, b)
        if sym == ":>":
            if not isinstance(a, str):
                raise StructEvalError(":> key must be a string here")
            return ((a, b),)
        raise StructEvalError(f"unhandled binop {sym!r}")

    def _except(self, f, path, val_ast, env, primed):
        idx = path[0]
        old = fn_apply(f, idx)
        if len(path) > 1:
            val = self._except(old, path[1:], val_ast, env, primed)
        else:
            env2 = dict(env)
            env2["@"] = old
            val = self.eval(val_ast, env2, primed)
        if isinstance(f, tuple) and f and is_fn(f):
            return tuple(sorted(
                (k, val if k == idx else v) for k, v in f
            ))
        if isinstance(f, tuple) and isinstance(idx, int):
            return f[: idx - 1] + (val,) + f[idx:]
        raise StructEvalError("EXCEPT on a non-function")

    def _call(self, ast, env, primed):
        _, name, args = ast
        target = None
        if env is not None and isinstance(env.get(name), Definition):
            target = env[name]
        elif name in self.defs:
            target = self.defs[name]
        if target is not None:
            if len(target.params) != len(args):
                raise StructEvalError(
                    f"{name} expects {len(target.params)} args, "
                    f"got {len(args)}"
                )
            env2 = dict(env)
            for p, a in zip(target.params, args):
                env2[p] = self.eval(a, env, primed)
            return self.eval(target.body, env2, primed)
        vals = [self.eval(a, env, primed) for a in args]
        if name == "Cardinality":
            (s,) = vals
            if not isinstance(s, frozenset):
                raise StructEvalError("Cardinality expects a set")
            return len(s)
        if name == "Len":
            (s,) = vals
            if not isinstance(s, tuple) or is_fn(s) and s:
                raise StructEvalError("Len expects a sequence")
            return len(s)
        if name == "Head":
            (s,) = vals
            if not isinstance(s, tuple) or not s:
                raise StructEvalError("Head of empty/non-sequence")
            return s[0]
        if name == "Tail":
            (s,) = vals
            if not isinstance(s, tuple) or not s:
                raise StructEvalError("Tail of empty/non-sequence")
            return s[1:]
        if name == "Append":
            s, e = vals
            if not isinstance(s, tuple):
                raise StructEvalError("Append expects a sequence")
            return s + (e,)
        if name == "Assert":
            cond, msg = vals
            if cond is not True:
                raise TlaAssertionError(str(msg))
            return True
        raise StructEvalError(f"unknown operator {name!r}")


def _pairs_to_fn(pairs):
    """Key-typed function literal: string keys -> sorted pairs; 1..n ->
    sequence; empty -> () (empty function == empty sequence)."""
    if not pairs:
        return ()
    if all(isinstance(k, str) for k, _ in pairs):
        return tuple(sorted(pairs))
    keys = {k for k, _ in pairs}
    if keys == set(range(1, len(pairs) + 1)):
        return tuple(v for _, v in sorted(pairs))
    raise StructEvalError(
        "function domains must be strings or 1..n here"
    )
