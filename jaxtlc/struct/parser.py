"""Full-module TLA+ parser for the structural frontend (E1).

Parses real TLA+ modules - the reference's own committed translation
included (/root/reference/KubeAPI.tla:373-768) - into expression ASTs:

* junction lists by column alignment (the PlusCal translator's bullet
  style; TLA+'s /\\ and \\/ list grammar),
* IF/THEN/ELSE, CASE [] arms, LET..IN, CHOOSE,
* EXCEPT with multi-update paths (![c].status = ...),
* set literals / filters {x \\in S : P} / maps {e : x \\in S},
* sequences <<...>>, \\o, Head/Tail/Append/Len,
* records [f |-> e], singleton functions k :> v, left-biased merge @@,
* DOMAIN, function sets [S -> T], function literals [x \\in S |-> e],
* quantifiers with multiple binders (\\A o1, o2 \\in S : P),
* temporal property shapes: P ~> Q and []P ~> Q (MC.out's checked
  property forms), WF_vars(Next)-style Spec conjunctions.

The parse obligations mirror what SANY reports for the reference model
(MC.out:8-24).  Original hand-rolled design - no code from TLC/SANY
(which are Java) is or could be reused.

AST nodes are plain tuples (texpr-compatible where the form overlaps):
  ("num", n) ("str", s) ("bool", b) ("name", x) ("prime", x)
  ("and", [..]) ("or", [..]) ("not", e) ("implies", a, b)
  ("box", e) ("leadsto", a, b)
  ("cmp", op, a, b)            op in = # < > <= >= \\in \\notin \\subseteq
  ("binop", op, a, b)          op in \\cup \\cap \\ + - .. \\o @@ :>
  ("apply", f, arg)            f[arg] and r.field (field as ("str", f))
  ("call", name, [args])       operator application Foo(a, b)
  ("setlit", [..]) ("setfilter", var, dom, pred) ("setmap", e, var, dom)
  ("tuple", [..]) ("record", [(f, e), ..])
  ("fnlit", var, dom, body) ("funcset", dom, rng)
  ("except", f, [([path..], val), ..])   path elements are value ASTs
  ("if", c, t, e) ("case", [(g, e), ..], other|None)
  ("let", [(name, params, body), ..], e)
  ("choose", var, dom, pred)
  ("forall", [vars], dom, body) ("exists", [vars], dom, body)
  ("unchanged", [names]) ("domain", e) ("atref",)
"""

from __future__ import annotations

import re
from typing import Dict, List, NamedTuple, Optional, Tuple


class StructParseError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Comment stripping (position-preserving) and module header handling
# ---------------------------------------------------------------------------


def strip_comments(src: str) -> str:
    """Blank out (* .. *) blocks (nested), \\* line comments, module
    header/separator lines - preserving every character position."""
    out = list(src)
    i, n = 0, len(src)
    depth = 0
    in_str = False
    while i < n:
        c = src[i]
        if depth == 0 and not in_str and c == '"':
            in_str = True
            i += 1
            continue
        if in_str:
            if c == '"':
                in_str = False
            i += 1
            continue
        if src.startswith("(*", i):
            depth += 1
            out[i] = out[i + 1] = " "
            i += 2
            continue
        if depth > 0:
            if src.startswith("*)", i):
                depth -= 1
                out[i] = out[i + 1] = " "
                i += 2
                continue
            if c != "\n":
                out[i] = " "
            i += 1
            continue
        if src.startswith("\\*", i):
            j = src.find("\n", i)
            j = n if j < 0 else j
            for k in range(i, j):
                out[k] = " "
            i = j
            continue
        i += 1
    text = "".join(out)
    # blank module header / separator / terminator lines
    lines = text.split("\n")
    for li, ln in enumerate(lines):
        if re.match(r"^\s*----+\s*MODULE\s+\w+\s*----+\s*$", ln):
            lines[li] = " " * len(ln)
        elif re.match(r"^\s*(----+|====+)\s*$", ln):
            lines[li] = " " * len(ln)
    return "\n".join(lines)


def module_name(src: str) -> Optional[str]:
    m = re.search(r"^\s*----+\s*MODULE\s+(\w+)\s*----+\s*$", src, re.M)
    return m.group(1) if m else None


# ---------------------------------------------------------------------------
# Tokenizer (line/column aware)
# ---------------------------------------------------------------------------


class Tok(NamedTuple):
    kind: str
    val: str
    line: int
    col: int


_TOKEN_RE = re.compile(
    r"""
    (?P<ws>[ \t\r\f]+)
  | (?P<land>/\\)
  | (?P<lor>\\/)
  | (?P<forall>\\A\b)
  | (?P<exists>\\E\b)
  | (?P<op>\\(?:in|notin|subseteq|cup|cap|o)\b)
  | (?P<setminus>\\)
  | (?P<leadsto>~>)
  | (?P<implies>=>)
  | (?P<mapsto>\|->)
  | (?P<arrow>->)
  | (?P<defeq>==)
  | (?P<range>\.\.)
  | (?P<le><=)
  | (?P<ge>>=)
  | (?P<ltup><<)
  | (?P<rtup>>>)
  | (?P<box>\[\])
  | (?P<colongt>:>)
  | (?P<atat>@@)
  | (?P<eq>=)
  | (?P<ne>\#|/=)
  | (?P<lt><)
  | (?P<gt>>)
  | (?P<num>\d+)
  | (?P<str>"[^"]*")
  | (?P<name>[A-Za-z_][A-Za-z0-9_]*)
  | (?P<sym>[()\[\]{},.~'+\-!@:*])
    """,
    re.VERBOSE,
)


def tokenize(text: str) -> List[Tok]:
    toks: List[Tok] = []
    for line_no, line in enumerate(text.split("\n"), start=1):
        pos = 0
        while pos < len(line):
            m = _TOKEN_RE.match(line, pos)
            if not m:
                raise StructParseError(
                    f"line {line_no}: cannot tokenize {line[pos:pos+20]!r}"
                )
            if m.lastgroup != "ws":
                toks.append(Tok(m.lastgroup, m.group(), line_no, pos))
            pos = m.end()
    return toks


# ---------------------------------------------------------------------------
# Module structure
# ---------------------------------------------------------------------------


class Definition(NamedTuple):
    name: str
    params: Tuple[str, ...]
    body: tuple  # AST


class Module(NamedTuple):
    name: str
    extends: Tuple[str, ...]
    constants: Tuple[str, ...]
    variables: Tuple[str, ...]  # declaration order
    defs: Dict[str, Definition]
    def_order: Tuple[str, ...]


_DECL_KEYWORDS = {
    "CONSTANT", "CONSTANTS", "VARIABLE", "VARIABLES", "EXTENDS",
    "ASSUME", "ASSUMPTION", "THEOREM", "LOCAL", "INSTANCE",
}


def parse_module(src: str) -> Module:
    name = module_name(src) or ""
    toks = tokenize(strip_comments(src))
    extends: List[str] = []
    constants: List[str] = []
    variables: List[str] = []
    defs: Dict[str, Definition] = {}
    def_order: List[str] = []

    i, n = 0, len(toks)

    def is_def_start(j: int) -> bool:
        """name at column 0 followed by `==` or `(p, ..) ==`."""
        if toks[j].kind != "name" or toks[j].col != 0:
            return False
        if toks[j].val in _DECL_KEYWORDS:
            return False
        k = j + 1
        if k < n and toks[k].kind == "sym" and toks[k].val == "(":
            depth = 0
            while k < n:
                t = toks[k]
                if t.kind == "sym" and t.val == "(":
                    depth += 1
                elif t.kind == "sym" and t.val == ")":
                    depth -= 1
                    if depth == 0:
                        k += 1
                        break
                k += 1
        return k < n and toks[k].kind == "defeq"

    def unit_end(j: int) -> int:
        """First index >= j that starts a new top-level unit."""
        while j < n:
            t = toks[j]
            if t.col == 0 and t.kind == "name" and (
                t.val in _DECL_KEYWORDS or is_def_start(j)
            ):
                return j
            j += 1
        return n

    while i < n:
        t = toks[i]
        if t.kind == "name" and t.val == "EXTENDS" and t.col == 0:
            i += 1
            while i < n and toks[i].kind == "name":
                extends.append(toks[i].val)
                i += 1
                if i < n and toks[i].kind == "sym" and toks[i].val == ",":
                    i += 1
                else:
                    break
        elif t.kind == "name" and t.val in ("CONSTANT", "CONSTANTS") \
                and t.col == 0:
            i += 1
            while i < n and toks[i].kind == "name" \
                    and not (toks[i].col == 0 and (
                        toks[i].val in _DECL_KEYWORDS or is_def_start(i))):
                constants.append(toks[i].val)
                i += 1
                if i < n and toks[i].kind == "sym" and toks[i].val == ",":
                    i += 1
                else:
                    break
        elif t.kind == "name" and t.val in ("VARIABLE", "VARIABLES") \
                and t.col == 0:
            i += 1
            while i < n and toks[i].kind == "name" \
                    and not (toks[i].col == 0 and (
                        toks[i].val in _DECL_KEYWORDS or is_def_start(i))):
                variables.append(toks[i].val)
                i += 1
                if i < n and toks[i].kind == "sym" and toks[i].val == ",":
                    i += 1
                else:
                    break
        elif t.kind == "name" and t.val in ("ASSUME", "ASSUMPTION") \
                and t.col == 0:
            i = unit_end(i + 1)  # assumptions are not checked here
        elif is_def_start(i):
            dname = t.val
            j = i + 1
            params: List[str] = []
            if toks[j].kind == "sym" and toks[j].val == "(":
                j += 1
                while toks[j].kind == "name":
                    params.append(toks[j].val)
                    j += 1
                    if toks[j].kind == "sym" and toks[j].val == ",":
                        j += 1
                if not (toks[j].kind == "sym" and toks[j].val == ")"):
                    raise StructParseError(
                        f"{dname}: malformed parameter list"
                    )
                j += 1
            assert toks[j].kind == "defeq"
            j += 1
            end = unit_end(j)
            body_toks = toks[j:end]
            if dname == "Spec":
                body = _parse_spec_body(body_toks)
            else:
                body = _ExprParser(body_toks).parse_full()
            if dname not in defs:
                def_order.append(dname)
            defs[dname] = Definition(dname, tuple(params), body)
            i = end
        else:
            raise StructParseError(
                f"unexpected top-level token {t.val!r} at line {t.line}"
            )

    return Module(
        name=name,
        extends=tuple(extends),
        constants=tuple(constants),
        variables=tuple(variables),
        defs=defs,
        def_order=tuple(def_order),
    )


def _parse_spec_body(toks: List[Tok]) -> tuple:
    """Spec == /\\ Init /\\ [][Next]_vars /\\ WF_vars(Next): extract the
    temporal normal form structurally (("spec", init, next, fairness));
    fairness is "wf_next" | None."""
    text = " ".join(t.val for t in toks)
    init = next_ = None
    fairness = None
    m = re.search(r"\[\]\s*\[\s*(\w+)\s*\]\s*_", text)
    if m:
        next_ = m.group(1)
    m = re.search(r"WF_\w*\s*\(\s*(\w+)\s*\)", text)
    if m and next_ and m.group(1) == next_:
        fairness = "wf_next"
    for t in toks:
        if t.kind == "name" and t.val not in ("WF_vars", "SF_vars") \
                and t.val != next_:
            init = t.val
            break
    return ("spec", init, next_, fairness)


# ---------------------------------------------------------------------------
# Expression parser (precedence climbing + junction-boundary stack)
# ---------------------------------------------------------------------------

_KEYWORDS_STOP = {"THEN", "ELSE", "IN", "OTHER", "EXCEPT", "LET", "CASE",
                  "IF", "CHOOSE", "UNCHANGED", "DOMAIN", "SUBSET", "UNION"}

_EOF = Tok("eof", "", 1 << 30, -1)


class _ExprParser:
    def __init__(self, toks: List[Tok]):
        self.toks = toks
        self.i = 0
        # junction boundaries: (line, col) of the current bullet; tokens
        # at line > bullet line with col <= bullet col end the item
        self.bounds: List[Tuple[int, int]] = []

    # -- token access ------------------------------------------------------

    def _blocked(self, t: Tok) -> bool:
        if not self.bounds:
            return False
        bl, bc = self.bounds[-1]
        return t.line > bl and t.col <= bc

    def peek(self) -> Tok:
        if self.i >= len(self.toks):
            return _EOF
        t = self.toks[self.i]
        return _EOF if self._blocked(t) else t

    def peek_raw(self) -> Tok:
        return self.toks[self.i] if self.i < len(self.toks) else _EOF

    def next(self) -> Tok:
        t = self.peek()
        if t.kind != "eof":
            self.i += 1
        return t

    def expect(self, kind: str, what: str = "") -> Tok:
        t = self.next()
        if t.kind != kind and t.val != kind:
            raise StructParseError(
                f"expected {what or kind}, got {t.val!r} (line {t.line})"
            )
        return t

    def expect_kw(self, kw: str):
        t = self.next()
        if t.kind != "name" or t.val != kw:
            raise StructParseError(
                f"expected {kw}, got {t.val!r} (line {t.line})"
            )

    # -- entry points ------------------------------------------------------

    def parse_full(self) -> tuple:
        e = self.parse_expr()
        t = self.peek()
        if t.kind != "eof":
            raise StructParseError(
                f"trailing input {t.val!r} at line {t.line}"
            )
        return e

    def parse_expr(self) -> tuple:
        return self.parse_leadsto()

    # -- precedence levels -------------------------------------------------

    def parse_leadsto(self) -> tuple:
        left = self.parse_implies()
        if self.peek().kind == "leadsto":
            self.next()
            return ("leadsto", left, self.parse_leadsto())
        return left

    def parse_implies(self) -> tuple:
        left = self.parse_or()
        if self.peek().kind == "implies":
            self.next()
            return ("implies", left, self.parse_implies())
        return left

    def parse_or(self) -> tuple:
        left = self.parse_and()
        items = [left]
        while self.peek().kind == "lor":
            self.next()
            items.append(self.parse_and())
        return items[0] if len(items) == 1 else ("or", items)

    def parse_and(self) -> tuple:
        left = self.parse_not()
        items = [left]
        while self.peek().kind == "land":
            self.next()
            items.append(self.parse_not())
        return items[0] if len(items) == 1 else ("and", items)

    def parse_not(self) -> tuple:
        t = self.peek()
        if t.kind == "sym" and t.val == "~":
            self.next()
            return ("not", self.parse_not())
        if t.kind == "box":
            self.next()
            return ("box", self.parse_not())
        if t.kind in ("land", "lor"):
            return self.parse_junction(t)
        if t.kind in ("forall", "exists"):
            return self.parse_quantifier(t)
        return self.parse_cmp()

    def parse_junction(self, bullet: Tok) -> tuple:
        kind = bullet.kind
        col = bullet.col
        items: List[tuple] = []
        while True:
            t = self.peek()
            if t.kind != kind or t.col != col:
                break
            self.next()
            self.bounds.append((t.line, col))
            try:
                items.append(self.parse_expr())
            finally:
                self.bounds.pop()
        if not items:
            raise StructParseError(
                f"empty junction list at line {bullet.line}"
            )
        node = "and" if kind == "land" else "or"
        return items[0] if len(items) == 1 else (node, items)

    def parse_quantifier(self, t: Tok) -> tuple:
        self.next()
        names = [self.expect("name").val]
        while self.peek().kind == "sym" and self.peek().val == ",":
            self.next()
            names.append(self.expect("name").val)
        op = self.next()
        if (op.kind, op.val) != ("op", r"\in"):
            raise StructParseError(
                f"expected \\in in quantifier (line {t.line})"
            )
        dom = self.parse_cmp_operand()
        self.expect(":", "':' in quantifier")
        body = self.parse_expr()
        node = "forall" if t.kind == "forall" else "exists"
        return (node, names, dom, body)

    _CMP_KINDS = {"eq": "=", "ne": "#", "lt": "<", "gt": ">", "le": "<=",
                  "ge": ">="}

    def parse_cmp(self) -> tuple:
        left = self.parse_cmp_operand()
        t = self.peek()
        if t.kind in self._CMP_KINDS:
            self.next()
            return ("cmp", self._CMP_KINDS[t.kind], left,
                    self.parse_cmp_operand())
        if t.kind == "op" and t.val in (r"\in", r"\notin", r"\subseteq"):
            self.next()
            return ("cmp", t.val, left, self.parse_cmp_operand())
        return left

    def parse_cmp_operand(self) -> tuple:
        return self.parse_setop()

    def parse_setop(self) -> tuple:
        # @@ (left, loosest here) < \cup/\cap/\ < :> ; then .. + - \o
        left = self.parse_setop2()
        while self.peek().kind == "atat":
            self.next()
            left = ("binop", "@@", left, self.parse_setop2())
        return left

    def parse_setop2(self) -> tuple:
        left = self.parse_colongt()
        while True:
            t = self.peek()
            if t.kind == "op" and t.val in (r"\cup", r"\cap"):
                self.next()
                left = ("binop", t.val, left, self.parse_colongt())
            elif t.kind == "setminus":
                self.next()
                left = ("binop", "\\", left, self.parse_colongt())
            else:
                return left

    def parse_colongt(self) -> tuple:
        left = self.parse_range()
        if self.peek().kind == "colongt":
            self.next()
            return ("binop", ":>", left, self.parse_range())
        return left

    def parse_range(self) -> tuple:
        left = self.parse_add()
        if self.peek().kind == "range":
            self.next()
            return ("binop", "..", left, self.parse_add())
        return left

    def parse_add(self) -> tuple:
        left = self.parse_mul()
        while True:
            t = self.peek()
            if t.kind == "sym" and t.val in ("+", "-"):
                self.next()
                left = ("binop", t.val, left, self.parse_mul())
            else:
                return left

    def parse_mul(self) -> tuple:
        left = self.parse_concat()
        while True:
            t = self.peek()
            if t.kind == "sym" and t.val == "*":
                self.next()
                left = ("binop", "*", left, self.parse_concat())
            else:
                return left

    def parse_concat(self) -> tuple:
        left = self.parse_postfix()
        while self.peek().kind == "op" and self.peek().val == r"\o":
            self.next()
            left = ("binop", r"\o", left, self.parse_postfix())
        return left

    def parse_postfix(self) -> tuple:
        e = self.parse_atom()
        while True:
            t = self.peek()
            if t.kind == "sym" and t.val == "[":
                self.next()
                arg = self.parse_expr()
                args = [arg]
                while self.peek().kind == "sym" and self.peek().val == ",":
                    self.next()
                    args.append(self.parse_expr())
                self.expect("]")
                for a in args:
                    e = ("apply", e, a)
            elif t.kind == "sym" and t.val == ".":
                # field access - but only when followed by a name (guards
                # against tokenizer surprises)
                nxt = self.toks[self.i + 1] if self.i + 1 < len(self.toks) \
                    else _EOF
                if nxt.kind != "name":
                    return e
                self.next()
                f = self.next()
                e = ("apply", e, ("str", f.val))
            elif t.kind == "sym" and t.val == "'":
                self.next()
                if e[0] != "name":
                    raise StructParseError(
                        f"prime on non-variable (line {t.line})"
                    )
                e = ("prime", e[1])
            else:
                return e

    # -- atoms -------------------------------------------------------------

    def parse_atom(self) -> tuple:
        t = self.next()
        if t.kind == "num":
            return ("num", int(t.val))
        if t.kind == "str":
            return ("str", t.val[1:-1])
        if t.kind == "name":
            return self.parse_name_atom(t)
        if t.kind == "sym" and t.val == "(":
            e = self.parse_expr()
            self.expect(")")
            return e
        if t.kind == "sym" and t.val == "{":
            return self.parse_braces()
        if t.kind == "ltup":
            items = []
            if self.peek().kind != "rtup":
                items.append(self.parse_expr())
                while self.peek().kind == "sym" and self.peek().val == ",":
                    self.next()
                    items.append(self.parse_expr())
            self.expect("rtup", ">>")
            return ("tuple", items)
        if t.kind == "sym" and t.val == "[":
            return self.parse_brackets()
        if t.kind == "sym" and t.val == "@":
            return ("atref",)
        if t.kind == "sym" and t.val == "-":
            inner = self.parse_postfix()
            return ("binop", "-", ("num", 0), inner)
        raise StructParseError(
            f"unexpected token {t.val!r} (line {t.line})"
        )

    def parse_name_atom(self, t: Tok) -> tuple:
        v = t.val
        if v == "TRUE":
            return ("bool", True)
        if v == "FALSE":
            return ("bool", False)
        if v == "IF":
            c = self.parse_expr()
            self.expect_kw("THEN")
            a = self.parse_expr()
            self.expect_kw("ELSE")
            b = self.parse_expr()
            return ("if", c, a, b)
        if v == "CASE":
            arms = []
            other = None
            while True:
                if self.peek().kind == "name" and self.peek().val == "OTHER":
                    self.next()
                    self.expect("arrow", "->")
                    other = self.parse_expr()
                else:
                    g = self.parse_expr()
                    self.expect("arrow", "->")
                    arms.append((g, self.parse_expr()))
                if self.peek().kind == "box":
                    self.next()
                    continue
                break
            return ("case", arms, other)
        if v == "LET":
            binds = []
            while True:
                dname = self.expect("name").val
                params: List[str] = []
                if self.peek().kind == "sym" and self.peek().val == "(":
                    self.next()
                    while self.peek().kind == "name":
                        params.append(self.next().val)
                        if self.peek().kind == "sym" \
                                and self.peek().val == ",":
                            self.next()
                    self.expect(")")
                self.expect("defeq", "==")
                body = self.parse_expr()
                binds.append((dname, tuple(params), body))
                nt = self.peek()
                if nt.kind == "name" and nt.val == "IN":
                    self.next()
                    break
                if nt.kind == "name" and nt.val not in _KEYWORDS_STOP \
                        and self._looks_like_let_def():
                    continue
                self.expect_kw("IN")
            return ("let", binds, self.parse_expr())
        if v == "CHOOSE":
            var = self.expect("name").val
            op = self.next()
            if (op.kind, op.val) != ("op", r"\in"):
                raise StructParseError("expected \\in in CHOOSE")
            dom = self.parse_cmp_operand()
            self.expect(":", "':' in CHOOSE")
            pred = self.parse_expr()
            return ("choose", var, dom, pred)
        if v == "UNCHANGED":
            t2 = self.peek()
            if t2.kind == "ltup":
                self.next()
                names = [self.expect("name").val]
                while self.peek().kind == "sym" and self.peek().val == ",":
                    self.next()
                    names.append(self.expect("name").val)
                self.expect("rtup", ">>")
                return ("unchanged", names)
            return ("unchanged", [self.expect("name").val])
        if v == "DOMAIN":
            return ("domain", self.parse_postfix())
        if self.peek().kind == "sym" and self.peek().val == "(":
            self.next()
            args = [self.parse_expr()]
            while self.peek().kind == "sym" and self.peek().val == ",":
                self.next()
                args.append(self.parse_expr())
            self.expect(")")
            return ("call", v, args)
        return ("name", v)

    def _looks_like_let_def(self) -> bool:
        """After one LET binding, is the next token run another
        `name [(params)] ==` binding?"""
        j = self.i
        toks = self.toks
        if j >= len(toks) or toks[j].kind != "name":
            return False
        j += 1
        if j < len(toks) and toks[j].kind == "sym" and toks[j].val == "(":
            depth = 0
            while j < len(toks):
                if toks[j].val == "(":
                    depth += 1
                elif toks[j].val == ")":
                    depth -= 1
                    if depth == 0:
                        j += 1
                        break
                j += 1
        return j < len(toks) and toks[j].kind == "defeq"

    def parse_braces(self) -> tuple:
        """{ } | {a, b} | {x \\in S : P} | {e : x \\in S}"""
        if self.peek().kind == "sym" and self.peek().val == "}":
            self.next()
            return ("setlit", [])
        save = self.i
        t = self.peek()
        if t.kind == "name":
            self.next()
            t2 = self.peek()
            if t2.kind == "op" and t2.val == r"\in":
                self.next()
                dom = self.parse_cmp_operand()
                t3 = self.peek()
                if t3.kind == "sym" and t3.val == ":":
                    self.next()
                    pred = self.parse_expr()
                    self.expect("}")
                    return ("setfilter", t.val, dom, pred)
            self.i = save
        first = self.parse_expr()
        t2 = self.peek()
        if t2.kind == "sym" and t2.val == ":":
            self.next()
            var = self.expect("name").val
            op = self.next()
            if (op.kind, op.val) != ("op", r"\in"):
                raise StructParseError("expected \\in in set map")
            dom = self.parse_cmp_operand()
            self.expect("}")
            return ("setmap", first, var, dom)
        items = [first]
        while self.peek().kind == "sym" and self.peek().val == ",":
            self.next()
            items.append(self.parse_expr())
        self.expect("}")
        return ("setlit", items)

    def parse_brackets(self) -> tuple:
        """[f |-> e, ..] | [x \\in S |-> e] | [f EXCEPT !..] | [S -> T]"""
        save = self.i
        t = self.peek()
        if t.kind == "name":
            self.next()
            t2 = self.peek()
            if t2.kind == "mapsto":
                self.i = save
                return self.parse_record_literal()
            if t2.kind == "op" and t2.val == r"\in":
                self.next()
                dom = self.parse_expr()
                self.expect("mapsto", "|->")
                body = self.parse_expr()
                self.expect("]")
                return ("fnlit", t.val, dom, body)
            self.i = save
        fexpr = self.parse_expr()
        t2 = self.peek()
        if t2.kind == "name" and t2.val == "EXCEPT":
            self.next()
            updates = []
            while True:
                self.expect("!", "'!' in EXCEPT")
                path = []
                while True:
                    t3 = self.peek()
                    if t3.kind == "sym" and t3.val == "[":
                        self.next()
                        path.append(self.parse_expr())
                        self.expect("]")
                    elif t3.kind == "sym" and t3.val == ".":
                        self.next()
                        path.append(("str", self.expect("name").val))
                    else:
                        break
                if not path:
                    raise StructParseError("empty EXCEPT path")
                self.expect("eq", "=")
                val = self.parse_expr()
                updates.append((path, val))
                t3 = self.next()
                if t3.kind == "sym" and t3.val == "]":
                    break
                if not (t3.kind == "sym" and t3.val == ","):
                    raise StructParseError(
                        f"expected , or ] in EXCEPT (line {t3.line})"
                    )
            return ("except", fexpr, updates)
        if t2.kind == "arrow":
            self.next()
            rng = self.parse_expr()
            self.expect("]")
            return ("funcset", fexpr, rng)
        raise StructParseError(
            f"unsupported bracket expression (line {t.line})"
        )

    def parse_record_literal(self) -> tuple:
        fields = []
        while True:
            f = self.expect("name").val
            self.expect("mapsto", "|->")
            fields.append((f, self.parse_expr()))
            t = self.next()
            if t.kind == "sym" and t.val == "]":
                break
            if not (t.kind == "sym" and t.val == ","):
                raise StructParseError("expected , or ] in record literal")
        return ("record", fields)


def parse_expression(src: str) -> tuple:
    """Parse a standalone expression (tests / trace expressions)."""
    return _ExprParser(tokenize(strip_comments(src))).parse_full()
