"""Structural TLA+ frontend (E1): parse and execute real TLA+ modules.

Unlike jaxtlc.gen (the finite-domain subset compiler), this package
parses the reference's own module text - including the committed PlusCal
translation in /root/reference/KubeAPI.tla:373-768 - into ASTs and
executes the transition relation directly:

* parser:  full-module tokenizer + junction-list expression grammar
* eval:    TLA+ value semantics over the oracle's canonical value model
* actions: next-state enumeration (the constraint-program reading of a
           translation action)
* oracle:  BFS model checker over the interpreted relation
* shapes:  finite-universe inference for device compilation
* compile: AST -> lane kernel for the fused device engine
* backend: the lane kernel as a SpecBackend for the production engines
           (fused single-device, mesh-sharded, supervised/segmented)
* cache:   in-process step-compile memo + persistent XLA compile cache
"""
