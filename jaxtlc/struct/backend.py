"""Struct-compiled specs as a SpecBackend (the engine seam).

The LaneCompiler step (struct.compile) becomes a pluggable kernel for
the production engines: the fused single-device loop
(engine.bfs.make_backend_engine), the mesh-sharded loop
(engine.sharded.make_sharded_engine) and the resil supervisor's
segmented drivers all consume this backend, so struct specs get
segmented execution, fingerprint-space mesh sharding, checkpoints,
auto-regrow and two-tier adaptive stepping through the exact code paths
the hand kernel uses - no private BFS loop (the round-6 tentpole; the
old struct/engine.py loop is retired).

The compiler emits a batch step ([B, L, F] directly); the engines
expect a per-row kernel they vmap themselves, so the step here is a
B=1 wrapper - under vmap the batch dimension is re-introduced by
tracing, producing the same fused XLA as the native batch compile.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..engine.backend import SpecBackend
from ..engine.bfs import VIOL_ASSERT
from .codec import StructCodec
from .compile import LaneCompiler
from .loader import StructModel
from .shapes import infer_shapes, typeok_hints

VIOL_INVARIANT_BASE = 100


def struct_viol_names(model: StructModel) -> Dict[int, str]:
    """Violation-code name overrides for a struct model (invariants by
    cfg order + the PlusCal assertion channel)."""
    names = {VIOL_ASSERT: "Failure of PlusCal assertion"}
    for k, name in enumerate(model.invariants):
        names[VIOL_INVARIANT_BASE + k] = f"Invariant {name} is violated"
    return names


def struct_backend(model: StructModel,
                   check_deadlock: bool = True) -> SpecBackend:
    """Compile `model` into a SpecBackend: parse -> shape-infer ->
    lane-compile, the pipeline struct.cache memoizes in-process."""
    system = model.system
    hints = typeok_hints(system.ev, model.invariants, system.variables)
    var_shapes = infer_shapes(system.ev, system.variables,
                              system.init_ast, system.next_ast,
                              hints=hints)
    cdc = StructCodec(system.variables, var_shapes)
    compiler = LaneCompiler(system.ev, system.variables, var_shapes, cdc)
    batch_step = compiler.build_step(system.next_ast)
    inv_fns = [
        compiler.build_invariant(ast) for ast in model.invariants.values()
    ]
    F = cdc.n_fields

    # discover the lane structure (labels) with a shape-only trace
    jax.eval_shape(batch_step, jax.ShapeDtypeStruct((1, F), jnp.int32))
    labels: List[str] = list(compiler.labels)
    action_names: Tuple[str, ...] = tuple(sorted(set(labels)))
    lane_action = jnp.asarray(
        [action_names.index(x) for x in labels], jnp.int32
    )

    def step(vec):
        succs, valid, ovf, afail = batch_step(vec[None])
        return succs[0], valid[0], lane_action, afail[0], ovf[0]

    def inv_check(vec):
        bits = jnp.int32(0)
        for k, fn in enumerate(inv_fns):
            bits = bits | (fn(vec[None])[0].astype(jnp.int32) << k)
        return bits

    def initial_vectors():
        inits = system.initial_states()
        return np.stack([cdc.encode(st) for st in inits])

    return SpecBackend(
        cdc=cdc,
        step=step,
        n_lanes=len(labels),
        inv_check=inv_check,
        inv_codes=tuple(
            VIOL_INVARIANT_BASE + k for k in range(len(model.invariants))
        ),
        initial_vectors=initial_vectors,
        labels=action_names,
        viol_names=struct_viol_names(model),
        lane_action=lane_action,
        check_deadlock=check_deadlock,
    )


def canonical_constants(model: StructModel) -> dict:
    """JSON-stable rendering of the model's resolved constants (the
    checkpoint-meta / cache-key form; frozensets sort, everything else
    goes through repr so model values and numbers stay distinct)."""
    out = {}
    for k in sorted(model.constants):
        v = model.constants[k]
        out[k] = (sorted(map(repr, v)) if isinstance(v, frozenset)
                  else repr(v))
    return out


def struct_meta_config(model: StructModel) -> dict:
    """The checkpoint `config` stanza for struct runs: digest +
    canonical constants + invariant list - everything that shapes the
    compiled step, so a -recover against a different spec text or
    overrides is a loud mismatch, never a silent misrun."""
    return {
        "frontend": "struct",
        "root": model.root_name,
        "digest": model.source_digest,
        "constants": canonical_constants(model),
        "invariants": list(model.invariants),
    }
