"""Struct-compiled specs as a SpecBackend (the engine seam).

The LaneCompiler step (struct.compile) becomes a pluggable kernel for
the production engines: the fused single-device loop
(engine.bfs.make_backend_engine), the mesh-sharded loop
(engine.sharded.make_sharded_engine) and the resil supervisor's
segmented drivers all consume this backend, so struct specs get
segmented execution, fingerprint-space mesh sharding, checkpoints,
auto-regrow and two-tier adaptive stepping through the exact code paths
the hand kernel uses - no private BFS loop (the round-6 tentpole; the
old struct/engine.py loop is retired).

The compiler emits a batch step ([B, L, F] directly); the engines
expect a per-row kernel they vmap themselves, so the step here is a
B=1 wrapper - under vmap the batch dimension is re-introduced by
tracing, producing the same fused XLA as the native batch compile.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..engine.backend import SpecBackend
from ..engine.bfs import VIOL_ASSERT
from .codec import StructCodec
from .compile import LaneCompiler, TrapPolicy
from .loader import StructModel
from .shapes import infer_shapes, typeok_hints

VIOL_INVARIANT_BASE = 100


def struct_viol_names(model: StructModel) -> Dict[int, str]:
    """Violation-code name overrides for a struct model (invariants by
    cfg order + the PlusCal assertion channel)."""
    names = {VIOL_ASSERT: "Failure of PlusCal assertion"}
    for k, name in enumerate(model.invariants):
        names[VIOL_INVARIANT_BASE + k] = f"Invariant {name} is violated"
    return names


def make_cert_check(cdc: StructCodec, card_specs=()):
    """The on-device runtime certificate check for a (narrowed) codec:
    every VALID generated successor's raw int32 fields must hold a
    legal code of its universe claim (0 <= field <= max_code - checked
    PRE-packing, so escapes that would wrap into a legal-looking word
    are still caught), and every cardinality-bounded mask variable's
    popcount must fit its certified bound.  Returns a scalar bool:
    "some reachable state violated a claimed bound" - the signal the
    engines latch into the sticky certificate column."""
    from jax import lax

    max_code = jnp.asarray(np.asarray(cdc.max_codes(), np.int32))
    specs = tuple((int(off), int(nf), int(bound))
                  for off, nf, bound in card_specs)

    def cert_check(flat, valid):
        bad = (flat < 0) | (flat > max_code[None, :])
        viol = bad.any(axis=1)
        for off, nf, bound in specs:
            pc = lax.population_count(
                flat[:, off:off + nf].astype(jnp.uint32)
            )
            viol = viol | (pc.sum(axis=1).astype(jnp.int32) > bound)
        return (viol & valid).any()

    return cert_check


def _card_specs(cdc: StructCodec, variables, card_bounds) -> list:
    """(field offset, field count, bound) triples for the mask-layout
    variables whose certified cardinality bound actually constrains."""
    from .codec import MaskLeaf

    out = []
    for v, lay in zip(variables, cdc.layouts):
        bound = (card_bounds or {}).get(v)
        if bound is None or not isinstance(lay, MaskLeaf):
            continue
        if bound < lay.n_bits:
            out.append((cdc.offsets[v], lay.n_fields, bound))
    return out


def struct_backend(model: StructModel,
                   check_deadlock: bool = True,
                   bounds=None,
                   elide: bool = True,
                   coverage: bool = False,
                   symmetry: bool = False,
                   por: bool = False) -> SpecBackend:
    """Compile `model` into a SpecBackend: parse -> shape-infer ->
    lane-compile, the pipeline struct.cache memoizes in-process.

    `bounds` (a CERTIFIED analysis.absint.BoundReport) swaps the
    widened inferred shapes for the certified reachable bounds: the
    codec's enum universes, mask bit counts and sequence caps shrink
    to the certified ranges (fewer packed uint32 words through the
    fingerprint/sort/probe path) and, with `elide` (default), the
    compiler drops the range traps and slot lanes the bounds prove
    safe while the backend carries the on-device certificate check
    that re-verifies every claimed bound on every generated state -
    so an unsound bound turns the verdict loud instead of silently
    narrowing real states away.  `elide=False` narrows the codec but
    keeps every trap and carries no certificate (the mesh-sharded
    engines, which have no certificate column: the encode traps stay
    the soundness story there).

    `coverage` compiles the device coverage plane in (ISSUE 11): the
    lane walker assigns a stable site id to every guard conjunct,
    branch arm, action-position binder body and update conjunct, and
    the backend exposes an obs.coverage.CoveragePlane whose count hook
    the engines fold into the cumulative per-site counter leaf.  The
    site table opens with one "action" site per action (the PR 3
    per-action coverage lines are a prefix view of per-site coverage).
    Pure telemetry: coverage-on results are bit-for-bit coverage-off
    results.

    `symmetry` / `por` (RESOLVED bools; the tri-state flags resolve via
    engine.bfs.resolve_symmetry / resolve_por) attach the state-space
    reduction capability (engine.reduce.ReduceOps, ISSUE 18):
    symmetry canonicalizes every successor to its orbit representative
    over the statically-verified symmetric constant sets
    (analysis.symfind) before fingerprinting, POR prunes commutative
    interleavings through singleton ample sets.  Verdicts, invariant
    outcomes and rendered traces are preserved; DISTINCT/GENERATED
    counts legitimately shrink, which is why both default off."""
    system = model.system
    trap_policy = None
    cert = False
    if bounds is not None and getattr(bounds, "certified", False):
        var_shapes = {v: bounds.bounds[v] for v in system.variables}
        if elide:
            trap_policy = TrapPolicy(
                elide_range=True,
                card_bounds=dict(bounds.card_bounds),
            )
            cert = True
    else:
        bounds = None
        hints = typeok_hints(system.ev, model.invariants,
                             system.variables)
        var_shapes = infer_shapes(system.ev, system.variables,
                                  system.init_ast, system.next_ast,
                                  hints=hints)
    cdc = StructCodec(system.variables, var_shapes)
    compiler = LaneCompiler(system.ev, system.variables, var_shapes,
                            cdc, trap_policy=trap_policy)
    batch_step = compiler.build_step(system.next_ast)
    inv_fns = [
        compiler.build_invariant(ast) for ast in model.invariants.values()
    ]
    F = cdc.n_fields

    # discover the lane structure (labels) with a shape-only trace
    jax.eval_shape(batch_step, jax.ShapeDtypeStruct((1, F), jnp.int32))
    labels: List[str] = list(compiler.labels)
    action_names: Tuple[str, ...] = tuple(sorted(set(labels)))
    lane_action = jnp.asarray(
        [action_names.index(x) for x in labels], jnp.int32
    )
    trap_stats = (compiler.trap_sites, compiler.elided_traps,
                  compiler.reduced_slot_lanes)

    def step(vec):
        succs, valid, ovf, afail = batch_step(vec[None])
        return succs[0], valid[0], lane_action, afail[0], ovf[0]

    def inv_check(vec):
        bits = jnp.int32(0)
        for k, fn in enumerate(inv_fns):
            bits = bits | (fn(vec[None])[0].astype(jnp.int32) << k)
        return bits

    def initial_vectors():
        inits = system.initial_states()
        return np.stack([cdc.encode(st) for st in inits])

    cert_check = None
    if cert:
        cert_check = make_cert_check(
            cdc, _card_specs(cdc, system.variables, bounds.card_bounds)
        )

    plane = None
    if coverage:
        from ..obs.coverage import (
            CoveragePlane,
            Site,
            action_site_table,
        )

        cov_fn = compiler.build_cov(system.next_ast)
        # discover the site table with a shape-only trace (the same
        # discipline as the label discovery above)
        jax.eval_shape(
            cov_fn,
            jax.ShapeDtypeStruct((1, F), jnp.int32),
            jax.ShapeDtypeStruct((1,), jnp.bool_),
            jax.ShapeDtypeStruct((1, len(labels)), jnp.bool_),
        )
        fine_sites = tuple(
            Site(key=k, kind=kind, action=a, loc=desc)
            for k, kind, a, desc in compiler.cov.sites
        )
        sites = tuple(action_site_table(model.root_name, action_names)
                      ) + fine_sites
        label_ids = jnp.arange(len(action_names), dtype=jnp.int32)

        def cov_count(batch, mask, valid):
            # action-prefix sites = per-action generated counts, the
            # same [L, n_actions] fold the engine's gen counters use -
            # one accounting, two renderings
            lane_counts = valid.sum(axis=0).astype(jnp.uint32)
            act = (
                (lane_action[:, None] == label_ids[None, :])
                * lane_counts[:, None]
            ).sum(axis=0).astype(jnp.uint32)
            return jnp.concatenate([act, cov_fn(batch, mask, valid)])

        plane = CoveragePlane(sites=sites, count=cov_count,
                              module=model.root_name)

    reduce_ops = None
    if symmetry or por:
        from ..analysis.speclint import analyze_spec
        from ..analysis.symfind import analyze_reduction
        from ..engine.reduce import ReduceOps, build_plan

        rep = analyze_reduction(
            model, analyze_spec(model, var_shapes=var_shapes)
        )
        plan, dropped = (None, {})
        if symmetry:
            plan, dropped = build_plan(cdc, rep.symmetric_sets)
        safe_ids: Tuple[int, ...] = ()
        if por:
            safe_ids = tuple(
                action_names.index(a) for a in rep.safe_actions
                if a in action_names
            )
        reduce_ops = ReduceOps(
            plan=plan,
            safe_ids=safe_ids,
            por=bool(por),
            sym_sets=tuple(sorted(plan.sym_sets.items()))
            if plan is not None else (),
            dropped_sets=tuple(sorted(
                {**rep.rejected_sets, **dropped}.items()
            )),
        )

    viol_names = struct_viol_names(model)
    if bounds is not None:
        from ..engine.bfs import VIOL_SLOT_OVERFLOW

        viol_names[VIOL_SLOT_OVERFLOW] = (
            "Codec slot overflow / certified-bound escape (narrowed "
            "codec: a value left the certified reachable range - "
            "re-run with -no-narrow; if that passes, report the spec: "
            "the bound certification is unsound)"
        )
    backend = SpecBackend(
        cdc=cdc,
        step=step,
        n_lanes=len(labels),
        inv_check=inv_check,
        inv_codes=tuple(
            VIOL_INVARIANT_BASE + k for k in range(len(model.invariants))
        ),
        initial_vectors=initial_vectors,
        labels=action_names,
        viol_names=viol_names,
        lane_action=lane_action,
        check_deadlock=check_deadlock,
        cert_check=cert_check,
        coverage=plane,
        reduce=reduce_ops,
    )
    # trap-audit surface (preflight renders which traps remain and why)
    backend.cdc.trap_stats = trap_stats
    return backend


def canonical_constants(model: StructModel) -> dict:
    """JSON-stable rendering of the model's resolved constants (the
    checkpoint-meta / cache-key form; frozensets sort, everything else
    goes through repr so model values and numbers stay distinct)."""
    out = {}
    for k in sorted(model.constants):
        v = model.constants[k]
        out[k] = (sorted(map(repr, v)) if isinstance(v, frozenset)
                  else repr(v))
    return out


def struct_meta_config(model: StructModel, bounds=None) -> dict:
    """The checkpoint `config` stanza for struct runs: digest +
    canonical constants + invariant list - everything that shapes the
    compiled step, so a -recover against a different spec text or
    overrides is a loud mismatch, never a silent misrun.  A narrowed
    run additionally records its bound digest: a narrowed checkpoint
    resumed without -narrow (or with re-derived different bounds) is a
    different carry layout and must mismatch loudly."""
    out = {
        "frontend": "struct",
        "root": model.root_name,
        "digest": model.source_digest,
        "constants": canonical_constants(model),
        "invariants": list(model.invariants),
    }
    if bounds is not None:
        out["bound_digest"] = bounds.digest()
    return out
