"""AST -> tensor-lane compiler for structural specs (E1 device path).

Compiles the parsed translation (struct.parser ASTs) against the
inferred shapes (struct.shapes) and codec layouts (struct.codec) into a
branchless batched step function for the fused device engine - the same
compilation target the hand-written KubeAPI kernel and the gen-subset
compiler feed, now derived from the module text alone.

TPU-first design decisions (vs TLC's heap interpreter):

* Enumerated universes become integer lanes; record field access is a
  precomputed table gather ([U] int32 per (record-universe, field)).
* Sets over record universes are bitmask planes; set algebra is
  bitwise; quantifiers/filters/maps/CHOOSE over them LIFT the bound
  variable onto a fresh trailing tensor axis (the binder becomes the
  arange of the universe) so the body compiles ONCE, vectorized -
  no per-element Python unrolling, no data-dependent control flow.
* Nested two-set quantifiers whose predicate is state-independent
  (constant [U,U] plane, e.g. OnlyOneVersion's IsVersionOf) reduce via
  a matmul - the MXU does the pair enumeration.
* Nondeterminism fans into static lanes: disjuncts, bound parameters
  over constant sets, per-key unrolls for quantifiers over partial-
  function domains (PendingClients), and k-th-set-bit slot lanes for
  `with x \\in <set-valued expr>` picks, with an overflow flag when a
  state's set exceeds the slot budget (the hand kernel's convention).

Reference semantics: /root/reference/KubeAPI.tla:455-768; every path is
differentially pinned against the structural oracle (tests/test_struct
_engine.py).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from ..spec.labels import DEFAULT_INIT
from .codec import EnumLeaf, MaskLeaf, RecNode, SeqNode, StructCodec, layout_of
from .eval import _SORT_KEY, BUILTIN_SETS, Evaluator, is_fn
from .parser import Definition
from .shapes import (
    SAtoms,
    SBool,
    SInt,
    SRec,
    SSeq,
    SSet,
    SUnion,
    Shape,
    ShapeError,
    _mentions_prime_static,
)

UNROLL_LIMIT = 12  # quantifier domains up to this size unroll in Python
SLOT_CAP = 4  # lanes per set-valued nondeterministic pick


class TrapPolicy:
    """What the certified bound report (analysis.absint) lets the
    compiler drop: range traps whose value interval is PROVEN inside
    the destination universe, and slot lanes / slot-overflow traps on
    set binders whose certified cardinality bound fits.  Built only
    from a CERTIFIED BoundReport; the runtime certificate column
    re-verifies every claim on device, so an unsound bound turns the
    verdict loud instead of silently narrowing states away."""

    def __init__(self, elide_range: bool = False,
                 card_bounds: Optional[Dict[str, int]] = None):
        self.elide_range = bool(elide_range)
        self.card_bounds = dict(card_bounds or {})


class CompileError(ValueError):
    pass


class CovCollector:
    """Site table + per-trace visit conditions for the device coverage
    plane (obs.coverage, ISSUE 11).

    The lane walker already visits every guard conjunct, IF/CASE arm,
    action-position binder and update conjunct while fanning the
    nondeterminism into lanes; with a collector attached it REGISTERS a
    stable site for each (action label, construct) pair on first
    encounter - keyed by the AST node's identity, which is stable for
    the lifetime of the parsed module, so retraces (eval_shape then
    jit) resolve to the same table - and records, per trace, the lane
    condition under which that site is visited.  build_cov folds the
    conditions into one ``[n_sites] uint32`` visit-increment vector per
    block; the engines accumulate it exactly like the obs ring (pure
    telemetry, no control flow).

    Visit semantics (the device analogue of TLC's evaluation counts):
    a guard conjunct is visited once per state whose enumeration path
    reaches it (the guard-so-far at that point - TLC's short-circuit),
    a branch arm once per state selecting it, a binder body once per
    (state, binding) with the binding live, and an update conjunct once
    per state in which its lane fires (the completed successor path)."""

    def __init__(self):
        self.sites: List[tuple] = []  # (key, kind, action, desc)
        self._index: Dict = {}  # (label, kind, id(ast)) -> site idx
        self._ordinals: Dict = {}  # (label, kind) -> next ordinal
        self._kept = []  # keep registered AST nodes alive (id() keys)
        self.active = False
        self._contribs = None  # per-trace [(idx, cond LB/LC)]

    _TAG = {"guard": "g", "branch": "b", "quant": "e", "effect": "w",
            "unchanged": "u"}

    def site(self, label, kind, ast, desc="") -> int:
        label = label or "?"
        key3 = (label, kind, id(ast))
        idx = self._index.get(key3)
        if idx is None:
            n = self._ordinals.get((label, kind), 0)
            self._ordinals[(label, kind)] = n + 1
            key = f"{label}.{self._TAG[kind]}{n}"
            idx = len(self.sites)
            self.sites.append((key, kind, label, desc))
            self._index[key3] = idx
            self._kept.append(ast)
        return idx

    def hit(self, idx: int, cond) -> None:
        if self._contribs is not None:
            self._contribs.append((idx, cond))

    def begin(self):
        self.active = True
        self._contribs = []

    def end(self):
        out = self._contribs
        self.active = False
        self._contribs = None
        return out


# ---------------------------------------------------------------------------
# Lane values
# ---------------------------------------------------------------------------


class LV:
    """Base lane value; arr shapes are [B, d1..d_depth] (B=batch or 1)."""

    depth = 0


class LC(LV):
    """Static host value (bindings, literals, folded subexpressions)."""

    def __init__(self, value):
        self.value = value

    def __repr__(self):
        return f"LC({self.value!r})"


class LB(LV):
    def __init__(self, arr, depth=0):
        self.arr = arr
        self.depth = depth


class LI(LV):
    """Integer lanes, optionally carrying a CERTIFIED (lo, hi) interval.

    Bounds originate only where they are unconditionally true of the
    lanes: committed-state decodes (state fields hold legal codes - the
    encode traps enforce it, and certificate mode re-verifies it on
    device), literals, and interval arithmetic over bounded operands.
    Derived reads that can yield the absent code (-1) - field gathers,
    dynamic sequence indexing - carry no bounds, so the range-trap
    elision (analysis.absint TrapPolicy) can never fire on them."""

    def __init__(self, arr, depth=0, bounds=None):
        self.arr = arr
        self.depth = depth
        self.bounds = bounds  # Optional[(lo, hi)]


def _int_bounds(lv) -> Optional[Tuple[int, int]]:
    if isinstance(lv, LC):
        v = int(lv.value)
        return (v, v)
    if isinstance(lv, LI):
        return lv.bounds
    return None


class LE(LV):
    """Enum-coded value: arr holds indices into leaf.values; -1 = absent
    / invalid (guard-unreachable paths)."""

    def __init__(self, arr, leaf: EnumLeaf, depth=0):
        self.arr = arr
        self.leaf = leaf
        self.depth = depth


class LM(LV):
    """Set as bool plane over elem leaf universe.

    `depth` counts the PREFIX lift axes the mask varies over; bits has
    shape [B, l1..l_depth, U] - the universe axis is always last and is
    NOT a lift axis (until a quantifier lifts over this very mask)."""

    def __init__(self, bits, elem_leaf: EnumLeaf, depth=0):
        self.bits = bits
        self.elem_leaf = elem_leaf
        self.depth = depth


class LRec(LV):
    """Structural record/function: ordered (field, present, value)."""

    def __init__(self, entries):
        # entries: list[(fname, LB|LC(bool) present, LV value)]
        self.entries = list(entries)

    def get(self, fname):
        for f, p, v in self.entries:
            if f == fname:
                return p, v
        return None, None


class LSeq(LV):
    def __init__(self, length, slots, leaf: EnumLeaf, cap: int):
        self.length = length  # LI
        self.slots = slots  # list[LE] (leaf), padded with index 0
        self.leaf = leaf
        self.cap = cap


def _align(arr, from_depth: int, to_depth: int):
    for _ in range(to_depth - from_depth):
        arr = arr[..., None]
    return arr


def _binop_arrs(a_arr, a_d, b_arr, b_d):
    d = max(a_d, b_d)
    return _align(a_arr, a_d, d), _align(b_arr, b_d, d), d


# ---------------------------------------------------------------------------
# Compiler
# ---------------------------------------------------------------------------


class LaneCompiler:
    def __init__(self, ev: Evaluator, variables: Tuple[str, ...],
                 var_shapes: Dict[str, Shape], codec: StructCodec,
                 sweep_vars: frozenset = frozenset(),
                 trap_policy: Optional[TrapPolicy] = None):
        self.ev = ev
        self.variables = variables
        self.var_shapes = var_shapes
        self.codec = codec
        # certified-bound trap policy (None = every trap stays); the
        # counters below feed the preflight trap-audit report
        self.trap_policy = trap_policy
        self.trap_sites = 0
        self.elided_traps = 0
        self.reduced_slot_lanes = 0
        # swept constants (jaxtlc.serve.sweep): CONSTANT names promoted
        # to read-only codec fields so their value is RUNTIME data - one
        # compiled step serves every configuration of the constants
        # class.  decode_state hands them to expressions like any state
        # variable (env wins over ev.constants in _comp_name); the spec
        # never primes them, so build_step passes them through verbatim
        self.sweep_vars = frozenset(sweep_vars)
        self._field_tables: Dict = {}
        self._trans_tables: Dict = {}
        self._pred_tables: Dict = {}
        self.trap = None  # LB set when a guard-unreachable encode happens
        # device coverage plane (obs.coverage): a CovCollector while a
        # build_cov trace is walking, None otherwise - build_step's own
        # walks never record (self.cov.active gates every hook)
        self.cov: Optional[CovCollector] = None

    # -- tables ------------------------------------------------------------

    def _leaf_of_shape(self, shape) -> EnumLeaf:
        lay = layout_of(shape)
        if isinstance(lay, EnumLeaf):
            return lay
        if isinstance(lay, (MaskLeaf, SeqNode)):
            # a set stored as a mask still has a (tiny) subset-enum leaf
            # when nested inside an enumerated record (KubeAPI's vv);
            # likewise a bounded sequence whose universe is small enough
            # that the NARROWED record containing it enum-encodes
            # (certified bounds can shrink a RecNode variable into one
            # EnumLeaf - its sequence fields then gather through this)
            key = ("enum", shape)
            hit = self._field_tables.get(key)
            if hit is None:
                hit = EnumLeaf(shape)
                self._field_tables[key] = hit
            return hit
        raise CompileError(f"shape not enum-layout: {shape}")

    def field_table(self, leaf: EnumLeaf, fname: str,
                    tgt: EnumLeaf) -> np.ndarray:
        """[U] int32: index of value.fname in tgt's universe; -1 absent."""
        key = (id(leaf), fname, id(tgt))
        t = self._field_tables.get(key)
        if t is None:
            rows = []
            for v in leaf.values:
                if isinstance(v, tuple) and is_fn(v):
                    d = dict(v)
                    if fname in d:
                        rows.append(tgt.index.get(d[fname], -1))
                    else:
                        rows.append(-1)
                else:
                    rows.append(-1)
            t = np.asarray(rows, np.int32)
            self._field_tables[key] = t
        return t

    def presence_table(self, leaf: EnumLeaf, fname: str) -> np.ndarray:
        key = (id(leaf), fname, "present")
        t = self._field_tables.get(key)
        if t is None:
            t = np.asarray([
                isinstance(v, tuple) and is_fn(v) and fname in dict(v)
                for v in leaf.values
            ], bool)
            self._field_tables[key] = t
        return t

    def trans_table(self, src: EnumLeaf, dst: EnumLeaf) -> np.ndarray:
        key = (id(src), id(dst))
        t = self._trans_tables.get(key)
        if t is None:
            t = np.asarray(
                [dst.index.get(v, -1) for v in src.values], np.int32
            )
            self._trans_tables[key] = t
        return t

    def choose_rank_table(self, leaf: EnumLeaf) -> np.ndarray:
        """rank[i] = position of leaf.values[i] under the evaluator's
        CHOOSE iteration order (sorted by _SORT_KEY): the device witness
        pick minimizes this rank so both engines agree."""
        key = (id(leaf), "#choose_rank")
        t = self._pred_tables.get(key)
        if t is None:
            order = sorted(range(len(leaf.values)),
                           key=lambda i: _SORT_KEY(leaf.values[i]))
            t = np.zeros(len(leaf.values), np.int32)
            for r, i in enumerate(order):
                t[i] = r
            self._pred_tables[key] = t
        return t

    def value_pred_table(self, leaf: EnumLeaf, fn) -> np.ndarray:
        key = (id(leaf), fn.__name__, getattr(fn, "_key", None))
        t = self._pred_tables.get(key)
        if t is None:
            t = np.asarray([bool(fn(v)) for v in leaf.values], bool)
            self._pred_tables[key] = t
        return t

    # -- conversions -------------------------------------------------------

    def to_leaf(self, lv: LV, leaf: EnumLeaf) -> LE:
        """Any lane value -> enum index in `leaf` (arr; -1 = absent)."""
        if isinstance(lv, LE):
            if lv.leaf is leaf:
                return lv
            t = self.trans_table(lv.leaf, leaf)
            idx = jnp.where(
                lv.arr >= 0, jnp.asarray(t)[jnp.maximum(lv.arr, 0)], -1
            )
            return LE(idx, leaf, lv.depth)
        if isinstance(lv, LC):
            return LE(jnp.full((1,), leaf.index.get(lv.value, -1),
                               jnp.int32), leaf, 0)
        if isinstance(lv, LB):
            if isinstance(leaf.shape, SBool):
                return LE(lv.arr.astype(jnp.int32), leaf, lv.depth)
            return self.to_leaf(
                LE(lv.arr.astype(jnp.int32),
                   self._leaf_of_shape(SBool()), lv.depth), leaf)
        if isinstance(lv, LI):
            sh = leaf.shape
            if isinstance(sh, SInt):
                self.trap_sites += 1
                b = lv.bounds
                if (self.trap_policy is not None
                        and self.trap_policy.elide_range
                        and b is not None
                        and b[0] >= sh.lo and b[1] <= sh.hi):
                    # the certified interval proves the range trap
                    # unreachable: compile it out (the runtime
                    # certificate column re-verifies the claim)
                    self.elided_traps += 1
                    return LE(lv.arr - sh.lo, leaf, lv.depth)
                # range trap: a value outside the (widened) inferred
                # range encodes as -1 and halts the engine loudly
                ok = (lv.arr >= sh.lo) & (lv.arr <= sh.hi)
                return LE(jnp.where(ok, lv.arr - sh.lo, -1), leaf,
                          lv.depth)
            raise CompileError("int value into non-int leaf")
        if isinstance(lv, LRec):
            return self._rec_to_leaf(lv, leaf)
        if isinstance(lv, LM):
            return self._mask_to_leaf(lv, leaf)
        if isinstance(lv, LSeq):
            return self._seq_to_leaf(lv, leaf)
        raise CompileError(f"cannot convert {type(lv).__name__} to leaf")

    def _resolve_alt(self, leaf: EnumLeaf, klass):
        """(offset, alt EnumLeaf) of the `klass` alternative inside a
        union leaf (universe concatenation order = alts order)."""
        sh = leaf.shape
        if isinstance(sh, klass):
            return 0, leaf
        if isinstance(sh, SUnion):
            off = 0
            for alt in sh.alts:
                alt_leaf = self._leaf_of_shape(alt)
                if isinstance(alt, klass):
                    return off, alt_leaf
                off += len(alt_leaf.values)
        raise CompileError(f"no {klass.__name__} alternative in {sh}")

    def _rec_to_leaf(self, lv: LRec, leaf: EnumLeaf) -> LE:
        off, rec_leaf = self._resolve_alt(leaf, SRec)
        sh: SRec = rec_leaf.shape
        # mixed-radix index, first field most significant (codec
        # universe order: itertools.product over field-sorted options)
        radices = []
        for f, s, opt in sh.fields:
            n = len(self._leaf_of_shape(s).values)
            radices.append(n + 1 if opt else n)
        idx = None
        depth = 0
        for (f, s, opt), radix in zip(sh.fields, radices):
            p, v = lv.get(f)
            fleaf = self._leaf_of_shape(s)
            if v is None:
                if not opt:
                    raise CompileError(f"required field {f} missing")
                code = jnp.zeros((1,), jnp.int32)
                pd = 0
            else:
                fe = self.to_leaf(v, fleaf)
                code = fe.arr + (1 if opt else 0)
                pd = fe.depth
                if opt and not (isinstance(p, LC) and p.value is True):
                    # dynamic presence
                    parr = p.arr if isinstance(p, LB) else jnp.full(
                        (1,), bool(p.value))
                    code, parr2, pd = _binop_arrs(code, pd, parr,
                                                  p.depth if isinstance(
                                                      p, LB) else 0)
                    code = jnp.where(parr2, code, 0)
            if idx is None:
                idx, depth = code, pd
            else:
                ia, ca, depth = _binop_arrs(idx, depth, code, pd)
                idx = ia * radix + ca
        if idx is None:
            idx = jnp.zeros((1,), jnp.int32)
        return LE(idx + off, leaf, depth)

    def _mask_to_leaf(self, lv: LM, leaf: EnumLeaf) -> LE:
        off, set_leaf = self._resolve_alt(leaf, SSet)
        sh: SSet = set_leaf.shape
        elem_leaf = self._leaf_of_shape(sh.elem)
        src = lv
        if lv.elem_leaf is not elem_leaf:
            src = self.remask(lv, elem_leaf)
        n = len(elem_leaf.values)
        weights = jnp.asarray([1 << i for i in range(n)], jnp.int32)
        idx = (src.bits.astype(jnp.int32) * weights).sum(axis=-1)
        return LE(idx + off, leaf, src.depth)

    def _seq_to_leaf(self, lv: LSeq, leaf: EnumLeaf) -> LE:
        off, seq_leaf = self._resolve_alt(leaf, SSeq)
        sh: SSeq = seq_leaf.shape
        n = len(self._leaf_of_shape(sh.elem).values)
        # universe order: length-0 block, then length-1, ... ; within a
        # block, position 0 most significant
        idx = None
        depth = 0
        for k in range(sh.cap + 1):
            block_off = sum(n ** j for j in range(k))
            code = jnp.zeros((1,), jnp.int32)
            cd = 0
            for i in range(k):
                se = self.to_leaf(lv.slots[i], self._leaf_of_shape(sh.elem))
                ca, sa, cd = _binop_arrs(code, cd, se.arr, se.depth)
                code = ca * n + sa
            code = code + block_off
            la, ca2, d2 = _binop_arrs(lv.length.arr, lv.length.depth,
                                      code, cd)
            here = jnp.where(la == k, ca2, 0)
            if idx is None:
                idx, depth = here, d2
            else:
                ia, ha, depth = _binop_arrs(idx, depth, here, d2)
                idx = ia + ha
        return LE(idx + off, leaf, depth)

    def remask(self, lv: LM, elem_leaf: EnumLeaf) -> LM:
        """Re-express a mask over a different element universe."""
        t = self.trans_table(lv.elem_leaf, elem_leaf)
        n = len(elem_leaf.values)
        onehot = np.zeros((len(lv.elem_leaf.values), n), bool)
        for i, j in enumerate(t):
            if j >= 0:
                onehot[i, j] = True
        m = jnp.asarray(onehot)
        bits = jnp.einsum("...u,uv->...v", lv.bits.astype(jnp.int32),
                          m.astype(jnp.int32)) > 0
        return LM(bits, elem_leaf, lv.depth)

    def remask_tracked(self, lv: LM, elem_leaf: EnumLeaf):
        """remask + lane-wise LB flag: a set bit had no image in the new
        universe (it was DROPPED - membership of it is False by
        construction, but equality through the reduced planes would lie)."""
        t = self.trans_table(lv.elem_leaf, elem_leaf)
        lost = jnp.asarray(t < 0)
        dropped = LB((lv.bits & lost).any(axis=-1), lv.depth)
        return self.remask(lv, elem_leaf), dropped

    def _setlit_dropped(self, lit: "LSetLit", elem_leaf: EnumLeaf) -> LV:
        """Lane-wise LB: some literal item has no index in elem_leaf
        (to_leaf returned -1), i.e. _setlit_mask dropped it."""
        dropped = LC(False)
        for item in lit.items:
            ie = self.to_leaf(item, elem_leaf)
            dropped = self._lor(dropped, LB(ie.arr < 0, ie.depth))
        return dropped

    def explode(self, lv: LE) -> LRec:
        """Enum record -> structural record (field gathers)."""
        sh = lv.leaf.shape
        rec_sh = None
        if isinstance(sh, SRec):
            rec_sh = sh
        elif isinstance(sh, SUnion):
            for alt in sh.alts:
                if isinstance(alt, SRec):
                    rec_sh = alt
        if rec_sh is None:
            raise CompileError(f"cannot explode non-record leaf {sh}")
        entries = []
        safe = jnp.maximum(lv.arr, 0)
        for f, s, opt in rec_sh.fields:
            fleaf = self._leaf_of_shape(s)
            tab = jnp.asarray(self.field_table(lv.leaf, f, fleaf))
            val = LE(tab[safe], fleaf, lv.depth)
            pres = jnp.asarray(self.presence_table(lv.leaf, f))[safe]
            entries.append((f, LB(pres, lv.depth), self._from_leaf(val, s)))
        return LRec(entries)

    def _from_leaf(self, lv: LE, shape, trusted: bool = False) -> LV:
        """Enum-decoded values regain their native lane type: ints/bools
        become arithmetic/boolean lanes, sets become masks so set
        algebra stays bitwise after an explode.  `trusted` marks codes
        that CANNOT be the absent sentinel (-1) - committed-state
        decodes - whose int view therefore carries certified bounds."""
        if isinstance(shape, SInt):
            return LI(lv.arr + shape.lo, lv.depth,
                      bounds=(shape.lo, shape.hi) if trusted else None)
        if isinstance(shape, SBool):
            return LB(lv.arr == 1, lv.depth)
        if isinstance(shape, SSet):
            elem_leaf = self._leaf_of_shape(shape.elem)
            n = len(elem_leaf.values)
            weights = jnp.asarray([1 << i for i in range(n)], jnp.int32)
            safe = jnp.maximum(lv.arr, 0)
            # the value's index IS the subset bit pattern (codec order)
            bits = (safe[..., None] // weights) % 2 == 1
            return LM(bits, elem_leaf, lv.depth)
        if isinstance(shape, SSeq) and isinstance(lv.leaf.shape, SSeq):
            # enum-coded bounded sequence (a seq field gathered out of
            # an enum-encoded record) -> structural LSeq via length /
            # slot gather tables, so Len/Head/Tail/indexing keep
            # working after the narrowed layout enum-encodes the parent
            elem_leaf = self._leaf_of_shape(shape.elem)
            key = (id(lv.leaf), "#seq", id(elem_leaf))
            tabs = self._pred_tables.get(key)
            if tabs is None:
                lens, slots = [], [[] for _ in range(shape.cap)]
                for v in lv.leaf.values:
                    t = v if isinstance(v, tuple) else ()
                    lens.append(len(t))
                    for k in range(shape.cap):
                        slots[k].append(
                            elem_leaf.index.get(t[k], 0)
                            if k < len(t) else 0
                        )
                tabs = (np.asarray(lens, np.int32),
                        [np.asarray(s, np.int32) for s in slots])
                self._pred_tables[key] = tabs
            safe = jnp.maximum(lv.arr, 0)
            length = LI(jnp.asarray(tabs[0])[safe], lv.depth,
                        bounds=(0, shape.cap))
            slot_lvs = [LE(jnp.asarray(t)[safe], elem_leaf, lv.depth)
                        for t in tabs[1]]
            return LSeq(length, slot_lvs, elem_leaf, shape.cap)
        return lv

    # -- equality ----------------------------------------------------------

    def eq(self, a: LV, b: LV) -> LB:
        if isinstance(a, LC) and isinstance(b, LC):
            return LC(a.value == b.value)
        if isinstance(a, LC) and not isinstance(b, LC):
            return self.eq(b, a)
        if isinstance(a, LB) and isinstance(b, (LB, LC)):
            barr = b.arr if isinstance(b, LB) else jnp.asarray(
                bool(b.value))[None]
            x, y, d = _binop_arrs(a.arr, a.depth,
                                  barr, b.depth if isinstance(b, LB) else 0)
            return LB(x == y, d)
        if isinstance(a, LI) and isinstance(b, (LI, LC)):
            barr = b.arr if isinstance(b, LI) else jnp.asarray(
                int(b.value))[None]
            x, y, d = _binop_arrs(a.arr, a.depth,
                                  barr, b.depth if isinstance(b, LI) else 0)
            return LB(x == y, d)
        if isinstance(a, LM) or isinstance(b, LM):
            am = self.as_mask(a)
            # a's elements all live in am's universe, so any element of b
            # DROPPED while expressing it there makes equality impossible:
            # dropping silently would compare a against b-intersect-universe
            # and let `s = K` / `s # K` corrupt exploration (ADVICE.md)
            dropped = LC(False)
            if isinstance(b, LC):
                if not isinstance(b.value, frozenset):
                    raise CompileError(f"not a set constant: {b.value!r}")
                if any(x not in am.elem_leaf.index for x in b.value):
                    return LC(False)
            if isinstance(b, LSetLit):
                dropped = self._setlit_dropped(b, am.elem_leaf)
            bm = self.as_mask(b, like=am)
            if bm.elem_leaf is not am.elem_leaf:
                bm, rdrop = self.remask_tracked(bm, am.elem_leaf)
                dropped = self._lor(dropped, rdrop)
            x, y, d = _mask_align(am.bits, am.depth, bm.bits, bm.depth)
            return self._land(LB((x == y).all(axis=-1), d),
                              self._lnot(dropped))
        if isinstance(a, LE):
            be = self.to_leaf(b, a.leaf)
            x, y, d = _binop_arrs(a.arr, a.depth, be.arr, be.depth)
            return LB((x == y) & (x >= 0), d)
        if isinstance(b, LE):
            return self.eq(b, a)
        if isinstance(a, LSeq) and isinstance(b, LSeq):
            # slots beyond the live length may hold garbage in derived
            # sequences (Append/Tail), so compare only live positions
            la, lad = self._int_arr(a.length)
            lb, lbd = self._int_arr(b.length)
            x, y, d = _binop_arrs(la, lad, lb, lbd)
            out = LB(x == y, d)
            for i in range(min(a.cap, b.cap)):
                sa = self.to_leaf(a.slots[i], a.leaf)
                sb = self.to_leaf(b.slots[i], a.leaf)
                same = self.eq(sa, sb)
                dead = LB(x <= i, d)
                out = self._land(out, self._lor(dead, same))
            return out
        if isinstance(a, (LRec, LSeq)) or isinstance(b, (LRec, LSeq)):
            # compare through a common enum leaf
            leaf = self._leaf_for_value(a) or self._leaf_for_value(b)
            if leaf is None:
                raise CompileError("cannot compare structural values")
            ae = self.to_leaf(a, leaf)
            return self.eq(ae, b)
        raise CompileError(
            f"cannot compare {type(a).__name__} and {type(b).__name__}"
        )

    def _leaf_for_value(self, lv) -> Optional[EnumLeaf]:
        if isinstance(lv, LE):
            return lv.leaf
        return None

    def as_mask(self, lv: LV, like: Optional[LM] = None) -> LM:
        if isinstance(lv, LM):
            return lv
        if isinstance(lv, LSetLit):
            if like is None:
                raise CompileError("set literal needs an element leaf")
            return self._setlit_mask(lv, like.elem_leaf)
        if isinstance(lv, LC):
            if not isinstance(lv.value, frozenset):
                raise CompileError(f"not a set constant: {lv.value!r}")
            if like is None:
                raise CompileError("constant set needs an element leaf")
            bits = np.zeros(len(like.elem_leaf.values), bool)
            for x in lv.value:
                i = like.elem_leaf.index.get(x)
                if i is not None:
                    bits[i] = True
                # elements outside the universe are unreachable values;
                # membership of them is False by construction
            return LM(jnp.asarray(bits)[None, :], like.elem_leaf, 0)
        if isinstance(lv, LE):
            sh = lv.leaf.shape
            if isinstance(sh, SSet) or (
                isinstance(sh, SUnion)
                and any(isinstance(a, SSet) for a in sh.alts)
            ):
                target = None
                if isinstance(sh, SSet):
                    target = sh
                else:
                    for alt in sh.alts:
                        if isinstance(alt, SSet):
                            target = alt
                off, set_leaf = self._resolve_alt(lv.leaf, SSet)
                elem_leaf = self._leaf_of_shape(target.elem)
                n = len(elem_leaf.values)
                weights = jnp.asarray([1 << i for i in range(n)], jnp.int32)
                safe = jnp.maximum(lv.arr - off, 0)
                bits = (safe[..., None] // weights) % 2 == 1
                return LM(bits, elem_leaf, lv.depth)
        raise CompileError(f"cannot view {type(lv).__name__} as mask")


    # ======================================================================
    # Expression compilation
    # ======================================================================

    def comp(self, ast, env, ctx) -> LV:
        """Compile an expression AST to a lane value.  `env` maps names
        to LVs / Definitions; primed variables live under ("'", name);
        `ctx` is the LaneCtx accumulating afail/trap."""
        op = ast[0]
        if op in ("num",):
            return LC(ast[1])
        if op in ("str", "bool"):
            return LC(ast[1])
        if op == "name":
            return self._comp_name(ast[1], env, ctx)
        if op == "prime":
            key = ("'", ast[1])
            if key not in env:
                raise CompileError(f"{ast[1]}' read before assignment")
            v = env[key]
            if v == "passthrough":
                return env[ast[1]]
            return v
        if op == "setlit":
            items = [self.comp(x, env, ctx) for x in ast[1]]
            if all(isinstance(x, LC) for x in items):
                return LC(frozenset(x.value for x in items))
            return LSetLit(items)
        if op == "tuple":
            return LTuple([self.comp(x, env, ctx) for x in ast[1]])
        if op == "record":
            return LRec([
                (f, LC(True), self.comp(x, env, ctx)) for f, x in ast[1]
            ])
        if op == "apply":
            return self._comp_apply(ast, env, ctx)
        if op == "domain":
            return self._comp_domain(self.comp(ast[1], env, ctx))
        if op == "not":
            v = self.comp(ast[1], env, ctx)
            if isinstance(v, LC):
                return LC(not v.value)
            return LB(~v.arr, v.depth)
        if op in ("and", "or"):
            return self._comp_junction(op, ast[1], env, ctx)
        if op == "implies":
            a = self.comp(ast[1], env, ctx)
            b = self.comp(ast[2], env, ctx)
            return self._lor(self._lnot(a), b)
        if op == "cmp":
            return self._comp_cmp(ast, env, ctx)
        if op == "binop":
            return self._comp_binop(ast, env, ctx)
        if op == "if":
            c = self.comp(ast[1], env, ctx)
            if isinstance(c, LC):
                return self.comp(ast[2] if c.value else ast[3], env, ctx)
            # effects (trap/ovf/afail) raised inside a branch only count
            # when that branch is SELECTED: the host evaluator never
            # looks at the untaken branch, so e.g. LastTerm's
            # `IF Len(s) = 0 THEN 0 ELSE s[Len(s)]` must not trap on the
            # ELSE read when Len(s) = 0 (the RaftReplication device break)
            t, t_fx = self._comp_branch(ast[2], env, ctx)
            e, e_fx = self._comp_branch(ast[3], env, ctx)
            self._merge_branch_fx(ctx, c, t_fx, e_fx)
            return self.select(c, t, e)
        if op == "case":
            arms = []
            for g_ast, e_ast in ast[1]:
                g = self.comp(g_ast, env, ctx)
                e, fx = self._comp_branch(e_ast, env, ctx)
                # arm effects gated by the arm's own guard (a sound
                # over-approximation when several guards hold; TLA CASE
                # is nondeterministic among them anyway)
                self._merge_branch_fx(ctx, g, fx, None)
                arms.append((g, e))
            if ast[2] is not None:
                any_g = LC(False)
                for g, _ in arms:
                    any_g = self._lor(any_g, g)
                o, fx = self._comp_branch(ast[2], env, ctx)
                self._merge_branch_fx(ctx, self._lnot(any_g), fx, None)
                out = o
            else:
                out = arms[-1][1]
            for g, e in reversed(arms):
                if isinstance(g, LC):
                    out = e if g.value else out
                else:
                    out = self.select(g, e, out)
            return out
        if op == "let":
            env2 = dict(env)
            for name, params, body in ast[1]:
                if params:
                    env2[name] = Definition(name, params, body)
                else:
                    env2[name] = self.comp(body, env2, ctx)
            return self.comp(ast[2], env2, ctx)
        if op == "choose":
            return self._comp_choose(ast, env, ctx)
        if op in ("forall", "exists"):
            return self._comp_quant(ast, env, ctx)
        if op == "setfilter":
            return self._comp_setfilter(ast, env, ctx)
        if op == "setmap":
            return self._comp_setmap(ast, env, ctx)
        if op == "except":
            return self._comp_except(ast, env, ctx)
        if op == "atref":
            if "@" not in env:
                raise CompileError("@ outside EXCEPT")
            return env["@"]
        if op == "call":
            return self._comp_call(ast, env, ctx)
        if op == "fnlit":
            return self._comp_fnlit(ast, env, ctx)
        raise CompileError(f"cannot compile node {op!r}")

    def _comp_name(self, name, env, ctx) -> LV:
        if name in env:
            v = env[name]
            if isinstance(v, Definition):
                if v.params:
                    raise CompileError(f"{name} needs arguments")
                return self.comp(v.body, env, ctx)
            return v
        if name in self.ev.constants:
            return LC(self.ev.constants[name])
        if name in BUILTIN_SETS:
            return LC(BUILTIN_SETS[name])
        d = self.ev.defs.get(name)
        if d is not None:
            if d.params:
                raise CompileError(f"{name} needs arguments")
            return self.comp(d.body, env, ctx)
        raise CompileError(f"unknown name {name!r}")

    def _comp_junction(self, op, items, env, ctx) -> LV:
        acc = None
        for x in items:
            v = self.comp(x, env, ctx)
            acc = v if acc is None else (
                self._land(acc, v) if op == "and" else self._lor(acc, v)
            )
        return acc

    def _lnot(self, a):
        if isinstance(a, LC):
            return LC(not a.value)
        return LB(~a.arr, a.depth)

    def _land(self, a, b):
        if isinstance(a, LC):
            return b if a.value else LC(False)
        if isinstance(b, LC):
            return a if b.value else LC(False)
        x, y, d = _binop_arrs(a.arr, a.depth, b.arr, b.depth)
        return LB(x & y, d)

    def _lor(self, a, b):
        if isinstance(a, LC):
            return LC(True) if a.value else b
        if isinstance(b, LC):
            return LC(True) if b.value else a
        x, y, d = _binop_arrs(a.arr, a.depth, b.arr, b.depth)
        return LB(x | y, d)

    _FX = ("trap", "ovf", "afail")

    def _comp_branch(self, ast, env, ctx):
        """Compile `ast` with the effect accumulators (trap/ovf/afail)
        swapped out, returning (value, {effect: LB}) so the caller can
        re-apply them gated by the branch condition.  The guard is NOT
        swapped: it belongs to the lane, not the expression."""
        saved = {f: getattr(ctx, f) for f in self._FX}
        for f in self._FX:
            setattr(ctx, f, LC(False))
        v = self.comp(ast, env, ctx)
        fx = {f: getattr(ctx, f) for f in self._FX}
        for f in self._FX:
            setattr(ctx, f, saved[f])
        return v, fx

    def _merge_branch_fx(self, ctx, cond, t_fx, e_fx):
        """Fold branch effects into ctx, each gated by its branch being
        the one selected.  A non-boolean condition degrades to the old
        ungated behavior (sound: traps at worst too eagerly)."""
        gateable = isinstance(cond, (LB, LC))
        for f in self._FX:
            for fx, gate in ((t_fx, cond),
                             (e_fx, self._lnot(cond) if gateable
                              else None)):
                if fx is None:
                    continue
                eff = fx[f]
                if isinstance(eff, LC) and not eff.value:
                    continue
                if gateable:
                    eff = self._land(gate, eff)
                setattr(ctx, f, self._lor(getattr(ctx, f), eff))

    def _comp_apply(self, ast, env, ctx) -> LV:
        base = self.comp(ast[1], env, ctx)
        arg = self.comp(ast[2], env, ctx)
        if isinstance(base, LSeq) and isinstance(arg, LI):
            # dynamic sequence index (s[Len(s)]): a where-chain over the
            # bounded cap - still branchless
            out = base.slots[base.cap - 1]
            for i in range(base.cap - 2, -1, -1):
                here = self.eq(arg, LC(i + 1))
                out = self.select(here, base.slots[i], out)
            # an index outside 1..Len(s) must emit the -1 trap (to_leaf's
            # range-trap discipline) - never the where-chain default slot,
            # which would be a silently wrong value for a reachable
            # out-of-bounds read (host evaluator raises here).  The slot
            # -1 alone is not loud enough: _from_leaf re-bases enum codes
            # into the ELEM value range, which can land back inside the
            # destination universe - so the read also registers in
            # ctx.trap directly (reduced over lift axes; a trap on any
            # branch of a lifted binder halts, loud beats silent)
            oe = self.to_leaf(out, base.leaf)
            av, ad = self._int_arr(arg)
            lnv, lnd = self._int_arr(base.length)
            x, y, d0 = _binop_arrs(av, ad, lnv, lnd)
            okb = (x >= 1) & (x <= y)
            bad = ~okb
            for _ in range(d0):
                bad = bad.any(axis=-1)
            ctx.trap = self._lor(ctx.trap, LB(bad, 0))
            oka, oa, d = _binop_arrs(okb.astype(jnp.int32), d0,
                                     oe.arr, oe.depth)
            oe = LE(jnp.where(oka == 1, oa, -1), base.leaf, d)
            return self._from_leaf(oe, base.leaf.shape)
        if not isinstance(arg, LC):
            raise CompileError("dynamic function application index")
        key = arg.value
        if isinstance(base, LC):
            from .eval import fn_apply

            return LC(fn_apply(base.value, key))
        if isinstance(base, LRec):
            p, v = base.get(key)
            if v is None:
                raise CompileError(f"field {key!r} not in record layout")
            return v
        if isinstance(base, LE):
            sh = base.leaf.shape
            fs = None
            if isinstance(sh, SRec):
                fs = sh.field(key)
            elif isinstance(sh, SUnion):
                for alt in sh.alts:
                    if isinstance(alt, SRec) and alt.field(key):
                        fs = alt.field(key)
            if fs is None:
                raise CompileError(f"no field {key!r} on {sh}")
            fleaf = self._leaf_of_shape(fs[0])
            tab = jnp.asarray(self.field_table(base.leaf, key, fleaf))
            safe = jnp.maximum(base.arr, 0)
            return self._from_leaf(LE(tab[safe], fleaf, base.depth), fs[0])
        if isinstance(base, LSeq) and isinstance(key, int):
            if 1 <= key <= base.cap:
                return self._from_leaf(base.slots[key - 1],
                                       base.leaf.shape)
            raise CompileError("sequence index out of cap")
        raise CompileError(
            f"cannot apply {type(base).__name__}[{key!r}]"
        )

    def _comp_domain(self, base) -> LV:
        if isinstance(base, LC):
            from .eval import fn_domain

            return LC(fn_domain(base.value))
        if isinstance(base, LRec):
            names = [f for f, _, _ in base.entries]
            leaf = self._leaf_of_shape(SAtoms(frozenset(names)))
            cols = []
            depth = 0
            for f, p, _ in base.entries:
                if isinstance(p, LC):
                    cols.append((f, None, bool(p.value)))
                else:
                    cols.append((f, p, None))
                    depth = max(depth, p.depth)
            order = {v: i for i, v in enumerate(leaf.values)}
            arrs = [None] * len(leaf.values)
            for f, p, const in cols:
                i = order[f]
                if p is None:
                    arrs[i] = jnp.full((1,) + (1,) * depth, const)
                else:
                    arrs[i] = _align(p.arr, p.depth, depth)
            bits = jnp.stack(jnp.broadcast_arrays(*arrs), axis=-1)
            return LM(bits, leaf, depth)
        if isinstance(base, LE):
            sh = base.leaf.shape
            rec_sh = sh if isinstance(sh, SRec) else None
            if rec_sh is None and isinstance(sh, SUnion):
                for alt in sh.alts:
                    if isinstance(alt, SRec):
                        rec_sh = alt
            if rec_sh is None:
                raise CompileError(f"DOMAIN of {sh}")
            names = [f for f, _, _ in rec_sh.fields]
            leaf = self._leaf_of_shape(SAtoms(frozenset(names)))
            safe = jnp.maximum(base.arr, 0)
            cols = []
            for v in leaf.values:
                cols.append(jnp.asarray(self.presence_table(
                    base.leaf, v))[safe])
            bits = jnp.stack(cols, axis=-1)
            return LM(bits, leaf, base.depth)
        raise CompileError(f"DOMAIN of {type(base).__name__}")

    # -- comparisons -------------------------------------------------------

    def _comp_cmp(self, ast, env, ctx) -> LV:
        _, sym, la, ra = ast
        if sym == r"\in" and ra[0] == "funcset":
            return self._member_funcset(la, ra, env, ctx)
        if sym == r"\notin" and ra[0] == "funcset":
            return self._lnot(self._member_funcset(la, ra, env, ctx))
        a = self.comp(la, env, ctx)
        b = self.comp(ra, env, ctx)
        if sym == "=":
            return self._eq_lv(a, b)
        if sym == "#":
            return self._lnot(self._eq_lv(a, b))
        if sym in (r"\in", r"\notin"):
            m = self._member_lv(a, b)
            return self._lnot(m) if sym == r"\notin" else m
        if sym == r"\subseteq":
            return self._subseteq_lv(a, b)
        if sym in ("<", ">", "<=", ">="):
            if isinstance(a, LC) and isinstance(b, LC):
                return LC({"<": a.value < b.value, ">": a.value > b.value,
                           "<=": a.value <= b.value,
                           ">=": a.value >= b.value}[sym])
            av, ad = self._int_arr(a)
            bv, bd = self._int_arr(b)
            x, y, d = _binop_arrs(av, ad, bv, bd)
            return LB({"<": x < y, ">": x > y, "<=": x <= y,
                       ">=": x >= y}[sym], d)
        raise CompileError(f"cannot compile cmp {sym}")

    def _int_arr(self, lv):
        """(arr, depth) int view of a lane value (LI, int LC, or an
        enum-coded SInt)."""
        if isinstance(lv, LI):
            return lv.arr, lv.depth
        if isinstance(lv, LC):
            return jnp.asarray(int(lv.value))[None], 0
        if isinstance(lv, LE) and isinstance(lv.leaf.shape, SInt):
            return lv.arr + lv.leaf.shape.lo, lv.depth
        raise CompileError(
            f"cannot order {type(lv).__name__} values"
        )

    def _member_funcset(self, la, ra, env, ctx) -> LV:
        f = self.comp(la, env, ctx)
        return self._member_funcset_lv(f, ra, env, ctx)

    def _member_funcset_lv(self, f, ra, env, ctx) -> LV:
        """f \\in [S -> T] without enumerating the function space: the
        domain is exactly S and every value lands in T (TypeOK's usual
        function-typing conjunct).  A funcset codomain recurses per key
        instead of compiling [S2 -> T2] as a value (two-level functions
        like `view \\in [Sidecars -> [Endpoints -> {"ok","down"}]]`)."""
        _, s_ast, t_ast = ra
        s = self.comp(s_ast, env, ctx)
        if not isinstance(s, LC) or not isinstance(s.value, frozenset):
            raise CompileError("[S -> T] with dynamic domain")
        nested = isinstance(t_ast, tuple) and t_ast and t_ast[0] == "funcset"
        t = None if nested else self.comp(t_ast, env, ctx)
        if isinstance(f, LE):
            f = self.explode(f)
        if not isinstance(f, LRec):
            raise CompileError("\\in [S -> T] on a non-function value")
        out = LC(True)
        names = {fn for fn, _, _ in f.entries}
        if names != s.value:
            # layout fields outside S must be absent; S-fields present
            for extra in names - s.value:
                p, _ = f.get(extra)
                out = self._land(out, self._lnot(p))
        for key in sorted(s.value):
            p, v = f.get(key)
            if v is None:
                return LC(False)
            out = self._land(out, p)
            if nested:
                if isinstance(v, LE):
                    v = self.explode(v)
                out = self._land(out,
                                 self._member_funcset_lv(v, t_ast, env, ctx))
            else:
                out = self._land(out, self._member_lv(v, t))
        return out

    def _eq_lv(self, a, b) -> LV:
        if isinstance(a, (LSetLit, LTuple)) or isinstance(b, (LSetLit,
                                                              LTuple)):
            raise CompileError("structural literal equality unsupported")
        v = self.eq(a, b)
        return v

    def _member_lv(self, a, b) -> LV:
        if isinstance(b, LC):
            bv = b.value
            if isinstance(bv, frozenset):
                if isinstance(a, LC):
                    return LC(a.value in bv)
                if isinstance(a, LE):
                    tab = self.value_pred_table(
                        a.leaf, _named(lambda v: v in bv,
                                       ("inset", tuple(sorted(map(repr,
                                                                  bv))))))
                    safe = jnp.maximum(a.arr, 0)
                    return LB(jnp.asarray(tab)[safe] & (a.arr >= 0),
                              a.depth)
                if isinstance(a, LB):
                    ok_t = True in bv
                    ok_f = False in bv
                    return LB(jnp.where(a.arr, ok_t, ok_f), a.depth)
                if isinstance(a, LI) and all(
                    isinstance(x, int) for x in bv
                ):
                    ints = sorted(bv)
                    if ints and ints == list(range(ints[0],
                                                   ints[-1] + 1)):
                        return LB((a.arr >= ints[0])
                                  & (a.arr <= ints[-1]), a.depth)
                    out = jnp.zeros_like(a.arr, bool)
                    for x in ints:
                        out = out | (a.arr == x)
                    return LB(out, a.depth)
                raise CompileError("\\in constant set: unsupported lhs")
            if bv is BUILTIN_SETS["STRING"]:
                if isinstance(a, LC):
                    return LC(isinstance(a.value, str)
                              and a.value != DEFAULT_INIT)
                if isinstance(a, LE):
                    tab = self.value_pred_table(
                        a.leaf, _named(
                            lambda v: isinstance(v, str)
                            and v != DEFAULT_INIT, ("isstr",)))
                    safe = jnp.maximum(a.arr, 0)
                    return LB(jnp.asarray(tab)[safe] & (a.arr >= 0),
                              a.depth)
            raise CompileError(f"\\in over constant {bv!r}")
        if isinstance(b, LM):
            if isinstance(a, LC):
                i = b.elem_leaf.index.get(a.value)
                if i is None:
                    return LC(False)
                return LB(b.bits[..., i], b.depth)
            ae = self.to_leaf(a, b.elem_leaf)
            d = max(ae.depth, b.depth)
            idx = _align(ae.arr, ae.depth, d)
            bits = b.bits
            for _ in range(d - b.depth):
                bits = bits[..., None, :]
            onehot = jnp.arange(len(b.elem_leaf.values)) == idx[..., None]
            return LB((onehot & bits).any(axis=-1) & (idx >= 0), d)
        raise CompileError(f"\\in over {type(b).__name__}")

    def _subseteq_lv(self, a, b) -> LV:
        if isinstance(b, LM):
            if isinstance(a, LC):
                out = LC(True)
                for x in a.value:
                    out = self._land(out, self._member_lv(LC(x), b))
                return out
            am = self.as_mask(a, like=b)
            if am.elem_leaf is not b.elem_leaf:
                am = self.remask(am, b.elem_leaf)
            x, y, d = _mask_align(am.bits, am.depth, b.bits, b.depth)
            return LB((~x | y).all(axis=-1), d)
        if isinstance(b, LC) and isinstance(b.value, frozenset):
            if isinstance(a, LC):
                return LC(a.value <= b.value)
            if isinstance(a, LM):
                miss = [
                    i for i, v in enumerate(a.elem_leaf.values)
                    if v not in b.value
                ]
                if not miss:
                    return LC(True)
                bad = a.bits[..., jnp.asarray(miss)].any(axis=-1)
                return LB(~bad, a.depth)
        raise CompileError("unsupported \\subseteq operands")

    # -- set algebra -------------------------------------------------------

    def _comp_binop(self, ast, env, ctx) -> LV:
        _, sym, la, ra = ast
        a = self.comp(la, env, ctx)
        b = self.comp(ra, env, ctx)
        if sym in (r"\cup", r"\cap", "\\"):
            am, bm = self._two_masks(a, b)
            if am is None:  # both constant
                from .eval import Evaluator as _E

                return LC({
                    r"\cup": a.value | b.value,
                    r"\cap": a.value & b.value,
                    "\\": a.value - b.value,
                }[sym])
            x, y, d = _mask_align(am.bits, am.depth, bm.bits, bm.depth)
            bits = {r"\cup": x | y, r"\cap": x & y, "\\": x & ~y}[sym]
            return LM(bits, am.elem_leaf, d)
        if sym in ("+", "-", "*"):
            if isinstance(a, LC) and isinstance(b, LC):
                return LC({"+": a.value + b.value,
                           "-": a.value - b.value,
                           "*": a.value * b.value}[sym])
            av = a.arr if isinstance(a, LI) else jnp.asarray(
                int(a.value))[None]
            bv = b.arr if isinstance(b, LI) else jnp.asarray(
                int(b.value))[None]
            x, y, d = _binop_arrs(av, getattr(a, "depth", 0),
                                  bv, getattr(b, "depth", 0))
            ba, bb = _int_bounds(a), _int_bounds(b)
            nb = None
            if ba is not None and bb is not None:
                if sym == "+":
                    nb = (ba[0] + bb[0], ba[1] + bb[1])
                elif sym == "-":
                    nb = (ba[0] - bb[1], ba[1] - bb[0])
                else:
                    cs = [ba[0] * bb[0], ba[0] * bb[1],
                          ba[1] * bb[0], ba[1] * bb[1]]
                    nb = (min(cs), max(cs))
            return LI({"+": x + y, "-": x - y, "*": x * y}[sym], d,
                      bounds=nb)
        if sym == "..":
            if isinstance(a, LC) and isinstance(b, LC):
                return LC(frozenset(range(a.value, b.value + 1)))
            raise CompileError("dynamic .. range")
        if sym == r"\o":
            return self._concat(a, b, ctx)
        if sym == ":>":
            if not isinstance(a, LC):
                raise CompileError(":> with dynamic key")
            return LRec([(a.value, LC(True), b)])
        if sym == "@@":
            return self._merge(a, b)
        raise CompileError(f"cannot compile binop {sym}")

    def _two_masks(self, a, b):
        if isinstance(a, LM):
            bm = b if isinstance(b, LM) else self.as_mask(b, like=a)
            if bm.elem_leaf is not a.elem_leaf:
                bm = self.remask(bm, a.elem_leaf)
            return a, bm
        if isinstance(b, LM):
            am = self.as_mask(a, like=b)
            if am.elem_leaf is not b.elem_leaf:
                am = self.remask(am, b.elem_leaf)
            return am, b
        if isinstance(a, LC) and isinstance(b, LC):
            return None, None
        if isinstance(a, (LSetLit,)) or isinstance(b, (LSetLit,)):
            # resolve the literal against the other side
            if isinstance(a, LSetLit) and isinstance(b, LM):
                return self._setlit_mask(a, b.elem_leaf), b
            if isinstance(b, LSetLit) and isinstance(a, LM):
                return a, self._setlit_mask(b, a.elem_leaf)
        raise CompileError("set operation without a mask operand")

    def _setlit_mask(self, lit: "LSetLit", elem_leaf: EnumLeaf) -> LM:
        bits = None
        depth = 0
        n = len(elem_leaf.values)
        for item in lit.items:
            ie = self.to_leaf(item, elem_leaf)
            oh = (jnp.arange(n) ==
                  _align(ie.arr, ie.depth, ie.depth)[..., None])
            oh = oh & (ie.arr >= 0)[..., None]
            if bits is None:
                bits, depth = oh, ie.depth
            else:
                x, y, depth = _mask_align(bits, depth, oh, ie.depth)
                bits = x | y
        if bits is None:
            bits = jnp.zeros((1, n), bool)
        return LM(bits, elem_leaf, depth)

    def _concat(self, a, b, ctx) -> LSeq:
        if not isinstance(b, LSeq):
            raise CompileError("\\o rhs must be a sequence value")
        if not isinstance(a, LTuple):
            raise CompileError("\\o lhs must be a tuple literal here")
        k = len(a.items)
        new_len = LI(b.length.arr + k, b.length.depth)
        ctx.ovf = self._lor(ctx.ovf, LB(b.length.arr + k > b.cap,
                                        b.length.depth))
        slots = [self.to_leaf(x, b.leaf) for x in a.items]
        slots = slots + b.slots[: b.cap - k] if k < b.cap else \
            slots[: b.cap]
        # zero out beyond new length happens at encode
        return LSeq(new_len, slots, b.leaf, b.cap)

    def _merge(self, a, b) -> LRec:
        """a @@ b, left-biased, over structural records."""
        def as_rec(v):
            if isinstance(v, LRec):
                return v
            if isinstance(v, LE):
                return self.explode(v)
            if isinstance(v, LC):
                if isinstance(v.value, tuple) and (v.value == () or
                                                   is_fn(v.value)):
                    return LRec([
                        (f, LC(True), LC(x)) for f, x in v.value
                    ])
            raise CompileError(f"@@ over {type(v).__name__}")

        ra = as_rec(a)
        rb = as_rec(b)
        entries = []
        names = [f for f, _, _ in ra.entries] + [
            f for f, _, _ in rb.entries
            if all(f != g for g, _, _ in ra.entries)
        ]
        for f in names:
            pa, va = ra.get(f)
            pb, vb = rb.get(f)
            if va is None:
                entries.append((f, pb, vb))
            elif vb is None:
                entries.append((f, pa, va))
            else:
                # present in a wins; where a absent, b's entry shows
                if isinstance(pa, LC) and pa.value is True:
                    entries.append((f, LC(True), va))
                else:
                    pres = self._lor(pa, pb)
                    entries.append((f, pres, self.select(pa, va, vb)))
        return LRec(entries)

    # -- selection ---------------------------------------------------------

    def select(self, c, a, b) -> LV:
        """IF c THEN a ELSE b over lane values."""
        if isinstance(c, LC):
            return a if c.value else b
        if isinstance(a, LC) and isinstance(b, LC) and a.value == b.value:
            return a
        if isinstance(a, LM) or isinstance(b, LM):
            am = a if isinstance(a, LM) else self.as_mask(
                a, like=b if isinstance(b, LM) else None)
            bm = b if isinstance(b, LM) else self.as_mask(b, like=am)
            if bm.elem_leaf is not am.elem_leaf:
                bm = self.remask(bm, am.elem_leaf)
            x, y, d = _mask_align(am.bits, am.depth, bm.bits, bm.depth)
            carr = _align(c.arr, c.depth, d)[..., None]
            return LM(jnp.where(carr, x, y), am.elem_leaf, d)
        if isinstance(a, LRec) and isinstance(b, LRec):
            entries = []
            names = [f for f, _, _ in a.entries]
            for f in names:
                pa, va = a.get(f)
                pb, vb = b.get(f)
                if vb is None:
                    pb, vb = LC(False), va
                entries.append((
                    f,
                    self.select(c, pa, pb) if not (
                        isinstance(pa, LC) and isinstance(pb, LC)
                        and pa.value == pb.value) else pa,
                    self.select(c, va, vb),
                ))
            for f, pb, vb in b.entries:
                if a.get(f)[1] is None:
                    entries.append((f, self.select(c, LC(False), pb), vb))
            return LRec(entries)
        if isinstance(a, LSeq) or isinstance(b, LSeq):
            if not (isinstance(a, LSeq) and isinstance(b, LSeq)):
                raise CompileError("IF mixes sequence and non-sequence")
            ln = self.select(c, a.length, b.length)
            slots = [self.select(c, x, self.to_leaf(y, a.leaf))
                     for x, y in zip(a.slots, b.slots)]
            return LSeq(ln, slots, a.leaf, max(a.cap, b.cap))
        if isinstance(a, LB) or isinstance(b, LB) or (
            isinstance(a, LC) and isinstance(a.value, bool)
        ):
            aa = a.arr if isinstance(a, LB) else jnp.asarray(
                bool(a.value))[None]
            bb = b.arr if isinstance(b, LB) else jnp.asarray(
                bool(b.value))[None]
            x, y, d0 = _binop_arrs(aa, getattr(a, "depth", 0),
                                   bb, getattr(b, "depth", 0))
            carr, x2, d = _binop_arrs(_align(c.arr, c.depth, c.depth),
                                      c.depth, x, d0)
            _, y2, _ = _binop_arrs(carr, d, y, d0)
            return LB(jnp.where(carr, x2, y2), d)
        if isinstance(a, LI) or isinstance(b, LI):
            aa = a.arr if isinstance(a, LI) else jnp.asarray(
                int(a.value))[None]
            bb = b.arr if isinstance(b, LI) else jnp.asarray(
                int(b.value))[None]
            x, y, d0 = _binop_arrs(aa, getattr(a, "depth", 0),
                                   bb, getattr(b, "depth", 0))
            carr, x2, d = _binop_arrs(c.arr, c.depth, x, d0)
            _, y2, _ = _binop_arrs(carr, d, y, d0)
            ba, bb2 = _int_bounds(a), _int_bounds(b)
            hull = (min(ba[0], bb2[0]), max(ba[1], bb2[1])) \
                if ba is not None and bb2 is not None else None
            return LI(jnp.where(carr, x2, y2), d, bounds=hull)
        # enum path: unify through a leaf
        leaf = None
        if isinstance(a, LE):
            leaf = a.leaf
        elif isinstance(b, LE):
            leaf = b.leaf
        if leaf is None:
            raise CompileError(
                f"cannot select between {type(a).__name__} and "
                f"{type(b).__name__}"
            )
        ae = self.to_leaf(a, leaf)
        be = self.to_leaf(b, leaf)
        x, y, d0 = _binop_arrs(ae.arr, ae.depth, be.arr, be.depth)
        carr, x2, d = _binop_arrs(c.arr, c.depth, x, d0)
        _, y2, _ = _binop_arrs(carr, d, y, d0)
        return LE(jnp.where(carr, x2, y2), leaf, d)

    # -- quantifiers / comprehensions / CHOOSE -----------------------------

    def _dom_descriptor(self, dom_ast, env, ctx):
        """Compile a quantifier domain: ("const", values) |
        ("atoms", LM small) | ("mask", LM big)."""
        dom = self.comp(dom_ast, env, ctx)
        if isinstance(dom, LC):
            if not isinstance(dom.value, frozenset):
                raise CompileError("quantifier over non-set constant")
            return ("const", sorted(dom.value, key=repr))
        if isinstance(dom, LM):
            if len(dom.elem_leaf.values) <= UNROLL_LIMIT:
                return ("atoms", dom)
            return ("mask", dom)
        raise CompileError(
            f"quantifier domain {type(dom).__name__} unsupported"
        )

    def _comp_quant(self, ast, env, ctx) -> LV:
        _, names, dom_ast, body = ast
        return self._quant_rec(names, dom_ast, body, env, ctx, "forall"
                               if ast[0] == "forall" else "exists",
                               ast[0])

    def _quant_rec(self, names, dom_ast, body, env, ctx, _ignored, kind):
        if not names:
            return self.comp(body, env, ctx)
        name, rest = names[0], names[1:]
        desc = self._dom_descriptor(dom_ast, env, ctx)
        if desc[0] == "const":
            acc = None
            for v in desc[1]:
                env2 = dict(env)
                env2[name] = LC(v)
                r = self._quant_rec(rest, dom_ast, body, env2, ctx,
                                    None, kind)
                acc = r if acc is None else (
                    self._land(acc, r) if kind == "forall"
                    else self._lor(acc, r))
            return acc if acc is not None else LC(kind == "forall")
        if desc[0] == "atoms":
            m = desc[1]
            acc = None
            for i, v in enumerate(m.elem_leaf.values):
                env2 = dict(env)
                env2[name] = LC(v)
                member = LB(m.bits[..., i], m.depth)
                r = self._quant_rec(rest, dom_ast, body, env2, ctx,
                                    None, kind)
                r = self._lor(self._lnot(member), r) if kind == "forall" \
                    else self._land(member, r)
                acc = r if acc is None else (
                    self._land(acc, r) if kind == "forall"
                    else self._lor(acc, r))
            return acc if acc is not None else LC(kind == "forall")
        # big mask: lift
        m: LM = desc[1]
        lifted, level = self._lift_binder(m)
        env2 = dict(env)
        env2[name] = lifted
        r = self._quant_rec(rest, dom_ast, body, env2, ctx, None, kind)
        return self._quant_reduce(m, r, level, kind)

    def _lift_binder(self, m: LM):
        """New lift axis over m's universe; binder = arange as LE with
        depth = m.depth + 1 (its own axis is the last)."""
        n = len(m.elem_leaf.values)
        level = m.depth + 1
        arange = jnp.arange(n, dtype=jnp.int32).reshape(
            (1,) + (1,) * (level - 1) + (n,)
        )
        return LE(arange, m.elem_leaf, level), level

    def _quant_reduce(self, m: LM, body, level, kind) -> LB:
        if isinstance(body, LC):
            if kind == "forall" and body.value:
                return LC(True)
            if kind == "exists" and not body.value:
                return LC(False)
            # constant-FALSE forall / constant-TRUE exists: reduces to
            # the set's (non-)emptiness
            ne = m.bits.any(axis=-1)
            return LB(ne if kind == "exists" else ~ne, m.depth)
        barr = _align(body.arr, body.depth, level)
        mbits = m.bits  # prefix == level-1, so ranks already agree
        if kind == "forall":
            return LB((~mbits | barr).all(axis=-1), level - 1)
        return LB((mbits & barr).any(axis=-1), level - 1)

    def _comp_setfilter(self, ast, env, ctx) -> LV:
        _, var, dom_ast, pred = ast
        desc = self._dom_descriptor(dom_ast, env, ctx)
        if desc[0] == "const":
            results = []
            for v in desc[1]:
                env2 = dict(env)
                env2[var] = LC(v)
                results.append((v, self.comp(pred, env2, ctx)))
            if all(isinstance(r, LC) for _, r in results):
                return LC(frozenset(v for v, r in results if r.value))
            # state-dependent filter over a constant set (quorum
            # counting: {n \\in Nodes : Len(log[n]) >= k}): a mask over
            # the atom universe with per-element predicate bits
            if not all(isinstance(v, str) for v, _ in results):
                raise CompileError(
                    "state-dependent filter over non-atom constant set"
                )
            leaf = self._leaf_of_shape(
                SAtoms(frozenset(v for v, _ in results))
            )
            depth = max((r.depth for _, r in results
                         if isinstance(r, LB)), default=0)
            cols = [None] * len(leaf.values)
            for v, r in results:
                i = leaf.index[v]
                if isinstance(r, LC):
                    cols[i] = jnp.full((1,) + (1,) * depth, bool(r.value))
                else:
                    cols[i] = _align(r.arr, r.depth, depth)
            bits = jnp.stack(jnp.broadcast_arrays(*cols), axis=-1)
            return LM(bits, leaf, depth)
        m: LM = desc[1]
        if desc[0] == "atoms":
            cols = []
            depth = m.depth
            for i, v in enumerate(m.elem_leaf.values):
                env2 = dict(env)
                env2[var] = LC(v)
                r = self.comp(pred, env2, ctx)
                if isinstance(r, LC):
                    col = m.bits[..., i] if r.value else (
                        m.bits[..., i] & False)
                    cols.append((col, m.depth))
                else:
                    x, y, d = _binop_arrs(m.bits[..., i], m.depth,
                                          r.arr, r.depth)
                    cols.append((x & y, d))
                    depth = max(depth, d)
            arrs = [_align(c, d, depth) for c, d in cols]
            bits = jnp.stack(jnp.broadcast_arrays(*arrs), axis=-1)
            return LM(bits, m.elem_leaf, depth)
        lifted, level = self._lift_binder(m)
        env2 = dict(env)
        env2[var] = lifted
        r = self.comp(pred, env2, ctx)
        if isinstance(r, LC):
            return m if r.value else LM(m.bits & False, m.elem_leaf,
                                        m.depth)
        barr = _align(r.arr, r.depth, level)
        mbits = _mask_align(m.bits, m.depth, barr, level - 1)[0]
        return LM(mbits & barr, m.elem_leaf, level - 1)

    def _comp_setmap(self, ast, env, ctx) -> LV:
        _, expr, var, dom_ast = ast
        desc = self._dom_descriptor(dom_ast, env, ctx)
        if desc[0] != "mask":
            raise CompileError("set map over non-mask domain")
        m: LM = desc[1]
        lifted, level = self._lift_binder(m)
        env2 = dict(env)
        env2[var] = lifted
        r = self.comp(expr, env2, ctx)
        re = self.to_leaf(r, m.elem_leaf)
        idx = _align(re.arr, re.depth, level)
        mbits = _mask_align(m.bits, m.depth, idx, level - 1)[0]
        n = len(m.elem_leaf.values)
        # scatter: out[t] = any_u (bits[u] & idx[u] == t)
        onehot = idx[..., None] == jnp.arange(n)
        bits = (onehot & mbits[..., None]).any(axis=-2)
        return LM(bits, m.elem_leaf, level - 1)

    def _comp_choose(self, ast, env, ctx) -> LV:
        _, var, dom_ast, pred = ast
        desc = self._dom_descriptor(dom_ast, env, ctx)
        if desc[0] != "mask":
            raise CompileError("CHOOSE over non-mask domain")
        m: LM = desc[1]
        lifted, level = self._lift_binder(m)
        env2 = dict(env)
        env2[var] = lifted
        r = self.comp(pred, env2, ctx)
        if isinstance(r, LC):
            sel = m.bits if r.value else m.bits & False
            depth = m.depth
        else:
            barr = _align(r.arr, r.depth, level)
            mbits = _mask_align(m.bits, m.depth, barr, level - 1)[0]
            sel = mbits & barr
            depth = level - 1
        # pick the witness the HOST evaluator picks (eval.py choose: the
        # _SORT_KEY-least satisfying element), not the first set bit in
        # universe enumeration order - with a non-unique predicate the two
        # orders diverge and the engines' state spaces drift apart
        n = len(m.elem_leaf.values)
        rank = jnp.asarray(self.choose_rank_table(m.elem_leaf))
        idx = jnp.argmin(jnp.where(sel, rank, n), axis=-1).astype(jnp.int32)
        ok = sel.any(axis=-1)
        return LE(jnp.where(ok, idx, -1), m.elem_leaf, depth)

    def _comp_except(self, ast, env, ctx) -> LV:
        base = self.comp(ast[1], env, ctx)
        for path_asts, val_ast in ast[2]:
            path = [self.comp(p, env, ctx) for p in path_asts]
            base = self._except_apply(base, path, val_ast, env, ctx)
        return base

    def _except_apply(self, base, path, val_ast, env, ctx):
        idx = path[0]
        if not isinstance(idx, LC):
            raise CompileError("dynamic EXCEPT index")
        key = idx.value
        if isinstance(base, LE):
            base = self.explode(base)
        if isinstance(base, LRec):
            p, old = base.get(key)
            if old is None:
                raise CompileError(f"EXCEPT unknown field {key!r}")
            if len(path) > 1:
                new = self._except_apply(old, path[1:], val_ast, env, ctx)
            else:
                env2 = dict(env)
                env2["@"] = old
                new = self.comp(val_ast, env2, ctx)
            entries = [
                (f, pp, new if f == key else vv)
                for f, pp, vv in base.entries
            ]
            return LRec(entries)
        raise CompileError(
            f"EXCEPT on {type(base).__name__}"
        )

    def _comp_call(self, ast, env, ctx) -> LV:
        _, name, args = ast
        d = env.get(name)
        if not isinstance(d, Definition):
            d = self.ev.defs.get(name)
        if isinstance(d, Definition):
            env2 = dict(env)
            for p, a in zip(d.params, args):
                env2[p] = self.comp(a, env, ctx)
            return self.comp(d.body, env2, ctx)
        vals = [self.comp(a, env, ctx) for a in args]
        if name == "Cardinality":
            (s,) = vals
            if isinstance(s, LC):
                return LC(len(s.value))
            m = self.as_mask(s)
            return LI(m.bits.sum(axis=-1).astype(jnp.int32), m.depth,
                      bounds=(0, len(m.elem_leaf.values)))
        if name == "Len":
            (s,) = vals
            if isinstance(s, LSeq):
                return s.length
            raise CompileError("Len of non-sequence")
        if name == "Head":
            (s,) = vals
            if isinstance(s, LSeq):
                return s.slots[0]
            raise CompileError("Head of non-sequence")
        if name == "Tail":
            (s,) = vals
            if isinstance(s, LSeq):
                lb = s.length.bounds
                ln = LI(jnp.maximum(s.length.arr - 1, 0),
                        s.length.depth,
                        bounds=(max(lb[0] - 1, 0), max(lb[1] - 1, 0))
                        if lb is not None else None)
                zero = LE(jnp.zeros((1,), jnp.int32), s.leaf, 0)
                return LSeq(ln, s.slots[1:] + [zero], s.leaf, s.cap)
            raise CompileError("Tail of non-sequence")
        if name == "Append":
            s, e = vals
            if not isinstance(s, LSeq):
                raise CompileError("Append to non-sequence")
            ee = self.to_leaf(e, s.leaf)
            ctx.ovf = self._lor(ctx.ovf, LB(s.length.arr + 1 > s.cap,
                                            s.length.depth))
            slots = []
            for i in range(s.cap):
                at_i = LB(s.length.arr == i, s.length.depth)
                slots.append(self.select(at_i, ee, s.slots[i]))
            lb = s.length.bounds
            return LSeq(LI(s.length.arr + 1, s.length.depth,
                           bounds=(lb[0] + 1, lb[1] + 1)
                           if lb is not None else None), slots,
                        s.leaf, s.cap)
        if name == "Assert":
            cond, _msg = vals
            if isinstance(cond, LC):
                if cond.value is not True:
                    ctx.afail = LC(True)
            else:
                ctx.afail = self._lor(ctx.afail, self._lnot(cond))
            return LC(True)
        raise CompileError(f"unknown operator {name!r}")

    def _comp_fnlit(self, ast, env, ctx) -> LV:
        _, var, dom_ast, body = ast
        dom = self.comp(dom_ast, env, ctx)
        if isinstance(dom, LC) and isinstance(dom.value, frozenset):
            entries = []
            for v in sorted(dom.value, key=repr):
                env2 = dict(env)
                env2[var] = LC(v)
                entries.append((v, LC(True), self.comp(body, env2, ctx)))
            return LRec(entries)
        raise CompileError("function literal over dynamic domain")


    # ======================================================================
    # State decode / encode
    # ======================================================================

    def decode_state(self, fields) -> Dict[str, LV]:
        """fields [B, F] int32 -> {var: LV} (batch-resident values)."""
        out: Dict[str, LV] = {}
        pos = 0
        for v, lay in zip(self.variables, self.codec.layouts):
            lv, pos = self._decode_layout(lay, fields, pos,
                                          self.var_shapes[v])
            out[v] = lv
        return out

    def _decode_layout(self, lay, fields, pos, shape):
        if isinstance(lay, EnumLeaf):
            lv = LE(fields[:, pos], lay, 0)
            # committed-state fields hold legal codes (encode traps
            # enforce it; certificate mode re-verifies on device), so
            # the decoded int view carries certified bounds
            return self._from_leaf(lv, shape, trusted=True), pos + 1
        if isinstance(lay, MaskLeaf):
            cols = []
            for gi, w in enumerate(lay.widths):
                word = fields[:, pos + gi]
                for b in range(w):
                    cols.append((word >> b) & 1)
            bits = jnp.stack(cols, axis=-1) == 1
            return LM(bits, lay.elem, 0), pos + lay.n_fields
        if isinstance(lay, RecNode):
            entries = []
            for (f, opt, child), (fs, fsh, fopt) in zip(
                lay.entries, lay.shape.fields
            ):
                if opt:
                    pres = LB(fields[:, pos] == 1, 0)
                    pos += 1
                else:
                    pres = LC(True)
                val, pos = self._decode_layout(child, fields, pos, fsh)
                entries.append((f, pres, val))
            return LRec(entries), pos
        if isinstance(lay, SeqNode):
            length = LI(fields[:, pos], 0, bounds=(0, lay.cap))
            pos += 1
            slots = []
            for _ in range(lay.cap):
                slots.append(LE(fields[:, pos], lay.elem, 0))
                pos += 1
            return LSeq(length, slots, lay.elem, lay.cap), pos
        raise CompileError(f"cannot decode layout {type(lay).__name__}")

    def encode_var(self, lv, lay, shape, B, ctx) -> List:
        """LV -> list of [B] int32 field arrays matching the layout."""
        if lv == "passthrough":
            raise CompileError("passthrough handled by caller")
        if isinstance(lay, EnumLeaf):
            le = self.to_leaf(lv, lay)
            arr = jnp.broadcast_to(_to_b(le.arr, B), (B,))
            ctx.trap = self._lor(ctx.trap, LB(arr < 0, 0))
            return [jnp.maximum(arr, 0)]
        if isinstance(lay, MaskLeaf):
            m = self.as_mask(lv, like=LM(jnp.zeros(
                (1, len(lay.elem.values)), bool), lay.elem, 0))
            if m.elem_leaf is not lay.elem:
                m = self.remask(m, lay.elem)
            if m.depth != 0:
                raise CompileError("lifted mask at encode")
            bits = jnp.broadcast_to(m.bits, (B, len(lay.elem.values)))
            out = []
            off = 0
            for w in lay.widths:
                weights = jnp.asarray([1 << i for i in range(w)],
                                      jnp.int32)
                out.append(
                    (bits[:, off:off + w].astype(jnp.int32) * weights)
                    .sum(axis=-1)
                )
                off += w
            return out
        if isinstance(lay, RecNode):
            rec = lv
            if isinstance(rec, LE):
                rec = self.explode(rec)
            if isinstance(rec, LC):
                rec = LRec([
                    (f, LC(True), LC(x)) for f, x in rec.value
                ])
            if not isinstance(rec, LRec):
                raise CompileError(
                    f"cannot encode {type(lv).__name__} as record"
                )
            out = []
            for f, opt, child in lay.entries:
                fsh = lay.shape.field(f)[0]
                p, v = rec.get(f)
                if v is None:
                    p = LC(False)
                if opt:
                    parr = (jnp.broadcast_to(_to_b(p.arr, B), (B,))
                            if isinstance(p, LB)
                            else jnp.full((B,), bool(p.value)))
                    out.append(parr.astype(jnp.int32))
                else:
                    if isinstance(p, LC) and p.value is False:
                        raise CompileError(f"required field {f} absent")
                    parr = None
                if v is None:
                    out.extend([jnp.zeros((B,), jnp.int32)]
                               * child.n_fields)
                else:
                    sub = self.encode_var(v, child, fsh, B, ctx)
                    if opt:
                        mask = parr == 1
                        sub = [jnp.where(mask, s, 0) for s in sub]
                    out.extend(sub)
            return out
        if isinstance(lay, SeqNode):
            if not isinstance(lv, LSeq):
                raise CompileError("cannot encode non-sequence")
            ln = jnp.broadcast_to(_to_b(lv.length.arr, B), (B,))
            ln = jnp.clip(ln, 0, lay.cap)
            out = [ln.astype(jnp.int32)]
            for i in range(lay.cap):
                se = self.to_leaf(lv.slots[i], lay.elem) \
                    if i < len(lv.slots) else LE(
                        jnp.zeros((1,), jnp.int32), lay.elem, 0)
                arr = jnp.broadcast_to(_to_b(se.arr, B), (B,))
                live = i < ln
                ctx.trap = self._lor(ctx.trap, LB(live & (arr < 0), 0))
                out.append(jnp.where(live, jnp.maximum(arr, 0), 0))
            return out
        raise CompileError(f"cannot encode layout {type(lay).__name__}")

    # ======================================================================
    # Lane walker (compile-time nondeterminism fan-out)
    # ======================================================================

    def walk_lanes(self, next_ast, env0) -> List["Lane"]:
        lanes: List[Lane] = []
        ctx = LaneCtx()
        self._walk(next_ast, dict(env0), ctx, None, lanes)
        return lanes

    def _walk(self, ast, env, ctx, label, out):
        op = ast[0]
        if op == "and":
            self._walk_seq(list(ast[1]), 0, env, ctx, label, out)
            return
        self._walk_seq([ast], 0, env, ctx, label, out)

    def _cov_on(self) -> bool:
        return self.cov is not None and self.cov.active

    def _walk_seq(self, items, i, env, ctx, label, out):
        if i == len(items):
            if self._cov_on():
                # update-conjunct sites log once per completed
                # successor path: the lane's full guard is exactly
                # "this path fires for this state"
                for idx in ctx.cov_effects:
                    self.cov.hit(idx, ctx.guard)
            out.append(Lane(label or "?", env, ctx))
            return
        ast = items[i]
        rest = items[i + 1:]
        op = ast[0]
        if op == "and":
            self._walk_seq(list(ast[1]) + rest, 0, env, ctx, label, out)
            return
        if op == "or":
            for branch in ast[1]:
                self._walk_seq([branch] + rest, 0, dict(env),
                               ctx.fork(), label, out)
            return
        if op == "exists":
            self._walk_exists(ast, rest, env, ctx, label, out)
            return
        if op == "if":
            cond = self.comp(ast[1], env, ctx)
            if isinstance(cond, LC):
                self._walk_seq([ast[2] if cond.value else ast[3]] + rest,
                               0, env, ctx, label, out)
                return
            for guard, branch, arm in ((cond, ast[2], "THEN"),
                                       (self._lnot(cond), ast[3],
                                        "ELSE")):
                c2 = ctx.fork()
                c2.guard = self._land(c2.guard, guard)
                if self._cov_on():
                    # branch-arm site: visited once per state whose
                    # path selects this arm (reach AND the arm guard)
                    self.cov.hit(
                        self.cov.site(label, "branch", branch, arm),
                        c2.guard,
                    )
                self._walk_seq([branch] + rest, 0, dict(env), c2, label,
                               out)
            return
        if op == "let":
            env2 = dict(env)
            for name, params, body in ast[1]:
                if params:
                    env2[name] = Definition(name, params, body)
                else:
                    env2[name] = self.comp(body, env2, ctx)
            self._walk_seq([ast[2]] + rest, 0, env2, ctx, label, out)
            return
        if op in ("call", "name"):
            dname = ast[1]
            d = env.get(dname)
            if not isinstance(d, Definition):
                d = self.ev.defs.get(dname)
            if isinstance(d, Definition) and _mentions_prime_static(
                d.body, self.ev.defs
            ):
                args = ast[2] if op == "call" else []
                env2 = dict(env)
                for p, a in zip(d.params, args):
                    env2[p] = self.comp(a, env, ctx)
                inner = label if d.body[0] == "or" else dname
                self._walk_seq([d.body] + rest, 0, env2, ctx, inner, out)
                return
        if op == "unchanged":
            from .actions import expand_unchanged

            env2 = dict(env)
            for v in expand_unchanged(ast[1], self.ev.defs,
                                      set(self.variables)):
                env2[("'", v)] = "passthrough"
            if self._cov_on():
                ctx.cov_effects.append(self.cov.site(
                    label, "unchanged", ast,
                    "UNCHANGED " + ", ".join(ast[1])))
            self._walk_seq(rest, 0, env2, ctx, label, out)
            return
        if op == "cmp" and ast[1] == "=" and ast[2][0] == "prime":
            name = ast[2][1]
            val = self.comp(ast[3], env, ctx)
            key = ("'", name)
            env2 = dict(env)
            if key in env:
                prev = env[key]
                prev_lv = env[name] if prev == "passthrough" else prev
                ctx.guard = self._land(ctx.guard, self.eq(prev_lv, val))
            else:
                env2[key] = val
            if self._cov_on():
                ctx.cov_effects.append(self.cov.site(
                    label, "effect", ast, f"{name}' :="))
            self._walk_seq(rest, 0, env2, ctx, label, out)
            return
        # plain guard conjunct: the site logs at the reach of THIS
        # conjunct (the guard-so-far, TLC's short-circuit discipline)
        if self._cov_on():
            self.cov.hit(self.cov.site(label, "guard", ast), ctx.guard)
        g = self.comp(ast, env, ctx)
        if isinstance(g, LC):
            if g.value is True:
                self._walk_seq(rest, 0, env, ctx, label, out)
            elif g.value is not False:
                raise CompileError("guard is not BOOLEAN")
            return
        ctx.guard = self._land(ctx.guard, g)
        self._walk_seq(rest, 0, self._refine_guard_env(ast, env), ctx,
                       label, out)

    def _refine_guard_env(self, ast, env):
        """Bare-variable interval refinement under a lane guard: after
        `x < N` joins the lane guard, x's certified interval within
        THIS lane meets the comparison (sound for trap elision: the
        elided trap is ANDed with the lane's validity, which includes
        exactly this guard - build_step's `ovf & valid`)."""
        if not (isinstance(ast, tuple) and len(ast) == 4
                and ast[0] == "cmp"):
            return env
        _, sym, la, ra = ast
        for lhs, rhs, s in ((la, ra, sym),
                            (ra, la, {"<": ">", ">": "<", "<=": ">=",
                                      ">=": "<="}.get(sym, sym))):
            if not (isinstance(lhs, tuple) and lhs[0] == "name"):
                continue
            lv = env.get(lhs[1])
            if not isinstance(lv, LI) or lv.bounds is None:
                continue
            try:
                rb = _int_bounds(self.comp(rhs, env, LaneCtx()))
            except (ValueError, KeyError, TypeError):
                continue
            if rb is None:
                continue
            lo, hi = lv.bounds
            if s == "<":
                hi = min(hi, rb[1] - 1)
            elif s == "<=":
                hi = min(hi, rb[1])
            elif s == ">":
                lo = max(lo, rb[0] + 1)
            elif s == ">=":
                lo = max(lo, rb[0])
            elif s == "=":
                lo, hi = max(lo, rb[0]), min(hi, rb[1])
            else:
                continue
            if lo <= hi:
                env = dict(env)
                env[lhs[1]] = LI(lv.arr, lv.depth, bounds=(lo, hi))
        return env

    def _walk_exists(self, ast, rest, env, ctx, label, out):
        _, names, dom_ast, body = ast
        if len(names) != 1:
            raise CompileError("multi-binder \\E in action position")
        name = names[0]
        desc = self._dom_descriptor(dom_ast, env, ctx)
        cov_idx = None
        if self._cov_on():
            # binder-body site: one visit per (state, live binding) -
            # the quantifier-body count of TLC's dump
            cov_idx = self.cov.site(label, "quant", ast, f"\\E {name}")
        if desc[0] == "const":
            for v in desc[1]:
                env2 = dict(env)
                env2[name] = LC(v)
                c2 = ctx.fork()
                if cov_idx is not None:
                    self.cov.hit(cov_idx, c2.guard)
                self._walk_seq([body] + rest, 0, env2, c2,
                               label, out)
            return
        m: LM = desc[1]
        if m.depth != 0:
            raise CompileError("lifted set in action-position \\E")
        if desc[0] == "atoms":
            for i, v in enumerate(m.elem_leaf.values):
                env2 = dict(env)
                env2[name] = LC(v)
                c2 = ctx.fork()
                c2.guard = self._land(c2.guard, LB(m.bits[..., i], 0))
                if cov_idx is not None:
                    self.cov.hit(cov_idx, c2.guard)
                self._walk_seq([body] + rest, 0, env2, c2, label, out)
            return
        # record-universe set: k-th set-bit slot lanes.  A certified
        # cardinality bound on a bare-variable domain (analysis.absint
        # TrapPolicy) shrinks the lane fan to the bound and - when the
        # bound fits the slot budget - elides the overflow trap: lanes
        # k >= |set| are never valid, so dropping them is count-exact,
        # and the runtime certificate column re-verifies the bound
        # (popcount of the committed mask) on device
        slot_cap = SLOT_CAP
        card = None
        if self.trap_policy is not None and dom_ast[0] == "name":
            card = self.trap_policy.card_bounds.get(dom_ast[1])
        if card is not None and card < SLOT_CAP:
            self.reduced_slot_lanes += SLOT_CAP - card
            slot_cap = max(card, 1)
        counts = m.bits.astype(jnp.int32).cumsum(axis=-1)
        total = counts[..., -1]
        self.trap_sites += 1
        proven = card is not None and card <= slot_cap
        if proven:
            self.elided_traps += 1
        for k in range(slot_cap):
            sel = m.bits & (counts == k + 1)
            idx = jnp.argmax(sel, axis=-1).astype(jnp.int32)
            has = sel.any(axis=-1)
            env2 = dict(env)
            env2[name] = self._from_leaf(
                LE(jnp.where(has, idx, -1), m.elem_leaf, 0),
                m.elem_leaf.shape,
            )
            c2 = ctx.fork()
            c2.guard = self._land(c2.guard, LB(has, 0))
            if cov_idx is not None:
                self.cov.hit(cov_idx, c2.guard)
            if not proven:
                c2.ovf = self._lor(c2.ovf, LB(total > slot_cap, 0))
            self._walk_seq([body] + rest, 0, env2, c2, label, out)

    # ======================================================================
    # Step function
    # ======================================================================

    def build_step(self, next_ast):
        """step(fields [B,F] int32) ->
        (succs [B,L,F], valid [B,L], ovf [B,L], afail [B,L]); also sets
        self.labels (per-lane action names) on first run."""
        self.labels: Optional[List[str]] = None

        def step(fields):
            B = fields.shape[0]
            # trap accounting restarts per trace so retraces (eval_shape
            # then jit) report one compile's numbers, not a running sum
            self.trap_sites = 0
            self.elided_traps = 0
            self.reduced_slot_lanes = 0
            env0 = dict(self.decode_state(fields))
            lanes = self.walk_lanes(next_ast, env0)
            labels = []
            succ_cols, valids, ovfs, afails = [], [], [], []
            for lane in lanes:
                labels.append(lane.label)
                cols = []
                for v, lay in zip(self.variables, self.codec.layouts):
                    lv = lane.env.get(("'", v))
                    if lv is None and v in self.sweep_vars:
                        # a swept constant is unchanged by construction
                        lv = "passthrough"
                    if lv is None:
                        raise CompileError(
                            f"lane {lane.label}: {v}' unassigned"
                        )
                    if lv == "passthrough":
                        off = self.codec.offsets[v]
                        for j in range(lay.n_fields):
                            cols.append(fields[:, off + j])
                    else:
                        cols.extend(self.encode_var(
                            lv, lay, self.var_shapes[v], B, lane.ctx))
                succ_cols.append(jnp.stack(cols, axis=-1))
                valids.append(self._guard_arr(lane.ctx.guard, B))
                # overflow/trap only matter when the lane actually
                # fires (a guard-disabled Append past cap is harmless);
                # trap = semantic escape (a value fell outside the
                # inferred universe) - both halt the run loudly
                ovfs.append(
                    (self._guard_arr(lane.ctx.ovf, B)
                     | self._guard_arr(lane.ctx.trap, B)) & valids[-1]
                )
                afails.append(self._guard_arr(lane.ctx.afail, B)
                              & valids[-1])
            if self.labels is None:
                self.labels = labels
            succs = jnp.stack(succ_cols, axis=1)
            valid = jnp.stack(valids, axis=1)
            ovf = jnp.stack(ovfs, axis=1)
            afail = jnp.stack(afails, axis=1)
            return succs, valid, ovf, afail

        return step

    def _guard_arr(self, g, B):
        if isinstance(g, LC):
            return jnp.full((B,), bool(g.value))
        if g.depth != 0:
            raise CompileError("lane guard kept a lift axis")
        return jnp.broadcast_to(_to_b(g.arr, B), (B,))

    def build_cov(self, next_ast):
        """Device coverage hook for the live coverage plane (ISSUE 11):
        ``cov_fn(fields [B,F], mask [B], valid [B,L]) -> [n_sites]
        uint32`` - this block's per-site visit increments.

        The instrumented walk re-derives only the lane GUARD structure
        (no successor encode), from the same pure functions of the
        state fields the step evaluates, so XLA can CSE the shared
        subgraphs when both live in one jit; the site table is
        discovered on the first trace (self.cov.sites) and stable
        across retraces.  Pure telemetry - the result feeds no control
        flow."""
        self.cov = CovCollector()

        def cov_fn(fields, mask, valid):
            B = fields.shape[0]
            saved = (self.trap_sites, self.elided_traps,
                     self.reduced_slot_lanes)
            self.cov.begin()
            try:
                env0 = dict(self.decode_state(fields))
                self.walk_lanes(next_ast, env0)
            finally:
                contribs = self.cov.end()
                (self.trap_sites, self.elided_traps,
                 self.reduced_slot_lanes) = saved
            n = len(self.cov.sites)
            if n == 0:
                return jnp.zeros(0, jnp.uint32)
            # one [M, B] stack + one masked matvec + one segment
            # scatter-add instead of M separate reduces (the cheap
            # shape the --cov-ab overhead gate depends on)
            idxs, cols = [], []
            for idx, cond in contribs:
                if isinstance(cond, LC):
                    if not cond.value:
                        continue
                    arr = jnp.ones((B,), jnp.int32)
                else:
                    if cond.depth != 0:
                        raise CompileError(
                            "coverage condition kept a lift axis"
                        )
                    arr = jnp.broadcast_to(
                        _to_b(cond.arr, B), (B,)
                    ).astype(jnp.int32)
                idxs.append(idx)
                cols.append(arr)
            if not cols:
                return jnp.zeros(n, jnp.uint32)
            sums = jnp.stack(cols) @ mask.astype(jnp.int32)
            return jnp.zeros(n, jnp.uint32).at[
                jnp.asarray(idxs, jnp.int32)
            ].add(sums.astype(jnp.uint32))

        return cov_fn

    def build_invariant(self, ast):
        """inv(fields [B,F]) -> ok [B] bool."""

        def inv(fields):
            B = fields.shape[0]
            env = dict(self.decode_state(fields))
            ctx = LaneCtx()
            r = self.comp(ast, env, ctx)
            return self._guard_arr(r, B)

        return inv


class LaneCtx:
    def __init__(self):
        self.guard = LC(True)
        self.ovf = LC(False)
        self.afail = LC(False)
        self.trap = LC(False)
        # coverage: update-conjunct site ids pending this path's
        # completion (resolved against the final lane guard)
        self.cov_effects: List[int] = []

    def fork(self) -> "LaneCtx":
        c = LaneCtx()
        c.guard = self.guard
        c.ovf = self.ovf
        c.afail = self.afail
        c.trap = self.trap
        c.cov_effects = list(self.cov_effects)
        return c


class Lane:
    def __init__(self, label, env, ctx):
        self.label = label
        self.env = env
        self.ctx = ctx


def _to_b(arr, B):
    """[1]- or [B]-shaped array -> broadcastable to [B]."""
    if arr.ndim == 0:
        return arr[None]
    return arr


class LSetLit(LV):
    """Unresolved set literal with dynamic elements ({Write(...)})."""

    def __init__(self, items):
        self.items = items


class LTuple(LV):
    """Unresolved tuple literal (<<frame>> before \\o)."""

    def __init__(self, items):
        self.items = items


def _named(fn, key):
    fn._key = key
    fn.__name__ = "pred"
    return fn


def _mask_align(a_bits, a_pre, b_bits, b_pre):
    """Align two mask bit planes: insert lift axes BEFORE the trailing
    universe axis so both reach the same prefix depth."""
    pre = max(a_pre, b_pre)

    def fix(bits, p):
        for _ in range(pre - p):
            bits = bits[..., None, :]
        return bits

    return fix(a_bits, a_pre), fix(b_bits, b_pre), pre
