"""Persistent + in-process step-compile cache for struct specs.

Compiling a struct spec is the expensive part of running one: the
parse -> shape-infer -> lane-compile pipeline is seconds of Python and
the XLA compile of the fused engine loop is the dominant cold-start
cost (minutes for Model_1-class modules).  Both are pure functions of
(module text, constant overrides, engine geometry), so both cache:

* **In-process memo**: backends are keyed on (source digest, canonical
  constants, invariant list); built engines additionally on the full
  geometry (chunk, queue/fp capacities, fp polynomial + seed,
  highwater, deadlock switch, engine kind, mesh devices).  Repeated
  runs of the same model in one process skip straight to execution -
  and jax's jit cache keeps the compiled executable alive because the
  memo returns the SAME engine closures.

* **Persistent XLA compilation cache**: enabled (default
  ``~/.cache/jaxtlc/xla``, override with ``JAXTLC_COMPILE_CACHE=DIR``,
  disable with ``JAXTLC_COMPILE_CACHE=off``) whenever a struct engine
  is built, so a SECOND PROCESS checking the same model skips the XLA
  compile entirely: the cache key is the optimized HLO, which embeds
  the compiled lane tables - i.e. it already encodes (module-text hash,
  constant overrides, chunk, fp geometry).  Clear it by deleting the
  directory.  `bench.py --struct` measures the effect as
  ``struct_warm_start_s``.
"""

from __future__ import annotations

import os
from collections import OrderedDict
from typing import Tuple

_DEFAULT_CACHE_DIR = os.path.join(
    os.path.expanduser("~"), ".cache", "jaxtlc", "xla"
)

_persistent_enabled: str = ""


class _LRUMemo:
    """Bounded in-process memo (ISSUE 9 satellite): a long-lived
    serving process runs an unbounded stream of distinct models, so the
    memo that used to be a plain dict now evicts least-recently-used
    entries at a size cap and exposes hit/miss/size stats (the
    serve-side EnginePool builds on these counters for its own
    warm/cold accounting).  Eviction only drops OUR reference: callers
    holding an evicted backend/engine keep it alive (and jax keeps its
    compiled executable alive through their closures)."""

    def __init__(self, cap: int):
        self.cap = cap
        self._d: OrderedDict = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions = 0

    def get(self, key):
        hit = self._d.get(key)
        if hit is None:
            self.misses += 1
            return None
        self.hits += 1
        self._d.move_to_end(key)
        return hit

    def put(self, key, value) -> None:
        self._d[key] = value
        self._d.move_to_end(key)
        while len(self._d) > self.cap:
            self._d.popitem(last=False)
            self.evictions += 1

    def stats(self) -> dict:
        return dict(hits=self.hits, misses=self.misses,
                    size=len(self._d), cap=self.cap,
                    evictions=self.evictions)

    def clear(self) -> None:
        self._d.clear()


def _env_cap(name: str, default: int) -> int:
    try:
        return max(1, int(os.environ.get(name, "")))
    except ValueError:
        return default


# backends are cheap-ish Python (parse + shape-infer + closures); built
# engines pin compiled-executable references, so their cap is tighter
_BACKEND_MEMO = _LRUMemo(_env_cap("JAXTLC_BACKEND_MEMO_CAP", 64))
_ENGINE_MEMO = _LRUMemo(_env_cap("JAXTLC_ENGINE_MEMO_CAP", 32))


def stats() -> dict:
    """Hit/miss/size/eviction counters for the memos (cumulative per
    process; the serve /pool endpoint republishes them)."""
    return {"backend": _BACKEND_MEMO.stats(),
            "engine": _ENGINE_MEMO.stats(),
            "bounds": _BOUNDS_MEMO.stats()}


def set_caps(backend: int = None, engine: int = None) -> None:
    """Resize the memo caps (tests + server sizing; shrinking evicts
    LRU entries immediately)."""
    for memo, cap in ((_BACKEND_MEMO, backend), (_ENGINE_MEMO, engine)):
        if cap is None:
            continue
        memo.cap = max(1, int(cap))
        while len(memo._d) > memo.cap:
            memo._d.popitem(last=False)
            memo.evictions += 1


def enable_persistent_cache(path: str = None) -> str:
    """Point jax's persistent compilation cache at `path` (idempotent).

    Returns the directory in effect, or "" when disabled
    (JAXTLC_COMPILE_CACHE=off).  Thresholds are zeroed so every engine
    compile persists - struct steps are exactly the long-compile
    artifacts the cache exists for."""
    global _persistent_enabled
    env = os.environ.get("JAXTLC_COMPILE_CACHE", "")
    if env.lower() in ("off", "0", "none"):
        return ""
    path = path or env or _DEFAULT_CACHE_DIR
    if _persistent_enabled == path:
        return path
    import jax

    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    _persistent_enabled = path
    return path


def model_key(model) -> tuple:
    """The spec-meaning component of every cache key."""
    from .backend import canonical_constants

    consts = tuple(
        (k, tuple(v) if isinstance(v, list) else v)
        for k, v in canonical_constants(model).items()
    )
    return (
        model.source_digest or repr(id(model)),
        consts,
        tuple(model.invariants),
    )


# certified bound reports are pure functions of the spec meaning
# (digest + constants + invariants); milliseconds of host Python, but
# the memo keeps the narrowed-engine key stable within a process
_BOUNDS_MEMO = _LRUMemo(_env_cap("JAXTLC_BOUNDS_MEMO_CAP", 64))


def get_bounds(model):
    """Memoized certified bound report (analysis.absint) for a struct
    model - every consumer of the narrowed codec (backend memo, engine
    memo, checkpoint meta) derives its key from this one report."""
    from ..analysis.absint import analyze_bounds

    key = model_key(model)
    hit = _BOUNDS_MEMO.get(key)
    if hit is None:
        hit = analyze_bounds(model)
        _BOUNDS_MEMO.put(key, hit)
    return hit


def _bounds_key(bounds) -> str:
    """The bound-digest component of narrowed cache keys ("" = the
    un-narrowed baseline layout)."""
    if bounds is None:
        return ""
    return bounds.digest()


def get_backend(model, check_deadlock: bool = True, bounds=None,
                elide: bool = True, coverage: bool = False,
                symmetry: bool = False, por: bool = False):
    """Memoized struct_backend (the parse -> shape-infer -> lane-compile
    pipeline runs once per spec meaning per process).  `bounds` (a
    certified analysis.absint.BoundReport) selects the NARROWED
    compile - a distinct memo entry keyed on the bound digest;
    `elide=False` keeps every trap (the sharded engines' narrowed
    form, which has no certificate column).  `coverage` compiles the
    device coverage plane in (a distinct memo entry: the backend
    carries the site table + count hook).  `symmetry`/`por` (resolved
    bools) attach the state-space reduction ops - distinct memo
    entries because the reduced engine has a different carry layout
    (COL_SYM ring column, prune counters) and different step XLA."""
    from .backend import struct_backend

    enable_persistent_cache()
    key = (model_key(model), bool(check_deadlock), _bounds_key(bounds),
           bool(elide), bool(coverage), bool(symmetry), bool(por))
    hit = _BACKEND_MEMO.get(key)
    if hit is None:
        hit = struct_backend(model, check_deadlock=check_deadlock,
                             bounds=bounds, elide=elide,
                             coverage=coverage, symmetry=symmetry,
                             por=por)
        _BACKEND_MEMO.put(key, hit)
    return hit


def engine_key(
    model,
    chunk: int,
    queue_capacity: int,
    fp_capacity: int,
    fp_index: int,
    seed: int,
    fp_highwater: float,
    check_deadlock: bool = True,
    pipeline: bool = False,
    obs_slots: int = 0,
    bounds=None,
    coverage: bool = False,
    sort_free: bool = None,
    deferred: bool = None,
    symmetry: bool = None,
    por: bool = None,
) -> tuple:
    """The full engine-memo key: spec meaning (digest + canonical
    constants + invariants) x engine geometry x pipeline/obs/coverage/
    sort-free flags x the certified-bound digest (a narrowed engine is
    a DIFFERENT compile - its codec, lanes and traps all change with
    the bounds; a covered engine carries the coverage leaves; a
    sort-free engine compiles the hash-slab commit; a deferred
    engine moves invariant/cert evaluation to the commit stage, ISSUE
    15; a symmetry/POR-reduced engine canonicalizes and prunes in the
    expand stage, ISSUE 18).  The serve EnginePool keys its warm AOT
    entries on exactly this tuple so pool identity and memo identity
    cannot drift.  `sort_free`/`deferred`/`symmetry`/`por` are
    resolved (tri-state auto -> bool) against the chunk so the key
    never depends on who asked."""
    from ..engine.bfs import (
        resolve_deferred,
        resolve_por,
        resolve_sort_free,
        resolve_symmetry,
    )

    return (
        model_key(model), "single", chunk, queue_capacity, fp_capacity,
        fp_index, seed, fp_highwater, bool(check_deadlock),
        bool(pipeline), int(obs_slots), _bounds_key(bounds),
        bool(coverage), resolve_sort_free(sort_free, chunk),
        resolve_deferred(deferred, chunk),
        resolve_symmetry(symmetry, chunk), resolve_por(por, chunk),
    )


def get_engine(
    model,
    chunk: int,
    queue_capacity: int,
    fp_capacity: int,
    fp_index: int,
    seed: int,
    fp_highwater: float,
    check_deadlock: bool = True,
    pipeline: bool = False,
    obs_slots: int = 0,
    bounds=None,
    coverage: bool = False,
    sort_free: bool = None,
    deferred: bool = None,
    symmetry: bool = None,
    por: bool = None,
) -> Tuple:
    """Memoized single-device engine triple (init_fn, run_fn, step_fn)
    for a struct model; enables the persistent XLA cache as a side
    effect so the jit compiles it triggers land on disk.  obs_slots is
    part of the key: the ring changes the carry pytree, so an obs-on
    engine is a different compile than an obs-off one.  `bounds`
    selects the narrowed engine (certificate check on, keyed on the
    bound digest); `coverage` the covered engine (per-site counter
    leaves on the carry); `sort_free` the hash-slab commit (resolved
    against the chunk, so an auto caller and an explicit caller at the
    same geometry share one memo entry); `symmetry`/`por` the reduced
    engine (orbit canonicalization + ample-set pruning, ISSUE 18)."""
    from ..engine.bfs import (
        make_backend_engine,
        resolve_por,
        resolve_symmetry,
    )

    enable_persistent_cache()
    key = engine_key(
        model, chunk, queue_capacity, fp_capacity, fp_index, seed,
        fp_highwater, check_deadlock=check_deadlock, pipeline=pipeline,
        obs_slots=obs_slots, bounds=bounds, coverage=coverage,
        sort_free=sort_free, deferred=deferred, symmetry=symmetry,
        por=por,
    )
    hit = _ENGINE_MEMO.get(key)
    if hit is None:
        backend = get_backend(model, check_deadlock, bounds=bounds,
                              coverage=coverage,
                              symmetry=resolve_symmetry(symmetry, chunk),
                              por=resolve_por(por, chunk))
        hit = make_backend_engine(
            backend, chunk, queue_capacity, fp_capacity, fp_index, seed,
            fp_highwater=fp_highwater, pipeline=pipeline,
            obs_slots=obs_slots, sort_free=sort_free,
            deferred=deferred,
        )
        _ENGINE_MEMO.put(key, hit)
    return hit


def clear() -> None:
    """Drop the in-process memos (tests; the persistent cache is files)."""
    _BACKEND_MEMO.clear()
    _ENGINE_MEMO.clear()
    _BOUNDS_MEMO.clear()
