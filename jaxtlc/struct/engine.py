"""Device checking of struct-compiled specs (E1) on the production
engines.

The private struct BFS loop is retired (round-6 tentpole): the
LaneCompiler step plugs into the same fused v4 engine the hand kernel
uses (engine.bfs.make_backend_engine via struct.backend.struct_backend),
so struct specs get the bucketized sort-compacted dedup, MXU
fingerprints, contiguous enqueue, two-tier adaptive stepping, segmented
execution (the resil supervisor's unit of work), TLC outdegree stats
and the assertion-failure channel from one code path.  Mesh sharding
routes through engine.sharded with the same backend.

Engine builds are memoized and XLA compiles persist across processes
(struct.cache): repeated runs of the same model skip the minutes-long
compile (bench.py --struct tracks the warm-start win).
"""

from __future__ import annotations

import time

import jax

from ..engine.bfs import (
    CheckResult,
    VIOLATION_NAMES,
    result_from_carry,
)
from ..engine.fingerprint import DEFAULT_FP_INDEX, DEFAULT_SEED
from .backend import (  # noqa: F401 - VIOL_INVARIANT_BASE is API here
    VIOL_INVARIANT_BASE,
    struct_backend,
    struct_viol_names,
)
from .cache import get_backend, get_engine
from .loader import StructModel


def violation_name(model: StructModel, code: int) -> str:
    return struct_viol_names(model).get(code) or VIOLATION_NAMES.get(
        code, f"violation {code}"
    )


def check_struct(
    model: StructModel,
    chunk: int = 1024,
    queue_capacity: int = 1 << 15,
    fp_capacity: int = 1 << 20,
    fp_index: int = DEFAULT_FP_INDEX,
    seed: int = DEFAULT_SEED,
    check_deadlock: bool = True,
    fp_highwater: float = 0.85,
    pipeline: bool = False,
    obs_slots: int = 0,
    bounds=None,
    coverage: bool = False,
    sort_free: bool = None,
    deferred: bool = None,
    symmetry: bool = None,
    por: bool = None,
    capture_fps: bool = False,
) -> CheckResult:
    """Exhaustive device check of a struct-compiled spec (single device,
    fused loop; AOT-compiled before timing like bfs.check).  `bounds`
    (a certified analysis.absint.BoundReport) runs the NARROWED engine
    with the runtime certificate check on; `coverage` the covered
    engine (device per-site coverage on CheckResult.site_coverage);
    `sort_free` the hash-slab commit (bit-identical results);
    `symmetry`/`por` the state-space-reduced engine (orbit
    canonicalization with the runtime orbit certificate + ample-set
    pruning - same verdict, legitimately fewer states, ISSUE 18);
    `capture_fps` reads the final fingerprint table back to host on a
    clean verdict (CheckResult.fp_table - the artifact cache's
    reachable-set source, struct.artifacts)."""
    from ..engine.bfs import resolve_por, resolve_symmetry

    init_fn, run_fn, _ = get_engine(
        model, chunk, queue_capacity, fp_capacity, fp_index, seed,
        fp_highwater, check_deadlock=check_deadlock, pipeline=pipeline,
        obs_slots=obs_slots, bounds=bounds, coverage=coverage,
        sort_free=sort_free, deferred=deferred, symmetry=symmetry,
        por=por,
    )
    backend = get_backend(model, check_deadlock, bounds=bounds,
                          coverage=coverage,
                          symmetry=resolve_symmetry(symmetry, chunk),
                          por=resolve_por(por, chunk))
    carry = init_fn()
    compiled = run_fn.lower(carry).compile()
    t0 = time.time()
    out = jax.block_until_ready(compiled(carry))
    wall = time.time() - t0
    result = result_from_carry(
        out, wall, fp_capacity=fp_capacity, labels=backend.labels,
        viol_names=backend.viol_names,
        sites=backend.coverage.sites if backend.coverage else None,
    )
    if capture_fps and result.violation == 0:
        import numpy as np

        result = result._replace(
            fp_table=np.asarray(jax.device_get(out.fps.table))
        )
    return result


def check_struct_sharded(
    model: StructModel,
    mesh,
    chunk: int = 512,
    queue_capacity: int = 1 << 14,
    fp_capacity: int = 1 << 18,
    route_factor: float = 2.0,
    check_deadlock: bool = True,
    pipeline: bool = False,
    obs_slots: int = 0,
    bounds=None,
    coverage: bool = False,
    sort_free: bool = None,
    deferred: bool = None,
    symmetry: bool = None,
    por: bool = None,
) -> CheckResult:
    """Exhaustive mesh-sharded check of a struct-compiled spec
    (capacities PER DEVICE; fingerprint-space all_to_all partitioning,
    psum-reduced counters - engine.sharded, same backend seam).
    `bounds` narrows the codec; the mesh engine has no certificate
    column yet, so every trap stays compiled in (elide=False) and the
    encode traps carry the soundness story there.  `coverage` carries
    the per-device coverage partials, summed at readback.
    `symmetry`/`por` reduce the state space before routing: orbit
    canonicalization runs pre-fingerprint so representatives shard
    consistently (the fingerprint is a pure function of the canonical
    packed words on every device)."""
    from ..engine.bfs import resolve_por, resolve_symmetry
    from ..engine.sharded import check_sharded

    backend = get_backend(model, check_deadlock, bounds=bounds,
                          elide=False, coverage=coverage,
                          symmetry=resolve_symmetry(symmetry, chunk),
                          por=resolve_por(por, chunk))
    return check_sharded(
        None, mesh, chunk=chunk, queue_capacity=queue_capacity,
        fp_capacity=fp_capacity, route_factor=route_factor,
        backend=backend, pipeline=pipeline, obs_slots=obs_slots,
        sort_free=sort_free, deferred=deferred,
    )
