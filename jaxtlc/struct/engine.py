"""Device BFS engine for struct-compiled specs (E1).

The same fused v4 design as the generic engine (gen/engine.py): ping-
pong packed level buffers, sort-compacted dedup against the bucketized
fingerprint table, contiguous enqueue, MXU fingerprints - fed by the
lane kernel that struct.compile derives from the module text.  Adds an
assertion-failure channel (PlusCal `assert`, KubeAPI.tla:196,216,348 -
the hand kernel has the same channel; the gen subset has no Assert).

The step is batch-compiled (the compiler emits [B, L, F] directly), so
no vmap wrapper is needed.
"""

from __future__ import annotations

import time
from typing import Dict, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..engine.bfs import (
    OK,
    VIOL_ASSERT,
    VIOL_DEADLOCK,
    VIOL_FPSET_FULL,
    VIOL_QUEUE_FULL,
    VIOL_SLOT_OVERFLOW,
    VIOLATION_NAMES,
    CheckResult,
)
from ..engine.fingerprint import DEFAULT_FP_INDEX, DEFAULT_SEED, fp64_words_mxu
from ..engine.fpset import fpset_insert_sorted, fpset_new
from .codec import StructCodec
from .compile import LaneCompiler
from .loader import StructModel
from .shapes import infer_shapes

VIOL_INVARIANT_BASE = 100


class StructCarry(NamedTuple):
    fps: tuple
    queue: jnp.ndarray
    parity: jnp.ndarray
    qhead: jnp.ndarray
    level_n: jnp.ndarray
    next_n: jnp.ndarray
    level: jnp.ndarray
    depth: jnp.ndarray
    generated: jnp.ndarray
    distinct: jnp.ndarray
    act_gen: jnp.ndarray
    act_dist: jnp.ndarray
    viol: jnp.ndarray
    viol_state: jnp.ndarray


def make_struct_engine(
    model: StructModel,
    chunk: int = 1024,
    queue_capacity: int = 1 << 15,
    fp_capacity: int = 1 << 20,
    fp_index: int = DEFAULT_FP_INDEX,
    seed: int = DEFAULT_SEED,
    check_deadlock: bool = True,
):
    system = model.system
    from .shapes import typeok_hints

    hints = typeok_hints(system.ev, model.invariants, system.variables)
    var_shapes = infer_shapes(system.ev, system.variables,
                              system.init_ast, system.next_ast,
                              hints=hints)
    cdc = StructCodec(system.variables, var_shapes)
    compiler = LaneCompiler(system.ev, system.variables, var_shapes, cdc)
    step = compiler.build_step(system.next_ast)
    inv_fns = [
        (name, compiler.build_invariant(ast))
        for name, ast in model.invariants.items()
    ]
    F = cdc.n_fields
    W = cdc.n_words
    qcap = queue_capacity

    # discover lane structure (labels) with a tiny eager run
    inits = system.initial_states()
    init_fields = np.stack([cdc.encode(st) for st in inits])
    _ = jax.eval_shape(step, jax.ShapeDtypeStruct((1, F), jnp.int32))
    labels = compiler.labels
    L = len(labels)
    action_names = sorted(set(labels))
    n_actions = len(action_names)
    lane_action = jnp.asarray(
        [action_names.index(x) for x in labels], jnp.int32
    )

    def init_fn() -> StructCarry:
        inits_j = jnp.asarray(init_fields, jnp.int32)
        n0 = inits_j.shape[0]
        assert n0 <= chunk and n0 <= qcap
        packed0 = cdc.pack(inits_j)
        queue = (
            jnp.zeros((2, qcap + 2 * chunk, W), jnp.uint32)
            .at[0, :n0]
            .set(packed0)
        )
        lo, hi = fp64_words_mxu(packed0, cdc.nbits, fp_index, seed)
        fps, is_new_c, _, _ = fpset_insert_sorted(
            fpset_new(fp_capacity), lo, hi, jnp.ones(n0, bool)
        )
        viol = jnp.int32(OK)
        viol_state = jnp.zeros(F, jnp.int32)
        for k, (_, fn) in enumerate(inv_fns):
            bad = ~fn(inits_j)
            hit = bad.any() & (viol == OK)
            viol = jnp.where(hit, VIOL_INVARIANT_BASE + k, viol)
            viol_state = jnp.where(hit, inits_j[jnp.argmax(bad)],
                                   viol_state)
        return StructCarry(
            fps=fps,
            queue=queue,
            parity=jnp.int32(0),
            qhead=jnp.int32(0),
            level_n=jnp.int32(n0),
            next_n=jnp.int32(0),
            level=jnp.int32(1),
            depth=jnp.int32(1),
            generated=jnp.uint32(n0),
            distinct=is_new_c.sum().astype(jnp.uint32),
            act_gen=jnp.zeros(n_actions, jnp.uint32),
            act_dist=jnp.zeros(n_actions, jnp.uint32),
            viol=viol,
            viol_state=viol_state,
        )

    ncand = chunk * L
    R = min(2 * chunk, ncand)
    A = min(2 * chunk, ncand)

    def body(c: StructCarry) -> StructCarry:
        avail = c.level_n - c.qhead
        n = jnp.minimum(chunk, avail)
        rows = jnp.arange(chunk, dtype=jnp.int32)
        mask = rows < n

        block = lax.dynamic_slice(
            c.queue, (c.parity, c.qhead, jnp.int32(0)), (1, chunk, W)
        )[0]
        batch = cdc.unpack(block)

        succs, valid, ovf, afail = step(batch)
        valid = valid & mask[:, None]
        ovf = ovf & mask[:, None]
        afail = afail & mask[:, None]
        dead = mask & ~valid.any(axis=1) if check_deadlock else (
            jnp.zeros(chunk, bool)
        )

        flat = succs.reshape(ncand, F)
        fvalid = valid.reshape(-1)

        viol = c.viol
        viol_state = c.viol_state
        for k, (_, fn) in enumerate(inv_fns):
            bad = fvalid & ~fn(flat)
            hit = bad.any() & (viol == OK)
            viol = jnp.where(hit, VIOL_INVARIANT_BASE + k, viol)
            viol_state = jnp.where(hit, flat[jnp.argmax(bad)], viol_state)

        packed = cdc.pack(flat)
        lo, hi = fp64_words_mxu(packed, cdc.nbits, fp_index, seed)

        fp_full = (c.distinct.astype(jnp.int32) + ncand) > int(
            fp_capacity * 0.85
        )
        insert_mask = fvalid & ~fp_full
        fps, is_new_c, c_idx, _ = fpset_insert_sorted(
            c.fps, lo, hi, insert_mask, probe_width=R, claim_width=R
        )
        n_new = is_new_c.sum().astype(jnp.int32)
        q_full = c.next_n + n_new > qcap

        _, e_idx = lax.sort(
            ((~is_new_c).astype(jnp.uint32), c_idx.astype(jnp.uint32)),
            num_keys=2,
            is_stable=True,
        )
        e_idx_p = jnp.concatenate([e_idx, jnp.zeros(A, jnp.uint32)])

        def enq_cond(st):
            _, s = st
            return s * A < n_new

        def enq_body(st):
            queue, s = st
            offs = s * A
            idx_a = lax.dynamic_slice(e_idx_p, (offs,), (A,)).astype(
                jnp.int32
            )
            rows_a = packed[idx_a]
            woff = jnp.minimum(c.next_n + offs, qcap)
            queue = lax.dynamic_update_slice(
                queue, rows_a[None], (1 - c.parity, woff, jnp.int32(0))
            )
            return queue, s + 1

        queue, _ = lax.while_loop(enq_cond, enq_body,
                                  (c.queue, jnp.int32(0)))

        lane_onehot = (
            lane_action[:, None] == jnp.arange(n_actions)[None, :]
        )
        lane_counts = valid.sum(axis=0).astype(jnp.uint32)
        act_gen = c.act_gen + (
            lane_onehot * lane_counts[:, None]
        ).sum(axis=0).astype(jnp.uint32)

        new_act = jnp.where(
            jnp.arange(ncand) < n_new,
            lane_action[e_idx.astype(jnp.int32) % L],
            -1,
        )
        act_dist = c.act_dist + (
            new_act[:, None] == jnp.arange(n_actions)[None, :]
        ).sum(axis=0).astype(jnp.uint32)

        generated = c.generated + valid.sum().astype(jnp.uint32)
        distinct = c.distinct + n_new.astype(jnp.uint32)

        for code, vmask, states in (
            (VIOL_ASSERT, afail.any(axis=1), batch),
            (VIOL_SLOT_OVERFLOW, ovf.any(axis=1), batch),
            (VIOL_DEADLOCK, dead, batch),
        ):
            hit = vmask.any() & (viol == OK)
            viol = jnp.where(hit, code, viol)
            viol_state = jnp.where(
                hit, states[jnp.argmax(vmask)], viol_state
            )
        hit = fp_full & fvalid.any() & (viol == OK)
        viol = jnp.where(hit, VIOL_FPSET_FULL, viol)
        hit = q_full & (viol == OK)
        viol = jnp.where(hit, VIOL_QUEUE_FULL, viol)

        qhead = c.qhead + n
        next_n = jnp.minimum(c.next_n + n_new, qcap)
        level_done = qhead >= c.level_n
        advance = level_done & (next_n > 0)
        parity = jnp.where(level_done, 1 - c.parity, c.parity)
        level_n = jnp.where(level_done, next_n, c.level_n)
        next_n = jnp.where(level_done, 0, next_n)
        qhead = jnp.where(level_done, 0, qhead)
        level = jnp.where(advance, c.level + 1, c.level)
        depth = jnp.maximum(c.depth, level)

        return StructCarry(
            fps=fps, queue=queue, parity=parity, qhead=qhead,
            level_n=level_n, next_n=next_n, level=level, depth=depth,
            generated=generated, distinct=distinct, act_gen=act_gen,
            act_dist=act_dist, viol=viol, viol_state=viol_state,
        )

    def cond(c: StructCarry):
        return ((c.qhead < c.level_n) | (c.next_n > 0)) & (c.viol == OK)

    @jax.jit
    def run_fn(c: StructCarry) -> StructCarry:
        return lax.while_loop(cond, body, c)

    return init_fn, run_fn, cdc, action_names


def violation_name(model: StructModel, code: int) -> str:
    if code >= VIOL_INVARIANT_BASE:
        names = list(model.invariants.keys())
        k = code - VIOL_INVARIANT_BASE
        if k < len(names):
            return f"Invariant {names[k]} is violated"
        return "Invariant violated"
    if code == VIOL_ASSERT:
        return "Failure of PlusCal assertion"
    return VIOLATION_NAMES[code]


def check_struct(
    model: StructModel,
    chunk: int = 1024,
    queue_capacity: int = 1 << 15,
    fp_capacity: int = 1 << 20,
    fp_index: int = DEFAULT_FP_INDEX,
    seed: int = DEFAULT_SEED,
    check_deadlock: bool = True,
) -> CheckResult:
    """Exhaustive device check of a struct-compiled spec."""
    init_fn, run_fn, cdc, action_names = make_struct_engine(
        model, chunk, queue_capacity, fp_capacity, fp_index, seed,
        check_deadlock,
    )
    carry = init_fn()
    compiled = run_fn.lower(carry).compile()
    t0 = time.time()
    out = jax.block_until_ready(compiled(carry))
    wall = time.time() - t0
    code = int(out.viol)
    act_gen = np.asarray(out.act_gen)
    act_dist = np.asarray(out.act_dist)
    return CheckResult(
        generated=int(out.generated),
        distinct=int(out.distinct),
        depth=int(out.depth),
        queue_left=int(out.level_n) - int(out.qhead) + int(out.next_n),
        violation=code,
        violation_name=violation_name(model, code),
        violation_state=np.asarray(out.viol_state),
        violation_action=-1,
        action_generated={
            action_names[i]: int(v) for i, v in enumerate(act_gen) if v
        },
        action_distinct={
            action_names[i]: int(v) for i, v in enumerate(act_dist) if v
        },
        wall_s=wall,
        iterations=-1,
    )
