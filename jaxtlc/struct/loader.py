"""Load a TLC model directory through the structural frontend (E1).

Reads the unmodified reference artifacts the way TLC does
(MC.out:8-24's SANY pass): MC.cfg for CONSTANT/SPECIFICATION/INVARIANT/
PROPERTY, MC.tla for the generated constant-override definitions, and
the EXTENDS closure of real module files next to the config (Model_1
carries its own KubeAPI.tla copy) - falling back to the toolbox parent
directory for the root spec.  Standard modules (Naturals, FiniteSets,
Sequences, TLC) are built into the evaluator.
"""

from __future__ import annotations

import hashlib
import os
from typing import Dict, NamedTuple, Optional

from ..frontend.mc_cfg import parse_cfg_file
from ..spec.labels import DEFAULT_INIT
from .actions import ActionSystem
from .eval import Evaluator
from .parser import Definition, Module, StructParseError, parse_module

_BUILTIN_MODULES = {
    "TLC", "Naturals", "Integers", "Reals", "Sequences", "FiniteSets",
    "Bags", "TLAPS", "Toolbox",
}


class StructModel(NamedTuple):
    system: ActionSystem
    invariants: Dict[str, tuple]  # name -> AST
    properties: Dict[str, tuple]  # name -> AST (leadsto shapes)
    constants: Dict[str, object]
    module: Module
    fairness: Optional[str]  # "wf_next" | None
    root_name: str
    # sha256 over every source text this model was loaded from (cfg +
    # module closure) plus the constant overrides - the step-compile
    # cache key component that changes iff the spec's meaning can
    # (struct.cache keys its memo and the checkpoint meta on it)
    source_digest: str = ""


class StructLoadError(ValueError):
    pass


def _parse_const_literal(text: str):
    t = text.strip()
    if t == "TRUE":
        return True
    if t == "FALSE":
        return False
    if t.startswith('"') and t.endswith('"'):
        return t[1:-1]
    if t.lstrip("-").isdigit():
        return int(t)
    if t.startswith("{") and t.endswith("}"):
        # model-value set: CONSTANT RM = {r1, r2}.  Flat sets of simple
        # literals only - nested braces or quoted commas would split
        # wrong, so they are a loud error, not a garbage constant.
        inner = t[1:-1].strip()
        if not inner:
            return frozenset()
        if "{" in inner or '"' in inner:
            raise StructLoadError(
                f"unsupported constant set literal {t!r} (flat "
                "model-value/number sets only)"
            )
        return frozenset(
            _parse_const_literal(x) for x in inner.split(",")
        )
    if t == "defaultInitValue":
        return DEFAULT_INIT
    # TLC model value: an atom equal only to itself; the hand oracle
    # uses the same string-atom convention (spec/labels.py DEFAULT_INIT)
    return t


def _load_module_closure(path: str, search_dirs, texts=None) -> Module:
    """Parse `path` and fold in its non-builtin EXTENDS (depth-first,
    extended defs first so the extender can override).  `texts`, when
    given, collects every (path, source) read - the digest input."""
    with open(path) as f:
        src = f.read()
    if texts is not None:
        texts.append((path, src))
    root = parse_module(src)
    defs: Dict[str, Definition] = {}
    def_order = []
    variables = []
    constants = []

    def fold(mod: Module):
        for d in mod.def_order:
            if d not in defs:
                def_order.append(d)
            defs[d] = mod.defs[d]
        for v in mod.variables:
            if v not in variables:
                variables.append(v)
        for c in mod.constants:
            if c not in constants:
                constants.append(c)

    for ext in root.extends:
        if ext in _BUILTIN_MODULES:
            continue
        found = None
        for d in search_dirs:
            cand = os.path.join(d, f"{ext}.tla")
            if os.path.exists(cand):
                found = cand
                break
        if found is None:
            raise StructLoadError(
                f"EXTENDS {ext}: no {ext}.tla in {list(search_dirs)}"
            )
        fold(_load_module_closure(found, search_dirs, texts))
    fold(root)
    return Module(
        name=root.name,
        extends=root.extends,
        constants=tuple(constants),
        variables=tuple(variables),
        defs=defs,
        def_order=tuple(def_order),
    )


def load(cfg_path: str,
         const_overrides: Optional[Dict[str, object]] = None) -> StructModel:
    cfg = parse_cfg_file(cfg_path)
    model_dir = os.path.dirname(os.path.abspath(cfg_path))
    toolbox_parent = os.path.dirname(os.path.dirname(model_dir))
    search_dirs = (model_dir, toolbox_parent)
    texts = [(cfg_path, open(cfg_path).read())]

    mc_path = os.path.join(model_dir, "MC.tla")
    if os.path.exists(mc_path):
        module = _load_module_closure(mc_path, search_dirs, texts)
        root_name = next(
            (e for e in module.extends if e not in _BUILTIN_MODULES), "MC"
        )
    else:
        # bare layout: the cfg's own basename names the root module
        base = os.path.splitext(os.path.basename(cfg_path))[0]
        cand = os.path.join(model_dir, f"{base}.tla")
        if not os.path.exists(cand):
            tlas = [f for f in sorted(os.listdir(model_dir))
                    if f.endswith(".tla")]
            if len(tlas) != 1:
                raise StructLoadError(
                    f"no MC.tla and no {base}.tla next to {cfg_path}"
                )
            cand = os.path.join(model_dir, tlas[0])
        module = _load_module_closure(cand, search_dirs, texts)
        root_name = module.name

    digest = hashlib.sha256()
    for _, src in texts:
        digest.update(src.encode())
        digest.update(b"\x00")
    if const_overrides:
        for k in sorted(const_overrides):
            digest.update(f"{k}={const_overrides[k]!r};".encode())

    constants: Dict[str, object] = {}
    for name, val in cfg.constants.items():
        constants[name] = _parse_const_literal(val)
    ev0 = Evaluator(module.defs, {})
    for name, defname in cfg.substitutions.items():
        d = module.defs.get(defname)
        if d is None:
            raise StructLoadError(
                f"CONSTANT {name} <- {defname}: no such definition"
            )
        constants[name] = ev0.eval(d.body, {})
    if const_overrides:
        constants.update(const_overrides)
    # every declared constant needs a value (defaultInitValue is a model
    # value equal only to itself when left unassigned)
    for c in module.constants:
        if c not in constants:
            constants[c] = DEFAULT_INIT if c == "defaultInitValue" else c

    ev = Evaluator(module.defs, constants)

    spec_name = cfg.specification or "Spec"
    spec_def = module.defs.get(spec_name)
    if spec_def is not None and spec_def.body[0] == "spec":
        _, init_name, next_name, fairness = spec_def.body
    else:
        init_name, next_name, fairness = "Init", "Next", None
    if init_name not in module.defs or next_name not in module.defs:
        raise StructLoadError(
            f"cannot resolve Init/Next ({init_name}/{next_name})"
        )

    def _named_defs(names):
        out = {}
        for n in names:
            d = module.defs.get(n)
            if d is None:
                raise StructLoadError(f"no definition for {n!r}")
            out[n] = d.body
        return out

    return StructModel(
        system=ActionSystem(ev, module.variables, init_name, next_name),
        invariants=_named_defs(cfg.invariants),
        properties=_named_defs(cfg.properties),
        constants=constants,
        module=module,
        fairness=fairness,
        root_name=root_name,
        source_digest=digest.hexdigest(),
    )
