"""Finite-shape inference for structural specs (E1 device compilation).

TLC executes unbounded TLA+ values on a JVM heap; a tensor kernel needs
every variable laid out in fixed integer lanes.  This pass infers, by
abstract interpretation of Init and every action's primed updates, a
finite *shape* per variable - the TPU-first replacement for TLC's
dynamic value representations:

  SBool | SInt(lo,hi) | SAtoms(strings/model values) |
  SRec(field -> (shape, optional)) | SSet(elem) |
  SFun(keys, val, partial) | SSeq(elem, cap) | SUnion(alts)

Records with optional fields become presence-tagged products; sets of
records become bitmasks over the record universe (KubeAPI's apiState,
/root/reference/KubeAPI.tla:14); partial functions (requests :16) get
per-key presence bits; procedure frames/stacks (:466) become bounded
sequences.  The abstract domains over-approximate reachable values -
over-approximation costs lanes, never soundness, because the codec can
then represent every reachable value.  Fixpoint iteration with range
hulls for ints and a configurable cap for sequence growth (the kernel
flags overflow at runtime if a run exceeds it, like the hand kernel's
slot-overflow code).
"""

from __future__ import annotations

import dataclasses
from itertools import product as _product
from typing import Dict, FrozenSet, List, Optional, Tuple

from ..spec.labels import DEFAULT_INIT
from .eval import BUILTIN_SETS, Evaluator, is_fn
from .parser import Definition


class ShapeError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Shape classes (immutable, hashable)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class Shape:
    pass


@dataclasses.dataclass(frozen=True)
class SBool(Shape):
    pass


@dataclasses.dataclass(frozen=True)
class SInt(Shape):
    lo: int
    hi: int


@dataclasses.dataclass(frozen=True)
class SAtoms(Shape):
    atoms: FrozenSet[str]


@dataclasses.dataclass(frozen=True)
class SRec(Shape):
    # (field, shape, optional) triples, field-sorted
    fields: Tuple[Tuple[str, Shape, bool], ...]

    def field(self, name: str) -> Optional[Tuple[Shape, bool]]:
        for f, s, o in self.fields:
            if f == name:
                return s, o
        return None


@dataclasses.dataclass(frozen=True)
class SSet(Shape):
    elem: Optional[Shape]  # None = always-empty set


@dataclasses.dataclass(frozen=True)
class SFun(Shape):
    keys: Tuple[str, ...]
    val: Optional[Shape]  # None = always-empty function
    partial: bool


@dataclasses.dataclass(frozen=True)
class SSeq(Shape):
    elem: Optional[Shape]
    cap: int


@dataclasses.dataclass(frozen=True)
class SUnion(Shape):
    alts: Tuple[Shape, ...]  # at most one alt per shape class


SEQ_CAP_LIMIT = 2  # widening clamp; kernel checks overflow at runtime


# ---------------------------------------------------------------------------
# Join
# ---------------------------------------------------------------------------


def join(a: Optional[Shape], b: Optional[Shape]) -> Optional[Shape]:
    if a is None:
        return b
    if b is None:
        return a
    # the empty tuple value is both the empty function and the empty
    # sequence (eval._pairs_to_fn); its shape SSeq(None, 0) coerces to
    # whatever container it joins with
    if a == SSeq(None, 0) and not isinstance(b, SSeq):
        a = _empty_as(b)
    if b == SSeq(None, 0) and not isinstance(a, SSeq):
        b = _empty_as(a)
    if isinstance(a, SUnion) or isinstance(b, SUnion):
        alts = list(a.alts if isinstance(a, SUnion) else (a,))
        for x in (b.alts if isinstance(b, SUnion) else (b,)):
            alts = _merge_alt(alts, x)
        return alts[0] if len(alts) == 1 else SUnion(tuple(alts))
    if type(a) is not type(b):
        return SUnion(tuple(_merge_alt([a], b)))
    if isinstance(a, SBool):
        return a
    if isinstance(a, SInt):
        return SInt(min(a.lo, b.lo), max(a.hi, b.hi))
    if isinstance(a, SAtoms):
        return SAtoms(a.atoms | b.atoms)
    if isinstance(a, SRec):
        names = sorted({f for f, _, _ in a.fields}
                       | {f for f, _, _ in b.fields})
        out = []
        for n in names:
            fa, fb = a.field(n), b.field(n)
            if fa is None:
                out.append((n, fb[0], True))
            elif fb is None:
                out.append((n, fa[0], True))
            else:
                out.append((n, join(fa[0], fb[0]), fa[1] or fb[1]))
        return SRec(tuple(out))
    if isinstance(a, SSet):
        return SSet(join(a.elem, b.elem))
    if isinstance(a, SFun):
        keys = tuple(sorted(set(a.keys) | set(b.keys)))
        partial = a.partial or b.partial or set(a.keys) != set(b.keys)
        return SFun(keys, join(a.val, b.val), partial)
    if isinstance(a, SSeq):
        return SSeq(join(a.elem, b.elem), min(max(a.cap, b.cap),
                                              SEQ_CAP_LIMIT))
    raise ShapeError(f"cannot join {a} and {b}")


def _empty_as(like: Shape) -> Shape:
    """The empty-container shape coerced to `like`'s container class."""
    if isinstance(like, SFun):
        return SFun((), None, True)
    if isinstance(like, SRec):
        return SRec(())
    if isinstance(like, SUnion):
        for alt in like.alts:
            if isinstance(alt, (SFun, SRec)):
                return _empty_as(alt)
    return SSeq(None, 0)


def _merge_alt(alts: List[Shape], x: Shape) -> List[Shape]:
    out = []
    merged = False
    for alt in alts:
        if type(alt) is type(x):
            out.append(join(alt, x))
            merged = True
        else:
            out.append(alt)
    if not merged:
        out.append(x)
    return sorted(out, key=lambda s: type(s).__name__)


# ---------------------------------------------------------------------------
# Shape of a concrete value
# ---------------------------------------------------------------------------


def shape_of_value(v) -> Shape:
    if isinstance(v, bool):
        return SBool()
    if isinstance(v, int):
        return SInt(v, v)
    if isinstance(v, str):
        return SAtoms(frozenset({v}))
    if isinstance(v, frozenset):
        elem = None
        for x in v:
            elem = join(elem, shape_of_value(x))
        return SSet(elem)
    if isinstance(v, tuple):
        if v and is_fn(v):
            # records AND string-keyed functions both become SRec: per-key
            # field shapes with presence bits (partial functions get
            # optional fields); one shape class covers TLA's record/
            # function unification
            return SRec(tuple(
                (k, shape_of_value(x), False) for k, x in v
            ))
        elem = None
        for x in v:
            elem = join(elem, shape_of_value(x))
        return SSeq(elem, len(v))
    raise ShapeError(f"cannot shape value {v!r}")


# ---------------------------------------------------------------------------
# Universe enumeration
# ---------------------------------------------------------------------------

ENUM_LIMIT = 1 << 21


def universe(shape: Optional[Shape], limit: int = ENUM_LIMIT) -> List:
    """All canonical values of `shape`, deterministic order.  Raises
    ShapeError when the universe exceeds `limit` (caller then decomposes
    the shape structurally instead of enumerating it)."""
    if shape is None:
        return []
    if isinstance(shape, SBool):
        return [False, True]
    if isinstance(shape, SInt):
        n = shape.hi - shape.lo + 1
        if n > limit:
            raise ShapeError(f"int range too large: {shape}")
        return list(range(shape.lo, shape.hi + 1))
    if isinstance(shape, SAtoms):
        return sorted(shape.atoms)
    if isinstance(shape, SRec):
        per_field = []
        total = 1
        for f, s, opt in shape.fields:
            u = universe(s, limit)
            opts = ([None] if opt else []) + u
            total *= max(len(opts), 1)
            if total > limit:
                raise ShapeError(f"record universe too large at {f}")
            per_field.append((f, opts))
        out = []
        for combo in _product(*(opts for _, opts in per_field)):
            out.append(tuple(
                (f, v) for (f, _), v in zip(per_field, combo)
                if v is not None
            ))
        return out
    if isinstance(shape, SSet):
        eu = universe(shape.elem, 20)  # subsets only of tiny universes
        if len(eu) > 20:
            raise ShapeError("set universe too large to enumerate")
        out = []
        for bits in range(1 << len(eu)):
            out.append(frozenset(
                eu[i] for i in range(len(eu)) if bits >> i & 1
            ))
        return out
    if isinstance(shape, SSeq):
        eu = universe(shape.elem, limit)
        total = sum(len(eu) ** k for k in range(shape.cap + 1))
        if total > limit:
            raise ShapeError("sequence universe too large")
        out = [()]
        layer = [()]
        for _ in range(shape.cap):
            layer = [t + (e,) for t in layer for e in eu]
            out.extend(layer)
        return out
    if isinstance(shape, SFun):
        per_key = []
        total = 1
        for k in shape.keys:
            u = universe(shape.val, limit)
            opts = ([None] if shape.partial else []) + u
            total *= max(len(opts), 1)
            if total > limit:
                raise ShapeError("function universe too large")
            per_key.append((k, opts))
        out = []
        for combo in _product(*(opts for _, opts in per_key)):
            out.append(tuple(
                (k, v) for (k, _), v in zip(per_key, combo)
                if v is not None
            ))
        return out
    if isinstance(shape, SUnion):
        out = []
        for alt in shape.alts:
            out.extend(universe(alt, limit - len(out)))
        return out
    raise ShapeError(f"cannot enumerate {shape}")


def enumerable(shape: Optional[Shape], limit: int = ENUM_LIMIT) -> bool:
    try:
        universe(shape, limit)
        return True
    except ShapeError:
        return False


# ---------------------------------------------------------------------------
# Abstract interpretation of expressions
# ---------------------------------------------------------------------------


class ShapeInference:
    """Infers per-variable shapes from Init + all primed updates."""

    # abstract values for CONSTANT names, consulted before the concrete
    # ev.constants: the sweep-class audit (jaxtlc.analysis) widens a
    # swept constant to its whole lo..hi interval here, so one abstract
    # pass covers every configuration of the class
    const_hints: Dict[str, Shape] = {}

    def __init__(self, ev: Evaluator, variables: Tuple[str, ...],
                 init_ast, next_ast):
        self.ev = ev
        self.variables = variables
        self.init_ast = init_ast
        self.next_ast = next_ast
        self.var_shapes: Dict[str, Optional[Shape]] = {
            v: None for v in variables
        }

    # -- fixpoint ----------------------------------------------------------

    def run(self, max_iters: int = 30) -> Dict[str, Shape]:
        # seed from concrete initial states (uses the exact evaluator)
        from .actions import ActionSystem

        system = ActionSystem.__new__(ActionSystem)
        system.ev = self.ev
        system.variables = self.variables
        system.init_ast = self.init_ast
        system.next_ast = self.next_ast
        system._mentions_cache = {}
        for st in system.initial_states():
            for v, val in zip(self.variables, st):
                self.var_shapes[v] = join(
                    self.var_shapes[v], shape_of_value(val)
                )
        hints = getattr(self, "hints", {})
        for it in range(max_iters):
            before = dict(self.var_shapes)
            self._pass_next()
            if it >= 2:
                # widen growing int ranges up a threshold ladder so
                # counter-style specs (x' = x + 1 under a guard the
                # abstract pass cannot see) converge; the kernel traps
                # at runtime if a real value escapes the widened range
                for v in self.variables:
                    self.var_shapes[v] = _widen(before.get(v),
                                                self.var_shapes[v])
            for v, hint in hints.items():
                # TypeOK-declared bounds keep universes tight (one value
                # of slack, see typeok_hints); clamping LAST keeps the
                # widen/clamp pair convergent
                self.var_shapes[v] = _clamp(self.var_shapes[v], hint)
            if self.var_shapes == before:
                return {v: s for v, s in self.var_shapes.items()}
        raise ShapeError("shape inference did not converge")

    def _pass_next(self):
        env = {v: s for v, s in self.var_shapes.items()}
        self._walk_action(self.next_ast, dict(env))

    # -- action walk: collect var' = rhs joins -----------------------------

    def _walk_action(self, ast, env):
        op = ast[0]
        if op in ("and", "or"):
            for x in ast[1]:
                self._walk_action(x, env)
            return
        if op == "exists":
            _, names, dom_ast, body = ast
            dom_sh = self._abstract(dom_ast, env)
            elem = self._elem_shape(dom_sh)
            env2 = dict(env)
            for nm in names:
                env2[nm] = elem
            self._walk_action(body, env2)
            return
        if op == "if":
            self._walk_action(ast[2], env)
            self._walk_action(ast[3], env)
            return
        if op == "let":
            env2 = dict(env)
            for name, params, body in ast[1]:
                if params:
                    env2[name] = Definition(name, params, body)
                else:
                    env2[name] = self._abstract(body, env2)
            self._walk_action(ast[2], env2)
            return
        if op in ("call", "name"):
            dname = ast[1]
            d = env.get(dname)
            if not isinstance(d, Definition):
                d = self.ev.defs.get(dname)
            if isinstance(d, Definition) and _mentions_prime_static(
                d.body, self.ev.defs
            ):
                args = ast[2] if op == "call" else []
                env2 = dict(env)
                for p, a in zip(d.params, args):
                    env2[p] = self._abstract(a, env)
                self._walk_action(d.body, env2)
            return
        if op == "cmp" and ast[1] in ("=", r"\in") and ast[2][0] == "prime":
            name = ast[2][1]
            rhs = self._abstract(ast[3], env)
            if ast[1] == r"\in":
                rhs = self._elem_shape(rhs)
            self._record_write(name, rhs)
            return
        # guards / UNCHANGED contribute nothing

    def _record_write(self, name: str, sh: Optional[Shape]) -> None:
        """One primed assignment observed; the abstract-interpretation
        subclass (analysis.absint) collects writes separately to run
        descending (narrowing) iterations."""
        self.var_shapes[name] = join(self.var_shapes[name], sh)

    # -- abstract expression evaluation ------------------------------------

    def _elem_shape(self, sh: Optional[Shape]) -> Optional[Shape]:
        if isinstance(sh, SSet):
            return sh.elem
        if isinstance(sh, SUnion):
            out = None
            for a in sh.alts:
                if isinstance(a, SSet):
                    out = join(out, a.elem)
            return out
        return None

    def _abstract(self, ast, env) -> Optional[Shape]:
        op = ast[0]
        if op == "bool":
            return SBool()
        if op == "num":
            return SInt(ast[1], ast[1])
        if op == "str":
            return SAtoms(frozenset({ast[1]}))
        if op == "name":
            nm = ast[1]
            if nm in env and not isinstance(env[nm], Definition):
                return env[nm]
            if nm in self.const_hints:
                return self.const_hints[nm]
            if nm in self.ev.constants:
                return shape_of_value(self.ev.constants[nm])
            if nm in BUILTIN_SETS:
                v = BUILTIN_SETS[nm]
                if isinstance(v, frozenset):
                    return shape_of_value(v)
                raise ShapeError(f"cannot shape builtin set {nm}")
            d = self.ev.defs.get(nm)
            if d is not None and not d.params:
                return self._abstract(d.body, env)
            raise ShapeError(f"unknown name {nm!r} in shape inference")
        if op == "prime":
            return self.var_shapes[ast[1]]
        if op == "setlit":
            elem = None
            for x in ast[1]:
                elem = join(elem, self._abstract(x, env))
            return SSet(elem)
        if op == "tuple":
            elem = None
            for x in ast[1]:
                elem = join(elem, self._abstract(x, env))
            return SSeq(elem, len(ast[1]))
        if op == "record":
            return SRec(tuple(sorted(
                (f, self._abstract(x, env), False) for f, x in ast[1]
            )))
        if op == "apply":
            base = self._abstract(ast[1], env)
            arg_ast = ast[2]
            return self._apply_shape(base, arg_ast, env)
        if op == "domain":
            base = self._abstract(ast[1], env)
            keys = self._domain_atoms(base)
            if keys is not None:
                return SSet(SAtoms(frozenset(keys)))
            return SSet(SInt(1, SEQ_CAP_LIMIT))
        if op in ("not", "and", "or", "implies", "forall", "exists"):
            return SBool()
        if op == "cmp":
            return SBool()
        if op == "binop":
            return self._binop_shape(ast, env)
        if op == "if":
            return join(self._abstract(ast[2], env),
                        self._abstract(ast[3], env))
        if op == "case":
            out = None
            for _, e in ast[1]:
                out = join(out, self._abstract(e, env))
            if ast[2] is not None:
                out = join(out, self._abstract(ast[2], env))
            return out
        if op == "let":
            env2 = dict(env)
            for name, params, body in ast[1]:
                if params:
                    env2[name] = Definition(name, params, body)
                else:
                    env2[name] = self._abstract(body, env2)
            return self._abstract(ast[2], env2)
        if op == "choose":
            _, var, dom_ast, _ = ast
            return self._elem_shape(self._abstract(dom_ast, env))
        if op == "setfilter":
            _, var, dom_ast, _ = ast
            dom = self._abstract(dom_ast, env)
            if isinstance(dom, SSet):
                return dom
            return SSet(self._elem_shape(dom))
        if op == "setmap":
            _, expr, var, dom_ast = ast
            dom = self._abstract(dom_ast, env)
            env2 = dict(env)
            env2[var] = self._elem_shape(dom)
            return SSet(self._abstract(expr, env2))
        if op == "fnlit":
            _, var, dom_ast, body = ast
            dom = self._abstract(dom_ast, env)
            elem = self._elem_shape(dom)
            env2 = dict(env)
            env2[var] = elem
            val = self._abstract(body, env2)
            keys = self._atoms_of(elem)
            if keys is None:
                if elem is None:
                    return SRec(())
                raise ShapeError("fnlit over non-atom domain")
            return SRec(tuple(
                (k, val, False) for k in sorted(keys)
            ))
        if op == "funcset":
            dom = self._abstract(ast[1], env)
            rng = self._elem_shape(self._abstract(ast[2], env))
            keys = self._atoms_of(self._elem_shape(dom))
            if keys is None:
                raise ShapeError("function set over non-atom domain")
            return SSet(SRec(tuple(
                (k, rng, False) for k in sorted(keys)
            )))
        if op == "except":
            base = self._abstract(ast[1], env)
            for path_asts, val_ast in ast[2]:
                base = self._except_shape(base, path_asts, val_ast, env)
            return base
        if op == "atref":
            if "@" not in env:
                raise ShapeError("@ outside EXCEPT in shape inference")
            return env["@"]  # may be None (bottom) early in the fixpoint
        if op == "call":
            return self._call_shape(ast, env)
        if op == "unchanged":
            return SBool()
        raise ShapeError(f"cannot abstract {op!r}")

    def _atoms_of(self, sh) -> Optional[FrozenSet[str]]:
        if isinstance(sh, SAtoms):
            return sh.atoms
        if isinstance(sh, SUnion):
            out = frozenset()
            for a in sh.alts:
                if isinstance(a, SAtoms):
                    out |= a.atoms
                else:
                    return None
            return out
        return None

    def _domain_atoms(self, sh) -> Optional[FrozenSet[str]]:
        if isinstance(sh, SFun):
            return frozenset(sh.keys)
        if isinstance(sh, SRec):
            return frozenset(f for f, _, _ in sh.fields)
        if sh is None or sh == SSeq(None, 0):
            return frozenset()  # DOMAIN of the empty function is {}
        if isinstance(sh, SUnion):
            # alternatives with no DOMAIN (atoms flowing through guards)
            # are runtime-unreachable in DOMAIN position - skip them
            out = frozenset()
            any_dom = False
            for a in sh.alts:
                d = self._domain_atoms(a)
                if d is not None:
                    any_dom = True
                    out |= d
            return out if any_dom else None
        return None

    def _apply_shape(self, base, arg_ast, env) -> Optional[Shape]:
        shapes = base.alts if isinstance(base, SUnion) else (base,)
        out = None
        for sh in shapes:
            if isinstance(sh, SRec):
                if arg_ast[0] == "str":
                    f = sh.field(arg_ast[1])
                    if f is not None:
                        out = join(out, f[0])
                else:
                    for _, s, _ in sh.fields:
                        out = join(out, s)
            elif isinstance(sh, SFun):
                out = join(out, sh.val)
            elif isinstance(sh, SSeq):
                out = join(out, sh.elem)
        return out

    def _binop_shape(self, ast, env) -> Optional[Shape]:
        _, sym, la, ra = ast
        a = self._abstract(la, env)
        b = self._abstract(ra, env)
        if sym in (r"\cup", r"\cap", "\\"):
            ea = self._elem_shape(a)
            eb = self._elem_shape(b)
            if sym == r"\cup":
                return SSet(join(ea, eb))
            return SSet(ea)
        if sym in ("+", "-", "*"):
            if isinstance(a, SInt) and isinstance(b, SInt):
                if sym == "+":
                    return SInt(a.lo + b.lo, a.hi + b.hi)
                if sym == "-":
                    return SInt(a.lo - b.hi, a.hi - b.lo)
                corners = [a.lo * b.lo, a.lo * b.hi, a.hi * b.lo,
                           a.hi * b.hi]
                return SInt(min(corners), max(corners))
            return SInt(-(1 << 30), 1 << 30)
        if sym == "..":
            if isinstance(a, SInt) and isinstance(b, SInt):
                return SSet(SInt(a.lo, b.hi))
            raise ShapeError(".. over non-ints")
        if sym == r"\o":
            sa = a if isinstance(a, SSeq) else SSeq(None, 0)
            sb = b if isinstance(b, SSeq) else SSeq(None, 0)
            return SSeq(join(sa.elem, sb.elem),
                        min(sa.cap + sb.cap, SEQ_CAP_LIMIT))
        if sym == ":>":
            keys = self._atoms_of(a)
            if keys is None:
                raise ShapeError(":> with non-atom key")
            # single-key function; with several possible keys each is
            # optional (exactly one will be present at runtime)
            opt = len(keys) > 1
            return SRec(tuple(
                (k, b, opt) for k in sorted(keys)
            ))
        if sym == "@@":
            return self._merge_fun_shapes(a, b)
        raise ShapeError(f"cannot abstract binop {sym}")

    def _merge_fun_shapes(self, a, b) -> Shape:
        def as_fun(sh):
            """Function-like view of sh, or None.  Non-function
            alternatives (e.g. the defaultInitValue atom flowing through
            Write's argument) are guard-unreachable at runtime - TLC
            would error on them too - so they contribute nothing."""
            if isinstance(sh, SRec):
                return sh
            if isinstance(sh, SFun):
                return SRec(tuple(
                    (k, sh.val, sh.partial) for k in sh.keys
                ))
            if sh == SSeq(None, 0):
                return SRec(())
            if isinstance(sh, SUnion):
                out = None
                for alt in sh.alts:
                    f = as_fun(alt)
                    if f is not None:
                        out = join(out, f)
                return out
            return None

        fa, fb = as_fun(a), as_fun(b)
        if fa is None and fb is None:
            raise ShapeError(f"@@ over {a} and {b}")
        if fa is None:
            return fb
        if fb is None:
            return fa
        if isinstance(fa, SRec) or isinstance(fb, SRec):
            # record-style merge: union fields; a's fields win (present),
            # b-only fields keep b's optionality
            fields: Dict[str, Tuple[Shape, bool]] = {}
            if isinstance(fb, SRec):
                for f, s, o in fb.fields:
                    fields[f] = (s, o)
            else:
                for k in fb.keys:
                    fields[k] = (fb.val, fb.partial)
            if isinstance(fa, SRec):
                for f, s, o in fa.fields:
                    if f in fields:
                        fields[f] = (join(fields[f][0], s),
                                     fields[f][1] and o)
                    else:
                        fields[f] = (s, o)
            else:
                for k in fa.keys:
                    old = fields.get(k)
                    if old:
                        fields[k] = (join(old[0], fa.val),
                                     old[1] and fa.partial)
                    else:
                        fields[k] = (fa.val, fa.partial)
            return SRec(tuple(sorted(
                (f, s, o) for f, (s, o) in fields.items()
            )))
        keys = tuple(sorted(set(fa.keys) | set(fb.keys)))
        partial = fa.partial and fb.partial
        return SFun(keys, join(fa.val, fb.val), partial)

    def _except_shape(self, base, path_asts, val_ast, env):
        shapes = base.alts if isinstance(base, SUnion) else (base,)
        out = None
        for sh in shapes:
            out = join(out, self._except_one(sh, path_asts, val_ast, env))
        return out

    def _except_one(self, sh, path_asts, val_ast, env):
        idx_ast = path_asts[0]
        if sh is None or sh == SSeq(None, 0):
            # bottom / empty container: early fixpoint iterations see
            # EXCEPT before any assignment populated the base shape
            if idx_ast[0] == "str":
                sh = SRec(((idx_ast[1], None, True),))
            else:
                return None
        if isinstance(sh, SRec) and idx_ast[0] != "str":
            # dynamic index ![self]: the update may land on any key -
            # join the new value into every field (sound over-approx)
            fields = []
            for fn, s, o in sh.fields:
                if len(path_asts) > 1:
                    new = self._except_one(s, path_asts[1:], val_ast, env)
                else:
                    env2 = dict(env)
                    env2["@"] = s
                    new = self._abstract(val_ast, env2)
                fields.append((fn, join(s, new), o))
            return SRec(tuple(fields))
        if isinstance(sh, SRec) and idx_ast[0] == "str":
            f = sh.field(idx_ast[1])
            old = f[0] if f else None
            if len(path_asts) > 1:
                new = self._except_one(old, path_asts[1:], val_ast, env)
            else:
                env2 = dict(env)
                env2["@"] = old
                new = self._abstract(val_ast, env2)
            fields = []
            seen = False
            for fn, s, o in sh.fields:
                if fn == idx_ast[1]:
                    fields.append((fn, join(s, new), o))
                    seen = True
                else:
                    fields.append((fn, s, o))
            if not seen:
                fields.append((idx_ast[1], new, True))
            return SRec(tuple(sorted(fields)))
        if isinstance(sh, SFun):
            old = sh.val
            if len(path_asts) > 1:
                new = self._except_one(old, path_asts[1:], val_ast, env)
            else:
                env2 = dict(env)
                env2["@"] = old
                new = self._abstract(val_ast, env2)
            return SFun(sh.keys, join(sh.val, new), sh.partial)
        if isinstance(sh, SSeq):
            old = sh.elem
            if len(path_asts) > 1:
                new = self._except_one(old, path_asts[1:], val_ast, env)
            else:
                env2 = dict(env)
                env2["@"] = old
                new = self._abstract(val_ast, env2)
            return SSeq(join(sh.elem, new), sh.cap)
        raise ShapeError(f"EXCEPT on shape {sh}")

    def _call_shape(self, ast, env) -> Optional[Shape]:
        _, name, args = ast
        d = env.get(name)
        if not isinstance(d, Definition):
            d = self.ev.defs.get(name)
        if isinstance(d, Definition):
            env2 = dict(env)
            for p, a in zip(d.params, args):
                env2[p] = self._abstract(a, env)
            return self._abstract(d.body, env2)
        if name in ("Cardinality", "Len"):
            return SInt(0, 64)
        if name == "Head":
            sh = self._abstract(args[0], env)
            if isinstance(sh, SSeq):
                return sh.elem
            return None
        if name == "Tail":
            sh = self._abstract(args[0], env)
            if isinstance(sh, SSeq):
                return SSeq(sh.elem, max(sh.cap - 1, 0))
            return sh
        if name == "Append":
            sh = self._abstract(args[0], env)
            el = self._abstract(args[1], env)
            cap = sh.cap if isinstance(sh, SSeq) else 0
            elem = sh.elem if isinstance(sh, SSeq) else None
            return SSeq(join(elem, el), min(cap + 1, SEQ_CAP_LIMIT))
        if name == "Assert":
            return SBool()
        raise ShapeError(f"cannot abstract call {name}")


_INT_THRESHOLDS = (1, 3, 7, 15, 31, 63, 127, 255, 511, 1023, 4095,
                   16383, 65535)


def _widen(old: Optional[Shape], new: Optional[Shape]) -> Optional[Shape]:
    """Accelerate int-range growth to the next threshold (sticky at the
    top) so the fixpoint terminates; recurses through containers."""
    if new is None or old is None or old == new:
        return new
    if isinstance(new, SInt) and isinstance(old, SInt):
        hi = new.hi
        if hi > old.hi:
            hi = next((t for t in _INT_THRESHOLDS if t >= hi),
                      _INT_THRESHOLDS[-1])
        lo = new.lo
        if lo < old.lo:
            lo = -next((t for t in _INT_THRESHOLDS if t >= -lo),
                       _INT_THRESHOLDS[-1]) - 1
        return SInt(min(lo, hi), hi)
    if isinstance(new, SRec) and isinstance(old, SRec):
        return SRec(tuple(
            (f, _widen(old.field(f)[0] if old.field(f) else None, s), o)
            for f, s, o in new.fields
        ))
    if isinstance(new, SSet) and isinstance(old, SSet):
        return SSet(_widen(old.elem, new.elem))
    if isinstance(new, SSeq) and isinstance(old, SSeq):
        return SSeq(_widen(old.elem, new.elem), new.cap)
    if isinstance(new, SUnion) and isinstance(old, SUnion):
        olds = {type(a): a for a in old.alts}
        return SUnion(tuple(
            _widen(olds.get(type(a)), a) for a in new.alts
        ))
    return new


def _mentions_prime_static(ast, defs, _seen=None) -> bool:
    if _seen is None:
        _seen = set()
    stack = [ast]
    while stack:
        node = stack.pop()
        if isinstance(node, tuple):
            if node and node[0] in ("prime", "unchanged"):
                return True
            if node and node[0] in ("call", "name"):
                d = defs.get(node[1])
                if d is not None and node[1] not in _seen:
                    _seen.add(node[1])
                    stack.append(d.body)
            stack.extend(x for x in node if isinstance(x, (tuple, list)))
        elif isinstance(node, list):
            stack.extend(x for x in node if isinstance(x, (tuple, list)))
    return False


def typeok_hints(ev: Evaluator, invariants: Dict[str, tuple],
                 variables) -> Dict[str, Shape]:
    """Extract per-variable bounds from TypeOK-style conjuncts: the same
    place TLC users document type bounds (`x \\in 0..N`,
    `f \\in [S -> D]`).  Ints get one value of slack beyond the declared
    bound so an off-by-one violation still encodes faithfully and is
    reported as the invariant violation it is (values beyond the slack
    hit the runtime range trap instead)."""
    hints: Dict[str, Shape] = {}

    def dom_shape(ast) -> Optional[Shape]:
        """ELEMENT shape of a constant set expression, with int slack."""
        try:
            v = ev.eval(ast, {})
        except Exception:
            return None
        if not isinstance(v, frozenset):
            return None
        sh = None
        for x in v:
            sh = join(sh, shape_of_value(x))
        return _slack(sh)

    def visit(ast):
        if not isinstance(ast, tuple):
            return
        if ast[0] == "and":
            for x in ast[1]:
                visit(x)
            return
        if ast[0] == "cmp" and ast[1] == r"\in" and ast[2][0] == "name" \
                and ast[2][1] in variables:
            var = ast[2][1]
            rhs = ast[3]
            if rhs[0] == "funcset":
                keys_sh = dom_shape(rhs[1])
                val_sh = dom_shape(rhs[2])
                if val_sh is not None and isinstance(keys_sh, SAtoms):
                    hints[var] = SRec(tuple(
                        (k, val_sh, False)
                        for k in sorted(keys_sh.atoms)
                    ))
            else:
                sh = dom_shape(rhs)
                if sh is not None:
                    hints[var] = sh

    for ast in invariants.values():
        visit(ast)
    return hints


def _slack(sh: Optional[Shape]) -> Optional[Shape]:
    if isinstance(sh, SInt):
        return SInt(sh.lo - 1, sh.hi + 1)
    return sh


def _clamp(sh: Optional[Shape], hint: Optional[Shape]) -> Optional[Shape]:
    """Meet `sh` with a TypeOK hint (ints narrowed; containers
    recursed); anything the hint does not constrain stays as inferred."""
    if sh is None or hint is None:
        return sh
    if isinstance(sh, SInt) and isinstance(hint, SInt):
        lo = max(sh.lo, hint.lo)
        hi = min(sh.hi, hint.hi)
        return SInt(lo, max(lo, hi))
    if isinstance(sh, SRec) and isinstance(hint, SRec):
        return SRec(tuple(
            (f, _clamp(s, hint.field(f)[0] if hint.field(f) else None),
             o)
            for f, s, o in sh.fields
        ))
    if isinstance(sh, SSet) and isinstance(hint, SSet):
        return SSet(_clamp(sh.elem, hint.elem))
    if isinstance(sh, SSeq):
        elem_hint = hint.elem if isinstance(hint, SSeq) else (
            hint if isinstance(hint, SInt) else None)
        return SSeq(_clamp(sh.elem, elem_hint), sh.cap)
    if isinstance(sh, SUnion):
        return SUnion(tuple(_clamp(a, hint) if isinstance(a, type(hint))
                            else a for a in sh.alts))
    return sh


def shape_leq(a: Optional[Shape], b: Optional[Shape]) -> bool:
    """Abstract-domain containment: every concrete value of `a` is a
    value of `b`.  Conservative (False on anything unproven) - this is
    the check that CERTIFIES a narrowed bound environment as a
    post-fixpoint (analysis.absint), so an unprovable containment must
    fail closed."""
    if a is None:
        return True  # bottom
    if b is None:
        return False
    if a == b:
        return True
    # the empty container coerces across container classes (see join)
    if a == SSeq(None, 0) and isinstance(b, (SFun, SRec, SSeq)):
        return True
    if isinstance(b, SUnion):
        alts = a.alts if isinstance(a, SUnion) else (a,)
        return all(any(shape_leq(x, alt) for alt in b.alts)
                   for x in alts)
    if isinstance(a, SUnion):
        return all(shape_leq(x, b) for x in a.alts)
    if type(a) is not type(b):
        return False
    if isinstance(a, SBool):
        return True
    if isinstance(a, SInt):
        return b.lo <= a.lo and a.hi <= b.hi
    if isinstance(a, SAtoms):
        return a.atoms <= b.atoms
    if isinstance(a, SRec):
        bf = {f: (s, o) for f, s, o in b.fields}
        for f, s, o in a.fields:
            if f not in bf:
                return False
            bs, bo = bf[f]
            if o and not bo:
                return False  # a may omit the field; b cannot
            if not shape_leq(s, bs):
                return False
        # fields of b absent from a must be omittable in b
        anames = {f for f, _, _ in a.fields}
        return all(o for f, _, o in b.fields if f not in anames)
    if isinstance(a, SSet):
        return shape_leq(a.elem, b.elem)
    if isinstance(a, SSeq):
        return a.cap <= b.cap and shape_leq(a.elem, b.elem)
    if isinstance(a, SFun):
        if not set(a.keys) <= set(b.keys):
            return False
        if not b.partial and (a.partial or set(a.keys) != set(b.keys)):
            return False
        return shape_leq(a.val, b.val)
    return False


def infer_shapes(ev: Evaluator, variables, init_ast, next_ast,
                 hints: Optional[Dict[str, Shape]] = None,
                 const_hints: Optional[Dict[str, Shape]] = None
                 ) -> Dict[str, Shape]:
    inf = ShapeInference(ev, variables, init_ast, next_ast)
    inf.hints = hints or {}
    if const_hints:
        inf.const_hints = dict(const_hints)
    return inf.run()
