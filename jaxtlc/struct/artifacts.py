"""Content-addressed verdict + reachable-set artifact cache (ISSUE 13).

The "model-checking CI on every commit" workload re-runs checks on
specs that usually have not changed; the serve plane's warm pool
(ISSUE 9) amortizes the COMPILE but still pays the full BFS per job.
This module amortizes the CHECK itself with two on-disk tiers under
``~/.cache/jaxtlc/artifacts`` (``JAXTLC_ARTIFACT_CACHE=DIR`` overrides,
``=off`` disables; CLI ``-artifact-cache`` / ``-no-artifact-cache`` /
``-recheck``):

* **Verdict tier** - keyed on the SEMANTIC digest of a check: module
  source digest, canonical constants, invariant selection, property
  selection, the deadlock flag, and :data:`ENGINE_SEMVER`.  The key
  deliberately EXCLUDES engine geometry (chunk / queue / fp capacity),
  pipeline, sort_free, obs and narrowing: verdict and counters are
  pinned geometry-invariant by the existing parity tests, so one
  artifact answers every geometry.  An unchanged spec returns its
  cached ``CheckOutcome`` without building (let alone compiling) an
  engine - O(HTTP) on the serve path.

* **Reachable-set tier** - keyed on the BEHAVIOR digest (Init + Next +
  the definitions they transitively reference + constants + deadlock
  flag) so an invariant-only edit KEEPS the key while the verdict key
  changes.  The artifact stores the packed reachable states plus the
  run's counters; a re-check then skips BFS entirely and evaluates
  just the request's invariants in one vmapped pass through the
  existing SpecBackend invariant hooks.

Where the reachable states come from: the engines never materialize
them - but the 64-bit Rabin fingerprint is GF(2)-affine in the packed
state bits (engine.fingerprint.affine_basis) and, for codecs of
``nbits <= 64``, provably INJECTIVE (an irreducible degree-64
polynomial cannot divide a nonzero message of lower degree), so the
final fingerprint table IS the reachable set: unmix the stored table
words (engine.fpset.unmix_host, the regrow migration's own tool),
solve the affine system once by GF(2) elimination, and recover every
packed state exactly.  A round-trip re-fingerprint verifies the
recovery before anything is written; wider codecs simply skip the
reach tier (the verdict tier still applies).

Durability follows the PR 2 checkpoint idioms: every artifact carries
a CRC32 of its payload and is published with fsync-before-rename, so a
torn write is either invisible or detected at load - corrupted or
version-skewed artifacts are loud-warning MISSES, never wrong answers.
Artifacts are written only on clean final verdicts: error, violation,
exhausted, interrupted and certificate-tripped runs never cache.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from .cache import _LRUMemo

# Bump whenever engine semantics change in a way that can alter a
# verdict or the reachable set (violation codes, fingerprint algebra,
# invariant evaluation order...).  Part of every key: a bump invalidates
# the whole cache at once instead of serving stale answers.
ENGINE_SEMVER = 1

FORMAT_VERSION = 1

_DEFAULT_ROOT = os.path.join(
    os.path.expanduser("~"), ".cache", "jaxtlc", "artifacts"
)

VERDICT_DIR = "verdict"
REACH_DIR = "reach"

# invariant-recheck pass: states per vmapped block (padded; one compile
# serves any stored set size)
RECHECK_BLOCK = 4096


def _fsync_replace(tmp: str, path: str, f=None) -> None:
    """The PR 2 durable-publish idiom (engine.checkpoint.fsync_replace),
    re-stated here so the store stays importable without jax: fsync the
    tmp file BEFORE the rename (rename alone only orders metadata),
    rename, then fsync the directory so the rename itself is durable."""
    if f is not None:
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    dirfd = os.open(os.path.dirname(os.path.abspath(path)) or ".",
                    os.O_RDONLY)
    try:
        os.fsync(dirfd)
    finally:
        os.close(dirfd)


# ---------------------------------------------------------------------------
# Keys
# ---------------------------------------------------------------------------


def _canonical_constants(model) -> dict:
    """struct.backend.canonical_constants without the jax import chain
    (the key functions must work in jax-free contexts: cachectl, the
    obs.serve /cache endpoint)."""
    out = {}
    for k in sorted(model.constants):
        v = model.constants[k]
        out[k] = (sorted(map(repr, v)) if isinstance(v, frozenset)
                  else repr(v))
    return out


def verdict_key(model, check_deadlock: bool = True,
                properties: Tuple[str, ...] = ()) -> str:
    """The semantic digest of one check: spec text digest (constant
    overrides included - the loader folds them in), canonical
    constants, invariant + property selection, deadlock flag, engine
    semver.  Geometry/pipeline/sort-free/obs/narrowing are deliberately
    absent: verdict and counters are geometry-invariant (pinned by the
    engine parity tests), so one artifact answers every geometry."""
    blob = json.dumps([
        ENGINE_SEMVER,
        model.source_digest,
        _canonical_constants(model),
        sorted(model.invariants),
        bool(check_deadlock),
        sorted(properties or ()),
    ], sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()


def behavior_digest(model) -> str:
    """Digest of what shapes the REACHABLE SET: variables, constants,
    Init and Next ASTs, and every definition transitively referenced
    from them (by name, over-approximated: any AST string that names a
    module definition counts - over-inclusion can only make the key
    more conservative, never wrong).  Invariant/property definitions
    that the behavior does not reference drop out, which is exactly
    what lets an invariant-only edit keep its reachable-set artifact."""
    defs = model.module.defs
    seen: set = set()
    queue: List[str] = []

    def scan(ast):
        if isinstance(ast, (tuple, list)):
            for x in ast:
                scan(x)
        elif isinstance(ast, str) and ast in defs and ast not in seen:
            seen.add(ast)
            queue.append(ast)

    sys_ = model.system
    scan(sys_.init_ast)
    scan(sys_.next_ast)
    while queue:
        d = defs[queue.pop()]
        scan(d.body)
    parts = [
        repr(tuple(sys_.variables)),
        json.dumps(_canonical_constants(model), sort_keys=True),
        repr(sys_.init_ast),
        repr(sys_.next_ast),
    ]
    for n in sorted(seen):
        d = defs[n]
        parts.append(f"{n}{tuple(d.params)!r}={d.body!r}")
    h = hashlib.sha256()
    for p in parts:
        h.update(p.encode())
        h.update(b"\x00")
    return h.hexdigest()


def reach_key(model, check_deadlock: bool = True) -> str:
    """The verdict key MINUS the invariant/property selection: keyed on
    the behavior digest so an invariant-only edit still hits."""
    blob = json.dumps([
        ENGINE_SEMVER,
        behavior_digest(model),
        bool(check_deadlock),
    ], sort_keys=True)
    return hashlib.sha256(blob.encode()).hexdigest()


def codec_digest(cdc, bounds=None) -> str:
    """Layout digest of a StructCodec (+ the narrowing bound digest):
    the reach artifact records the layout its packed words were encoded
    under, and a recheck whose model infers a DIFFERENT layout (e.g. a
    TypeOK hint edit reshaped a field) is a miss, never a misdecode."""
    blob = json.dumps([
        list(cdc.variables),
        list(int(w) for w in cdc.widths),
        int(cdc.nbits),
        bounds.digest() if bounds is not None else "",
    ])
    return hashlib.sha256(blob.encode()).hexdigest()


# ---------------------------------------------------------------------------
# Fingerprint inversion (table words -> packed states)
# ---------------------------------------------------------------------------

_SOLVE_MEMO: Dict[tuple, Optional[tuple]] = {}


def _solve_basis(nbits: int, fp_index: int, seed: int):
    """Left-inverse of the affine fingerprint map for nbits <= 64.

    fp = const ^ XOR_{i: bit i set} basis[i]; the Rabin algebra makes
    the map injective below the polynomial degree, so GF(2) Gauss-
    Jordan elimination of the 64 x nbits system yields, per message
    bit i, a 64-bit mask M[i] with  bit_i = parity(M[i] & (fp ^ const)).
    Returns (const64, masks [nbits] uint64) - or None if elimination
    finds a rank deficiency (cannot happen for a correct basis; kept
    as a defensive skip, not an assert)."""
    key = (nbits, fp_index, seed)
    if key in _SOLVE_MEMO:
        return _SOLVE_MEMO[key]
    if nbits > 64:
        _SOLVE_MEMO[key] = None
        return None
    from ..engine.fingerprint import affine_basis

    const, basis = affine_basis(nbits, fp_index, seed)
    const64 = int(const[0]) | (int(const[1]) << 32)
    b64 = [int(basis[i, 0]) | (int(basis[i, 1]) << 32)
           for i in range(nbits)]
    # rows: 64 equations over the nbits unknowns; (a, m) = unknown
    # mask, fp-bit combination mask
    rows = [(0, 1 << j) for j in range(64)]
    for j in range(64):
        a = 0
        for i in range(nbits):
            if (b64[i] >> j) & 1:
                a |= 1 << i
        rows[j] = (a, 1 << j)
    pivot = [-1] * nbits
    used = [False] * 64
    for i in range(nbits):
        p = next((j for j in range(64)
                  if not used[j] and (rows[j][0] >> i) & 1), None)
        if p is None:
            _SOLVE_MEMO[key] = None
            return None
        used[p] = True
        pivot[i] = p
        pa, pm = rows[p]
        for j in range(64):
            if j != p and (rows[j][0] >> i) & 1:
                rows[j] = (rows[j][0] ^ pa, rows[j][1] ^ pm)
    masks = np.array([rows[pivot[i]][1] for i in range(nbits)],
                     dtype=np.uint64)
    out = (np.uint64(const64), masks, np.array(b64, dtype=np.uint64))
    _SOLVE_MEMO[key] = out
    return out


def invert_fps(lo: np.ndarray, hi: np.ndarray, nbits: int,
               fp_index: int, seed: int) -> Optional[np.ndarray]:
    """Recover packed state words [N, W] uint32 from RAW (unmixed)
    fingerprints.  Returns None when the codec is too wide (> 64 bits)
    or any recovered state fails the round-trip re-fingerprint (the
    2^-64 empty-marker remap class, or a corrupt table) - the caller
    must then skip the reach tier rather than store a wrong state."""
    solved = _solve_basis(nbits, fp_index, seed)
    if solved is None:
        return None
    const64, masks, b64 = solved
    y = ((lo.astype(np.uint64) | (hi.astype(np.uint64) << np.uint64(32)))
         ^ const64)
    # bit i of each message = parity of the masked fp bits
    bits = (np.bitwise_count(masks[None, :] & y[:, None])
            & np.uint64(1)).astype(np.uint32)  # [N, nbits]
    # round-trip: the affine map applied to the recovered bits must
    # reproduce the fingerprint exactly (catches out-of-image inputs)
    y2 = np.bitwise_xor.reduce(
        bits.astype(np.uint64) * b64[None, :], axis=1
    )
    if not np.array_equal(y2, y):
        return None
    W = (nbits + 31) // 32
    words = np.zeros((bits.shape[0], W), dtype=np.uint32)
    for i in range(nbits):
        words[:, i // 32] |= bits[:, i] << np.uint32(i % 32)
    return words


def states_from_table(table: np.ndarray, nbits: int, fp_index: int,
                      seed: int) -> Optional[np.ndarray]:
    """Packed reachable states from a final fpset TABLE ([nb, 2*B]
    interleaved uint32 bucket rows): occupied slots -> unmix -> affine
    inversion, rows sorted for a canonical (CRC-stable) artifact."""
    from ..engine.fpset import unmix_host

    t = np.asarray(table, np.uint32)
    lo = t[:, 0::2].reshape(-1)
    hi = t[:, 1::2].reshape(-1)
    occ = (lo != 0) | (hi != 0)
    rlo, rhi = unmix_host(lo[occ], hi[occ])
    words = invert_fps(rlo, rhi, nbits, fp_index, seed)
    if words is None:
        return None
    order = np.lexsort(tuple(words[:, w] for w in range(words.shape[1])))
    return np.ascontiguousarray(words[order])


# ---------------------------------------------------------------------------
# The store
# ---------------------------------------------------------------------------


class ArtifactStore:
    """Crash-consistent content-addressed artifact directory.

    Layout: ``<root>/verdict/<key>.json`` and ``<root>/reach/<key>.npz``
    - key is the full hex digest, file content carries format version,
    engine semver, a CRC32 of the payload, and the key echoed back
    (a renamed/misplaced file can never answer for another key).
    Reads that fail any of those checks are counted ``corrupt`` and
    reported through the caller's warn hook; version skew is a plain
    miss."""

    def __init__(self, root: str):
        self.root = root
        self._lock = threading.Lock()
        self.verdict_hits = 0
        self.verdict_misses = 0
        self.reach_hits = 0
        self.reach_misses = 0
        self.writes = 0
        self.corrupt = 0
        self.bypasses = 0

    # -- paths -------------------------------------------------------------

    def _path(self, tier: str, key: str) -> str:
        suffix = ".json" if tier == VERDICT_DIR else ".npz"
        return os.path.join(self.root, tier, key + suffix)

    def _count(self, field: str, n: int = 1) -> None:
        with self._lock:
            setattr(self, field, getattr(self, field) + n)

    @staticmethod
    def _unlink(path: str) -> None:
        try:
            os.remove(path)
        except OSError:
            pass  # best-effort: a stuck file stays a loud miss

    # -- verdict tier ------------------------------------------------------

    def put_verdict(self, key: str, payload: dict) -> str:
        path = self._path(VERDICT_DIR, key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        body = json.dumps(payload, sort_keys=True)
        doc = {
            "format": FORMAT_VERSION,
            "engine_semver": ENGINE_SEMVER,
            "key": key,
            "crc": zlib.crc32(body.encode()),
            "payload": payload,
        }
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(json.dumps(doc, sort_keys=True))
            _fsync_replace(tmp, path, f=f)
        self._count("writes")
        return path

    def lookup_verdict(self, key: str, warn=None) -> Optional[dict]:
        path = self._path(VERDICT_DIR, key)
        if not os.path.exists(path):
            self._count("verdict_misses")
            return None
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
            payload = doc["payload"]
            if doc.get("key") != key:
                raise ValueError("key echo mismatch")
            crc = zlib.crc32(
                json.dumps(payload, sort_keys=True).encode()
            )
            if crc != doc.get("crc"):
                raise ValueError(f"CRC mismatch ({crc} != {doc.get('crc')})")
        except (OSError, ValueError, KeyError, json.JSONDecodeError) as e:
            self._count("corrupt")
            self._count("verdict_misses")
            if warn is not None:
                warn(f"artifact cache: corrupt verdict artifact "
                     f"{path} ({e}) - treated as a miss")
            self._unlink(path)  # self-heal: the next clean run rewrites
            return None
        if (doc.get("format") != FORMAT_VERSION
                or doc.get("engine_semver") != ENGINE_SEMVER):
            self._count("verdict_misses")  # version skew: a plain miss
            return None
        self._count("verdict_hits")
        return payload

    # -- reach tier --------------------------------------------------------

    def put_reach(self, key: str, states: np.ndarray,
                  meta: dict) -> str:
        path = self._path(REACH_DIR, key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        states = np.ascontiguousarray(np.asarray(states, np.uint32))
        meta = {
            **meta,
            "format": FORMAT_VERSION,
            "engine_semver": ENGINE_SEMVER,
            "key": key,
            "n_states": int(states.shape[0]),
            "states_crc": zlib.crc32(states.tobytes()),
        }
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            np.savez_compressed(f, __meta__=json.dumps(meta),
                                states=states)
            _fsync_replace(tmp, path, f=f)
        self._count("writes")
        return path

    def has_reach(self, key: str) -> bool:
        return os.path.exists(self._path(REACH_DIR, key))

    def lookup_reach(self, key: str, warn=None
                     ) -> Optional[Tuple[np.ndarray, dict]]:
        path = self._path(REACH_DIR, key)
        if not os.path.exists(path):
            self._count("reach_misses")
            return None
        try:
            with np.load(path, allow_pickle=False) as z:
                meta = json.loads(str(z["__meta__"]))
                states = np.asarray(z["states"], np.uint32)
            if meta.get("key") != key:
                raise ValueError("key echo mismatch")
            crc = zlib.crc32(np.ascontiguousarray(states).tobytes())
            if crc != meta.get("states_crc"):
                raise ValueError(
                    f"states CRC mismatch ({crc} != "
                    f"{meta.get('states_crc')})"
                )
            if meta.get("n_states") != states.shape[0]:
                raise ValueError("state count mismatch")
        except (Exception) as e:  # zipfile/zlib/json/KeyError/Value...
            self._count("corrupt")
            self._count("reach_misses")
            if warn is not None:
                warn(f"artifact cache: corrupt reachable-set artifact "
                     f"{path} ({e}) - treated as a miss")
            self._unlink(path)  # self-heal: the next clean run rewrites
            return None
        if (meta.get("format") != FORMAT_VERSION
                or meta.get("engine_semver") != ENGINE_SEMVER):
            self._count("reach_misses")
            return None
        self._count("reach_hits")
        return states, meta

    # -- maintenance (tools/cachectl.py) -----------------------------------

    def _files(self) -> List[Tuple[str, str, str]]:
        out = []
        for tier, suffix in ((VERDICT_DIR, ".json"), (REACH_DIR, ".npz")):
            d = os.path.join(self.root, tier)
            if not os.path.isdir(d):
                continue
            for name in sorted(os.listdir(d)):
                if name.endswith(suffix) and not name.endswith(".tmp"):
                    out.append((tier, name[: -len(suffix)],
                                os.path.join(d, name)))
        return out

    def ls(self) -> List[dict]:
        """One row per artifact (newest first): tier, key, size, age,
        and the workload name when the file is readable."""
        rows = []
        for tier, key, path in self._files():
            try:
                st = os.stat(path)
            except OSError:
                continue
            row = dict(tier=tier, key=key, bytes=st.st_size,
                       mtime=st.st_mtime, workload=None)
            try:
                if tier == VERDICT_DIR:
                    with open(path, encoding="utf-8") as f:
                        row["workload"] = json.load(f)["payload"].get(
                            "workload")
                else:
                    with np.load(path, allow_pickle=False) as z:
                        row["workload"] = json.loads(
                            str(z["__meta__"])).get("workload")
            except Exception:
                row["workload"] = "<unreadable>"
            rows.append(row)
        rows.sort(key=lambda r: r["mtime"], reverse=True)
        return rows

    def verify(self) -> List[dict]:
        """Full integrity pass: re-run every artifact through its
        loading checks (CRC, key echo, version).  Returns one row per
        artifact with ok/reason - corrupt files are reported, never
        deleted (that is gc's job, on the operator's say-so)."""
        rows = []
        for tier, key, path in self._files():
            reason = ""
            if tier == VERDICT_DIR:
                ok = self._verify_verdict(key, path)
            else:
                ok = self._verify_reach(key, path)
            if not ok:
                reason = "CRC/format/key verification failed"
            rows.append(dict(tier=tier, key=key, path=path, ok=ok,
                             reason=reason))
        return rows

    def _verify_verdict(self, key: str, path: str) -> bool:
        try:
            with open(path, encoding="utf-8") as f:
                doc = json.load(f)
            body = json.dumps(doc["payload"], sort_keys=True)
            return (doc.get("key") == key
                    and doc.get("format") == FORMAT_VERSION
                    and zlib.crc32(body.encode()) == doc.get("crc"))
        except Exception:
            return False

    def _verify_reach(self, key: str, path: str) -> bool:
        try:
            with np.load(path, allow_pickle=False) as z:
                meta = json.loads(str(z["__meta__"]))
                states = np.ascontiguousarray(
                    np.asarray(z["states"], np.uint32))
            return (meta.get("key") == key
                    and meta.get("format") == FORMAT_VERSION
                    and zlib.crc32(states.tobytes())
                    == meta.get("states_crc"))
        except Exception:
            return False

    def gc(self, max_bytes: int) -> dict:
        """Prune least-recently-written artifacts until the store fits
        `max_bytes`.  Returns {kept, deleted, bytes}."""
        rows = []
        for tier, key, path in self._files():
            try:
                st = os.stat(path)
            except OSError:
                continue
            rows.append((st.st_mtime, st.st_size, path))
        rows.sort(reverse=True)  # newest first: keep from the top
        total, kept, deleted = 0, 0, 0
        for mtime, size, path in rows:
            if total + size <= max_bytes:
                total += size
                kept += 1
            else:
                try:
                    os.remove(path)
                    deleted += 1
                except OSError:
                    kept += 1
        return dict(kept=kept, deleted=deleted, bytes=total)

    def total_bytes(self) -> int:
        return sum(r["bytes"] for r in self.ls())

    def stats(self) -> dict:
        with self._lock:
            return dict(
                root=self.root,
                verdict_hits=self.verdict_hits,
                verdict_misses=self.verdict_misses,
                reach_hits=self.reach_hits,
                reach_misses=self.reach_misses,
                writes=self.writes,
                corrupt=self.corrupt,
                bypasses=self.bypasses,
            )


# ---------------------------------------------------------------------------
# Process-global store resolution
# ---------------------------------------------------------------------------

_STORE: Optional[ArtifactStore] = None
_STORE_ROOT: Optional[str] = None  # what _STORE was resolved against
_PINNED = False  # configure() overrides env resolution until restore()


def get_store() -> Optional[ArtifactStore]:
    """The process store per ``JAXTLC_ARTIFACT_CACHE`` (default
    ``~/.cache/jaxtlc/artifacts``; ``off``/``0``/``none`` disables ->
    None).  Singleton per resolved root, so counters accumulate across
    a serving process; configure() pins an explicit root over the env
    (tests, tools)."""
    global _STORE, _STORE_ROOT
    if _PINNED:
        return _STORE
    env = os.environ.get("JAXTLC_ARTIFACT_CACHE", "")
    if env.lower() in ("off", "0", "none"):
        return None
    root = env or _DEFAULT_ROOT
    if _STORE is None or _STORE_ROOT != root:
        _STORE = ArtifactStore(root)
        _STORE_ROOT = root
    return _STORE


def configure(root: Optional[str]):
    """Pin the process store to `root` regardless of the env (tests,
    tools/loadgen --cache).  ``None``/"off" pins it disabled.  Returns
    an opaque token for restore()."""
    global _STORE, _STORE_ROOT, _PINNED
    token = (_STORE, _STORE_ROOT, _PINNED)
    if root is None or str(root).lower() in ("off", "0", "none", ""):
        _STORE, _STORE_ROOT = None, "off"
    else:
        _STORE = ArtifactStore(str(root))
        _STORE_ROOT = str(root)
    _PINNED = True
    return token


def restore(token) -> None:
    """Undo a configure() (tests/tools cleanup)."""
    global _STORE, _STORE_ROOT, _PINNED
    _STORE, _STORE_ROOT, _PINNED = token


def store_for(args) -> Optional[ArtifactStore]:
    """Resolve the store a CheckRequest wants: ``-no-artifact-cache``
    wins, ``-artifact-cache DIR`` overrides the env/default root (a
    fresh store instance - explicit dirs do not hijack the process
    singleton), else the process store (None when the env disables
    it)."""
    if getattr(args, "noartifactcache", False):
        return None
    explicit = getattr(args, "artifactcache", "") or ""
    if explicit:
        return ArtifactStore(explicit)
    return get_store()


# ---------------------------------------------------------------------------
# Payload <-> CheckResult
# ---------------------------------------------------------------------------


def verdict_payload(model, result, n_init: int, properties=(),
                    action_order=None) -> dict:
    """The cached-verdict payload: everything the transcript/journal
    replay needs, no geometry-dependent fields (occupancy is recomputed
    against the requesting run's fp_capacity)."""
    return dict(
        workload=model.root_name,
        verdict="ok",
        generated=int(result.generated),
        distinct=int(result.distinct),
        depth=int(result.depth),
        queue=int(result.queue_left),
        n_init=int(n_init),
        action_generated={k: int(v) for k, v in
                          result.action_generated.items()},
        action_distinct={k: int(v) for k, v in
                         result.action_distinct.items()},
        action_order=list(action_order or ()),
        # plain floats: outdegree tuples carry numpy scalars json
        # cannot serialize (values are preserved exactly)
        outdegree=([float(v) for v in result.outdegree]
                   if result.outdegree is not None else None),
        properties=sorted(properties or ()),
        wall_s=round(float(result.wall_s), 6),
        created_t=round(time.time(), 3),
    )


def result_from_payload(payload: dict, fp_capacity: int = 0,
                        wall_s: float = 0.0):
    """A CheckResult materialized from a verdict payload (the O(HTTP)
    answer).  wall_s is the LOOKUP wall, not the original run's - the
    transcript reports what this invocation actually took."""
    from ..engine.bfs import CheckResult

    distinct = int(payload["distinct"])
    return CheckResult(
        generated=int(payload["generated"]),
        distinct=distinct,
        depth=int(payload["depth"]),
        queue_left=int(payload["queue"]),
        violation=0,
        violation_name="none",
        violation_state=np.zeros(0, np.int32),
        violation_action=-1,
        action_generated=dict(payload["action_generated"]),
        action_distinct=dict(payload["action_distinct"]),
        wall_s=wall_s,
        iterations=-1,
        outdegree=(tuple(payload["outdegree"])
                   if payload.get("outdegree") else None),
        fp_occupancy=(distinct / fp_capacity if fp_capacity else None),
    )


# ---------------------------------------------------------------------------
# The invariant-delta recheck
# ---------------------------------------------------------------------------

# compiled (unpack -> vmapped inv_check) passes, keyed like the backend
# memo so repeat rechecks of one spec meaning never recompile
_RECHECK_MEMO = _LRUMemo(8)


def _recheck_fn(backend, memo_key):
    hit = _RECHECK_MEMO.get(memo_key)
    if hit is not None:
        return hit
    import jax

    @jax.jit
    def f(words):  # [B, W] uint32 -> [B] int32 invariant-holds bits
        return jax.vmap(backend.inv_check)(backend.cdc.unpack(words))

    _RECHECK_MEMO.put(memo_key, f)
    return f


def run_recheck(model, backend, states: np.ndarray, memo_key):
    """Evaluate the model's CURRENT invariants over a stored reachable
    set in RECHECK_BLOCK-wide vmapped passes through the backend's
    invariant hook - the BFS-free half of an invariant-only edit.

    Returns (violation_code, violation_fields | None): 0 = every state
    (initial states included - they are in the set) satisfies every
    invariant; otherwise the first violating state in artifact order
    with the LOWEST violated invariant's code (the trace renderer
    re-finds the minimal counterexample on the host interpreter,
    exactly as a full run does)."""
    n_inv = len(backend.inv_codes)
    if n_inv == 0 or states.shape[0] == 0:
        return 0, None
    full = (1 << n_inv) - 1
    f = _recheck_fn(backend, memo_key)
    n = states.shape[0]
    for start in range(0, n, RECHECK_BLOCK):
        block = states[start:start + RECHECK_BLOCK]
        if block.shape[0] < RECHECK_BLOCK:
            # pad with replicas of the block's first row: a real state,
            # so padding can never fabricate a violation the block
            # does not contain
            pad = np.repeat(block[:1],
                            RECHECK_BLOCK - block.shape[0], axis=0)
            block = np.concatenate([block, pad], axis=0)
        bits = np.asarray(f(block))
        bad = (bits & full) != full
        if bad.any():
            i = int(np.argmax(bad))
            k = 0
            while (int(bits[i]) >> k) & 1:
                k += 1
            import jax.numpy as jnp

            fields = np.asarray(
                backend.cdc.unpack(jnp.asarray(states[start + i][None]))
            )[0]
            return int(backend.inv_codes[k]), fields
    return 0, None


def recheck_result(meta: dict, viol_code: int, viol_fields,
                   viol_name: str, wall_s: float,
                   fp_capacity: int = 0):
    """CheckResult of an invariant-delta recheck: clean rechecks carry
    the stored run's full counters (the reachable set IS that run's);
    a violated recheck reports the violation - counters still the
    stored exhaustive ones, clearly a superset of what a violating
    fresh run would have explored before halting."""
    from ..engine.bfs import CheckResult

    distinct = int(meta["distinct"])
    return CheckResult(
        generated=int(meta["generated"]),
        distinct=distinct,
        depth=int(meta["depth"]),
        queue_left=0,
        violation=int(viol_code),
        violation_name=viol_name,
        violation_state=(np.asarray(viol_fields, np.int32)
                         if viol_fields is not None
                         else np.zeros(0, np.int32)),
        violation_action=-1,
        action_generated=dict(meta["action_generated"]),
        action_distinct=dict(meta["action_distinct"]),
        wall_s=wall_s,
        iterations=-1,
        outdegree=(tuple(meta["outdegree"])
                   if meta.get("outdegree") else None),
        fp_occupancy=(distinct / fp_capacity if fp_capacity else None),
    )


# ---------------------------------------------------------------------------
# The api-side plan
# ---------------------------------------------------------------------------


class _PropertyHolds:
    """Stand-in temporal-check result on a verdict-tier hit: the cached
    clean verdict attests every selected property held."""

    holds = True
    lasso_prefix = ()
    lasso_cycle = ()


class ArtifactPlan:
    """One check's view of the artifact cache (api.run_check wires it
    into the struct path; serve.scheduler keys the same store
    directly).  Owns key computation, the two-tier lookup, the
    replacement check functions, and the clean-verdict write."""

    def __init__(self, store: ArtifactStore, model, check_deadlock: bool,
                 properties=(), fp_capacity: int = 0, bounds=None,
                 fp_index: int = None, seed: int = None,
                 bypass_read: bool = False):
        from ..engine.fingerprint import DEFAULT_FP_INDEX, DEFAULT_SEED

        self.store = store
        self.model = model
        self.check_deadlock = bool(check_deadlock)
        self.properties = tuple(properties or ())
        self.fp_capacity = int(fp_capacity)
        self.bounds = bounds
        self.fp_index = (fp_index if fp_index is not None
                         else DEFAULT_FP_INDEX)
        self.seed = seed if seed is not None else DEFAULT_SEED
        self.bypass_read = bool(bypass_read)
        self.vkey = verdict_key(model, check_deadlock, self.properties)
        self.rkey = reach_key(model, check_deadlock)
        self.verdict_hit = False
        self.reach_hit = False

    # -- helpers -----------------------------------------------------------

    def _backend(self):
        from .cache import get_backend

        return get_backend(self.model, self.check_deadlock,
                           bounds=self.bounds)

    def _memo_key(self):
        from .cache import model_key

        return (model_key(self.model), self.check_deadlock,
                self.bounds.digest() if self.bounds is not None else "")

    def _journal(self, journal, tier: str, outcome: str, key: str,
                 log=None, **extra) -> None:
        """Journal one cache decision (the single source of truth);
        hits additionally render their TLC-style banner as a derived
        view of that same event (obs.views), like every other
        supervisor banner."""
        if journal is not None:
            ev = journal.event("cache", tier=tier, outcome=outcome,
                               key=key, **extra)
        else:
            from ..obs.schema import SCHEMA_VERSION

            ev = {"v": SCHEMA_VERSION, "t": time.time(),
                  "event": "cache", "tier": tier, "outcome": outcome,
                  "key": key, **extra}
        if log is not None and outcome == "hit":
            from ..obs.views import render_tlc_event

            render_tlc_event(log, ev)

    # -- lookup ------------------------------------------------------------

    def fast_check(self, journal, log):
        """Try both tiers BEFORE any engine build.  Returns None (run
        normally) or (tier, check_fn, n_init): check_fn replaces the
        kit's engine dispatch and returns (CheckResult, None)."""

        def warn_for(tier, key):
            # a corrupt artifact is LOUD in both surfaces: a transcript
            # warning and a schema-v1 `cache` event with outcome
            # "corrupt" (the miss event still follows - corruption IS
            # a miss, the extra event says why)
            def warn(msg):
                log.msg(1000, f"Warning: {msg}", severity=1)
                self._journal(journal, tier, "corrupt", key)

            return warn

        if self.bypass_read:
            self.store._count("bypasses")
            self._journal(journal, "verdict", "bypass", self.vkey)
            return None
        payload = self.store.lookup_verdict(
            self.vkey, warn=warn_for("verdict", self.vkey))
        if payload is not None:
            self.verdict_hit = True
            self._journal(journal, "verdict", "hit", self.vkey,
                          log=log, workload=payload.get("workload"))
            t0 = time.time()

            def check():
                return (result_from_payload(
                    payload, fp_capacity=self.fp_capacity,
                    wall_s=time.time() - t0,
                ), None)

            return "verdict", check, int(payload["n_init"])
        self._journal(journal, "verdict", "miss", self.vkey)
        if self.properties:
            return None  # the reach tier cannot attest liveness
        reach = self.store.lookup_reach(
            self.rkey, warn=warn_for("reach", self.rkey))
        if reach is None:
            self._journal(journal, "reach", "miss", self.rkey)
            return None
        states, meta = reach
        backend = self._backend()
        if codec_digest(backend.cdc, self.bounds) != meta.get(
                "codec_digest"):
            # the new model infers a different packed layout (e.g. a
            # TypeOK hint reshaped a field): decoding would be garbage
            self._journal(journal, "reach", "miss", self.rkey,
                          detail="codec layout changed")
            self.store._count("reach_hits", -1)
            self.store._count("reach_misses")
            return None
        self.reach_hit = True
        self._journal(journal, "reach", "hit", self.rkey, log=log,
                      workload=meta.get("workload"),
                      states=int(states.shape[0]))

        def check():
            from .backend import struct_viol_names

            t0 = time.time()
            code, fields = run_recheck(self.model, backend, states,
                                       self._memo_key())
            name = struct_viol_names(self.model).get(code, "none")
            return (recheck_result(
                meta, code, fields, name, time.time() - t0,
                fp_capacity=self.fp_capacity,
            ), None)

        return "reach", check, int(meta["n_init"])

    # -- write -------------------------------------------------------------

    def record(self, result, n_init: int, journal=None,
               action_order=None) -> None:
        """Write both tiers after a CLEAN final verdict (the only write
        point: error/violation/exhausted/interrupted/cert runs never
        reach here with violation == 0).  The reach tier additionally
        needs the captured fpset table and an invertible (<= 64 bit)
        codec that passes the round-trip re-fingerprint."""
        if result is None or int(result.violation) != 0:
            return
        if getattr(result, "cert_violated", None):
            return
        backend = self._backend()
        if not self.verdict_hit:
            if action_order is None:
                action_order = backend.labels
            self.store.put_verdict(self.vkey, verdict_payload(
                self.model, result, n_init,
                properties=self.properties, action_order=action_order,
            ))
            self._journal(journal, "verdict", "write", self.vkey)
        table = getattr(result, "fp_table", None)
        if table is None or self.reach_hit or self.store.has_reach(
                self.rkey):
            return
        states = states_from_table(table, backend.cdc.nbits,
                                   self.fp_index, self.seed)
        if states is None or states.shape[0] != int(result.distinct):
            # > 64-bit codec, a failed round-trip, or a table whose
            # occupancy disagrees with the distinct counter: skip the
            # tier rather than store anything unverified
            self._journal(journal, "reach", "skip", self.rkey,
                          detail="codec not invertible")
            return
        self.store.put_reach(self.rkey, states, dict(
            workload=self.model.root_name,
            codec_digest=codec_digest(backend.cdc, self.bounds),
            nbits=int(backend.cdc.nbits),
            generated=int(result.generated),
            distinct=int(result.distinct),
            depth=int(result.depth),
            n_init=int(n_init),
            action_generated={k: int(v) for k, v in
                              result.action_generated.items()},
            action_distinct={k: int(v) for k, v in
                             result.action_distinct.items()},
            outdegree=([float(v) for v in result.outdegree]
                       if result.outdegree is not None else None),
            created_t=round(time.time(), 3),
        ))
        self._journal(journal, "reach", "write", self.rkey)
