"""Next-state enumeration for the structural frontend (E1).

Reads a translation action as a constraint program, the way TLC's
next-state generator does: conjuncts are processed in order; `var' = e`
binds the primed variable (or checks it, if already bound), `var' \\in S`
enumerates, UNCHANGED binds identities, disjunctions and \\E binders
branch, IF branches on an evaluated condition, and every other conjunct
is a guard.  PlusCal translations are emitted in an order where every
primed read follows its assignment (e.g. the `requests'[c].obj` read
inside Get's apiState' update, /root/reference/KubeAPI.tla:722), so
ordered processing is complete for them.

Operator applications expand into their definition body when the body
mentions primes or UNCHANGED (action operators: API(self), Client(self),
...); otherwise they are state predicates and evaluate as guards.  The
innermost expanded non-disjunction definition names the fired action -
exactly the PlusCal label attribution TLC's coverage output uses
(MC.out:44-1092 lists DoRequest/DoReply/... as the action names).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from .eval import Evaluator, canon
from .parser import Definition


class StructActionError(ValueError):
    pass


def expand_unchanged(names, defs, variables) -> List[str]:
    """UNCHANGED accepts state variables AND tuple-of-variables
    definitions (the universal `vars == <<...>>` convention TLC
    honors: `UNCHANGED vars`): expand definition names into the
    variables they bundle, recursively.  Names that are neither a
    variable nor such a definition pass through unchanged so the
    caller's own unknown-variable error still fires."""
    out: List[str] = []
    for v in names:
        if v in variables:
            out.append(v)
            continue
        d = defs.get(v)
        body = getattr(d, "body", None)
        if body is not None and body[0] == "tuple" and all(
            x[0] == "name" for x in body[1]
        ):
            out.extend(expand_unchanged(
                [x[1] for x in body[1]], defs, variables
            ))
            continue
        if body is not None and body[0] == "name":
            out.extend(expand_unchanged([body[1]], defs, variables))
            continue
        out.append(v)
    return out


class ActionSystem:
    """Enumerates initial states and successors of a parsed module."""

    def __init__(self, ev: Evaluator, variables: Tuple[str, ...],
                 init_name: str, next_name: str):
        self.ev = ev
        self.variables = variables
        self.init_ast = ev.defs[init_name].body
        self.next_ast = ev.defs[next_name].body
        self._mentions_cache: Dict[int, bool] = {}

    def with_constants(self, constants: Dict[str, object]) -> "ActionSystem":
        """The same Init/Next under different CONSTANT values - the
        constant-config sweep engine (jaxtlc.serve.sweep) enumerates
        each configuration's Init set host-side through this, against
        the one already-parsed module."""
        clone = ActionSystem.__new__(ActionSystem)
        clone.ev = Evaluator(self.ev.defs, dict(constants))
        clone.variables = self.variables
        clone.init_ast = self.init_ast
        clone.next_ast = self.next_ast
        clone._mentions_cache = {}
        return clone

    # -- prime detection ---------------------------------------------------

    def _mentions_prime(self, ast) -> bool:
        key = id(ast)
        hit = self._mentions_cache.get(key)
        if hit is None:
            from .shapes import _mentions_prime_static

            hit = _mentions_prime_static(ast, self.ev.defs)
            self._mentions_cache[key] = hit
        return hit

    # -- initial states ----------------------------------------------------

    def initial_states(self) -> List[tuple]:
        """All Init-satisfying assignments, as state tuples in variable
        declaration order."""
        outs: List[Dict[str, object]] = []
        self._enum_init(self.init_ast, {}, outs)
        states = []
        for a in outs:
            missing = [v for v in self.variables if v not in a]
            if missing:
                raise StructActionError(
                    f"Init leaves {missing} unassigned"
                )
            states.append(tuple(canon(a[v]) for v in self.variables))
        return states

    def _enum_init(self, ast, bound: Dict[str, object], outs: list):
        op = ast[0]
        if op == "and":
            self._enum_init_seq(ast[1], 0, bound, outs)
            return
        self._enum_init_seq([ast], 0, bound, outs)

    def _enum_init_seq(self, items, i, bound, outs):
        if i == len(items):
            outs.append(bound)
            return
        ast = items[i]
        op = ast[0]
        env = dict(self.ev.constants)
        env.update(bound)
        if op == "and":
            self._enum_init_seq(
                list(ast[1]) + items[i + 1:], 0, bound, outs
            )
            return
        if op == "cmp" and ast[1] == "=" and ast[2][0] == "name" \
                and ast[2][1] in self.variables:
            name = ast[2][1]
            val = canon(self.ev.eval(ast[3], env))
            if name in bound:
                if bound[name] != val:
                    return
                self._enum_init_seq(items, i + 1, bound, outs)
                return
            b2 = dict(bound)
            b2[name] = val
            self._enum_init_seq(items, i + 1, b2, outs)
            return
        if op == "cmp" and ast[1] == r"\in" and ast[2][0] == "name" \
                and ast[2][1] in self.variables:
            name = ast[2][1]
            dom = self.ev.eval(ast[3], env)
            if not isinstance(dom, frozenset):
                raise StructActionError("Init: var \\in non-set")
            for val in sorted(dom, key=repr):
                b2 = dict(bound)
                b2[name] = canon(val)
                self._enum_init_seq(items, i + 1, b2, outs)
            return
        # plain guard
        v = self.ev.eval(ast, env)
        if v is True:
            self._enum_init_seq(items, i + 1, bound, outs)
        elif v is not False:
            raise StructActionError(f"Init conjunct not BOOLEAN: {ast!r}")

    # -- successors --------------------------------------------------------

    def successors(self, state: tuple) -> List[Tuple[str, tuple]]:
        """[(action_label, next_state)] - all Next successors, including
        self-loops (TLC counts them as generated successors)."""
        env = dict(self.ev.constants)
        env.update(zip(self.variables, state))
        outs: List[Tuple[str, Dict[str, object]]] = []
        self._enum(self.next_ast, env, {}, None, outs)
        result = []
        for label, primed in outs:
            missing = [v for v in self.variables if v not in primed]
            if missing:
                raise StructActionError(
                    f"action {label}: primed vars {missing} unassigned"
                )
            result.append((
                label or "?",
                tuple(canon(primed[v]) for v in self.variables),
            ))
        return result

    def _enum(self, ast, env, primed, label: Optional[str], outs):
        """Yield completed (label, primed) into outs; `primed` is never
        mutated (copied at every bind/branch)."""
        op = ast[0]
        if op == "and":
            self._enum_seq(ast[1], 0, env, primed, label, outs)
            return
        if op == "or":
            for branch in ast[1]:
                self._enum(branch, env, primed, label, outs)
            return
        if op == "exists":
            _, names, dom_ast, body = ast
            dom = self.ev.eval(dom_ast, env, primed)
            if not isinstance(dom, frozenset):
                raise StructActionError("\\E over non-set in action")
            from itertools import product as _product
            for combo in _product(sorted(dom, key=repr),
                                  repeat=len(names)):
                env2 = dict(env)
                env2.update(zip(names, combo))
                self._enum(body, env2, primed, label, outs)
            return
        if op == "if":
            c = self.ev.eval(ast[1], env, primed)
            if not isinstance(c, bool):
                raise StructActionError("IF condition not BOOLEAN")
            self._enum(ast[2] if c else ast[3], env, primed, label, outs)
            return
        if op == "let":
            env2 = dict(env)
            for name, params, body in ast[1]:
                if params:
                    env2[name] = Definition(name, params, body)
                else:
                    env2[name] = self.ev.eval(body, env2, primed)
            self._enum(ast[2], env2, primed, label, outs)
            return
        if op in ("call", "name"):
            dname = ast[1]
            d = env.get(dname)
            if not isinstance(d, Definition):
                d = self.ev.defs.get(dname)
            if isinstance(d, Definition) and self._mentions_prime(d.body):
                args = ast[2] if op == "call" else []
                if len(d.params) != len(args):
                    raise StructActionError(
                        f"{dname}: arity mismatch in action position"
                    )
                env2 = dict(env)
                for p, a in zip(d.params, args):
                    env2[p] = self.ev.eval(a, env, primed)
                inner_label = label
                if d.body[0] != "or":
                    inner_label = dname
                self._enum(d.body, env2, primed, inner_label, outs)
                return
            # falls through to guard evaluation
        if op == "unchanged":
            p2 = dict(primed)
            for v in expand_unchanged(ast[1], self.ev.defs,
                                      self.variables):
                old = env.get(v)
                if v not in env:
                    raise StructActionError(f"UNCHANGED unknown var {v}")
                if v in p2 and p2[v] != old:
                    return
                p2[v] = old
            self._enum_done(env, p2, label, outs)
            return
        if op == "cmp" and ast[1] == "=" and ast[2][0] == "prime":
            name = ast[2][1]
            val = canon(self.ev.eval(ast[3], env, primed))
            if name in primed:
                if primed[name] != val:
                    return
                self._enum_done(env, primed, label, outs)
                return
            p2 = dict(primed)
            p2[name] = val
            self._enum_done(env, p2, label, outs)
            return
        if op == "cmp" and ast[1] == r"\in" and ast[2][0] == "prime":
            name = ast[2][1]
            dom = self.ev.eval(ast[3], env, primed)
            if not isinstance(dom, frozenset):
                raise StructActionError("var' \\in non-set")
            for val in sorted(dom, key=repr):
                p2 = dict(primed)
                p2[name] = canon(val)
                self._enum_done(env, p2, label, outs)
            return
        # guard
        v = self.ev.eval(ast, env, primed)
        if v is True:
            self._enum_done(env, primed, label, outs)
        elif v is not False:
            raise StructActionError(
                f"action conjunct not BOOLEAN: {ast[:2]!r}"
            )

    def _enum_seq(self, items, i, env, primed, label, outs):
        """Process conjunct i; the continuation collects into a local list
        and forwards the rest."""
        if i == len(items):
            outs.append((label, primed))
            return
        here: List[Tuple[Optional[str], dict]] = []
        self._enum(items[i], env, primed, label, here)
        for lab, p in here:
            self._enum_seq(items, i + 1, env, p, lab or label, outs)

    def _enum_done(self, env, primed, label, outs):
        outs.append((label, primed))
