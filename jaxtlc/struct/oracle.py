"""BFS model checker over the structurally-interpreted relation (E1).

Same accounting as TLC and the hand oracle (spec.oracle.bfs): initial
states count toward generated and distinct (MC.out:29-32); every
enumerated successor counts as generated; depth = BFS levels with Init
at level 1 (MC.out:1101); deadlock = a state with no successor at all
(self-loops count as successors); invariants are checked on every
distinct state.  Action attribution uses the PlusCal label names
(MC.out:44-1092), so per-action generated counts diff directly against
the hand oracle and the TLC log.
"""

from __future__ import annotations

from collections import deque
from typing import Dict, List, NamedTuple, Optional, Tuple

from .actions import ActionSystem
from .eval import StructEvalError, TlaAssertionError


class StructBFSResult(NamedTuple):
    generated: int
    distinct: int
    depth: int
    max_outdegree: int
    min_outdegree: int
    violations: List[Tuple[str, tuple]]
    action_generated: Dict[str, int]
    action_distinct: Dict[str, int]
    levels: List[int]
    parents: Optional[Dict[tuple, Tuple[Optional[tuple], Optional[str]]]]
    states: Optional[Dict[tuple, int]]  # state -> level (collect_states)


def bfs(
    system: ActionSystem,
    invariants: Dict[str, tuple],
    check_deadlock: bool = True,
    max_states: int = 10_000_000,
    keep_parents: bool = False,
    stop_on_violation: bool = True,
    collect_states: bool = False,
) -> StructBFSResult:
    ev = system.ev
    inits = system.initial_states()
    seen: Dict[tuple, int] = {}
    parents: Optional[Dict] = {} if keep_parents else None
    generated = 0
    violations: List[Tuple[str, tuple]] = []
    frontier: List[tuple] = []
    act_gen: Dict[str, int] = {}
    act_dist: Dict[str, int] = {}

    def check_invs(st: tuple):
        env = dict(ev.constants)
        env.update(zip(system.variables, st))
        for name, ast in invariants.items():
            try:
                ok = ev.eval(ast, env) is True
            except StructEvalError as e:
                # TLC reports an invariant that cannot be evaluated on a
                # reachable state (e.g. an out-of-range index) as an
                # error with a trace; same here, as a violation kind
                violations.append((f"{name} (evaluation error: {e})",
                                   st))
                continue
            if not ok:
                violations.append((name, st))

    for s in inits:
        generated += 1
        if s not in seen:
            seen[s] = 1
            frontier.append(s)
            if keep_parents:
                parents[s] = (None, None)
            check_invs(s)
    depth = 1
    levels = [len(frontier)]
    max_out, min_out = 0, 1 << 30
    while frontier and not (violations and stop_on_violation):
        nxt: List[tuple] = []
        for s in frontier:
            try:
                succs = system.successors(s)
            except TlaAssertionError as e:
                violations.append((f"assert:{e.tla_msg}", s))
                if stop_on_violation:
                    break
                continue
            generated += len(succs)
            distinct_succs = {t for _, t in succs}
            outdeg = len(distinct_succs)
            max_out = max(max_out, outdeg)
            min_out = min(min_out, outdeg)
            if outdeg == 0 and check_deadlock:
                violations.append(("deadlock", s))
            for label, t in succs:
                act_gen[label] = act_gen.get(label, 0) + 1
                if t not in seen:
                    if len(seen) >= max_states:
                        raise RuntimeError("state-space bound exceeded")
                    seen[t] = depth + 1
                    nxt.append(t)
                    act_dist[label] = act_dist.get(label, 0) + 1
                    if keep_parents:
                        parents[t] = (s, label)
                    check_invs(t)
        frontier = nxt
        if frontier:
            depth += 1
            levels.append(len(frontier))
    return StructBFSResult(
        generated=generated,
        distinct=len(seen),
        depth=depth,
        max_outdegree=max_out,
        min_outdegree=min_out if min_out != 1 << 30 else 0,
        violations=violations,
        action_generated=act_gen,
        action_distinct=act_dist,
        levels=levels,
        parents=parents,
        states=seen if collect_states else None,
    )


def state_env(system: ActionSystem, st: tuple) -> dict:
    env = dict(system.ev.constants)
    env.update(zip(system.variables, st))
    return env


def state_to_tla(system: ActionSystem, st: tuple) -> str:
    """TLA-conjunct rendering of a structural state (TLC trace style)."""
    from ..spec.pretty import value_to_tla

    return "\n".join(
        f"/\\ {v} = {value_to_tla(val)}"
        for v, val in zip(system.variables, st)
    )


class LivenessResult(NamedTuple):
    name: str
    holds: bool
    lasso_prefix: Optional[List[tuple]]
    lasso_cycle: Optional[List[tuple]]


def check_leads_to(system: ActionSystem, p_ast, q_ast, name: str = "",
                   max_states: int = 1_000_000) -> LivenessResult:
    """P ~> Q under WF_vars(Next) over the structural relation - the
    same greatest-fixpoint peeling as the generic path (gen.oracle):
    survive(s) iff ~Q(s) and (no state-changing successor, or some
    state-changing successor survives); a violation is a reachable
    surviving P-state."""
    ev = system.ev

    def holds(ast, st) -> bool:
        env = dict(ev.constants)
        env.update(zip(system.variables, st))
        return ev.eval(ast, env) is True

    init_states = system.initial_states()
    states: Dict[tuple, int] = {}
    order: List[tuple] = []
    edges: Dict[int, List[int]] = {}
    frontier = deque()
    init_ids = []
    for st in init_states:
        if st not in states:
            init_ids.append(len(order))
            states[st] = len(order)
            order.append(st)
            frontier.append(st)
    while frontier:
        st = frontier.popleft()
        sid = states[st]
        outs = []
        for _, nxt in system.successors(st):
            if nxt == st:
                continue
            if nxt not in states:
                if len(states) >= max_states:
                    raise RuntimeError("liveness graph bound exceeded")
                states[nxt] = len(order)
                order.append(nxt)
                frontier.append(nxt)
            outs.append(states[nxt])
        edges[sid] = outs
    n = len(order)
    alive = [not holds(q_ast, s) for s in order]
    changed = True
    while changed:
        changed = False
        for i in range(n):
            if not alive[i]:
                continue
            outs = edges[i]
            if outs and not any(alive[j] for j in outs):
                alive[i] = False
                changed = True
    for i in range(n):
        if alive[i] and holds(p_ast, order[i]):
            prefix = _path_to(edges, init_ids, i)
            cycle = _alive_tail(edges, i, alive)
            return LivenessResult(
                name, False,
                [order[j] for j in prefix],
                [order[j] for j in cycle],
            )
    return LivenessResult(name, True, None, None)


def _path_to(edges, srcs, dst):
    """BFS path from ANY of `srcs` to dst (multi-initial-state specs)."""
    if isinstance(srcs, int):
        srcs = [srcs]
    prev = {s: None for s in srcs}
    q = deque(srcs)
    while q:
        u = q.popleft()
        if u == dst:
            break
        for v in edges[u]:
            if v not in prev:
                prev[v] = u
                q.append(v)
    path, cur = [], dst
    while cur is not None:
        path.append(cur)
        cur = prev[cur]
    return list(reversed(path))


def _alive_tail(edges, start, alive):
    seen = {start: 0}
    seq = [start]
    cur = start
    while True:
        outs = [j for j in edges[cur] if alive[j]]
        if not outs:
            return seq
        cur = outs[0]
        if cur in seen:
            return seq[seen[cur]:]
        seen[cur] = len(seq)
        seq.append(cur)


def violation_trace(system: ActionSystem, invariants: Dict[str, tuple],
                    check_deadlock: bool = True,
                    max_states: int = 10_000_000):
    """(kind, [(state, label|None), ...]) for the first violation, or
    None - the trace-explorer re-run over the structural relation."""
    r = bfs(system, invariants, check_deadlock=check_deadlock,
            max_states=max_states, keep_parents=True)
    if not r.violations:
        return None
    kind, bad = r.violations[0]
    chain = []
    cur: Optional[tuple] = bad
    while cur is not None:
        parent, label = r.parents[cur]
        chain.append((cur, label))
        cur = parent
    chain.reverse()
    return kind, chain
