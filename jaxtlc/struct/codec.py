"""Tensor codec for structurally-inferred shapes (E1 device path).

Maps each variable's inferred Shape (struct.shapes) to a fixed layout of
int32 fields, composing four layout forms:

* EnumLeaf  - the whole (sub)value indexes into its enumerated universe:
              one field.  Records, unions with atoms, frames - anything
              whose universe fits ENUM_LEAF_LIMIT.
* MaskLeaf  - a set over an enumerable element universe becomes a
              bitmask: 16 universe elements per field (KubeAPI's
              apiState and per-client list results).
* RecNode   - structural product: optional fields get a presence bit
              field; absent children are zeroed so states compare equal
              field-wise (canonical zero).
* SeqNode   - bounded sequence: a length field + cap slot fields, each
              slot an EnumLeaf of the element universe (procedure call
              stacks, /root/reference/KubeAPI.tla:466).

Packing to uint32 words reuses the bit-concatenation scheme of the
KubeAPI and generic codecs, so the MXU fingerprint path and fingerprint
set run unchanged on struct-compiled states.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

from .shapes import (
    Shape,
    SRec,
    SSeq,
    SSet,
    ShapeError,
    universe,
)

ENUM_LEAF_LIMIT = 1 << 17
MASK_BITS_PER_FIELD = 16


def _bits_for(n: int) -> int:
    return max(1, (n - 1).bit_length())


class EnumLeaf:
    def __init__(self, shape: Optional[Shape]):
        self.shape = shape
        self.values: List = universe(shape, ENUM_LEAF_LIMIT)
        if not self.values:
            self.values = [None]  # degenerate: a single dummy value
        self.index: Dict = {v: i for i, v in enumerate(self.values)}
        self.widths = [_bits_for(len(self.values))]
        self.n_fields = 1

    def encode(self, v, out: List[int]):
        try:
            out.append(self.index[v])
        except (KeyError, TypeError):
            raise ValueError(f"value {v!r} outside enumerated universe")

    def decode(self, fields, pos: int) -> Tuple[object, int]:
        return self.values[int(fields[pos])], pos + 1


class MaskLeaf:
    def __init__(self, shape: SSet):
        self.shape = shape
        self.elem = EnumLeaf(shape.elem)
        self.n_bits = len(self.elem.values)
        self.n_fields = (self.n_bits + MASK_BITS_PER_FIELD - 1) \
            // MASK_BITS_PER_FIELD
        self.widths = []
        left = self.n_bits
        for _ in range(self.n_fields):
            take = min(left, MASK_BITS_PER_FIELD)
            self.widths.append(take)
            left -= take

    def encode(self, v, out: List[int]):
        if not isinstance(v, frozenset):
            raise ValueError(f"expected a set, got {v!r}")
        bits = 0
        for x in v:
            bits |= 1 << self.elem.index[x]
        for w in self.widths:
            out.append(bits & ((1 << w) - 1))
            bits >>= w

    def decode(self, fields, pos: int) -> Tuple[object, int]:
        bits = 0
        shift = 0
        for w in self.widths:
            bits |= int(fields[pos]) << shift
            shift += w
            pos += 1
        return frozenset(
            self.elem.values[i] for i in range(self.n_bits)
            if bits >> i & 1
        ), pos


class RecNode:
    def __init__(self, shape: SRec):
        self.shape = shape
        self.entries: List[Tuple[str, bool, object]] = []
        self.widths: List[int] = []
        for f, s, opt in shape.fields:
            child = layout_of(s)
            self.entries.append((f, opt, child))
            if opt:
                self.widths.append(1)
            self.widths.extend(child.widths)
        self.n_fields = len(self.widths)

    def encode(self, v, out: List[int]):
        d = dict(v) if isinstance(v, tuple) else None
        if d is None:
            raise ValueError(f"expected record/function, got {v!r}")
        for f, opt, child in self.entries:
            present = f in d
            if opt:
                out.append(int(present))
            elif not present:
                raise ValueError(f"required field {f} absent in {v!r}")
            if present:
                child.encode(d[f], out)
            else:
                out.extend([0] * child.n_fields)

    def decode(self, fields, pos: int) -> Tuple[object, int]:
        pairs = []
        for f, opt, child in self.entries:
            present = True
            if opt:
                present = bool(int(fields[pos]))
                pos += 1
            val, pos2 = child.decode(fields, pos)
            pos = pos2
            if present:
                pairs.append((f, val))
        return tuple(sorted(pairs)), pos


class SeqNode:
    def __init__(self, shape: SSeq):
        self.shape = shape
        self.cap = shape.cap
        self.elem = EnumLeaf(shape.elem)
        self.widths = [_bits_for(self.cap + 1)] + \
            self.elem.widths * self.cap
        self.n_fields = len(self.widths)

    def encode(self, v, out: List[int]):
        if not isinstance(v, tuple):
            raise ValueError(f"expected sequence, got {v!r}")
        if len(v) > self.cap:
            raise ValueError(
                f"sequence longer than inferred cap {self.cap}: {v!r}"
            )
        out.append(len(v))
        for x in v:
            self.elem.encode(x, out)
        out.extend([0] * ((self.cap - len(v)) * self.elem.n_fields))

    def decode(self, fields, pos: int) -> Tuple[object, int]:
        n = int(fields[pos])
        pos += 1
        items = []
        for k in range(self.cap):
            val, pos2 = self.elem.decode(fields, pos)
            pos = pos2
            if k < n:
                items.append(val)
        return tuple(items), pos


def _layout_max_codes(lay, out: List[int]) -> None:
    """Append `lay`'s per-field max legal codes to `out` (layout-walk
    mirror of the widths concatenation in StructCodec.__init__)."""
    if isinstance(lay, EnumLeaf):
        out.append(len(lay.values) - 1)
        return
    if isinstance(lay, MaskLeaf):
        for w in lay.widths:
            out.append((1 << w) - 1)
        return
    if isinstance(lay, RecNode):
        for _f, opt, child in lay.entries:
            if opt:
                out.append(1)
            _layout_max_codes(child, out)
        return
    if isinstance(lay, SeqNode):
        out.append(lay.cap)
        for _ in range(lay.cap):
            out.append(len(lay.elem.values) - 1)
        return
    raise ShapeError(f"no max codes for layout {type(lay).__name__}")


_LAYOUT_CACHE: Dict[Shape, object] = {}


def layout_of(shape: Optional[Shape]):
    """Layout for a shape: EnumLeaf when the universe is small enough,
    else a structural decomposition."""
    key = shape
    hit = _LAYOUT_CACHE.get(key)
    if hit is not None:
        return hit
    lay = _build_layout(shape)
    _LAYOUT_CACHE[key] = lay
    return lay


def _build_layout(shape: Optional[Shape]):
    if isinstance(shape, SSet):
        # prefer the mask form for sets (quantifier compilation wants
        # bits); tiny set universes nested inside records still go
        # through universe() enumeration
        try:
            return MaskLeaf(shape)
        except ShapeError:
            raise ShapeError(
                f"set element universe not enumerable: {shape.elem}"
            )
    if isinstance(shape, SSeq):
        # sequences always take the structural form so the lane
        # compiler's Len/Head/Tail/indexing see an LSeq, however small
        # the universe (nested inside enumerated records they still
        # enum-encode via universe())
        return SeqNode(shape)
    try:
        return EnumLeaf(shape)
    except ShapeError:
        pass
    if isinstance(shape, SRec):
        return RecNode(shape)
    raise ShapeError(f"no layout for shape {shape}")


class StructCodec:
    """Whole-state codec: variable order -> concatenated field layout."""

    def __init__(self, variables: Tuple[str, ...],
                 var_shapes: Dict[str, Shape]):
        self.variables = variables
        self.layouts = [layout_of(var_shapes[v]) for v in variables]
        self.offsets: Dict[str, int] = {}
        self.widths: List[int] = []
        for v, lay in zip(variables, self.layouts):
            self.offsets[v] = len(self.widths)
            self.widths.extend(lay.widths)
        self.n_fields = len(self.widths)
        self.nbits = sum(self.widths)
        self.n_words = (self.nbits + 31) // 32

    def max_codes(self) -> List[int]:
        """Per-field maximum LEGAL code ([F] ints): the universe claim
        the runtime certificate check (analysis.absint) verifies on
        every generated state.  A field can hold up to 2^width - 1
        after packing; codes above max_codes (or below 0 pre-pack) are
        values the certified bounds claim unreachable."""
        out: List[int] = []
        for lay in self.layouts:
            _layout_max_codes(lay, out)
        assert len(out) == self.n_fields
        return out

    def encode(self, st: tuple) -> np.ndarray:
        out: List[int] = []
        for lay, val in zip(self.layouts, st):
            lay.encode(val, out)
        return np.asarray(out, np.int32)

    def decode(self, vec) -> tuple:
        fields = np.asarray(vec)
        vals = []
        pos = 0
        for lay in self.layouts:
            v, pos = lay.decode(fields, pos)
            vals.append(v)
        return tuple(vals)

    # -- packing (same scheme as gen.codec / spec.codec) ------------------

    def pack(self, vecs):
        v = vecs.astype(jnp.uint32)
        words, cur, cur_bits = [], None, 0
        for j, width in enumerate(self.widths):
            remaining = v[..., j]
            rbits = width
            while rbits > 0:
                if cur is None:
                    cur = jnp.zeros_like(remaining)
                    cur_bits = 0
                take = min(rbits, 32 - cur_bits)
                cur = cur | (
                    (remaining & ((jnp.uint32(1) << take) - jnp.uint32(1)))
                    << cur_bits
                )
                remaining = remaining >> take
                rbits -= take
                cur_bits += take
                if cur_bits == 32:
                    words.append(cur)
                    cur = None
        if cur is not None:
            words.append(cur)
        return jnp.stack(words, axis=-1)

    def unpack(self, words):
        w = words.astype(jnp.uint32)
        out = []
        wi, bitpos = 0, 0
        for width in self.widths:
            val = jnp.zeros_like(w[..., 0])
            got = 0
            while got < width:
                take = min(width - got, 32 - bitpos)
                piece = (w[..., wi] >> bitpos) & jnp.uint32((1 << take) - 1)
                val = val | (piece << got)
                got += take
                bitpos += take
                if bitpos == 32:
                    wi += 1
                    bitpos = 0
            out.append(val.astype(jnp.int32))
        return jnp.stack(out, axis=-1)
