"""Device-resident live coverage plane (ISSUE 11 tentpole).

TLC's headline observability product is its per-expression coverage
dump (reference MC.out:44-1092); until this round we reproduced it only
by host-side instrumented RE-WALKS of the whole state space
(spec/coverage.py for the KubeAPI family, gen/coverage.py for the gen
subset) - a third exploration, after the run finished.  This module is
the shared vocabulary of the device-native replacement: coverage
counters live IN the computation, the way large-scale ML systems carry
telemetry - a cumulative ``[n_sites]`` uint32 tensor riding the engine
carry exactly like the PR 5 obs ring (optional None-default leaf, pure
telemetry, bit-for-bit gated), incremented by the compiled step itself
and read back only at the segment fences the supervisor already pays.

* ``Site`` / ``CoveragePlane`` - what a SpecBackend exposes: an ordered
  site table plus a ``count(batch, mask, valid) -> [n_sites] uint32``
  device hook the expand stage folds into every block.  The FIRST
  ``len(plane.actions)`` sites are always the per-action sites (kind
  "action"), so the PR 3 per-action coverage lines are a PREFIX VIEW of
  per-site coverage - one accounting, two renderings, no drift.
* site-table builders (``action_site_table``) shared by the struct lane
  compiler (struct/compile.py assigns the fine-grained sites), the
  KubeAPI hand-kernel table (spec/coverage_device.py, pinned
  site-for-site against the host coverage walker) and gen/coverage.py.
* journal/views plumbing: ``coverage`` journal events carry per-segment
  DELTAS; ``coverage_from_events`` folds them back into cumulative
  totals for obs.serve ``GET /coverage``, the Prometheus
  ``coverage_site_total`` counters, tlcstat's coverage line and
  tools/covdiff.py.
* ``render_site_dump`` - the end-of-run dump in MC.out's exact message
  framing (2201 banner, 2772 action headers, 2221 span lines), with
  the span table's source locations when the spec has one
  (coverage_spans) and the stable site keys otherwise.
"""

from __future__ import annotations

from typing import Callable, Dict, List, NamedTuple, Optional, Sequence

import numpy as np


class Site(NamedTuple):
    """One coverage site: a stable key, its kind, and the action it
    belongs to.  `loc` is a source span when the frontend knows one
    (the KubeAPI span table); the key renders in its place otherwise.

    Kinds: "action" (per-action header site, distinct:generated prefix
    view), "guard" (guard conjunct), "branch" (IF/CASE arm), "quant"
    (quantifier/binder body), "effect" (update conjunct / UNCHANGED),
    "init" (Init conjunct), "inv" (invariant span)."""

    key: str
    kind: str
    action: str
    loc: str = ""


class CoveragePlane(NamedTuple):
    """The backend -> engine coverage seam (SpecBackend.coverage).

    ``count(batch [ck,F] int32, mask [ck] bool, valid [ck,L] bool) ->
    [n_sites] uint32`` runs inside the expand stage and returns this
    block's visit increments; the commit stage accumulates them into
    the carry's cumulative ``cov_counts`` leaf.  ``init_count`` is a
    HOST function charging the Init-site visits for the seed states
    (None = all-zero seed).  Pure telemetry: neither feeds control
    flow, so coverage-on results are bit-for-bit coverage-off results
    (bench.py --cov-ab gates the wall overhead)."""

    sites: tuple  # tuple[Site]
    count: object  # device fn(batch, mask, valid) -> [n_sites] uint32
    init_count: object = None  # host fn(inits [n0,F] np) -> [n_sites]
    module: str = ""  # module name for the MC.out-format dump

    @property
    def n_sites(self) -> int:
        return len(self.sites)

    def seed(self, inits) -> np.ndarray:
        """[n_sites] uint32 Init-visit seed for `inits` (host-side)."""
        if self.init_count is None:
            return np.zeros(self.n_sites, np.uint32)
        out = np.asarray(self.init_count(np.asarray(inits)), np.uint32)
        assert out.shape == (self.n_sites,)
        return out


def action_site_table(module: str, actions: Sequence[str],
                      locs: Optional[Dict[str, str]] = None
                      ) -> List[Site]:
    """The per-action PREFIX of every site table: one "action" site per
    action, in rendering order.  gen/coverage.py, the struct compiler
    and the KubeAPI device table all open with exactly this prefix, so
    the per-action coverage lines (PR 3 CLI path) are site table rows
    0..n_actions-1 - one accounting, no drift between renderers."""
    locs = locs or {}
    return [Site(key=a, kind="action", action=a, loc=locs.get(a, ""))
            for a in actions]


def site_totals_dict(sites: Sequence[Site], counts) -> Dict[str, int]:
    """{site key: cumulative count} from a device counts vector."""
    counts = np.asarray(counts)
    return {s.key: int(c) for s, c in zip(sites, counts)}


# ---------------------------------------------------------------------------
# Journal plumbing: per-segment deltas -> cumulative views
# ---------------------------------------------------------------------------


def coverage_delta_event(sites: Sequence[Site], totals: np.ndarray,
                         seen: Optional[np.ndarray]) -> Optional[dict]:
    """The `coverage` journal-event payload for one segment fence:
    nonzero per-site DELTAS since `seen` plus the visited/total header.
    None when nothing moved (no event is journaled)."""
    totals = np.asarray(totals, np.int64)
    prev = (np.zeros_like(totals) if seen is None
            else np.asarray(seen, np.int64))
    delta = totals - prev
    if not (delta != 0).any():
        return None
    return {
        "visited": int((totals > 0).sum()),
        "sites": len(sites),
        "delta": {s.key: int(d) for s, d in zip(sites, delta) if d},
    }


def coverage_from_events(events) -> Optional[dict]:
    """Fold a journal's `coverage` delta events back into cumulative
    totals - the derived view obs.serve's ``GET /coverage``, the
    Prometheus ``coverage_site_total`` counters, tlcstat and covdiff
    all render.  None when the run carried no coverage plane.

    Pod-aware (ISSUE 20): merged ``{base}.hN`` sibling journals carry
    per-host PARTIAL deltas (disjoint fingerprint shards, so the sum
    of partials IS the global total) whose `visited` headers describe
    only that host's rows - so `visited` recomputes from the folded
    totals instead of trusting any single header, and the pod counts
    as saturated only when EVERY host that emitted coverage carried
    its once-per-run saturation event (the level reported is the max)."""
    totals: Dict[str, int] = {}
    n_sites = 0
    sat: Dict = {}  # host key (None = single journal) -> sat level
    covered = set()
    for ev in events:
        if ev.get("event") != "coverage":
            continue
        hk = ev.get("host")
        covered.add(hk)
        for k, d in ev.get("delta", {}).items():
            totals[k] = totals.get(k, 0) + int(d)
        n_sites = ev.get("sites", n_sites)
        if ev.get("saturated"):
            sat[hk] = ev.get("level")
    if not totals and n_sites == 0:
        return None
    saturated_at = None
    if covered and covered <= set(sat):
        levels = [v for v in sat.values() if v is not None]
        saturated_at = max(levels) if levels else None
    return {
        "sites": totals,
        "visited": sum(1 for v in totals.values() if v),
        "n_sites": n_sites or len(totals),
        "saturated_at_level": saturated_at,
    }


# ---------------------------------------------------------------------------
# MC.out-format rendering
# ---------------------------------------------------------------------------


def render_site_dump(sites: Sequence[Site], counts,
                     module: str, stamp: str,
                     init_count: int = 0,
                     act_gen: Optional[Dict[str, int]] = None,
                     act_dist: Optional[Dict[str, int]] = None,
                     order: Optional[Sequence[str]] = None,
                     ) -> List[str]:
    """The end-of-run device coverage dump in MC.out's format/order:
    the 2201 banner text, one 2772-style action header per action (its
    prefix "action" site carries the generated count; `act_dist` fills
    TLC's distinct:generated pair), and one indented span line per
    fine-grained site under its action, rendered with the site's source
    loc when the table has one and the stable key otherwise.  Message
    framing (STARTMSG/ENDMSG) is added by TLCLog.coverage_site_dump."""
    counts = np.asarray(counts)
    act_gen = act_gen or {}
    act_dist = act_dist or {}
    by_action: Dict[str, List] = {}
    # header order: the caller's (module-definition / MC.out) order
    # when given, the site table's otherwise; actions the order list
    # does not know render after it
    order = list(order) if order is not None else []
    for s, c in zip(sites, counts):
        if s.kind == "action":
            if s.action not in order:
                order.append(s.action)
            continue
        by_action.setdefault(s.action, []).append((s, int(c)))
    for s in sites:  # actions that only have fine-grained sites
        if s.kind != "action" and s.action not in order:
            order.append(s.action)
    lines = [f"The coverage statistics at {stamp}"]
    lines.append(f"<Init of module {module}>: {init_count}:{init_count}")
    idx = {s.key: i for i, s in enumerate(sites)}
    for a in order:
        g = act_gen.get(a)
        if g is None:
            i = idx.get(a)
            g = int(counts[i]) if i is not None else 0
        d = act_dist.get(a, 0)
        lines.append(f"<{a} of module {module}>: {d}:{g}")
        for s, c in by_action.get(a, []):
            where = s.loc or s.key
            lines.append(f"  |{where} of module {module}: {c}")
    return lines


def zero_sites(sites: Sequence[Site], counts) -> List[Site]:
    """Sites with zero cumulative visits (the dead-site lint's input);
    action-prefix sites included."""
    counts = np.asarray(counts)
    return [s for s, c in zip(sites, counts) if int(c) == 0]
