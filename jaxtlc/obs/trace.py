"""Chrome-trace (Perfetto) export of a run journal: the timeline tier.

Renders the journal's host-observed intervals as a `chrome://tracing` /
https://ui.perfetto.dev JSON file (`-trace-out run.trace.json`):

* pid "device": one slice per supervised segment (dispatch -> fence),
  subdivided into per-level expand/commit sub-slices on two threads.
  When the journal carries MEASURED per-level `phase` events (a
  `-phase-timing` run, obs.phases), the sub-slices use those walls -
  the lanes are measurement, not illustration.  Without them the
  per-level spans fall back to the SCHEMATIC body-count-proportional
  placement inside the segment's host-observed wall; the overlap
  structure is still real either way: in pipeline mode the commit lane
  of level k overlaps the expand lane of level k+1 (the staged-block
  schedule), in fused mode they abut.  Ground-truth device timelines
  come from `-xprof DIR` (jax.profiler).
* pid "host": checkpoint-write and regrow-migration slices, plus
  instant markers for retries, faults, interruption, recovery and the
  final verdict - so "why was this segment slow" is one glance (the
  TensorFlow timeline discipline, arXiv:1605.08695 §5).
* counter tracks: distinct states, queue depth and fingerprint-table
  load per level, which Perfetto renders as rate/occupancy graphs.

The export is a pure function of the journal events (obs.journal), so
it can be produced live (`-trace-out`), after the fact from any
journal file (`python -m jaxtlc.obs.trace run.journal.jsonl`), or
across an interruption - a SIGTERM'd + `-recover`ed run's single
continuous journal renders as one timeline with the gap visible.

Pod runs (ISSUE 20): a merged ``{base}.hN`` sibling stream renders as
ONE trace with a process-row PAIR per host (device lanes + host lanes,
keyed by the events' ``host`` field).  Every host's segment slices
share the same time origin, so cross-host skew is the horizontal
offset between the rows' fence edges, and the all_to_all fence wait is
the gap a fast host's segment end leaves before the slow host's - the
distributed-timeline reading the TensorFlow timeline discipline
(arXiv:1605.08695 §5) is built for.  Spill flushes carry their
measured wall (the highwater-triggered sweep) and render as duration
slices on their host's row.
"""

from __future__ import annotations

import json
import os
from typing import List

PID_DEVICE = 1
PID_HOST = 2
POD_PID_BASE = 10  # host h -> pids (BASE + 2h, BASE + 2h + 1)
TID_SEGMENT = 1
TID_EXPAND = 2
TID_COMMIT = 3
TID_CKPT = 1
TID_REGROW = 2


def _meta(pid: int, name: str) -> dict:
    return {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": name}}


def _thread(pid: int, tid: int, name: str) -> dict:
    return {"name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
            "args": {"name": name}}


def chrome_trace_events(events: List[dict]) -> List[dict]:
    """The journal -> traceEvents transform (timestamps in us, relative
    to the first journal event)."""
    if not events:
        return []
    t0 = events[0]["t"]
    us = lambda t: (t - t0) * 1e6  # noqa: E731

    pipeline = False
    for ev in events:
        if ev["event"] == "run_start":
            pipeline = bool(ev.get("params", {}).get("pipeline"))
            break

    out = []
    known: set = set()

    def pid_device(h):
        return PID_DEVICE if h is None else POD_PID_BASE + 2 * h

    def pid_host(h):
        return PID_HOST if h is None else POD_PID_BASE + 2 * h + 1

    def ensure(h):
        """Emit the process/thread metadata rows for host key `h` once
        (None = the single-process row pair; pod hosts each get their
        own pair, so the merged journal renders one process row per
        host with identical lane structure)."""
        if h in known:
            return
        known.add(h)
        tag = "" if h is None else f" host {h}"
        out.extend([
            _meta(pid_device(h), f"device engine{tag}"),
            _meta(pid_host(h), f"host (checkpoint/regrow){tag}"),
            _thread(pid_device(h), TID_SEGMENT, "segments"),
            _thread(pid_device(h), TID_EXPAND,
                    "expand (per level, schematic)"),
            _thread(pid_device(h), TID_COMMIT,
                    "commit (per level, schematic)"),
            _thread(pid_host(h), TID_CKPT, "checkpoint writes"),
            _thread(pid_host(h), TID_REGROW, "regrow migrations"),
        ])

    ensure(None)

    def instant(ev, name, args=None, h=None):
        ensure(h)
        out.append({"name": name, "ph": "i", "s": "g",
                    "ts": us(ev["t"]), "pid": pid_host(h),
                    "tid": TID_CKPT, "args": args or {}})

    # level events journal at the fence AFTER the segment they ran in:
    # walk in order, buffering levels (and any measured per-level phase
    # walls) against the most recent segment - PER HOST KEY, so a
    # merged pod stream's interleaved hosts never cross-attribute
    pending_levels: dict = {}  # host key -> [level rows]
    pending_phases: dict = {}  # host key -> {level: {expand, commit}}
    last_segment: dict = {}  # host key -> segment event
    prev_level: dict = {}  # host key -> last level event

    def flush_levels(h):
        """Subdivide host `h`'s last segment wall among its buffered
        levels, emitting expand/commit sub-slices whose overlap mirrors
        the engine's step schedule.  MEASURED placement when the
        segment's `phase` events cover every buffered level (a
        -phase-timing run: sequential expand->commit slices of the
        measured walls); body-count-proportional schematic otherwise."""
        seg = last_segment.get(h)
        levels = pending_levels.pop(h, [])
        phases = pending_phases.pop(h, {})
        if seg is None or not levels:
            return
        # shadow the module pids with this host's row pair: the slice
        # emission below then lands on the right process row unchanged
        PID_DEVICE = pid_device(h)
        seg_ts = us(seg["t_dispatch"])
        seg_dur = max(seg["wall_s"] * 1e6, 1.0)
        measured = all(
            {"expand", "commit"} <= set(phases.get(lv["level"], {}))
            for lv in levels
        )
        if measured:
            cursor = seg_ts
            for lv in levels:
                ph = phases[lv["level"]]
                args = {k: lv[k] for k in
                        ("level", "generated", "distinct", "queue",
                         "bodies", "expanded") if k in lv}
                args["measured"] = True
                for phase in ("expand", "commit"):
                    dur = max(ph[phase] * 1e6, 1.0)
                    out.append({
                        "name": f"{phase} L{lv['level']}", "ph": "X",
                        "ts": cursor, "dur": dur, "pid": PID_DEVICE,
                        "tid": TID_EXPAND if phase == "expand"
                        else TID_COMMIT,
                        "args": {**args, "wall_s": ph[phase]},
                    })
                    cursor += dur
                out.append({"name": "states", "ph": "C",
                            "ts": cursor, "pid": PID_DEVICE, "tid": 0,
                            "args": {"distinct": lv["distinct"],
                                     "queue": lv["queue"]}})
                if "fp_load" in lv:
                    out.append({"name": "fp_load", "ph": "C",
                                "ts": cursor, "pid": PID_DEVICE,
                                "tid": 0,
                                "args": {"load": lv["fp_load"]}})
            return
        bodies = [max(lv.get("bodies_level", 1), 1) for lv in levels]
        total = float(sum(bodies))
        cursor = seg_ts
        for lv, b in zip(levels, bodies):
            dur = seg_dur * (b / total)
            half = dur / 2.0
            args = {k: lv[k] for k in
                    ("level", "generated", "distinct", "queue",
                     "bodies", "expanded") if k in lv}
            if pipeline:
                # staged schedule: commit of level k rides alongside the
                # NEXT level's expansion - draw commit shifted half a
                # span so the overlap is visible in the two lanes
                out.append({"name": f"expand L{lv['level']}", "ph": "X",
                            "ts": cursor, "dur": dur, "pid": PID_DEVICE,
                            "tid": TID_EXPAND, "args": args})
                out.append({"name": f"commit L{lv['level']}", "ph": "X",
                            "ts": cursor + half, "dur": dur,
                            "pid": PID_DEVICE, "tid": TID_COMMIT,
                            "args": args})
            else:
                out.append({"name": f"expand L{lv['level']}", "ph": "X",
                            "ts": cursor, "dur": half,
                            "pid": PID_DEVICE, "tid": TID_EXPAND,
                            "args": args})
                out.append({"name": f"commit L{lv['level']}", "ph": "X",
                            "ts": cursor + half, "dur": half,
                            "pid": PID_DEVICE, "tid": TID_COMMIT,
                            "args": args})
            out.append({"name": "states", "ph": "C",
                        "ts": cursor + dur, "pid": PID_DEVICE, "tid": 0,
                        "args": {"distinct": lv["distinct"],
                                 "queue": lv["queue"]}})
            if "fp_load" in lv:
                out.append({"name": "fp_load", "ph": "C",
                            "ts": cursor + dur, "pid": PID_DEVICE,
                            "tid": 0,
                            "args": {"load": lv["fp_load"]}})
            cursor += dur

    for ev in events:
        kind = ev["event"]
        h = ev.get("host") if kind in (
            "segment", "level", "phase", "checkpoint", "spill") else None
        if kind == "segment":
            ensure(h)
            flush_levels(h)
            last_segment[h] = ev
            out.append({
                "name": f"segment {ev['index']}", "ph": "X",
                "ts": us(ev["t_dispatch"]),
                "dur": max(ev["wall_s"] * 1e6, 1.0),
                "pid": pid_device(h), "tid": TID_SEGMENT,
                "args": {"index": ev["index"],
                         "wall_s": ev["wall_s"]},
            })
        elif kind == "level":
            prev = prev_level.get(h)
            if prev is not None and prev["level"] == ev["level"]:
                # empty-queue trailing flips re-record the final
                # level's (identical, cumulative) row each no-op step
                continue
            lv = dict(ev)
            # per-level body count from the cumulative counter
            lv["bodies_level"] = (
                ev["bodies"] - prev["bodies"]
                if prev is not None else ev["bodies"]
            )
            prev_level[h] = ev
            pending_levels.setdefault(h, []).append(lv)
        elif kind == "phase":
            if ev["scope"] == "level":
                pending_phases.setdefault(h, {}).setdefault(
                    ev["index"], {}
                )[ev["phase"]] = ev["wall_s"]
            elif ev["scope"] == "segment" and ev["phase"] == "readback":
                ensure(h)
                out.append({
                    "name": "readback", "ph": "X",
                    "ts": us(ev["t"] - ev["wall_s"]),
                    "dur": max(ev["wall_s"] * 1e6, 1.0),
                    "pid": pid_host(h), "tid": TID_CKPT,
                    "args": {"segment": ev["index"]},
                })
        elif kind == "checkpoint":
            ensure(h)
            out.append({
                "name": f"checkpoint ({ev['label']})", "ph": "X",
                "ts": us(ev["t"] - ev["seconds"]),
                "dur": max(ev["seconds"] * 1e6, 1.0),
                "pid": pid_host(h), "tid": TID_CKPT,
                "args": {"path": ev["path"]},
            })
        elif kind == "regrow":
            out.append({
                "name": f"regrow {ev['resource']}", "ph": "X",
                "ts": us(ev["t"] - ev["seconds"]),
                "dur": max(ev["seconds"] * 1e6, 1.0),
                "pid": PID_HOST, "tid": TID_REGROW,
                "args": {"old": ev["old"], "new": ev["new"],
                         "violation": ev["violation"]},
            })
        elif kind == "retry":
            instant(ev, f"retry #{ev['attempt']}",
                    {"error": ev["error"]})
        elif kind == "fault":
            instant(ev, f"fault {ev['kind']}@{ev['at']}")
        elif kind == "spill":
            # the host tier's lifecycle rides the regrow thread (both
            # are host-side capacity work); also a counter track so
            # Perfetto graphs the cold-tier growth.  Highwater flushes
            # carry their measured wall (ISSUE 20) and render as
            # DURATION slices, so the timeline shows what the sweep
            # cost at the fence that paid it
            ensure(h)
            if ev.get("phase") == "flush" and ev.get("wall_s"):
                out.append({
                    "name": "spill flush", "ph": "X",
                    "ts": us(ev["t"] - ev["wall_s"]),
                    "dur": max(ev["wall_s"] * 1e6, 1.0),
                    "pid": pid_host(h), "tid": TID_REGROW,
                    "args": {"spilled": ev["spilled"],
                             "flushed_tables": ev.get("flushed_tables"),
                             "wall_s": ev["wall_s"]},
                })
            else:
                instant(ev, f"spill {ev['phase']}",
                        {"spilled": ev["spilled"],
                         "hits": ev.get("hits"),
                         "probes": ev.get("probes")}, h=h)
            out.append({"name": "spilled_fps", "ph": "C",
                        "ts": us(ev["t"]), "pid": pid_host(h), "tid": 0,
                        "args": {"spilled": ev["spilled"]}})
        elif kind == "degrade":
            instant(ev, f"degrade [{ev['rung']}] {ev['resource']}",
                    {"action": ev["action"], "reason": ev["reason"]})
        elif kind == "exhausted":
            instant(ev, f"exhausted ({ev['resource']})",
                    {"checkpoint": ev["path"],
                     "distinct": ev["distinct"]})
        elif kind == "interrupted":
            instant(ev, f"interrupted (signal {ev['signum']})",
                    {"checkpoint": ev["path"]})
        elif kind in ("recovery", "run_resume"):
            instant(ev, kind, {"path": ev["path"]})
        elif kind == "final":
            instant(ev, f"final: {ev['verdict']}",
                    {"generated": ev["generated"],
                     "distinct": ev["distinct"],
                     "wall_s": ev["wall_s"]})
    for h in list(pending_levels):
        flush_levels(h)
    return out


def export_chrome_trace(events: List[dict], path: str) -> int:
    """Write the Perfetto-loadable JSON for `events` to `path` (fsync +
    rename, the checkpoint durability discipline).  Returns the number
    of trace events written."""
    from ..engine.checkpoint import fsync_replace

    trace = chrome_trace_events(events)
    doc = {"traceEvents": trace, "displayTimeUnit": "ms",
           "otherData": {"producer": "jaxtlc obs.trace"}}
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(doc, f)
        fsync_replace(tmp, path, f=f)
    return len(trace)


def _tiny_journal(path: str) -> None:
    """A synthetic but schema-valid journal exercising every event kind
    the exporter renders (the --tiny smoke's input)."""
    from .journal import RunJournal

    with RunJournal(path) as j:
        base = j.event("run_start", version="tiny", workload="FF",
                       engine="single", device="cpu",
                       params={"pipeline": True, "chunk": 128})["t"]
        for s in range(2):
            td = base + 0.1 * s
            j.event("segment", index=s, t_dispatch=td,
                    t_fence=td + 0.09, wall_s=0.09)
            j.event("phase", scope="segment", index=s, phase="device",
                    wall_s=0.09)
            j.event("phase", scope="segment", index=s, phase="readback",
                    wall_s=0.002)
            for i in range(2):
                lvl = 2 * s + i + 1
                # second segment: measured per-level walls (the
                # -phase-timing tier) so the exporter's measured-lane
                # path is exercised alongside the schematic one
                if s == 1:
                    j.event("phase", scope="level", index=lvl,
                            phase="expand", wall_s=0.03, bodies=2)
                    j.event("phase", scope="level", index=lvl,
                            phase="commit", wall_s=0.012, bodies=2)
                j.event("level", level=lvl, generated=100 * lvl,
                        distinct=60 * lvl, queue=30, bodies=4 * lvl,
                        expanded=50 * lvl, fp_load=0.01 * lvl)
            j.event("progress", depth=2 * s + 2, generated=200 * (s + 1),
                    distinct=120 * (s + 1), queue=30)
        j.event("checkpoint", path="ck.g000001.npz", seconds=0.004,
                label="periodic")
        j.event("regrow", resource="fp_capacity", old=1 << 11,
                new=1 << 12, violation="fpset full", seconds=0.01)
        j.event("degrade", rung="regrow", resource="fp_capacity",
                action="denied", reason="RESOURCE_EXHAUSTED (tiny)")
        j.event("spill", phase="activate", resident=240, spilled=0,
                capacity=1 << 12, hits=0, probes=0)
        j.event("spill", phase="flush", resident=0, spilled=240,
                capacity=1 << 12, hits=12, probes=60, wall_s=0.003,
                flushed_tables=1)
        j.event("retry", attempt=1, delay_s=0.01, error="injected")
        j.event("interrupted", signum=15, path=None, generated=400,
                distinct=240, queue=30, wall_s=0.2)
        j.event("final", verdict="interrupted", generated=400,
                distinct=240, depth=4, queue=30, wall_s=0.2,
                interrupted=True)


def main(argv=None) -> int:
    """CLI: `python -m jaxtlc.obs.trace JOURNAL [-o OUT]` exports a
    journal file; `--tiny` self-tests the whole pipeline on a synthetic
    journal (wired into tier-1, the profile_v4 --tiny pattern)."""
    import argparse
    import sys
    import tempfile

    from . import journal as jr

    p = argparse.ArgumentParser(prog="jaxtlc.obs.trace")
    p.add_argument("journal", nargs="?", help="run journal (JSONL)")
    p.add_argument("-o", "--out", default="", help="trace output path "
                   "(default: <journal>.trace.json)")
    p.add_argument("--tiny", action="store_true",
                   help="smoke: synthesize a journal, export it, "
                        "validate the result")
    args = p.parse_args(argv)
    if args.tiny:
        with tempfile.TemporaryDirectory() as d:
            jpath = os.path.join(d, "tiny.journal.jsonl")
            _tiny_journal(jpath)
            events = jr.read(jpath)
            out = args.out or os.path.join(d, "tiny.trace.json")
            n = export_chrome_trace(events, out)
            with open(out) as f:
                doc = json.load(f)
            assert doc["traceEvents"] and n == len(doc["traceEvents"])
            names = {e.get("name", "") for e in doc["traceEvents"]}
            assert any(s.startswith("expand L") for s in names)
            assert any(s.startswith("commit L") for s in names)
        print(f"trace-export tiny OK: {n} trace events "
              f"({len(events)} journal events)")
        return 0
    if not args.journal:
        p.error("journal path required (or --tiny)")
    events = jr.read(args.journal, validate=False)
    out = args.out or args.journal + ".trace.json"
    n = export_chrome_trace(events, out)
    print(f"wrote {n} trace events from {len(events)} journal events "
          f"to {out}")
    return 0


if __name__ == "__main__":  # pragma: no cover
    import sys

    sys.exit(main())
