"""Run-monitoring server: the live serving surface over the run journal.

TLC's value rests partly on its always-on reporting (the reference
MC.out is 1108 lines of live progress); ours was post-hoc only - the
journal had to be read after the fact.  This module is the front door
of the checking-as-a-service direction (ROADMAP #4): a stdlib-only HTTP
server over a journal file or a directory of them, serving

* ``/metrics`` - Prometheus text format (states/s, distinct, fp load,
  spill occupancy/hit-rate, queue-drain ETA, per-phase walls) derived
  by obs.views.metrics_from_events - the SAME arithmetic as the TLC
  2200 line and tlcstat, so a scrape cannot disagree with the
  transcript;
* ``/events`` - Server-Sent-Events tail of the journal (one ``data:``
  line per event).  Because `-recover` APPENDS to the same journal
  file, a subscriber that spans a SIGTERM + resume sees ONE continuous
  stream: run_start ... interrupted ... run_resume ... final.  A torn
  trailing line (the crash window) is held back until it completes;
  ``?once=1`` dumps the current events and closes;
* ``/runs`` - the run registry: every ``*.journal.jsonl`` under the
  root, with workload/engine/verdict summary - many concurrent runs
  multiplex through one server (``?run=NAME`` selects on the other
  endpoints).  A multi-host pod's per-host journals
  (``{base}.h{pid}.journal.jsonl``, jaxtlc.dist) are GROUPED into one
  registry row (``run={base}``, ``pod_hosts=N``); selecting that row
  serves the N journals merged into one time-ordered stream on
  /metrics /journal /events, so a pod reads like a single run;
* ``/journal`` - the raw JSONL (tools/tlcstat.py --connect renders its
  dashboard from this, a remote client of the same views).

Wiring: ``python -m jaxtlc.obs.serve DIR_OR_JOURNAL [--port N]``
standalone, or CLI ``-serve PORT`` to serve the live run's journal.
The server is read-only over files the run appends+fsyncs per event,
so it never blocks the writer.  The /events tail re-reads the file per
poll - O(file) per tick, fine for the journal sizes a run produces;
a seek-based tail is the upgrade path if journals grow past that.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

from . import journal as jr
from .views import merge_journals, metrics_from_events

JOURNAL_SUFFIX = ".journal.jsonl"
POLL_S = 0.2


# /runs registry cache: journal scans keyed on (path, mtime, size) so
# a poll over a directory of mostly-idle runs rescans only the files
# that actually changed (ISSUE 11 satellite; the old code re-read every
# journal per request).  Rows are immutable snapshots; entries for
# vanished files are dropped on the next scan.
_RUNS_CACHE: dict = {}
_RUNS_CACHE_LOCK = threading.Lock()


def _run_row(p: str) -> Optional[dict]:
    try:
        st = os.stat(p)
    except OSError:
        return None
    key = (st.st_mtime_ns, st.st_size)
    with _RUNS_CACHE_LOCK:
        hit = _RUNS_CACHE.get(p)
        if hit is not None and hit[0] == key:
            return hit[1]
    try:
        events = jr.read(p, validate=False)
    except OSError:
        return None
    manifest = next(
        (e for e in events if e["event"] == "run_start"), None
    )
    fin = next(
        (e for e in reversed(events) if e["event"] == "final"), None
    )
    # coverage saturation (ISSUE 20 satellite): the journal's
    # once-per-run saturation event (PR 11), surfaced on the registry
    # row so /runs answers "did coverage plateau" without a re-read -
    # `coverage` marks journals that carry the plane at all, so a pod
    # row can distinguish "no plane" from "not yet saturated"
    cov_evs = [e for e in events if e["event"] == "coverage"]
    sat = next((e for e in reversed(cov_evs) if e.get("saturated")),
               None)
    row = {
        "run": os.path.basename(p)[: -len(JOURNAL_SUFFIX)]
        if p.endswith(JOURNAL_SUFFIX) else os.path.basename(p),
        "path": p,
        "events": len(events),
        "workload": manifest["workload"] if manifest else None,
        "engine": manifest["engine"] if manifest else None,
        "verdict": fin["verdict"] if fin else "running",
        "last_t": events[-1]["t"] if events else None,
        "resumes": sum(
            1 for e in events if e["event"] == "run_resume"
        ),
        "coverage": bool(cov_evs),
        "coverage_saturated": sat is not None,
        "coverage_saturated_level": (sat.get("level")
                                     if sat is not None else None),
    }
    with _RUNS_CACHE_LOCK:
        _RUNS_CACHE[p] = (key, row)
    return row


# per-host pod journal names: {base}.h{pid}.journal.jsonl (jaxtlc.dist)
_POD_HOST_RE = re.compile(r"^(?P<base>.+)\.h(?P<host>\d+)$")

# worst verdict wins when a pod's hosts disagree (one host's violation
# outranks the others' ok; a still-running host outranks finished ok)
_VERDICT_RANK = {"ok": 0, "running": 1, "interrupted": 2,
                 "exhausted": 3, "error": 4, "violation": 5}


def _group_pod_rows(rows: List[dict]) -> List[dict]:
    """Collapse per-host pod journal rows into one row per pod run.

    Hosts of the same run share everything but their shard, so the
    merged row sums events/resumes, takes the newest last_t, and keeps
    the worst verdict; `paths` (host order) lets the other endpoints
    serve the journals merged into one stream."""
    out, pods = [], {}
    for r in rows:
        m = _POD_HOST_RE.match(r["run"])
        if m:
            pods.setdefault(m.group("base"), []).append(
                (int(m.group("host")), r))
        else:
            out.append(r)
    for base, members in pods.items():
        members.sort()
        hrows = [r for _, r in members]
        # pod saturation: every host's coverage is a disjoint
        # fingerprint shard, so the POD has plateaued only when EVERY
        # covered host carried its once-per-run saturation event; the
        # level reported is the last (max) host level to plateau
        covered = [r for r in hrows if r.get("coverage")]
        saturated = bool(covered) and all(
            r.get("coverage_saturated") for r in covered)
        out.append({
            "run": base,
            "path": hrows[0]["path"],
            "paths": [r["path"] for r in hrows],
            "pod_hosts": len(hrows),
            "events": sum(r["events"] for r in hrows),
            "workload": hrows[0]["workload"],
            "engine": hrows[0]["engine"],
            "verdict": max((r["verdict"] for r in hrows),
                           key=lambda v: _VERDICT_RANK.get(v, 4)),
            "last_t": max((r["last_t"] or 0 for r in hrows)) or None,
            "resumes": sum(r["resumes"] for r in hrows),
            "coverage": bool(covered),
            "coverage_saturated": saturated,
            "coverage_saturated_level": (max(
                (r.get("coverage_saturated_level") or 0
                 for r in covered), default=0) or None
                if saturated else None),
        })
    return out


def _runs(root: str) -> List[dict]:
    """The run registry: one row per journal under `root` (or the row
    of `root` itself when it IS a journal file), newest first.  Scans
    are cached by (path, mtime, size) - unchanged journals cost one
    stat per request, not a full re-read.  Per-host pod journals are
    grouped into one row per pod (_group_pod_rows)."""
    paths = []
    if os.path.isdir(root):
        for name in sorted(os.listdir(root)):
            if name.endswith(JOURNAL_SUFFIX):
                paths.append(os.path.join(root, name))
    elif os.path.exists(root):
        paths = [root]
    rows = [r for r in (_run_row(p) for p in paths) if r is not None]
    with _RUNS_CACHE_LOCK:
        for stale in set(_RUNS_CACHE) - set(paths):
            if os.path.dirname(stale) == (root if os.path.isdir(root)
                                          else os.path.dirname(root)):
                _RUNS_CACHE.pop(stale, None)
    rows = _group_pod_rows(rows)
    rows.sort(key=lambda r: r["last_t"] or 0, reverse=True)
    return rows


def _row_events(row: dict) -> List[dict]:
    """Read a registry row's events - one journal, or a pod's per-host
    journals k-way merged into one time-ordered stream."""
    paths = row.get("paths") or [row["path"]]
    if len(paths) == 1:
        return jr.read(paths[0], validate=False)
    return merge_journals(*(jr.read(p, validate=False) for p in paths))


def prometheus_text(metrics: dict) -> str:
    """Render the metrics_from_events dict as Prometheus exposition
    text (flat gauges, one info-style labeled gauge, one labeled gauge
    per measured phase)."""
    lines = []
    info = metrics.get("run_info", {})
    labels = ",".join(
        f'{k}="{v}"' for k, v in sorted(info.items()) if v is not None
    )
    lines.append("# HELP jaxtlc_run_info run manifest + verdict labels")
    lines.append("# TYPE jaxtlc_run_info gauge")
    lines.append(f"jaxtlc_run_info{{{labels}}} 1")
    for key, val in sorted(metrics.items()):
        if key == "run_info":
            continue
        if key == "phase_wall_seconds":
            lines.append("# TYPE jaxtlc_phase_wall_seconds counter")
            for phase, secs in sorted(val.items()):
                lines.append(
                    f'jaxtlc_phase_wall_seconds{{phase="{phase}"}} '
                    f"{secs}"
                )
            continue
        if key == "pod_host_rates":
            # per-host per-level rates (ISSUE 20): the same figures as
            # jaxtlc_states_per_second, computed from each host's RAW
            # partial level rows - so a scrape sees the pod rate both
            # without (folded) and with host labels
            lines.append("# HELP jaxtlc_host_states_per_second "
                         "per-host per-level state rates")
            for host, gauges in sorted(val.items()):
                for gk, gv in sorted(gauges.items()):
                    lines.append(
                        f'jaxtlc_host_{gk}{{host="{host}"}} {gv}'
                    )
            continue
        if key == "pod_hosts":
            # per-host pod gauges (jaxtlc.dist): shard-table load,
            # spill-store bytes, level-fence exchange wall
            lines.append("# HELP jaxtlc_host_shard_occupancy per-host "
                         "fingerprint-table load fraction")
            for host, gauges in sorted(val.items()):
                for gk, gv in sorted(gauges.items()):
                    lines.append(
                        f'jaxtlc_host_{gk}{{host="{host}"}} {gv}'
                    )
            continue
        if key == "coverage_sites":
            # the device coverage plane's per-site counters (ISSUE 11)
            lines.append("# HELP jaxtlc_coverage_site_total cumulative "
                         "visits per coverage site")
            lines.append("# TYPE jaxtlc_coverage_site_total counter")
            for site, n in sorted(val.items()):
                lines.append(
                    f'jaxtlc_coverage_site_total{{site="{site}"}} {n}'
                )
            continue
        lines.append(f"jaxtlc_{key} {val}")
    return "\n".join(lines) + "\n"


class _JournalTail:
    """Seek-position tail over an append-only journal (ISSUE 11
    satellite: the /events SSE poll used to re-read the WHOLE file per
    tick - O(file) per poll; this reads only the bytes appended since
    the last complete line).  The torn-trailing-line contract is
    preserved: a line without its newline yet (the writer's crash
    window) is buffered and held back until the writer completes it, so
    a subscriber never sees a partial event and never sees one twice.
    A file that shrank (recreated journal) resets the tail."""

    def __init__(self, path: str):
        self.path = path
        self.pos = 0  # file offset of the next unread byte
        self._buf = b""  # held-back torn trailing line

    def poll(self) -> List[dict]:
        """Complete events appended since the last poll."""
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return []
        if size < self.pos:
            self.pos = 0
            self._buf = b""
        if size == self.pos:
            return []
        try:
            with open(self.path, "rb") as f:
                f.seek(self.pos)
                chunk = f.read()
        except OSError:
            return []
        self.pos += len(chunk)
        lines = (self._buf + chunk).split(b"\n")
        self._buf = lines[-1]  # b"" after a complete trailing newline
        out = []
        for ln in lines[:-1]:
            if not ln.strip():
                continue
            try:
                out.append(json.loads(ln.decode("utf-8")))
            except (json.JSONDecodeError, UnicodeDecodeError):
                continue  # defensive: skip an unparseable mid-file line
        return out


class _Handler(BaseHTTPRequestHandler):
    # the owning OpsServer stamps these class-wide at construction
    root: str = "."
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # quiet: the run owns stdout
        pass

    # -- helpers ---------------------------------------------------------

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _journal_row(self, qs: dict) -> Optional[dict]:
        """Resolve ?run=NAME against the registry (default: the most
        recently appended run).  A pod row carries `paths` - all its
        per-host journals; NAME matches the pod base or any member."""
        rows = _runs(self.root)
        want = qs.get("run", [None])[0]
        if want is None:
            return rows[0] if rows else None
        for r in rows:
            if (r["run"] == want or r["path"] == want
                    or want in r.get("paths", ())):
                return r
        return None

    # -- endpoints -------------------------------------------------------

    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler contract
        parsed = urllib.parse.urlparse(self.path)
        qs = urllib.parse.parse_qs(parsed.query)
        route = parsed.path.rstrip("/") or "/"
        try:
            if route == "/runs":
                self._send(200, json.dumps(
                    {"runs": _runs(self.root)}
                ).encode(), "application/json")
            elif route == "/metrics":
                row = self._journal_row(qs)
                if row is None:
                    self._send(404, b"no journal\n", "text/plain")
                    return
                events = _row_events(row)
                self._send(
                    200,
                    prometheus_text(metrics_from_events(events)).encode(),
                    "text/plain; version=0.0.4",
                )
            elif route == "/journal":
                row = self._journal_row(qs)
                if row is None:
                    self._send(404, b"no journal\n", "text/plain")
                    return
                events = _row_events(row)
                body = "".join(
                    json.dumps(e, sort_keys=True) + "\n" for e in events
                ).encode()
                self._send(200, body, "application/x-ndjson")
            elif route == "/coverage":
                # live device coverage: cumulative per-site totals,
                # derived from the journal's `coverage` delta events
                # (the same fold the Prometheus counters render)
                row = self._journal_row(qs)
                if row is None:
                    self._send(404, b"no journal\n", "text/plain")
                    return
                from .coverage import coverage_from_events

                events = _row_events(row)
                cov = coverage_from_events(events)
                if cov is None:
                    self._send(404, b"run has no coverage plane\n",
                               "text/plain")
                    return
                self._send(200, json.dumps(cov).encode(),
                           "application/json")
            elif route == "/cache":
                # incremental re-checking (ISSUE 13): the process
                # artifact store's counters + content listing.  In a
                # serving/run process this is the store its checks use;
                # a standalone monitor reports the default store on
                # disk (the same files cachectl ls shows)
                from ..struct.artifacts import get_store

                store = get_store()
                if store is None:
                    body = json.dumps({"enabled": False}).encode()
                else:
                    body = json.dumps({
                        "enabled": True,
                        "stats": store.stats(),
                        "entries": store.ls(),
                    }).encode()
                self._send(200, body, "application/json")
            elif route == "/events":
                self._events(qs)
            elif route == "/":
                body = (
                    "jaxtlc run monitor\n"
                    "  /runs     run registry (JSON)\n"
                    "  /metrics  Prometheus text   [?run=NAME]\n"
                    "  /coverage live per-site coverage [?run=NAME]\n"
                    "  /cache    artifact-cache stats + contents\n"
                    "  /events   SSE journal tail  [?run=NAME]"
                    "[&once=1][&since=N]\n"
                    "  /journal  raw JSONL         [?run=NAME]\n"
                ).encode()
                self._send(200, body, "text/plain")
            else:
                self._send(404, b"unknown endpoint\n", "text/plain")
        except (BrokenPipeError, ConnectionResetError):
            pass  # subscriber went away mid-write: their call

    def _events(self, qs: dict) -> None:
        """SSE tail: emit every complete journal line, then poll for
        appends with a SEEK-POSITION tail (_JournalTail) - each tick
        reads only the appended bytes, not the whole file, and a torn
        trailing line is held back until the writer completes it, so a
        subscriber never sees a partial event (and never sees it
        twice).  The stream survives the writer's interrupt+`-recover`
        because resume APPENDS to the same file - one continuous
        stream per logical run.  A pod run tails EVERY per-host journal
        and merges each tick's batch by timestamp - one stream for the
        whole pod (cross-tick ordering is arrival order, the same
        best-effort a scrape of live files can ever give)."""
        row = self._journal_row(qs)
        if row is None:
            self._send(404, b"no journal\n", "text/plain")
            return
        once = qs.get("once", ["0"])[0] not in ("0", "")
        skip = int(qs.get("since", ["0"])[0])
        self.send_response(200)
        self.send_header("Content-Type", "text/event-stream")
        self.send_header("Cache-Control", "no-cache")
        # SSE is an unbounded stream: no Content-Length, close delimits
        self.send_header("Connection", "close")
        self.end_headers()
        tails = [_JournalTail(p)
                 for p in (row.get("paths") or [row["path"]])]
        emitted = 0
        while not self.server._jaxtlc_shutdown.is_set():
            batch = merge_journals(*(t.poll() for t in tails))
            wrote = False
            for ev in batch:
                emitted += 1
                if emitted <= skip:
                    continue
                data = json.dumps(ev, sort_keys=True)
                self.wfile.write(f"data: {data}\n\n".encode())
                wrote = True
            if wrote:
                self.wfile.flush()
            if once:
                return
            time.sleep(POLL_S)


class OpsServer:
    """A running monitor server (daemon-threaded).  `port=0` binds an
    ephemeral port; read it back from `.port`."""

    def __init__(self, root: str, port: int = 0,
                 host: str = "127.0.0.1"):
        handler = type("BoundHandler", (_Handler,), {"root": root})
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.httpd._jaxtlc_shutdown = threading.Event()
        self.httpd.daemon_threads = True
        self.root = root
        self.host = host
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def shutdown(self) -> None:
        self.httpd._jaxtlc_shutdown.set()
        self.httpd.shutdown()
        self.httpd.server_close()


def start_server(root: str, port: int = 0,
                 host: str = "127.0.0.1") -> OpsServer:
    """Start a monitor server over `root` (a journal file or a
    directory of them).  Returns the running OpsServer."""
    return OpsServer(root, port=port, host=host)


def _http_get(url: str, timeout: float = 5.0) -> str:
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode()


def _tiny() -> int:
    """Smoke the whole serving pipeline on a synthetic journal: start a
    server, hit every endpoint with stdlib urllib, assert the derived
    views landed (wired into tier-1; no engine, no jax)."""
    import tempfile

    from .trace import _tiny_journal

    with tempfile.TemporaryDirectory() as d:
        jpath = os.path.join(d, "tiny.journal.jsonl")
        _tiny_journal(jpath)
        srv = start_server(d)
        try:
            runs = json.loads(_http_get(srv.url + "/runs"))["runs"]
            assert len(runs) == 1 and runs[0]["run"] == "tiny", runs
            assert runs[0]["verdict"] == "interrupted", runs
            metrics = _http_get(srv.url + "/metrics")
            for needle in ("jaxtlc_run_info", "jaxtlc_generated_total",
                           "jaxtlc_distinct_total",
                           "jaxtlc_spill_occupancy",
                           "jaxtlc_phase_wall_seconds{phase="):
                assert needle in metrics, (needle, metrics)
            sse = _http_get(srv.url + "/events?once=1&run=tiny")
            datas = [ln for ln in sse.splitlines()
                     if ln.startswith("data: ")]
            events = jr.read(jpath, validate=False)
            assert len(datas) == len(events), (len(datas), len(events))
            assert '"event": "final"' in datas[-1]
            raw = _http_get(srv.url + "/journal")
            assert len(raw.splitlines()) == len(events)
        finally:
            srv.shutdown()
    print(f"serve tiny OK: {len(events)} events served on "
          f"/runs /metrics /events /journal")
    return 0


def main(argv=None) -> int:
    """CLI: ``python -m jaxtlc.obs.serve DIR_OR_JOURNAL [--port N]``."""
    import argparse

    p = argparse.ArgumentParser(prog="jaxtlc.obs.serve")
    p.add_argument("root", nargs="?",
                   help="journal file or a directory of *.journal.jsonl")
    p.add_argument("--port", type=int, default=8790)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--tiny", action="store_true",
                   help="smoke: serve a synthetic journal end-to-end "
                        "(no engine run; wired into tier-1)")
    args = p.parse_args(argv)
    if args.tiny:
        return _tiny()
    if not args.root:
        p.error("root path required (or --tiny)")
    srv = start_server(args.root, port=args.port, host=args.host)
    print(f"jaxtlc monitor serving {args.root!r} at {srv.url} "
          "(/runs /metrics /events /journal; ctrl-c exits)")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        srv.shutdown()
        return 0


if __name__ == "__main__":  # pragma: no cover
    import sys

    sys.exit(main())
