"""Versioned schema of the run journal (the telemetry contract).

Every line of a run journal (obs.journal.RunJournal) is one JSON event
validated against this module BEFORE it is written, and the tier-1
golden test re-validates every line of a real run's journal after the
fact - so event-shape drift is a loud failure in both directions
(producer and consumer), never a silently-changed dashboard.

The schema is deliberately dependency-free (no jsonschema package in
the image): each event kind declares its REQUIRED fields with python
type tuples; extra fields are allowed (views ignore what they don't
know), missing/badly-typed required fields raise JournalSchemaError.

Bump SCHEMA_VERSION whenever a required field is added, removed, or
changes meaning; readers (tools/tlcstat.py, obs.trace) check it and
refuse journals from the future.
"""

from __future__ import annotations

SCHEMA_VERSION = 1

_NUM = (int, float)
_STR = (str,)
_BOOL = (bool,)
_OPT_STR = (str, type(None))
_OPT_NUM = (int, float, type(None))

# common envelope fields stamped by RunJournal.event on every line
ENVELOPE = {
    "v": (int,),  # SCHEMA_VERSION of the writer
    "t": _NUM,  # host wall-clock (epoch seconds) at write time
    "event": _STR,  # the event kind (a key of EVENTS)
}

# event kind -> {required field: accepted types}
EVENTS = {
    # -- run lifecycle -----------------------------------------------------
    # the run manifest: first event of a fresh journal
    "run_start": {"version": _STR, "workload": _STR, "engine": _STR,
                  "device": _STR, "params": (dict,)},
    # -recover appended to an existing journal (one continuous history)
    "run_resume": {"version": _STR, "path": _STR},
    # one supervised segment fenced: host-observed dispatch/fence times
    "segment": {"index": _NUM, "t_dispatch": _NUM, "t_fence": _NUM,
                "wall_s": _NUM},
    # one BFS level completed (decoded from the device counter ring).
    # Pod journals (jaxtlc.dist, ISSUE 20) tag these with an extra
    # `host` field and PARTIAL counters - each host decodes its own
    # ring, and obs.views.fold_pod_levels sums the {base}.hN siblings
    # back to pod-global rows (last row per (host, level) wins: the
    # ring re-records the final level on empty-queue trailing steps)
    "level": {"level": _NUM, "generated": _NUM, "distinct": _NUM,
              "queue": _NUM, "bodies": _NUM, "expanded": _NUM},
    # the TLC 2200 Progress-line source (segment-boundary counters)
    "progress": {"depth": _NUM, "generated": _NUM, "distinct": _NUM,
                 "queue": _NUM},
    # -- resilience --------------------------------------------------------
    "checkpoint": {"path": _STR, "seconds": _NUM, "label": _STR},
    "ckpt_write_failed": {"error": _STR},
    "ckpt_fallback": {"path": _STR, "error": _STR},
    "recovery": {"path": _STR, "depth": _NUM, "generated": _NUM,
                 "distinct": _NUM, "queue": _NUM},
    "regrow": {"resource": _STR, "old": _NUM, "new": _NUM,
               "violation": _STR, "seconds": _NUM},
    "retry": {"attempt": _NUM, "delay_s": _NUM, "error": _STR},
    "fault": {"kind": _STR, "at": _NUM},
    "interrupted": {"signum": _OPT_NUM, "path": _OPT_STR,
                    "generated": _NUM, "distinct": _NUM, "queue": _NUM,
                    "wall_s": _NUM},
    # one per degradation-ladder transition (resil.supervisor): rung in
    # ("regrow", "spill", "shrink", "oom", "halt")
    "degrade": {"rung": _STR, "resource": _STR, "action": _STR,
                "reason": _STR},
    # host spill tier lifecycle (engine.spill): phase in
    # ("activate", "flush"); resident = device-tier occupancy after,
    # spilled = host-store count, hits/probes = cumulative host traffic
    "spill": {"phase": _STR, "resident": _NUM, "spilled": _NUM,
              "capacity": _NUM, "hits": _NUM, "probes": _NUM},
    # ladder rung 4: capacity unrecoverable, final checkpoint written
    # (or path None = progress kept only in this journal), resume me
    "exhausted": {"resource": _STR, "path": _OPT_STR,
                  "generated": _NUM, "distinct": _NUM, "queue": _NUM,
                  "wall_s": _NUM},
    # -- verdicts ----------------------------------------------------------
    "violation": {"code": _NUM, "name": _STR},
    # the structured final event: EVERY run (clean, violated, interrupted,
    # progress-lost) ends its journal with exactly one of these
    "final": {"verdict": _STR, "generated": _NUM, "distinct": _NUM,
              "depth": _NUM, "queue": _NUM, "wall_s": _NUM,
              "interrupted": _BOOL},
    # -- device coverage plane (obs.coverage, ISSUE 11) --------------------
    # one per segment fence with coverage movement: nonzero per-site
    # visit DELTAS since the previous event (cumulative totals are the
    # fold of all deltas - obs.coverage.coverage_from_events), plus the
    # visited-site header.  An event with saturated=true (extra field)
    # is the "no new site for N levels" signal.  Pod journals carry a
    # `host` field with per-host partial deltas; coverage_from_events
    # folds siblings into one summed site table (visited/saturation
    # recomputed from the folded totals)
    "coverage": {"visited": _NUM, "sites": _NUM, "delta": (dict,)},
    # -- phase attribution (obs.phases) ------------------------------------
    # one measured wall per (scope, index, phase): scope "segment" rows
    # come free at the fences the supervisor already pays (phase
    # "device"/"readback"), scope "level" rows from the -phase-timing
    # fenced step loop (phase "expand"/"commit", measured walls the
    # trace exporter renders instead of its schematic lanes), scope
    # "chunk" from the spill runtime's host-driven loop
    "phase": {"scope": _STR, "index": _NUM, "phase": _STR,
              "wall_s": _NUM},
    # -- preflight analysis (jaxtlc.analysis) ------------------------------
    # one event per finding, severity in ("error", "warning", "info")
    "analysis": {"layer": _STR, "check": _STR, "severity": _STR,
                 "subject": _STR, "detail": _STR},
    # one per preflight run: the banner-level totals
    "analysis_summary": {"name": _STR, "findings": _NUM,
                         "errors": _NUM, "warnings": _NUM,
                         "wall_s": _NUM},
    # -- incremental re-checking (struct.artifacts, ISSUE 13) --------------
    # one per artifact-cache decision: tier in ("verdict", "reach"),
    # outcome in ("hit", "miss", "write", "bypass", "skip", "corrupt"),
    # key = the content-address digest.  A "hit" on the verdict tier
    # means the run's result was replayed from the cache (no engine was
    # built); on the reach tier it means BFS was skipped and only the
    # invariants were re-evaluated over the stored reachable set
    "cache": {"tier": _STR, "outcome": _STR, "key": _STR},
    # -- simulation tier (jaxtlc.sim, ISSUE 14) ----------------------------
    # phase in ("progress", "summary", "replay"): progress rows at the
    # supervised driver's segment fences, one summary per run (extra
    # fields: seed, distinct_est, fp_saturated, halted, depth_hist - a
    # [steps, lanes] histogram of final walk depths), and one replay
    # row when a violating lane was re-walked host-side (extra fields:
    # lane, violation).  `steps` is the walk cursor, `transitions` the
    # cumulative transitions taken across all lanes
    "sim": {"phase": _STR, "walkers": _NUM, "depth": _NUM,
            "steps": _NUM, "transitions": _NUM},
    # one inference progress row: a filter round (phase "round", extra
    # fields: round, evidence, n_states) or the run summary (phase
    # "summary", extra fields: certified_names, evidence, n_states,
    # dropped).  `candidates` is the conjectured pool size, `killed`
    # the cumulative evidence refutations, `certified` the survivors
    # with a machine-checked inductive basis
    "infer": {"phase": _STR, "candidates": _NUM, "killed": _NUM,
              "survivors": _NUM, "certified": _NUM},
    # -- state-space reduction (engine.reduce, ISSUE 18) -------------------
    # one per reduced run, before the final event: what the symmetry/
    # POR reduction bought.  states_pruned = transitions the singleton
    # ample sets cut pre-dedup, ample_hit_rate = pruned/(generated+
    # pruned), orbit_factor = the group order (product of |S|! over
    # the reduced sets; 1 = symmetry off or no realisable set).  Extra
    # fields: symmetry/por (resolved bools), symmetric_sets,
    # dropped_sets, safe_actions
    "reduce": {"states_pruned": _NUM, "ample_hit_rate": _NUM,
               "orbit_factor": _NUM, "generated": _NUM,
               "distinct": _NUM},
    # -- multi-host pods (jaxtlc.dist, ISSUE 19) ---------------------------
    # host membership + per-host shard telemetry on the writing HOST's
    # journal: phase in ("join", "leave", "reshard", "stats"); host =
    # the jax process index, hosts = pod width at the event.  "stats"
    # rows carry the per-host gauges obs.views surfaces as
    # jaxtlc_host_* (extra fields: shard_occupancy, spill_bytes,
    # exchange_us); "leave" rows carry the checkpoint path; "reshard"
    # rows carry old_hosts/new_hosts
    "pod": {"phase": _STR, "host": _NUM, "hosts": _NUM},
    # -- serve-plane scheduling (serve.scheduler, ISSUE 17) ----------------
    # one per scheduler decision, written to the scheduler's own
    # journal (root/sched.journal.jsonl): action in ("admit", "reject",
    # "expire", "preempt", "requeue", "retry", "quarantine", "cancel",
    # "dispatch").  Extra fields carry the decision's facts (tenant,
    # priority, reason, retry_after_s, queued = queue depth after)
    "sched": {"action": _STR, "job": _STR},
    # -- derived artifacts -------------------------------------------------
    "trace_export": {"path": _STR, "events": _NUM},
    # one bench.py metric payload (the BENCH_*.json line contract)
    "bench_metric": {"metric": _STR, "value": _NUM, "unit": _STR,
                     "vs_baseline": _NUM},
}

# the verdict vocabulary of the "final" event.  The last three are
# scheduler-terminal verdicts (ISSUE 17): a job that never got (or
# never finished) an engine run still ends its journal with exactly one
# final event - deadline-expired, client-canceled, or breaker-
# quarantined - so SSE followers terminate on every outcome
VERDICTS = ("ok", "violation", "liveness_violation", "interrupted",
            "exhausted", "error", "expired", "canceled", "quarantined")


class JournalSchemaError(ValueError):
    """A journal event does not satisfy the versioned schema."""


def validate_event(ev: dict) -> dict:
    """Validate one journal event dict; returns it unchanged on success.

    Checks the envelope (v/t/event), that the kind is known, and that
    every required field of the kind is present with an accepted type.
    Extra fields pass - views ignore what they don't know."""
    if not isinstance(ev, dict):
        raise JournalSchemaError(f"event is not an object: {ev!r}")
    for field, types in ENVELOPE.items():
        if field not in ev:
            raise JournalSchemaError(f"event missing envelope {field!r}: {ev!r}")
        if not isinstance(ev[field], types) or isinstance(ev[field], bool):
            # bool is an int subclass; envelope fields are never bools
            raise JournalSchemaError(
                f"envelope {field!r} has type {type(ev[field]).__name__}, "
                f"want one of {[t.__name__ for t in types]}: {ev!r}"
            )
    if ev["v"] > SCHEMA_VERSION:
        raise JournalSchemaError(
            f"journal schema v{ev['v']} is newer than this reader "
            f"(v{SCHEMA_VERSION})"
        )
    kind = ev["event"]
    spec = EVENTS.get(kind)
    if spec is None:
        raise JournalSchemaError(f"unknown event kind {kind!r}: {ev!r}")
    for field, types in spec.items():
        if field not in ev:
            raise JournalSchemaError(
                f"{kind!r} event missing required field {field!r}: {ev!r}"
            )
        v = ev[field]
        if isinstance(v, bool) and bool not in types:
            raise JournalSchemaError(
                f"{kind!r} field {field!r} is bool, want "
                f"{[t.__name__ for t in types]}: {ev!r}"
            )
        if not isinstance(v, types):
            raise JournalSchemaError(
                f"{kind!r} field {field!r} has type {type(v).__name__}, "
                f"want one of {[t.__name__ for t in types]}: {ev!r}"
            )
    if kind == "final" and ev["verdict"] not in VERDICTS:
        raise JournalSchemaError(
            f"final verdict {ev['verdict']!r} not in {VERDICTS}"
        )
    return ev
