"""Derived views of the run journal.

The journal (obs.journal) is the single source of truth for run
telemetry; everything user-facing renders FROM it:

* `render_tlc_event` - the TLC structured-log banners (2200 Progress,
  2195 checkpoint, 2196 recovery, 2198 regrow, ...) as a pure function
  of one journal event, used by the CLI's supervisor hook.  The 2200
  line's per-minute rates come from io.tlc_log's stored previous
  progress report, exactly as TLC computes them.
* `interval_rates` - the shared rate arithmetic (states/min between two
  observations), used by TLCLog and tools/tlcstat.py alike so the
  progress line and the dashboard can never disagree.
* `bench_payload` - the BENCH_*.json line contract: every bench.py
  payload is stamped through a journal as a `bench_metric` event, so
  the required metric/unit/vs_baseline fields are schema-enforced at
  emit time instead of by reviewer eyeball.
* `eta_s` - queue-drain ETA from the two most recent observations.
"""

from __future__ import annotations

from typing import Optional, Tuple

from .journal import RunJournal


def merge_journals(*streams):
    """Fold per-host pod journals (jaxtlc.dist writes one
    ``{base}.h{pid}.journal.jsonl`` per process) into ONE time-ordered
    event stream.  Each journal is append-ordered by its own `t`
    stamps, so this is a k-way sorted merge; ties keep input order
    (host-major), preserving every host's internal event order.  The
    serve plane's /runs registry uses it to present a pod as one
    logical run."""
    import heapq

    return list(heapq.merge(*streams, key=lambda e: e.get("t", 0)))


def pod_sibling_journals(path):
    """All ``{base}.hN.journal.jsonl`` siblings of `path` on disk
    (host-ordered), or ``[path]`` when it is not a per-host pod
    journal - so a CLI pointed at ANY one host's journal (tlcstat,
    covdiff) can render the whole pod merged."""
    import os
    import re

    m = re.match(r"^(?P<base>.+)\.h\d+\.journal\.jsonl$",
                 os.path.basename(path))
    if not m:
        return [path]
    d = os.path.dirname(os.path.abspath(path))
    pat = re.compile(re.escape(m.group("base"))
                     + r"\.h(\d+)\.journal\.jsonl$")
    out = {}
    for name in os.listdir(d):
        mm = pat.fullmatch(name)
        if mm:
            out[int(mm.group(1))] = os.path.join(d, name)
    return [out[k] for k in sorted(out)] or [path]


def fold_pod_levels(events):
    """Fold per-host PARTIAL ``level`` rows (jaxtlc.dist pods tag each
    with a ``host`` field, decoded from that process's ring rows only)
    into pod-global per-level rows: devices flip levels in lock-step
    (the level fence is a global psum), so the rows of every host at
    one level describe the SAME level with per-host partial cumulative
    counters - sum them, exactly shard_rows_from_ring's arithmetic
    lifted to the journal tier.  fp_load sums too (each host's load is
    its partial over the GLOBAL pod capacity); sticky flags OR; the
    action dicts add; `t` keeps the latest host stamp.  Journals with
    no host-tagged level rows pass through unchanged, so every
    single-process surface is untouched.

    Each host contributes AT MOST ONE row per level: the ring flips
    once per chunk step while the queue stays empty, so the final
    segment of a finished run re-records the last level's row on every
    no-op step - cumulative counters make those rows identical, and
    the LAST one per (host, level) is the authoritative partial."""
    host_levels = [e for e in events
                   if e.get("event") == "level" and "host" in e]
    if not host_levels:
        return events
    last: dict = {}  # (host, level) -> the host's final row for it
    for e in host_levels:
        last[(e["host"], int(e["level"]))] = e
    by_level: dict = {}
    for (_h, lv), e in sorted(last.items(),
                              key=lambda kv: (kv[0][1], kv[0][0])):
        g = by_level.setdefault(lv, {
            "event": "level", "t": e.get("t", 0), "level": lv,
            "generated": 0, "distinct": 0, "queue": 0,
            "bodies": 0, "expanded": 0,
        })
        g["t"] = max(g["t"], e.get("t", 0))
        for k in ("generated", "distinct", "queue", "bodies",
                  "expanded", "spill_hits"):
            if k in e:
                g[k] = g.get(k, 0) + int(e[k])
        if "fp_load" in e:
            g["fp_load"] = round(g.get("fp_load", 0.0)
                                 + float(e["fp_load"]), 6)
        for k in ("counter_overflow", "cert_violation", "sym_violation"):
            if e.get(k):
                g[k] = True
        for k in ("action_generated", "action_distinct"):
            if k in e:
                d = g.setdefault(k, {})
                for a, v in e[k].items():
                    d[a] = d.get(a, 0) + int(v)
    rest = [e for e in events
            if not (e.get("event") == "level" and "host" in e)]
    return sorted(rest + list(by_level.values()),
                  key=lambda e: e.get("t", 0))


def pod_host_gauges(events) -> Optional[dict]:
    """The per-host gauge table from a (merged) journal's ``pod``
    events: {host: {shard_occupancy, spill_bytes, exchange_us}}, each
    host's LATEST stats row winning (the rows arrive at segment fences).
    None when the journal carries no pod plane."""
    hosts = {}
    for e in events:
        if e.get("event") == "pod" and e.get("phase") == "stats":
            hosts[int(e["host"])] = {
                "shard_occupancy": e.get("shard_occupancy", 0),
                "spill_bytes": e.get("spill_bytes", 0),
                "exchange_us": e.get("exchange_us", 0),
            }
    return hosts or None


def interval_rates(prev: Optional[Tuple[float, int, int]],
                   now: float, generated: int,
                   distinct: int) -> Tuple[int, int]:
    """(states/min, distinct-states/min) between two observations.

    With no previous observation TLC reports the raw first-interval
    counts as the per-minute figures (MC.out:35); we do the same."""
    if prev is None or now <= prev[0]:
        return generated, distinct
    dt = now - prev[0]
    return (
        int((generated - prev[1]) * 60 / dt),
        int((distinct - prev[2]) * 60 / dt),
    )


def eta_s(prev: Optional[dict], cur: dict) -> Optional[float]:
    """Seconds until the current queue drains at the current distinct-
    state rate - the rough time-to-exhaustive figure tlcstat prints.
    None when the rate is unknown or zero (first report / stalled)."""
    if prev is None:
        return None
    dt = cur["t"] - prev["t"]
    dd = cur["distinct"] - prev["distinct"]
    if dt <= 0 or dd <= 0:
        return None
    return cur["queue"] / (dd / dt)


def phase_totals(events) -> dict:
    """Cumulative measured wall seconds per phase name from the `phase`
    events (obs.phases) of a journal: {phase: seconds}.  Level- and
    segment-scope rows both accumulate (they attribute different walls:
    expand/commit device halves vs device/readback fence intervals)."""
    out = {}
    for ev in events:
        if ev.get("event") == "phase":
            key = ev["phase"]
            out[key] = out.get(key, 0.0) + float(ev["wall_s"])
    return out


def metrics_from_events(events) -> dict:
    """The run-monitoring metric set (obs.serve /metrics) as one flat
    dict, derived from a journal event list by the SAME arithmetic the
    TLC 2200 line and tlcstat use (interval_rates / eta_s above), so a
    Prometheus scrape can never disagree with the transcript.

    Pod journals (merged ``{base}.hN`` siblings) fold first: the
    headline counters/rates come from the pod-global per-level rows
    (fold_pod_levels), and the RAW per-host rows additionally yield
    `pod_host_rates` so Prometheus can export per-level rates both
    with and without host labels."""
    raw = events
    events = fold_pod_levels(events)
    prog = [e for e in events
            if e["event"] in ("level", "progress", "final",
                              "interrupted", "exhausted", "recovery")]
    cur = prog[-1] if prog else None
    levels = [e for e in events if e["event"] == "level"]
    prev = levels[-2] if len(levels) > 1 else None
    counts = {}
    for e in events:
        counts[e["event"]] = counts.get(e["event"], 0) + 1
    out = {
        "events_total": len(events),
        "segments_total": counts.get("segment", 0),
        "checkpoints_total": counts.get("checkpoint", 0),
        "regrows_total": counts.get("regrow", 0),
        "retries_total": counts.get("retry", 0),
        "degrades_total": counts.get("degrade", 0),
    }
    cache_evs = [e for e in events if e["event"] == "cache"]
    if cache_evs:
        # incremental re-checking (ISSUE 13): this run's artifact-cache
        # decisions as Prometheus counters (jaxtlc_artifact_cache_*)
        out["artifact_cache_hit_total"] = sum(
            1 for e in cache_evs if e.get("outcome") == "hit"
        )
        out["artifact_cache_miss_total"] = sum(
            1 for e in cache_evs if e.get("outcome") == "miss"
        )
    manifest = next((e for e in events if e["event"] == "run_start"),
                    None)
    fin = next((e for e in reversed(events) if e["event"] == "final"),
               None)
    info = {}
    if manifest is not None:
        info = {"workload": manifest["workload"],
                "engine": manifest["engine"],
                "device": manifest["device"]}
    info["verdict"] = fin["verdict"] if fin is not None else "running"
    out["run_info"] = info
    if cur is not None:
        out["generated_total"] = cur.get("generated", 0)
        out["distinct_total"] = cur.get("distinct", 0)
        out["queue"] = cur.get("queue", 0)
        out["depth"] = cur.get("level", cur.get("depth", 0))
        if prev is not None and cur["event"] == "level":
            spm, dpm = interval_rates(
                (prev["t"], prev["generated"], prev["distinct"]),
                cur["t"], cur["generated"], cur["distinct"],
            )
            out["states_per_second"] = round(spm / 60.0, 3)
            out["distinct_per_second"] = round(dpm / 60.0, 3)
            eta = eta_s(prev, cur)
            if eta is not None:
                out["queue_drain_eta_seconds"] = round(eta, 3)
        if "fp_load" in cur:
            out["fp_load"] = cur["fp_load"]
    sim = next((e for e in reversed(events) if e["event"] == "sim"),
               None)
    if sim is not None:
        # simulation tier (ISSUE 14): walk progress as Prometheus
        # gauges (jaxtlc_sim_*) - the smoke job class's live surface
        out["sim_walkers"] = sim["walkers"]
        out["sim_depth"] = sim["depth"]
        out["sim_steps"] = sim["steps"]
        out["sim_transitions"] = sim["transitions"]
        if "distinct_est" in sim:
            out["sim_distinct_estimate"] = sim["distinct_est"]
    inf = next((e for e in reversed(events) if e["event"] == "infer"),
               None)
    if inf is not None:
        # inference tier (ISSUE 16): the candidate-pool funnel as
        # Prometheus gauges (jaxtlc_infer_*) - conjectured, killed by
        # evidence, surviving, certified inductive
        out["infer_candidates"] = inf["candidates"]
        out["infer_killed"] = inf["killed"]
        out["infer_survivors"] = inf["survivors"]
        out["infer_certified"] = inf["certified"]
        if "n_states" in inf:
            out["infer_evidence_states"] = inf["n_states"]
    red = next((e for e in reversed(events) if e["event"] == "reduce"),
               None)
    if red is not None:
        # state-space reduction (ISSUE 18): what symmetry/POR bought
        # this run, as Prometheus gauges (jaxtlc_reduce_*) - the
        # transitions the ample sets cut, their hit rate, and the
        # orbit factor the canonicalization divides the space by
        out["reduce_states_pruned"] = red["states_pruned"]
        out["reduce_ample_hit_rate"] = red["ample_hit_rate"]
        out["reduce_orbit_factor"] = red["orbit_factor"]
        out["reduce_distinct"] = red["distinct"]
    sched_evs = [e for e in events if e["event"] == "sched"]
    if sched_evs:
        # serve-plane control decisions (ISSUE 17): the scheduler's
        # own journal as Prometheus counters (jaxtlc_sched_*) - one
        # per admit/reject/expire/preempt/requeue/retry/quarantine/
        # cancel decision, plus the queue depth the latest decision
        # observed
        for action in ("admit", "reject", "expire", "preempt",
                       "requeue", "retry", "quarantine", "cancel",
                       "dispatch"):
            n = sum(1 for e in sched_evs if e.get("action") == action)
            if n:
                out[f"sched_{action}_total"] = n
        depth = next((e["queued"] for e in reversed(sched_evs)
                      if "queued" in e), None)
        if depth is not None:
            out["sched_queue_depth"] = depth
    pod_evs = [e for e in events if e["event"] == "pod"]
    if pod_evs:
        # multi-host pods (ISSUE 19): membership counters + the
        # per-host shard gauges (Prometheus jaxtlc_host_* with a host
        # label - shard table load, spill-store bytes, and the
        # level-fence exchange/consensus wall in µs)
        out["pod_size"] = max(int(e["hosts"]) for e in pod_evs)
        out["pod_joins_total"] = sum(
            1 for e in pod_evs if e.get("phase") == "join")
        leaves = sum(1 for e in pod_evs if e.get("phase") == "leave")
        reshards = sum(
            1 for e in pod_evs if e.get("phase") == "reshard")
        if leaves:
            out["pod_leaves_total"] = leaves
        if reshards:
            out["pod_reshards_total"] = reshards
        hosts = pod_host_gauges(pod_evs)
        if hosts:
            out["pod_hosts"] = hosts
    host_levels: dict = {}
    for e in raw:
        if e.get("event") == "level" and "host" in e:
            host_levels.setdefault(int(e["host"]), []).append(e)
    if host_levels:
        # per-host per-level rates from each host's RAW partial rows
        # (Prometheus jaxtlc_host_states_per_second{host=...}); the
        # unlabeled rates above come from the folded pod-global rows
        rates = {}
        for h, lv in sorted(host_levels.items()):
            if len(lv) > 1:
                p, c = lv[-2], lv[-1]
                spm, dpm = interval_rates(
                    (p["t"], p["generated"], p["distinct"]),
                    c["t"], c["generated"], c["distinct"],
                )
                rates[h] = {
                    "states_per_second": round(spm / 60.0, 3),
                    "distinct_per_second": round(dpm / 60.0, 3),
                }
        if rates:
            out["pod_host_rates"] = rates
    sp = next((e for e in reversed(events) if e["event"] == "spill"),
              None)
    if sp is not None:
        out["spill_spilled"] = sp["spilled"]
        out["spill_capacity"] = sp["capacity"]
        out["spill_occupancy"] = round(
            sp["spilled"] / max(sp["capacity"], 1), 6
        )
        out["spill_hit_rate"] = round(
            sp.get("hits", 0) / max(sp.get("probes", 0), 1), 6
        )
    phases = phase_totals(events)
    if phases:
        out["phase_wall_seconds"] = {
            k: round(v, 6) for k, v in sorted(phases.items())
        }
    from .coverage import coverage_from_events

    cov = coverage_from_events(events)
    if cov is not None:
        # per-site cumulative counters (Prometheus coverage_site_total)
        # + the visited/total header gauges
        out["coverage_sites"] = cov["sites"]
        out["coverage_visited"] = cov["visited"]
        out["coverage_n_sites"] = cov["n_sites"]
        if cov.get("saturated_at_level") is not None:
            out["coverage_saturated_at_level"] = (
                cov["saturated_at_level"]
            )
    if fin is not None:
        out["wall_seconds"] = fin["wall_s"]
    return out


def render_tlc_event(log, ev: dict, resume_cmd: str = "") -> None:
    """Render one journal event as its TLC structured-log banner.

    The inverse direction of the old ad-hoc wiring: the journal event
    is primary, the 2200/2195/2196/2198 lines are derived from it.
    Unknown kinds render nothing (the journal may carry events - levels,
    segments - that have no TLC-line analog)."""
    kind = ev["event"]
    if kind == "progress":
        log.progress(ev["depth"], ev["generated"], ev["distinct"],
                     ev["queue"])
    elif kind == "analysis":
        log.msg(
            1000,
            f"Preflight {ev['severity']} "
            f"[{ev['layer']}/{ev['check']}] {ev['subject']}: "
            f"{ev['detail']}",
            severity=1,
        )
    elif kind == "analysis_summary":
        if ev["findings"]:
            log.msg(
                1000,
                f"Preflight analysis: {ev['errors']} error(s), "
                f"{ev['warnings']} warning(s) "
                f"({ev['findings']} finding(s) total).",
                severity=1,
            )
    elif kind == "level" and ev.get("sym_violation"):
        # the ring's sticky COL_SYM flag: the runtime orbit check
        # caught the symmetry canonicalization NOT constant on a
        # reachable orbit - loud once per run; the driver escalates
        # the verdict to error
        if not getattr(log, "_warned_sym_violation", False):
            log._warned_sym_violation = True
            log.msg(
                1000,
                "ERROR: runtime orbit-certificate violation - the "
                "symmetry canonicalization mapped members of one "
                "reachable orbit to different representatives "
                "(jaxtlc.engine.reduce); the reduced run's results "
                "are NOT trustworthy.  Re-run with -no-symmetry and "
                "report the spec.",
                severity=1,
            )
        if ev.get("cert_violation") or ev.get("counter_overflow"):
            render_tlc_event(log, {**ev, "sym_violation": False})
    elif kind == "level" and ev.get("cert_violation"):
        # the ring's sticky COL_CERT flag: a generated state violated a
        # bound the certified abstract interpretation claimed - loud
        # once per run; the driver escalates the verdict to error
        if not getattr(log, "_warned_cert_violation", False):
            log._warned_cert_violation = True
            log.msg(
                1000,
                "ERROR: runtime certificate violation - a reachable "
                "state lies outside the certified bounds the narrowed "
                "codec was built from (jaxtlc.analysis.absint); the "
                "narrowed run's results are NOT trustworthy.  Re-run "
                "with -no-narrow and report the spec.",
                severity=1,
            )
        if ev.get("counter_overflow"):
            render_tlc_event(log, {**ev, "cert_violation": False})
    elif kind == "level" and ev.get("counter_overflow"):
        # the ring's sticky COL_OVERFLOW flag: warn once per run (the
        # flag never unsets, so every later level row carries it too)
        if not getattr(log, "_warned_counter_overflow", False):
            log._warned_counter_overflow = True
            log.msg(
                1000,
                "Warning: on-device cumulative uint32 counters "
                "saturated (ring overflow flag set); generated/"
                "distinct totals beyond this level may have wrapped.",
                severity=1,
            )
    elif kind == "cache" and ev.get("outcome") == "hit":
        # incremental re-checking (ISSUE 13): loud when a run was
        # answered (or BFS-skipped) from the artifact cache - misses,
        # writes and bypasses stay journal-only
        what = ("verdict replayed from the artifact cache (no engine "
                "was built)" if ev["tier"] == "verdict" else
                "reachable set loaded from the artifact cache; "
                "re-evaluating invariants only (BFS skipped)")
        log.msg(
            1000,
            f"Incremental re-check: {what}  [key "
            f"{ev['key'][:12]}..., -recheck forces a full run]",
            severity=1,
        )
    elif kind == "checkpoint":
        log.checkpoint_saved(ev["path"])
    elif kind == "recovery":
        log.recovery(ev["path"], ev["distinct"])
    elif kind == "regrow":
        log.regrow(ev["resource"], ev["old"], ev["new"], ev["violation"])
    elif kind == "retry":
        log.msg(
            1000,
            f"Transient error (attempt {ev['attempt']}): {ev['error']}; "
            f"retrying in {ev['delay_s']}s from the last good state.",
            severity=1,
        )
    elif kind == "ckpt_write_failed":
        log.msg(
            1000,
            f"Checkpoint write failed: {ev['error']} (run continues; "
            "the next segment boundary retries).",
            severity=1,
        )
    elif kind == "ckpt_fallback":
        log.msg(
            1000,
            f"Checkpoint {ev['path']} failed verification "
            f"({ev['error']}); falling back to the previous generation.",
            severity=1,
        )
    elif kind == "interrupted":
        log.interrupted(ev["signum"], ev["path"], resume_cmd)
    elif kind == "degrade":
        log.msg(
            1000,
            f"Capacity ladder [{ev['rung']}] {ev['resource']}: "
            f"{ev['action']} ({ev['reason']}).",
            severity=1,
        )
    elif kind == "spill":
        if ev["phase"] == "activate":
            log.msg(
                1000,
                "Host fingerprint spill tier activated: device table "
                f"stays at {ev['resident']:,} resident fingerprints, "
                "cold fingerprints migrate to host RAM "
                f"(store capacity {ev['capacity']:,}, auto-grows).",
                severity=1,
            )
        # flushes are journal-only (one per highwater crossing - a
        # banner each would flood the transcript; tlcstat shows them)
    elif kind == "infer" and ev.get("phase") == "round":
        # inference filter rounds (ISSUE 16): one banner per evidence
        # round - the candidate-funnel's live surface (the summary row
        # stays journal-only; the API path renders its own verdict
        # lines with the certified invariant texts)
        log.msg(
            1000,
            f"Inference round {ev.get('round', '?')}: "
            f"{ev['killed']} of {ev['candidates']} candidates killed "
            f"against {ev.get('n_states', 0):,} "
            f"{ev.get('evidence', '')} evidence states "
            f"({ev['survivors']} survive).",
        )
    elif kind == "sched" and ev.get("action") in (
            "reject", "expire", "preempt", "quarantine"):
        # serve-plane control decisions (ISSUE 17): the LOAD-SHEDDING
        # ones get banners (admit/dispatch/retry/requeue/cancel are
        # high-rate bookkeeping - journal + /metrics only)
        what = {
            "reject": f"admission rejected job {ev['job']} "
                      f"({ev.get('reason', 'queue_bound')}; "
                      f"retry after {ev.get('retry_after_s', '?')}s)",
            "expire": f"job {ev['job']} expired "
                      f"({ev.get('reason', 'deadline')})",
            "preempt": f"job {ev['job']} preempted "
                       f"({ev.get('reason', 'priority')})",
            "quarantine": f"job {ev['job']} quarantined "
                          f"({ev.get('reason', 'circuit open')})",
        }[ev["action"]]
        log.msg(1000, f"Scheduler: {what}.", severity=1)
    elif kind == "exhausted":
        log.msg(
            1000,
            f"Capacity exhausted ({ev['resource']}): "
            f"{ev['distinct']:,} distinct states checkpointed"
            + (f" at {ev['path']}" if ev.get("path") else
               " (no -checkpoint: progress lost)")
            + (f"; resume with: {resume_cmd}" if resume_cmd else ""),
            severity=1,
        )


_BENCH_BASE = {
    "metric": "distinct_states_per_s",
    "value": 0,
    "unit": "states/s",
    "vs_baseline": 0,
    "pipeline": False,
    # which commit dedup produced the number (ISSUE 12): the sorted
    # path (False) or the hash-slab sort-free path (True); modes that
    # run both put their setting in explicitly, like "pipeline"
    "sort_free": False,
    # which search produced the number (ISSUE 14): exhaustive BFS
    # (False) or the random-walk simulation tier (True - walks/s
    # payloads, bench.py --sim)
    "sim": False,
    # which expand mode produced the number (ISSUE 15): immediate
    # per-candidate invariant/cert evaluation (False) or the
    # distinct-first deferred evaluation on the fresh-insert
    # claimants (True - bench.py --expand-ab); modes that run both
    # put their setting in explicitly, like "pipeline"/"sort_free"
    "deferred": False,
    # which job class produced the number (ISSUE 16): checking (False)
    # or the invariant-inference predicates x states filter (True -
    # predicate-evals/s payloads, bench.py --infer)
    "infer": False,
    # which state space produced the number (ISSUE 18): the full one
    # (False/False) or one shrunk by symmetry canonicalization /
    # partial-order ample-set pruning (bench.py --reduce-ab puts the
    # reduced engine's settings in explicitly)
    "symmetry": False,
    "por": False,
}


def bench_payload(payload: dict,
                  journal: Optional[RunJournal] = None) -> dict:
    """Assemble one bench metric line: base contract fields + `payload`,
    schema-validated by stamping it through a journal as a
    `bench_metric` event (an in-memory journal when none is given).
    Returns the payload WITHOUT the journal envelope - the emitted JSON
    line is byte-compatible with every committed BENCH_*.json."""
    out = dict(_BENCH_BASE)
    out.update(payload)
    j = journal if journal is not None else RunJournal()
    if "error" in out:
        # failure payloads carry the contract fields too (zeroed metric)
        j.event("bench_metric", **{
            k: out.get(k, _BENCH_BASE.get(k)) for k in
            ("metric", "value", "unit", "vs_baseline")
        }, error=str(out["error"]))
    else:
        j.event("bench_metric", **out)
    return out
