"""Crash-safe append-only run journal (the host telemetry tier).

One JSONL file is the single source of truth for what a run did, when:
the manifest (run_start), every segment fence, level flip, checkpoint
write, regrow, retry, fault, violation and the final verdict.  The
TLC-style 2200 progress lines (io.tlc_log via obs.views.render_tlc),
`tools/tlcstat.py`'s dashboard, the Chrome-trace export (obs.trace) and
bench payloads are all DERIVED VIEWS of these events - none of them
assembles its own private dict of run facts anymore.

Durability discipline (the engine.checkpoint school): every event is
appended as one line, flushed, and fsync'd before `event()` returns, so
a SIGKILL between events loses nothing and a crash mid-write tears at
most the final line - which the reader skips explicitly (`read()`
tolerates exactly one trailing partial line, and only at EOF).  A
`-recover` run OPENS THE SAME FILE IN APPEND MODE and stamps a
`run_resume` event: an interrupted-and-resumed run has ONE continuous
journal, not two halves.

Every event is validated against the versioned schema (obs.schema) at
write time, so shape drift fails in the producer, loudly, instead of in
next month's dashboard.
"""

from __future__ import annotations

import json
import os
import time
from typing import Iterator, List, Optional

from .schema import SCHEMA_VERSION, JournalSchemaError, validate_event


class RunJournal:
    """Append-only JSONL event sink.

    path=None keeps the journal in memory only (bench / tests want the
    event stream without a file); otherwise the file is created (or
    appended to, for `resume=True`) with per-event fsync.

    fsync_every=N (default 1) batches the fsync: every event is still
    written + flushed per call (a line is complete or absent - the SSE
    tail and the torn-line reader contract are unchanged), but the
    durability barrier is paid once per N events.  Checkpointed runs
    keep the default - a checkpoint generation must never be newer than
    its journal - while server-side high-rate job journals (ISSUE 9)
    run with N in the tens: a crash there loses at most the last N
    TELEMETRY lines of a job the scheduler will re-report anyway."""

    def __init__(self, path: Optional[str] = None, resume: bool = False,
                 fsync_every: int = 1):
        self.path = path
        self.events: List[dict] = []
        self.fsync_every = max(1, int(fsync_every))
        self._unsynced = 0
        self._f = None
        if path:
            mode = "a" if resume and os.path.exists(path) else "w"
            self._f = open(path, mode, encoding="utf-8")

    def event(self, kind: str, **fields) -> dict:
        """Validate + append one event; returns the stamped event dict."""
        ev = {"v": SCHEMA_VERSION, "t": time.time(), "event": kind,
              **fields}
        validate_event(ev)
        self.events.append(ev)
        if self._f is not None:
            self._f.write(json.dumps(ev, sort_keys=True) + "\n")
            self._f.flush()
            self._unsynced += 1
            if self._unsynced >= self.fsync_every:
                os.fsync(self._f.fileno())
                self._unsynced = 0
        return ev

    def sync(self) -> None:
        """Force the durability barrier now (batched mode's checkpoint
        hook; a no-op when nothing is pending or the journal is
        in-memory)."""
        if self._f is not None and self._unsynced:
            os.fsync(self._f.fileno())
            self._unsynced = 0

    def close(self) -> None:
        if self._f is not None:
            self.sync()
            self._f.close()
            self._f = None

    def __enter__(self) -> "RunJournal":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False


def read(path: str, validate: bool = True) -> List[dict]:
    """Load a journal file.  A single torn TRAILING line (the crash-window
    artifact of an append cut mid-write) is skipped; a torn line anywhere
    else - or any schema violation when validate=True - raises, because
    that is corruption, not a crash artifact."""
    out: List[dict] = []
    with open(path, "r", encoding="utf-8") as f:
        lines = f.read().splitlines()
    for i, line in enumerate(lines):
        if not line.strip():
            continue
        try:
            ev = json.loads(line)
        except json.JSONDecodeError:
            if i == len(lines) - 1:
                break  # torn final line: the documented crash window
            raise JournalSchemaError(
                f"{path}:{i + 1}: unparseable journal line {line!r}"
            )
        if validate:
            validate_event(ev)
        out.append(ev)
    return out


def tail(path: str, since: int = 0) -> Iterator[dict]:
    """Yield journal events after index `since` (tlcstat's follow mode);
    invalid/torn lines at the tail are skipped until complete."""
    try:
        events = read(path, validate=False)
    except (OSError, JournalSchemaError):
        return
    for ev in events[since:]:
        yield ev
