"""On-device counter ring: the device telemetry tier.

A fixed-shape uint32 ring buffer rides inside every engine carry
(EngineCarry / ShardCarry / EnumCarry optional leaves, None when obs is
off so pre-obs checkpoint layouts are untouched).  The engines write
ONE row per BFS level flip (the enumerator: one per body) with a single
contiguous dynamic-update-slice - no host sync, no scatter - and
non-flip bodies write into a dump row, so the write is unconditional
and XLA-friendly.  The host reads the ring back only at the segment
fences it already pays for (the supervisor's batched async device_get),
decodes the new rows here, and journals them as `level` events: that is
where TLC-style per-level rate attribution (BLEST, arXiv:2512.21967)
comes from at near-zero steady-state cost (bench.py --obs-ab gates the
overhead at <= 2%).

Row layout (all cumulative uint32 counters; cumulative so a lost row -
ring wrap between fences - degrades per-level resolution, never total
accuracy):

    col 0  level      BFS level just completed
    col 1  generated  states generated so far
    col 2  distinct   distinct states found so far
    col 3  queue      width of the NEXT level (states left on queue)
    col 4  bodies     engine loop bodies executed so far
    col 5  expanded   states popped/expanded so far
    col 6  overflow   STICKY saturation flag: 1 once any cumulative
                      uint32 column wrapped (new < old between bodies);
                      decoded as a `counter_overflow` warning so
                      saturated counters are detected, never silently
                      wrong (the jaxtlc.analysis counter-width audit
                      flags the risky configs before the run)
    col 7  spill      cumulative host-spill-tier hits: candidates the
                      host fingerprint store vetoed (engine.spill);
                      always 0 on engines without the spill tier, so
                      pre-spill ring layouts are unchanged
    col 8  cert       STICKY certificate flag: 1 once any generated
                      state violated a bound the certified abstract
                      interpretation (jaxtlc.analysis.absint) claimed -
                      decoded as `cert_violation` and escalated to an
                      error verdict, so an unsound narrowing can never
                      silently drop real states; always 0 on engines
                      without a certificate check
    col 9  sym        STICKY orbit-certificate flag (ISSUE 18): 1 once
                      the runtime orbit check caught the symmetry
                      canonicalization NOT constant on a reachable
                      orbit - decoded as `sym_violation` and escalated
                      to an error verdict, so an unsound symmetry
                      reduction can never silently merge real states;
                      always 0 on engines without symmetry reduction
    col 10..10+A-1      per-action generated (cumulative)
    col 10+A..10+2A-1   per-action distinct  (cumulative)

The ring array is [slots + 1, cols]: row `slots` is the dump row.
`head` counts rows ever written (the slot of row k is k % slots), so
wrap-around is detectable host-side.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

DEFAULT_OBS_SLOTS = 256

N_FIXED_COLS = 10
(COL_LEVEL, COL_GENERATED, COL_DISTINCT, COL_QUEUE, COL_BODIES,
 COL_EXPANDED, COL_OVERFLOW, COL_SPILL, COL_CERT,
 COL_SYM) = range(N_FIXED_COLS)
COL_RES0 = COL_OVERFLOW  # pre-overflow name of col 6
COL_RES1 = COL_SPILL  # pre-spill name of col 7


def ring_cols(n_labels: int) -> int:
    """Row width for an engine with `n_labels` actions."""
    return N_FIXED_COLS + 2 * n_labels


def ring_new(slots: int, n_labels: int):
    """Fresh device ring ([slots + 1, cols]; last row = dump) + head."""
    import jax.numpy as jnp

    return (
        jnp.zeros((slots + 1, ring_cols(n_labels)), jnp.uint32),
        jnp.int32(0),
    )


def ring_update(ring, head, row, flip):
    """Write `row` at the ring head when `flip` is true, else into the
    dump row - one unconditional contiguous row write either way (the
    queue-enqueue discipline applied to telemetry)."""
    import jax.numpy as jnp
    from jax import lax

    slots = ring.shape[0] - 1
    idx = jnp.where(flip, head % slots, jnp.int32(slots))
    ring = lax.dynamic_update_slice(
        ring, row[None, :], (idx, jnp.int32(0))
    )
    return ring, head + flip.astype(head.dtype)


def pack_row(level, generated, distinct, queue, bodies, expanded,
             act_gen, act_dist, overflow=None, spill=None, cert=None,
             sym=None):
    """Assemble one ring row from carry scalars (device-side).
    `overflow` is the sticky uint32 saturation flag (COL_OVERFLOW);
    `spill` the cumulative host-spill-hit counter (COL_SPILL); `cert`
    the sticky certificate-violation flag (COL_CERT); `sym` the sticky
    orbit-certificate flag (COL_SYM); None writes 0 (engines that
    predate the flag / carry no such tier)."""
    import jax.numpy as jnp

    u = jnp.uint32
    fixed = jnp.stack([
        level.astype(u), generated.astype(u), distinct.astype(u),
        queue.astype(u), bodies.astype(u), expanded.astype(u),
        u(0) if overflow is None else overflow.astype(u),
        u(0) if spill is None else spill.astype(u),
        u(0) if cert is None else cert.astype(u),
        u(0) if sym is None else sym.astype(u),
    ])
    return jnp.concatenate(
        [fixed, act_gen.astype(u), act_dist.astype(u)]
    )


def sticky_overflow(ring, wrapped):
    """The sticky saturation flag for the row about to be written:
    1 once ANY past row recorded an overflow (the flag never unsets,
    so the max over the whole ring - dump row included - is exactly
    "ever wrapped") OR a cumulative counter wrapped this body.
    `wrapped` is a device bool; returns uint32."""
    import jax.numpy as jnp

    prev = ring[:, COL_OVERFLOW].max()
    return jnp.maximum(prev, wrapped.astype(jnp.uint32))


def wrapped_any(pairs):
    """Device bool: any (new, old) cumulative uint32 pair wrapped this
    body (new < old is impossible for a monotone counter except via
    2^32 wrap-around)."""
    import jax.numpy as jnp

    out = jnp.bool_(False)
    for new, old in pairs:
        out = out | (new < old).any()
    return out


def rows_from_ring(
    ring: np.ndarray,
    head: int,
    labels: Optional[Sequence[str]] = None,
    since: int = 0,
    fp_capacity: int = 0,
) -> List[Dict]:
    """Decode the ring rows written in [since, head) that are still
    resident (ring wrap drops the oldest; cumulative counters mean the
    NEXT retained row still carries exact totals).  Returns journal-
    `level`-event-shaped dicts, oldest first."""
    ring = np.asarray(ring)
    head = int(head)
    slots = ring.shape[0] - 1
    first = max(int(since), head - slots, 0)
    out = []
    for k in range(first, head):
        r = ring[k % slots].astype(np.int64)
        row = {
            "level": int(r[COL_LEVEL]),
            "generated": int(r[COL_GENERATED]),
            "distinct": int(r[COL_DISTINCT]),
            "queue": int(r[COL_QUEUE]),
            "bodies": int(r[COL_BODIES]),
            "expanded": int(r[COL_EXPANDED]),
        }
        if fp_capacity:
            row["fp_load"] = round(int(r[COL_DISTINCT]) / fp_capacity, 6)
        if r[COL_OVERFLOW]:
            # sticky device-side saturation flag: totals in this row
            # (and every later one) may have wrapped uint32
            row["counter_overflow"] = True
        if r[COL_SPILL]:
            # host spill tier active: cumulative host-store vetoes
            row["spill_hits"] = int(r[COL_SPILL])
        if r[COL_CERT]:
            # sticky certificate flag: a generated state violated a
            # bound the certified abstract interpretation claimed
            row["cert_violation"] = True
        if r[COL_SYM]:
            # sticky orbit-certificate flag: the symmetry
            # canonicalization was caught non-constant on an orbit
            row["sym_violation"] = True
        if labels is not None:
            a = len(labels)
            gen = r[N_FIXED_COLS:N_FIXED_COLS + a]
            dist = r[N_FIXED_COLS + a:N_FIXED_COLS + 2 * a]
            row["action_generated"] = {
                labels[i]: int(v) for i, v in enumerate(gen) if v
            }
            row["action_distinct"] = {
                labels[i]: int(v) for i, v in enumerate(dist) if v
            }
        out.append(row)
    return out


def shard_rows_from_ring(
    ring: np.ndarray,
    head: np.ndarray,
    labels: Optional[Sequence[str]] = None,
    since: int = 0,
    fp_capacity_total: int = 0,
) -> List[Dict]:
    """Sharded decode: every device flips levels in lock-step (level
    fencing is a global psum), so row k of each device's ring describes
    the SAME level with per-device partial counters - sum them.  level
    and queue-of-next-level semantics: level is replicated (max), the
    others add."""
    ring = np.asarray(ring)  # [D, slots + 1, cols]
    heads = np.asarray(head)
    h = int(heads.min())
    summed = ring.astype(np.int64).sum(axis=0)
    summed[:, COL_LEVEL] = ring[:, :, COL_LEVEL].max(axis=0)
    return rows_from_ring(
        summed, h, labels=labels, since=since,
        fp_capacity=fp_capacity_total,
    )
