"""On-device counter ring: the device telemetry tier.

A fixed-shape uint32 ring buffer rides inside every engine carry
(EngineCarry / ShardCarry / EnumCarry optional leaves, None when obs is
off so pre-obs checkpoint layouts are untouched).  The engines write
ONE row per BFS level flip (the enumerator: one per body) with a single
contiguous dynamic-update-slice - no host sync, no scatter - and
non-flip bodies write into a dump row, so the write is unconditional
and XLA-friendly.  The host reads the ring back only at the segment
fences it already pays for (the supervisor's batched async device_get),
decodes the new rows here, and journals them as `level` events: that is
where TLC-style per-level rate attribution (BLEST, arXiv:2512.21967)
comes from at near-zero steady-state cost (bench.py --obs-ab gates the
overhead at <= 2%).

Row layout (all cumulative uint32 counters; cumulative so a lost row -
ring wrap between fences - degrades per-level resolution, never total
accuracy):

    col 0  level      BFS level just completed
    col 1  generated  states generated so far
    col 2  distinct   distinct states found so far
    col 3  queue      width of the NEXT level (states left on queue)
    col 4  bodies     engine loop bodies executed so far
    col 5  expanded   states popped/expanded so far
    col 6  reserved
    col 7  reserved
    col 8..8+A-1      per-action generated (cumulative)
    col 8+A..8+2A-1   per-action distinct  (cumulative)

The ring array is [slots + 1, cols]: row `slots` is the dump row.
`head` counts rows ever written (the slot of row k is k % slots), so
wrap-around is detectable host-side.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

DEFAULT_OBS_SLOTS = 256

N_FIXED_COLS = 8
(COL_LEVEL, COL_GENERATED, COL_DISTINCT, COL_QUEUE, COL_BODIES,
 COL_EXPANDED, COL_RES0, COL_RES1) = range(N_FIXED_COLS)


def ring_cols(n_labels: int) -> int:
    """Row width for an engine with `n_labels` actions."""
    return N_FIXED_COLS + 2 * n_labels


def ring_new(slots: int, n_labels: int):
    """Fresh device ring ([slots + 1, cols]; last row = dump) + head."""
    import jax.numpy as jnp

    return (
        jnp.zeros((slots + 1, ring_cols(n_labels)), jnp.uint32),
        jnp.int32(0),
    )


def ring_update(ring, head, row, flip):
    """Write `row` at the ring head when `flip` is true, else into the
    dump row - one unconditional contiguous row write either way (the
    queue-enqueue discipline applied to telemetry)."""
    import jax.numpy as jnp
    from jax import lax

    slots = ring.shape[0] - 1
    idx = jnp.where(flip, head % slots, jnp.int32(slots))
    ring = lax.dynamic_update_slice(
        ring, row[None, :], (idx, jnp.int32(0))
    )
    return ring, head + flip.astype(head.dtype)


def pack_row(level, generated, distinct, queue, bodies, expanded,
             act_gen, act_dist):
    """Assemble one ring row from carry scalars (device-side)."""
    import jax.numpy as jnp

    u = jnp.uint32
    fixed = jnp.stack([
        level.astype(u), generated.astype(u), distinct.astype(u),
        queue.astype(u), bodies.astype(u), expanded.astype(u),
        u(0), u(0),
    ])
    return jnp.concatenate(
        [fixed, act_gen.astype(u), act_dist.astype(u)]
    )


def rows_from_ring(
    ring: np.ndarray,
    head: int,
    labels: Optional[Sequence[str]] = None,
    since: int = 0,
    fp_capacity: int = 0,
) -> List[Dict]:
    """Decode the ring rows written in [since, head) that are still
    resident (ring wrap drops the oldest; cumulative counters mean the
    NEXT retained row still carries exact totals).  Returns journal-
    `level`-event-shaped dicts, oldest first."""
    ring = np.asarray(ring)
    head = int(head)
    slots = ring.shape[0] - 1
    first = max(int(since), head - slots, 0)
    out = []
    for k in range(first, head):
        r = ring[k % slots].astype(np.int64)
        row = {
            "level": int(r[COL_LEVEL]),
            "generated": int(r[COL_GENERATED]),
            "distinct": int(r[COL_DISTINCT]),
            "queue": int(r[COL_QUEUE]),
            "bodies": int(r[COL_BODIES]),
            "expanded": int(r[COL_EXPANDED]),
        }
        if fp_capacity:
            row["fp_load"] = round(int(r[COL_DISTINCT]) / fp_capacity, 6)
        if labels is not None:
            a = len(labels)
            gen = r[N_FIXED_COLS:N_FIXED_COLS + a]
            dist = r[N_FIXED_COLS + a:N_FIXED_COLS + 2 * a]
            row["action_generated"] = {
                labels[i]: int(v) for i, v in enumerate(gen) if v
            }
            row["action_distinct"] = {
                labels[i]: int(v) for i, v in enumerate(dist) if v
            }
        out.append(row)
    return out


def shard_rows_from_ring(
    ring: np.ndarray,
    head: np.ndarray,
    labels: Optional[Sequence[str]] = None,
    since: int = 0,
    fp_capacity_total: int = 0,
) -> List[Dict]:
    """Sharded decode: every device flips levels in lock-step (level
    fencing is a global psum), so row k of each device's ring describes
    the SAME level with per-device partial counters - sum them.  level
    and queue-of-next-level semantics: level is replicated (max), the
    others add."""
    ring = np.asarray(ring)  # [D, slots + 1, cols]
    heads = np.asarray(head)
    h = int(heads.min())
    summed = ring.astype(np.int64).sum(axis=0)
    summed[:, COL_LEVEL] = ring[:, :, COL_LEVEL].max(axis=0)
    return rows_from_ring(
        summed, h, labels=labels, since=since,
        fp_capacity=fp_capacity_total,
    )
