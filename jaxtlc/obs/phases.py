"""Phase-attribution timing tier: measured expand/commit walls.

TLC's MC.out proves where its time went; our trace exporter's per-level
expand/commit lanes were an admitted body-count-proportional SCHEMATIC
inside the host-observed segment wall (obs.trace docstring) - pretty,
not evidence.  ROADMAP #1 (the MXU commit rewrite) needs evidence: a
measured baseline of where commit time goes (sort vs fpset probe vs
enqueue), per BLEST's cost accounting.  This module is that instrument,
in three capture modes of increasing resolution and cost:

1. **Fence mode** (always on with the journal): the supervisor already
   pays a host sync at every segment fence; `segment_phases` turns the
   readback/checkpoint walls it already measures into schema-validated
   `phase` journal events (scope="segment").  Zero device work, zero
   extra syncs - pure host arithmetic, which is why the `--obs-ab`
   harness gates its overhead at <= 0.5%.  The pod driver
   (jaxtlc.dist, ISSUE 20) emits the same rows per host with a `host`
   field, so a merged pod journal's phase walls attribute per process.
2. **`-phase-timing`** (PhasedRuntime): the supervisor swaps its fused
   segment dispatch for a host-fenced step loop whose expand and commit
   halves are SEPARATELY jitted from the very `make_stage_pair` closures
   the fused body composes - so results stay bit-for-bit while every
   level gets measured expand/commit walls (scope="level" `phase`
   events; the trace exporter renders these as measured lanes instead
   of the schematic).  The per-step fences cost real wall time - that
   is the price of resolution, measured in PERF.md round 11 - hence the
   flag.  Unpipelined single-device engines only: fencing the pipelined
   body would serialize the overlap it exists to create, and the
   sharded body's halves live inside one shard_map.
3. **Differential sub-phase profiler** (`subphase_walls`): times nested
   partial jits on a warmed mid-run carry (the tools/profile_v4.py
   technique, packaged as a library) and attributes commit time to
   sort / fpset probe / enqueue+stats by subtraction.  This is the
   cost-model fitter's (tools/costmodel.py) input.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

# canonical phase names (the `phase` field vocabulary; extra names are
# allowed by the schema - views ignore what they don't know)
PHASE_EXPAND = "expand"
PHASE_COMMIT = "commit"
PHASE_DEVICE = "device"
PHASE_READBACK = "readback"


class PhaseRecorder:
    """Accumulates per-level expand/commit walls between fences.

    The phased step loop calls `step(level, expand_s, commit_s)` per
    engine step; the supervisor drains completed measurements at each
    segment fence and journals them as `phase` events.  `reset()` drops
    measurements of a segment that is about to be replayed (retry /
    regrow roll back the carry; its timings must not double-count)."""

    def __init__(self):
        self._levels: Dict[int, Dict[str, float]] = {}
        self._order: List[int] = []

    def step(self, level: int, expand_s: float, commit_s: float) -> None:
        row = self._levels.get(level)
        if row is None:
            row = {"expand": 0.0, "commit": 0.0, "bodies": 0}
            self._levels[level] = row
            self._order.append(level)
        row["expand"] += expand_s
        row["commit"] += commit_s
        row["bodies"] += 1

    def reset(self) -> None:
        self._levels.clear()
        self._order.clear()

    def drain(self) -> List[dict]:
        """Completed measurements as `phase`-event field dicts (oldest
        first, expand before commit per level), then reset.  A level
        spanning two segments yields one row per segment; walls are
        additive, so consumers sum by level."""
        out = []
        for lvl in self._order:
            row = self._levels[lvl]
            for phase in (PHASE_EXPAND, PHASE_COMMIT):
                out.append({
                    "scope": "level", "index": lvl, "phase": phase,
                    "wall_s": round(row[phase], 6),
                    "bodies": row["bodies"],
                })
        self.reset()
        return out


def segment_phases(index: int, wall_s: float,
                   readback_s: float = None) -> List[dict]:
    """Fence-mode `phase` event rows for one supervised segment: the
    device dispatch->fence wall plus the host readback wall the
    supervisor measures around the progress/ring device_get it already
    pays.  Pure host arithmetic over timestamps that already exist."""
    rows = [{"scope": "segment", "index": index, "phase": PHASE_DEVICE,
             "wall_s": round(wall_s, 6)}]
    if readback_s is not None:
        rows.append({"scope": "segment", "index": index,
                     "phase": PHASE_READBACK,
                     "wall_s": round(readback_s, 6)})
    return rows


class PhasedRuntime:
    """`-phase-timing` execution of the single-device engine: the same
    supervision contract as engine.spill.SpillRuntime (the supervisor
    swaps its segment function), but the host sits in the step loop to
    FENCE between the expand and commit halves, crediting each level's
    wall to the half that spent it.

    Bit-exactness: expand_fn/commit_fn are jitted directly from the
    `make_stage_pair` closures the fused body composes, with the same
    pop-cursor arithmetic and the same two-tier small-body dispatch,
    so the carry after N phased steps equals the carry after N fused
    steps bit-for-bit (tests/test_obs.py pins the full signature)."""

    def __init__(self, backend, chunk: int, queue_capacity: int,
                 fp_capacity: int, fp_index: int = None, seed: int = None,
                 fp_highwater: float = None, check_deadlock: bool = None,
                 obs_slots: int = 0, sort_free: bool = None,
                 deferred: bool = None,
                 recorder: Optional[PhaseRecorder] = None):
        import jax

        from ..engine.bfs import (
            DEFAULT_FP_HIGHWATER,
            make_backend_engine,
            make_stage_pair,
            resolve_deferred,
            resolve_sort_free,
        )
        from ..engine.fingerprint import DEFAULT_FP_INDEX, DEFAULT_SEED

        fp_index = DEFAULT_FP_INDEX if fp_index is None else fp_index
        seed = DEFAULT_SEED if seed is None else seed
        fp_highwater = (DEFAULT_FP_HIGHWATER if fp_highwater is None
                        else fp_highwater)
        sort_free = resolve_sort_free(sort_free, chunk)
        deferred = resolve_deferred(deferred, chunk)
        self.recorder = recorder if recorder is not None else PhaseRecorder()
        self.chunk = chunk
        # init template through the production factory (jits are lazy)
        init_fn, _, _ = make_backend_engine(
            backend, chunk, queue_capacity, fp_capacity, fp_index, seed,
            fp_highwater=fp_highwater, check_deadlock=check_deadlock,
            donate=False, obs_slots=obs_slots, sort_free=sort_free,
            deferred=deferred,
        )
        self._base_init = init_fn

        def stage_fns(ck):
            pop_expand, commit = make_stage_pair(
                backend, ck, queue_capacity=queue_capacity,
                fp_capacity=fp_capacity, fp_highwater=fp_highwater,
                check_deadlock=check_deadlock, fp_index=fp_index,
                seed=seed, obs_slots=obs_slots, sort_free=sort_free,
                deferred=deferred,
            )
            expand_fn = jax.jit(lambda c: pop_expand(c))
            commit_fn = jax.jit(
                lambda c, ex, n: commit(c, ex, n, c.qhead + n,
                                        c.qhead + n)
            )
            return expand_fn, commit_fn

        # two-tier small-body dispatch mirrors make_backend_engine:
        # big-chunk engines run a small body on narrow level remainders
        # (the host picks the tier from the scalars it fences anyway)
        self._small = chunk // 16 if chunk >= 1 << 14 else 0
        self._big_fns = stage_fns(chunk)
        self._small_fns = stage_fns(self._small) if self._small else None

        def audit_step(c):
            ex, n = self._big_fns[0](c)
            return self._big_fns[1](c, ex, n)

        # donation metadata for the preflight audit (selfcheck "phased")
        audit_step.donate_requested = False
        audit_step.donates_carry = False
        self.audit_step_fn = audit_step

    def init_fn(self):
        return self._base_init()

    def segment_fn(self, ckpt_every: int) -> Callable:
        """seg_fn(carry) -> carry after up to `ckpt_every` steps, fully
        fenced (the supervisor's block_until_ready at the fence is then
        a no-op), recording per-level expand/commit walls."""
        import jax

        rec = self.recorder

        def seg(carry):
            for _ in range(ckpt_every):
                viol, level, level_n, qhead, next_n = map(int, jax.device_get(
                    (carry.viol, carry.level, carry.level_n,
                     carry.qhead, carry.next_n)
                ))
                if viol != 0 or (level_n - qhead <= 0 and next_n == 0):
                    break
                avail = level_n - qhead
                expand_fn, commit_fn = (
                    self._big_fns if (not self._small
                                      or avail >= self.chunk // 2)
                    else self._small_fns
                )
                t0 = time.perf_counter()
                ex, n = expand_fn(carry)
                jax.block_until_ready((ex, n))
                t1 = time.perf_counter()
                carry = commit_fn(carry, ex, n)
                jax.block_until_ready(carry)
                t2 = time.perf_counter()
                rec.step(level, t1 - t0, t2 - t1)
            return carry

        return seg


def _fused_time(body, carry, K: int = 4, reps: int = 3) -> float:
    """Best-of-`reps` seconds per iteration of `body` run K times inside
    one jitted fori_loop (the profile_v4 technique: the loop amortizes
    the dispatch floor so small phases are not all floor)."""
    import jax
    from jax import lax

    @jax.jit
    def loop(c):
        return lax.fori_loop(0, K, lambda _, cc: body(cc), c)

    jax.block_until_ready(loop(carry))  # compile
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        jax.block_until_ready(loop(carry))
        best = min(best, time.perf_counter() - t0)
    return best / K


def subphase_walls(backend, chunk: int, queue_capacity: int,
                   fp_capacity: int, warm_steps: int = 8,
                   K: int = 4, reps: int = 3,
                   check_deadlock: bool = None,
                   sort_free: bool = False,
                   deferred: bool = False) -> Dict[str, float]:
    """Differential sub-phase attribution on a warmed mid-run carry.

    Drives the real engine `warm_steps` steps (realistic frontier block
    + realistic table load), then times nested partial jits and carves
    the step by subtraction:

        kernel        pop + unpack + vmap(step)           (measured)
        inv           the invariant + certificate MACHINERY at its
                      mode's site, measured as an ISOLATED body (not
                      a difference of stage walls - a sub-ms signal
                      drowns in the noise of two ~10 ms probes):
                      immediate = the chunk*L invariant sweep plus its
                      bad-mask and first-wins any/argmax/gather
                      consumers, composed exactly as the expand stage
                      composes them; deferred (ISSUE 15) = the
                      commit-site claimant checker over a real
                      insert's compacted verdicts - same column, so
                      the before/after of the distinct-first collapse
                      lines up
        fp            the expand-stage remainder: pack + MXU
                      fingerprints + counters + the violation reduce
        expand        the full expand stage                 (measured)
        sort          the in-batch dedup stage: the two full-width
                      stable sorts of fpset_insert_sorted, or (under
                      sort_free=True) the hash-slab dedup that
                      replaces them (fpset.slab_dedup) - same column,
                      so before/after cost models line up
        probe         insert - sort: the fpset probe/claim walk
        enqueue       step - expand - insert: enqueue + stats + fencing
        commit        step - expand (deferred mode: includes the
                      claimant checker, which the `inv` column then
                      attributes)
        step          the real fused step_fn                (measured)

    v2 reported `inv_fp` as one wall; v3 (ISSUE 15) splits it so the
    fit can see which half the deferred evaluation actually moves.
    Returns seconds/step per phase.  CPU numbers are the committed
    COSTMODEL baseline until the TPU tunnel returns (ROADMAP standing
    item); the tool records the device either way."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    from ..engine.backend import make_expand_stage
    from ..engine.bfs import make_backend_engine
    from ..engine.fingerprint import DEFAULT_FP_INDEX, DEFAULT_SEED
    from ..engine.fpset import fpset_insert_dedup, slab_dedup

    cdc = backend.cdc
    W = (cdc.nbits + 31) // 32
    F = cdc.n_fields
    L = backend.n_lanes
    ncand = chunk * L
    R = min(2 * chunk, ncand)

    init_fn, _, step_fn = make_backend_engine(
        backend, chunk, queue_capacity, fp_capacity,
        check_deadlock=check_deadlock, donate=False,
        sort_free=sort_free, deferred=deferred,
    )
    carry = init_fn()
    for _ in range(warm_steps):
        carry = step_fn(carry)
    carry = jax.block_until_ready(carry)

    block = lax.dynamic_slice(
        carry.queue, (carry.parity, carry.qhead, jnp.int32(0)),
        (1, chunk, W),
    )[0]
    batch = cdc.unpack(block)
    mask_all = jnp.ones(chunk, bool)
    expand_stage = make_expand_stage(
        backend, chunk, check_deadlock, DEFAULT_FP_INDEX, DEFAULT_SEED,
        deferred=deferred,
    )
    ex = jax.block_until_ready(expand_stage(batch, mask_all))
    step = backend.step

    # kernel: pop + unpack + vmapped successor kernel only (all five
    # outputs folded so XLA cannot slice the kernel - see _consume)
    def b_kernel(c):
        b = cdc.unpack(block ^ c[None, :])
        s, v, a, af, ov = jax.vmap(step)(b)
        return c ^ (
            s.sum().astype(jnp.uint32) + v.sum().astype(jnp.uint32)
            + a.sum().astype(jnp.uint32) + af.sum().astype(jnp.uint32)
            + ov.sum().astype(jnp.uint32)
        )

    t_kernel = _fused_time(b_kernel, jnp.zeros(W, jnp.uint32), K, reps)

    # full-consumption fold: the inv/fp columns are DIFFERENCES of
    # expand-stage probes, so every probe must materialize everything
    # the real stage hands to commit - a partially-consumed ExpandOut
    # lets XLA slice the computation and understate the phase (the v2
    # inv_fp column partly suffered this)
    def _consume(e):
        return (e.packed.sum() + e.lo.sum() + e.hi.sum()
                + e.valid.sum().astype(jnp.uint32)
                + e.action.sum().astype(jnp.uint32) + e.gen.sum()
                + e.viol.astype(jnp.uint32))

    # the invariant-free expand stage (the deferred stage IS the
    # immediate stage minus the invariant/cert machinery); its wall
    # anchors the `fp` column, and its ExpandOut carries the raw
    # fields both isolated inv probes below consume
    stage_noinv = (expand_stage if deferred else make_expand_stage(
        backend, chunk, check_deadlock, DEFAULT_FP_INDEX, DEFAULT_SEED,
        deferred=True,
    ))

    def b_expand(c):
        e = expand_stage(cdc.unpack(block ^ c[None, :]), mask_all)
        return c ^ _consume(e)

    t_expand = _fused_time(b_expand, jnp.zeros(W, jnp.uint32), K, reps)

    if deferred:
        t_expand_noinv = t_expand
        ex_def = ex
    else:
        def b_expand_noinv(c):
            e = stage_noinv(cdc.unpack(block ^ c[None, :]), mask_all)
            return c ^ _consume(e)

        t_expand_noinv = _fused_time(
            b_expand_noinv, jnp.zeros(W, jnp.uint32), K, reps
        )
        ex_def = jax.block_until_ready(stage_noinv(batch, mask_all))

    # the `inv` column: BOTH sites measured as isolated machinery
    # bodies over the same candidate block, not as differences of
    # ~10x-larger stage walls (a diff of two noisy 9 ms measurements
    # drowns a sub-ms signal - the v3 design note).  Immediate: the
    # chunk*L invariant sweep plus its consumers exactly as
    # make_expand_stage composes them (bad masks + the first-wins
    # any/argmax/gather entries).  Deferred: the commit-site claimant
    # checker over a real insert's compacted verdicts.
    flat0 = ex_def.flat
    inv_check = backend.inv_check
    inv_codes = backend.inv_codes

    def b_inv_imm(x):
        fl = flat0 + x
        iv = jax.vmap(inv_check)(fl)
        viol = jnp.int32(0)
        vstate = jnp.zeros(F, jnp.int32)
        vact = jnp.int32(-1)
        for k, code in enumerate(inv_codes):
            bad = ex.valid & ((iv & (1 << k)) == 0)
            hit = bad.any() & (viol == 0)
            viol = jnp.where(hit, jnp.int32(code), viol)
            vstate = jnp.where(hit, fl[jnp.argmax(bad)], vstate)
            vact = jnp.where(
                hit, ex.action[jnp.argmax(bad)].astype(jnp.int32), vact
            )
        cert = jnp.int32(0)
        if backend.cert_check is not None:
            cert = backend.cert_check(fl, ex.valid).astype(jnp.int32)
        return x + viol + vstate.sum() + vact + cert

    t_inv_imm = _fused_time(b_inv_imm, jnp.int32(0), K, reps)

    # sort: the in-batch dedup stage - the two full-width stable sorts,
    # or the hash-slab dedup that replaces them under -sort-free
    idx = jnp.arange(ncand, dtype=jnp.uint32)

    if sort_free:
        def b_sort(x):
            c_lo, _c_hi, _c_ix, _nreps, _fb = slab_dedup(
                ex.lo ^ x, ex.hi, ex.valid, probe_width=R,
            )
            return x + c_lo[0]
    else:
        def b_sort(x):
            s_hi, s_lo, s_idx = lax.sort(
                (ex.hi, ex.lo ^ x, idx), num_keys=2, is_stable=True
            )
            last = jnp.concatenate(
                [(s_hi[1:] != s_hi[:-1]) | (s_lo[1:] != s_lo[:-1]),
                 jnp.ones(1, bool)]
            )
            rep = ((s_hi != 0) | (s_lo != 0)) & last
            _, c_lo, c_hi, c_idx = lax.sort(
                ((~rep).astype(jnp.uint32), s_lo, s_hi, s_idx),
                num_keys=1, is_stable=True,
            )
            return x + c_lo[0]

    t_sort = _fused_time(b_sort, jnp.uint32(1), K, reps)

    # insert: dedup + probe/claim at real table load (vary lo so the
    # probes are honest; occupancy growth over K reps is negligible)
    def b_ins(c):
        fps_c, x = c
        f2, _, _, _ = fpset_insert_dedup(
            fps_c, ex.lo ^ x, ex.hi, ex.valid,
            probe_width=R, claim_width=R, sort_free=sort_free,
        )
        return (f2, x + jnp.uint32(1))

    t_ins = _fused_time(b_ins, (carry.fps, jnp.uint32(1)), K, reps)

    # deferred mode's inv site, isolated the same way: the claimant
    # checker alone, over the compacted verdicts of a REAL insert of
    # this block (computed once, held constant; the raw fields vary
    # per rep to defeat caching)
    t_inv_def = None
    if deferred:
        from ..engine.backend import make_deferred_checker

        checker = make_deferred_checker(backend, ncand, probe_width=R)
        _, is_new0, c_idx0, nreps0 = jax.block_until_ready(
            fpset_insert_dedup(
                carry.fps, ex.lo, ex.hi, ex.valid,
                probe_width=R, claim_width=R, sort_free=sort_free,
            )
        )

        def b_inv_def(x):
            dv, ds, da, dc = checker(
                flat0 + x, ex.action, is_new0, c_idx0, nreps0
            )
            y = x + dv + ds.sum() + da
            if dc is not None:
                y = y + dc.astype(jnp.int32)
            return y

        t_inv_def = _fused_time(b_inv_def, jnp.int32(0), K, reps)

    # step: the engine's own jitted step (one dispatch per call)
    jax.block_until_ready(step_fn(carry))
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        c2 = carry
        for _ in range(K):
            c2 = step_fn(c2)
        jax.block_until_ready(c2)
        best = min(best, time.perf_counter() - t0)
    t_step = best / K

    t_probe = max(t_ins - t_sort, 0.0)
    t_commit = max(t_step - t_expand, 0.0)
    t_fp = max(t_expand_noinv - t_kernel, 0.0)
    if deferred:
        t_inv = t_inv_def
        t_enqueue = max(t_step - t_expand - t_ins - t_inv, 0.0)
    else:
        t_inv = t_inv_imm
        t_enqueue = max(t_step - t_expand - t_ins, 0.0)
    return {
        "kernel": t_kernel,
        "inv": t_inv,
        "fp": t_fp,
        "expand": t_expand,
        "sort": t_sort,
        "probe": t_probe,
        "enqueue": t_enqueue,
        "commit": t_commit,
        "step": t_step,
    }
