"""Unified observability plane: device counter ring, structured run
journal, and pipeline timeline tracing.

Three tiers, one source of truth:

* **Device tier** (obs.counters): a fixed-shape per-level counter ring
  carried inside every engine carry, written with one contiguous row
  store per level flip and read back only at the segment fences the
  drivers already pay for.
* **Host tier** (obs.journal + obs.schema): a crash-safe append-only
  JSONL run journal - manifest, segments, levels, checkpoints, regrows,
  retries, faults, violations, final verdict - validated against a
  versioned schema at write AND read time.  TLC progress lines, bench
  payloads and the tlcstat dashboard are derived views (obs.views).
* **Timeline tier** (obs.trace): Chrome-trace/Perfetto export of the
  journal (`-trace-out`), plus the `-xprof DIR` jax.profiler hook in
  the CLI for ground-truth device timelines.

The live ops plane rides on top (ISSUE 8): **phase attribution**
(obs.phases - free segment-scope walls at every fence, measured
per-level expand/commit walls behind `-phase-timing`) and the
**run-monitoring server** (obs.serve - /metrics Prometheus text,
/events SSE journal tail, /runs registry; `-serve PORT` or
`python -m jaxtlc.obs.serve`), with tools/costmodel.py fitting the
per-phase cost model from the phase events.
"""

from .counters import (  # noqa: F401
    DEFAULT_OBS_SLOTS,
    ring_cols,
    ring_new,
    rows_from_ring,
    shard_rows_from_ring,
)
from .journal import RunJournal, read as read_journal  # noqa: F401
from .phases import PhaseRecorder, segment_phases  # noqa: F401
from .schema import (  # noqa: F401
    SCHEMA_VERSION,
    JournalSchemaError,
    validate_event,
)
from .trace import export_chrome_trace  # noqa: F401
from .views import bench_payload, render_tlc_event  # noqa: F401
