"""Thin client for the checking service (stdlib urllib only).

`submit` posts a job, `wait` polls it to completion, `stream` follows
the job-scoped SSE event feed, `check` is submit+wait in one call,
`cancel` is DELETE /jobs/<id>.  A 429 from admission control (ISSUE
17) is retried automatically, honoring the server's drain-rate
``Retry-After`` with capped deterministic-jitter backoff.
The CLI form drives a live server from a model directory::

    python -m jaxtlc.serve.client http://HOST:PORT path/to/MC.cfg \
        [--name N] [--chunk 64] [--qcap 1024] [--fpcap 4096] \
        [--sweep CONST:LO:HI --set CONST=V]

tools/loadgen.py uses exactly these calls to drive its load test.
"""

from __future__ import annotations

import json
import os
import random
import time
import urllib.error
import urllib.request
from typing import Dict, Iterator, Optional

# deterministic jitter for the 429 backoff: two identical overload
# replays back off on the same clock
_RNG = random.Random(0x5EED429)


class ClientError(RuntimeError):
    """An HTTP-level failure.  `code` is the status (0 when the error
    was not an HTTP response); `retry_after` carries a 429's
    Retry-After hint in seconds (None otherwise)."""

    def __init__(self, msg: str, code: int = 0,
                 retry_after: Optional[int] = None):
        super().__init__(msg)
        self.code = int(code)
        self.retry_after = retry_after


def _post(url: str, payload: dict, timeout: float = 30.0) -> dict:
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"}, method="POST",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        ra = e.headers.get("Retry-After")
        raise ClientError(f"{url}: {e.code} {e.read().decode()}",
                          code=e.code,
                          retry_after=(int(ra) if ra else None))


def _get(url: str, timeout: float = 30.0) -> dict:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return json.loads(r.read().decode())


def submit(url: str, spec: str, cfg: str, name: str = "",
           constants: Optional[Dict] = None, sweep: Optional[Dict] = None,
           options: Optional[Dict] = None, tenant: str = None,
           retries: int = 4, backoff_cap_s: float = 30.0) -> str:
    """POST /jobs; returns the job id.

    A 429 (admission control) is retried up to `retries` times: each
    attempt sleeps the server's Retry-After hint scaled by a
    deterministic jitter in [0.5, 1.0), doubled per attempt and capped
    at `backoff_cap_s` - honoring the server's estimate without
    thundering back in lockstep.  `retries=0` surfaces the 429 raw."""
    attempt = 0
    while True:
        try:
            out = _post(url.rstrip("/") + "/jobs", {
                "spec": spec, "cfg": cfg, "name": name,
                "constants": constants or {}, "sweep": sweep,
                "options": options or {}, "tenant": tenant,
            })
            return out["id"]
        except ClientError as e:
            if e.code != 429 or attempt >= retries:
                raise
            attempt += 1
            hint = max(1, e.retry_after or 1)
            delay = min(backoff_cap_s, hint * (2 ** (attempt - 1)))
            time.sleep(delay * (0.5 + 0.5 * _RNG.random()))


def status(url: str, job_id: str) -> dict:
    return _get(f"{url.rstrip('/')}/jobs/{job_id}")


def wait(url: str, job_id: str, timeout: float = 300.0,
         poll_s: float = 0.05) -> dict:
    """Poll until the job leaves queued/running; returns its record.
    Returns immediately on EVERY terminal state - done, error, and the
    scheduler-terminal expired / canceled / quarantined (ISSUE 17)."""
    deadline = time.time() + timeout
    while True:
        st = status(url, job_id)
        if st["state"] not in ("queued", "running"):
            return st
        if time.time() > deadline:
            raise ClientError(f"job {job_id} still {st['state']} "
                              f"after {timeout}s")
        time.sleep(poll_s)


def cancel(url: str, job_id: str, timeout: float = 30.0) -> dict:
    """DELETE /jobs/<id>; returns the job record (state `canceled`
    for a queued job; a running checkpointed heavy job drains through
    the preempt path and reaches `canceled` shortly after)."""
    req = urllib.request.Request(f"{url.rstrip('/')}/jobs/{job_id}",
                                 method="DELETE")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return json.loads(r.read().decode())
    except urllib.error.HTTPError as e:
        raise ClientError(f"{url}: {e.code} {e.read().decode()}",
                          code=e.code)


def health(url: str) -> dict:
    return _get(url.rstrip("/") + "/health")


def check(url: str, spec: str, cfg: str, **kw) -> dict:
    """submit + wait: the one-call remote analog of api.run_check."""
    timeout = kw.pop("timeout", 300.0)
    return wait(url, submit(url, spec, cfg, **kw), timeout=timeout)


def stream(url: str, job_id: str, timeout: float = 300.0) -> Iterator[dict]:
    """Follow the job-scoped SSE feed (`/events?run=<id>`), yielding
    event dicts until the job's `final` event arrives."""
    u = f"{url.rstrip('/')}/events?run={job_id}"
    with urllib.request.urlopen(u, timeout=timeout) as r:
        while True:
            line = r.readline()
            if not line:
                return
            if line.startswith(b"data: "):
                ev = json.loads(line[6:].decode())
                yield ev
                if ev.get("event") == "final":
                    return


def pool_stats(url: str) -> dict:
    return _get(url.rstrip("/") + "/pool")


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(prog="jaxtlc.serve.client")
    p.add_argument("url", help="server base URL (http://host:port)")
    p.add_argument("config", help="path to a model .cfg (the sibling "
                                  ".tla module is read and shipped)")
    p.add_argument("--name", default="")
    p.add_argument("--chunk", type=int, default=64)
    p.add_argument("--qcap", type=int, default=1 << 10)
    p.add_argument("--fpcap", type=int, default=1 << 12)
    p.add_argument("--sweep", default="",
                   help="CONST:LO:HI - mark CONST sweepable over "
                        "[LO, HI] (compatible jobs batch)")
    p.add_argument("--set", dest="sets", action="append", default=[],
                   metavar="CONST=V", help="constant override")
    p.add_argument("--timeout", type=float, default=300.0)
    args = p.parse_args(argv)

    model_dir = os.path.dirname(os.path.abspath(args.config))
    base = os.path.splitext(os.path.basename(args.config))[0]
    tla = os.path.join(model_dir, f"{base}.tla")
    with open(args.config) as f:
        cfg = f.read()
    with open(tla) as f:
        spec = f.read()
    constants = {}
    for s in args.sets:
        k, _, v = s.partition("=")
        constants[k.strip()] = int(v)
    sweep = None
    if args.sweep:
        c, lo, hi = args.sweep.split(":")
        sweep = {"const": c, "lo": int(lo), "hi": int(hi)}
    st = check(
        args.url, spec, cfg, name=args.name or base,
        constants=constants, sweep=sweep,
        options=dict(chunk=args.chunk, qcap=args.qcap,
                     fpcap=args.fpcap),
        timeout=args.timeout,
    )
    print(json.dumps(st, indent=2, sort_keys=True))
    return 0 if st["state"] == "done" else 1


if __name__ == "__main__":  # pragma: no cover
    import sys

    sys.exit(main())
