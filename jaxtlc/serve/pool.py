"""Warm AOT engine pool: the serving tier's compile amortizer.

A checking service lives or dies on cold-start amortization (the
TensorFlow-serving lesson in PAPERS.md: compile the graph once, serve
it forever).  The pool holds FULLY COMPILED engines - the AOT
executable, not just the jit closures - keyed by the struct-cache memo
key for plain engines (`struct.cache.engine_key`: spec digest x
canonical constants x geometry x pipeline/obs flags) and by the
constants-CLASS key for sweep engines (`sweep.class_key`: the swept
values drop out, which is what lets one entry serve a whole config
portfolio).  LRU eviction bounds a long-lived process; hit/miss/
eviction/compile counters make the warm-path contract assertable.

The contract - **warm submit performs ZERO fresh XLA compiles** - is
pinned by `CompileMeter`, which counts jax's own
`/jax/core/compile/backend_compile_duration` monitoring events: every
real backend compile fires one, a warm AOT call fires none, so a test
(and `tools/loadgen.py --tiny`) can assert the meter's delta across a
resubmit is exactly zero.  Our own `compiles` counter says when the
POOL built; the meter says what XLA actually did - the two together
catch both a broken pool key and a silently-recompiling executable.
"""

from __future__ import annotations

import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

from ..engine.fingerprint import DEFAULT_FP_INDEX, DEFAULT_SEED


class CompileMeter:
    """Process-wide XLA backend-compile counter (jax.monitoring).

    Counts `/jax/core/compile/backend_compile_duration` events - fired
    once per real XLA compile (AOT .compile() included, persistent-
    cache hits included: deserialization still passes through the
    event), never by a warm executable call.  Monotonic; assert on
    deltas.

    Registration prefers the public ``jax.monitoring`` module (the
    private ``jax._src`` spelling is a fallback for old jaxes) and a
    jax that exposes neither degrades the meter to ``available=False``
    - the count stays zero and pool construction/server start proceed;
    only the zero-compile ASSERTION loses its ground truth, and
    callers can see that in `/pool`'s ``xla_meter`` field."""

    _instance: Optional["CompileMeter"] = None

    def __init__(self):
        self.count = 0
        self.wall_s = 0.0
        self.available = False
        self._lock = threading.Lock()

        def on_event(name, duration, **kw):
            if name.endswith("backend_compile_duration"):
                with self._lock:
                    self.count += 1
                    self.wall_s += float(duration)

        try:
            try:
                from jax import monitoring
            except ImportError:  # pragma: no cover - pre-public-API jax
                from jax._src import monitoring
            monitoring.register_event_duration_secs_listener(on_event)
            self.available = True
        except Exception:  # pragma: no cover - a metric, not a fault line
            pass

    @classmethod
    def instance(cls) -> "CompileMeter":
        if cls._instance is None:
            cls._instance = CompileMeter()
        return cls._instance


def xla_compiles() -> int:
    """Monotonic count of real XLA compiles this process performed."""
    return CompileMeter.instance().count


class PoolEntry:
    """One warm engine: the AOT executable plus everything needed to
    run a job against it without touching the compiler."""

    def __init__(self, key, kind: str, runner, meta: dict):
        self.key = key
        self.kind = kind  # "single" | "sweep"
        self.runner = runner  # _SingleRunner | sweep.SweepEngine
        self.meta = meta
        self.built_t = time.time()
        self.last_used = self.built_t
        self.uses = 0


class _SingleRunner:
    """AOT wrapper for one plain struct engine (one model, one config):
    compile once at build, fresh carry + warm executable per job."""

    def __init__(self, model, chunk, queue_capacity, fp_capacity,
                 fp_index, seed, check_deadlock, pipeline, obs_slots,
                 sort_free=None, deferred=None):
        from ..engine.bfs import DEFAULT_FP_HIGHWATER
        from ..struct.cache import get_backend, get_engine

        self.model = model
        self.fp_capacity = fp_capacity
        self.backend = get_backend(model, check_deadlock)
        init_fn, run_fn, _ = get_engine(
            model, chunk, queue_capacity, fp_capacity, fp_index, seed,
            DEFAULT_FP_HIGHWATER, check_deadlock=check_deadlock,
            pipeline=pipeline, obs_slots=obs_slots, sort_free=sort_free,
            deferred=deferred,
        )
        import jax

        # the engine memo shares jit closures; the POOL owns the AOT
        # executables so a warm submit never re-lowers or re-traces
        # (lower().compile() bypasses the jit call cache, and an EAGER
        # init_fn re-compiles its fpset while_loop per call - both
        # would make every submit of a memo-hit engine pay fresh XLA
        # compiles; the zero-compile warm contract pins this)
        self._mk_carry = jax.jit(lambda: init_fn())
        carry0 = self._mk_carry()
        self._aot = run_fn.lower(carry0).compile()

    def run(self, capture_fps: bool = False):
        import jax

        from ..engine.bfs import result_from_carry
        from ..struct.backend import struct_viol_names

        carry = self._mk_carry()
        t0 = time.time()
        out = jax.block_until_ready(self._aot(carry))
        wall = time.time() - t0
        result = result_from_carry(
            out, wall, fp_capacity=self.fp_capacity,
            labels=self.backend.labels,
            viol_names=struct_viol_names(self.model),
        )
        if capture_fps and result.violation == 0:
            # the artifact cache's reachable-set source (ISSUE 13):
            # one host copy of the final table, clean verdicts only
            import numpy as np

            result = result._replace(
                fp_table=np.asarray(jax.device_get(out.fps.table))
            )
        return result


class EnginePool:
    """LRU pool of warm AOT engines (thread-safe: the HTTP handlers
    read stats while the scheduler thread builds/runs)."""

    def __init__(self, capacity: int = 8,
                 sweep_width: int = None):
        from .sweep import DEFAULT_WIDTH

        self.capacity = max(1, int(capacity))
        self.sweep_width = sweep_width or DEFAULT_WIDTH
        self._entries: "OrderedDict[tuple, PoolEntry]" = OrderedDict()
        self._lock = threading.RLock()
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.compiles = 0  # pool-level builds (one per miss)
        self.compile_wall_s = 0.0
        # --prewarm accounting (ISSUE 13 satellite): engines compiled
        # ahead of traffic so the FIRST submit rides the warm path
        self.prewarmed = 0
        self.prewarm_errors = 0
        self.prewarm_wall_s = 0.0
        CompileMeter.instance()  # start metering before the first build

    # -- lookup ------------------------------------------------------------

    def _get_or_build(self, key, build, kind: str, meta: dict):
        with self._lock:
            hit = self._entries.get(key)
            if hit is not None:
                self.hits += 1
                hit.uses += 1
                hit.last_used = time.time()
                self._entries.move_to_end(key)
                return hit
            self.misses += 1
        # build OUTSIDE the lock: compiles are seconds-to-minutes and
        # stats reads must not block behind them
        t0 = time.time()
        runner = build()
        wall = time.time() - t0
        entry = PoolEntry(key, kind, runner, meta)
        with self._lock:
            self.compiles += 1
            self.compile_wall_s += wall
            self._entries[key] = entry
            self._entries.move_to_end(key)
            while len(self._entries) > self.capacity:
                self._entries.popitem(last=False)
                self.evictions += 1
        return entry

    def get_single(
        self,
        model,
        chunk: int = 64,
        queue_capacity: int = 1 << 10,
        fp_capacity: int = 1 << 12,
        fp_index: int = DEFAULT_FP_INDEX,
        seed: int = DEFAULT_SEED,
        check_deadlock: bool = True,
        pipeline: bool = False,
        obs_slots: int = 0,
        sort_free: bool = None,
        deferred: bool = None,
    ) -> PoolEntry:
        """Warm plain engine for (model meaning, geometry) - keyed on
        the struct-cache memo key, so pool identity == memo identity."""
        from ..engine.bfs import DEFAULT_FP_HIGHWATER
        from ..struct.cache import engine_key

        key = engine_key(
            model, chunk, queue_capacity, fp_capacity, fp_index, seed,
            DEFAULT_FP_HIGHWATER, check_deadlock=check_deadlock,
            pipeline=pipeline, obs_slots=obs_slots, sort_free=sort_free,
            deferred=deferred,
        )
        return self._get_or_build(
            key,
            lambda: _SingleRunner(
                model, chunk, queue_capacity, fp_capacity, fp_index,
                seed, check_deadlock, pipeline, obs_slots,
                sort_free=sort_free, deferred=deferred,
            ),
            "single",
            dict(workload=model.root_name, chunk=chunk,
                 fp_capacity=fp_capacity),
        )

    def get_sweep(
        self,
        model,
        params: Dict[str, Tuple[int, int]],
        chunk: int = 64,
        queue_capacity: int = 1 << 10,
        fp_capacity: int = 1 << 12,
        fp_index: int = DEFAULT_FP_INDEX,
        seed: int = DEFAULT_SEED,
        check_deadlock: bool = True,
        sort_free: bool = None,
        deferred: bool = None,
    ) -> PoolEntry:
        """Warm constants-class sweep engine: one entry per CLASS (the
        swept values are runtime data, not key material)."""
        from ..engine.bfs import resolve_deferred, resolve_sort_free
        from .sweep import SweepEngine, class_key

        key = ("sweep", class_key(model, params), chunk, queue_capacity,
               fp_capacity, fp_index, seed, bool(check_deadlock),
               int(self.sweep_width), resolve_sort_free(sort_free, chunk),
               resolve_deferred(deferred, chunk))
        return self._get_or_build(
            key,
            lambda: SweepEngine(
                model, params, chunk=chunk,
                queue_capacity=queue_capacity, fp_capacity=fp_capacity,
                fp_index=fp_index, seed=seed,
                check_deadlock=check_deadlock, width=self.sweep_width,
                sort_free=sort_free, deferred=deferred,
            ),
            "sweep",
            dict(workload=model.root_name, chunk=chunk,
                 fp_capacity=fp_capacity,
                 params={c: list(d) for c, d in sorted(params.items())}),
        )

    def get_sim(
        self,
        model,
        params: Optional[Dict[str, Tuple[int, int]]] = None,
        walkers: int = 64,
        depth: int = 64,
        fp_capacity: int = 0,
        check_deadlock: bool = True,
    ) -> PoolEntry:
        """Warm random-walk engine for the smoke job class (jaxtlc.sim,
        ISSUE 14), keyed like the sweep classes: the SEED is run data
        (a vmapped batch lane), so one entry serves every per-commit
        smoke submit of a spec, and `params` (swept constant domains)
        keys a seeds-x-configs class exactly as sweep.class_key does."""
        from ..sim.engine import SimEngine, sim_engine_key
        from .sweep import class_key

        if params:
            key = ("sim-sweep", class_key(model, params), int(walkers),
                   int(depth), int(fp_capacity), bool(check_deadlock),
                   int(self.sweep_width))
        else:
            key = sim_engine_key(
                model, walkers, depth, fp_capacity, check_deadlock
            ) + (int(self.sweep_width),)
        return self._get_or_build(
            key,
            lambda: SimEngine(
                model, params=params, walkers=walkers, depth=depth,
                fp_capacity=fp_capacity, check_deadlock=check_deadlock,
                width=self.sweep_width,
            ),
            "sim",
            dict(workload=model.root_name, walkers=int(walkers),
                 depth=int(depth), fp_capacity=int(fp_capacity)),
        )

    def get_infer(
        self,
        model,
        budget: int = 64,
        walkers: int = 64,
        depth: int = 64,
        check_deadlock: bool = True,
        max_host_states: int = None,
    ) -> PoolEntry:
        """Warm inference engine for the infer job class (jaxtlc.infer,
        ISSUE 16).  Like sim, the SEED is run data - candidate pool,
        filter/certify kernels (AOT against their fixed block shapes)
        and exact evidence all build once per (model, budget, walk
        geometry) class, so a warm resubmit is pure dispatch."""
        from ..infer.driver import InferEngine
        from ..infer.filter import DEFAULT_MAX_HOST_STATES
        from ..struct.cache import model_key

        if max_host_states is None:
            max_host_states = DEFAULT_MAX_HOST_STATES
        key = ("infer", model_key(model), int(budget), int(walkers),
               int(depth), bool(check_deadlock), int(max_host_states))
        return self._get_or_build(
            key,
            lambda: InferEngine(
                model, budget=budget, walkers=walkers, depth=depth,
                check_deadlock=check_deadlock,
                max_host_states=max_host_states,
            ),
            "infer",
            dict(workload=model.root_name, budget=int(budget),
                 walkers=int(walkers), depth=int(depth)),
        )

    # -- prewarm (ISSUE 13 satellite) --------------------------------------

    def prewarm(self, specs, chunk: int = None, queue_capacity: int = None,
                fp_capacity: int = None) -> dict:
        """Compile the listed models into the pool ahead of traffic.

        `specs` is a list of ``CFG`` paths (or ``SPEC:CFG`` pairs - the
        spec half is informational; the loader reads the sibling .tla
        from the cfg's directory anyway).  Geometry defaults to the
        scheduler's pooled-path defaults, so a prewarmed engine and a
        default submit land on the SAME pool key: the first submit of a
        prewarmed spec rides the disk-warm/AOT path (0.77 s class)
        instead of the true-cold path (4.8 s class, PERF.md round 12).
        Errors are counted, never fatal - a bad prewarm entry must not
        stop the server."""
        from ..struct.loader import load
        from .scheduler import DEFAULT_CHUNK, DEFAULT_FPCAP, DEFAULT_QCAP

        chunk = chunk or DEFAULT_CHUNK
        queue_capacity = queue_capacity or DEFAULT_QCAP
        fp_capacity = fp_capacity or DEFAULT_FPCAP
        report = {"ok": [], "errors": []}
        for item in specs:
            cfg = item.split(":", 1)[1] if ":" in item else item
            t0 = time.time()
            try:
                model = load(cfg)
                self.get_single(model, chunk=chunk,
                                queue_capacity=queue_capacity,
                                fp_capacity=fp_capacity)
            except Exception as e:  # noqa: BLE001 - count, don't die
                with self._lock:
                    self.prewarm_errors += 1
                report["errors"].append(f"{cfg}: {e}")
                continue
            wall = time.time() - t0
            with self._lock:
                self.prewarmed += 1
                self.prewarm_wall_s += wall
            report["ok"].append(dict(cfg=cfg, workload=model.root_name,
                                     wall_s=round(wall, 3)))
        return report

    # -- introspection -----------------------------------------------------

    def stats(self) -> dict:
        """Pool + memo + compile-meter counters (the /pool endpoint)."""
        from ..struct import cache as struct_cache

        meter = CompileMeter.instance()
        with self._lock:
            entries = [
                dict(kind=e.kind, uses=e.uses,
                     built_t=round(e.built_t, 3),
                     last_used=round(e.last_used, 3), **e.meta)
                for e in self._entries.values()
            ]
            return dict(
                capacity=self.capacity,
                size=len(self._entries),
                hits=self.hits,
                misses=self.misses,
                evictions=self.evictions,
                compiles=self.compiles,
                compile_wall_s=round(self.compile_wall_s, 6),
                prewarmed=self.prewarmed,
                prewarm_errors=self.prewarm_errors,
                prewarm_wall_s=round(self.prewarm_wall_s, 6),
                xla_compiles=meter.count,
                xla_compile_wall_s=round(meter.wall_s, 6),
                xla_meter="ok" if meter.available else "unavailable",
                sweep_width=self.sweep_width,
                memo=struct_cache.stats(),
                entries=entries,
            )

    def keys(self) -> List[tuple]:
        with self._lock:
            return list(self._entries)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
