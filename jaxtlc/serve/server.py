"""Checking-as-a-service: the HTTP job API (stdlib only).

The front door of ROADMAP #4: a long-lived process that accepts
spec+cfg jobs, runs them through the FIFO scheduler (serve.scheduler)
against the warm AOT engine pool (serve.pool), and serves results plus
live telemetry.  The monitoring surface IS obs.serve - this handler
subclasses it, so ``/runs``, ``/metrics``, ``/journal`` and the SSE
``/events`` tail come from the same code the single-run ``-serve``
monitor uses, reading the per-job journals the scheduler writes.  A
job-scoped event stream is just ``/events?run=<job id>``.

Endpoints (on top of the inherited monitor):

* ``POST /jobs`` - submit a check.  JSON body::

      {"name": "...", "spec": "---- MODULE M ----\\n...",
       "cfg": "CONSTANT ...", "constants": {"N": 3},
       "tenant": "ci", "sweep": {"const": "N", "lo": 1, "hi": 4},
       "options": {"chunk": 64, "qcap": 1024, "fpcap": 4096,
                   "priority": 5, "deadline_s": 30}}

  -> 202 with the job id + the URLs to poll/stream.  Compatible sweep
  jobs batch into one vmapped dispatch; large jobs route through the
  resil supervisor (see serve.scheduler for the discipline).  An
  over-limit submit (queue bound / tenant quota) is **429** with a
  ``Retry-After`` header computed from the measured drain rate.
* ``GET /jobs`` - the job registry (state, engine, result per job).
* ``GET /jobs/<id>`` - one job's record (the verdict lives here).
* ``DELETE /jobs/<id>`` - cancel: a queued job flips to the terminal
  ``canceled`` state; a running checkpointed heavy job drains through
  the programmatic preempt path (ISSUE 17).
* ``GET /health`` - scheduler liveness: queue depth vs bound, drain
  rate, open breakers (``status`` flips to "overloaded" at 80% of the
  admission bound).
* ``GET /pool`` - engine-pool + scheduler + compile-meter stats (the
  warm/cold accounting ``tools/loadgen.py`` asserts on).

``python -m jaxtlc.serve`` starts it; ``jaxtlc.serve.client`` is the
thin submit/wait/stream client driving it.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
from typing import Optional

from ..obs import serve as obs_serve
from .pool import EnginePool
from .scheduler import AdmissionError, JobError, Scheduler


class _JobHandler(obs_serve._Handler):
    """The monitor handler + the job API.  `scheduler` is stamped
    class-wide by CheckServer (same pattern as `root`)."""

    scheduler: Scheduler = None

    # -- job API -----------------------------------------------------------

    def do_POST(self):  # noqa: N802 - BaseHTTPRequestHandler contract
        parsed_path = self.path.rstrip("/")
        if parsed_path != "/jobs":
            self._send(404, b"unknown endpoint\n", "text/plain")
            return
        try:
            n = int(self.headers.get("Content-Length", 0))
            body = json.loads(self.rfile.read(n).decode() or "{}")
            spec, cfg = body.get("spec"), body.get("cfg")
            if not spec or not cfg:
                raise JobError("body needs 'spec' and 'cfg' text")
            job = self.scheduler.submit(
                spec, cfg, name=body.get("name", ""),
                constants=body.get("constants"),
                sweep=body.get("sweep"),
                options=body.get("options"),
                tenant=body.get("tenant"),
            )
        except AdmissionError as e:
            # admission control: 429 + the drain-rate Retry-After the
            # client's backoff honors (serve.client)
            payload = json.dumps({
                "error": str(e), "retry_after": e.retry_after,
            }).encode()
            self.send_response(429)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(payload)))
            self.send_header("Retry-After", str(e.retry_after))
            self.end_headers()
            try:
                self.wfile.write(payload)
            except (BrokenPipeError, ConnectionResetError):
                pass
            return
        except (JobError, ValueError) as e:
            self._send(400, f"bad job: {e}\n".encode(), "text/plain")
            return
        self._send(202, json.dumps({
            "id": job.id,
            "job": f"/jobs/{job.id}",
            "events": f"/events?run={job.id}",
            "journal": f"/journal?run={job.id}",
        }).encode(), "application/json")

    def do_GET(self):  # noqa: N802
        route = self.path.split("?", 1)[0].rstrip("/") or "/"
        try:
            if route == "/jobs":
                self._send(200, json.dumps(
                    {"jobs": self.scheduler.list()}
                ).encode(), "application/json")
            elif route.startswith("/jobs/"):
                job = self.scheduler.get(route[len("/jobs/"):])
                if job is None:
                    self._send(404, b"no such job\n", "text/plain")
                    return
                self._send(200, json.dumps(job.summary()).encode(),
                           "application/json")
            elif route == "/pool":
                self._send(200, json.dumps({
                    "pool": self.scheduler.pool.stats(),
                    "scheduler": self.scheduler.stats(),
                }).encode(), "application/json")
            elif route == "/health":
                self._send(200,
                           json.dumps(self.scheduler.health()).encode(),
                           "application/json")
            else:
                super().do_GET()
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away mid-write: their call

    def do_DELETE(self):  # noqa: N802
        route = self.path.split("?", 1)[0].rstrip("/")
        if not route.startswith("/jobs/"):
            self._send(404, b"unknown endpoint\n", "text/plain")
            return
        try:
            job = self.scheduler.cancel(route[len("/jobs/"):])
            if job is None:
                self._send(404, b"no such job\n", "text/plain")
                return
            self._send(200, json.dumps(job.summary()).encode(),
                       "application/json")
        except (BrokenPipeError, ConnectionResetError):
            pass


class CheckServer:
    """A running checking service: HTTP front + scheduler + pool over
    one runs directory.  `port=0` binds ephemeral; read `.port`."""

    def __init__(self, root: Optional[str] = None, port: int = 0,
                 host: str = "127.0.0.1", pool: EnginePool = None,
                 pool_capacity: int = 8, sweep_width: int = None,
                 large_fpcap: int = None, prewarm: list = None,
                 queue_bound: int = None, tenant_quota: int = None,
                 tenant_weights: dict = None, job_retries: int = None,
                 breaker_threshold: int = None,
                 breaker_cooldown_s: float = None, faults=None):
        from http.server import ThreadingHTTPServer

        from .scheduler import DEFAULT_LARGE_FPCAP

        self.root = root or tempfile.mkdtemp(prefix="jaxtlc-serve-")
        os.makedirs(self.root, exist_ok=True)
        self.pool = pool or EnginePool(capacity=pool_capacity,
                                       sweep_width=sweep_width)
        sched_kw = {k: v for k, v in dict(
            queue_bound=queue_bound, tenant_quota=tenant_quota,
            tenant_weights=tenant_weights, job_retries=job_retries,
            breaker_threshold=breaker_threshold,
            breaker_cooldown_s=breaker_cooldown_s, faults=faults,
        ).items() if v is not None}
        self.scheduler = Scheduler(
            self.root, pool=self.pool,
            large_fpcap=large_fpcap or DEFAULT_LARGE_FPCAP,
            **sched_kw,
        )
        if prewarm:
            # compile ahead of traffic WITHOUT blocking startup; /pool's
            # prewarmed counter reports progress (ISSUE 13 satellite)
            threading.Thread(
                target=self.pool.prewarm, args=(list(prewarm),),
                daemon=True,
            ).start()
        handler = type("BoundJobHandler", (_JobHandler,),
                       {"root": self.root, "scheduler": self.scheduler})
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.httpd._jaxtlc_shutdown = threading.Event()
        self.httpd.daemon_threads = True
        self.host = host
        self.port = self.httpd.server_address[1]
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def shutdown(self) -> None:
        self.scheduler.shutdown()
        self.httpd._jaxtlc_shutdown.set()
        self.httpd.shutdown()
        self.httpd.server_close()


def start_server(root: Optional[str] = None, port: int = 0,
                 host: str = "127.0.0.1", **kw) -> CheckServer:
    """Start the checking service; returns the running CheckServer."""
    return CheckServer(root, port=port, host=host, **kw)
