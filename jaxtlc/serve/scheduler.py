"""FIFO job scheduler: many checks, one device, warm engines.

The queue discipline of the checking service (serve.server): jobs run
in submission order, but the scheduler looks ahead for **compatible
small jobs** - same spec text, same cfg, same geometry, same sweep
descriptor, constants differing only in the swept names - and folds up
to `pool.sweep_width` of them into ONE vmapped dispatch through the
constants-class sweep engine.  Everything else runs alone:

* small struct jobs without a sweep descriptor go through the pool's
  warm plain engine (AOT executable; warm submit = zero fresh XLA
  compiles - the pool's assertable contract);
* large jobs (geometry above `large_fpcap`, or any resilience option:
  checkpoint/recover/sharded/liveness/faults) route through
  `api.run_check`, i.e. the resil supervisor with auto-regrow, the
  degradation ladder, and the full TLC transcript.

Before any of that, the incremental re-checking cache
(struct.artifacts, ISSUE 13) gets first refusal on pooled jobs: an
unchanged spec is answered from the verdict tier in O(HTTP) (job
engine "cache" - no pool lookup, no engine dispatch), and a spec with
a stored reachable set routes through api.run_check's reach tier,
which skips BFS and re-evaluates only the invariants.  Sweep jobs
bypass the cache (their per-config results live in one vmapped
dispatch; caching them is a per-lane story for later).

Every job writes its own journal into the server root - the /runs
registry and the job-scoped SSE stream (`/events?run=<job id>`) are the
existing obs.serve machinery reading those files.  Scheduler-run jobs
journal in batched-fsync mode (obs.journal fsync_every): job journals
are high-rate telemetry, and a crash loses at most a tail the
scheduler re-reports in the job record anyway.
"""

from __future__ import annotations

import hashlib
import io
import json
import os
import threading
import time
import uuid
from collections import OrderedDict, deque
from typing import Dict, List, Optional

from .pool import EnginePool

JOB_FSYNC_EVERY = 16  # batched-fsync journals for scheduler-run jobs
DEFAULT_LARGE_FPCAP = 1 << 16  # above this, a job is "large"

# the pooled path's default engine geometry - ALSO the geometry
# `--prewarm` compiles against, so a prewarmed engine and a default
# submit land on the same pool key
DEFAULT_CHUNK = 64
DEFAULT_QCAP = 1 << 10
DEFAULT_FPCAP = 1 << 12

# the smoke job class's default walk geometry (jaxtlc.sim, ISSUE 14):
# cheap enough for "check something on every commit in 2 seconds",
# overridable per job via options walkers/depth
DEFAULT_SIM_WALKERS = 64
DEFAULT_SIM_DEPTH = 64

# job options forwarded to api.CheckRequest on the supervised path
_REQUEST_OPTIONS = (
    "workers", "frontend", "chunk", "qcap", "fpcap", "pipeline",
    "sortfree", "deferredinv", "sharded", "checkpoint", "recover",
    "liveness",
    "fairness", "nodeadlock", "faults", "retry", "maxregrow", "spill",
    "obs", "obsslots", "coverage", "recheck", "noartifactcache",
    "simulate", "depth", "walkers", "simseed",
    "infer", "inferbudget",
)
_HEAVY_OPTIONS = ("checkpoint", "recover", "sharded", "liveness",
                  "faults", "coverage")


class JobError(ValueError):
    pass


class Job:
    """One submitted check: spec + cfg text, optional constant
    overrides, optional sweep descriptor, engine options."""

    def __init__(self, spec: str, cfg: str, name: str = "",
                 constants: Optional[dict] = None,
                 sweep: Optional[dict] = None,
                 options: Optional[dict] = None):
        self.id = f"job-{uuid.uuid4().hex[:10]}"
        self.spec = spec
        self.cfg = cfg
        self.name = name or self.id
        self.constants = dict(constants or {})
        self.sweep = dict(sweep) if sweep else None
        self.options = dict(options or {})
        self.state = "queued"  # queued | running | done | error
        self.result: Optional[dict] = None
        self.error: Optional[str] = None
        self.engine = ""  # "sweep" | "pool" | "supervised"
        self.submitted_t = time.time()
        self.started_t: Optional[float] = None
        self.finished_t: Optional[float] = None

    # -- routing -----------------------------------------------------------

    def sweep_params(self) -> Dict[str, tuple]:
        """{const: (lo, hi)} from the job's sweep descriptor."""
        if not self.sweep:
            return {}
        c = self.sweep.get("const")
        if not c:
            raise JobError("sweep descriptor needs a 'const' name")
        if self.sweep.get("hi") is None:
            raise JobError("sweep descriptor needs a 'hi' domain bound")
        lo, hi = int(self.sweep.get("lo", 0)), int(self.sweep["hi"])
        return {c: (lo, hi)}

    def is_large(self, large_fpcap: int) -> bool:
        if any(self.options.get(k) for k in _HEAVY_OPTIONS):
            return True
        return int(self.options.get("fpcap", 1 << 12)) > large_fpcap

    def is_smoke(self) -> bool:
        """The simulation job class (options.simulate): random walks
        through the warm sim engine - the cheap per-commit check."""
        return bool(self.options.get("simulate"))

    def is_infer(self) -> bool:
        """The inference job class (options.infer): conjecture ->
        filter -> certify through the warm infer engine (ISSUE 16)."""
        return bool(self.options.get("infer"))

    def batch_signature(self) -> str:
        """Jobs with equal signatures fold into one vmapped dispatch:
        identical spec/cfg/options/sweep, constants equal OUTSIDE the
        swept names (inside them is the batch axis).  Smoke jobs
        additionally drop `simseed` from the compared options - the
        seed is a batch lane, so one warm sim engine serves seeds x
        configs in one dispatch (ISSUE 14).  Infer jobs drop it too:
        the seed is run data against one warm infer engine (ISSUE
        16)."""
        fixed = {k: v for k, v in sorted(self.constants.items())
                 if k not in self.sweep_params()}
        opts = {k: v for k, v in self.options.items()
                if not ((self.is_smoke() or self.is_infer())
                        and k == "simseed")}
        blob = json.dumps(
            [self.spec, self.cfg, sorted(opts.items()),
             sorted((self.sweep or {}).items()), fixed],
            sort_keys=True,
        )
        return hashlib.sha256(blob.encode()).hexdigest()

    def summary(self) -> dict:
        return dict(
            id=self.id, name=self.name, state=self.state,
            engine=self.engine, sweep=self.sweep,
            constants=self.constants, options=self.options,
            submitted_t=round(self.submitted_t, 3),
            started_t=self.started_t and round(self.started_t, 3),
            finished_t=self.finished_t and round(self.finished_t, 3),
            result=self.result, error=self.error,
            journal=f"{self.id}.journal.jsonl",
        )


def _module_name(spec_text: str) -> str:
    for line in spec_text.splitlines():
        s = line.strip()
        if s.startswith("----") and "MODULE" in s:
            return s.split("MODULE", 1)[1].strip().strip("- ").split()[0]
    raise JobError("spec text has no ---- MODULE Name ---- header")


def _loader_constants(constants: dict) -> dict:
    """Job constants arrive as JSON, which has no set type: a list
    value is the JSON spelling of an MC.cfg set literal ({r1, r2}),
    which the loaders/evaluator represent as a frozenset."""
    return {k: frozenset(v) if isinstance(v, list) else v
            for k, v in constants.items()}


def _result_dict(r, engine: str, pool_hit: bool = None) -> dict:
    verdict = "ok" if r.violation == 0 else "violation"
    out = dict(
        verdict=verdict, generated=r.generated, distinct=r.distinct,
        depth=r.depth, queue=r.queue_left, violation=r.violation,
        violation_name=(None if r.violation == 0 else r.violation_name),
        action_generated=r.action_generated,
        action_distinct=r.action_distinct,
        wall_s=round(r.wall_s, 6), engine=engine,
    )
    if pool_hit is not None:
        out["pool_hit"] = pool_hit
    return out


class Scheduler:
    """The FIFO worker: owns the queue, the job registry, the pool and
    the per-job journals under `root`."""

    def __init__(self, root: str, pool: Optional[EnginePool] = None,
                 large_fpcap: int = DEFAULT_LARGE_FPCAP):
        self.root = root
        self.pool = pool or EnginePool()
        self.large_fpcap = large_fpcap
        self.jobs: "OrderedDict[str, Job]" = OrderedDict()
        self._queue: deque = deque()
        self._cond = threading.Condition()
        self._stop = False
        self.batches_run = 0
        self.batched_jobs = 0
        self.cache_hits = 0  # jobs answered from the artifact cache
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()

    # -- submission --------------------------------------------------------

    def submit(self, spec: str, cfg: str, **kw) -> Job:
        job = Job(spec, cfg, **kw)
        if job.sweep:
            params = job.sweep_params()  # validates the descriptor
            missing = [c for c in params if c not in job.constants]
            if missing:
                raise JobError(
                    f"sweep job must pin its swept constants "
                    f"{missing} in 'constants'"
                )
        _module_name(spec)  # validates the module header
        with self._cond:
            self.jobs[job.id] = job
            self._queue.append(job.id)
            self._cond.notify()
        return job

    def get(self, job_id: str) -> Optional[Job]:
        with self._cond:
            return self.jobs.get(job_id)

    def list(self) -> List[dict]:
        with self._cond:
            return [j.summary() for j in self.jobs.values()]

    def stats(self) -> dict:
        with self._cond:
            states: Dict[str, int] = {}
            for j in self.jobs.values():
                states[j.state] = states.get(j.state, 0) + 1
            return dict(jobs=len(self.jobs), queued=len(self._queue),
                        states=states, batches_run=self.batches_run,
                        batched_jobs=self.batched_jobs,
                        cache_hits=self.cache_hits,
                        large_fpcap=self.large_fpcap)

    def drain(self, timeout: float = 60.0) -> bool:
        """Block until every submitted job left the queue and finished
        (tools/loadgen + tests); False on timeout."""
        deadline = time.time() + timeout
        while time.time() < deadline:
            with self._cond:
                busy = self._queue or any(
                    j.state in ("queued", "running")
                    for j in self.jobs.values()
                )
            if not busy:
                return True
            time.sleep(0.02)
        return False

    def shutdown(self) -> None:
        with self._cond:
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout=10)

    # -- the worker --------------------------------------------------------

    def _loop(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._stop:
                    self._cond.wait(0.5)
                if self._stop:
                    return
                head = self.jobs[self._queue.popleft()]
                batch = [head]
                if (head.sweep or head.is_smoke()
                        or head.is_infer()) \
                        and not head.is_large(self.large_fpcap):
                    # look ahead: fold queued jobs of the same class
                    # into this dispatch (FIFO among the folded; the
                    # skipped-over rest keeps its order)
                    sig = head.batch_signature()
                    width = self.pool.sweep_width
                    keep = deque()
                    while self._queue and len(batch) < width:
                        cand = self.jobs[self._queue.popleft()]
                        if cand.batch_signature() == sig:
                            batch.append(cand)
                        else:
                            keep.append(cand.id)
                    self._queue.extendleft(reversed(keep))
                for j in batch:
                    j.state = "running"
                    j.started_t = time.time()
            try:
                self._run_batch(batch)
            except Exception as e:  # a broken job must not kill the loop
                for j in batch:
                    if j.state == "running":
                        self._finish_error(j, f"{type(e).__name__}: {e}")

    # -- execution paths ---------------------------------------------------

    def _jobdir(self, job: Job) -> str:
        d = os.path.join(self.root, "jobs", job.id)
        os.makedirs(d, exist_ok=True)
        mod = _module_name(job.spec)
        with open(os.path.join(d, f"{mod}.tla"), "w") as f:
            f.write(job.spec)
        with open(os.path.join(d, f"{mod}.cfg"), "w") as f:
            f.write(job.cfg)
        return os.path.join(d, f"{mod}.cfg")

    def _journal(self, job: Job):
        from ..obs.journal import RunJournal

        return RunJournal(
            os.path.join(self.root, f"{job.id}.journal.jsonl"),
            fsync_every=JOB_FSYNC_EVERY,
        )

    def _run_batch(self, batch: List[Job]) -> None:
        head = batch[0]
        if head.is_infer() and not head.is_large(self.large_fpcap):
            self._run_infer(batch)
            return
        if head.is_smoke() and not head.is_large(self.large_fpcap):
            self._run_smoke(batch)
            return
        if head.sweep and not head.is_large(self.large_fpcap):
            self._run_sweep(batch)
            return
        assert len(batch) == 1
        if head.is_large(self.large_fpcap):
            self._run_supervised(head)
        else:
            self._run_pooled(head)

    def _geometry(self, job: Job) -> dict:
        o = job.options
        return dict(
            chunk=int(o.get("chunk", DEFAULT_CHUNK)),
            queue_capacity=int(o.get("qcap", DEFAULT_QCAP)),
            fp_capacity=int(o.get("fpcap", DEFAULT_FPCAP)),
            check_deadlock=not o.get("nodeadlock", False),
            sort_free=o.get("sortfree", None),
            deferred=o.get("deferredinv", None),
        )

    def _run_sweep(self, batch: List[Job]) -> None:
        """One vmapped dispatch for the whole compatible batch."""
        import jax

        from . import sweep as sw

        head = batch[0]
        params = head.sweep_params()
        cfg_path = self._jobdir(head)
        # the job's FIXED constants bake into the anchor (batch_signature
        # already folds only equal-fixed jobs together, so head's dict
        # speaks for the whole batch); two batches differing in a fixed
        # override land on different class keys, not one shared engine
        fixed = _loader_constants({
            k: v for k, v in head.constants.items() if k not in params
        })
        model = sw.load_anchored(cfg_path, params,
                                 const_overrides=fixed or None)
        pre = self.pool.hits
        entry = self.pool.get_sweep(model, params, **self._geometry(head))
        hit = self.pool.hits > pre
        configs = [
            {c: int(j.constants[c]) for c in params} for j in batch
        ]
        device = str(jax.devices()[0])
        journals = []
        for j in batch:
            if j is not head:
                self._jobdir(j)  # each job keeps its own artifacts
            jr = self._journal(j)
            jr.event("run_start", version=_version(), workload=j.name,
                     engine="sweep", device=device,
                     params=dict(**self._geometry(j),
                                 sweep=j.sweep, constants=j.constants,
                                 batch=len(batch), pool_hit=hit))
            journals.append(jr)
        try:
            results = entry.runner.run(configs)
        except BaseException:
            self._abort_journals(journals)
            raise
        with self._cond:
            self.batches_run += 1
            self.batched_jobs += len(batch)
        for j, jr, r in zip(batch, journals, results):
            if r.violation != 0:
                jr.event("violation", code=int(r.violation),
                         name=r.violation_name)
            jr.event("final",
                     verdict="ok" if r.violation == 0 else "violation",
                     generated=r.generated, distinct=r.distinct,
                     depth=r.depth, queue=r.queue_left,
                     wall_s=round(r.wall_s, 6), interrupted=False)
            jr.close()
            self._finish_ok(j, _result_dict(r, "sweep", pool_hit=hit))

    def _run_smoke(self, batch: List[Job]) -> None:
        """The smoke job class (jaxtlc.sim, ISSUE 14): one vmapped
        random-walk dispatch for the whole compatible batch - the
        batch axis is (seed, swept-constants config), so N per-commit
        smoke submits (different seeds) and a constants sweep both
        ride ONE warm sim engine.  The artifact cache is BYPASSED
        (journaled per job): simulation verdicts are from incomplete
        search and must never publish to the verdict tier."""
        import jax

        from ..struct import artifacts as arts
        from ..struct.loader import StructLoadError, load
        from ..struct.parser import StructParseError
        from . import sweep as sw

        head = batch[0]
        params = head.sweep_params() or None
        cfg_path = self._jobdir(head)
        fixed = _loader_constants({
            k: v for k, v in head.constants.items()
            if k not in (params or {})
        })
        try:
            if params:
                model = sw.load_anchored(cfg_path, params,
                                         const_overrides=fixed or None)
            else:
                model = load(cfg_path, const_overrides=fixed or None)
        except (StructLoadError, StructParseError):
            # the sim engine is struct-only today: route through
            # api.run_check with the frontend forced struct (it runs
            # any spec) so the job still gets a real answer or a
            # real error
            for j in batch:
                self._run_supervised(j, frontend="struct")
            return
        o = head.options
        walkers = int(o.get("walkers", DEFAULT_SIM_WALKERS))
        depth = int(o.get("depth", DEFAULT_SIM_DEPTH))
        fp_capacity = int(o.get("fpcap", DEFAULT_FPCAP))
        check_deadlock = not o.get("nodeadlock", False)
        pre = self.pool.hits
        entry = self.pool.get_sim(
            model, params=params, walkers=walkers, depth=depth,
            fp_capacity=fp_capacity, check_deadlock=check_deadlock,
        )
        hit = self.pool.hits > pre
        items = [
            (int(j.options.get("simseed", 0)),
             ({c: int(j.constants[c]) for c in params}
              if params else None))
            for j in batch
        ]
        bypass = (arts.get_store() is not None
                  and not o.get("noartifactcache"))
        device = str(jax.devices()[0])
        journals = []
        for j, (seed, values) in zip(batch, items):
            if j is not head:
                self._jobdir(j)
            jr = self._journal(j)
            jr.event("run_start", version=_version(), workload=j.name,
                     engine="sim", device=device,
                     params=dict(walkers=walkers, depth=depth,
                                 sim_seed=seed, fp_capacity=fp_capacity,
                                 sweep=j.sweep, constants=j.constants,
                                 batch=len(batch), pool_hit=hit))
            if bypass:
                jr.event("cache", tier="verdict", outcome="bypass",
                         key="", reason="simulation verdicts are from "
                                        "incomplete search and never "
                                        "publish")
            journals.append(jr)
        try:
            results = entry.runner.run(items)
        except BaseException:
            self._abort_journals(journals)
            raise
        with self._cond:
            self.batches_run += 1
            self.batched_jobs += len(batch)
        for j, jr, r in zip(batch, journals, results):
            jr.event("sim", phase="summary", walkers=r.walkers,
                     depth=r.depth, steps=r.steps,
                     transitions=r.transitions, seed=r.seed,
                     distinct_est=r.distinct,
                     fp_saturated=r.fp_saturated, halted=r.halted,
                     depth_hist=[list(p) for p in r.depth_hist],
                     violation=r.violation)
            if r.violation != 0:
                jr.event("violation", code=int(r.violation),
                         name=r.violation_name)
            jr.event("final",
                     verdict="ok" if r.violation == 0 else "violation",
                     generated=r.generated, distinct=r.distinct,
                     depth=r.steps, queue=0,
                     wall_s=round(r.wall_s, 6), interrupted=False)
            jr.close()
            res = _result_dict(r, "sim", pool_hit=hit)
            res["depth"] = r.steps  # depth REACHED (r.depth = budget)
            res["sim"] = dict(
                walkers=r.walkers, depth=r.depth, steps=r.steps,
                transitions=r.transitions, seed=r.seed,
                distinct_est=r.distinct, fp_saturated=r.fp_saturated,
                violation_lane=r.violation_lane,
                violation_step=r.violation_step,
            )
            self._finish_ok(j, res)

    def _run_infer(self, batch: List[Job]) -> None:
        """The inference job class (jaxtlc.infer, ISSUE 16): every job
        in the folded batch runs through ONE warm infer engine - the
        candidate pool, the AOT [P, S] filter kernel and the exact
        evidence all belong to the engine, so the per-job work is pure
        dispatch (the seed only matters under sampled evidence).  Like
        sim, the artifact-cache verdict tier is BYPASSED (journaled
        per job): an inference verdict is about CANDIDATES, not the
        spec's stated invariants."""
        import jax

        from ..struct import artifacts as arts
        from ..struct.loader import StructLoadError, load
        from ..struct.parser import StructParseError

        head = batch[0]
        cfg_path = self._jobdir(head)
        fixed = _loader_constants(head.constants)
        try:
            model = load(cfg_path, const_overrides=fixed or None)
        except (StructLoadError, StructParseError):
            # inference conjectures over the struct IR: route through
            # api.run_check with the frontend forced struct (it runs
            # any spec) so the job still gets a real answer or a real
            # error
            for j in batch:
                self._run_supervised(j, frontend="struct")
            return
        o = head.options
        budget = int(o.get("inferbudget", 64))
        walkers = int(o.get("walkers", DEFAULT_SIM_WALKERS))
        depth = int(o.get("depth", DEFAULT_SIM_DEPTH))
        check_deadlock = not o.get("nodeadlock", False)
        pre = self.pool.hits
        entry = self.pool.get_infer(
            model, budget=budget, walkers=walkers, depth=depth,
            check_deadlock=check_deadlock,
        )
        hit = self.pool.hits > pre
        bypass = (arts.get_store() is not None
                  and not o.get("noartifactcache"))
        device = str(jax.devices()[0])
        for j in batch:
            if j is not head:
                self._jobdir(j)
            jr = self._journal(j)
            jr.event("run_start", version=_version(), workload=j.name,
                     engine="infer", device=device,
                     params=dict(budget=budget, walkers=walkers,
                                 depth=depth,
                                 sim_seed=int(j.options.get(
                                     "simseed", 0)),
                                 constants=j.constants,
                                 batch=len(batch), pool_hit=hit))
            if bypass:
                jr.event("cache", tier="verdict", outcome="bypass",
                         key="", reason="inference verdicts are about "
                                        "candidate invariants and "
                                        "never publish")
            try:
                rep = entry.runner.run(
                    seed=int(j.options.get("simseed", 0)))
            except BaseException:
                self._abort_journals([jr])
                raise
            jr.event("infer", phase="summary",
                     candidates=rep.candidates, killed=rep.killed,
                     survivors=len(rep.survivors),
                     certified=len(rep.certified),
                     certified_names=[c.name for c in rep.certified],
                     evidence=rep.evidence, n_states=rep.n_states,
                     dropped=rep.dropped)
            violated = bool(rep.cfg_killed)
            if violated:
                jr.event("violation", code=100,
                         name=f"Invariant {rep.cfg_killed[0]} is "
                              f"violated.")
            jr.event("final",
                     verdict="violation" if violated else "ok",
                     generated=rep.n_states, distinct=rep.n_states,
                     depth=0, queue=0,
                     wall_s=round(rep.wall_s, 6), interrupted=False)
            jr.close()
            res = dict(
                verdict="violation" if violated else "ok",
                violation=(100 if violated else 0),
                violation_name=(f"Invariant {rep.cfg_killed[0]} is "
                                f"violated." if violated else None),
                generated=rep.n_states, distinct=rep.n_states,
                depth=0, queue_left=0,
                wall_s=round(rep.wall_s, 6),
                engine="infer", pool_hit=hit,
                infer=dict(
                    candidates=rep.candidates, dropped=rep.dropped,
                    killed=rep.killed, survivors=len(rep.survivors),
                    certified=[
                        dict(name=c.name, text=c.text, basis=b,
                             implies=list(c.implies))
                        for c, b in zip(rep.certified, rep.cert_basis)
                    ],
                    uncertified=[
                        dict(name=c.name, text=c.text)
                        for c in rep.survivors
                        if c not in rep.certified
                    ],
                    uncompiled=list(rep.uncompiled),
                    cfg_killed=list(rep.cfg_killed),
                    evidence=rep.evidence, exact=rep.exact,
                    n_states=rep.n_states, seed=rep.seed,
                ),
            )
            self._finish_ok(j, res)
        with self._cond:
            self.batches_run += 1
            self.batched_jobs += len(batch)

    def _run_pooled(self, job: Job) -> None:
        """Warm plain engine via the pool; falls back to the supervised
        path when the spec does not resolve structurally.

        Incremental re-checking (ISSUE 13) sits BEFORE pool routing: an
        unchanged spec is answered from the verdict tier in O(HTTP) -
        no pool lookup, no engine dispatch - and a spec whose behavior
        digest has a stored reachable set routes through api.run_check,
        which skips BFS and re-evaluates only the invariants."""
        import jax

        from ..struct import artifacts as arts
        from ..struct.loader import StructLoadError, load
        from ..struct.parser import StructParseError

        cfg_path = self._jobdir(job)
        try:
            model = load(
                cfg_path,
                const_overrides=_loader_constants(job.constants) or None,
            )
        except (StructLoadError, StructParseError, JobError):
            self._run_supervised(job)
            return
        geo = self._geometry(job)
        store = arts.get_store()
        use_cache = (store is not None
                     and not job.options.get("recheck")
                     and not job.options.get("noartifactcache"))
        vkey = ""
        if use_cache:
            # the pooled path checks safety only, so its verdict key
            # carries an empty property selection (api keys runs WITH
            # properties differently - the two can never cross-answer)
            vkey = arts.verdict_key(model, geo["check_deadlock"])
            payload = store.lookup_verdict(vkey)
            if payload is not None:
                self._finish_cached(job, geo, vkey, payload)
                return
            if store.has_reach(
                    arts.reach_key(model, geo["check_deadlock"])):
                # invariant-only edit: api.run_check's reach tier
                # skips BFS entirely - cheaper than a pool dispatch.
                # Forced onto the struct frontend: the stored artifact
                # was keyed by this very struct load, and "auto" could
                # route a gen-subset spec away from the cache
                self._run_supervised(job, frontend="struct")
                return
        pre = self.pool.hits
        entry = self.pool.get_single(model, **geo)
        hit = self.pool.hits > pre
        jr = self._journal(job)
        jr.event("run_start", version=_version(), workload=job.name,
                 engine="pool", device=str(jax.devices()[0]),
                 params=dict(**geo, constants=job.constants,
                             pool_hit=hit))
        try:
            r = entry.runner.run(capture_fps=use_cache)
        except BaseException:
            self._abort_journals([jr])
            raise
        if r.violation != 0:
            jr.event("violation", code=int(r.violation),
                     name=r.violation_name)
        if use_cache and r.violation == 0:
            try:
                arts.ArtifactPlan(
                    store, model,
                    check_deadlock=geo["check_deadlock"],
                    fp_capacity=geo["fp_capacity"],
                ).record(r, n_init=len(model.system.initial_states()),
                         journal=jr)
            except OSError:
                pass  # a full disk must not fail the job
        jr.event("final",
                 verdict="ok" if r.violation == 0 else "violation",
                 generated=r.generated, distinct=r.distinct,
                 depth=r.depth, queue=r.queue_left,
                 wall_s=round(r.wall_s, 6), interrupted=False)
        jr.close()
        self._finish_ok(job, _result_dict(r, "pool", pool_hit=hit))

    def _finish_cached(self, job: Job, geo: dict, key: str,
                       payload: dict) -> None:
        """Answer a job from the verdict tier: journal a complete run
        (run_start -> cache hit -> final, so SSE/views/tlcstat render
        it like any other), no pool lookup, no engine dispatch."""
        from ..struct.artifacts import result_from_payload

        jr = self._journal(job)
        jr.event("run_start", version=_version(), workload=job.name,
                 engine="cache", device="artifact-cache",
                 params=dict(**geo, constants=job.constants,
                             cache_hit=True))
        jr.event("cache", tier="verdict", outcome="hit", key=key,
                 workload=payload.get("workload"))
        r = result_from_payload(payload,
                                fp_capacity=geo["fp_capacity"],
                                wall_s=time.time() - job.started_t)
        jr.event("final", verdict="ok", generated=r.generated,
                 distinct=r.distinct, depth=r.depth, queue=r.queue_left,
                 wall_s=round(r.wall_s, 6), interrupted=False)
        jr.close()
        with self._cond:
            self.cache_hits += 1
        res = _result_dict(r, "cache")
        res["cache_hit"] = True
        self._finish_ok(job, res)

    def _abort_journals(self, journals) -> None:
        """A runner that dies after the per-job journals opened must
        still terminate them: SSE followers only stop on a 'final'
        event, and an unclosed handle leaks per failed job (the loop's
        error handler knows jobs, not files)."""
        for jr in journals:
            try:
                jr.event("final", verdict="error", generated=0,
                         distinct=0, depth=0, queue=0, wall_s=0.0,
                         interrupted=True)
            except Exception:
                pass  # a sick journal must not mask the run's error
            finally:
                jr.close()

    def _run_supervised(self, job: Job, frontend: str = None) -> None:
        """Large / resilience-option jobs: the full api.run_check
        pipeline (resil supervisor, degradation ladder, preflight, TLC
        transcript captured as the job's output).  `frontend` overrides
        the resolver when the caller already knows the path (the
        artifact-cache reach route struct-loaded the model itself)."""
        from ..api import CheckRequest, run_check

        cfg_path = self._jobdir(job)
        out = io.StringIO()
        kw = {k: job.options[k] for k in _REQUEST_OPTIONS
              if k in job.options}
        kw.setdefault("workers", "cpu" if _on_cpu() else "tpu")
        if frontend is not None:
            kw.setdefault("frontend", frontend)
        req = CheckRequest(
            config=cfg_path,
            constants=_loader_constants(job.constants),
            journal=os.path.join(self.root,
                                 f"{job.id}.journal.jsonl"),
            noTool=True, out=out, err=out, **kw,
        )
        outcome = run_check(req)
        r = outcome.result
        res = dict(verdict=outcome.verdict,
                   exit_code=outcome.exit_code, engine="supervised",
                   transcript=out.getvalue())
        if kw.get("coverage"):
            # per-job coverage artifact (ISSUE 11): the cumulative
            # site table folded from the job journal's coverage
            # events - GET /jobs/<id> returns it, and the journal
            # itself stays queryable via /coverage?run=<job id>
            try:
                from ..obs.coverage import coverage_from_events
                from ..obs.journal import read as read_journal

                cov = coverage_from_events(
                    read_journal(req.journal, validate=False)
                )
                if cov is not None:
                    res["coverage"] = cov
            except (OSError, ValueError):
                pass  # a sick journal must not mask the verdict
        if r is not None:
            res.update(
                generated=r.generated, distinct=r.distinct,
                depth=r.depth, queue=r.queue_left,
                violation=r.violation,
                action_generated=r.action_generated,
                wall_s=round(r.wall_s, 6),
            )
        if outcome.exit_code in (0, 12, 13, 75):
            self._finish_ok(job, res)
        else:
            job.result = res
            self._finish_error(
                job, f"exit {outcome.exit_code}: {out.getvalue()[-500:]}"
            )

    # -- completion --------------------------------------------------------

    def _finish_ok(self, job: Job, result: dict) -> None:
        with self._cond:
            job.result = result
            job.engine = result.get("engine", "")
            job.state = "done"
            job.finished_t = time.time()

    def _finish_error(self, job: Job, msg: str) -> None:
        with self._cond:
            job.error = msg
            job.state = "error"
            job.finished_t = time.time()


def _version() -> str:
    from .. import __version__

    return __version__


def _on_cpu() -> bool:
    import jax

    return jax.devices()[0].platform == "cpu"
