"""Overload-safe job scheduler: many checks, one device, warm engines.

The queue discipline of the checking service (serve.server).  Jobs run
in submission order WITHIN a tenant; between tenants the dequeue is a
weighted round-robin at the highest ready priority, so one flooding
client cannot starve the rest.  The scheduler still looks ahead for
**compatible small jobs** - same spec text, same cfg, same geometry,
same sweep descriptor, constants differing only in the swept names -
and folds up to `pool.sweep_width` of them into ONE vmapped dispatch
through the constants-class sweep engine.  Everything else runs alone:

* small struct jobs without a sweep descriptor go through the pool's
  warm plain engine (AOT executable; warm submit = zero fresh XLA
  compiles - the pool's assertable contract);
* large jobs (geometry above `large_fpcap`, or any resilience option:
  checkpoint/recover/sharded/liveness/faults) route through
  `api.run_check`, i.e. the resil supervisor with auto-regrow, the
  degradation ladder, and the full TLC transcript.

The overload control plane (ISSUE 17) wraps that core:

* **Admission control** - the queue is bounded (`queue_bound`, plus an
  optional per-tenant `tenant_quota`); an over-limit submit raises
  AdmissionError carrying a Retry-After computed from the MEASURED
  drain rate (a deque of recent finish timestamps), which the HTTP
  layer maps to 429.
* **Deadlines** - a per-job `deadline_s` option is enforced by a
  reaper thread: queued jobs expire to the terminal `expired` state;
  a running supervised job is preempted through its programmatic
  drain Event (the in-process twin of the resil _SignalCatcher, so
  preempting ONE job never signals the whole server) and rides the
  existing checkpoint + exit-75 machinery.
* **Priorities** - a `priority` option; a high-priority arrival
  preempts a running lower-priority checkpointed heavy job, which is
  requeued as a `-recover` resume against its own journal (one
  continuous history; the resumed result is bit-for-bit the
  uninterrupted run's, the PR 2/7 contract).  Pooled / sweep / smoke /
  infer dispatches run to completion - they are short by construction.
* **Retry + circuit breaker** - a dispatch that dies with a transient
  fault (resil's `_TRANSIENT` minus `is_resource_exhausted`) is
  requeued with deterministic-jitter backoff up to `job_retries`;
  specs that keep failing trip a breaker keyed on the spec digest
  (open -> cooldown -> half-open single probe -> closed), and
  submits against an open breaker land terminally `quarantined`.
* **Telemetry** - every decision (admit / reject / expire / preempt /
  requeue / retry / quarantine / cancel / dispatch) is a schema-v1
  `sched` event in the scheduler's own journal
  (`<root>/sched.journal.jsonl`), so /runs, /metrics, SSE and tlcstat
  render the control plane with the same machinery as any run.

Scheduling policy is host Python throughout - no new engine factories,
no new XLA compiles.

Every job writes its own journal into the server root - the /runs
registry and the job-scoped SSE stream (`/events?run=<job id>`) are the
existing obs.serve machinery reading those files.  A job that never
ran (expired while queued, canceled, quarantined) still gets a minimal
journal (run_start engine="sched" + final), so SSE followers terminate
on EVERY outcome.  Scheduler-run jobs journal in batched-fsync mode
(obs.journal fsync_every): job journals are high-rate telemetry, and a
crash loses at most a tail the scheduler re-reports in the job record
anyway.
"""

from __future__ import annotations

import hashlib
import io
import json
import math
import os
import random
import threading
import time
import uuid
from collections import OrderedDict, deque
from typing import Dict, List, Optional

from ..resil.faults import FaultInjector, FaultPlan, TransientFault
from .pool import EnginePool

JOB_FSYNC_EVERY = 16  # batched-fsync journals for scheduler-run jobs
DEFAULT_LARGE_FPCAP = 1 << 16  # above this, a job is "large"

# the pooled path's default engine geometry - ALSO the geometry
# `--prewarm` compiles against, so a prewarmed engine and a default
# submit land on the same pool key
DEFAULT_CHUNK = 64
DEFAULT_QCAP = 1 << 10
DEFAULT_FPCAP = 1 << 12

# the smoke job class's default walk geometry (jaxtlc.sim, ISSUE 14):
# cheap enough for "check something on every commit in 2 seconds",
# overridable per job via options walkers/depth
DEFAULT_SIM_WALKERS = 64
DEFAULT_SIM_DEPTH = 64

# overload-control defaults (ISSUE 17)
DEFAULT_QUEUE_BOUND = 256  # admission bound on QUEUED jobs
DEFAULT_JOB_RETRIES = 2  # transient-fault redispatches per job
DEFAULT_BREAKER_THRESHOLD = 3  # digest failures before the breaker trips
DEFAULT_BREAKER_COOLDOWN_S = 30.0  # open -> half-open probe window
RETRY_BACKOFF_BASE_S = 0.05
RETRY_BACKOFF_CAP_S = 2.0
REAPER_PERIOD_S = 0.02  # deadline/preemption scan cadence

# job states a drain() no longer waits on
TERMINAL_STATES = ("done", "error", "expired", "canceled", "quarantined")

# job options forwarded to api.CheckRequest on the supervised path
_REQUEST_OPTIONS = (
    "workers", "frontend", "chunk", "qcap", "fpcap", "pipeline",
    "sortfree", "deferredinv", "symmetry", "por",
    "sharded", "checkpoint", "checkpointevery",
    "recover", "liveness",
    "fairness", "nodeadlock", "faults", "retry", "maxregrow", "spill",
    "obs", "obsslots", "coverage", "recheck", "noartifactcache",
    "simulate", "depth", "walkers", "simseed",
    "infer", "inferbudget",
)
_HEAVY_OPTIONS = ("checkpoint", "recover", "sharded", "liveness",
                  "faults", "coverage")
# scheduling-only options: they gate WHEN a job runs, never WHAT it
# computes, so they are invisible to batch folding and are never
# forwarded to the engine request
_SCHED_OPTIONS = ("priority", "deadline_s")


class JobError(ValueError):
    pass


class AdmissionError(JobError):
    """A submit refused by admission control (the HTTP layer's 429).
    `retry_after` is the drain-rate-derived client backoff hint in
    whole seconds."""

    def __init__(self, msg: str, retry_after: int):
        super().__init__(msg)
        self.retry_after = int(retry_after)


class DrainTimeout(RuntimeError):
    """drain() gave up waiting; `pending` names the unfinished jobs
    (the silent-False of the old API wedged callers invisibly)."""

    def __init__(self, msg: str, pending: List[str]):
        super().__init__(msg)
        self.pending = list(pending)


class Job:
    """One submitted check: spec + cfg text, optional constant
    overrides, optional sweep descriptor, engine options, and the
    scheduling envelope (tenant / priority / deadline).

    State machine: ``queued`` -> ``running`` -> one of the terminal
    states ``done`` | ``error`` | ``expired`` | ``canceled`` |
    ``quarantined``.  The last three are scheduler-terminal - the job
    never got, or never finished, an engine run: ``expired`` (deadline
    passed while queued, or a running checkpointed job drained at its
    deadline), ``canceled`` (DELETE /jobs/<id>), ``quarantined``
    (submitted against an open circuit breaker).  A running job can
    also return to ``queued`` (priority preemption requeues it as a
    -recover resume; transient dispatch faults requeue with backoff).
    """

    def __init__(self, spec: str, cfg: str, name: str = "",
                 constants: Optional[dict] = None,
                 sweep: Optional[dict] = None,
                 options: Optional[dict] = None,
                 tenant: Optional[str] = None):
        self.id = f"job-{uuid.uuid4().hex[:10]}"
        self.spec = spec
        self.cfg = cfg
        self.name = name or self.id
        self.constants = dict(constants or {})
        self.sweep = dict(sweep) if sweep else None
        self.options = dict(options or {})
        self.tenant = str(tenant) if tenant else "default"
        # queued | running | done | error | expired | canceled |
        # quarantined (the last three are scheduler-terminal: the job
        # never got, or never finished, an engine run)
        self.state = "queued"
        self.result: Optional[dict] = None
        self.error: Optional[str] = None
        self.engine = ""  # "sweep" | "pool" | "supervised" | "sched" ...
        self.submitted_t = time.time()
        self.started_t: Optional[float] = None
        self.finished_t: Optional[float] = None
        # -- scheduling envelope (ISSUE 17) --------------------------------
        try:
            self.priority = int(self.options.get("priority", 0))
        except (TypeError, ValueError):
            raise JobError("options.priority must be an integer")
        d = self.options.get("deadline_s")
        try:
            self.deadline_s = None if d is None else float(d)
        except (TypeError, ValueError):
            raise JobError("options.deadline_s must be a number")
        if self.deadline_s is not None and self.deadline_s <= 0:
            raise JobError("options.deadline_s must be positive")
        self.deadline_t = (None if self.deadline_s is None
                           else self.submitted_t + self.deadline_s)
        # breaker key: the spec IDENTITY, not the job (a quarantine is
        # about a spec that keeps failing, whoever submits it)
        self.digest = hashlib.sha256(
            (spec + "\n\x00\n" + cfg).encode()
        ).hexdigest()[:16]
        self.retries = 0  # transient-fault redispatches so far
        self.requeues = 0  # priority preemptions survived so far
        self.not_before = 0.0  # retry backoff gate (epoch seconds)
        self.preempt_reason: Optional[str] = None
        self.cancel_requested = False
        self._drain: Optional[threading.Event] = None
        self._preemptible = False

    # -- routing -----------------------------------------------------------

    def sweep_params(self) -> Dict[str, tuple]:
        """{const: (lo, hi)} from the job's sweep descriptor."""
        if not self.sweep:
            return {}
        c = self.sweep.get("const")
        if not c:
            raise JobError("sweep descriptor needs a 'const' name")
        if self.sweep.get("hi") is None:
            raise JobError("sweep descriptor needs a 'hi' domain bound")
        lo, hi = int(self.sweep.get("lo", 0)), int(self.sweep["hi"])
        return {c: (lo, hi)}

    def is_large(self, large_fpcap: int) -> bool:
        if any(self.options.get(k) for k in _HEAVY_OPTIONS):
            return True
        return int(self.options.get("fpcap", 1 << 12)) > large_fpcap

    def is_smoke(self) -> bool:
        """The simulation job class (options.simulate): random walks
        through the warm sim engine - the cheap per-commit check."""
        return bool(self.options.get("simulate"))

    def is_infer(self) -> bool:
        """The inference job class (options.infer): conjecture ->
        filter -> certify through the warm infer engine (ISSUE 16)."""
        return bool(self.options.get("infer"))

    def batch_signature(self) -> str:
        """Jobs with equal signatures fold into one vmapped dispatch:
        identical spec/cfg/options/sweep, constants equal OUTSIDE the
        swept names (inside them is the batch axis).  Smoke jobs
        additionally drop `simseed` from the compared options - the
        seed is a batch lane, so one warm sim engine serves seeds x
        configs in one dispatch (ISSUE 14).  Infer jobs drop it too:
        the seed is run data against one warm infer engine (ISSUE 16).
        Scheduling-envelope options (priority, deadline_s) never enter
        the signature: they gate WHEN, not WHAT."""
        drop = set(_SCHED_OPTIONS)
        if self.is_smoke() or self.is_infer():
            drop.add("simseed")
        fixed = {k: v for k, v in sorted(self.constants.items())
                 if k not in self.sweep_params()}
        opts = {k: v for k, v in self.options.items() if k not in drop}
        blob = json.dumps(
            [self.spec, self.cfg, sorted(opts.items()),
             sorted((self.sweep or {}).items()), fixed],
            sort_keys=True,
        )
        return hashlib.sha256(blob.encode()).hexdigest()

    def summary(self) -> dict:
        return dict(
            id=self.id, name=self.name, state=self.state,
            engine=self.engine, sweep=self.sweep,
            constants=self.constants, options=self.options,
            tenant=self.tenant, priority=self.priority,
            deadline_s=self.deadline_s,
            retries=self.retries, requeues=self.requeues,
            submitted_t=round(self.submitted_t, 3),
            started_t=self.started_t and round(self.started_t, 3),
            finished_t=self.finished_t and round(self.finished_t, 3),
            result=self.result, error=self.error,
            journal=f"{self.id}.journal.jsonl",
        )


def _module_name(spec_text: str) -> str:
    for line in spec_text.splitlines():
        s = line.strip()
        if s.startswith("----") and "MODULE" in s:
            return s.split("MODULE", 1)[1].strip().strip("- ").split()[0]
    raise JobError("spec text has no ---- MODULE Name ---- header")


def _loader_constants(constants: dict) -> dict:
    """Job constants arrive as JSON, which has no set type: a list
    value is the JSON spelling of an MC.cfg set literal ({r1, r2}),
    which the loaders/evaluator represent as a frozenset."""
    return {k: frozenset(v) if isinstance(v, list) else v
            for k, v in constants.items()}


def _result_dict(r, engine: str, pool_hit: bool = None) -> dict:
    verdict = "ok" if r.violation == 0 else "violation"
    out = dict(
        verdict=verdict, generated=r.generated, distinct=r.distinct,
        depth=r.depth, queue=r.queue_left, violation=r.violation,
        violation_name=(None if r.violation == 0 else r.violation_name),
        action_generated=r.action_generated,
        action_distinct=r.action_distinct,
        wall_s=round(r.wall_s, 6), engine=engine,
    )
    if pool_hit is not None:
        out["pool_hit"] = pool_hit
    return out


class Scheduler:
    """The worker: owns the queue, the job registry, the pool, the
    per-job journals under `root`, and the overload control plane
    (admission, deadlines, priorities, retry/breaker, its own sched
    journal)."""

    def __init__(self, root: str, pool: Optional[EnginePool] = None,
                 large_fpcap: int = DEFAULT_LARGE_FPCAP,
                 queue_bound: int = DEFAULT_QUEUE_BOUND,
                 tenant_quota: Optional[int] = None,
                 tenant_weights: Optional[Dict[str, int]] = None,
                 job_retries: int = DEFAULT_JOB_RETRIES,
                 breaker_threshold: int = DEFAULT_BREAKER_THRESHOLD,
                 breaker_cooldown_s: float = DEFAULT_BREAKER_COOLDOWN_S,
                 faults=None):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.pool = pool or EnginePool()
        self.large_fpcap = large_fpcap
        self.queue_bound = int(queue_bound)
        self.tenant_quota = (int(tenant_quota) if tenant_quota else None)
        self.tenant_weights = dict(tenant_weights or {})
        self.job_retries = int(job_retries)
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        if isinstance(faults, str):
            faults = FaultPlan.parse(faults)
        self._injector = FaultInjector(faults) if faults else None
        self.jobs: "OrderedDict[str, Job]" = OrderedDict()
        self._queue: deque = deque()
        self._cond = threading.Condition()
        self._stop = False
        self._started_t = time.time()
        self.batches_run = 0
        self.batched_jobs = 0
        self.cache_hits = 0  # jobs answered from the artifact cache
        self._dispatches = 0
        # WRR state: the tenant cycle, each tenant repeated by weight
        self._rr: deque = deque()
        self._rr_tenants = set()
        # recent finish timestamps -> the measured drain rate behind
        # Retry-After (and /health)
        self._finished_ts: deque = deque(maxlen=32)
        # spec-digest circuit breakers:
        # digest -> {state, failures, opened_t, probe}
        self._breaker: Dict[str, dict] = {}
        self._counters = dict(admitted=0, rejected=0, expired=0,
                              canceled=0, quarantined=0, preempted=0,
                              requeued=0, retried=0)
        self._rng = random.Random(0xC0FFEE)  # deterministic jitter
        # the scheduler's own journal: every control-plane decision is
        # a schema-v1 `sched` event, rendered by the same /runs /
        # /metrics / SSE / tlcstat machinery as any run
        self._jlock = threading.Lock()
        from ..obs.journal import RunJournal

        self._sched = RunJournal(
            os.path.join(root, "sched.journal.jsonl"),
            fsync_every=JOB_FSYNC_EVERY,
        )
        self._sched.event(
            "run_start", version=_version(), workload="scheduler",
            engine="sched", device="host",
            params=dict(queue_bound=self.queue_bound,
                        tenant_quota=self.tenant_quota,
                        tenant_weights=self.tenant_weights,
                        job_retries=self.job_retries,
                        breaker_threshold=self.breaker_threshold,
                        breaker_cooldown_s=self.breaker_cooldown_s),
        )
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        self._reaper = threading.Thread(target=self._reap, daemon=True)
        self._reaper.start()

    # -- telemetry ---------------------------------------------------------

    def _sched_event(self, action: str, job: Optional[Job],
                     **extra) -> None:
        """One control-plane decision into the sched journal.  Lock
        ordering is always _cond -> _jlock (never the reverse), so the
        call is safe under _cond.  A sick disk must not take down
        scheduling - OSErrors are swallowed; schema errors are bugs
        and stay loud."""
        with self._jlock:
            if self._sched is None:
                return
            try:
                self._sched.event("sched", action=action,
                                  job=(job.id if job else ""), **extra)
            except OSError:
                pass

    # -- submission --------------------------------------------------------

    def submit(self, spec: str, cfg: str, tenant: str = None,
               **kw) -> Job:
        job = Job(spec, cfg, tenant=tenant, **kw)
        if job.sweep:
            params = job.sweep_params()  # validates the descriptor
            missing = [c for c in params if c not in job.constants]
            if missing:
                raise JobError(
                    f"sweep job must pin its swept constants "
                    f"{missing} in 'constants'"
                )
        _module_name(job.spec)  # validates the module header
        quarantined = False
        with self._cond:
            now = time.time()
            br = self._breaker.get(job.digest)
            if br is not None:
                if (br["state"] == "open"
                        and now - br["opened_t"]
                        >= self.breaker_cooldown_s):
                    # cooldown elapsed: the next submit is the single
                    # half-open probe
                    br["state"] = "half_open"
                    br["probe"] = None
                if br["state"] == "open" or (
                        br["state"] == "half_open"
                        and br["probe"] is not None):
                    quarantined = True
                elif br["state"] == "half_open":
                    br["probe"] = job.id
            if quarantined:
                self.jobs[job.id] = job
            else:
                queued = len(self._queue)
                if queued >= self.queue_bound:
                    ra = self._retry_after_locked()
                    self._counters["rejected"] += 1
                    self._sched_event(
                        "reject", job, tenant=job.tenant,
                        reason="queue_bound", retry_after_s=ra,
                        queued=queued)
                    raise AdmissionError(
                        f"queue full ({queued}/{self.queue_bound}); "
                        f"retry after {ra}s", ra)
                if self.tenant_quota:
                    tq = sum(1 for jid in self._queue
                             if self.jobs[jid].tenant == job.tenant)
                    if tq >= self.tenant_quota:
                        ra = self._retry_after_locked()
                        self._counters["rejected"] += 1
                        self._sched_event(
                            "reject", job, tenant=job.tenant,
                            reason="tenant_quota", retry_after_s=ra,
                            queued=queued)
                        raise AdmissionError(
                            f"tenant {job.tenant!r} quota full "
                            f"({tq}/{self.tenant_quota}); retry after "
                            f"{ra}s", ra)
                self.jobs[job.id] = job
                self._queue.append(job.id)
                self._counters["admitted"] += 1
                self._sched_event(
                    "admit", job, tenant=job.tenant,
                    priority=job.priority, queued=len(self._queue))
                self._maybe_preempt_locked()
                self._cond.notify()
        if quarantined:
            self._finish_terminal(
                job, "quarantined",
                reason=f"circuit open for spec digest {job.digest}")
        return job

    def _retry_after_locked(self) -> int:
        """Retry-After from the MEASURED drain rate: how long until
        the backlog above the bound has drained, at the recent pace.
        With no completions to measure yet, a small flat hint."""
        rate = self._drain_rate_locked()
        if not rate:
            return 5
        excess = max(1, len(self._queue) - self.queue_bound + 1)
        return max(1, min(60, int(math.ceil(excess / rate))))

    def _drain_rate_locked(self) -> Optional[float]:
        ts = self._finished_ts
        if len(ts) < 2:
            return None
        window = time.time() - ts[0]
        if window <= 0:
            return None
        return len(ts) / window

    def get(self, job_id: str) -> Optional[Job]:
        with self._cond:
            return self.jobs.get(job_id)

    def list(self) -> List[dict]:
        with self._cond:
            return [j.summary() for j in self.jobs.values()]

    def cancel(self, job_id: str) -> Optional[Job]:
        """DELETE /jobs/<id>: a queued job flips straight to the
        terminal `canceled` state (minimal journal, SSE terminates);
        a running preemptible job routes through the programmatic
        drain (checkpoint + exit 75 -> canceled).  A running
        non-preemptible dispatch runs to completion - they are short
        by construction - with the request noted on the record."""
        to_finish = None
        with self._cond:
            job = self.jobs.get(job_id)
            if job is None:
                return None
            if job.state == "queued":
                try:
                    self._queue.remove(job.id)
                except ValueError:
                    pass
                job.cancel_requested = True
                to_finish = job
            elif job.state == "running":
                job.cancel_requested = True
                if (job._preemptible and job._drain is not None
                        and not job._drain.is_set()
                        and job.preempt_reason is None):
                    job.preempt_reason = "cancel"
                    job._drain.set()
        if to_finish is not None:
            self._finish_terminal(job, "canceled",
                                  reason="canceled by client")
        return job

    def stats(self) -> dict:
        with self._cond:
            states: Dict[str, int] = {}
            tenants: Dict[str, int] = {}
            for j in self.jobs.values():
                states[j.state] = states.get(j.state, 0) + 1
            for jid in self._queue:
                t = self.jobs[jid].tenant
                tenants[t] = tenants.get(t, 0) + 1
            rate = self._drain_rate_locked()
            return dict(jobs=len(self.jobs), queued=len(self._queue),
                        states=states, batches_run=self.batches_run,
                        batched_jobs=self.batched_jobs,
                        cache_hits=self.cache_hits,
                        large_fpcap=self.large_fpcap,
                        queue_bound=self.queue_bound,
                        tenant_quota=self.tenant_quota,
                        queued_by_tenant=tenants,
                        dispatches=self._dispatches,
                        drain_rate_per_s=(round(rate, 3)
                                          if rate else None),
                        sched=dict(self._counters),
                        breakers={d: dict(state=b["state"],
                                          failures=b["failures"])
                                  for d, b in self._breaker.items()})

    def health(self) -> dict:
        """GET /health: is the service keeping up?  `overloaded` once
        the queue crosses 80% of the admission bound (the operator's
        early warning; admission itself rejects at 100%)."""
        with self._cond:
            queued = len(self._queue)
            running = [j.id for j in self.jobs.values()
                       if j.state == "running"]
            rate = self._drain_rate_locked()
            open_breakers = sum(1 for b in self._breaker.values()
                                if b["state"] != "closed")
            status = ("overloaded"
                      if queued >= max(1, int(0.8 * self.queue_bound))
                      else "ok")
            return dict(status=status, queued=queued,
                        queue_bound=self.queue_bound, running=running,
                        drain_rate_per_s=(round(rate, 3)
                                          if rate else None),
                        open_breakers=open_breakers,
                        counters=dict(self._counters),
                        uptime_s=round(time.time() - self._started_t,
                                       3))

    def drain(self, timeout: float = 60.0) -> bool:
        """Block until every submitted job reached a terminal state
        (tools/loadgen + tests).  Raises DrainTimeout naming the
        unfinished jobs on timeout - the old silent False wedged
        callers invisibly."""
        deadline = time.time() + timeout
        while True:
            with self._cond:
                pending = [j.id for j in self.jobs.values()
                           if j.state in ("queued", "running")]
            if not pending:
                return True
            if time.time() >= deadline:
                raise DrainTimeout(
                    f"drain timed out after {timeout}s; unfinished "
                    f"jobs: {pending}", pending)
            time.sleep(0.02)

    def shutdown(self) -> None:
        with self._cond:
            if self._stop:
                return
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout=10)
        self._reaper.join(timeout=10)
        with self._jlock:
            if self._sched is not None:
                try:
                    self._sched.event(
                        "final", verdict="ok", generated=0, distinct=0,
                        depth=0, queue=0,
                        wall_s=round(time.time() - self._started_t, 6),
                        interrupted=False,
                        counters=dict(self._counters))
                except OSError:
                    pass
                self._sched.close()
                self._sched = None

    # -- the worker --------------------------------------------------------

    def _pick_locked(self) -> Optional[Job]:
        """Dequeue one job: weighted round-robin between tenants at
        the highest READY priority (retry backoff and deadlines gate
        readiness), FIFO within a tenant.  Returns None when nothing
        is ready (backoff gates can leave a non-empty queue idle)."""
        now = time.time()
        ready = [jid for jid in self._queue
                 if self.jobs[jid].not_before <= now
                 and (self.jobs[jid].deadline_t is None
                      or now < self.jobs[jid].deadline_t)]
        if not ready:
            return None
        top = max(self.jobs[jid].priority for jid in ready)
        by_tenant: Dict[str, str] = {}
        for jid in ready:
            j = self.jobs[jid]
            if j.priority == top and j.tenant not in by_tenant:
                by_tenant[j.tenant] = jid  # FIFO head per tenant
        for t in by_tenant:
            if t not in self._rr_tenants:
                w = max(1, int(self.tenant_weights.get(t, 1)))
                self._rr.extend([t] * w)
                self._rr_tenants.add(t)
        for _ in range(len(self._rr)):
            t = self._rr[0]
            self._rr.rotate(-1)
            if t in by_tenant:
                jid = by_tenant[t]
                self._queue.remove(jid)
                return self.jobs[jid]
        jid = ready[0]  # unreachable: every ready tenant is cycled
        self._queue.remove(jid)
        return self.jobs[jid]

    def _loop(self) -> None:
        while True:
            with self._cond:
                head = None
                while not self._stop:
                    head = self._pick_locked()
                    if head is not None:
                        break
                    # short wait while backoff gates tick, long idle
                    self._cond.wait(0.05 if self._queue else 0.5)
                if self._stop:
                    return
                now = time.time()
                batch = [head]
                if (head.sweep or head.is_smoke()
                        or head.is_infer()) \
                        and not head.is_large(self.large_fpcap):
                    # look ahead: fold READY queued jobs of the same
                    # class into this dispatch (FIFO among the folded;
                    # the skipped-over rest keeps its order)
                    sig = head.batch_signature()
                    width = self.pool.sweep_width
                    for jid in list(self._queue):
                        if len(batch) >= width:
                            break
                        cand = self.jobs[jid]
                        if (cand.not_before <= now
                                and cand.batch_signature() == sig):
                            self._queue.remove(jid)
                            batch.append(cand)
                for j in batch:
                    j.state = "running"
                    j.started_t = now
                    # the programmatic drain twin of _SignalCatcher:
                    # set -> this ONE job checkpoints and exits 75
                    j._drain = threading.Event()
                    j._preemptible = (
                        len(batch) == 1
                        and j.is_large(self.large_fpcap)
                        and bool(j.options.get("checkpoint"))
                    )
                self._dispatches += 1
                n = self._dispatches
            self._sched_event("dispatch", batch[0], batch=len(batch),
                              n=n)
            try:
                if self._injector is not None:
                    self._injector.dispatch(n)
                self._run_batch(batch)
            except Exception as e:  # a broken job must not kill the loop
                self._dispatch_failed(batch, e)

    def _retryable(self, e: BaseException) -> bool:
        """The resil taxonomy applied to a dead dispatch: transient
        runtime errors retry with backoff; deterministic
        RESOURCE_EXHAUSTED never does (the PR 2 lesson - the ladder
        owns that class, and at this level the ladder already ran)."""
        from ..resil.supervisor import _TRANSIENT, is_resource_exhausted

        if is_resource_exhausted(e):
            return False
        return isinstance(e, _TRANSIENT)

    def _backoff_s(self, attempt: int) -> float:
        """Deterministic-jitter exponential backoff (seeded RNG: two
        runs of the same fault plan redispatch on the same clock)."""
        base = min(RETRY_BACKOFF_CAP_S,
                   RETRY_BACKOFF_BASE_S * (2 ** (attempt - 1)))
        return base * (0.5 + self._rng.random())

    def _dispatch_failed(self, batch: List[Job], e: Exception) -> None:
        """Classify a dead dispatch: transient faults requeue every
        affected job with backoff (their journals are rewritten by the
        retried run - RunJournal truncates, the SSE tail resets on
        shrink); anything else finalizes the jobs as errors and feeds
        the spec-digest breaker."""
        retryable = self._retryable(e)
        requeued, failed = [], []
        with self._cond:
            for j in batch:
                if j.state != "running":
                    continue
                if retryable and j.retries < self.job_retries:
                    j.retries += 1
                    delay = self._backoff_s(j.retries)
                    j.not_before = time.time() + delay
                    j.state = "queued"
                    j.started_t = None
                    j._drain = None
                    j._preemptible = False
                    self._queue.append(j.id)
                    self._counters["retried"] += 1
                    requeued.append((j, delay))
                else:
                    failed.append(j)
            if requeued:
                self._cond.notify()
        msg = f"{type(e).__name__}: {e}"
        for j, delay in requeued:
            self._sched_event("retry", j, attempt=j.retries,
                              delay_s=round(delay, 4),
                              error=msg[:300])
        for j in failed:
            self._finish_error(j, msg)

    # -- deadlines + preemption (the reaper) -------------------------------

    def _reap(self) -> None:
        """The scheduler's clock: expire queued jobs past their
        deadline, drain running preemptible jobs past theirs, and
        back-stop priority preemption for arrivals that raced the
        dispatch."""
        while True:
            expired = []
            with self._cond:
                if self._stop:
                    return
                now = time.time()
                for jid in list(self._queue):
                    j = self.jobs[jid]
                    if j.deadline_t is not None and now >= j.deadline_t:
                        self._queue.remove(jid)
                        expired.append(j)
                for j in self.jobs.values():
                    if (j.state == "running" and j._preemptible
                            and j.deadline_t is not None
                            and now >= j.deadline_t
                            and j._drain is not None
                            and not j._drain.is_set()
                            and j.preempt_reason is None):
                        j.preempt_reason = "deadline"
                        j._drain.set()
                        self._counters["preempted"] += 1
                        self._sched_event("preempt", j,
                                          reason="deadline")
                self._maybe_preempt_locked()
            for j in expired:
                self._finish_terminal(j, "expired",
                                      reason="deadline expired while "
                                             "queued")
            time.sleep(REAPER_PERIOD_S)

    def _maybe_preempt_locked(self) -> None:
        """Priority preemption AS scheduling: a queued job strictly
        above a running preemptible job's priority drains it; the
        preempted job requeues as a -recover resume (bit-for-bit the
        uninterrupted result, the PR 2/7 contract)."""
        if not self._queue:
            return
        top = max(self.jobs[jid].priority for jid in self._queue)
        for j in self.jobs.values():
            if (j.state == "running" and j._preemptible
                    and j._drain is not None
                    and not j._drain.is_set()
                    and j.preempt_reason is None
                    and j.priority < top):
                j.preempt_reason = "priority"
                j._drain.set()
                self._counters["preempted"] += 1
                self._sched_event("preempt", j, reason="priority",
                                  priority=j.priority, over=top)

    def _requeue_preempted(self, job: Job) -> None:
        """A priority-preempted job goes back in the queue as a
        `-recover` resume against its own checkpoint + journal
        (api._open_journal appends and stamps run_resume: one
        continuous history)."""
        with self._cond:
            job.options["recover"] = True
            job.requeues += 1
            job.preempt_reason = None
            job._drain = None
            job._preemptible = False
            job.state = "queued"
            job.started_t = None
            self._queue.append(job.id)
            self._counters["requeued"] += 1
            self._cond.notify()
        self._sched_event("requeue", job, reason="priority",
                          requeues=job.requeues)

    # -- execution paths ---------------------------------------------------

    def _jobdir(self, job: Job) -> str:
        d = os.path.join(self.root, "jobs", job.id)
        os.makedirs(d, exist_ok=True)
        mod = _module_name(job.spec)
        with open(os.path.join(d, f"{mod}.tla"), "w") as f:
            f.write(job.spec)
        with open(os.path.join(d, f"{mod}.cfg"), "w") as f:
            f.write(job.cfg)
        return os.path.join(d, f"{mod}.cfg")

    def _journal_path(self, job: Job) -> str:
        return os.path.join(self.root, f"{job.id}.journal.jsonl")

    def _journal(self, job: Job):
        from ..obs.journal import RunJournal

        return RunJournal(self._journal_path(job),
                          fsync_every=JOB_FSYNC_EVERY)

    def _run_batch(self, batch: List[Job]) -> None:
        head = batch[0]
        if head.is_infer() and not head.is_large(self.large_fpcap):
            self._run_infer(batch)
            return
        if head.is_smoke() and not head.is_large(self.large_fpcap):
            self._run_smoke(batch)
            return
        if head.sweep and not head.is_large(self.large_fpcap):
            self._run_sweep(batch)
            return
        assert len(batch) == 1
        if head.is_large(self.large_fpcap):
            self._run_supervised(head)
        else:
            self._run_pooled(head)

    def _geometry(self, job: Job) -> dict:
        o = job.options
        return dict(
            chunk=int(o.get("chunk", DEFAULT_CHUNK)),
            queue_capacity=int(o.get("qcap", DEFAULT_QCAP)),
            fp_capacity=int(o.get("fpcap", DEFAULT_FPCAP)),
            check_deadlock=not o.get("nodeadlock", False),
            sort_free=o.get("sortfree", None),
            deferred=o.get("deferredinv", None),
        )

    def _run_sweep(self, batch: List[Job]) -> None:
        """One vmapped dispatch for the whole compatible batch."""
        import jax

        from . import sweep as sw

        head = batch[0]
        params = head.sweep_params()
        cfg_path = self._jobdir(head)
        # the job's FIXED constants bake into the anchor (batch_signature
        # already folds only equal-fixed jobs together, so head's dict
        # speaks for the whole batch); two batches differing in a fixed
        # override land on different class keys, not one shared engine
        fixed = _loader_constants({
            k: v for k, v in head.constants.items() if k not in params
        })
        model = sw.load_anchored(cfg_path, params,
                                 const_overrides=fixed or None)
        pre = self.pool.hits
        entry = self.pool.get_sweep(model, params, **self._geometry(head))
        hit = self.pool.hits > pre
        configs = [
            {c: int(j.constants[c]) for c in params} for j in batch
        ]
        device = str(jax.devices()[0])
        journals = []
        for j in batch:
            if j is not head:
                self._jobdir(j)  # each job keeps its own artifacts
            jr = self._journal(j)
            jr.event("run_start", version=_version(), workload=j.name,
                     engine="sweep", device=device,
                     params=dict(**self._geometry(j),
                                 sweep=j.sweep, constants=j.constants,
                                 batch=len(batch), pool_hit=hit))
            journals.append(jr)
        try:
            results = entry.runner.run(configs)
        except BaseException:
            self._abort_journals(journals)
            raise
        with self._cond:
            self.batches_run += 1
            self.batched_jobs += len(batch)
        for j, jr, r in zip(batch, journals, results):
            if r.violation != 0:
                jr.event("violation", code=int(r.violation),
                         name=r.violation_name)
            jr.event("final",
                     verdict="ok" if r.violation == 0 else "violation",
                     generated=r.generated, distinct=r.distinct,
                     depth=r.depth, queue=r.queue_left,
                     wall_s=round(r.wall_s, 6), interrupted=False)
            jr.close()
            self._finish_ok(j, _result_dict(r, "sweep", pool_hit=hit))

    def _run_smoke(self, batch: List[Job]) -> None:
        """The smoke job class (jaxtlc.sim, ISSUE 14): one vmapped
        random-walk dispatch for the whole compatible batch - the
        batch axis is (seed, swept-constants config), so N per-commit
        smoke submits (different seeds) and a constants sweep both
        ride ONE warm sim engine.  The artifact cache is BYPASSED
        (journaled per job): simulation verdicts are from incomplete
        search and must never publish to the verdict tier."""
        import jax

        from ..struct import artifacts as arts
        from ..struct.loader import StructLoadError, load
        from ..struct.parser import StructParseError
        from . import sweep as sw

        head = batch[0]
        params = head.sweep_params() or None
        cfg_path = self._jobdir(head)
        fixed = _loader_constants({
            k: v for k, v in head.constants.items()
            if k not in (params or {})
        })
        try:
            if params:
                model = sw.load_anchored(cfg_path, params,
                                         const_overrides=fixed or None)
            else:
                model = load(cfg_path, const_overrides=fixed or None)
        except (StructLoadError, StructParseError):
            # the sim engine is struct-only today: route through
            # api.run_check with the frontend forced struct (it runs
            # any spec) so the job still gets a real answer or a
            # real error
            for j in batch:
                self._run_supervised(j, frontend="struct")
            return
        o = head.options
        walkers = int(o.get("walkers", DEFAULT_SIM_WALKERS))
        depth = int(o.get("depth", DEFAULT_SIM_DEPTH))
        fp_capacity = int(o.get("fpcap", DEFAULT_FPCAP))
        check_deadlock = not o.get("nodeadlock", False)
        pre = self.pool.hits
        entry = self.pool.get_sim(
            model, params=params, walkers=walkers, depth=depth,
            fp_capacity=fp_capacity, check_deadlock=check_deadlock,
        )
        hit = self.pool.hits > pre
        items = [
            (int(j.options.get("simseed", 0)),
             ({c: int(j.constants[c]) for c in params}
              if params else None))
            for j in batch
        ]
        bypass = (arts.get_store() is not None
                  and not o.get("noartifactcache"))
        device = str(jax.devices()[0])
        journals = []
        for j, (seed, values) in zip(batch, items):
            if j is not head:
                self._jobdir(j)
            jr = self._journal(j)
            jr.event("run_start", version=_version(), workload=j.name,
                     engine="sim", device=device,
                     params=dict(walkers=walkers, depth=depth,
                                 sim_seed=seed, fp_capacity=fp_capacity,
                                 sweep=j.sweep, constants=j.constants,
                                 batch=len(batch), pool_hit=hit))
            if bypass:
                jr.event("cache", tier="verdict", outcome="bypass",
                         key="", reason="simulation verdicts are from "
                                        "incomplete search and never "
                                        "publish")
            journals.append(jr)
        try:
            results = entry.runner.run(items)
        except BaseException:
            self._abort_journals(journals)
            raise
        with self._cond:
            self.batches_run += 1
            self.batched_jobs += len(batch)
        for j, jr, r in zip(batch, journals, results):
            jr.event("sim", phase="summary", walkers=r.walkers,
                     depth=r.depth, steps=r.steps,
                     transitions=r.transitions, seed=r.seed,
                     distinct_est=r.distinct,
                     fp_saturated=r.fp_saturated, halted=r.halted,
                     depth_hist=[list(p) for p in r.depth_hist],
                     violation=r.violation)
            if r.violation != 0:
                jr.event("violation", code=int(r.violation),
                         name=r.violation_name)
            jr.event("final",
                     verdict="ok" if r.violation == 0 else "violation",
                     generated=r.generated, distinct=r.distinct,
                     depth=r.steps, queue=0,
                     wall_s=round(r.wall_s, 6), interrupted=False)
            jr.close()
            res = _result_dict(r, "sim", pool_hit=hit)
            res["depth"] = r.steps  # depth REACHED (r.depth = budget)
            res["sim"] = dict(
                walkers=r.walkers, depth=r.depth, steps=r.steps,
                transitions=r.transitions, seed=r.seed,
                distinct_est=r.distinct, fp_saturated=r.fp_saturated,
                violation_lane=r.violation_lane,
                violation_step=r.violation_step,
            )
            self._finish_ok(j, res)

    def _run_infer(self, batch: List[Job]) -> None:
        """The inference job class (jaxtlc.infer, ISSUE 16): every job
        in the folded batch runs through ONE warm infer engine - the
        candidate pool, the AOT [P, S] filter kernel and the exact
        evidence all belong to the engine, so the per-job work is pure
        dispatch (the seed only matters under sampled evidence).  Like
        sim, the artifact-cache verdict tier is BYPASSED (journaled
        per job): an inference verdict is about CANDIDATES, not the
        spec's stated invariants."""
        import jax

        from ..struct import artifacts as arts
        from ..struct.loader import StructLoadError, load
        from ..struct.parser import StructParseError

        head = batch[0]
        cfg_path = self._jobdir(head)
        fixed = _loader_constants(head.constants)
        try:
            model = load(cfg_path, const_overrides=fixed or None)
        except (StructLoadError, StructParseError):
            # inference conjectures over the struct IR: route through
            # api.run_check with the frontend forced struct (it runs
            # any spec) so the job still gets a real answer or a real
            # error
            for j in batch:
                self._run_supervised(j, frontend="struct")
            return
        o = head.options
        budget = int(o.get("inferbudget", 64))
        walkers = int(o.get("walkers", DEFAULT_SIM_WALKERS))
        depth = int(o.get("depth", DEFAULT_SIM_DEPTH))
        check_deadlock = not o.get("nodeadlock", False)
        pre = self.pool.hits
        entry = self.pool.get_infer(
            model, budget=budget, walkers=walkers, depth=depth,
            check_deadlock=check_deadlock,
        )
        hit = self.pool.hits > pre
        bypass = (arts.get_store() is not None
                  and not o.get("noartifactcache"))
        device = str(jax.devices()[0])
        for j in batch:
            if j is not head:
                self._jobdir(j)
            jr = self._journal(j)
            jr.event("run_start", version=_version(), workload=j.name,
                     engine="infer", device=device,
                     params=dict(budget=budget, walkers=walkers,
                                 depth=depth,
                                 sim_seed=int(j.options.get(
                                     "simseed", 0)),
                                 constants=j.constants,
                                 batch=len(batch), pool_hit=hit))
            if bypass:
                jr.event("cache", tier="verdict", outcome="bypass",
                         key="", reason="inference verdicts are about "
                                        "candidate invariants and "
                                        "never publish")
            try:
                rep = entry.runner.run(
                    seed=int(j.options.get("simseed", 0)))
            except BaseException:
                self._abort_journals([jr])
                raise
            jr.event("infer", phase="summary",
                     candidates=rep.candidates, killed=rep.killed,
                     survivors=len(rep.survivors),
                     certified=len(rep.certified),
                     certified_names=[c.name for c in rep.certified],
                     evidence=rep.evidence, n_states=rep.n_states,
                     dropped=rep.dropped)
            violated = bool(rep.cfg_killed)
            if violated:
                jr.event("violation", code=100,
                         name=f"Invariant {rep.cfg_killed[0]} is "
                              f"violated.")
            jr.event("final",
                     verdict="violation" if violated else "ok",
                     generated=rep.n_states, distinct=rep.n_states,
                     depth=0, queue=0,
                     wall_s=round(rep.wall_s, 6), interrupted=False)
            jr.close()
            res = dict(
                verdict="violation" if violated else "ok",
                violation=(100 if violated else 0),
                violation_name=(f"Invariant {rep.cfg_killed[0]} is "
                                f"violated." if violated else None),
                generated=rep.n_states, distinct=rep.n_states,
                depth=0, queue_left=0,
                wall_s=round(rep.wall_s, 6),
                engine="infer", pool_hit=hit,
                infer=dict(
                    candidates=rep.candidates, dropped=rep.dropped,
                    killed=rep.killed, survivors=len(rep.survivors),
                    certified=[
                        dict(name=c.name, text=c.text, basis=b,
                             implies=list(c.implies))
                        for c, b in zip(rep.certified, rep.cert_basis)
                    ],
                    uncertified=[
                        dict(name=c.name, text=c.text)
                        for c in rep.survivors
                        if c not in rep.certified
                    ],
                    uncompiled=list(rep.uncompiled),
                    cfg_killed=list(rep.cfg_killed),
                    evidence=rep.evidence, exact=rep.exact,
                    n_states=rep.n_states, seed=rep.seed,
                ),
            )
            self._finish_ok(j, res)
        with self._cond:
            self.batches_run += 1
            self.batched_jobs += len(batch)

    def _run_pooled(self, job: Job) -> None:
        """Warm plain engine via the pool; falls back to the supervised
        path when the spec does not resolve structurally.

        Incremental re-checking (ISSUE 13) sits BEFORE pool routing: an
        unchanged spec is answered from the verdict tier in O(HTTP) -
        no pool lookup, no engine dispatch - and a spec whose behavior
        digest has a stored reachable set routes through api.run_check,
        which skips BFS and re-evaluates only the invariants."""
        import jax

        from ..struct import artifacts as arts
        from ..struct.loader import StructLoadError, load
        from ..struct.parser import StructParseError

        cfg_path = self._jobdir(job)
        try:
            model = load(
                cfg_path,
                const_overrides=_loader_constants(job.constants) or None,
            )
        except (StructLoadError, StructParseError, JobError):
            self._run_supervised(job)
            return
        geo = self._geometry(job)
        store = arts.get_store()
        use_cache = (store is not None
                     and not job.options.get("recheck")
                     and not job.options.get("noartifactcache"))
        vkey = ""
        if use_cache:
            # the pooled path checks safety only, so its verdict key
            # carries an empty property selection (api keys runs WITH
            # properties differently - the two can never cross-answer)
            vkey = arts.verdict_key(model, geo["check_deadlock"])
            payload = store.lookup_verdict(vkey)
            if payload is not None:
                self._finish_cached(job, geo, vkey, payload)
                return
            if store.has_reach(
                    arts.reach_key(model, geo["check_deadlock"])):
                # invariant-only edit: api.run_check's reach tier
                # skips BFS entirely - cheaper than a pool dispatch.
                # Forced onto the struct frontend: the stored artifact
                # was keyed by this very struct load, and "auto" could
                # route a gen-subset spec away from the cache
                self._run_supervised(job, frontend="struct")
                return
        pre = self.pool.hits
        entry = self.pool.get_single(model, **geo)
        hit = self.pool.hits > pre
        jr = self._journal(job)
        jr.event("run_start", version=_version(), workload=job.name,
                 engine="pool", device=str(jax.devices()[0]),
                 params=dict(**geo, constants=job.constants,
                             pool_hit=hit))
        try:
            r = entry.runner.run(capture_fps=use_cache)
        except BaseException:
            self._abort_journals([jr])
            raise
        if r.violation != 0:
            jr.event("violation", code=int(r.violation),
                     name=r.violation_name)
        if use_cache and r.violation == 0:
            try:
                arts.ArtifactPlan(
                    store, model,
                    check_deadlock=geo["check_deadlock"],
                    fp_capacity=geo["fp_capacity"],
                ).record(r, n_init=len(model.system.initial_states()),
                         journal=jr)
            except OSError:
                pass  # a full disk must not fail the job
        jr.event("final",
                 verdict="ok" if r.violation == 0 else "violation",
                 generated=r.generated, distinct=r.distinct,
                 depth=r.depth, queue=r.queue_left,
                 wall_s=round(r.wall_s, 6), interrupted=False)
        jr.close()
        self._finish_ok(job, _result_dict(r, "pool", pool_hit=hit))

    def _finish_cached(self, job: Job, geo: dict, key: str,
                       payload: dict) -> None:
        """Answer a job from the verdict tier: journal a complete run
        (run_start -> cache hit -> final, so SSE/views/tlcstat render
        it like any other), no pool lookup, no engine dispatch."""
        from ..struct.artifacts import result_from_payload

        jr = self._journal(job)
        jr.event("run_start", version=_version(), workload=job.name,
                 engine="cache", device="artifact-cache",
                 params=dict(**geo, constants=job.constants,
                             cache_hit=True))
        jr.event("cache", tier="verdict", outcome="hit", key=key,
                 workload=payload.get("workload"))
        r = result_from_payload(payload,
                                fp_capacity=geo["fp_capacity"],
                                wall_s=time.time() - job.started_t)
        jr.event("final", verdict="ok", generated=r.generated,
                 distinct=r.distinct, depth=r.depth, queue=r.queue_left,
                 wall_s=round(r.wall_s, 6), interrupted=False)
        jr.close()
        with self._cond:
            self.cache_hits += 1
        res = _result_dict(r, "cache")
        res["cache_hit"] = True
        self._finish_ok(job, res)

    def _abort_journals(self, journals) -> None:
        """A runner that dies after the per-job journals opened must
        still terminate them: SSE followers only stop on a 'final'
        event, and an unclosed handle leaks per failed job (the loop's
        error handler knows jobs, not files).  A retried dispatch
        truncates and rewrites these journals (RunJournal opens 'w');
        the SSE tail resets on shrink."""
        for jr in journals:
            try:
                jr.event("final", verdict="error", generated=0,
                         distinct=0, depth=0, queue=0, wall_s=0.0,
                         interrupted=True)
            except Exception:
                pass  # a sick journal must not mask the run's error
            finally:
                jr.close()

    def _run_supervised(self, job: Job, frontend: str = None) -> None:
        """Large / resilience-option jobs: the full api.run_check
        pipeline (resil supervisor, degradation ladder, preflight, TLC
        transcript captured as the job's output).  `frontend` overrides
        the resolver when the caller already knows the path (the
        artifact-cache reach route struct-loaded the model itself).

        The job's drain Event rides into SupervisorOptions: the reaper
        / a priority arrival / a cancel sets it, the supervisor
        checkpoints at the next segment fence and returns exit 75, and
        the preempt_reason decides what 75 MEANS here - requeue as a
        -recover resume (priority), terminal expired (deadline), or
        terminal canceled (client cancel)."""
        from ..api import CheckRequest, run_check

        cfg_path = self._jobdir(job)
        out = io.StringIO()
        kw = {k: job.options[k] for k in _REQUEST_OPTIONS
              if k in job.options}
        kw.setdefault("workers", "cpu" if _on_cpu() else "tpu")
        if frontend is not None:
            kw.setdefault("frontend", frontend)
        req = CheckRequest(
            config=cfg_path,
            constants=_loader_constants(job.constants),
            journal=self._journal_path(job),
            noTool=True, out=out, err=out, drain=job._drain, **kw,
        )
        outcome = run_check(req)
        r = outcome.result
        res = dict(verdict=outcome.verdict,
                   exit_code=outcome.exit_code, engine="supervised",
                   transcript=out.getvalue())
        if kw.get("coverage"):
            # per-job coverage artifact (ISSUE 11): the cumulative
            # site table folded from the job journal's coverage
            # events - GET /jobs/<id> returns it, and the journal
            # itself stays queryable via /coverage?run=<job id>
            try:
                from ..obs.coverage import coverage_from_events
                from ..obs.journal import read as read_journal

                cov = coverage_from_events(
                    read_journal(req.journal, validate=False)
                )
                if cov is not None:
                    res["coverage"] = cov
            except (OSError, ValueError):
                pass  # a sick journal must not mask the verdict
        if r is not None:
            res.update(
                generated=r.generated, distinct=r.distinct,
                depth=r.depth, queue=r.queue_left,
                violation=r.violation,
                action_generated=r.action_generated,
                wall_s=round(r.wall_s, 6),
            )
        reason = job.preempt_reason
        if outcome.exit_code == 75 and reason == "priority":
            self._requeue_preempted(job)
        elif outcome.exit_code == 75 and reason == "deadline":
            self._finish_terminal(job, "expired",
                                  reason="deadline expired while "
                                         "running", result=res)
        elif outcome.exit_code == 75 and reason == "cancel":
            self._finish_terminal(job, "canceled",
                                  reason="canceled by client",
                                  result=res)
        elif outcome.exit_code in (0, 12, 13, 75):
            self._finish_ok(job, res)
        else:
            job.result = res
            self._finish_error(
                job, f"exit {outcome.exit_code}: {out.getvalue()[-500:]}"
            )

    # -- completion --------------------------------------------------------

    def _breaker_note_locked(self, job: Job,
                             outcome: str) -> Optional[str]:
        """Feed one job outcome to the spec-digest breaker.  Returns
        "trip" / "reopen" when this outcome opened the circuit.
        outcome: "ok" closes, "error" counts toward the threshold (and
        re-opens a failed half-open probe), anything else only
        releases a held probe slot (a canceled probe must not wedge
        the breaker half-open forever)."""
        br = self._breaker.get(job.digest)
        if outcome == "ok":
            if br is not None:
                del self._breaker[job.digest]
            return None
        if outcome != "error":
            if br is not None and br.get("probe") == job.id:
                br["probe"] = None
            return None
        if br is None:
            br = self._breaker[job.digest] = dict(
                state="closed", failures=0, opened_t=0.0, probe=None)
        br["failures"] += 1
        if br["state"] == "half_open" and br.get("probe") == job.id:
            br.update(state="open", opened_t=time.time(), probe=None)
            return "reopen"
        if br["state"] == "closed" \
                and br["failures"] >= self.breaker_threshold:
            br.update(state="open", opened_t=time.time())
            return "trip"
        return None

    def _ensure_terminal_journal(self, job: Job, verdict: str) -> None:
        """A job finishing without ever having journaled (expired /
        canceled / quarantined before running, or a dispatch that died
        before opening journals) still gets a minimal one - run_start
        with engine "sched" plus the final - so /runs lists it and SSE
        followers terminate on EVERY outcome."""
        path = self._journal_path(job)
        if os.path.exists(path):
            return
        from ..obs.journal import RunJournal

        try:
            with RunJournal(path) as jr:
                jr.event("run_start", version=_version(),
                         workload=job.name, engine="sched",
                         device="host",
                         params=dict(tenant=job.tenant,
                                     priority=job.priority,
                                     verdict=verdict))
                jr.event("final", verdict=verdict, generated=0,
                         distinct=0, depth=0, queue=0, wall_s=0.0,
                         interrupted=False)
        except OSError:
            pass  # a sick disk must not mask the job's state

    def _finish_terminal(self, job: Job, verdict: str,
                         reason: str = None,
                         result: Optional[dict] = None) -> None:
        """Scheduler-terminal completion: expired / canceled /
        quarantined."""
        self._ensure_terminal_journal(job, verdict)
        action = {"expired": "expire", "canceled": "cancel",
                  "quarantined": "quarantine"}[verdict]
        with self._cond:
            job.state = verdict
            job.engine = job.engine or "sched"
            if result is not None:
                job.result = result
            if reason and not job.error:
                job.error = reason
            job.finished_t = time.time()
            self._finished_ts.append(job.finished_t)
            self._counters[verdict] += 1
            self._breaker_note_locked(job, verdict)
            self._cond.notify_all()
        self._sched_event(action, job, tenant=job.tenant,
                          reason=(reason or verdict))

    def _finish_ok(self, job: Job, result: dict) -> None:
        with self._cond:
            job.result = result
            job.engine = result.get("engine", "")
            job.state = "done"
            job.finished_t = time.time()
            self._finished_ts.append(job.finished_t)
            self._breaker_note_locked(job, "ok")
            self._cond.notify_all()

    def _finish_error(self, job: Job, msg: str) -> None:
        self._ensure_terminal_journal(job, "error")
        with self._cond:
            job.error = msg
            job.state = "error"
            job.finished_t = time.time()
            self._finished_ts.append(job.finished_t)
            tripped = self._breaker_note_locked(job, "error")
            self._cond.notify_all()
        if tripped:
            self._sched_event("quarantine", job, digest=job.digest,
                              transition=tripped,
                              cooldown_s=self.breaker_cooldown_s)


def _version() -> str:
    from .. import __version__

    return __version__


def _on_cpu() -> bool:
    import jax

    return jax.devices()[0].platform == "cpu"
